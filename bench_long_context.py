"""Long-context attention bench: seq-4096 flash vs ring (zig-zag vs
contiguous).  Prints ONE JSON line.

On real TPU hardware this records the single-chip flash-attention
fwd+bwd number at seq 4096 (the baseline sequence parallelism must beat
at scale).  Multi-chip SP cannot be timed meaningfully in this
environment (one physical chip; the CPU-mesh ring measures thread
scheduling, not ICI) — so the ring layouts are additionally compared by
their *causal work balance*: the max-over-devices count of unmasked
(query, key) block pairs per hop, the quantity that sets ring wall-clock.
Zig-zag's bound is ~half of contiguous — the same 2x the Megatron
context-parallel striped layout reports on hardware.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from ray_lightning_tpu.ops.ring_attention import zigzag_indices  # noqa: F401


def _work_imbalance(n: int, layout: str) -> float:
    """Max-over-devices unmasked attention AREA divided by the perfectly
    balanced share (total causal area / n).  1.0 = ideal; the contiguous
    layout's last device approaches ~2.0 (it owns the final chunk, which
    attends to everything), which is the ring's wall-clock multiplier."""
    if layout == "zigzag":
        chunks = {j: (j, 2 * n - 1 - j) for j in range(n)}
        n_chunks = 2 * n
    else:
        chunks = {j: (j,) for j in range(n)}
        n_chunks = n
    cell = (1.0 / n_chunks) ** 2  # area of one full (qc, kc) chunk pair
    per_dev = []
    for dev in range(n):
        total = 0.0
        for src in range(n):  # one hop per source device
            for qc in chunks[dev]:
                for kc in chunks[src]:
                    if kc < qc:
                        total += cell
                    elif kc == qc:
                        total += cell / 2
        per_dev.append(total)
    ideal = sum(per_dev) / n
    return max(per_dev) / ideal


def _peak_hbm_mb() -> float | None:
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            return round(stats["peak_bytes_in_use"] / 2**20, 1)
    except Exception:  # noqa: BLE001 - not all runtimes expose stats
        pass
    return None


def _time_attn(impl: str, S: int, B: int, H: int, D: int, reps: int = 5):
    """Fwd+bwd wall time for one attention impl at (B, S, H, D); returns
    (ms, tokens_per_sec, peak_hbm_mb) or an 'oom'/error marker string."""
    from ray_lightning_tpu.ops.attention import causal_attention

    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, S, H, D), jnp.bfloat16)
    k, v = q * 0.99, q * 1.01

    def fb(q, k, v):
        g = jax.grad(
            lambda q, k, v: causal_attention(q, k, v, impl=impl)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2),
        )(q, k, v)
        return sum(x.astype(jnp.float32).sum() for x in g)

    try:
        f = jax.jit(fb)
        float(jax.device_get(f(q, k, v)))  # compile + one run
        t0 = time.perf_counter()
        for _ in range(reps):
            s = f(q, k, v)
        float(jax.device_get(s))
        dt = (time.perf_counter() - t0) / reps
        return {
            "ms": round(dt * 1000, 2),
            "tokens_per_sec": round(B * S / dt, 1),
            "peak_hbm_mb": _peak_hbm_mb(),
        }
    except Exception as e:  # noqa: BLE001 - OOM at long seq is a finding
        msg = str(e).lower()
        return "oom" if ("resource_exhausted" in msg or "memory" in msg) \
            else f"error: {str(e)[:120]}"


def _one_in_subprocess(impl: str, S: int, B: int, H: int, D: int):
    """Run one (impl, S) measurement in a FRESH process so
    ``peak_bytes_in_use`` (a process-lifetime monotone max) is the peak
    of exactly this config — in-process, every entry after the first
    would inherit the largest earlier peak."""
    import os
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", impl,
             str(S), str(B), str(H), str(D)],
            capture_output=True, text=True, timeout=1200,
        )
    except subprocess.TimeoutExpired:
        # One slow config (e.g. the O(S^2) XLA arm at 32k) must not
        # discard the measurements already collected.
        return "error: timeout (1200s)"
    # The child prints one backend-tagged JSON dict; failed measurements
    # carry the marker under "result".
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            out = json.loads(line)
        except ValueError:
            continue
        if not isinstance(out, dict):
            continue
        if out.get("backend") != "tpu":
            return (f"error: child ran on {out.get('backend')!r}, not tpu "
                    f"(tunnel dropped mid-sweep?)")
        out.pop("backend", None)
        return out.get("result", out)
    return f"error: subprocess rc={proc.returncode}: {proc.stderr[-200:]}"


def _chunked_prefill_block() -> dict:
    """Serving-side long-context story: a long prompt admitted against
    RESIDENT decode traffic through chunked prefill
    (``ServeConfig.prefill_chunk``) — one fixed-width chunk per engine
    step interleaved with the decode tick, so the long admission never
    head-of-line-blocks in-flight streams.  CPU-runnable (tiny model;
    the contract being measured is scheduling, not flops).  Emits the
    schema-gated ``chunked_prefill`` block
    (``validate_bench_chunked_prefill``): ``resident_max_stall_ticks``
    is the max consecutive engine steps a resident slot went without
    emitting while the long prompt chunked in — the no-stall bound
    is 1.  ``RLT_PREFILL_CHUNK`` overrides the chunk width (the
    ``tools/hw_session.sh`` width sweep: {512, 1024, 2048} on real
    chips); the prompt and positional table scale with it so every
    width measures the same 6-chunk admission shape."""
    import os

    import numpy as np

    from ray_lightning_tpu.models.gpt import GPT, GPTConfig
    from ray_lightning_tpu.serve.engine import ServeConfig, ServeEngine
    from ray_lightning_tpu.serve.metrics import ServeStats
    from ray_lightning_tpu.telemetry import compile_event_count

    chunk = int(os.environ.get("RLT_PREFILL_CHUNK", "0") or 0) or 64
    prompt_len = 6 * chunk
    seq_len = max(512, 1 << (prompt_len + 128 - 1).bit_length())
    cfg = GPTConfig(vocab_size=128, n_layer=2, n_head=4, d_model=64,
                    seq_len=seq_len, warmup_steps=1)
    module = GPT(cfg, attn_impl="xla")
    params = module.init_params(jax.random.PRNGKey(0))
    eng = ServeEngine(module, params, ServeConfig(
        num_slots=4, block_size=16, prefill_chunk=chunk,
    ))
    rng = np.random.default_rng(3)

    def _short():
        return rng.integers(1, cfg.vocab_size, size=(24,)).tolist()

    long_prompt = rng.integers(1, cfg.vocab_size,
                               size=(prompt_len,)).tolist()
    try:
        # Warm every program the measured pass replays: the short-
        # bucket prefill + decode, and the chunk program (a full
        # chunked admission end to end).
        eng.generate(_short(), 4)
        eng.generate(rng.integers(1, cfg.vocab_size,
                                  size=(prompt_len,)).tolist(), 4)
        eng.stats = ServeStats()
        before = compile_event_count()

        emitted = {0: 0, 1: 0}
        residents = [
            eng.submit(_short(), 64,
                       on_token=lambda idx, tok, i=i: emitted.__setitem__(
                           i, emitted[i] + 1))
            for i in (0, 1)
        ]
        while not all(emitted.values()):    # both resident + decoding
            eng.step()
        first_long = []
        t_submit = time.perf_counter()
        h_long = eng.submit(
            long_prompt, 8,
            on_token=lambda idx, tok: first_long.append(
                time.perf_counter()),
        )
        # Drive until the long prompt's first token lands, tracking how
        # many consecutive steps each resident went token-less.
        stall, max_stall = {0: 0, 1: 0}, 0
        while not first_long:
            seen = dict(emitted)
            eng.step()
            for i in (0, 1):
                stall[i] = 0 if emitted[i] > seen[i] else stall[i] + 1
                max_stall = max(max_stall, stall[i])
        ttft_ms = (first_long[0] - t_submit) * 1e3
        eng.run_until_idle()
        assert h_long.done() and all(h.done() for h in residents)
        chunks = eng.stats.counters.get("prefill_chunks", 0)
        recompiles = int(compile_event_count() - before)
    finally:
        eng.stop()
    return {
        "prompt_len": prompt_len,
        "chunk_width": chunk,
        "chunks": int(chunks),
        "resident_requests": 2,
        "resident_max_stall_ticks": int(max_stall),
        "ttft_ms": round(ttft_ms, 2),
        "tokens_per_sec": None,
        "recompiles_steady_state": recompiles,
    }


def main() -> None:
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "--one":
        impl, S, B, H, D = sys.argv[2], *map(int, sys.argv[3:7])
        from bench import _detect_backend

        backend = _detect_backend()
        res = _time_attn(impl, S, B, H, D)
        # Always a dict tagged with the backend the child ACTUALLY ran
        # on: if the tunnel drops mid-sweep, _detect_backend degrades to
        # CPU and the parent must not record interpreter timings as TPU.
        out = res if isinstance(res, dict) else {"result": res}
        out["backend"] = backend
        print(json.dumps(out))
        return

    from bench import _detect_backend

    on_tpu = _detect_backend() == "tpu"
    H, D = 12, 64
    result = {
        "metric": "long_context_flash_vs_xla",
        "backend": "tpu" if on_tpu else "cpu",
        # Max-device work / ideal share (1.0 = balanced): the ring's
        # causal wall-clock multiplier per layout, 8-way ring.
        "ring_imbalance_contiguous": round(
            _work_imbalance(8, "contiguous"), 3),
        "ring_imbalance_zigzag": round(_work_imbalance(8, "zigzag"), 3),
    }
    if on_tpu:
        # The O(S·D)-memory flash kernel vs the O(S²) XLA einsum across
        # the long-context sweep (VERDICT r4 next #7).  Batch shrinks
        # with seq so the flash config always fits; an XLA OOM at long
        # seq is itself the datapoint.  One subprocess per entry so each
        # peak-HBM number is isolated.
        sweep = {}
        for S, B in ((4096, 4), (8192, 2), (16384, 1), (32768, 1)):
            sweep[str(S)] = {
                "batch": B,
                "flash": _one_in_subprocess("flash", S, B, H, D),
                "xla": _one_in_subprocess("xla", S, B, H, D),
            }
        result["seq_sweep_fwd_bwd"] = sweep
    # The serving-side long-context arm: chunked prefill vs resident
    # decode traffic (schema-gated; fails the bench on a stall or a
    # steady-state recompile).
    from ray_lightning_tpu.telemetry.schema import (
        validate_bench_chunked_prefill,
    )

    chunked = _chunked_prefill_block()
    problems = validate_bench_chunked_prefill(chunked)
    if chunked["resident_max_stall_ticks"] > 1:
        problems.append(
            f"chunked_prefill: resident stalled "
            f"{chunked['resident_max_stall_ticks']} ticks — the "
            "no-stall bound is 1 chunk tick"
        )
    if chunked["recompiles_steady_state"] != 0:
        problems.append(
            f"chunked_prefill: {chunked['recompiles_steady_state']} "
            "steady-state recompile(s)"
        )
    if problems:
        for p in problems:
            sys.stderr.write(f"bench_long_context schema: {p}\n")
        raise SystemExit(1)
    result["chunked_prefill"] = chunked
    print(json.dumps(result))
    with open("BENCH_LONGCTX.json", "w") as f:
        json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
