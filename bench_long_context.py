"""Long-context attention bench: seq-4096 flash vs ring (zig-zag vs
contiguous).  Prints ONE JSON line.

On real TPU hardware this records the single-chip flash-attention
fwd+bwd number at seq 4096 (the baseline sequence parallelism must beat
at scale).  Multi-chip SP cannot be timed meaningfully in this
environment (one physical chip; the CPU-mesh ring measures thread
scheduling, not ICI) — so the ring layouts are additionally compared by
their *causal work balance*: the max-over-devices count of unmasked
(query, key) block pairs per hop, the quantity that sets ring wall-clock.
Zig-zag's bound is ~half of contiguous — the same 2x the Megatron
context-parallel striped layout reports on hardware.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from ray_lightning_tpu.ops.ring_attention import zigzag_indices  # noqa: F401


def _work_imbalance(n: int, layout: str) -> float:
    """Max-over-devices unmasked attention AREA divided by the perfectly
    balanced share (total causal area / n).  1.0 = ideal; the contiguous
    layout's last device approaches ~2.0 (it owns the final chunk, which
    attends to everything), which is the ring's wall-clock multiplier."""
    if layout == "zigzag":
        chunks = {j: (j, 2 * n - 1 - j) for j in range(n)}
        n_chunks = 2 * n
    else:
        chunks = {j: (j,) for j in range(n)}
        n_chunks = n
    cell = (1.0 / n_chunks) ** 2  # area of one full (qc, kc) chunk pair
    per_dev = []
    for dev in range(n):
        total = 0.0
        for src in range(n):  # one hop per source device
            for qc in chunks[dev]:
                for kc in chunks[src]:
                    if kc < qc:
                        total += cell
                    elif kc == qc:
                        total += cell / 2
        per_dev.append(total)
    ideal = sum(per_dev) / n
    return max(per_dev) / ideal


def main() -> None:
    from bench import _detect_backend

    on_tpu = _detect_backend() == "tpu"
    S, B, H, D = 4096, 4, 12, 64
    result = {
        "metric": "long_context_seq4096",
        # Max-device work / ideal share (1.0 = balanced): the ring's
        # causal wall-clock multiplier per layout, 8-way ring.
        "ring_imbalance_contiguous": round(
            _work_imbalance(8, "contiguous"), 3),
        "ring_imbalance_zigzag": round(_work_imbalance(8, "zigzag"), 3),
    }
    if on_tpu:
        from ray_lightning_tpu.ops.flash_attention import flash_attention

        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (B, S, H, D), jnp.bfloat16)
        k, v = q * 0.99, q * 1.01

        def fb(q, k, v):
            g = jax.grad(
                lambda q, k, v: flash_attention(q, k, v)
                .astype(jnp.float32).sum(), argnums=(0, 1, 2),
            )(q, k, v)
            return sum(x.astype(jnp.float32).sum() for x in g)

        f = jax.jit(fb)
        s = f(q, k, v)
        float(jax.device_get(s))
        t0 = time.perf_counter()
        for _ in range(10):
            s = f(q, k, v)
        float(jax.device_get(s))
        dt = (time.perf_counter() - t0) / 10
        result.update({
            "flash_seq4096_fwd_bwd_ms_single_chip": round(dt * 1000, 2),
            "flash_seq4096_tokens_per_sec": round(B * S / dt, 1),
        })
    print(json.dumps(result))


if __name__ == "__main__":
    main()
