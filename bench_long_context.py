"""Long-context attention bench: seq-4096 flash vs ring (zig-zag vs
contiguous).  Prints ONE JSON line.

On real TPU hardware this records the single-chip flash-attention
fwd+bwd number at seq 4096 (the baseline sequence parallelism must beat
at scale).  Multi-chip SP cannot be timed meaningfully in this
environment (one physical chip; the CPU-mesh ring measures thread
scheduling, not ICI) — so the ring layouts are additionally compared by
their *causal work balance*: the max-over-devices count of unmasked
(query, key) block pairs per hop, the quantity that sets ring wall-clock.
Zig-zag's bound is ~half of contiguous — the same 2x the Megatron
context-parallel striped layout reports on hardware.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from ray_lightning_tpu.ops.ring_attention import zigzag_indices  # noqa: F401


def _work_imbalance(n: int, layout: str) -> float:
    """Max-over-devices unmasked attention AREA divided by the perfectly
    balanced share (total causal area / n).  1.0 = ideal; the contiguous
    layout's last device approaches ~2.0 (it owns the final chunk, which
    attends to everything), which is the ring's wall-clock multiplier."""
    if layout == "zigzag":
        chunks = {j: (j, 2 * n - 1 - j) for j in range(n)}
        n_chunks = 2 * n
    else:
        chunks = {j: (j,) for j in range(n)}
        n_chunks = n
    cell = (1.0 / n_chunks) ** 2  # area of one full (qc, kc) chunk pair
    per_dev = []
    for dev in range(n):
        total = 0.0
        for src in range(n):  # one hop per source device
            for qc in chunks[dev]:
                for kc in chunks[src]:
                    if kc < qc:
                        total += cell
                    elif kc == qc:
                        total += cell / 2
        per_dev.append(total)
    ideal = sum(per_dev) / n
    return max(per_dev) / ideal


def _peak_hbm_mb() -> float | None:
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            return round(stats["peak_bytes_in_use"] / 2**20, 1)
    except Exception:  # noqa: BLE001 - not all runtimes expose stats
        pass
    return None


def _time_attn(impl: str, S: int, B: int, H: int, D: int, reps: int = 5):
    """Fwd+bwd wall time for one attention impl at (B, S, H, D); returns
    (ms, tokens_per_sec, peak_hbm_mb) or an 'oom'/error marker string."""
    from ray_lightning_tpu.ops.attention import causal_attention

    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, S, H, D), jnp.bfloat16)
    k, v = q * 0.99, q * 1.01

    def fb(q, k, v):
        g = jax.grad(
            lambda q, k, v: causal_attention(q, k, v, impl=impl)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2),
        )(q, k, v)
        return sum(x.astype(jnp.float32).sum() for x in g)

    try:
        f = jax.jit(fb)
        float(jax.device_get(f(q, k, v)))  # compile + one run
        t0 = time.perf_counter()
        for _ in range(reps):
            s = f(q, k, v)
        float(jax.device_get(s))
        dt = (time.perf_counter() - t0) / reps
        return {
            "ms": round(dt * 1000, 2),
            "tokens_per_sec": round(B * S / dt, 1),
            "peak_hbm_mb": _peak_hbm_mb(),
        }
    except Exception as e:  # noqa: BLE001 - OOM at long seq is a finding
        msg = str(e).lower()
        return "oom" if ("resource_exhausted" in msg or "memory" in msg) \
            else f"error: {str(e)[:120]}"


def _one_in_subprocess(impl: str, S: int, B: int, H: int, D: int):
    """Run one (impl, S) measurement in a FRESH process so
    ``peak_bytes_in_use`` (a process-lifetime monotone max) is the peak
    of exactly this config — in-process, every entry after the first
    would inherit the largest earlier peak."""
    import os
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", impl,
             str(S), str(B), str(H), str(D)],
            capture_output=True, text=True, timeout=1200,
        )
    except subprocess.TimeoutExpired:
        # One slow config (e.g. the O(S^2) XLA arm at 32k) must not
        # discard the measurements already collected.
        return "error: timeout (1200s)"
    # The child prints one backend-tagged JSON dict; failed measurements
    # carry the marker under "result".
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            out = json.loads(line)
        except ValueError:
            continue
        if not isinstance(out, dict):
            continue
        if out.get("backend") != "tpu":
            return (f"error: child ran on {out.get('backend')!r}, not tpu "
                    f"(tunnel dropped mid-sweep?)")
        out.pop("backend", None)
        return out.get("result", out)
    return f"error: subprocess rc={proc.returncode}: {proc.stderr[-200:]}"


def main() -> None:
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "--one":
        impl, S, B, H, D = sys.argv[2], *map(int, sys.argv[3:7])
        from bench import _detect_backend

        backend = _detect_backend()
        res = _time_attn(impl, S, B, H, D)
        # Always a dict tagged with the backend the child ACTUALLY ran
        # on: if the tunnel drops mid-sweep, _detect_backend degrades to
        # CPU and the parent must not record interpreter timings as TPU.
        out = res if isinstance(res, dict) else {"result": res}
        out["backend"] = backend
        print(json.dumps(out))
        return

    from bench import _detect_backend

    on_tpu = _detect_backend() == "tpu"
    H, D = 12, 64
    result = {
        "metric": "long_context_flash_vs_xla",
        "backend": "tpu" if on_tpu else "cpu",
        # Max-device work / ideal share (1.0 = balanced): the ring's
        # causal wall-clock multiplier per layout, 8-way ring.
        "ring_imbalance_contiguous": round(
            _work_imbalance(8, "contiguous"), 3),
        "ring_imbalance_zigzag": round(_work_imbalance(8, "zigzag"), 3),
    }
    if on_tpu:
        # The O(S·D)-memory flash kernel vs the O(S²) XLA einsum across
        # the long-context sweep (VERDICT r4 next #7).  Batch shrinks
        # with seq so the flash config always fits; an XLA OOM at long
        # seq is itself the datapoint.  One subprocess per entry so each
        # peak-HBM number is isolated.
        sweep = {}
        for S, B in ((4096, 4), (8192, 2), (16384, 1), (32768, 1)):
            sweep[str(S)] = {
                "batch": B,
                "flash": _one_in_subprocess("flash", S, B, H, D),
                "xla": _one_in_subprocess("xla", S, B, H, D),
            }
        result["seq_sweep_fwd_bwd"] = sweep
    print(json.dumps(result))
    with open("BENCH_LONGCTX.json", "w") as f:
        json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
