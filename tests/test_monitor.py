"""Live observability plane: heartbeats, RunMonitor watchdog, crash
flight recorder, OpenMetrics export, rlt_top.

Unit tier drives the monitor with a fake clock and synthetic beats;
integration tier (marked ``remote``) injects real hangs/crashes into
worker actors and asserts the acceptance criteria of ISSUE 3: stall
detected within K heartbeat intervals, a stack-dump event naming the
stalled rank in ``trainer.monitor_report``, clean abort at the
deadline, and a schema-valid flight bundle named by the raised error.
"""

import glob
import json
import logging
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from ray_lightning_tpu.cluster.actor import ActorDiedError, RemoteError
from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models.boring import BoringDataModule, BoringModel
from ray_lightning_tpu.parallel.strategies import LocalStrategy, RayStrategy
from ray_lightning_tpu.telemetry import (
    MonitorConfig,
    RunMonitor,
    Telemetry,
    TelemetryConfig,
)
from ray_lightning_tpu.telemetry.export_prom import (
    PromExporter,
    render_openmetrics,
)
from ray_lightning_tpu.telemetry.flight_recorder import FlightRecorder
from ray_lightning_tpu.telemetry.heartbeat import (
    HeartbeatPublisher,
    make_beat,
)
from ray_lightning_tpu.telemetry.logs import RankLogHandler
from ray_lightning_tpu.telemetry.schema import (
    validate_event,
    validate_flight_bundle,
    validate_heartbeat,
    validate_stream_item,
)


class _Ctx:
    """Duck-typed LoopContext stand-in for worker-side unit tests."""

    def __init__(self):
        self.global_step = 0
        self.micro_step = 0
        self.current_epoch = 0
        self.progress = 0
        self.phase = "train"
        self.telemetry_dir = None


class _ListSink:
    def __init__(self):
        self.items = []

    def put(self, item):
        self.items.append(item)


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _beat(rank=0, seq=1, step=0, progress=0, phase="train", done=False):
    ctx = _Ctx()
    ctx.global_step = step
    ctx.micro_step = step
    ctx.progress = progress
    ctx.phase = phase
    return make_beat(rank, seq, ctx, done=done)


def _monitor(clock, heartbeat_s=1.0, hang_intervals=2, **cfg_kw):
    cfg = MonitorConfig(
        heartbeat_s=heartbeat_s, hang_intervals=hang_intervals, **cfg_kw
    )
    return RunMonitor(cfg, world_size=2, now_fn=clock)


# ---------------------------------------------------------------------------
# Heartbeat publisher
# ---------------------------------------------------------------------------

class TestHeartbeat:
    def test_beat_schema_valid(self):
        beat = _beat(rank=3, seq=7, step=12, progress=40)
        assert validate_heartbeat(beat) == []
        assert beat["rank"] == 3 and beat["global_step"] == 12

    def test_publisher_beats_and_final_done(self):
        ctx, sink = _Ctx(), _ListSink()
        tel = Telemetry(TelemetryConfig(tier="cheap", heartbeat_s=0.05))
        pub = HeartbeatPublisher(0, ctx, sink, 0.05, telemetry=tel)
        pub.start()
        deadline = time.time() + 5
        while len(sink.items) < 3 and time.time() < deadline:
            ctx.progress += 1
            time.sleep(0.02)
        pub.stop(final=True)
        assert len(sink.items) >= 3, "publisher produced too few beats"
        problems = [
            p for b in sink.items for p in validate_stream_item(b)
        ]
        assert problems == []
        assert sink.items[-1].get("done") is True
        seqs = [b["seq"] for b in sink.items]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)

    def test_maybe_start_gates(self, tmp_path):
        ctx = _Ctx()
        off = Telemetry(TelemetryConfig(tier="off"))
        assert HeartbeatPublisher.maybe_start(off, ctx, None, None) is None
        disabled = Telemetry(TelemetryConfig(tier="cheap", heartbeat_s=0))
        assert (
            HeartbeatPublisher.maybe_start(disabled, ctx, None, None)
            is None
        )
        # No queue AND no telemetry dir: nowhere to publish.
        cheap = Telemetry(TelemetryConfig(tier="cheap", heartbeat_s=1))
        assert HeartbeatPublisher.maybe_start(cheap, ctx, None, None) is None
        # File sink engages once the dir exists.
        ctx.telemetry_dir = str(tmp_path)
        pub = HeartbeatPublisher.maybe_start(cheap, ctx, None, None)
        assert pub is not None
        pub.stop()
        assert (tmp_path / "heartbeats-rank0.jsonl").exists()

    def test_publisher_survives_dead_sink(self):
        class DeadSink:
            def put(self, item):
                raise ConnectionError("queue gone")

        ctx = _Ctx()
        pub = HeartbeatPublisher(0, ctx, DeadSink(), 0.01)
        pub.start()
        time.sleep(0.1)
        pub.stop(final=True)  # must not raise
        assert pub.beats_sent == 0


# ---------------------------------------------------------------------------
# RunMonitor watchdog rules (fake clock)
# ---------------------------------------------------------------------------

class TestRunMonitor:
    def test_tracks_ranks_and_progress(self):
        clock = _Clock()
        mon = _monitor(clock)
        mon.on_item(_beat(rank=0, seq=1, step=1, progress=1))
        mon.on_item(_beat(rank=1, seq=1, step=1, progress=1))
        snap = mon.snapshot()
        assert snap["ranks_reporting"] == 2
        assert snap["ranks"]["0"]["status"] == "ok"
        assert mon.beats_received == 2

    def test_stall_detected_within_k_intervals_and_dump_requested(self):
        clock = _Clock()
        dumps = []

        def dump_cb(rank):
            dumps.append(rank)
            return {"stacks": "thread 1: stuck in collective",
                    "device_memory": {"bytes_in_use": 5.0}}

        cfg = MonitorConfig(heartbeat_s=1.0, hang_intervals=2)
        mon = RunMonitor(cfg, world_size=2, now_fn=clock, dump_cb=dump_cb)
        # Both ranks make progress, then rank 1 freezes while its beats
        # keep flowing (the wedged-collective signature).
        for seq in range(1, 3):
            mon.on_item(_beat(rank=0, seq=seq, step=seq, progress=seq))
            mon.on_item(_beat(rank=1, seq=seq, step=seq, progress=seq))
            clock.advance(1.0)
            mon.tick()
        for seq in range(3, 7):
            mon.on_item(_beat(rank=0, seq=seq, step=seq, progress=seq))
            mon.on_item(_beat(rank=1, seq=seq, step=2, progress=2))
            clock.advance(1.0)
            mon.tick()
        kinds = [(e["kind"], e["rank"]) for e in mon.events]
        assert ("stall", 1) in kinds
        assert dumps == [1]
        dump_ev = next(e for e in mon.events if e["kind"] == "stack_dump")
        assert dump_ev["rank"] == 1
        assert "collective" in dump_ev["stacks"]
        assert dump_ev["device_memory"] == {"bytes_in_use": 5.0}
        assert all(validate_event(e) == [] for e in mon.events)
        # rank 0 kept advancing: never flagged
        assert ("stall", 0) not in kinds

    def test_heartbeat_lost_when_beats_stop(self):
        clock = _Clock()
        mon = _monitor(clock, heartbeat_s=1.0, hang_intervals=3)
        mon.on_item(_beat(rank=0, seq=1, step=1, progress=1))
        clock.advance(3.5)
        mon.tick()
        kinds = [e["kind"] for e in mon.events]
        assert "heartbeat_lost" in kinds
        assert mon.snapshot()["ranks"]["0"]["status"] == "lost"

    def test_compile_phase_never_flags(self):
        """Detection arms only after real progress — a long first
        compile (progress == 0) must not read as a hang."""
        clock = _Clock()
        mon = _monitor(clock, heartbeat_s=1.0, hang_intervals=2)
        for seq in range(1, 10):
            mon.on_item(_beat(rank=0, seq=seq, step=0, progress=0))
            clock.advance(1.0)
            mon.tick()
        assert [e for e in mon.events if e["kind"] == "stall"] == []

    def test_phase_change_rearms_detection(self):
        """A phase flip (train→validation) resets the arming: the first
        validation batch may hide a 20-40s eval compile that must not
        read as a hang.  Detection re-engages once the new phase shows
        progress and then freezes."""
        clock = _Clock()
        mon = _monitor(clock, heartbeat_s=1.0, hang_intervals=2)
        for seq in (1, 2):
            mon.on_item(_beat(rank=0, seq=seq, step=seq, progress=seq))
            clock.advance(1.0)
            mon.tick()
        # Validation starts; progress frozen through a long compile.
        for seq in range(3, 10):
            mon.on_item(_beat(rank=0, seq=seq, step=2, progress=2,
                              phase="validation"))
            clock.advance(1.0)
            mon.tick()
        assert [e for e in mon.events if e["kind"] == "stall"] == []
        # Progress inside validation, THEN a freeze: now it is a hang.
        mon.on_item(_beat(rank=0, seq=10, step=2, progress=3,
                          phase="validation"))
        for seq in range(11, 16):
            mon.on_item(_beat(rank=0, seq=seq, step=2, progress=3,
                              phase="validation"))
            clock.advance(1.0)
            mon.tick()
        assert [e for e in mon.events if e["kind"] == "stall"] != []

    def test_closing_phase_exempt_and_done_retires(self):
        clock = _Clock()
        mon = _monitor(clock, heartbeat_s=1.0, hang_intervals=2)
        mon.on_item(_beat(rank=0, seq=1, step=4, progress=9))
        clock.advance(1.0)
        mon.on_item(_beat(rank=0, seq=2, step=4, progress=9,
                          phase="closing"))
        for _ in range(5):
            clock.advance(1.0)
            mon.tick()
            mon.on_item(_beat(rank=0, seq=3, step=4, progress=9,
                              phase="closing"))
        assert [e for e in mon.events if e["kind"] == "stall"] == []
        mon.on_item(_beat(rank=0, seq=4, step=4, progress=9, done=True))
        clock.advance(10.0)
        mon.tick()
        assert [e for e in mon.events if e["kind"] == "heartbeat_lost"] == []
        assert mon.snapshot()["ranks"]["0"]["status"] == "done"

    def test_straggler_flagged_live(self):
        clock = _Clock()
        cfg = MonitorConfig(heartbeat_s=1.0, straggler_lag_steps=10)
        mon = RunMonitor(cfg, world_size=2, now_fn=clock)
        mon.on_item(_beat(rank=0, seq=1, step=100, progress=100))
        mon.on_item(_beat(rank=1, seq=1, step=50, progress=50))
        clock.advance(1.0)
        mon.tick()
        stragglers = [
            e for e in mon.events if e["kind"] == "straggler"
        ]
        assert len(stragglers) == 1 and stragglers[0]["rank"] == 1
        assert stragglers[0]["lag_steps"] >= 10

    def test_abort_after_deadline(self):
        clock = _Clock()
        aborts = []
        cfg = MonitorConfig(heartbeat_s=1.0, hang_intervals=2,
                            abort_after_s=3.0)
        mon = RunMonitor(cfg, world_size=1, now_fn=clock,
                         abort_cb=aborts.append)
        mon.on_item(_beat(rank=0, seq=1, step=1, progress=1))
        for seq in range(2, 10):
            mon.on_item(_beat(rank=0, seq=seq, step=1, progress=1))
            clock.advance(1.0)
            mon.tick()
        assert mon.aborted
        assert len(aborts) == 1 and "abort_after_s" in aborts[0]
        assert any(e["kind"] == "abort" for e in mon.events)
        report = mon.report()
        assert report["aborted"] and "abort_reason" in report

    def test_crash_event_tracks_bundle(self):
        clock = _Clock()
        mon = _monitor(clock)
        mon.on_item({"type": "event", "kind": "crash", "rank": 1,
                     "ts": time.time(), "error": "boom",
                     "bundle": "/tmp/b.json"})
        assert mon.crash_bundles() == ["/tmp/b.json"]
        assert mon.snapshot()["ranks"]["1"]["status"] == "crashed"

    def test_log_items_land_in_report(self):
        clock = _Clock()
        mon = _monitor(clock)
        mon.on_item({"type": "log", "rank": 0, "ts": 1.0,
                     "level": "WARNING", "logger": "x", "message": "m"})
        assert mon.report()["logs"]["0"][0]["message"] == "m"

    def test_live_json_written(self, tmp_path):
        clock = _Clock()
        cfg = MonitorConfig(heartbeat_s=1.0, out_dir=str(tmp_path),
                            live_every_s=0.0)
        mon = RunMonitor(cfg, world_size=1, now_fn=clock)
        mon.on_item(_beat(rank=0, seq=1, step=2, progress=2))
        clock.advance(1.0)
        mon.tick()
        mon.finalize()
        live = json.load(open(tmp_path / "live.json"))
        assert live["ranks"]["0"]["global_step"] == 2


# ---------------------------------------------------------------------------
# OpenMetrics export + rlt_top
# ---------------------------------------------------------------------------

class TestPromExport:
    def _snapshot(self):
        clock = _Clock()
        mon = _monitor(clock)
        mon.on_item(_beat(rank=0, seq=1, step=5, progress=5))
        return mon.snapshot(), mon.event_counts()

    def test_render_openmetrics(self):
        snap, counts = self._snapshot()
        text = render_openmetrics(snap, counts)
        assert 'rlt_rank_global_step{rank="0"} 5' in text
        assert "# TYPE rlt_fleet_ranks gauge" in text
        assert text.rstrip().endswith("# EOF")

    def test_textfile_and_http(self, tmp_path):
        snap, counts = self._snapshot()
        out = tmp_path / "rlt.prom"
        exporter = PromExporter(textfile=str(out), port=0)
        try:
            exporter.update(snap, counts)
            assert "rlt_rank_global_step" in out.read_text()
            assert exporter.port is not None
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/metrics", timeout=10
            ).read().decode()
            assert 'rlt_rank_global_step{rank="0"} 5' in body
        finally:
            exporter.close()

    def test_rlt_top_renders_live_json(self, tmp_path):
        snap, _ = self._snapshot()
        (tmp_path / "live.json").write_text(json.dumps(snap, default=str))
        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "tools",
                          "rlt_top.py"),
             "--once", str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "rank" in out.stdout and "ok" in out.stdout


# ---------------------------------------------------------------------------
# Log ring + flight recorder (worker side, no actors)
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_log_ring_and_forwarding(self):
        sink = _ListSink()
        handler = RankLogHandler(2, queue=sink, ring_size=3,
                                 forward_cap=2).install()
        try:
            log = logging.getLogger("rlt.test.ring")
            for i in range(5):
                log.warning("w%d", i)
        finally:
            handler.uninstall()
        records = handler.records()
        assert [r["message"] for r in records] == ["w2", "w3", "w4"]
        assert len(sink.items) == 2  # forward cap holds
        assert all(validate_stream_item(i) == [] for i in sink.items)
        assert sink.items[0]["rank"] == 2

    def test_bundle_schema_and_contents(self, tmp_path):
        ctx = _Ctx()
        ctx.global_step, ctx.micro_step, ctx.progress = 4, 8, 12
        tel = Telemetry(TelemetryConfig(tier="full", heartbeat_s=0))
        with tel.span("dispatch"):
            pass
        tel.add_counter("checkpoint_writes", 1)
        handler = RankLogHandler(0, ring_size=5)
        handler.install()
        logging.getLogger("rlt.test.fr").warning("about to die")
        handler.uninstall()
        rec = FlightRecorder(0, str(tmp_path), ctx, telemetry=tel,
                             log_handler=handler)
        sink = _ListSink()
        rec._queue = sink
        try:
            raise RuntimeError("synthetic crash")
        except RuntimeError as err:
            path = rec.record_crash(err)
        doc = json.load(open(path))
        assert validate_flight_bundle(doc) == []
        assert "synthetic crash" in doc["error"]
        assert doc["global_step"] == 4 and doc["micro_step"] == 8
        assert doc["counters"]["checkpoint_writes"] == 1
        assert any(s["name"] == "dispatch" for s in doc["spans"])
        assert any("about to die" in r["message"] for r in doc["logs"])
        assert "test_bundle_schema_and_contents" in doc["stacks"]
        # The crash also travelled as an event naming the bundle.
        assert sink.items and sink.items[0]["bundle"] == path
        assert validate_stream_item(sink.items[0]) == []

    def test_bundles_disabled_still_cleans_up_plane(self, tmp_path):
        """RLT_FLIGHT_RECORDER=off gates the OUTPUT only: a crash must
        still stop the heartbeat thread and remove the log handler, or
        a disabled recorder would leak a publisher per failed fit."""

        class StubHeartbeat:
            stopped = None

            def stop(self, final=True, **kw):
                self.stopped = final

        ctx = _Ctx()
        handler = RankLogHandler(0, ring_size=5).install()
        hb = StubHeartbeat()
        rec = FlightRecorder(0, str(tmp_path), ctx, log_handler=handler,
                             heartbeat=hb, bundles_enabled=False)
        rec.install()
        try:
            raise RuntimeError("crash with output disabled")
        except RuntimeError as err:
            path = rec.record_crash(err)
        assert path is None
        assert list(tmp_path.iterdir()) == []  # no bundle, no fatal log
        assert hb.stopped is False  # stopped, without a "done" beat
        assert handler not in logging.getLogger().handlers

    def test_fixture_bundle_schema_valid(self):
        fixture = os.path.join(
            os.path.dirname(__file__), "data", "flight_bundle.json"
        )
        doc = json.load(open(fixture))
        assert validate_flight_bundle(doc) == []

    def test_off_tier_installs_nothing(self, tmp_path):
        tel = Telemetry(TelemetryConfig(tier="off"))
        ctx = _Ctx()
        ctx.telemetry_dir = str(tmp_path)
        assert FlightRecorder.maybe_install(tel, ctx, None) is None


# ---------------------------------------------------------------------------
# Trainer stream routing (the metrics rank-guard satellite)
# ---------------------------------------------------------------------------

class TestStreamRouting:
    def test_non_rank0_metrics_rejected(self):
        trainer = Trainer(strategy=LocalStrategy())
        trainer._on_stream_item(
            {"type": "metrics", "rank": 1, "metrics": {"loss": 99.0}}
        )
        assert "loss" not in trainer.callback_metrics
        trainer._on_stream_item(
            {"type": "metrics", "rank": 0, "metrics": {"loss": 1.0}}
        )
        assert trainer.callback_metrics["loss"] == 1.0

    def test_typed_items_route_to_monitor_not_metrics(self):
        trainer = Trainer(strategy=LocalStrategy())
        clock = _Clock()
        mon = _monitor(clock)
        trainer._attach_monitor(mon)
        trainer._on_stream_item(_beat(rank=0, seq=1, step=1, progress=1))
        trainer._on_stream_item({"type": "event", "kind": "stall",
                                 "rank": 0, "ts": 1.0})
        assert trainer.callback_metrics == {}
        assert mon.beats_received == 1 and len(mon.events) == 1
        trainer._adopt_monitor(mon)
        assert trainer.monitor_report["beats"] == 1
        assert trainer._monitor is None


# ---------------------------------------------------------------------------
# Integration: real worker actors (the ISSUE 3 acceptance criteria)
# ---------------------------------------------------------------------------

class _StallAt(Callback):
    """Wedge the loop thread mid-training — the observable behavior of
    a sleep inside training_step, injected host-side so it hits every
    step boundary deterministically."""

    def __init__(self, epoch=1, batch=0, sleep_s=300.0):
        self.epoch = epoch
        self.batch = batch
        self.sleep_s = sleep_s

    def on_train_batch_end(self, trainer, module, logs, batch_idx):
        if trainer.current_epoch == self.epoch and batch_idx == self.batch:
            time.sleep(self.sleep_s)


class _CrashAt(Callback):
    def on_train_batch_end(self, trainer, module, logs, batch_idx):
        if batch_idx == 1:
            raise RuntimeError("injected mid-fit crash")


@pytest.mark.remote
class TestLivePlaneIntegration:
    @pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
    def test_hang_detected_dumped_and_aborted(self, tmp_path):
        """Acceptance: a stalled worker is detected within K heartbeat
        intervals, a stack-dump event names the stalled rank in
        ``trainer.monitor_report["events"]``, and the fit aborts
        cleanly when the deadline is set."""
        trainer = Trainer(
            strategy=RayStrategy(
                num_workers=1,
                telemetry={"tier": "cheap", "heartbeat_s": 0.2},
                monitor={"hang_intervals": 2, "abort_after_s": 1.0},
            ),
            max_epochs=1,
            default_root_dir=str(tmp_path),
            # batch 1: the rank has shown progress, so stall detection
            # is armed (batch 0 would read as a long compile).
            callbacks=[_StallAt(epoch=0, batch=1)],
        )
        with pytest.raises(ActorDiedError) as excinfo:
            trainer.fit(BoringModel(), BoringDataModule())
        report = trainer.monitor_report
        kinds = [(e["kind"], e["rank"]) for e in report["events"]]
        assert ("stall", 0) in kinds
        dump = next(
            e for e in report["events"] if e["kind"] == "stack_dump"
        )
        assert dump["rank"] == 0
        # The dump reached INTO the wedged call: the fit loop's frames
        # are visible even though the actor was mid-call.
        assert "run_fit" in dump["stacks"]
        assert report["aborted"]
        assert "RunMonitor" in str(excinfo.value)
        assert excinfo.value.rank == 0
        assert excinfo.value.last_heartbeat_age_s is not None

    def test_crash_leaves_bundle_and_error_names_it(self, tmp_path):
        """Acceptance: a worker raising mid-fit leaves a schema-valid
        flight bundle on disk and the driver-side error names it."""
        trainer = Trainer(
            strategy=RayStrategy(
                num_workers=1,
                telemetry={"tier": "cheap", "heartbeat_s": 0.2},
            ),
            max_epochs=1,
            default_root_dir=str(tmp_path),
            callbacks=[_CrashAt()],
        )
        with pytest.raises(RemoteError) as excinfo:
            trainer.fit(BoringModel(), BoringDataModule())
        bundles = glob.glob(
            str(tmp_path / "telemetry" / "flight" / "bundle-*.json")
        )
        assert len(bundles) == 1
        doc = json.load(open(bundles[0]))
        assert validate_flight_bundle(doc) == []
        assert "injected mid-fit crash" in doc["traceback"]
        assert bundles[0] in str(excinfo.value)
        assert trainer.monitor_report["crash_bundles"] == bundles

    def test_worker_death_report_enriched(self, tmp_path):
        """Satellite: ActorDiedError carries exit code + rank +
        last-heartbeat age, so the report says when/how, not just that."""

        class Die(Callback):
            def on_train_batch_end(self, trainer, module, logs, batch_idx):
                if batch_idx == 1:
                    os._exit(7)

        trainer = Trainer(
            strategy=RayStrategy(
                num_workers=1,
                telemetry={"tier": "cheap", "heartbeat_s": 0.2},
            ),
            max_epochs=1,
            default_root_dir=str(tmp_path),
            callbacks=[Die()],
        )
        with pytest.raises(ActorDiedError) as excinfo:
            trainer.fit(BoringModel(), BoringDataModule())
        err = excinfo.value
        assert err.rank == 0
        assert err.exit_code == 7
        assert err.last_heartbeat_age_s is not None
        assert "exit_code=7" in str(err)

    def test_off_tier_installs_no_plane(self, tmp_path):
        """Acceptance: telemetry="off" → no publisher, no monitor, no
        new metric keys, no live artifacts."""
        trainer = Trainer(
            strategy=RayStrategy(num_workers=1, telemetry="off"),
            max_epochs=1,
            default_root_dir=str(tmp_path),
        )
        trainer.fit(BoringModel(), BoringDataModule())
        assert trainer.monitor_report == {}
        assert "step_time_ms" not in trainer.callback_metrics
        tel_dir = tmp_path / "telemetry"
        assert not list(tel_dir.glob("heartbeats-*")) if tel_dir.exists() \
            else True
        assert not (tel_dir / "live.json").exists()
        assert not (tel_dir / "flight").exists()

    def test_healthy_fit_clean_report_and_live_json(self, tmp_path):
        """A healthy monitored fit: beats arrive, no events, live.json
        reflects the final state, the rank retires as done."""
        trainer = Trainer(
            strategy=RayStrategy(
                num_workers=1,
                telemetry={"tier": "cheap", "heartbeat_s": 0.1},
            ),
            max_epochs=1,
            default_root_dir=str(tmp_path),
        )
        trainer.fit(BoringModel(), BoringDataModule())
        report = trainer.monitor_report
        assert report["beats"] >= 1
        assert report["events"] == []
        assert not report["aborted"]
        live = json.load(open(tmp_path / "telemetry" / "live.json"))
        assert live["ranks"]["0"]["status"] == "done"

    def test_heartbeat_overhead_smoke(self, tmp_path):
        """LOOSE wall-clock bound (the precise number is bench.py's
        ``heartbeat_overhead_pct``): an aggressive 20ms cadence must
        not change the fit's cost class vs a publisher-less run."""

        def run(hb, sub):
            t0 = time.time()
            trainer = Trainer(
                strategy=LocalStrategy(
                    telemetry={"tier": "cheap", "heartbeat_s": hb}
                ),
                max_epochs=2,
                default_root_dir=str(tmp_path / sub),
                enable_checkpointing=False,
                limit_val_batches=0,
            )
            trainer.fit(BoringModel(),
                        BoringDataModule(length=128, batch_size=16))
            return time.time() - t0

        silent = run(0, "off")
        beating = run(0.02, "on")
        assert beating < silent * 1.5 + 1.0, (
            f"heartbeat wall {beating:.2f}s vs silent {silent:.2f}s"
        )

    def test_dump_stacks_control_lane_mid_call(self):
        """The control lane answers while a call is in flight — the
        mechanism the watchdog's dumps depend on."""
        from ray_lightning_tpu.cluster.actor import ProcessActor

        actor = ProcessActor(name="ctl-actor")
        try:
            fut = actor.submit(time.sleep, 1.5)
            time.sleep(0.2)  # let the call start
            dump = actor.dump_stacks(timeout=10)
            assert "rlt-actor-calls" in dump["stacks"]
            assert not fut.done()  # dump answered while call still ran
            fut.result(timeout=30)
        finally:
            actor.kill()
