"""Backward-overlapped gradient sync (parallel/overlap.py) + quantized
MPMD wire (mpmd/transfer.py WireCodec): overlap-plan/partition units,
fit-level loss parity of the overlapped schedule against step-end sync
(bitwise at full width, 1%-relative at int8_ef) across accumulation /
megastep / ZeRO flavors, EF-residual reconciliation across a segment-
count change, wire-dtype parity + compression ratio on the in-process
2-worker pipeline, and the chaos contract on quantized SEND segments.
"""

import dataclasses
import warnings

import numpy as np
import pytest

import jax

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.models.gpt import GPT, GPTConfig, SyntheticLMDataModule
from ray_lightning_tpu.parallel import grad_sync as gsync
from ray_lightning_tpu.parallel import overlap as ovl
from ray_lightning_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_lightning_tpu.parallel.strategies import LocalStrategy


# -- knob normalization / resolution -----------------------------------------

def test_normalize_grad_overlap_values():
    assert ovl.normalize_grad_overlap(None) is None
    assert ovl.normalize_grad_overlap("") == 0
    assert ovl.normalize_grad_overlap("off") == 0
    assert ovl.normalize_grad_overlap("4") == 4
    assert ovl.normalize_grad_overlap(2) == 2
    with pytest.raises(ValueError, match="expected 'off'"):
        ovl.normalize_grad_overlap("bogus")
    with pytest.raises(ValueError, match=">= 0"):
        ovl.normalize_grad_overlap(-1)
    with pytest.raises(TypeError):
        ovl.normalize_grad_overlap(True)


def test_resolve_grad_overlap_env_bus(monkeypatch):
    monkeypatch.delenv("RLT_GRAD_OVERLAP", raising=False)
    assert ovl.resolve_grad_overlap(None) == 0
    monkeypatch.setenv("RLT_GRAD_OVERLAP", "3")
    assert ovl.resolve_grad_overlap(None) == 3
    # Explicit knob wins over the bus; an explicit "" clears it.
    assert ovl.resolve_grad_overlap(2) == 2
    assert ovl.resolve_grad_overlap("") == 0
    monkeypatch.setenv("RLT_GRAD_OVERLAP", "")
    assert ovl.resolve_grad_overlap(None) == 0


# -- overlap plan units ------------------------------------------------------

def _sds(*shape):
    return jax.ShapeDtypeStruct(shape, np.float32)


def _gpt3():
    # Three layers: segments=2 splits non-divisibly (2+1).
    return dataclasses.replace(GPTConfig.tiny(), n_layer=3)


def _gpt_plan(segments, n_shards=8, **kw):
    module = GPT(_gpt3())
    abstract = jax.eval_shape(module.init_params, jax.random.PRNGKey(0))
    spec = module.grad_overlap_groups(abstract, segments)
    return ovl.build_overlap_plan(spec, n_shards, **kw), abstract


def test_overlap_plan_partitions_gpt_exactly():
    plan, abstract = _gpt_plan(segments=2)
    mono = gsync.build_bucket_plan(abstract, n_shards=8)
    # The groups partition the whole tree — same element count as the
    # monolithic step-end plan, no leaf lost or double-counted.
    assert plan.total_elems == mono.total_elems
    # Backward-completion order: head first, trunk segments, embeddings
    # last; segments=2 over 3 layers splits non-divisibly.
    assert [g.name for g in plan.groups] == ["head", "seg0", "seg1", "embed"]
    assert plan.trunk_segments == 2
    seg_layers = [g.leaf_sizes for g in plan.groups if not g.entry]
    n_per_layer = sum(plan.group("seg0").leaf_sizes) // 2
    assert sum(plan.group("seg1").leaf_sizes) == n_per_layer
    assert len(seg_layers) == 2
    # Residual slices are contiguous and disjoint in group order.
    offset = 0
    for g in plan.groups:
        assert g.resid_offset == offset
        offset += g.plan.total_padded
    assert plan.total_padded == offset
    # Entry groups carry their top-level keys; trunk segments don't.
    assert set(plan.group("head").keys) == {"ln_f_g", "ln_f_b"}
    assert set(plan.group("embed").keys) == {"wte", "wpe"}
    assert plan.group("seg0").keys == ()


def test_overlap_plan_segments_clamp_to_layer_count():
    # More segments than layers: the module clamps to n_layer sub-scans.
    plan, _ = _gpt_plan(segments=16)
    assert plan.trunk_segments == 3


def test_overlap_plan_oversize_leaf_gets_own_bucket():
    # Within a group, a leaf exceeding bucket_bytes must not merge —
    # same packer rule as the step-end plan, applied per group.
    # Dict keys flatten alphabetically — a0/a1/a2 pins leaf order.
    spec = [
        ("a", {"a0": _sds(8), "a1": _sds(4096), "a2": _sds(8)}, True),
        ("b", {"x": _sds(64)}, True),
    ]
    plan = ovl.build_overlap_plan(
        spec, n_shards=2, bucket_bytes=1024, block_size=8
    )
    assert [b.indices for b in plan.group("a").plan.buckets] == [
        (0,), (1,), (2,)
    ]
    assert plan.num_buckets == 4
    # Accounting sums over groups like one plan.
    assert plan.wire_bytes_per_step("int8") == sum(
        g.plan.wire_bytes_per_step("int8") for g in plan.groups
    )


def test_overlap_plan_build_errors():
    with pytest.raises(ValueError, match="duplicate"):
        ovl.build_overlap_plan(
            [("g", {"w": _sds(8)}, True), ("g", {"x": _sds(8)}, True)],
            n_shards=2,
        )
    with pytest.raises(ValueError, match="must be a dict"):
        ovl.build_overlap_plan([("g", [_sds(8)], True)], n_shards=2)
    with pytest.raises(ValueError, match="no groups"):
        ovl.build_overlap_plan([], n_shards=2)


def test_tap_plane_guards_misrouted_forwards():
    plan = ovl.build_overlap_plan(
        [("g0", {"w": _sds(16)}, True), ("g1", {"v": _sds(16)}, True)],
        n_shards=2,
    )
    plane = ovl.TapPlane(plan, ("data",), 2, use_ef=False)
    with pytest.raises(ValueError, match="not in the overlap plan"):
        plane.tap("nope", {"w": np.zeros(16, np.float32)})
    # Layout drift between the declared group and the tapped subtree.
    with pytest.raises(ValueError, match="leaf layout"):
        plane.tap("g0", {"w": np.zeros(8, np.float32)})
    out = plane.tap("g0", {"w": np.zeros(16, np.float32)})
    assert out["w"].shape == (16,)
    with pytest.raises(ValueError, match="consumed twice"):
        plane.tap("g0", {"w": np.zeros(16, np.float32)})
    # g1 was declared but never tapped: a silent miss would drop its
    # sync, so the trace-end check must name it.
    with pytest.raises(ValueError, match="never tapped.*g1"):
        plane.check_consumed()


# -- resolution: loud downgrade + coverage failure ---------------------------

@pytest.fixture
def mesh8(cpu_mesh_devices):
    return build_mesh(MeshSpec({"data": 8}))


def test_overlap_without_groups_downgrades_loudly(mesh8):
    # BoringModel has no grad_overlap_groups: the sync stays active but
    # step-end — schedule changes are never silent.
    module = BoringModel(in_dim=64, out_dim=8)
    with pytest.warns(UserWarning, match="does not partition"):
        gs = gsync.maybe_build_grad_sync(
            module, mesh8, {"mode": "int8_ef", "dcn_only": False},
            overlap_segments=2,
        )
    assert gs is not None
    assert gs.overlap is None
    assert gs.stats()["grad_sync_overlap_segments"] == 0


def test_overlap_partition_coverage_enforced(mesh8):
    class LeakyGPT(GPT):
        def grad_overlap_groups(self, abstract_params, segments):
            groups = super().grad_overlap_groups(abstract_params, segments)
            return groups[:-1]  # drop the embed group: params uncovered

    module = LeakyGPT(_gpt3())
    with pytest.raises(ValueError, match="partition the whole param tree"):
        gsync.maybe_build_grad_sync(
            module, mesh8, {"mode": "int8_ef", "dcn_only": False},
            overlap_segments=2,
        )


def test_overlap_active_plan_is_the_overlap_plan(mesh8):
    module = GPT(_gpt3())
    gs = gsync.maybe_build_grad_sync(
        module, mesh8, {"mode": "int8_ef", "dcn_only": False},
        overlap_segments=2,
    )
    assert isinstance(gs.plan, ovl.OverlapPlan)
    assert gs.stats()["grad_sync_overlap_segments"] == 2
    # Wire accounting carries over: bytes come from the same codec and
    # alignment rule, so the compression ratio still clears the bar.
    full = gs.plan.wire_bytes_per_step("full")
    assert full / gs.plan.wire_bytes_per_step("int8") >= 3.5


def test_reconcile_residual_across_segment_change(mesh8):
    from ray_lightning_tpu.core.module import TrainState

    # Six layers: a 3+3 split pads each half-trunk group separately,
    # while one 6-layer group crosses an extra alignment boundary — the
    # two layouts land on different residual-row lengths (smaller layer
    # counts can coincide, which is exactly the silent case to avoid).
    module = GPT(dataclasses.replace(GPTConfig.tiny(), n_layer=6))

    def build(segments):
        return gsync.maybe_build_grad_sync(
            module, mesh8, {"mode": "int8_ef", "dcn_only": False},
            overlap_segments=segments,
        )

    g1, g2 = build(1), build(2)
    # The group layouts pad differently, so the residual rows disagree.
    assert g1.plan.total_padded != g2.plan.total_padded
    stale = TrainState(
        {}, None, 0, np.ones((8, g1.plan.total_padded), np.float32)
    )
    with pytest.warns(UserWarning, match="resetting to zero"):
        out = g2.reconcile_resumed_state(stale)
    assert out.grad_residual.shape == (8, g2.plan.total_padded)
    assert not out.grad_residual.any()
    # A residual already in this run's layout passes through untouched.
    good = TrainState(
        {}, None, 0, np.ones((8, g2.plan.total_padded), np.float32)
    )
    assert g2.reconcile_resumed_state(good) is good


# -- fit-level parity: overlapped vs step-end --------------------------------

def _fit_gpt(tmp_path, *, grad_comm, segments, accumulate=1,
             megastep=None, zero_stage=0, num_batches=8,
             resume=None, max_epochs=1):
    cfg = GPTConfig.tiny()
    trainer = Trainer(
        strategy=LocalStrategy(
            mesh_axes={"data": 8},
            grad_comm=grad_comm,
            grad_overlap_segments=segments,
            megastep=megastep,
            zero_stage=zero_stage,
        ),
        max_epochs=max_epochs,
        accumulate_grad_batches=accumulate,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        resume_from_checkpoint=resume,
    )
    trainer.fit(
        GPT(cfg), SyntheticLMDataModule(cfg, batch_size=8,
                                        num_batches=num_batches)
    )
    return trainer


def test_full_width_segmentation_is_bitwise_neutral(tmp_path):
    """G sub-scans with no taps (grad_comm full) run the same per-layer
    op sequence as the single scan — segmentation alone must not move a
    single bit, so RLT_GRAD_OVERLAP is safe to flip independently."""
    t0 = _fit_gpt(tmp_path / "g0", grad_comm="full", segments=0)
    t2 = _fit_gpt(tmp_path / "g2", grad_comm="full", segments=2)
    assert (
        t0.callback_metrics["train_loss"]
        == t2.callback_metrics["train_loss"]
    )


def test_overlap_ef_parity_fast(tmp_path):
    """The headline contract on the minimal config: same wire bytes,
    same mode, loss within 1% relative of the step-end schedule."""
    ef = {"mode": "int8_ef", "dcn_only": False}
    t_end = _fit_gpt(tmp_path / "end", grad_comm=ef, segments=0)
    t_ovl = _fit_gpt(tmp_path / "ovl", grad_comm=ef, segments=2)
    ref = t_end.callback_metrics["train_loss"]
    assert abs(t_ovl.callback_metrics["train_loss"] - ref) <= 0.01 * abs(ref)
    # Overlap changes the SCHEDULE, not the wire: same codec and
    # alignment rule, so bytes agree up to per-group padding (at most
    # align-1 extra elements per group — well under 2%).
    b_end = t_end.comm_stats["grad_sync_bytes"]
    b_ovl = t_ovl.comm_stats["grad_sync_bytes"]
    assert abs(b_ovl - b_end) <= 0.02 * b_end
    assert t_end.comm_stats["grad_sync_overlap_segments"] == 0
    assert t_ovl.comm_stats["grad_sync_overlap_segments"] == 2
    assert t_ovl.comm_stats["grad_sync_mode"] == "int8_ef"


@pytest.mark.slow
@pytest.mark.parametrize("accumulate,megastep,zero_stage", [
    (4, None, 0),
    (1, 4, 0),
    (1, None, 1),
    (4, 4, 1),
])
def test_overlap_ef_parity_matrix(tmp_path, accumulate, megastep,
                                  zero_stage):
    """Overlapped sync composes with the loop's other schedules —
    accumulation (taps fire per micro-batch, the accumulator averages
    synced grads), megastep (taps live inside the scanned stride body)
    and ZeRO-1 (sharded optimizer consumes the same synced grads)."""
    ef = {"mode": "int8_ef", "dcn_only": False}
    kw = dict(
        accumulate=accumulate, megastep=megastep, zero_stage=zero_stage,
        num_batches=16,
    )
    t_end = _fit_gpt(tmp_path / "end", grad_comm=ef, segments=0, **kw)
    t_ovl = _fit_gpt(tmp_path / "ovl", grad_comm=ef, segments=2, **kw)
    ref = t_end.callback_metrics["train_loss"]
    assert abs(t_ovl.callback_metrics["train_loss"] - ref) <= 0.01 * abs(ref)
    b_end = t_end.comm_stats["grad_sync_bytes"]
    b_ovl = t_ovl.comm_stats["grad_sync_bytes"]
    assert abs(b_ovl - b_end) <= 0.02 * b_end
    assert t_ovl.global_step == t_end.global_step


@pytest.mark.slow
def test_overlap_resume_across_segment_count_change(tmp_path):
    """A checkpoint from a G=2 EF fit resumes into a G=1 fit: gathers
    exclude the per-device residual, so the new layout attaches a fresh
    zero row and training proceeds on the new schedule."""
    ef = {"mode": "int8_ef", "dcn_only": False}
    t1 = _fit_gpt(tmp_path, grad_comm=ef, segments=2)
    ckpt = str(tmp_path / "g2.ckpt")
    t1.save_checkpoint(ckpt)
    t2 = _fit_gpt(
        tmp_path, grad_comm=ef, segments=1, resume=ckpt, max_epochs=2
    )
    assert t2.comm_stats["grad_sync_overlap_segments"] == 1
    assert t2.global_step > t1.global_step
    assert np.isfinite(t2.callback_metrics["train_loss"])


# -- MPMD quantized wire -----------------------------------------------------

def test_wire_dtype_config_coerce(monkeypatch):
    from ray_lightning_tpu.mpmd.transfer import WireDtypeConfig

    monkeypatch.delenv("RLT_MPMD_WIRE_DTYPE", raising=False)
    assert not WireDtypeConfig.coerce(None).active
    monkeypatch.setenv("RLT_MPMD_WIRE_DTYPE", "int8")
    cfg = WireDtypeConfig.coerce(None)
    assert (cfg.act, cfg.grad, cfg.active) == ("int8", "int8", True)
    cfg = WireDtypeConfig.coerce("act:bf16,grad:int8")
    assert cfg.enc == "act:bf16,grad:int8"
    assert WireDtypeConfig.coerce({"act": "bf16"}).grad == "f32"
    assert WireDtypeConfig.coerce("") == WireDtypeConfig()
    with pytest.raises(ValueError, match="expected one of"):
        WireDtypeConfig.coerce("int4")
    with pytest.raises(ValueError, match="unknown keys"):
        WireDtypeConfig.coerce({"activations": "int8"})
    with pytest.raises(TypeError):
        WireDtypeConfig.coerce(7)


def test_wire_codec_roundtrip_ratio_and_ef():
    from ray_lightning_tpu.mpmd import transfer as xfer

    rng = np.random.default_rng(0)
    tree = {
        "h": rng.standard_normal((64, 256)).astype(np.float32),
        "idx": np.arange(32, dtype=np.int32),  # non-float passes through
    }
    codec = xfer.WireCodec(xfer.WireDtypeConfig.coerce("int8"))
    payload = codec.encode_payload("act", 0, 0, 0, tree)
    back = xfer.decode_tree(payload)
    assert back["h"].dtype == np.float32
    np.testing.assert_array_equal(back["idx"], tree["idx"])
    amax = np.abs(tree["h"]).reshape(-1, 256).max(axis=1)
    err = np.abs(back["h"] - tree["h"]).reshape(-1, 256).max(axis=1)
    assert (err <= amax / 254.0 + 1e-7).all()
    assert codec.bytes_full_width / len(payload) >= 3.5

    # Grad-direction EF: resending the same slot telescopes — the mean
    # of N decoded payloads beats any single-shot decode.
    g = rng.standard_normal(4096).astype(np.float32)
    ef = xfer.WireCodec(xfer.WireDtypeConfig.coerce("int8"))
    outs = [
        xfer.decode_tree(ef.encode_payload("grad", s, 0, 0, g))
        for s in range(8)
    ]
    single = np.abs(outs[0] - g).mean()
    averaged = np.abs(np.mean(outs, axis=0) - g).mean()
    assert averaged < single / 4
    # A slot whose shape changes resets its residual, never misapplies.
    out = xfer.decode_tree(ef.encode_payload("grad", 9, 0, 0, g[:1024]))
    assert out.shape == (1024,)


def test_mpmd_strategy_validates_wire_dtype_eagerly():
    from ray_lightning_tpu.parallel.strategies import MpmdStrategy

    with pytest.raises(ValueError, match="expected one of"):
        MpmdStrategy(num_stages=2, wire_dtype="int4")
    s = MpmdStrategy(num_stages=2, devices_per_stage=1,
                     wire_dtype="act:bf16,grad:int8")
    assert s.wire_dtype == "act:bf16,grad:int8"


def _pipeline_setup():
    from ray_lightning_tpu.mpmd.plan import _gpt_untie, gpt_mpmd_spec

    cfg = GPTConfig(vocab_size=256, n_layer=4, n_head=4, d_model=64,
                    seq_len=64, warmup_steps=2)
    module = GPT(cfg, attn_impl="xla")
    module.precision = "f32"
    spec = gpt_mpmd_spec(module)
    full = _gpt_untie(module.init_params(jax.random.PRNGKey(0)))
    rng = np.random.default_rng(11)
    steps, bsz = 4, 8
    data = [
        {"tokens": rng.integers(
            0, cfg.vocab_size, (bsz, cfg.seq_len + 1)).astype(np.int32)}
        for _ in range(steps)
    ]
    return spec, full, data, steps


@pytest.mark.slow
def test_mpmd_wire_dtype_pipeline_parity_and_ratio():
    """Quantized DCN segments against the f32 wire on the in-process
    2-worker pipeline: int8 ships >= 3x fewer payload bytes and the
    loss trajectory stays put (grad EF keeps the error telescoping
    across the 1f1b resends of each micro-batch slot)."""
    from ray_lightning_tpu.mpmd.inproc import run_inproc_pipeline_fit

    spec, full, data, steps = _pipeline_setup()

    def run(wire):
        # Meshless per-stage devices (like the bench probe): the wire
        # codec is transport-layer, orthogonal to stage sharding.
        return run_inproc_pipeline_fit(
            spec, full, spec.tx_factory, lambda s: data[s], steps,
            n_workers=2, n_micro=4, schedule="1f1b", wire_dtype=wire,
        )

    ref = run(None)
    assert all(x["wire_ratio"] == 1.0 for x in ref["xfer"] if x["wire_ratio"])

    q = run("int8")
    np.testing.assert_allclose(
        q["losses"], ref["losses"], rtol=2e-3, atol=1e-4
    )
    sent = sum(x["bytes_sent"] for x in q["xfer"])
    fullw = sum(x["bytes_full_width"] for x in q["xfer"])
    assert fullw / sent >= 3.0
    assert all(
        x["enc"] == "act:int8,grad:int8" for x in q["xfer"] if x["bytes_sent"]
    )

    # The shipping default for DCN: bf16 activations, int8+EF grads.
    mixed = run("act:bf16,grad:int8")
    np.testing.assert_allclose(
        mixed["losses"], ref["losses"], rtol=2e-3, atol=1e-4
    )
    m_sent = sum(x["bytes_sent"] for x in mixed["xfer"])
    assert fullw / m_sent >= 1.8  # bf16 halves acts; grads still ~4x


def test_quantized_send_torn_segment_fails_loudly(tmp_path, monkeypatch):
    """Chaos contract: a torn shm segment under a QUANTIZED payload must
    poison the receiving mailbox (decode raises, recv surfaces it) —
    never dequantize garbage into a silently-wrong activation."""
    from ray_lightning_tpu.mpmd.transfer import (
        QueueChannel, StageInbox, WireCodec, WireDtypeConfig,
    )

    monkeypatch.setenv("RLT_FAULT", "torn@point:handoff_send")
    monkeypatch.setenv("RLT_FAULT_STATE", str(tmp_path / "chaos"))
    inbox = StageInbox()
    chan = QueueChannel(
        inbox.handle, same_host=True, shm_threshold=0,
        codec=WireCodec(WireDtypeConfig.coerce("int8")),
    )
    try:
        chan.send(
            "act", 0, 0,
            {"h": np.ones((64, 256), np.float32)},
        )
        with pytest.raises(RuntimeError, match="transfer lane failed"):
            inbox.mailbox.recv(("act", 0, 0, 0), timeout=20.0)
        assert chan.shm_sends == 1
    finally:
        chan.close()
        inbox.close()


def test_unquantized_send_unaffected_by_codec_default():
    """wire_dtype unset → f32 wire, bitwise-identical payload bytes to
    the pre-codec channel (the zero-risk default)."""
    from ray_lightning_tpu.mpmd.transfer import LocalChannel, Mailbox

    box = Mailbox()
    chan = LocalChannel(box)
    tree = {"h": np.arange(12, dtype=np.float32)}
    chan.send("act", 0, 0, tree)
    payload, blocked = box.recv(("act", 0, 0, 0), timeout=5.0)
    np.testing.assert_array_equal(payload["h"], tree["h"])
    stats = chan.xfer_stats()
    assert stats["enc"] == "act:f32,grad:f32"
    assert stats["bytes_sent"] == stats["bytes_full_width"]
