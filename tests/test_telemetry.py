"""Telemetry subsystem tests (ISSUE 2): spans, step stats, MFU,
recompile counters, fleet aggregation, profiler hardening, overhead.
"""

import json
import math
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_lightning_tpu.core.callbacks import (
    ProfilerCallback,
    TelemetryCallback,
)
from ray_lightning_tpu.core.loop import _RunningMeanLogs
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import BoringDataModule, BoringModel
from ray_lightning_tpu.parallel.strategies import LocalStrategy, RayStrategy
from ray_lightning_tpu.telemetry import (
    SpanTracer,
    StepStats,
    Telemetry,
    TelemetryConfig,
    compile_event_count,
    host_stats,
    merge_snapshots,
    model_flops_per_token,
    straggler_ranks,
)
from ray_lightning_tpu.telemetry.schema import (
    validate_bench_telemetry,
    validate_chrome_trace,
    validate_span_jsonl,
)
from ray_lightning_tpu.telemetry.trace_parse import (
    bucket_totals,
    collect_file,
)

from utils import get_trainer


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

def test_span_nesting_and_ordering():
    tracer = SpanTracer(enabled=True, maxlen=16, rank=3)
    with tracer.span("outer"):
        time.sleep(0.001)
        with tracer.span("inner"):
            time.sleep(0.001)
    spans = tracer.events()
    # Inner CLOSES first, so it is recorded first; depth encodes nesting.
    assert [s.name for s in spans] == ["inner", "outer"]
    inner, outer = spans
    assert inner.depth == 1 and outer.depth == 0
    assert inner.rank == 3 and outer.rank == 3
    # Temporal containment: inner lies inside outer.
    assert outer.ts <= inner.ts
    assert inner.ts + inner.dur <= outer.ts + outer.dur + 1e-6
    assert outer.dur >= inner.dur > 0


def test_span_ring_buffer_bounded():
    tracer = SpanTracer(enabled=True, maxlen=8)
    for i in range(20):
        with tracer.span(f"s{i}"):
            pass
    assert len(tracer.events()) == 8
    assert tracer.dropped == 12
    # Newest spans win.
    assert tracer.events()[-1].name == "s19"


def test_disabled_tracer_is_noop():
    tracer = SpanTracer(enabled=False)
    with tracer.span("x"):
        pass
    tracer.record("y", 0.0, 1.0)
    assert tracer.events() == []


def test_span_exports_schema_validate(tmp_path):
    tracer = SpanTracer(enabled=True, rank=1)
    with tracer.span("checkpoint_write", path="/x"):
        with tracer.span("host_transfer"):
            pass
    tracer.instant("grad_sync", mode="int8")
    jsonl = str(tmp_path / "spans.jsonl")
    chrome = str(tmp_path / "trace.json")
    assert tracer.export_jsonl(jsonl) == 3
    assert tracer.export_chrome(chrome) == 3
    with open(jsonl) as f:
        assert validate_span_jsonl(f.readlines()) == []
    with open(chrome) as f:
        doc = json.load(f)
    assert validate_chrome_trace(doc) == []
    # Chrome events are µs and carry the rank as pid.
    assert all(ev["pid"] == 1 for ev in doc["traceEvents"])


def test_trace_parse_roundtrip(tmp_path):
    tracer = SpanTracer(enabled=True)
    with tracer.span("dot_general"):
        time.sleep(0.002)
    with tracer.span("copy.3"):
        pass
    path = str(tmp_path / "trace.json")
    tracer.export_chrome(path)
    durs = collect_file(path)
    assert set(durs) == {"dot_general", "copy.3"}
    buckets = bucket_totals(durs)
    assert buckets["matmul"] == durs["dot_general"]
    assert buckets["layout"] == durs["copy.3"]


# ---------------------------------------------------------------------------
# Step stats: MFU math, recompiles, config
# ---------------------------------------------------------------------------

def test_mfu_math_on_known_gpt_config():
    """Closed-form check on GPT-2-small: the analytic accounting must
    match the published-MFU convention digit for digit."""
    from ray_lightning_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=50304, n_layer=12, n_head=12, d_model=768,
                    seq_len=1024)
    d, L, s, V = 768, 12, 1024, 50304
    expected = 3.0 * (24 * L * d * d + 4 * L * s * d + 2 * d * V)
    assert model_flops_per_token(cfg) == expected
    # Causal halves only the attention term.
    assert model_flops_per_token(cfg, "causal") == (
        3.0 * (24 * L * d * d + 2 * L * s * d + 2 * d * V)
    )

    # MFU = tokens/s * F / (peak * chips): feed a synthetic run whose
    # numbers make the expected value exact.
    ss = StepStats(flops_per_example=expected * s, tokens_per_example=s,
                   peak_flops=1e12, n_chips=2)
    ss.record_step(0.1, 0.0, 0.0, examples=1)     # compile step
    for _ in range(4):
        ss.record_step(0.05, 0.0, 0.0, examples=8)
    tp = ss.throughput()
    assert tp["tokens_per_sec"] == pytest.approx(
        tp["examples_per_sec"] * s
    )
    assert ss.mfu() == pytest.approx(
        tp["examples_per_sec"] * expected * s / (1e12 * 2)
    )


def test_vit_flops_positive_and_scales():
    from ray_lightning_tpu.models.vit import ViTConfig
    from ray_lightning_tpu.telemetry import vit_flops_per_example

    small, big = ViTConfig.tiny(), ViTConfig()
    assert 0 < vit_flops_per_example(small) < vit_flops_per_example(big)


def test_recompile_counter_increments_on_shape_change():
    ss = StepStats()

    @jax.jit
    def f(x):
        return x * 2 + 1

    f(jnp.ones((3,)))
    mid = ss.recompiles
    assert mid >= 1
    f(jnp.ones((5,)))  # shape change → new XLA compile
    assert ss.recompiles >= mid + 1
    # A second StepStats starts from NOW, not from process start.
    ss2 = StepStats()
    assert ss2.recompiles == 0
    assert compile_event_count() >= 2


def test_step_stats_compile_step_excluded():
    ss = StepStats()
    ss.record_step(5.0, 0.0, 4.9, examples=8)      # compile
    ss.record_step(0.01, 0.001, 0.002, examples=8)
    ss.record_step(0.02, 0.002, 0.003, examples=8, sampled=True)
    assert ss.compile_ms == pytest.approx(5000.0)
    head = ss.headline()
    assert head["step_time_ms"] == pytest.approx(15.0)
    assert head["data_wait_ms"] == pytest.approx(1.5)
    assert head["device_step_ms"] == pytest.approx(20.0)
    summary = ss.summary()
    assert summary["steps"] == 3 and summary["examples"] == 16


def test_telemetry_config_coercion(monkeypatch):
    assert TelemetryConfig.coerce(None).tier == "cheap"
    monkeypatch.setenv("RLT_TELEMETRY", "full")
    monkeypatch.setenv("RLT_TELEMETRY_SAMPLE", "7")
    cfg = TelemetryConfig.coerce(None)
    assert cfg.tier == "full" and cfg.sample_every == 7
    assert TelemetryConfig.coerce("off").tier == "off"
    assert TelemetryConfig.coerce({"tier": "cheap", "span_buffer": 9})
    with pytest.raises(ValueError):
        TelemetryConfig.coerce("verbose")
    with pytest.raises(ValueError):
        LocalStrategy(telemetry="typo")  # strategies validate eagerly


# ---------------------------------------------------------------------------
# Fleet aggregation
# ---------------------------------------------------------------------------

def _snap(rank, step_ms, bytes_=1000):
    return {
        "rank": rank,
        "tier": "cheap",
        "counters": {"grad_sync_bytes": bytes_,
                     "grad_sync_compression_ratio": 3.9,
                     "checkpoint_writes": 1},
        "meta": {"grad_sync_mode": "int8"},
        "step_stats": {"step_mean_ms": step_ms, "steps": 10},
    }


def test_merge_snapshots_min_max_mean_skew():
    report = merge_snapshots([_snap(1, 30.0), _snap(0, 10.0)])
    assert report["world_size"] == 2
    view = report["step_stats"]["step_mean_ms"]
    assert view["min"] == 10.0 and view["max"] == 30.0
    assert view["mean"] == 20.0
    assert view["skew_pct"] == pytest.approx(100.0)
    # Per-rank snapshots kept, rank-sorted.
    assert [s["rank"] for s in report["per_rank"]] == [0, 1]
    # grad_sync_* stats are per-device analytic constants — NEVER
    # summed across ranks (a "fleet total" would be a misread); real
    # additive counters are.
    assert "sum" not in report["counters"]["grad_sync_bytes"]
    assert "sum" not in report["counters"]["grad_sync_compression_ratio"]
    assert report["counters"]["checkpoint_writes"]["sum"] == 2
    assert report["meta"]["grad_sync_mode"] == "int8"
    assert straggler_ranks(report, "step_mean_ms", 20.0) == [1]
    assert merge_snapshots([]) == {}
    assert merge_snapshots([{}, None]) == {}


def test_merge_keeps_rank_zero_only_counters():
    """checkpoint_writes (rank-0-guarded file I/O) and nonfinite_logs
    (one poisoned rank) must survive the merge as zero-padded views,
    not vanish exactly when ranks disagree."""
    a = _snap(0, 10.0)
    a["counters"]["nonfinite_logs"] = 4
    b = _snap(1, 10.0)
    del b["counters"]["checkpoint_writes"]
    report = merge_snapshots([a, b])
    ckpt = report["counters"]["checkpoint_writes"]
    assert ckpt["mean"] == 0.5 and ckpt["sum"] == 1
    assert ckpt["ranks_reporting"] == 1
    nan = report["counters"]["nonfinite_logs"]
    assert nan["max"] == 4 and nan["sum"] == 4
    # Fleet-complete rule still applies to step timings: a metric only
    # SOME ranks computed would make the mean lie about the fleet.
    a2, b2 = _snap(0, 10.0), _snap(1, 10.0)
    a2["step_stats"]["mfu"] = 0.4
    partial = merge_snapshots([a2, b2])
    assert "mfu" not in partial["step_stats"]


def test_host_stats_shape():
    stats = host_stats()
    assert isinstance(stats, dict)
    assert stats.get("cpu_count")
    if "mem_total_bytes" in stats:
        assert stats["mem_total_bytes"] > 0


# ---------------------------------------------------------------------------
# Loop integration
# ---------------------------------------------------------------------------

def test_fit_records_headline_metrics(tmp_path):
    """Acceptance: a plain fit() records step_time_ms, data_wait_ms and
    examples_per_sec in callback_metrics, and the trainer carries a
    telemetry report with grad-sync visibility."""
    trainer = get_trainer(LocalStrategy(), max_epochs=2, tmp_path=tmp_path)
    trainer.fit(BoringModel(), BoringDataModule(length=64, batch_size=16))
    cm = trainer.callback_metrics
    for key in ("step_time_ms", "data_wait_ms", "dispatch_ms",
                "examples_per_sec", "recompiles"):
        assert key in cm, f"missing {key}"
        assert np.isfinite(cm[key])
    assert cm["examples_per_sec"] > 0
    report = trainer.telemetry_report
    assert report["world_size"] == 1 and report["tier"] == "cheap"
    assert report["step_stats"]["step_mean_ms"]["mean"] > 0
    # Grad-sync is visible through the SAME report (full-width here).
    assert report["meta"]["grad_sync_mode"] == "full"
    # Checkpoint writes + result-package host transfers were counted.
    assert report["counters"]["checkpoint_writes"]["mean"] >= 1
    assert report["counters"]["host_transfers"]["mean"] >= 1


def test_gpt_fit_records_tokens_and_mfu(tmp_path, monkeypatch):
    """Acceptance: the GPT family additionally gets tokens/sec and an
    MFU (peak pinned via the env override on CPU).  With the program
    ledger live the numerator flips to XLA's measured cost_analysis
    FLOPs (basis "measured"); the consistency check follows the basis
    the report declares."""
    from ray_lightning_tpu.models.gpt import (
        GPT,
        GPTConfig,
        SyntheticLMDataModule,
    )
    from ray_lightning_tpu.telemetry import program_ledger

    monkeypatch.setenv("RLT_TELEMETRY_PEAK", "1e12")
    cfg = GPTConfig.tiny()
    trainer = get_trainer(
        LocalStrategy(), max_epochs=1, tmp_path=tmp_path,
        enable_checkpointing=False, limit_val_batches=0,
    )
    trainer.fit(GPT(cfg), SyntheticLMDataModule(cfg, batch_size=8,
                                                num_batches=4))
    cm = trainer.callback_metrics
    assert cm["tokens_per_sec"] > 0
    assert "mfu" in cm and 0 < cm["mfu"]
    # MFU consistency with the accounting basis the report declares:
    # measured = this fit's train/step cost_analysis FLOPs per example,
    # analytic = the shared per-token model.
    meta = (trainer.telemetry_report or {}).get("meta") or {}
    if meta.get("mfu_basis") == "measured":
        site_flops = program_ledger.ledger().site_flops_latest(
            "train/step"
        )
        assert site_flops is not None
        flops_per_example = site_flops / 8  # batch_size above
    else:
        flops_per_example = model_flops_per_token(cfg) * cfg.seq_len
    expected = cm["examples_per_sec"] * flops_per_example / 1e12
    n_chips = jax.local_device_count()
    assert cm["mfu"] == pytest.approx(expected / n_chips, rel=1e-6)


def test_off_tier_records_nothing_and_overhead_smoke(tmp_path):
    """telemetry="off" leaves callback_metrics clean; the default cheap
    tier's overhead is loosely bounded (precise number in BENCH_*)."""
    def run(tier, sub):
        t0 = time.perf_counter()
        trainer = get_trainer(
            LocalStrategy(telemetry=tier), max_epochs=2,
            tmp_path=tmp_path / sub, enable_checkpointing=False,
            limit_val_batches=0,
        )
        trainer.fit(BoringModel(),
                    BoringDataModule(length=128, batch_size=16))
        return trainer, time.perf_counter() - t0

    t_off, off_wall = run("off", "off")
    t_cheap, cheap_wall = run("cheap", "cheap")
    assert "step_time_ms" not in t_off.callback_metrics
    assert t_off.telemetry_report == {}
    assert "step_time_ms" in t_cheap.callback_metrics
    # LOOSE smoke bound (CI wall clocks are noisy; compile dominates
    # both runs equally): cheap must not change the fit's cost class.
    assert cheap_wall < off_wall * 1.5 + 1.0, (
        f"cheap tier wall {cheap_wall:.2f}s vs off {off_wall:.2f}s"
    )


def test_full_tier_exports_artifacts(tmp_path):
    trainer = get_trainer(
        LocalStrategy(telemetry={"tier": "full",
                                 "export_dir": str(tmp_path / "tel")}),
        max_epochs=1, tmp_path=tmp_path, limit_val_batches=0,
    )
    trainer.fit(BoringModel(), BoringDataModule(length=32, batch_size=16))
    out = tmp_path / "tel"
    jsonl = out / "spans-rank0.jsonl"
    chrome = out / "trace-rank0.json"
    assert jsonl.exists() and chrome.exists()
    with open(jsonl) as f:
        assert validate_span_jsonl(f.readlines()) == []
    with open(chrome) as f:
        doc = json.load(f)
    assert validate_chrome_trace(doc) == []
    names = {ev["name"] for ev in doc["traceEvents"]}
    # The instrumented phases show up: compile + steady-state dispatch,
    # data waits and the checkpoint/host-transfer tail.
    assert {"compile", "dispatch", "data_wait",
            "checkpoint_write", "host_transfer"} <= names
    snap = json.loads((out / "snapshot-rank0.json").read_text())
    assert snap["tier"] == "full" and snap["spans_recorded"] > 0


def test_eval_and_predict_fill_telemetry_report(tmp_path):
    """validate()/predict() without a prior fit still produce a fleet
    report (the snapshots they ship are consumed, not dead weight)."""
    trainer = get_trainer(
        LocalStrategy(), max_epochs=1, tmp_path=tmp_path,
        enable_checkpointing=False,
    )
    module = BoringModel()
    dm = BoringDataModule(length=32, batch_size=16)
    trainer.validate(module, dm)
    assert trainer.telemetry_report.get("world_size") == 1
    assert trainer.telemetry_report["tier"] == "cheap"
    trainer.predict(module, dm)
    assert trainer.telemetry_report.get("world_size") == 1


def test_telemetry_callback_upgrades_cheap_fit(tmp_path):
    cb = TelemetryCallback(dirpath=str(tmp_path / "cbtel"))
    trainer = get_trainer(
        LocalStrategy(), max_epochs=1, tmp_path=tmp_path,
        callbacks=[cb], enable_checkpointing=False, limit_val_batches=0,
    )
    trainer.fit(BoringModel(), BoringDataModule(length=32, batch_size=16))
    # The callback is the per-fit spans opt-in on a cheap-tier run.
    assert (tmp_path / "cbtel" / "spans-rank0.jsonl").exists()
    assert cb.report.get("step_stats", {}).get("steps") == 2
    assert cb.export_paths


def test_bench_telemetry_block_schema():
    block = {
        "tier": "cheap",
        "overhead_pct": 0.4,
        "report": {"step_stats": {}, "counters": {}},
    }
    assert validate_bench_telemetry(block) == []
    assert validate_bench_telemetry({"overhead_pct": 1}) != []  # no tier


# ---------------------------------------------------------------------------
# _RunningMeanLogs non-finite hardening (satellite)
# ---------------------------------------------------------------------------

def test_running_mean_skips_nonfinite():
    acc = _RunningMeanLogs()
    acc.update({"loss": jnp.float32(1.0), "aux": jnp.float32(2.0)})
    acc.update({"loss": jnp.float32(float("nan")),
                "aux": jnp.float32(4.0)})
    acc.update({"loss": jnp.float32(3.0),
                "aux": jnp.float32(float("inf"))})
    out = acc.result()
    assert out["loss"] == pytest.approx(2.0)   # (1+3)/2, NaN excluded
    assert out["aux"] == pytest.approx(3.0)    # (2+4)/2, inf excluded
    assert acc.nonfinite_count == 2


def test_running_mean_all_nonfinite_is_nan_not_zero():
    acc = _RunningMeanLogs()
    acc.update({"loss": jnp.float32(float("nan"))})
    out = acc.result()
    assert math.isnan(out["loss"])
    assert acc.nonfinite_count == 1


def test_fit_surfaces_nonfinite_counter(tmp_path):
    class NaNSpikeModel(BoringModel):
        def training_step(self, params, batch, rng):
            loss, logs = super().training_step(params, batch, rng)
            # Poison a LOGGED metric on every step — training itself
            # stays healthy; only the log stream carries NaN.
            logs["spiky"] = logs["train_loss"] / 0.0 * 0.0
            return loss, logs

    trainer = get_trainer(
        LocalStrategy(), max_epochs=1, tmp_path=tmp_path,
        enable_checkpointing=False, limit_val_batches=0,
    )
    trainer.fit(NaNSpikeModel(),
                BoringDataModule(length=32, batch_size=16))
    counters = trainer.telemetry_report["counters"]
    assert counters["nonfinite_logs"]["mean"] >= 1
    assert np.isfinite(trainer.callback_metrics["train_loss"])


# ---------------------------------------------------------------------------
# ProfilerCallback hardening (satellite)
# ---------------------------------------------------------------------------

class _FakeTrainer:
    def __init__(self, root):
        self.default_root_dir = str(root)
        self.is_global_zero = True
        self.global_rank = 0
        self.global_step = 0
        self.state = None
        self.telemetry_dir = None


class _ProfilerSpy:
    def __init__(self, monkeypatch):
        self.starts = 0
        self.stops = 0
        self.active = False
        monkeypatch.setattr(jax.profiler, "start_trace", self._start)
        monkeypatch.setattr(jax.profiler, "stop_trace", self._stop)

    def _start(self, path):
        if self.active:
            raise RuntimeError("profiler already active")
        self.active = True
        self.starts += 1

    def _stop(self):
        self.active = False
        self.stops += 1


def test_profiler_overlapping_windows_merge(tmp_path, monkeypatch):
    """Regression (satellite): two overlapping schedule windows must
    produce exactly ONE start/stop pair — never a double start_trace."""
    spy = _ProfilerSpy(monkeypatch)
    cb = ProfilerCallback(schedule=[(2, 4), (4, 3)])  # [2,6) ∪ [4,7)
    assert cb._windows == [(2, 5)]  # merged to [2,7)
    trainer = _FakeTrainer(tmp_path)
    cb.setup(trainer, None, "fit")
    for step in range(12):
        trainer.global_step = step
        cb.on_train_batch_end(trainer, None, {}, step)
    assert spy.starts == 1 and spy.stops == 1
    # teardown is idempotent — the window closed already, and calling
    # twice more must not double-stop.
    cb.teardown(trainer, None, "fit")
    cb.teardown(trainer, None, "fit")
    assert spy.stops == 1


def test_profiler_two_disjoint_windows(tmp_path, monkeypatch):
    spy = _ProfilerSpy(monkeypatch)
    cb = ProfilerCallback(schedule=[(1, 2), (6, 2)])
    trainer = _FakeTrainer(tmp_path)
    cb.setup(trainer, None, "fit")
    for step in range(12):
        trainer.global_step = step
        cb.on_train_batch_end(trainer, None, {}, step)
    assert spy.starts == 2 and spy.stops == 2


def test_profiler_resume_never_restores_active(tmp_path, monkeypatch):
    spy = _ProfilerSpy(monkeypatch)
    cb = ProfilerCallback(start_step=0, num_steps=2)
    trainer = _FakeTrainer(tmp_path)
    cb.setup(trainer, None, "fit")
    trainer.global_step = 0
    cb.on_train_batch_end(trainer, None, {}, 0)
    assert cb._active
    # A resume ships the state dict to a fresh process: the restored
    # object must NOT believe a trace is live there.
    cb2 = ProfilerCallback(start_step=0, num_steps=2)
    cb2.load_state_dict(cb.state_dict())
    assert not cb2._active
    # And re-setup on the original resets capture state cleanly.
    cb.teardown(trainer, None, "fit")
    cb.setup(trainer, None, "fit")
    assert not cb._active and cb._win_i == 0
    assert spy.stops == 1


def test_profiler_mid_trace_teardown_closes_once(tmp_path, monkeypatch):
    spy = _ProfilerSpy(monkeypatch)
    cb = ProfilerCallback(start_step=0, num_steps=100)
    trainer = _FakeTrainer(tmp_path)
    cb.setup(trainer, None, "fit")
    cb.on_train_batch_end(trainer, None, {}, 0)
    assert spy.active
    cb.teardown(trainer, None, "fit")
    cb.teardown(trainer, None, "fit")
    assert spy.stops == 1 and not spy.active


def test_profiler_double_start_degrades_to_skip(tmp_path, monkeypatch):
    """An already-active outer trace (or stale resume) must skip the
    window with a warning, not crash the fit."""
    spy = _ProfilerSpy(monkeypatch)
    spy.active = True  # someone else's trace is live
    cb = ProfilerCallback(start_step=0, num_steps=2)
    trainer = _FakeTrainer(tmp_path)
    cb.setup(trainer, None, "fit")
    with pytest.warns(UserWarning, match="start_trace skipped"):
        cb.on_train_batch_end(trainer, None, {}, 0)
    assert not cb._active and spy.starts == 0


def test_profiler_schedule_validation():
    with pytest.raises(ValueError):
        ProfilerCallback(schedule=[])
    with pytest.raises(ValueError):
        ProfilerCallback(schedule=[(2, 0)])
    with pytest.raises(ValueError):
        ProfilerCallback(num_steps=0)


# ---------------------------------------------------------------------------
# Multi-worker aggregation (reuses the test_multiworker harness)
# ---------------------------------------------------------------------------

@pytest.mark.remote
@pytest.mark.multiworker
def test_multiworker_telemetry_aggregation(tmp_path):
    """Acceptance: after a multi-worker fit, trainer.telemetry_report
    merges BOTH ranks' snapshots into min/max/mean views."""
    trainer = get_trainer(
        RayStrategy(num_workers=2), max_epochs=1, tmp_path=tmp_path
    )
    trainer.fit(BoringModel(), BoringDataModule(length=64, batch_size=32))
    report = trainer.telemetry_report
    assert report["world_size"] == 2
    assert [s["rank"] for s in report["per_rank"]] == [0, 1]
    view = report["step_stats"]["step_mean_ms"]
    assert view["min"] <= view["mean"] <= view["max"]
    assert "skew_pct" in view
    assert report["counters"]["host_transfers"]["mean"] >= 1
