"""MNIST classifier convergence (BASELINE.md config #1 analogue;
≙ reference predict_test accuracy>=0.5, tests/utils.py:256-272)."""

import pytest

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models.mnist import MNISTClassifier, MNISTDataModule
from ray_lightning_tpu.parallel.strategies import LocalStrategy


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_mnist_converges(tmp_path):
    trainer = Trainer(
        strategy=LocalStrategy(),
        max_epochs=2,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
    )
    trainer.fit(MNISTClassifier(), MNISTDataModule())
    assert trainer.callback_metrics["ptl/val_accuracy"] >= 0.5
