"""Fleet SLO & capacity plane tests (ISSUE 18): the bounded
time-series store's windowed queries, Google-SRE multi-window
burn-rate alerting (fire / dedup / re-arm / fast-spike silence), the
headroom oracle's measured-phase-cost tick model with its sampled-gauge
fallback, the fleet fold, the engine integration (plane on → schema-
valid ``capacity`` block on every snapshot + ``rlt_capacity_*`` /
``rlt_slo_*`` prom families), the rlt_top capacity pane with its
staleness tag, and the bench-diff tool's self-test.

Everything below the engine class is jax-free and clock-driven
(RLT004): no sleeps, no wall-clock flake.  The saturation-calibration
truth test (predicted vs measured Poisson knee) lives in
bench_serve.py phase 9 — here we pin the math on synthetic counters.
"""

import time

import pytest

from ray_lightning_tpu.serve.capacity import (
    CapacityOracle, aggregate_fleet,
)
from ray_lightning_tpu.serve.metrics import ServeStats
from ray_lightning_tpu.telemetry.export_prom import render_openmetrics
from ray_lightning_tpu.telemetry.schema import (
    validate_capacity_snapshot,
    validate_serve_snapshot,
    validate_slo_alert,
    validate_timeseries_point,
)
from ray_lightning_tpu.telemetry.slo import (
    SloEvaluator, SloSpec, default_serve_slos,
)
from ray_lightning_tpu.telemetry.timeseries import TimeSeriesStore

pytestmark = pytest.mark.serve


class _Clock:
    """Injectable wall clock — tests advance time explicitly."""

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# TimeSeriesStore: binning, windowed queries, persistence shape
# ---------------------------------------------------------------------------

class TestTimeSeriesStore:
    def test_fixed_interval_binning_is_bounded(self):
        clock = _Clock()
        store = TimeSeriesStore(interval_s=1.0, capacity=4, clock=clock)
        for i in range(10):
            clock.t = 1000.0 + i
            store.observe("g", float(i))
        points = store.series("g")
        assert len(points) == 4          # ring dropped the oldest bins
        assert [v for _, v in points] == [6.0, 7.0, 8.0, 9.0]
        assert points[-1][0] == 1009.0   # bin_start_ts, not raw ts

    def test_gauge_last_write_wins_within_bin(self):
        clock = _Clock()
        store = TimeSeriesStore(interval_s=1.0, clock=clock)
        store.observe("g", 1.0)
        store.observe("g", 2.0)          # same bin
        assert store.last("g") == 2.0
        assert len(store.series("g")) == 1

    def test_counter_rate_is_reset_safe(self):
        clock = _Clock()
        store = TimeSeriesStore(interval_s=1.0, clock=clock)
        # Cumulative 0, 10, 20, then a restart back to 5: the ramp
        # restarts at 0, so the window saw 10+10+5 increments over 3s.
        for i, total in enumerate((0.0, 10.0, 20.0, 5.0)):
            clock.t = 1000.0 + i
            store.observe("c", total, kind="counter")
        assert store.rate("c", 10.0) == pytest.approx(25.0 / 3.0)

    def test_rate_wants_a_counter(self):
        store = TimeSeriesStore(clock=_Clock())
        store.observe("g", 1.0)
        store.observe("g", 2.0, ts=1002.0)
        with pytest.raises(ValueError, match="wants a counter"):
            store.rate("g", 10.0)

    def test_kind_mismatch_raises(self):
        store = TimeSeriesStore(clock=_Clock())
        store.observe("x", 1.0, kind="gauge")
        with pytest.raises(ValueError, match="is a gauge"):
            store.observe("x", 1.0, kind="counter")

    def test_out_of_order_past_live_bin_dropped(self):
        clock = _Clock()
        store = TimeSeriesStore(interval_s=1.0, clock=clock)
        store.observe("g", 1.0, ts=1005.0)
        store.observe("g", 9.0, ts=1001.0)   # older than the live bin
        assert store.series("g") == [(1005.0, 1.0)]

    def test_hist_percentile_merges_bins(self):
        clock = _Clock()
        store = TimeSeriesStore(interval_s=1.0, clock=clock)
        for i in range(10):
            store.observe("h", float(i), kind="hist",
                          ts=1000.0 + i * 0.5)
        assert store.percentile("h", 0.0, 60.0) == 0.0
        assert store.percentile("h", 100.0, 60.0) == 9.0
        assert store.percentile("h", 50.0, 60.0) in (4.0, 5.0)

    def test_slope_and_eta_to_threshold(self):
        clock = _Clock()
        store = TimeSeriesStore(interval_s=1.0, clock=clock)
        for i in range(5):
            store.observe("free", 100.0 - 10.0 * i, ts=1000.0 + i)
        assert store.slope("free", 60.0) == pytest.approx(-10.0)
        # 60 units above zero, draining 10/s → 6s out.
        assert store.eta_to("free", 0.0, 60.0) == pytest.approx(6.0)
        # Trend pointing AWAY from the threshold: no crossing.
        assert store.eta_to("free", 200.0, 60.0) is None

    def test_points_are_schema_valid(self):
        clock = _Clock()
        store = TimeSeriesStore(interval_s=1.0, clock=clock)
        store.observe("c", 5.0, kind="counter")
        store.observe("g", 1.5)
        store.observe("h", 3.0, kind="hist")
        points = store.points()
        assert len(points) == 3
        for point in points:
            assert validate_timeseries_point(point, "test") == []

    def test_dump_jsonl_appends(self, tmp_path):
        store = TimeSeriesStore(clock=_Clock())
        store.observe("g", 1.0)
        path = str(tmp_path / "ts.jsonl")
        assert store.dump_jsonl(path) == 1
        assert store.dump_jsonl(path) == 1
        assert len(open(path).read().splitlines()) == 2


# ---------------------------------------------------------------------------
# SloEvaluator: multi-window burn-rate semantics
# ---------------------------------------------------------------------------

def _ratio_spec(windows=((2.0, 6.0, 1.0),)):
    # target 0.5 → budget 0.5 → burn = 2·error_rate; fires at err ≥ 0.5
    # in BOTH the 2s and the 6s window.
    return SloSpec(name="avail", target=0.5, mode="ratio",
                   bad="rejected", total="submitted", windows=windows)


class _SloRig:
    """Store + evaluator on a fake clock, with a per-second feeder."""

    def __init__(self, spec):
        self.clock = _Clock()
        self.store = TimeSeriesStore(interval_s=1.0, clock=self.clock)
        self.emitted = []
        self.ev = SloEvaluator(self.store, [spec], clock=self.clock,
                               emit=self.emitted.append)
        self._submitted = 0.0
        self._rejected = 0.0

    def tick(self, submitted=10.0, rejected=0.0):
        self.clock.t += 1.0
        self._submitted += submitted
        self._rejected += rejected
        self.store.observe("submitted", self._submitted, kind="counter")
        self.store.observe("rejected", self._rejected, kind="counter")
        return self.ev.evaluate()


class TestSloEvaluator:
    def test_fires_when_both_windows_burn(self):
        rig = _SloRig(_ratio_spec())
        alerts = []
        for _ in range(8):
            alerts += rig.tick(rejected=10.0)   # 100% errors
        assert len(alerts) == 1                 # deduplicated while firing
        assert rig.emitted == alerts
        assert validate_slo_alert(alerts[0], "test") == []
        detail = alerts[0]["detail"]
        assert detail["slo"] == "avail"
        assert detail["burn_rate"] >= 1.0
        assert rig.ev.alerts_total == 1

    def test_fast_spike_alone_stays_silent(self):
        rig = _SloRig(_ratio_spec())
        alerts = []
        for _ in range(7):
            alerts += rig.tick()                # clean history
        for _ in range(2):
            alerts += rig.tick(rejected=10.0)   # 2s burst: fast burns,
        assert alerts == []                     # slow window holds it

    def test_rearm_after_recovery_fires_again(self):
        rig = _SloRig(_ratio_spec())
        for _ in range(8):
            rig.tick(rejected=10.0)
        assert rig.ev.alerts_total == 1
        for _ in range(10):
            rig.tick()                          # recover: burn → 0
        assert rig.ev.snapshot()["avail"]["firing"] is False
        fired = []
        for _ in range(8):
            fired += rig.tick(rejected=10.0)
        assert len(fired) == 1                  # re-armed, new alert
        assert rig.ev.alerts_total == 2

    def test_threshold_mode_counts_over_bins(self):
        clock = _Clock()
        store = TimeSeriesStore(interval_s=1.0, clock=clock)
        spec = SloSpec(name="wait", target=0.5, mode="threshold",
                       gauge="queue_wait_p50_ms", threshold=100.0,
                       windows=((2.0, 6.0, 1.0),))
        ev = SloEvaluator(store, [spec], clock=clock)
        for i in range(8):
            clock.t += 1.0
            store.observe("queue_wait_p50_ms", 500.0)
            out = ev.evaluate()
        assert len(out) == 0                    # fired on an EARLIER pass
        assert ev.alerts_total == 1
        snap = ev.snapshot()["wait"]
        assert snap["firing"] is True
        assert snap["burn_rate"] == pytest.approx(2.0)

    def test_no_data_means_no_alert(self):
        rig = _SloRig(_ratio_spec())
        assert rig.ev.evaluate() == []
        assert rig.ev.snapshot()["avail"]["burn_rate"] == 0.0

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="outside"):
            SloSpec(name="bad", target=1.5)
        with pytest.raises(ValueError, match="needs bad"):
            SloSpec(name="bad", target=0.9, mode="ratio")
        with pytest.raises(ValueError, match="needs gauge"):
            SloSpec(name="bad", target=0.9, mode="threshold")
        with pytest.raises(ValueError, match="unknown mode"):
            SloSpec(name="bad", target=0.9, mode="latency")
        store = TimeSeriesStore(clock=_Clock())
        with pytest.raises(ValueError, match="duplicate"):
            SloEvaluator(store, [_ratio_spec(), _ratio_spec()])

    def test_default_serve_slos_cover_both_modes(self):
        specs = default_serve_slos()
        modes = {s.mode for s in specs}
        assert modes == {"ratio", "threshold"}


# ---------------------------------------------------------------------------
# CapacityOracle: tick-cost model, fallback, prediction, fleet fold
# ---------------------------------------------------------------------------

class _OracleRig:
    def __init__(self, interval_s=1.0):
        self.clock = _Clock()
        self.oracle = CapacityOracle(interval_s=interval_s,
                                     window_s=60.0, clock=self.clock)
        self.counters = {}

    def feed(self, gauges=None, **deltas):
        """Advance 1s and feed one stats view with counter DELTAS
        (accumulated here into the cumulative totals the oracle
        differences back out)."""
        self.clock.t += 1.0
        for name, d in deltas.items():
            self.counters[name] = self.counters.get(name, 0) + d
        self.oracle.observe({
            "ts": self.clock.t,
            "counters": dict(self.counters),
            "gauges": dict(gauges or {}),
            "latency": {},
        })


class TestCapacityOracle:
    # Synthetic ground truth for the affine tick-cost model:
    # tick_us = C + H·busy, one admission costs ADMIT_US.
    C_US, H_US, ADMIT_US = 20000.0, 1000.0, 5000.0

    def _feed_tick_bins(self, rig, busies, ticks=10, admitted=2):
        for busy in busies:
            rig.feed(
                gauges={"num_slots": 8.0, "slots_active": float(busy)},
                decode_steps=ticks,
                decode_us=ticks * (self.C_US + self.H_US * busy),
                tokens_out=ticks * busy + admitted,
                admitted=admitted,
                admit_us=admitted * self.ADMIT_US,
                submitted=admitted,
            )

    def test_tick_model_recovers_synthetic_costs(self):
        rig = _OracleRig()
        self._feed_tick_bins(rig, [1, 3, 5, 7, 2, 4, 6, 8, 1, 5, 3, 7])
        model = rig.oracle._tick_model(60.0)
        assert model is not None
        assert model["c_us"] == pytest.approx(self.C_US, rel=1e-6)
        assert model["h_us"] == pytest.approx(self.H_US, rel=1e-6)
        assert model["admit_s"] == pytest.approx(self.ADMIT_US / 1e6)

        snap = rig.oracle.snapshot(60.0)
        assert validate_capacity_snapshot(snap, "test") == []
        # Full-width tick: 20000 + 1000·8 = 28ms for 8 tokens.
        assert snap["capacity_tokens_per_s"] == \
            pytest.approx(8.0 / 0.028, rel=1e-6)

        # Knee: admit + 15 full-width tick shares per request.
        pred = rig.oracle.predict_saturation_rps(16, window_s=60.0)
        per_req = self.ADMIT_US / 1e6 + 15 * 0.028 / 8
        assert pred == pytest.approx(1.0 / per_req, rel=1e-6)

    def test_saturated_window_degrades_to_median_tick(self):
        rig = _OracleRig()
        self._feed_tick_bins(rig, [8] * 10)     # zero occupancy spread
        model = rig.oracle._tick_model(60.0)
        assert model is not None
        assert model["h_us"] == 0.0
        assert model["c_us"] == pytest.approx(
            self.C_US + self.H_US * 8, rel=1e-6)

    def test_counter_reset_rows_are_skipped(self):
        rig = _OracleRig()
        self._feed_tick_bins(rig, [1, 3, 5, 7, 2, 4])
        rig.counters = {}                       # engine restart
        self._feed_tick_bins(rig, [6, 8, 1, 5, 3, 7])
        model = rig.oracle._tick_model(60.0)
        assert model is not None                # reset row dropped, not
        assert model["c_us"] == pytest.approx(  # poisoning the fit
            self.C_US, rel=1e-6)

    def test_gauge_fallback_without_tick_counters(self):
        rig = _OracleRig()
        for _ in range(6):
            rig.feed(gauges={"num_slots": 8.0, "slots_active": 2.0},
                     tokens_out=20, submitted=2)
        snap = rig.oracle.snapshot(60.0)
        assert validate_capacity_snapshot(snap, "test") == []
        # 20 tok/s over 2 busy slots → 10/slot → 80 at full width.
        assert snap["service_rate_per_slot"] == pytest.approx(10.0)
        assert snap["capacity_tokens_per_s"] == pytest.approx(80.0)
        assert snap["utilization"] == pytest.approx(0.25)
        assert snap["headroom_tokens_per_s"] == pytest.approx(60.0)
        # No phase-cost model → token-capacity fallback prediction.
        assert rig.oracle.predict_saturation_rps(16, window_s=60.0) \
            == pytest.approx(5.0)

    def test_kv_eta_and_rejection_rate(self):
        rig = _OracleRig()
        free = 120.0
        for _ in range(6):
            rig.feed(gauges={"num_slots": 8.0, "slots_active": 2.0,
                             "blocks_free": free},
                     tokens_out=20, submitted=10, rejected=1)
            free -= 10.0
        snap = rig.oracle.snapshot(60.0)
        assert snap["kv_exhaustion_eta_s"] == pytest.approx(7.0)
        assert snap["rejection_rate"] == pytest.approx(0.1)

    def test_fresh_oracle_refuses_to_guess(self):
        oracle = CapacityOracle(clock=_Clock())
        assert oracle.predict_saturation_rps(16) is None
        snap = oracle.snapshot()
        assert snap["capacity_tokens_per_s"] is None
        assert validate_capacity_snapshot(snap, "test") == []

    def test_aggregate_fleet_folds_and_takes_worst_eta(self):
        a = {"tokens_per_s": 100.0, "capacity_tokens_per_s": 200.0,
             "kv_exhaustion_eta_s": 30.0}
        b = {"tokens_per_s": 50.0, "capacity_tokens_per_s": 100.0,
             "kv_exhaustion_eta_s": 12.0}
        fleet = aggregate_fleet([a, None, b])
        assert fleet["replicas_reporting"] == 2
        assert fleet["tokens_per_s"] == pytest.approx(150.0)
        assert fleet["capacity_tokens_per_s"] == pytest.approx(300.0)
        assert fleet["headroom_tokens_per_s"] == pytest.approx(150.0)
        assert fleet["utilization"] == pytest.approx(0.5)
        assert fleet["kv_exhaustion_eta_s"] == 12.0   # first to exhaust
        assert aggregate_fleet([None, 3, "x"]) is None

    def test_capacity_view_is_the_cheap_slice(self):
        stats = ServeStats()
        stats.bump("tokens_out", 7)
        view = stats.capacity_view()
        assert view["counters"]["tokens_out"] == 7
        assert "gauges" in view and "ts" in view
        assert view["latency"] == {}            # no reservoir sorts


# ---------------------------------------------------------------------------
# Engine integration: plane on → schema-valid snapshot + prom families
# ---------------------------------------------------------------------------

class TestEnginePlane:
    @pytest.fixture(scope="class")
    def model(self):
        import jax

        from ray_lightning_tpu.models.gpt import GPT, GPTConfig

        cfg = GPTConfig(vocab_size=128, n_layer=2, n_head=4,
                        d_model=64, seq_len=64, warmup_steps=1)
        m = GPT(cfg, attn_impl="xla")
        return m, m.init_params(jax.random.PRNGKey(0))

    def _engine(self, model, **kw):
        from ray_lightning_tpu.serve.engine import (
            ServeConfig, ServeEngine,
        )

        m, params = model
        cfg = ServeConfig(num_slots=2, num_blocks=24, block_size=8,
                          export_every_s=0.05, **kw)
        return ServeEngine(m, params, cfg)

    def test_plane_on_snapshot_and_prom(self, model):
        eng = self._engine(model, capacity=True, slo=True,
                           ts_interval_s=0.1)
        try:
            assert eng.capacity_oracle is not None
            assert eng.slo_evaluator is not None
            for seed in range(3):
                eng.generate([seed + 1, 5, 9], 4)
            counters = eng.stats.snapshot()["counters"]
            # The engine feeds the oracle real phase costs.
            assert counters["decode_us"] > 0
            assert counters["admit_us"] > 0
            eng.slo_evaluator.evaluate()
            eng._maybe_export(force=True)

            snap = eng.snapshot()
            assert validate_serve_snapshot(snap, "test") == []
            assert "capacity" in snap
            assert validate_capacity_snapshot(snap["capacity"],
                                              "test") == []

            text = render_openmetrics(
                {"serve": snap, "slo": eng.slo_evaluator.snapshot()}
            )
            assert "rlt_capacity_tokens_per_sec" in text
            assert "rlt_capacity_rejection_rate" in text
            assert 'rlt_slo_burn_rate{slo="serve_availability"}' in text
        finally:
            eng.stop()

    def test_plane_off_has_no_capacity_block(self, model):
        eng = self._engine(model)
        try:
            eng.generate([1, 5, 9], 4)
            assert eng.capacity_oracle is None
            assert eng.slo_evaluator is None
            snap = eng.snapshot()
            assert "capacity" not in snap
            assert validate_serve_snapshot(snap, "test") == []
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# rlt_top capacity pane + staleness tag; fleet fold in the router pane
# ---------------------------------------------------------------------------

class TestRltTopPane:
    def _serve_snapshot(self):
        return {
            "ts": 1000.0,
            "serve": {
                "counters": {"completed": 4, "submitted": 5},
                "gauges": {"slots_active": 1.0},
                "latency": {},
                "capacity": {
                    "tokens_per_s": 40.0,
                    "capacity_tokens_per_s": 80.0,
                    "headroom_tokens_per_s": 40.0,
                    "utilization": 0.5,
                    "kv_exhaustion_eta_s": 12.0,
                    "queue_depth": 2.0,
                },
            },
            "slo": {"avail": {"firing": True, "burn_rate": 3.2,
                              "error_rate": 0.04, "target": 0.99,
                              "alerts_total": 1}},
        }

    def test_capacity_pane_renders_with_sparkline(self):
        from tools import rlt_top

        snap = self._serve_snapshot()
        history = {}
        for load in (10.0, 20.0, 40.0):
            snap["serve"]["capacity"]["tokens_per_s"] = load
            rlt_top.note_history(snap, history)
        text = rlt_top.render(snap, "test", history=history,
                              now=1001.0)
        assert "capacity:" in text
        assert "ceiling 80.0" in text
        assert "avail" in text and "3.2" in text   # SLO line
        assert "STALE" not in text

    def test_stale_tag_marks_dead_source(self):
        from tools import rlt_top

        text = rlt_top.render(self._serve_snapshot(), "test",
                              now=1000.0 + 3600.0)
        assert "STALE" in text

    def test_router_pane_renders_fleet_fold(self):
        from tools import rlt_top

        snap = {
            "ts": 1000.0,
            "router": {
                "replicas": {}, "counters": {},
                "capacity": aggregate_fleet([
                    {"tokens_per_s": 100.0,
                     "capacity_tokens_per_s": 200.0},
                    {"tokens_per_s": 60.0,
                     "capacity_tokens_per_s": 100.0},
                ]),
            },
        }
        text = rlt_top.render(snap, "test", now=1001.0)
        assert "ceiling 300.0" in text


# ---------------------------------------------------------------------------
# tools/rlt_bench_diff.py: the regression differ's own contract
# ---------------------------------------------------------------------------

class TestBenchDiff:
    def test_self_test_passes(self):
        from tools.rlt_bench_diff import self_test

        assert self_test() == 0

    def test_lookup_and_direction(self):
        from tools.rlt_bench_diff import diff_docs, lookup

        doc = {"serve": {"requests_per_sec": 12.5}}
        assert lookup(doc, "serve.requests_per_sec") == 12.5
        assert lookup(doc, "serve.missing") is None
        rows = {r["key"]: r for r in diff_docs(
            {"serve": {"requests_per_sec": 10.0}},
            {"serve": {"requests_per_sec": 8.0}},
        )}
        assert rows["serve.requests_per_sec"]["status"] == "regression"
