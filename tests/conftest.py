"""Test harness: simulate an 8-device TPU mesh on CPU.

The analogue of the reference fixture pattern ``ray.init(num_cpus=N)`` +
Gloo backend for CPU integration tests (``tests/test_ddp.py:20-39``,
SURVEY §4): we force the JAX host platform and split it into 8 virtual
devices so every mesh/sharding/collective path runs in CI without TPU
hardware.  Must run before the first ``import jax`` anywhere in the test
process — conftest import time is the earliest reliable hook.

Worker actors spawned by the LocalBackend inherit this environment, so
they also see 8 CPU devices.
"""

import os

# Force-override: the host environment pins JAX_PLATFORMS to the real TPU
# tunnel; tests must run on the virtual CPU mesh.  Set RLT_REAL_TPU=1 to
# opt in to real-hardware tests (the analogue of the reference's CLUSTER=1
# gate, test_ddp_gpu.py:125-136).
if not os.environ.get("RLT_REAL_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Neutralize any real-TPU sitecustomize hook in spawned worker actors:
    # a PJRT plugin registered at interpreter startup would lock jax state
    # before jax.distributed.initialize runs in the worker.
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# A sitecustomize hook may have imported jax at interpreter startup (before
# this conftest), freezing the platform choice from the original env.  The
# env vars above still govern *spawned worker actors*; for THIS process we
# must override via jax.config before the backend initializes.
if not os.environ.get("RLT_REAL_TPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def cpu_mesh_devices():
    import jax

    devices = jax.devices()
    assert len(devices) == 8, "conftest env did not take effect"
    return devices
