"""Remote-strategy integration tests: full driver→actor→mesh→driver cycle.

≙ the reference's core DDP integration tier (``test_ddp.py``) — training
runs on worker actors, the driver only ships/pumps/recovers.  Single-actor
workers here own the whole 8-device CPU mesh (one actor ≙ one TPU host).
"""

import os

import numpy as np
import pytest

import jax

from ray_lightning_tpu.cluster.actor import RemoteError
from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import (
    BoringDataModule,
    BoringModel,
    XORDataModule,
    XORModel,
)
from ray_lightning_tpu.parallel.strategies import (
    HorovodRayStrategy,
    LocalStrategy,
    RayShardedStrategy,
    RayStrategy,
)

from utils import get_trainer, train_test


pytestmark = pytest.mark.remote


def test_ray_strategy_fit(tmp_path):
    trainer = get_trainer(
        RayStrategy(num_workers=1), max_epochs=2, tmp_path=tmp_path
    )
    train_test(trainer, BoringModel(), BoringDataModule())


def test_horovod_flavor_fit(tmp_path):
    trainer = get_trainer(
        HorovodRayStrategy(num_workers=1), max_epochs=2, tmp_path=tmp_path
    )
    train_test(trainer, BoringModel(), BoringDataModule())


def test_sharded_strategy_fit(tmp_path):
    trainer = get_trainer(
        RayShardedStrategy(num_workers=1, zero_stage=3),
        max_epochs=2,
        tmp_path=tmp_path,
    )
    train_test(trainer, BoringModel(in_dim=256, out_dim=128),
               BoringDataModule(in_dim=256))


def test_remote_matches_local_trajectory(tmp_path):
    """Same seed/data ⇒ identical final params local vs remote (the
    DDP↔pmap parity check at the strategy level)."""
    local = get_trainer(LocalStrategy(), max_epochs=2,
                        tmp_path=tmp_path / "a")
    local.fit(BoringModel(), BoringDataModule())
    remote = get_trainer(RayStrategy(num_workers=1), max_epochs=2,
                         tmp_path=tmp_path / "b")
    remote.fit(BoringModel(), BoringDataModule())
    # Tolerance note (SURVEY §7 hard-part #5): across *processes* the XLA
    # CPU runtime's reduction order is not bitwise-stable, and 8 SGD steps
    # amplify the fp32 noise; ~1e-3 rel observed, 5e-3 bound.
    for x, y in zip(
        jax.tree_util.tree_leaves(local.params),
        jax.tree_util.tree_leaves(remote.params),
    ):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=5e-3, atol=1e-3)


def test_metrics_and_best_path_recovered(tmp_path):
    # ≙ reference metrics fidelity (test_ddp.py:326-350) + best-path
    # adoption (ray_ddp.py:393-395).
    trainer = get_trainer(
        RayStrategy(num_workers=1), max_epochs=2, tmp_path=tmp_path
    )
    trainer.fit(BoringModel(), BoringDataModule())
    assert "train_loss" in trainer.callback_metrics
    assert "val_loss" in trainer.callback_metrics
    assert trainer.best_model_path
    assert os.path.exists(trainer.best_model_path)


def test_worker_exception_propagates(tmp_path):
    class Exploding(BoringModel):
        def configure_optimizers(self):
            raise RuntimeError("worker-side boom")

    trainer = get_trainer(RayStrategy(num_workers=1), tmp_path=tmp_path)
    with pytest.raises(RemoteError, match="worker-side boom"):
        trainer.fit(Exploding(), BoringDataModule())


def test_init_hook_runs_on_workers(tmp_path):
    # ≙ reference init_hook (ray_ddp.py:122,194-195) — runs before training.
    marker = str(tmp_path / "hook-ran")

    def hook():
        open(marker, "w").write("yes")

    strategy = RayStrategy(num_workers=1, init_hook=hook)
    trainer = get_trainer(strategy, tmp_path=tmp_path)
    trainer.fit(BoringModel(), BoringDataModule())
    assert os.path.exists(marker)


def test_session_rank_available_in_callbacks(tmp_path):
    # Callbacks inside the remote loop can query the session (≙ reference
    # get_actor_rank used by Tune callbacks, session.py:56-58).
    class RankProbe(Callback):
        def on_fit_start(self, trainer, module):
            from ray_lightning_tpu.session import get_actor_rank

            self.seen_rank = get_actor_rank()
            assert trainer.world_size == 1

        def state_dict(self):
            return {"seen_rank": self.seen_rank}

    probe = RankProbe()
    trainer = get_trainer(
        RayStrategy(num_workers=1), tmp_path=tmp_path, callbacks=[probe],
        enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), BoringDataModule())
    # state came back from the worker through callback_states
    assert not hasattr(probe, "seen_rank") or probe.seen_rank == 0


def test_predict_remote(tmp_path):
    trainer = get_trainer(
        RayStrategy(num_workers=1), max_epochs=4, tmp_path=tmp_path
    )
    trainer.fit(XORModel(), XORDataModule())
    preds = trainer.predict(XORModel(), XORDataModule())
    assert preds.ndim == 1 and len(preds) > 0


def test_resource_resolution_matrix():
    # ≙ reference test_ddp.py:138-176 resource resolution.
    s = RayStrategy(num_workers=2, num_cpus_per_worker=4)
    assert s.num_cpus_per_worker == 4 and s.use_tpu
    s = RayStrategy(
        num_workers=2, resources_per_worker={"CPU": 2, "TPU": 0}
    )
    assert s.num_cpus_per_worker == 2 and not s.use_tpu
    s = RayStrategy(
        num_workers=1, resources_per_worker={"custom": 1.0}
    )
    assert s.additional_resources_per_worker == {"custom": 1.0}
    with pytest.raises(ValueError):
        RayStrategy(num_workers=0)


def test_driver_never_initializes_accelerator_backend(tmp_path):
    """The DelayedGPUAccelerator contract (≙ reference ``util.py:11-37``,
    VERDICT r4 weak #4): during a remote fit, jax runs ONLY in the worker
    actors — the driver process must finish the whole ship→pump→recover
    cycle without ever initializing a jax backend.  Fresh subprocess so
    no other test's device work contaminates the check."""
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent(f"""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        from ray_lightning_tpu.core.trainer import Trainer
        from ray_lightning_tpu.models import BoringDataModule, BoringModel
        from ray_lightning_tpu.parallel.strategies import RayStrategy

        trainer = Trainer(
            strategy=RayStrategy(num_workers=1), max_epochs=1,
            default_root_dir={str(tmp_path)!r}, enable_checkpointing=False,
        )
        trainer.fit(BoringModel(), BoringDataModule())
        assert trainer.state is not None  # the fit really happened

        import jax._src.xla_bridge as xb
        if hasattr(xb, "backends_are_initialized"):
            initialized = xb.backends_are_initialized()
        else:
            initialized = bool(xb._backends)
        assert not initialized, (
            "driver initialized a jax backend during a remote fit"
        )
        print("DRIVER_DISCIPLINE_OK")
    """)
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "DRIVER_DISCIPLINE_OK" in proc.stdout


def test_zero_stage_2_normalizes_to_1_with_warning():
    """zero_stage=2 has no distinct GSPMD semantics (VERDICT r4 weak #6):
    accepting it silently as an alias would let users misreport what they
    benchmarked — it must normalize loudly."""
    import warnings

    with pytest.warns(UserWarning, match="zero_stage=2"):
        s = RayShardedStrategy(num_workers=1, zero_stage=2)
    assert s.zero_stage == 1
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert RayShardedStrategy(num_workers=1, zero_stage=1).zero_stage == 1
        assert RayShardedStrategy(num_workers=1, zero_stage=3).zero_stage == 3
