"""Mixture-of-Experts routing + expert-parallel GPT.

Net-new capability over the reference (SURVEY §2.3 "EP: absent"); the
test pattern follows the framework's sharded-parity discipline: an
``expert``-axis mesh must be numerically a no-op.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models.gpt import GPT, GPTConfig, SyntheticLMDataModule
from ray_lightning_tpu.ops.moe import (
    load_balance_loss,
    moe_mlp,
    topk_capacity_routing,
)
from ray_lightning_tpu.parallel.strategies import LocalStrategy


def test_routing_respects_topk_and_capacity():
    rng = np.random.default_rng(0)
    probs = jax.nn.softmax(
        jnp.asarray(rng.standard_normal((64, 4)), jnp.float32), -1
    )
    combine, dispatch = topk_capacity_routing(probs, top_k=2, capacity=8)
    # ≤ top_k assignments per token; ≤ capacity tokens per expert slot.
    assert float(dispatch.sum(axis=(1, 2)).max()) <= 2
    assert float(dispatch.sum(axis=(0, 2)).max()) <= 8
    # Each (expert, slot) holds at most one token.
    assert float(dispatch.sum(axis=0).max()) <= 1
    # Combine gates normalized over a token's accepted experts.
    totals = combine.sum(axis=(1, 2))
    assigned = dispatch.sum(axis=(1, 2)) > 0
    np.testing.assert_allclose(
        np.asarray(totals)[np.asarray(assigned)], 1.0, atol=1e-5
    )


def test_balanced_router_minimizes_aux_loss():
    S, E = 64, 4
    uniform = jnp.full((S, E), 1.0 / E, jnp.float32)
    _, dispatch = topk_capacity_routing(uniform, top_k=1, capacity=S)
    assert float(load_balance_loss(uniform, dispatch)) == pytest.approx(
        1.0, rel=1e-5
    )


def test_moe_mlp_matches_single_expert_dense():
    """E=1, ample capacity: MoE must reduce to the plain FFN exactly
    (gate prob is 1 after softmax over one expert)."""
    rng = np.random.default_rng(0)
    B, T, d, h = 2, 8, 16, 32
    x = jnp.asarray(rng.standard_normal((B, T, d)), jnp.float32)
    w_in = jnp.asarray(rng.standard_normal((1, d, h)), jnp.float32) * 0.1
    w_out = jnp.asarray(rng.standard_normal((1, h, d)), jnp.float32) * 0.1
    gate = jnp.zeros((d, 1), jnp.float32)
    y, aux = moe_mlp(x, gate, w_in, jnp.zeros((1, h)), w_out,
                     jnp.zeros((1, d)), top_k=1, capacity_factor=1.0)
    dense = jax.nn.gelu(x @ w_in[0]) @ w_out[0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), atol=1e-5)
    assert float(aux) == pytest.approx(1.0, rel=1e-5)


def test_tiny_capacity_drops_tokens_but_stays_finite():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.float32)
    gate = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    w_in = jnp.asarray(rng.standard_normal((4, 8, 16)), jnp.float32) * 0.1
    w_out = jnp.asarray(rng.standard_normal((4, 16, 8)), jnp.float32) * 0.1
    y, aux = moe_mlp(x, gate, w_in, jnp.zeros((4, 16)), w_out,
                     jnp.zeros((4, 8)), top_k=2, capacity_factor=0.1)
    assert np.all(np.isfinite(np.asarray(y)))
    assert np.isfinite(float(aux))


def make_trainer(**kw):
    kw.setdefault("max_epochs", 1)
    kw.setdefault("limit_train_batches", 2)
    kw.setdefault("limit_val_batches", 1)
    kw.setdefault("enable_checkpointing", False)
    return Trainer(**kw)


def fit_moe(strategy, **cfg_kw):
    cfg = GPTConfig.tiny_moe(**cfg_kw)
    tr = make_trainer(strategy=strategy)
    tr.fit(GPT(cfg),
           SyntheticLMDataModule(cfg, batch_size=8, num_batches=2))
    return tr


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_moe_gpt_trains():
    tr = fit_moe(LocalStrategy())
    assert np.isfinite(tr.callback_metrics["train_loss"])
    assert 4.0 < tr.callback_metrics["train_loss"] < 8.0
    # Aux loss logged and near 1 (≈ balanced) for random init.
    assert 0.5 < tr.callback_metrics["moe_aux_loss"] < 4.0


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_moe_expert_parallel_parity():
    """ep × tp × dp mesh must match the unsharded run numerically.

    Drop-free capacity (factor = E): grouped routing (groups follow the
    data-shard count) only changes *which slot* a token occupies, never
    which experts serve it, so the math is mesh-invariant.
    """
    base = fit_moe(LocalStrategy(), moe_capacity_factor=4.0)
    sharded = fit_moe(
        LocalStrategy(mesh_axes={"data": 2, "expert": 2, "tensor": 2}),
        moe_capacity_factor=4.0,
    )
    assert base.callback_metrics["train_loss"] == pytest.approx(
        sharded.callback_metrics["train_loss"], rel=1e-5
    )
    assert base.callback_metrics["moe_aux_loss"] == pytest.approx(
        sharded.callback_metrics["moe_aux_loss"], rel=1e-4
    )


def test_routing_group_count_invariance_at_drop_free_capacity():
    """At drop-free capacity the group reshape must be a pure relabeling:
    same token→expert assignment set, same (zero) drop count, same aux
    loss — for any group count that divides the token count."""
    rng = np.random.default_rng(3)
    B, T, d, E = 2, 32, 8, 4
    S = B * T
    x = jnp.asarray(rng.standard_normal((B, T, d)), jnp.float32)
    gate = jnp.asarray(rng.standard_normal((d, E)), jnp.float32)
    w_in = jnp.asarray(rng.standard_normal((E, d, 16)), jnp.float32) * 0.1
    w_out = jnp.asarray(rng.standard_normal((E, 16, d)), jnp.float32) * 0.1

    def run(groups):
        # Reproduce moe_mlp's routing path to inspect dispatch directly.
        G = groups
        s = S // G
        capacity = int(np.ceil(s / E * E))  # drop-free: capacity == s
        xg = x.reshape(G, s, d)
        logits = jnp.einsum("gsd,de->gse", xg, gate)
        probs = jax.nn.softmax(logits, -1)
        _, dispatch = jax.vmap(
            lambda p: topk_capacity_routing(p, top_k=2, capacity=capacity)
        )(probs)
        # [S, E] token→expert assignment, group/slot structure erased.
        assign = dispatch.sum(axis=-1).reshape(S, E)
        dropped = 2 * S - float(dispatch.sum())
        y, aux = moe_mlp(x, gate, w_in, jnp.zeros((E, 16)), w_out,
                         jnp.zeros((E, d)), top_k=2,
                         capacity_factor=float(E), groups=G)
        return assign, dropped, y, aux

    a1, d1, y1, aux1 = run(1)
    for G in (2, 4):
        aG, dG, yG, auxG = run(G)
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(aG))
        assert d1 == dG == 0.0
        np.testing.assert_allclose(np.asarray(y1), np.asarray(yG),
                                   atol=1e-5)
        assert float(aux1) == pytest.approx(float(auxG), rel=1e-6)


def test_moe_partition_specs_cover_params():
    model = GPT(GPTConfig.tiny_moe())
    params = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    from jax.sharding import PartitionSpec as P

    specs = model.param_partition_specs()
    p_paths = {
        jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    s_paths = {
        jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))[0]
    }
    assert p_paths == s_paths
