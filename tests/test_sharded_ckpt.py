"""Sharded (per-host) restart checkpoints: round-trip, completeness
discipline, and the no-all-gather property (VERDICT r3 item #3).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_lightning_tpu.utils import sharded_ckpt


def _sharded_tree(mesh):
    """A ZeRO-3-shaped tree: params sharded over the mesh, scalars
    replicated."""
    w = jax.device_put(
        np.arange(16 * 8, dtype=np.float32).reshape(16, 8),
        NamedSharding(mesh, P("data", None)),
    )
    b = jax.device_put(
        np.arange(8, dtype=np.float32), NamedSharding(mesh, P())
    )
    step = jax.device_put(
        jnp.int32(7), NamedSharding(mesh, P())
    )
    return {"w": w, "b": b, "step": step}


def test_roundtrip_single_process(tmp_path):
    mesh = Mesh(mesh_utils.create_device_mesh((8,)), ("data",))
    tree = _sharded_tree(mesh)
    tag = str(tmp_path / "ck.ckpt")
    sharded_ckpt.save_shard(tree, tag, rank=0, world=1)
    sharded_ckpt.save_meta(tree, tag, world=1, extra={"epoch": 3})
    assert sharded_ckpt.is_sharded_ckpt(tag)
    payload = sharded_ckpt.load_sharded(tag)
    assert payload["epoch"] == 3
    got = payload["state"]
    np.testing.assert_array_equal(got["w"], np.asarray(tree["w"]))
    np.testing.assert_array_equal(got["b"], np.asarray(tree["b"]))
    assert int(got["step"]) == 7


def test_shard_files_split_the_state(tmp_path):
    """Simulate 2 hosts by splitting one 8-device mesh's shards in half:
    each rank's file must contain ~half the sharded bytes, and the loader
    must stitch them back together."""
    mesh = Mesh(mesh_utils.create_device_mesh((8,)), ("data",))
    tree = _sharded_tree(mesh)

    # Fake per-host addressability: filter addressable_shards by rank.
    class _HalfView:
        def __init__(self, arr, lo, hi):
            self._arr = arr
            self._lo, self._hi = lo, hi
            self.dtype = arr.dtype
            self.shape = arr.shape

        @property
        def addressable_shards(self):
            shards = sorted(
                self._arr.addressable_shards,
                key=lambda s: (s.index[0].start or 0) if s.index else 0,
            )
            return shards[self._lo:self._hi]

    jax_Array = jax.Array

    def half(tree, lo, hi):
        return jax.tree_util.tree_map(
            lambda a: _HalfView(a, lo, hi)
            if isinstance(a, jax_Array) else a, tree
        )

    tag = str(tmp_path / "ck.ckpt")
    # _leaf_record only duck-types (isinstance check) — patch it through
    # the public API by monkeypatching isinstance is overkill; instead
    # write the two halves directly through _leaf_record's array branch.
    import ray_lightning_tpu.utils.sharded_ckpt as sc

    orig = sc._leaf_record

    def patched(leaf):
        if isinstance(leaf, _HalfView):
            fake = leaf

            class _Shim:
                pass

            # reuse the real encoder by handing it an object that walks
            # like a jax.Array for the attributes it touches
            rec_entries = []
            seen = set()
            for sh in fake.addressable_shards:
                idx = tuple(
                    (0 if s.start is None else int(s.start),
                     d if s.stop is None else int(s.stop))
                    for s, d in zip(sh.index, fake.shape)
                )
                if idx in seen:
                    continue
                seen.add(idx)
                rec_entries.append({
                    "i": [list(p) for p in idx],
                    "b": np.asarray(jax.device_get(sh.data)).tobytes(),
                })
            return {"s": list(fake.shape), "d": str(fake.dtype),
                    "e": rec_entries}
        return orig(leaf)

    sc._leaf_record = patched
    try:
        sharded_ckpt.save_shard(half(tree, 0, 4), tag, rank=0, world=2)
        sharded_ckpt.save_shard(half(tree, 4, 8), tag, rank=1, world=2)
    finally:
        sc._leaf_record = orig
    sharded_ckpt.save_meta(tree, tag, world=2, extra={"epoch": 0})

    sizes = sorted(
        os.path.getsize(os.path.join(tag, n))
        for n in os.listdir(tag) if n.startswith("shard-")
    )
    w_bytes = 16 * 8 * 4
    # Neither shard file holds the whole sharded leaf.
    assert all(s < w_bytes + 600 for s in sizes)
    got = sharded_ckpt.load_sharded(tag)["state"]
    np.testing.assert_array_equal(got["w"], np.asarray(tree["w"]))


def test_incomplete_checkpoint_is_ignored(tmp_path):
    """No META (crash before the barrier) => not a checkpoint; missing
    shard file => loud error, not silent partial state."""
    from ray_lightning_tpu.parallel.strategies import (
        _remote_latest_restart_checkpoint,
    )

    mesh = Mesh(mesh_utils.create_device_mesh((8,)), ("data",))
    tree = _sharded_tree(mesh)
    rdir = tmp_path / "restarts"
    tag = str(rdir / "restart-epoch-000000.ckpt")
    sharded_ckpt.save_shard(tree, tag, rank=0, world=2)
    # no META, only 1/2 shards
    assert not sharded_ckpt.is_sharded_ckpt(tag)
    assert _remote_latest_restart_checkpoint(str(rdir))["path"] is None
    sharded_ckpt.save_meta(tree, tag, world=2)
    # META present but a shard file is gone: discovery VERIFIES and
    # walks past it (previous-good fallback) instead of handing the
    # resume a checkpoint that cannot load...
    info = _remote_latest_restart_checkpoint(str(rdir))
    assert info["path"] is None
    assert [c["path"] for c in info["corrupt"]] == [tag]
    # ...and a direct load of the broken checkpoint stays loud.
    with pytest.raises(FileNotFoundError, match="missing"):
        sharded_ckpt.load_sharded(tag)


def test_resume_from_sharded_checkpoint(tmp_path):
    """End-to-end: run_fit writes a sharded restart checkpoint, and a
    second fit RESUMES from it (the elastic path's exact format)."""
    from ray_lightning_tpu.core.loop import FitConfig, run_fit
    from ray_lightning_tpu.models import BoringDataModule, BoringModel
    from ray_lightning_tpu.parallel.strategies import (
        _remote_latest_restart_checkpoint,
    )

    rs = str(tmp_path / "rs")
    dm = lambda: BoringDataModule(length=32, batch_size=16)  # noqa: E731
    cfg1 = FitConfig(
        max_epochs=2, seed=0, default_root_dir=str(tmp_path),
        restart_dir=rs, restart_every_n_epochs=1,
    )
    res1 = run_fit(BoringModel(), dm(), cfg1, callbacks=[])
    # Discovery returns the newest VERIFIED checkpoint plus any
    # corrupt ones it walked past (the previous-good fallback).
    info = _remote_latest_restart_checkpoint(rs)
    tag = info["path"]
    assert tag is not None and sharded_ckpt.is_sharded_ckpt(tag)
    assert info["corrupt"] == []

    cfg2 = FitConfig(
        max_epochs=4, seed=0, default_root_dir=str(tmp_path),
        resume_from_checkpoint=tag,
    )
    res2 = run_fit(BoringModel(), dm(), cfg2, callbacks=[])
    assert res2["epochs_run"] == 4
    assert res2["global_step"] > res1["global_step"]
