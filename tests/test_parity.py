"""Gradient/loss parity across execution flavors — the north-star metric's
second half (BASELINE.md: "DDP↔pmap gradient parity").

Single-device vs GSPMD-sharded vs shard_map-explicit must produce the same
gradients and the same training trajectory on a fixed seed/batch, within
fp32 tolerance (SURVEY §7 hard-part #5: bitwise equality is not achievable
across different collective schedules; 1e-5 rel is).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from ray_lightning_tpu.core.loop import init_train_state
from ray_lightning_tpu.core.module import TrainState
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.parallel import step_fns
from ray_lightning_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_lightning_tpu.parallel.sharding import make_global_batch


@pytest.fixture
def setup():
    module = BoringModel(in_dim=16, out_dim=4, lr=0.1)
    tx = module.configure_optimizers()
    rng = jax.random.PRNGKey(0)
    batch = {"x": np.random.default_rng(0).standard_normal(
        (16, 16), dtype=np.float32)}
    return module, tx, rng, batch


def _run_steps(module, tx, rng, batch, mesh, mode, zero_stage=0, n=3):
    state, shardings = init_train_state(module, tx, mesh, zero_stage, seed=0)
    step = step_fns.build_train_step(
        module, tx, mesh, mode=mode, state_shardings=shardings
    )
    placed = batch if mesh is None else make_global_batch(batch, mesh)
    losses = []
    for i in range(n):
        state, logs = step(state, placed, jax.random.fold_in(rng, i))
        losses.append(float(logs["loss"]))
    return jax.device_get(state.params), losses


def _assert_close(pa, pb, tol=1e-5):
    la, lb = jax.tree_util.tree_leaves(pa), jax.tree_util.tree_leaves(pb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(x, y, rtol=tol, atol=tol)


def test_gspmd_matches_single_device(setup):
    module, tx, rng, batch = setup
    p_single, l_single = _run_steps(module, tx, rng, batch, None, "gspmd")
    mesh = build_mesh(MeshSpec())
    p_mesh, l_mesh = _run_steps(module, tx, rng, batch, mesh, "gspmd")
    _assert_close(p_single, p_mesh)
    np.testing.assert_allclose(l_single, l_mesh, rtol=1e-5)


def test_shard_map_matches_single_device(setup):
    module, tx, rng, batch = setup
    p_single, _ = _run_steps(module, tx, rng, batch, None, "gspmd")
    mesh = build_mesh(MeshSpec())
    p_sm, _ = _run_steps(module, tx, rng, batch, mesh, "shard_map")
    _assert_close(p_single, p_sm)


def test_zero1_matches_replicated(setup):
    module, tx, rng, batch = setup
    mesh = build_mesh(MeshSpec())
    p_repl, _ = _run_steps(module, tx, rng, batch, mesh, "gspmd", 0)
    p_z1, _ = _run_steps(module, tx, rng, batch, mesh, "gspmd", 1)
    _assert_close(p_repl, p_z1)


def test_zero3_matches_replicated(setup):
    module, tx, rng, batch = setup
    mesh = build_mesh(MeshSpec())
    p_repl, _ = _run_steps(module, tx, rng, batch, mesh, "gspmd", 0)
    p_z3, _ = _run_steps(module, tx, rng, batch, mesh, "gspmd", 3)
    _assert_close(p_repl, p_z3)


def test_zero3_actually_shards_large_params():
    """ZeRO-3 must physically partition big leaves over the mesh."""
    module = BoringModel(in_dim=256, out_dim=128)
    tx = module.configure_optimizers()
    mesh = build_mesh(MeshSpec())
    state, shardings = init_train_state(module, tx, mesh, 3, seed=0)
    w = state.params["w"]  # (256, 128) = 32768 elems > min_leaf_size
    assert not w.sharding.is_fully_replicated
    # Each device holds 1/8 of the rows.
    shard_shape = w.sharding.shard_shape(w.shape)
    assert shard_shape[0] * 8 == 256 or shard_shape[1] * 8 == 128


def test_loss_decreases(setup):
    module, tx, rng, batch = setup
    mesh = build_mesh(MeshSpec())
    _, losses = _run_steps(module, tx, rng, batch, mesh, "gspmd", n=10)
    assert losses[-1] < losses[0]
