"""Recovery-plane unit tests: the chaos grammar/plan, checkpoint
integrity (crc frames, per-shard checksums, verified walk-back
discovery), drain coordination, and restart-governance arithmetic.

The end-to-end acceptance matrix (real worker actors + injected
faults) lives in ``tests/test_fault_tolerance.py`` / ``tools/
chaos_sweep.py``; everything here is in-process and fast.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_lightning_tpu.fault import drain as drain_mod
from ray_lightning_tpu.fault import inject
from ray_lightning_tpu.fault.drain import PreemptedError
from ray_lightning_tpu.utils import sharded_ckpt, state_stream


# ---------------------------------------------------------------------------
# RLT_FAULT grammar + plan semantics
# ---------------------------------------------------------------------------

def test_grammar_parses_full_spec():
    specs = inject.parse_faults(
        "crash@step:7,rank:1;hang@step:5,secs:2.5;"
        "bitflip@point:ckpt_write,nth:2;sigterm@epoch:1,once:0"
    )
    assert [s.kind for s in specs] == ["crash", "hang", "bitflip",
                                      "sigterm"]
    assert specs[0].step == 7 and specs[0].rank == 1
    assert specs[1].secs == 2.5
    assert specs[2].point == "ckpt_write" and specs[2].nth == 2
    assert specs[3].epoch == 1 and specs[3].once is False
    assert [s.index for s in specs] == [0, 1, 2, 3]


@pytest.mark.parametrize("bad", [
    "explode@step:1",        # unknown kind
    "crash@step",            # not key:value
    "crash@wat:1",           # unknown key
    "crash@point:nowhere",   # unknown point
])
def test_grammar_rejects_typos_loudly(bad):
    with pytest.raises(ValueError):
        inject.parse_faults(bad)


def test_plan_matches_exact_coordinates_only():
    plan = inject.FaultPlan(inject.parse_faults("exc@step:2,rank:0"), None)
    assert not plan.due("step", rank=0, step=1, epoch=0)
    assert not plan.due("step", rank=1, step=2, epoch=0)
    assert not plan.due("queue_put", rank=0, step=2, epoch=0)
    assert len(plan.due("step", rank=0, step=2, epoch=0)) == 1


def test_plan_nth_counts_matching_occurrences():
    plan = inject.FaultPlan(
        inject.parse_faults("torn@point:ckpt_write,nth:3"), None
    )
    assert not plan.due("ckpt_write", None, None, None)
    assert not plan.due("ckpt_write", None, None, None)
    assert len(plan.due("ckpt_write", None, None, None)) == 1


def test_plan_once_markers_survive_process_restart(tmp_path):
    state = str(tmp_path / "chaos")
    plan = inject.FaultPlan(inject.parse_faults("exc@step:2"), state)
    (spec,) = plan.due("step", None, 2, None)
    plan.mark_fired(spec)
    # Same plan: marker blocks a refire.
    assert not plan.due("step", None, 2, None)
    # A FRESH plan (= the respawned worker process) sees the marker too.
    fresh = inject.FaultPlan(inject.parse_faults("exc@step:2"), state)
    assert not fresh.due("step", None, 2, None)


def test_fire_reads_env_and_raises(monkeypatch, tmp_path):
    monkeypatch.setenv("RLT_FAULT", "exc@step:4,rank:0")
    monkeypatch.setenv("RLT_FAULT_STATE", str(tmp_path / "chaos"))
    inject.set_rank(0)
    try:
        inject.fire("step", step=3, epoch=0)  # no match
        with pytest.raises(inject.FaultInjected):
            inject.fire("step", step=4, epoch=0)
        # once=1: the marker blocks a second firing.
        inject.fire("step", step=4, epoch=0)
    finally:
        inject.set_rank(None)


def test_fire_is_inert_without_env(monkeypatch):
    monkeypatch.delenv("RLT_FAULT", raising=False)
    inject.fire("step", step=0, epoch=0, rank=0)  # must be a no-op


# ---------------------------------------------------------------------------
# Checkpoint integrity: crc frames, shard checksums, verified discovery
# ---------------------------------------------------------------------------

def _write_stream_ckpt(path, value=5):
    stream = state_stream.to_state_stream(
        {"w": np.arange(value, dtype=np.float32)}
    )
    state_stream.state_stream_to_file(stream, str(path))
    return str(path)


def test_stream_file_crc_roundtrip_and_corruption(tmp_path):
    path = _write_stream_ckpt(tmp_path / "m.ckpt")
    assert state_stream.verify_stream_file(path) == []
    back = state_stream.load_state_stream(
        state_stream.state_stream_from_file(path)
    )
    np.testing.assert_array_equal(back["w"], np.arange(5, dtype=np.float32))
    # Raw-bytes path (open().read()) accepts the framed file too.
    back2 = state_stream.load_state_stream(open(path, "rb").read())
    np.testing.assert_array_equal(back2["w"], back["w"])
    inject._corrupt_bitflip(path)
    assert state_stream.verify_stream_file(path)
    with pytest.raises(state_stream.CorruptCheckpointError):
        state_stream.state_stream_from_file(path)


def test_stream_file_legacy_unframed_still_loads(tmp_path):
    path = str(tmp_path / "legacy.ckpt")
    stream = state_stream.to_state_stream({"w": np.ones(3, np.float32)})
    with open(path, "wb") as f:  # pre-crc writer: raw msgpack bytes
        f.write(stream)
    assert state_stream.verify_stream_file(path) == []
    back = state_stream.load_state_stream(
        state_stream.state_stream_from_file(path)
    )
    np.testing.assert_array_equal(back["w"], np.ones(3, np.float32))


def _write_sharded(tmp_path, name, epoch):
    tree = {"w": jnp.arange(16.0) + epoch, "step": jnp.int32(epoch)}
    tag = str(tmp_path / name)
    sharded_ckpt.save_shard(tree, tag, 0, 1)
    sharded_ckpt.save_meta(tree, tag, 1, extra={"epoch": epoch})
    return tag


def test_sharded_checksums_catch_bitflip_and_torn(tmp_path):
    tag = _write_sharded(tmp_path, "restart-epoch-000000.ckpt", 0)
    assert sharded_ckpt.verify_sharded(tag) == []
    shard = os.path.join(tag, "shard-00000-of-00001.ckpt")
    inject._corrupt_bitflip(shard)
    assert sharded_ckpt.verify_sharded(tag)
    with pytest.raises(sharded_ckpt.CorruptCheckpointError):
        sharded_ckpt.load_sharded(tag)
    tag2 = _write_sharded(tmp_path, "restart-epoch-000001.ckpt", 1)
    inject._corrupt_torn(os.path.join(tag2, "shard-00000-of-00001.ckpt"))
    assert sharded_ckpt.verify_sharded(tag2)


def test_meta_self_checksum_catches_corruption(tmp_path):
    tag = _write_sharded(tmp_path, "restart-epoch-000000.ckpt", 0)
    inject._corrupt_bitflip(os.path.join(tag, "META.ckpt"))
    problems = sharded_ckpt.verify_sharded(tag)
    assert problems, "corrupted META passed verification"


def test_discovery_walks_back_to_newest_verified(tmp_path):
    from ray_lightning_tpu.parallel.strategies import (
        _remote_latest_restart_checkpoint,
    )

    good = _write_sharded(tmp_path, "restart-epoch-000000.ckpt", 0)
    bad = _write_sharded(tmp_path, "restart-epoch-000001.ckpt", 1)
    # Make mtime ordering deterministic: the corrupt one is newest.
    os.utime(os.path.join(good, "META.ckpt"), (1_000_000, 1_000_000))
    inject._corrupt_bitflip(os.path.join(bad, "shard-00000-of-00001.ckpt"))
    info = _remote_latest_restart_checkpoint(str(tmp_path))
    assert info["path"] == good
    assert [c["path"] for c in info["corrupt"]] == [bad]
    # With the newest intact it wins outright.
    good2 = _write_sharded(tmp_path, "drain-step-00000042.ckpt", 2)
    info2 = _remote_latest_restart_checkpoint(str(tmp_path))
    assert info2["path"] == good2 and info2["corrupt"] == []


def test_discovery_ignores_incomplete_and_empty(tmp_path):
    from ray_lightning_tpu.parallel.strategies import (
        _remote_latest_restart_checkpoint,
    )

    assert _remote_latest_restart_checkpoint(str(tmp_path)) == {
        "path": None, "corrupt": []
    }
    os.makedirs(tmp_path / "restart-epoch-000000.ckpt")  # no META
    assert _remote_latest_restart_checkpoint(
        str(tmp_path)
    )["path"] is None


# ---------------------------------------------------------------------------
# Drain coordination + PreemptedError transport
# ---------------------------------------------------------------------------

def test_drain_request_reset_cycle():
    drain_mod.reset_drain()
    assert not drain_mod.drain_requested()
    drain_mod.request_drain("unit-test")
    assert drain_mod.drain_requested()
    assert drain_mod.drain_reason() == "unit-test"
    drain_mod.request_drain("second")  # first reason wins
    assert drain_mod.drain_reason() == "unit-test"
    drain_mod.reset_drain()
    assert not drain_mod.drain_requested()
    assert drain_mod.drain_reason() is None


def test_preempted_error_pickles_with_fields():
    from ray_lightning_tpu.cluster import rpc

    err = PreemptedError(
        "fit preempted (test)", checkpoint="/tmp/d.ckpt", step=7,
        epoch=2, rank=1, reason="signal:SIGTERM", drain_s=0.25,
    )
    back = rpc.loads(rpc.dumps(err))
    assert isinstance(back, PreemptedError)
    assert back.checkpoint == "/tmp/d.ckpt"
    assert back.step == 7 and back.epoch == 2 and back.rank == 1
    assert back.reason == "signal:SIGTERM" and back.drain_s == 0.25
    assert "fit preempted" in str(back)


def test_drain_poll_reduces_across_mesh(cpu_mesh_devices):
    """The drain-agreement collective: any process's flag drains all.
    Exercised on a single-process 8-device mesh (the multi-process
    topology is environment-gated), where the reduction semantics are
    identical."""
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    from ray_lightning_tpu.core.loop import _make_drain_poll

    mesh = Mesh(mesh_utils.create_device_mesh((8,)), ("data",))
    poll = _make_drain_poll(mesh, world_size=8)
    assert poll is not None
    assert poll(False) is False
    assert poll(True) is True
    # world_size 1 / no mesh: the zero-overhead local path.
    assert _make_drain_poll(mesh, 1) is None
    assert _make_drain_poll(None, 8) is None


def test_inline_drain_writes_checkpoint_and_resumes(tmp_path):
    """LocalStrategy drain end-to-end: PreemptedError names a
    step-granular checkpoint; resuming from it completes the fit with
    no lost or repeated steps."""
    from ray_lightning_tpu.core.callbacks import Callback
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.boring import (
        BoringDataModule,
        BoringModel,
    )
    from ray_lightning_tpu.parallel.strategies import LocalStrategy

    class DrainAt(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            if trainer.micro_step == 3:
                drain_mod.request_drain("unit-test")

    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=3,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        limit_train_batches=2, limit_val_batches=1,
        callbacks=[DrainAt()],
    )
    with pytest.raises(PreemptedError) as err:
        trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    ckpt = err.value.checkpoint
    assert ckpt and os.path.exists(ckpt)
    assert err.value.step == 3 and err.value.drain_s is not None

    resumed = Trainer(
        strategy=LocalStrategy(), max_epochs=3,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        limit_train_batches=2, limit_val_batches=1,
        resume_from_checkpoint=ckpt,
    )
    resumed.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert resumed.epochs_run == 3
    assert resumed.micro_step == 6  # 3 pre-drain + 3 post-resume


def test_drain_checkpoint_prefers_restart_dir(tmp_path):
    """With a caller-provided restart_dir, drain checkpoints land there
    (one place to look for ALL recovery state)."""
    from ray_lightning_tpu.core.callbacks import Callback
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.boring import (
        BoringDataModule,
        BoringModel,
    )
    from ray_lightning_tpu.parallel.strategies import LocalStrategy

    class DrainNow(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            drain_mod.request_drain("unit-test")

    restart_dir = str(tmp_path / "recovery")
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=2,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        limit_train_batches=2, restart_dir=restart_dir,
        callbacks=[DrainNow()],
    )
    with pytest.raises(PreemptedError) as err:
        trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert err.value.checkpoint.startswith(restart_dir)


# ---------------------------------------------------------------------------
# Restart governance arithmetic
# ---------------------------------------------------------------------------

def test_backoff_schedule_grows_caps_and_jitters():
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    s = RayStrategy(num_workers=1, max_restarts=3, restart_backoff_s=1.0,
                    restart_backoff_max_s=8.0)
    for streak, base in ((1, 1.0), (2, 2.0), (3, 4.0), (4, 8.0),
                         (10, 8.0)):  # capped
        for _ in range(5):
            d = s._backoff_delay(streak)
            assert base <= d <= base * 1.25, (streak, d)
    off = RayStrategy(num_workers=1, max_restarts=1,
                      restart_backoff_s=0.0)
    assert off._backoff_delay(1) == 0.0


def test_recovery_events_are_schema_valid():
    from ray_lightning_tpu.parallel.strategies import RayStrategy
    from ray_lightning_tpu.telemetry.schema import validate_stream_item

    s = RayStrategy(num_workers=1, max_restarts=1)
    s._record_recovery("backoff", delay_s=1.5, attempt=1, message="t")
    s._record_recovery("elastic_restart", attempt=1, recover_s=0.4,
                       ckpt="/tmp/x.ckpt", message="t")
    s._record_recovery("ckpt_corrupt", ckpt="/tmp/y.ckpt", message="t")
    s._record_recovery("preempt_restart", ckpt="/tmp/z.ckpt", message="t")
    for ev in s.recovery_events:
        assert validate_stream_item(ev, ev["kind"]) == []


def test_restart_knob_validation():
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    with pytest.raises(ValueError):
        RayStrategy(num_workers=1, restart_window_s=0)
    with pytest.raises(ValueError):
        RayStrategy(num_workers=1, restart_backoff_s=-1)
