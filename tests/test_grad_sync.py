"""Quantized, bucketed gradient sync (parallel/grad_sync.py +
ops/collective_quant.py): bucket-plan edge cases, codec error bounds, the
compressed all-reduce against an exact psum, wire accounting, and
fit-level loss parity (full vs int8 vs int8+error-feedback) on the
8-device CPU mesh.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.ops import collective_quant as cq
from ray_lightning_tpu.parallel import grad_sync as gsync
from ray_lightning_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_lightning_tpu.parallel.strategies import LocalStrategy

from test_trainer_features import FixedDataModule


# -- bucket plan -------------------------------------------------------------

def _abstract(*shapes):
    return [jax.ShapeDtypeStruct(s, jnp.float32) for s in shapes]


def test_bucket_plan_covers_every_leaf_once_in_order():
    tree = _abstract((64, 64), (64,), (128, 32), (32,))
    plan = gsync.build_bucket_plan(
        tree, n_shards=8, bucket_bytes=4 * (64 * 64 + 64), block_size=64
    )
    seen = [i for b in plan.buckets for i in b.indices]
    assert seen == [0, 1, 2, 3]  # layer order, each leaf exactly once
    sizes = [s for b in plan.buckets for s in b.sizes]
    assert sizes == [64 * 64, 64, 128 * 32, 32]
    assert plan.total_elems == sum(sizes)
    # Buckets respect the byte bound: first bucket is exactly the two
    # leaves that fit, the rest spill over.
    assert plan.buckets[0].indices == (0, 1)


def test_bucket_plan_empty_tree():
    plan = gsync.build_bucket_plan([], n_shards=8)
    assert plan.num_buckets == 0
    assert plan.total_elems == 0
    assert plan.wire_bytes_per_step("int8") == 0


def test_bucket_plan_skips_zero_element_leaves():
    # An empty placeholder leaf has nothing to sync; counting it as one
    # phantom element would desync padding from the actual payload.
    tree = _abstract((4, 4), (0,), ())
    plan = gsync.build_bucket_plan(tree, n_shards=2, block_size=8)
    sizes = [s for b in plan.buckets for s in b.sizes]
    assert sizes == [16, 1]  # matrix + scalar; the (0,) leaf is skipped
    assert 1 not in [i for b in plan.buckets for i in b.indices]


def test_env_bus_forwarded_to_worker_env():
    import os

    from ray_lightning_tpu.parallel.strategies import RayStrategy

    os.environ["RLT_GRAD_COMM"] = "int8_ef"
    try:
        s = RayStrategy(num_workers=1)
        # The env bus rides env_per_worker like RLT_COMPILE_CACHE, so
        # remote workers (agent/Ray spawned — they inherit the AGENT's
        # env, not the driver's) still see the driver's request.
        assert s.env_per_worker["RLT_GRAD_COMM"] == "int8_ef"
    finally:
        del os.environ["RLT_GRAD_COMM"]


def test_bucket_plan_single_tiny_param_pads_to_alignment():
    plan = gsync.build_bucket_plan(
        _abstract((3,)), n_shards=8, block_size=16
    )
    (b,) = plan.buckets
    assert b.size == 3
    assert b.padded == 128  # one n_shards*block_size alignment unit
    assert b.padded % (8 * 16) == 0


def test_bucket_plan_oversized_leaf_gets_own_bucket():
    # leaf 1 alone exceeds the bound; it must not merge with neighbors.
    tree = _abstract((8,), (4096,), (8,))
    plan = gsync.build_bucket_plan(
        tree, n_shards=2, bucket_bytes=1024, block_size=8
    )
    assert [b.indices for b in plan.buckets] == [(0,), (1,), (2,)]
    # Ragged tail: the last bucket holds only the 8-element leaf.
    assert plan.buckets[-1].size == 8


def test_wire_accounting_ratio_beats_3_5x():
    plan = gsync.build_bucket_plan(
        _abstract((256, 128), (128,)), n_shards=8, block_size=256
    )
    full = plan.wire_bytes_per_step("full")
    q = plan.wire_bytes_per_step("int8")
    assert full / q >= 3.5
    # int8 payload + f32 scales, ring-accounted: 2(n-1)/n traversals.
    padded = sum(b.padded for b in plan.buckets)
    expect = int(2 * 7 / 8 * (padded + padded // 256 * 4))
    assert q == expect


# -- block-scaled codec ------------------------------------------------------

def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.standard_normal(1024).astype(np.float32) * 3.0)
    q, s = cq.quantize_block_scaled(v, 128)
    back = cq.dequantize_block_scaled(q, s, 128)
    # Per-block bound: |err| <= scale/2 = absmax/254.
    err = np.abs(np.asarray(v - back)).reshape(-1, 128)
    amax = np.abs(np.asarray(v)).reshape(-1, 128).max(axis=1)
    assert (err.max(axis=1) <= amax / 254.0 + 1e-7).all()


def test_quantize_zero_block_is_exact_and_finite():
    v = jnp.zeros((256,), jnp.float32)
    q, s = cq.quantize_block_scaled(v, 128)
    assert np.asarray(q).sum() == 0
    assert np.isfinite(np.asarray(s)).all()
    back = cq.dequantize_block_scaled(q, s, 128)
    assert np.asarray(back).sum() == 0


# -- compressed all-reduce vs exact psum ------------------------------------

@pytest.fixture
def mesh8(cpu_mesh_devices):
    return build_mesh(MeshSpec({"data": 8}))


def _per_device_partials(n, size, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, size)).astype(np.float32)


def test_int8_all_reduce_matches_psum_within_quant_error(mesh8):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_lightning_tpu.utils.jax_compat import shard_map

    size, block = 8 * 256, 64
    parts = _per_device_partials(8, size)

    def body(x):
        red, err = cq.int8_all_reduce(
            x[0], ("data",), 8, block, want_error=True
        )
        return red[None], err[None]

    fn = shard_map(
        body, mesh=mesh8, in_specs=(P("data"),),
        out_specs=(P("data"), P("data")), check_vma=False,
    )
    red, err = jax.jit(fn)(
        jax.device_put(parts, NamedSharding(mesh8, P("data")))
    )
    red, err = np.asarray(red), np.asarray(err)
    exact = parts.sum(axis=0)
    # Every device holds the same reduced vector...
    assert np.allclose(red, red[0][None], atol=0)
    # ...close to the exact sum (two quantization passes of error).
    scale = np.abs(parts).max() / 127.0
    assert np.abs(red[0] - exact).max() <= (8 + 1) * scale
    # EF invariant: the per-device errors SUM to exactly the total
    # compression error, so reinjection telescopes.
    np.testing.assert_allclose(
        err.sum(axis=0), exact - red[0], rtol=1e-5, atol=1e-5
    )


# -- resolution / gating -----------------------------------------------------

def test_resolution_downgrades_loudly(mesh8):
    module = BoringModel(in_dim=64, out_dim=8)
    cfg = {"mode": "int8", "dcn_only": False}
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert gsync.maybe_build_grad_sync(
            module, mesh8, cfg, mode="shard_map") is None
        assert gsync.maybe_build_grad_sync(
            module, mesh8, cfg, mode="gspmd", zero_stage=3) is None
    assert len(w) == 2 and all("full width" in str(x.message) for x in w)
    # dcn_only=True on a single-process mesh: ICI-only, stays full.
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert gsync.maybe_build_grad_sync(
            module, mesh8, "int8", mode="gspmd") is None
    assert any("ICI-only" in str(x.message) for x in w)
    # full mode: silently inactive (the default path).
    assert gsync.maybe_build_grad_sync(module, mesh8, "full") is None
    assert gsync.maybe_build_grad_sync(module, mesh8, None) is None


def test_resolution_rejects_model_parallel_mesh(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec({"data": 4, "tensor": 2}))
    module = BoringModel(in_dim=64, out_dim=8)
    with pytest.warns(UserWarning, match="model-parallel"):
        assert gsync.maybe_build_grad_sync(
            module, mesh, {"mode": "int8", "dcn_only": False}) is None


def test_bad_mode_fails_fast():
    with pytest.raises(ValueError, match="grad_comm mode"):
        gsync.GradCommConfig(mode="int4")
    with pytest.raises(ValueError, match="grad_comm mode"):
        LocalStrategy(grad_comm="int4")


def test_env_bus_sets_default(monkeypatch):
    monkeypatch.setenv("RLT_GRAD_COMM", "int8_ef")
    monkeypatch.setenv("RLT_GRAD_BUCKET_MB", "2")
    monkeypatch.setenv("RLT_GRAD_DCN_ONLY", "0")
    cfg = gsync.GradCommConfig.coerce(None)
    assert cfg.mode == "int8_ef"
    assert cfg.bucket_bytes == 2 * 2**20
    assert cfg.dcn_only is False


# -- fit-level parity on the 8-device CPU mesh -------------------------------

def _fit(tmp_path, grad_comm, max_epochs=2, in_dim=256, out_dim=128):
    x = np.random.default_rng(7).standard_normal(
        (64, in_dim)).astype(np.float32)
    module = BoringModel(in_dim=in_dim, out_dim=out_dim, lr=0.05)
    trainer = Trainer(
        strategy=LocalStrategy(
            mesh_axes={"data": 8}, grad_comm=grad_comm
        ),
        max_epochs=max_epochs,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        log_every_n_steps=1,
    )
    trainer.fit(module, FixedDataModule(x, batch_size=16))
    return trainer


def test_fit_loss_parity_int8_and_ef_vs_full(tmp_path):
    t_full = _fit(tmp_path / "full", "full")
    t_ef = _fit(
        tmp_path / "ef", {"mode": "int8_ef", "dcn_only": False}
    )
    # Small bucket bound forces the multi-bucket path: the 256x128
    # weight exceeds it (own bucket), the bias trails in a ragged one.
    t_i8 = _fit(
        tmp_path / "i8",
        {"mode": "int8", "dcn_only": False, "bucket_bytes": 65536},
    )
    ref = t_full.callback_metrics["train_loss"]
    # Error feedback: within 1% relative of full-width final loss.
    assert abs(t_ef.callback_metrics["train_loss"] - ref) <= 0.01 * abs(ref)
    # Plain int8: bounded divergence (no residual, bias may accumulate).
    assert abs(t_i8.callback_metrics["train_loss"] - ref) <= 0.10 * abs(ref)

    # Wire accounting is a recorded artifact on both surfaces:
    for t, mode in ((t_ef, "int8_ef"), (t_i8, "int8")):
        assert t.comm_stats["grad_sync_mode"] == mode
        assert t.comm_stats["grad_sync_compression_ratio"] >= 3.5
        assert (
            t.callback_metrics["grad_sync_bytes"]
            == t.comm_stats["grad_sync_bytes"]
        )
        assert t.comm_stats["grad_sync_bytes"] * 3.5 <= (
            t.comm_stats["grad_sync_bytes_full_width"]
        )
    assert t_full.comm_stats == {"grad_sync_mode": "full"}
    assert "grad_sync_bytes" not in t_full.callback_metrics
    # The bounded-bucket run really synced in two collective groups.
    assert t_i8.comm_stats["grad_sync_buckets"] == 2
    assert t_ef.comm_stats["grad_sync_buckets"] == 1

    # The EF residual rides the DEVICE-side train state only: gathered
    # payloads (checkpoints, the rank-0→driver stream) exclude it — it
    # is n_devices × params of f32, and resumes re-attach zeros.
    assert t_ef.state.grad_residual is None
    assert t_full.state.grad_residual is None


def test_ef_checkpoint_roundtrip_and_mode_switch(tmp_path):
    x = np.random.default_rng(3).standard_normal((32, 64)).astype(
        np.float32)
    dm = FixedDataModule(x, batch_size=16)
    ef = {"mode": "int8_ef", "dcn_only": False}

    def make_trainer(grad_comm, resume=None):
        return Trainer(
            strategy=LocalStrategy(
                mesh_axes={"data": 8}, grad_comm=grad_comm
            ),
            max_epochs=2 if resume else 1,
            default_root_dir=str(tmp_path),
            enable_checkpointing=False,
            resume_from_checkpoint=resume,
        )

    t1 = make_trainer(ef)
    t1.fit(BoringModel(in_dim=64, out_dim=32, lr=0.05), dm)
    ckpt = str(tmp_path / "ef.ckpt")
    t1.save_checkpoint(ckpt)
    assert t1.comm_stats["grad_sync_mode"] == "int8_ef"

    # EF → EF resume: the checkpoint carries no residual (gathers
    # exclude it); a fresh zero one is attached and training proceeds.
    t2 = make_trainer(ef, resume=ckpt)
    t2.fit(BoringModel(in_dim=64, out_dim=32, lr=0.05), dm)
    assert t2.comm_stats["grad_sync_mode"] == "int8_ef"
    assert t2.global_step > t1.global_step

    # EF → full resume: no residual expected anywhere, loads cleanly.
    t3 = make_trainer("full", resume=ckpt)
    t3.fit(BoringModel(in_dim=64, out_dim=32, lr=0.05), dm)
    assert t3.comm_stats == {"grad_sync_mode": "full"}

    # full → EF resume: a fresh zero residual is attached on-device.
    plain = str(tmp_path / "plain.ckpt")
    t3.save_checkpoint(plain)
    t4 = make_trainer(ef, resume=plain)
    t4.fit(BoringModel(in_dim=64, out_dim=32, lr=0.05), dm)
    assert t4.comm_stats["grad_sync_mode"] == "int8_ef"
