"""Pipeline parallelism: forward + gradient parity vs the plain scan.

Strategy ≙ the repo's standard grad-parity verification (SURVEY §6): the
unpipelined ``lax.scan`` over the full layer stack is the reference; the
GPipe pipeline over a ``pipe`` mesh axis must match it bitwise-close in
f32 for every (stages, microbatches) split, including gradients through
the ``ppermute`` handoffs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from ray_lightning_tpu.parallel.pipeline import pipeline_apply

L, B, Dm = 8, 16, 32


def _params(key):
    kw, kb = jax.random.split(key)
    return {
        "w": jax.random.normal(kw, (L, Dm, Dm), jnp.float32) * 0.3,
        "b": jax.random.normal(kb, (L, Dm), jnp.float32) * 0.1,
    }


def _stage(params, x):
    """One stage's layer stack (works for any leading layer count)."""
    def body(x, p):
        return jnp.tanh(x @ p["w"] + p["b"]), None

    x, _ = jax.lax.scan(body, x, params)
    return x


def _reference(params, x):
    return _stage(params, x)  # full stack = one "stage"


@pytest.fixture(scope="module")
def data():
    key = jax.random.PRNGKey(0)
    return _params(key), jax.random.normal(
        jax.random.split(key)[1], (B, Dm), jnp.float32
    )


@pytest.mark.parametrize("n_stages,micro", [(2, 2), (4, 4), (4, 8), (8, 16)])
def test_pipeline_forward_parity(data, n_stages, micro):
    params, x = data
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pipe",))
    ref = _reference(params, x)
    out = pipeline_apply(
        _stage, params, x, mesh, num_microbatches=micro
    )
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_pipeline_grad_parity(data):
    """Gradients flow back through the reversed pipeline (transpose of
    ppermute) and match the plain stack for params AND inputs."""
    params, x = data
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pipe",))

    def loss_pp(params, x):
        return (pipeline_apply(
            _stage, params, x, mesh, num_microbatches=8) ** 2).sum()

    def loss_ref(params, x):
        return (_reference(params, x) ** 2).sum()

    gp = jax.grad(loss_pp, argnums=(0, 1))(params, x)
    gr = jax.grad(loss_ref, argnums=(0, 1))(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_pipeline_under_jit(data):
    params, x = data
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pipe",))
    fn = jax.jit(lambda p, x: pipeline_apply(
        _stage, p, x, mesh, num_microbatches=4))
    np.testing.assert_allclose(
        np.asarray(fn(params, x)), np.asarray(_reference(params, x)),
        rtol=1e-6, atol=1e-6,
    )


def test_pipeline_rejects_ragged_microbatches(data):
    params, x = data
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pipe",))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(_stage, params, x, mesh, num_microbatches=3)


def test_pipeline_fewer_microbatches_than_stages(data):
    """M < P: the drain dominates (bubble (P-1)/(M+P-1)) but the math
    must stay exact — the MPMD parity tests lean on this edge."""
    params, x = data
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pipe",))
    out = pipeline_apply(_stage, params, x, mesh, num_microbatches=2)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_reference(params, x)),
        rtol=1e-6, atol=1e-6,
    )


def test_pipeline_single_stage_degenerate(data):
    """P=1: the pipeline collapses to the plain scan (plus the
    micro-batch loop).  Forward-only here for tier-1 budget; gradients
    through the degenerate pipe ride the MPMD P=1 parity fit
    (tests/test_mpmd.py)."""
    params, x = data
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("pipe",))
    out = pipeline_apply(_stage, params, x, mesh, num_microbatches=4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_reference(params, x)),
        rtol=1e-6, atol=1e-6,
    )


def test_pipeline_rejects_nondivisible_layer_count(data):
    """8 layers over 3 stages: the SPMD flavor shards ONE stacked leaf
    and must refuse (the MPMD plane balances the remainder instead —
    parallel/pipeline.py::layer_splits is the shared split math)."""
    params, x = data
    mesh = Mesh(np.asarray(jax.devices()[:3]), ("pipe",))
    with pytest.raises(ValueError, match="pipeline stages"):
        pipeline_apply(_stage, params, x, mesh, num_microbatches=4)


def test_pipeline_gpt_blocks():
    """The flagship model's stacked block tree pipelines as-is: run the
    GPT-tiny transformer trunk (dense blocks, XLA attention) through a
    4-stage pipeline and match the plain scan forward."""
    from ray_lightning_tpu.models.gpt import (
        GPT, GPTConfig, make_block_stage,
    )

    cfg = GPTConfig(vocab_size=128, n_layer=4, n_head=4, d_model=64,
                    seq_len=32, warmup_steps=1)
    model = GPT(cfg, attn_impl="xla")
    params = model.init_params(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
    x0 = (params["wte"][tokens] + params["wpe"][:32]).astype(jnp.float32)

    block_stage = make_block_stage(cfg)

    ref = block_stage(params["blocks"], x0)
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pipe",))
    out = pipeline_apply(
        block_stage, params["blocks"], x0, mesh, num_microbatches=4
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
