"""LoRA fine-tuning for the GPT family (net-new): adapters on the
attention projections, frozen base, merge-for-inference.

The design guarantees tested here: zero-initialized B makes step 0
bit-identical to the base model; only lora_* params move under training
(the base carries no optimizer moments); merged weights reproduce the
adapter-form logits; the sharded mesh is a numeric no-op; and the
HF-import → add adapters → warm-start flow works end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import (
    GPT,
    GPTConfig,
    SyntheticLMDataModule,
    add_lora_adapters,
    merge_lora,
)
from ray_lightning_tpu.parallel.strategies import LocalStrategy


def lora_cfg(**kw):
    return GPTConfig(vocab_size=512, n_layer=2, n_head=4, d_model=128,
                     seq_len=128, warmup_steps=0, lr=1e-2,
                     lora_rank=4, **kw)


def test_lora_starts_identical_to_base():
    """B = 0 at init: the adapted forward equals the base forward on the
    same base weights."""
    cfg = lora_cfg()
    base_cfg = GPTConfig(**{**cfg.__dict__, "lora_rank": 0})
    lora_model, base_model = GPT(cfg), GPT(base_cfg)
    lp = lora_model.init_params(jax.random.PRNGKey(0))
    bp = base_model.init_params(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32)
    out_l = np.asarray(jax.jit(lora_model.forward)(lp, tokens))
    out_b = np.asarray(jax.jit(base_model.forward)(bp, tokens))
    np.testing.assert_array_equal(out_l, out_b)


def test_lora_trains_only_adapters():
    cfg = lora_cfg()
    model = GPT(cfg)
    trainer = Trainer(strategy=LocalStrategy(), max_epochs=1,
                      limit_train_batches=3, limit_val_batches=0,
                      enable_checkpointing=False)
    p0 = jax.device_get(model.init_params(jax.random.PRNGKey(0)))
    model.initial_params = p0
    trainer.fit(model, SyntheticLMDataModule(cfg, batch_size=8,
                                             num_batches=3))
    p1 = jax.device_get(trainer.params)
    for name in ("qkv_w", "proj_w", "mlp_in_w", "ln1_g", "qkv_b"):
        np.testing.assert_array_equal(
            p1["blocks"][name], p0["blocks"][name], err_msg=name)
    np.testing.assert_array_equal(p1["wte"], p0["wte"])
    moved = sum(
        float(np.abs(p1["blocks"][k] - p0["blocks"][k]).max())
        for k in ("lora_qkv_a", "lora_qkv_b", "lora_proj_a",
                  "lora_proj_b")
    )
    assert moved > 0, "no adapter moved"


def test_lora_base_has_no_optimizer_moments():
    """The frozen base must not allocate Adam moments — the LoRA memory
    contract."""
    import optax

    cfg = lora_cfg()
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    state = model.configure_optimizers().init(params)
    adam = next(
        s for s in jax.tree_util.tree_leaves(
            state, is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState)
        ) if isinstance(s, optax.ScaleByAdamState)
    )
    mu_leaves = [
        x for x in jax.tree_util.tree_leaves(adam.mu)
        if hasattr(x, "shape") and np.prod(x.shape or (1,)) > 0
    ]
    n_lora = 4 * cfg.n_layer  # four adapter tensors, stacked per layer
    total_adapter_elems = cfg.n_layer * (
        cfg.d_model * cfg.lora_rank * 2
        + cfg.lora_rank * 3 * cfg.d_model + cfg.lora_rank * cfg.d_model
    )
    assert sum(int(np.prod(x.shape)) for x in mu_leaves) == \
        total_adapter_elems, "moments exist for frozen base params"


def test_merge_lora_reproduces_adapter_logits():
    cfg = lora_cfg()
    model = GPT(cfg)
    params = jax.device_get(model.init_params(jax.random.PRNGKey(0)))
    # Give the adapters nonzero B so the merge actually does something.
    params["blocks"]["lora_qkv_b"] = (
        np.random.default_rng(1).standard_normal(
            params["blocks"]["lora_qkv_b"].shape) * 0.02
    ).astype(np.float32)
    params["blocks"]["lora_proj_b"] = (
        np.random.default_rng(2).standard_normal(
            params["blocks"]["lora_proj_b"].shape) * 0.02
    ).astype(np.float32)

    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32)
    out_adapter = np.asarray(jax.jit(model.forward)(params, tokens))

    merged = merge_lora(params, cfg)
    assert not any(k.startswith("lora_") for k in merged["blocks"])
    base_model = GPT(GPTConfig(**{**cfg.__dict__, "lora_rank": 0}))
    out_merged = np.asarray(jax.jit(base_model.forward)(merged, tokens))
    np.testing.assert_allclose(out_merged, out_adapter, rtol=2e-5,
                               atol=2e-5)


def test_lora_sharded_mesh_parity(tmp_path):
    """TP×FSDP sharding of a LoRA fit is numerically a no-op."""

    def run(strategy):
        cfg = lora_cfg()
        tr = Trainer(strategy=strategy, max_epochs=1,
                     limit_train_batches=2, limit_val_batches=1,
                     enable_checkpointing=False,
                     default_root_dir=str(tmp_path))
        tr.fit(GPT(cfg), SyntheticLMDataModule(cfg, batch_size=8,
                                               num_batches=2))
        return tr.callback_metrics["train_loss"]

    base = run(LocalStrategy())
    sharded = run(LocalStrategy(
        mesh_axes={"data": 2, "fsdp": 2, "tensor": 2}, zero_stage=3))
    assert base == pytest.approx(sharded, rel=1e-5)


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_hf_import_lora_flow():
    """The migration recipe: import HF GPT-2 → add adapters →
    warm-start a LoRA fit → the base stays at the imported values."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    config = transformers.GPT2Config(
        vocab_size=97, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(config).eval()

    from ray_lightning_tpu.utils import import_gpt2

    cfg, params = import_gpt2(hf)
    import dataclasses

    cfg = dataclasses.replace(cfg, lora_rank=4, lr=1e-2, warmup_steps=0)
    params = add_lora_adapters(params, cfg, jax.random.PRNGKey(0))

    model = GPT(cfg, attn_impl="xla")
    model.initial_params = params
    trainer = Trainer(strategy=LocalStrategy(), max_epochs=1,
                      limit_train_batches=2, limit_val_batches=0,
                      enable_checkpointing=False)
    trainer.fit(model, SyntheticLMDataModule(cfg, batch_size=8,
                                             num_batches=2))
    p1 = jax.device_get(trainer.params)
    np.testing.assert_array_equal(p1["blocks"]["qkv_w"],
                                  params["blocks"]["qkv_w"])
    assert np.abs(p1["blocks"]["lora_qkv_b"]).max() > 0


def test_lora_rejects_moe():
    with pytest.raises(ValueError, match="lora"):
        GPT(GPTConfig.tiny_moe(n_experts=2, lora_rank=4))


def test_generate_rejects_unmerged_lora():
    from ray_lightning_tpu.models.generate import generate

    cfg = lora_cfg()
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="merge_lora"):
        generate(model, params, jnp.ones((1, 4), jnp.int32),
                 max_new_tokens=2)
    # Merged params decode fine.
    merged = merge_lora(jax.device_get(params), cfg)
    out = generate(GPT(GPTConfig(**{**cfg.__dict__, "lora_rank": 0})),
                   merged, jnp.ones((1, 4), jnp.int32), max_new_tokens=2)
    assert out.shape == (1, 6)


def test_block_stage_rejects_lora():
    from ray_lightning_tpu.models.gpt import make_block_stage

    with pytest.raises(ValueError, match="merge_lora"):
        make_block_stage(lora_cfg())


def test_clip_sees_adapter_norm_only():
    """The global-norm clip must scale by the ADAPTER grad norm: with
    tiny adapter grads and huge (frozen) base grads, adapter updates
    must pass through unclipped."""
    import optax

    cfg = lora_cfg()
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    tx = model.configure_optimizers()
    state = tx.init(params)
    # Forged grads: base grads enormous, adapter grads tiny.
    grads = jax.tree_util.tree_map_with_path(
        lambda path, leaf: jnp.full_like(
            leaf,
            1e-4 if str(getattr(path[-1], "key", "")).startswith("lora_")
            else 1e6,
        ),
        params,
    )
    updates, _ = tx.update(grads, state, params)
    lora_up = updates["blocks"]["lora_qkv_a"]
    base_up = updates["blocks"]["qkv_w"]
    assert float(jnp.abs(base_up).max()) == 0.0  # frozen
    # Unclipped tiny grads produce a full-size first adamw step
    # (~lr * sign); if the clip had seen the 1e6 base norm, the adapter
    # update would be ~0.
    assert float(jnp.abs(lora_up).max()) > 1e-3


def test_prefill_and_decode_reject_unmerged_lora():
    from ray_lightning_tpu.models.generate import (
        decode_step, init_kv_cache, prefill,
    )

    cfg = lora_cfg()
    params = GPT(cfg).init_params(jax.random.PRNGKey(0))
    cache = init_kv_cache(cfg, batch=1, total_len=8)
    with pytest.raises(ValueError, match="merge_lora"):
        prefill(cfg, params, cache, jnp.ones((1, 4), jnp.int32))
    with pytest.raises(ValueError, match="merge_lora"):
        decode_step(cfg, params, cache, jnp.ones((1,), jnp.int32),
                    jnp.asarray(4))


def test_add_lora_adapters_refuses_overwrite():
    cfg = lora_cfg()
    params = jax.device_get(GPT(cfg).init_params(jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="already contain"):
        add_lora_adapters(params, cfg, jax.random.PRNGKey(1))
