"""GPT flagship-model tests: training moves weights, parallel flavors agree.

≙ the reference test taxonomy (SURVEY §4): ``train_test`` weights-changed,
plus the TPU-specific addition — loss parity between the plain data mesh
and the TP/FSDP/ZeRO-sharded mesh (sharding must be a no-op numerically).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models.gpt import GPT, GPTConfig, SyntheticLMDataModule
from ray_lightning_tpu.parallel.strategies import LocalStrategy


def tiny():
    return GPTConfig.tiny()


def make_trainer(**kw):
    kw.setdefault("max_epochs", 1)
    kw.setdefault("limit_train_batches", 2)
    kw.setdefault("limit_val_batches", 1)
    kw.setdefault("enable_checkpointing", False)
    return Trainer(**kw)


def fit_metrics(strategy, attn_impl="xla", **model_kw):
    cfg = tiny()
    tr = make_trainer(strategy=strategy)
    tr.fit(GPT(cfg, attn_impl=attn_impl, **model_kw),
           SyntheticLMDataModule(cfg, batch_size=8, num_batches=2))
    return tr


def test_gpt_trains_and_moves_weights():
    tr = fit_metrics(LocalStrategy())
    assert np.isfinite(tr.callback_metrics["train_loss"])
    # Loss near ln(vocab) for random tokens — the model is wired correctly.
    assert 4.0 < tr.callback_metrics["train_loss"] < 8.0
    assert tr.state is not None


def test_gpt_tp_fsdp_parity_with_data_mesh():
    """ZeRO-3 + tensor parallel must be numerically identical to plain DP."""
    base = fit_metrics(LocalStrategy())
    sharded = fit_metrics(
        LocalStrategy(mesh_axes={"data": 2, "fsdp": 2, "tensor": 2},
                      zero_stage=3)
    )
    assert base.callback_metrics["train_loss"] == pytest.approx(
        sharded.callback_metrics["train_loss"], rel=1e-5
    )
    assert base.callback_metrics["val_loss"] == pytest.approx(
        sharded.callback_metrics["val_loss"], rel=1e-5
    )


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_gpt_ring_attention_training():
    """Sequence-parallel (ring attention) flavor trains and agrees."""
    base = fit_metrics(LocalStrategy())
    ring = fit_metrics(
        LocalStrategy(mesh_axes={"data": 2, "sp": 4}),
        attn_impl="ring",
    )
    assert base.callback_metrics["train_loss"] == pytest.approx(
        ring.callback_metrics["train_loss"], rel=1e-4
    )


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_gpt_zigzag_ring_training():
    """Zig-zag (causally balanced) sequence parallelism trains and agrees
    with the plain local run — the in/out permutations cancel."""
    base = fit_metrics(LocalStrategy())
    ring = fit_metrics(
        LocalStrategy(mesh_axes={"data": 2, "sp": 4}),
        attn_impl="ring", ring_layout="zigzag",
    )
    assert base.callback_metrics["train_loss"] == pytest.approx(
        ring.callback_metrics["train_loss"], rel=1e-4
    )


def test_param_partition_specs_cover_params():
    model = GPT(tiny())
    params = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    specs = model.param_partition_specs()
    p_leaves = jax.tree_util.tree_leaves(params)
    s_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    assert len(p_leaves) == len(s_leaves)


def test_state_shardings_follow_tp_specs():
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    from ray_lightning_tpu.core.module import TrainState
    from ray_lightning_tpu.parallel.sharding import (
        state_shardings_for_module,
    )

    model = GPT(tiny())
    mesh = Mesh(
        mesh_utils.create_device_mesh((2, 2, 2)),
        ("data", "fsdp", "tensor"),
    )
    tx = model.configure_optimizers()

    def make(rng):
        return TrainState.create(model.init_params(rng), tx)

    abstract = jax.eval_shape(make, jax.random.PRNGKey(0))
    sh = state_shardings_for_module(model, abstract, mesh, zero_stage=1)
    # TP spec honored on params:
    assert sh.params["blocks"]["qkv_w"].spec == P(None, None, "tensor")
    # Optimizer moments inherit the param TP spec + the fsdp zero axis:
    mu_qkv = jax.tree_util.tree_leaves_with_path(sh.opt_state)
    hits = [
        s for path, s in mu_qkv
        if any(getattr(k, "key", None) == "qkv_w" for k in path)
    ]
    assert hits, "no optimizer-moment sharding found for qkv_w"
    for s in hits:
        assert "tensor" in jax.tree_util.tree_leaves(tuple(s.spec)) or (
            s.spec and "tensor" in str(s.spec)
        )
        assert "fsdp" in str(s.spec)


def test_adamw_momentum_stored_bf16():
    """The default optimizer keeps the first moment in bf16 (HBM-bound
    update reads/writes half the bytes for that state) while the second
    moment stays f32; mu_dtype='float32' opts out."""
    from dataclasses import replace

    import optax

    model = GPT(tiny())
    params = model.init_params(jax.random.PRNGKey(0))
    state = model.configure_optimizers().init(params)
    adam = next(
        s for s in jax.tree_util.tree_leaves(
            state, is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState)
        ) if isinstance(s, optax.ScaleByAdamState)
    )
    assert all(
        leaf.dtype == jnp.bfloat16 for leaf in jax.tree.leaves(adam.mu)
    )
    assert all(
        leaf.dtype == jnp.float32 for leaf in jax.tree.leaves(adam.nu)
    )

    f32_model = GPT(replace(tiny(), mu_dtype="float32"))
    f32_state = f32_model.configure_optimizers().init(params)
    adam32 = next(
        s for s in jax.tree_util.tree_leaves(
            f32_state, is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState)
        ) if isinstance(s, optax.ScaleByAdamState)
    )
    assert all(
        leaf.dtype == jnp.float32 for leaf in jax.tree.leaves(adam32.mu)
    )


def test_gpt_remat_matches_no_remat():
    """jax.checkpoint is numerically inert: remat only trades FLOPs for
    activation memory."""
    base = fit_metrics(LocalStrategy())
    cfg = tiny()
    tr = make_trainer(strategy=LocalStrategy())
    tr.fit(GPT(cfg, remat=True),
           SyntheticLMDataModule(cfg, batch_size=8, num_batches=2))
    assert base.callback_metrics["train_loss"] == pytest.approx(
        tr.callback_metrics["train_loss"], rel=1e-6
    )


def test_kernel_ln_under_remat_matches_xla_ln(monkeypatch):
    """Fused-LN custom_vjp composes with jax.checkpoint: a rematerialized
    training step with the kernel LN forced on (interpret mode — the
    single-TPU-chip configuration) matches the XLA-LN step."""
    import ray_lightning_tpu.models.gpt as gptmod

    cfg = tiny()
    m = GPT(cfg, remat=True)
    params = m.init_params(jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, cfg.seq_len + 1), 0, cfg.vocab_size)

    def loss(params):
        return m.training_step(params, {"tokens": tokens}, None)[0]

    l_base, g_base = jax.value_and_grad(loss)(params)

    # Spy on the kernel entry so the test fails loudly if the gate ever
    # silently falls back to XLA (which would compare XLA against XLA).
    from ray_lightning_tpu.ops import layer_norm as lnmod

    kernel_calls = []
    real_fused = lnmod._fused_ln

    def spying_fused(x, g, b):
        kernel_calls.append(x.shape)
        return real_fused(x, g, b)

    monkeypatch.setattr(lnmod, "_fused_ln", spying_fused)
    orig = gptmod._layer_norm
    monkeypatch.setattr(
        gptmod, "_layer_norm",
        lambda x, g, b, up=False: orig(x, g, b, use_pallas=True))
    l_k, g_k = jax.value_and_grad(loss)(params)
    assert kernel_calls, "fused LN kernel path was never taken"
    assert float(l_base) == pytest.approx(float(l_k), abs=1e-5)
    for a, b_, in zip(jax.tree.leaves(g_base), jax.tree.leaves(g_k)):
        assert float(jnp.abs(a - b_).max()) < 1e-4


def test_gpt_shard_map_flavor_trains():
    """The Horovod-duality (shard_map) flavor must trace GPT cleanly —
    the residual sharding anchor is a gspmd-only concept and must no-op
    inside a Manual-axes body."""
    from ray_lightning_tpu.parallel.strategies import HorovodRayStrategy

    base = fit_metrics(LocalStrategy())
    cfg = tiny()
    tr = make_trainer(strategy=HorovodRayStrategy(num_workers=1))
    tr.fit(GPT(cfg), SyntheticLMDataModule(cfg, batch_size=8, num_batches=2))
    assert base.callback_metrics["train_loss"] == pytest.approx(
        tr.callback_metrics["train_loss"], rel=1e-5
    )


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
@pytest.mark.parametrize("policy", ["dots+flash", "dots+flash-out", "dots"])
def test_remat_policy_variants_same_numerics(policy):
    """remat_policy only changes WHAT the backward saves, never the
    math: loss and grads must match the no-remat baseline.

    attn_impl='flash' explicitly (interpret-mode Pallas on the CPU
    mesh): under 'auto' the CPU path takes the XLA einsum, no flash_*
    checkpoint_name residuals exist, and all three policies would
    compile the same program — the arms must differ to be tested.
    head_dim 64 to satisfy the kernel's lane constraint."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    cfg = GPTConfig(vocab_size=512, n_layer=2, n_head=4, d_model=256,
                    seq_len=128, warmup_steps=2)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, cfg.seq_len + 1)),
        jnp.int32)

    def loss_fn(model):
        params = model.init_params(jax.random.PRNGKey(0))

        def loss(p):
            l, _ = model.training_step(p, {"tokens": tokens}, jax.random.PRNGKey(1))
            return l

        val, grads = jax.jit(jax.value_and_grad(loss))(params)
        return float(val), grads

    base_val, base_grads = loss_fn(GPT(cfg, attn_impl="flash", remat=False))
    val, grads = loss_fn(
        GPT(cfg, attn_impl="flash", remat=True, remat_policy=policy))
    assert val == pytest.approx(base_val, rel=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(base_grads),
                    jax.tree_util.tree_leaves(grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_remat_policy_rejects_unknown():
    with pytest.raises(ValueError, match="remat_policy"):
        GPT(GPTConfig.tiny(), remat_policy="everything")


@pytest.mark.slow  # same budget class as the other remat-variant fits
def test_remat_bf16_resid_close_numerics():
    """The "bf16-resid" arm stores the layer-scan carry in bf16 — by
    design a ROUNDING of the residual stream at block boundaries (the
    same rounding precision='bf16' applies everywhere), so loss/grads
    track the exact arms within bf16 tolerance rather than matching
    bitwise.  Flash attention explicitly, like the exact-parity test:
    the named flash residuals must exist for the save-set to differ."""
    import jax
    import numpy as np

    import jax.numpy as jnp

    cfg = GPTConfig(vocab_size=512, n_layer=2, n_head=4, d_model=256,
                    seq_len=128, warmup_steps=2)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, cfg.seq_len + 1)),
        jnp.int32)

    def loss_fn(model):
        params = model.init_params(jax.random.PRNGKey(0))

        def loss(p):
            l, _ = model.training_step(
                p, {"tokens": tokens}, jax.random.PRNGKey(1))
            return l

        val, grads = jax.jit(jax.value_and_grad(loss))(params)
        return float(val), grads

    base_val, base_grads = loss_fn(
        GPT(cfg, attn_impl="flash", remat=True,
            remat_policy="dots+flash-out"))
    val, grads = loss_fn(
        GPT(cfg, attn_impl="flash", remat=True,
            remat_policy="bf16-resid"))
    assert val == pytest.approx(base_val, rel=1e-3)
    assert np.isfinite(val)
    for a, b in zip(jax.tree_util.tree_leaves(base_grads),
                    jax.tree_util.tree_leaves(grads)):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        assert np.isfinite(b).all()
        # bf16 rounding of the residual stream: absolute tolerance at
        # the bf16 ulp scale of the gradient magnitudes involved.
        np.testing.assert_allclose(a, b, rtol=0.05, atol=2e-3)


def test_remat_bf16_resid_without_remat_is_exact():
    """Without remat nothing is saved per layer, so the bf16-resid
    carry rounding must NOT engage — the forward equals the default
    policy's bitwise."""
    import jax
    import numpy as np

    import jax.numpy as jnp

    cfg = GPTConfig.tiny()
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32)
    params = GPT(cfg).init_params(jax.random.PRNGKey(0))
    ref = GPT(cfg, remat=False).forward(params, tokens)
    got = GPT(cfg, remat=False, remat_policy="bf16-resid").forward(
        params, tokens)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_residual_save_bytes_accounting():
    """The analytic model behind the bench ``residual_policy`` block:
    arm ordering must match the design — dots+flash (double-save) >
    dots+flash-out > bf16-resid(f32 run) > dots — and the bf16 carry
    must save exactly half the carry bytes of an f32 run."""
    from ray_lightning_tpu.models.gpt import residual_save_bytes

    cfg = GPTConfig.tiny()
    B = 16
    flash = residual_save_bytes(cfg, B, "dots+flash", "f32")
    flash_out = residual_save_bytes(cfg, B, "dots+flash-out", "f32")
    bf16r = residual_save_bytes(cfg, B, "bf16-resid", "f32")
    dots = residual_save_bytes(cfg, B, "dots", "f32")
    assert flash > flash_out > bf16r > dots
    carry_f32 = cfg.n_layer * B * cfg.seq_len * cfg.d_model * 4
    assert flash_out - bf16r == carry_f32 // 2
    # On a bf16-precision run the carry is already 2 bytes — the arm
    # changes nothing.
    assert (residual_save_bytes(cfg, B, "bf16-resid", "bf16")
            == residual_save_bytes(cfg, B, "dots+flash-out", "bf16"))


def test_decay_mask_exempts_norms_biases_everywhere():
    """The weight-decay mask must exempt LN params and biases at every
    nesting level — stacked blocks and MoE tensors carry extra leading
    dims that break any raw ndim rule."""
    from ray_lightning_tpu.models.optim import decay_mask
    from ray_lightning_tpu.models import ViT, ViTConfig

    p = GPT(GPTConfig.tiny_moe()).init_params(jax.random.PRNGKey(0))
    m = decay_mask(p)
    assert m["wte"] is True  # tied to the LM head — a matrix
    assert m["wpe"] is False  # positional table — exempt in both families
    assert m["ln_f_g"] is False and m["ln_f_b"] is False
    b = m["blocks"]
    assert b["qkv_w"] and b["moe_in_w"] and b["moe_out_w"] and b["gate_w"]
    assert not (b["qkv_b"] or b["moe_in_b"] or b["moe_out_b"]
                or b["ln1_g"] or b["ln2_b"])

    pv = ViT(ViTConfig.tiny()).init_params(jax.random.PRNGKey(0))
    mv = decay_mask(pv)
    assert mv["patch_w"] and mv["head_w"] and mv["blocks"]["mlp_in_w"]
    assert not (mv["pos"] or mv["patch_b"] or mv["head_b"]
                or mv["blocks"]["mlp_in_b"] or mv["blocks"]["ln1_g"])


class TestByteLMDataModule:
    def _write_text(self, tmp_path, n=4096):
        p = tmp_path / "corpus.txt"
        text = ("the quick brown fox jumps over the lazy dog. " * 200)
        p.write_bytes(text.encode()[:n])
        return str(p)

    def test_windows_shape_and_bos(self, tmp_path):
        from ray_lightning_tpu.models import ByteLMDataModule

        dm = ByteLMDataModule(self._write_text(tmp_path), seq_len=64,
                              batch_size=4)
        dm.set_shard(0, 1)
        dm.setup("fit")
        batch = next(iter(dm.train_dataloader()))
        assert batch["tokens"].shape == (4, 65)
        assert batch["tokens"].dtype == np.int32
        assert (batch["tokens"][:, 0] == 256).all()  # BOS
        assert batch["tokens"].max() < ByteLMDataModule.vocab_size

    @pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
    def test_gpt_trains_on_real_text(self, tmp_path):
        """End-to-end: byte-level GPT on real text, loss clearly below
        uniform (ln 384 ≈ 5.95) after one epoch on repetitive text."""
        from ray_lightning_tpu.models import ByteLMDataModule

        dm = ByteLMDataModule(self._write_text(tmp_path, n=8192),
                              seq_len=64, batch_size=8)
        cfg = GPTConfig(vocab_size=ByteLMDataModule.vocab_size,
                        n_layer=2, n_head=4, d_model=128, seq_len=64,
                        warmup_steps=2, lr=3e-3)
        tr = Trainer(strategy=LocalStrategy(), max_epochs=2,
                     enable_checkpointing=False,
                     default_root_dir=str(tmp_path))
        tr.fit(GPT(cfg), dm)
        assert tr.callback_metrics["train_loss"] < 4.0

    def test_too_short_file_rejected(self, tmp_path):
        from ray_lightning_tpu.models import ByteLMDataModule

        p = tmp_path / "tiny.txt"
        p.write_bytes(b"short")
        dm = ByteLMDataModule(str(p), seq_len=64)
        with pytest.raises(ValueError, match="too short"):
            dm.setup("fit")

    def test_decode_bytes_roundtrip(self):
        from ray_lightning_tpu.models import decode_bytes

        toks = [256] + [ord(c) for c in "hello"] + [300]
        assert decode_bytes(np.asarray(toks)) == "hello"


def test_bytelm_requires_full_batches(tmp_path):
    """A file passing a naive 'two windows' check but yielding ZERO full
    train batches must be rejected, not silently train nothing."""
    from ray_lightning_tpu.models import ByteLMDataModule

    p = tmp_path / "small.txt"
    p.write_bytes(b"x" * 600)  # 9 windows at seq_len=64 < 8 train + 8 val
    dm = ByteLMDataModule(str(p), seq_len=64, batch_size=8)
    with pytest.raises(ValueError, match="too short"):
        dm.setup("fit")


def test_bytelm_val_is_file_tail(tmp_path):
    """Temporal holdout: validation windows come from the END of the
    file (documented contract — val on unseen later text)."""
    from ray_lightning_tpu.models import ByteLMDataModule

    p = tmp_path / "ab.txt"
    # First 2/3 'a' bytes, final third 'b' bytes.
    p.write_bytes(b"a" * 4000 + b"b" * 2000)
    dm = ByteLMDataModule(str(p), seq_len=50, batch_size=4)
    dm.set_shard(0, 1)
    dm.setup("fit")
    val = next(iter(dm.val_dataloader()))["tokens"]
    assert (val[:, 1:] == ord("b")).all()  # tail-only content
