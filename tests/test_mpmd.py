"""MPMD pipeline plane (ISSUE 7): plans, schedules, transfer lane,
stage execution, parity, fault integration.

Layer map:

* **plan/schedule units** — split math (incl. non-divisible), stream
  structure, deadlock-freedom by simulation, the interleaved-1F1B
  bubble win, measured-bubble accounting;
* **transfer units** — mailbox rendezvous, TCP inbox round-trips, shm
  payload routing, the chunked/size-scaled queue sends (satellite);
* **integration (all slow-marked — the 870s tier-1 budget barely fits
  the pre-existing sweep on this container)** — the in-process
  2-worker pipeline fits (1f1b / gpipe / interleaved / P=1 / M<P)
  matching the single-mesh SPMD GPipe reference to atol 1e-5, and the
  real actor plane: MpmdStrategy fit parity and the chaos stage-kill →
  restart-governor → step-exact-resume acceptance.  The same parity
  gates also run on every driver pass via the ``dryrun_multichip``
  mpmd flavor.
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from ray_lightning_tpu.mpmd.plan import StagePlan
from ray_lightning_tpu.mpmd.schedule import (
    BWD,
    FWD,
    Instr,
    build_schedule,
    build_streams,
    bubble_from_timeline,
    fleet_pipeline_stats,
    measured_schedule_bubble,
    pool_op_costs,
    simulate_streams,
    validate_streams,
)
from ray_lightning_tpu.mpmd.transfer import (
    LocalChannel,
    Mailbox,
    QueueChannel,
    StageInbox,
)
from ray_lightning_tpu.parallel.pipeline import layer_splits

pytestmark = pytest.mark.mpmd


# ---------------------------------------------------------------------------
# Plan / split math
# ---------------------------------------------------------------------------

def test_layer_splits_divisible():
    assert layer_splits(8, 4) == (0, 2, 4, 6, 8)
    assert layer_splits(4, 1) == (0, 4)


def test_layer_splits_remainder_front_loaded():
    assert layer_splits(7, 3) == (0, 3, 5, 7)
    assert layer_splits(5, 4) == (0, 2, 3, 4, 5)


def test_layer_splits_errors():
    with pytest.raises(ValueError, match="not divisible"):
        layer_splits(7, 3, require_divisible=True)
    with pytest.raises(ValueError, match="cannot fill"):
        layer_splits(2, 3)
    with pytest.raises(ValueError, match="n_stages"):
        layer_splits(4, 0)


def test_stage_plan_bounds_and_slice():
    import jax.numpy as jnp

    plan = StagePlan.split(7, 3)
    assert plan.stage_bounds(0) == (0, 3)
    assert plan.stage_bounds(2) == (5, 7)
    tree = {"w": jnp.arange(7)}
    assert list(plan.slice_stacked(tree, 1)["w"]) == [3, 4]
    with pytest.raises(ValueError, match="out of range"):
        plan.stage_bounds(3)


def _tiny_gpt(n_layer=2):
    from ray_lightning_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=32, n_layer=n_layer, n_head=2,
                    d_model=16, seq_len=8, warmup_steps=2)
    module = GPT(cfg, attn_impl="xla")
    module.precision = "f32"
    return module, cfg


def test_gpt_spec_split_assemble_roundtrip():
    import jax

    from ray_lightning_tpu.mpmd.plan import _gpt_untie, gpt_mpmd_spec

    module, _ = _tiny_gpt()
    spec = gpt_mpmd_spec(module)
    full = _gpt_untie(module.init_params(jax.random.PRNGKey(0)))
    plan = StagePlan.split(spec.n_layers, 2)
    parts = [spec.split_params(full, plan, p) for p in range(2)]
    assert "wte" in parts[0] and "wte" not in parts[1]
    assert "head_w" in parts[1] and "head_w" not in parts[0]
    rebuilt = spec.assemble_params(parts, plan)
    for key in ("wte", "wpe", "ln_f_g", "ln_f_b", "head_w"):
        np.testing.assert_array_equal(
            np.asarray(rebuilt[key]), np.asarray(full[key])
        )
    for key, leaf in full["blocks"].items():
        np.testing.assert_array_equal(
            np.asarray(rebuilt["blocks"][key]), np.asarray(leaf)
        )


def test_resolve_spec_rejects_unknown_module():
    from ray_lightning_tpu.mpmd.plan import resolve_mpmd_spec

    with pytest.raises(TypeError, match="mpmd_spec"):
        resolve_mpmd_spec(object())


# ---------------------------------------------------------------------------
# Schedules
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ["gpipe", "1f1b"])
@pytest.mark.parametrize("n_stages,n_micro", [(1, 4), (2, 8), (4, 3)])
def test_streams_validate_and_simulate(name, n_stages, n_micro):
    streams = build_streams(name, n_stages, n_micro)
    assert validate_streams(streams, n_micro) == []
    sim = simulate_streams(streams, transfer_s=0.1)
    assert sim["makespan"] > 0


def test_1f1b_warmup_counts():
    streams = build_streams("1f1b", 4, 8)
    for p, stream in enumerate(streams):
        # Forwards before the first BWD = the stage's warmup depth plus
        # the first steady-state forward.
        first_bwd = next(
            i for i, instr in enumerate(stream) if instr.op == BWD
        )
        fwds_before = sum(
            1 for instr in stream[:first_bwd] if instr.op == FWD
        )
        assert fwds_before == min(4 - 1 - p, 8) + 1


def test_gpipe_peak_stash_is_m_and_1f1b_is_bounded():
    """The memory story: count in-flight forwarded-not-backwarded
    micro-batches along each stream."""
    def peak_live(stream):
        live = peak = 0
        for instr in stream:
            if instr.op == FWD:
                live += 1
                peak = max(peak, live)
            elif instr.op == BWD:
                live -= 1
        return peak

    gpipe0 = build_schedule("gpipe", 0, 4, 8)
    f1b0 = build_schedule("1f1b", 0, 4, 8)
    assert peak_live(gpipe0) == 8          # all M stashed
    assert peak_live(f1b0) == 4            # bounded by P
    assert peak_live(build_schedule("1f1b", 3, 4, 8)) == 1


@pytest.mark.parametrize("n_workers,interleave", [(2, 2), (2, 4), (3, 2)])
def test_interleaved_streams_structurally_valid(n_workers, interleave):
    streams = build_streams("1f1b", n_workers, 8, interleave=interleave)
    assert validate_streams(streams, 8, interleave=interleave) == []
    # Deadlock-freedom is timing-independent for fixed total orders:
    # one successful simulation certifies the stream.
    simulate_streams(streams, transfer_s=0.3, interleave=interleave)


def test_interleaved_bubble_beats_gpipe_structurally():
    costs = {FWD: 1.0, BWD: 2.0, "SEND_ACT": 0.05}
    g = simulate_streams(build_streams("gpipe", 2, 8), costs,
                         transfer_s=0.1)
    i = simulate_streams(
        build_streams("1f1b", 2, 8, interleave=2),
        {FWD: 0.5, BWD: 1.0, "SEND_ACT": 0.05},
        transfer_s=0.1, interleave=2,
    )
    assert i["bubble_fraction"] < g["bubble_fraction"]
    # And through the measured-cost entry point the dryrun/bench use:
    mi = measured_schedule_bubble(
        "1f1b", 2, 8, 2, {"FWD": 0.5, "BWD": 1.0, "SEND": 0.05}
    )
    mg = measured_schedule_bubble(
        "gpipe", 2, 8, 1, {"FWD": 1.0, "BWD": 2.0, "SEND": 0.05}
    )
    assert mi < mg


def test_simulate_detects_deadlock():
    # Two workers that each RECV before anyone sends: a cyclic wait.
    streams = [
        [Instr("RECV_GRAD", 0), Instr(FWD, 0), Instr("SEND_ACT", 0),
         Instr(BWD, 0), Instr("UPDATE")],
        [Instr("RECV_ACT", 0), Instr(FWD, 0), Instr(BWD, 0),
         Instr("SEND_GRAD", 0), Instr("UPDATE")],
    ]
    with pytest.raises(RuntimeError, match="deadlock"):
        simulate_streams(streams)


def test_build_streams_rejects_bad_shapes():
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        build_streams("zigzag", 2, 4)
    with pytest.raises(ValueError, match="requires the '1f1b'"):
        build_streams("gpipe", 2, 4, interleave=2)
    with pytest.raises(ValueError, match="n_micro"):
        build_schedule("gpipe", 0, 2, 0)


def test_bubble_from_timeline_math():
    # 2s wall (t=0..2 to UPDATE), 1.2s busy -> bubble 0.4.
    timeline = [
        {"op": FWD, "mb": 0, "t0": 0.0, "t1": 0.7, "blocked_s": 0.0},
        {"op": "RECV_GRAD", "mb": 0, "t0": 0.7, "t1": 1.5,
         "blocked_s": 0.8},
        {"op": BWD, "mb": 0, "t0": 1.5, "t1": 2.0, "blocked_s": 0.0},
        {"op": "UPDATE", "mb": -1, "t0": 2.0, "t1": 2.3,
         "blocked_s": 0.0},
    ]
    s = bubble_from_timeline(timeline)
    assert s["bubble_fraction"] == pytest.approx(0.4)
    assert s["stage_occupancy"] == pytest.approx(0.6)
    assert s["blocked_s"] == pytest.approx(0.8)
    assert bubble_from_timeline([])["bubble_fraction"] == 0.0


def test_fleet_pipeline_stats_skew():
    stats = fleet_pipeline_stats([
        {"bubble_fraction": 0.1, "stage_occupancy": 0.9, "busy_s": 1.0},
        {"bubble_fraction": 0.3, "stage_occupancy": 0.7, "busy_s": 1.5},
    ])
    assert stats["bubble_fraction"] == pytest.approx(0.2)
    assert stats["stage_skew_ms"] == pytest.approx(500.0)


def test_pool_op_costs_median():
    pooled = pool_op_costs([
        {"FWD": 1.0, "BWD": 2.0}, {"FWD": 3.0}, {"FWD": 2.0},
    ])
    assert pooled["FWD"] == 2.0
    assert pooled["BWD"] == 2.0


# ---------------------------------------------------------------------------
# Transfer lane
# ---------------------------------------------------------------------------

def test_mailbox_rendezvous_and_blocked_accounting():
    box = Mailbox()

    def deliver_later():
        time.sleep(0.15)
        box.deliver(("act", 0, 1, 0), {"x": 1})

    threading.Thread(target=deliver_later).start()
    payload, blocked = box.recv(("act", 0, 1, 0), timeout=5.0)
    assert payload == {"x": 1}
    assert blocked >= 0.1


def test_mailbox_timeout_and_poison():
    box = Mailbox()
    with pytest.raises(TimeoutError, match="peer stage"):
        box.recv(("act", 0, 0, 0), timeout=0.1)
    box.fail(RuntimeError("peer died"))
    with pytest.raises(RuntimeError, match="transfer lane failed"):
        box.recv(("act", 0, 0, 0), timeout=1.0)


def test_inbox_queue_channel_roundtrip_tcp():
    inbox = StageInbox()
    try:
        chan = QueueChannel(inbox.handle, same_host=False)
        tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3)}
        chan.send("act", 2, 1, tree, chunk=1)
        got, _ = inbox.mailbox.recv(("act", 2, 1, 1), timeout=10.0)
        np.testing.assert_array_equal(got["a"], tree["a"])
        assert chan.bytes_sent > 0 and chan.shm_sends == 0
        chan.close()
    finally:
        inbox.close()


def test_inbox_shm_payload_routing():
    from ray_lightning_tpu.cluster.shm import segment_dir

    inbox = StageInbox()
    try:
        chan = QueueChannel(inbox.handle, same_host=True, shm_threshold=64)
        tree = {"a": np.ones((64, 64), np.float32)}
        chan.send("grad", 0, 3, tree)
        got, _ = inbox.mailbox.recv(("grad", 0, 3, 0), timeout=10.0)
        np.testing.assert_array_equal(got["a"], tree["a"])
        assert chan.shm_sends == 1
        # The consumer unlinks the segment after the read.
        time.sleep(0.1)
        leftovers = [
            e for e in os.listdir(segment_dir())
            if e.startswith(f"rlt-seg-{os.getpid()}-")
        ]
        assert leftovers == []
        chan.close()
    finally:
        inbox.close()


def test_local_channel_chunk_keys():
    box = Mailbox()
    chan = LocalChannel(box)
    chan.send("act", 1, 2, {"x": np.float32(3.0)}, chunk=1)
    assert not box.ready(("act", 1, 2, 0))
    got, _ = box.recv(("act", 1, 2, 1), timeout=1.0)
    assert float(got["x"]) == 3.0


# ---------------------------------------------------------------------------
# Queue satellite: chunked sends + size-scaled budgets
# ---------------------------------------------------------------------------

def test_send_timeout_scales_with_payload():
    from ray_lightning_tpu.cluster import queue as queue_mod

    assert queue_mod._send_timeout_s(0) == queue_mod._ACK_TIMEOUT_S
    big = 512 << 20
    assert queue_mod._send_timeout_s(big) == pytest.approx(
        big / queue_mod._MIN_SEND_THROUGHPUT
    )
    assert queue_mod._send_timeout_s(big) > queue_mod._ACK_TIMEOUT_S


def test_chunked_send_survives_throttled_reader(monkeypatch):
    """A slow consumer that would trip a single whole-payload timeout
    must NOT trip the per-chunk budgets (satellite: one slow multi-MB
    activation can't kill the lane)."""
    from ray_lightning_tpu.cluster import queue as queue_mod

    # Shrink the world: 64 KiB chunks, ~0.2 s per-chunk budget.
    monkeypatch.setattr(queue_mod, "_ACK_TIMEOUT_S", 0.2)
    chunk = 64 << 10
    payload = os.urandom(6 * chunk)
    a, b = socket.socketpair()
    a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 32 << 10)
    b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 32 << 10)
    got = []

    def slow_reader():
        while sum(len(c) for c in got) < len(payload):
            data = b.recv(16 << 10)
            if not data:
                return
            got.append(data)
            time.sleep(0.02)  # ~8x slower than the per-chunk budget
            # would allow for the WHOLE payload in one timeout window

    t = threading.Thread(target=slow_reader)
    t.start()
    try:
        # Control: the whole payload under ONE per-chunk-sized timeout
        # budget cannot finish against this reader...
        total_budget = queue_mod._send_timeout_s(chunk)
        assert total_budget < 0.3
        # ...but the chunked path re-arms the clock per slice.
        queue_mod._sendall_chunked(a, payload, chunk_bytes=chunk)
    finally:
        t.join(timeout=30)
        a.close()
        b.close()
    assert sum(len(c) for c in got) == len(payload)
    assert b"".join(got) == payload


def test_queue_put_chunked_roundtrip(monkeypatch):
    """A multi-chunk payload arrives intact through the real
    DriverQueue server (frame header + chunked body must reassemble)."""
    from ray_lightning_tpu.cluster import queue as queue_mod

    monkeypatch.setattr(queue_mod, "_SEND_CHUNK_BYTES", 32 << 10)
    q = queue_mod.DriverQueue()
    try:
        handle = q.handle
        blob = os.urandom(300 << 10)  # ~10 chunks
        handle.put({"blob": blob})
        item = q.get(timeout=30)
        assert item["blob"] == blob
        handle.close()
    finally:
        q.shutdown()


# ---------------------------------------------------------------------------
# shm sweep satellite
# ---------------------------------------------------------------------------

def test_sweep_reclaims_killed_producer_segments(tmp_path):
    """kill -9 a segment producer; the sweep must reclaim its tmpfs."""
    from ray_lightning_tpu.cluster.shm import (
        segment_dir,
        sweep_stale_segments,
    )

    code = (
        "from ray_lightning_tpu.cluster.shm import SegmentStore\n"
        "import sys, time\n"
        "store = SegmentStore(prefix='rlt-seg')\n"
        "path = store.put(b'x' * 4096)\n"
        "print(path, flush=True)\n"
        "time.sleep(60)\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code], stdout=subprocess.PIPE,
        env={**os.environ, "PYTHONPATH": os.pathsep.join(sys.path)},
    )
    try:
        path = proc.stdout.readline().decode().strip()
        assert os.path.exists(path), "producer failed to create a segment"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        # atexit never ran (SIGKILL): the segment is orphaned until the
        # sweep runs.
        assert os.path.exists(path)
        assert sweep_stale_segments() >= 1
        assert not os.path.exists(path)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert segment_dir()  # smoke: helper stays importable


def test_kill_workers_sweeps_stale_segments():
    """The strategy's kill path reclaims segments of dead pids even
    when no worker objects survive to tear down."""
    from ray_lightning_tpu.cluster.shm import segment_dir
    from ray_lightning_tpu.parallel.strategies import MpmdStrategy

    # Fabricate a stale segment owned by a definitely-dead pid (the
    # name format is what the sweeper matches).
    dead_pid = 2 ** 22 + 12345  # beyond pid_max on this container
    path = os.path.join(
        segment_dir(), f"rlt-seg-{dead_pid}-{'0' * 32}"
    )
    with open(path, "wb") as f:
        f.write(b"stale")
    try:
        strategy = MpmdStrategy(num_stages=1, devices_per_stage=1)
        strategy._kill_workers(why="test")
        assert not os.path.exists(path)
    finally:
        if os.path.exists(path):
            os.unlink(path)


# ---------------------------------------------------------------------------
# Chaos grammar stage pin + strategy validation
# ---------------------------------------------------------------------------

def test_fault_grammar_stage_alias():
    from ray_lightning_tpu.fault.inject import parse_faults

    (spec,) = parse_faults("crash@stage:1,step:3")
    assert spec.rank == 1 and spec.step == 3


def test_mpmd_strategy_eager_validation():
    from ray_lightning_tpu.parallel.strategies import MpmdStrategy

    with pytest.raises(ValueError, match="unknown schedule"):
        MpmdStrategy(schedule="zigzag")
    with pytest.raises(ValueError, match="requires schedule='1f1b'"):
        MpmdStrategy(schedule="gpipe", interleave=2)
    with pytest.raises(ValueError, match="num_microbatches"):
        MpmdStrategy(num_microbatches=0)
    strategy = MpmdStrategy(num_stages=2, devices_per_stage=2)
    with pytest.raises(NotImplementedError, match="fit only"):
        strategy.run("validation", None, None, None, [])


# ---------------------------------------------------------------------------
# Checkpoint discovery
# ---------------------------------------------------------------------------

def _write_stage_ckpt(tmp_path, step, stage, payload=b"ok"):
    from ray_lightning_tpu.mpmd.stage import stage_ckpt_name
    from ray_lightning_tpu.utils.state_stream import (
        state_stream_to_file,
        to_state_stream,
    )

    path = tmp_path / stage_ckpt_name(step, stage)
    state_stream_to_file(
        to_state_stream({"state": {"x": np.zeros(2)}, "step": step}),
        str(path),
    )
    return path


def test_latest_mpmd_checkpoint_walks_back(tmp_path):
    from ray_lightning_tpu.mpmd.worker import latest_mpmd_checkpoint

    assert latest_mpmd_checkpoint(str(tmp_path), 2)["path"] is None
    # Step 2: complete and valid.  Step 3: stage 1 missing (died
    # mid-write).  Step 4: complete but stage 0's file is corrupt.
    for stage in (0, 1):
        _write_stage_ckpt(tmp_path, 2, stage)
    _write_stage_ckpt(tmp_path, 3, 0)
    for stage in (0, 1):
        _write_stage_ckpt(tmp_path, 4, stage)
    bad = tmp_path / "mpmd-step00000004-stage0.ckpt"
    blob = bytearray(bad.read_bytes())
    blob[len(blob) // 2] ^= 0x01
    bad.write_bytes(bytes(blob))

    info = latest_mpmd_checkpoint(str(tmp_path), 2)
    assert info["path"].endswith("mpmd-step00000002")
    assert any("stage0" in c["path"] for c in info["corrupt"])


# ---------------------------------------------------------------------------
# Telemetry surfaces
# ---------------------------------------------------------------------------

def test_prom_and_rlt_top_render_mpmd():
    import importlib.util

    from ray_lightning_tpu.telemetry.export_prom import render_openmetrics

    beat = {
        "type": "mpmd_stage", "stage": 0, "step": 5,
        "bubble_fraction": 0.125, "stage_occupancy": 0.875,
        "busy_s": 0.2, "blocked_s": 0.01, "loss": 3.5,
    }
    snapshot = {
        "ranks_reporting": 0, "ranks": {},
        "mpmd": {
            "schedule": "1f1b", "interleave": 2, "n_micro": 8,
            "n_stages": 2, "stages": [beat],
        },
    }
    text = render_openmetrics(snapshot)
    assert 'rlt_mpmd_stage_bubble_fraction{stage="0"} 0.125' in text
    assert "rlt_mpmd_stages 2" in text
    assert text.rstrip().endswith("# EOF")

    spec = importlib.util.spec_from_file_location(
        "rlt_top", os.path.join(
            os.path.dirname(__file__), "..", "tools", "rlt_top.py"
        )
    )
    rlt_top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rlt_top)
    frame = rlt_top.render({"mpmd": snapshot["mpmd"]}, "x")
    assert "mpmd pipeline" in frame
    assert "1f1b x2" in frame


def test_mpmd_schema_validators():
    from ray_lightning_tpu.telemetry.schema import (
        validate_bench_mpmd,
        validate_mpmd_xfer,
        validate_stream_item,
    )

    beat = {
        "type": "mpmd_stage", "stage": 1, "step": 0,
        "bubble_fraction": 0.2, "stage_occupancy": 0.8,
    }
    assert validate_stream_item(beat) == []
    assert validate_stream_item({**beat, "bubble_fraction": 2.0})
    xfer = {"type": "mpmd_xfer", "kind": "act", "step": 0, "mb": 1,
            "chunk": 0, "data": b"x"}
    assert validate_mpmd_xfer(xfer) == []
    assert validate_mpmd_xfer({**xfer, "kind": "weird"})
    assert validate_bench_mpmd(
        {"schedule": "gpipe", "n_stages": 2, "n_micro": 8}
    ) == []
    assert validate_bench_mpmd({"schedule": "gpipe"})


# ---------------------------------------------------------------------------
# In-process pipeline fit: the fast parity gate
# ---------------------------------------------------------------------------

def _parity_setup(n_layer=2):
    import jax

    from ray_lightning_tpu.mpmd.plan import _gpt_untie, gpt_mpmd_spec

    module, cfg = _tiny_gpt(n_layer)
    spec = gpt_mpmd_spec(module)
    full = _gpt_untie(module.init_params(jax.random.PRNGKey(0)))
    rng = np.random.default_rng(7)
    steps, bsz = 2, 8
    data = [
        {"tokens": rng.integers(
            0, cfg.vocab_size, (bsz, cfg.seq_len + 1)).astype(np.int32)}
        for _ in range(steps)
    ]
    return module, spec, full, data, steps


def _reference_losses(spec, full, data, steps, n_micro, devices):
    from ray_lightning_tpu.mpmd.reference import gpipe_reference_fit

    return gpipe_reference_fit(
        spec, full, spec.tx_factory(), lambda s: data[s], steps,
        n_stages=2, n_micro=n_micro, devices=devices,
    )


@pytest.mark.slow
def test_inproc_pipeline_fit_matches_single_mesh_gpipe(cpu_mesh_devices):
    from ray_lightning_tpu.mpmd.inproc import run_inproc_pipeline_fit

    module, spec, full, data, steps = _parity_setup()
    devices = cpu_mesh_devices
    res = run_inproc_pipeline_fit(
        spec, full, spec.tx_factory, lambda s: data[s], steps,
        n_workers=2, n_micro=4, schedule="1f1b",
        device_groups=[devices[0:2], devices[2:4]],
    )
    ref = _reference_losses(spec, full, data, steps, 4, devices[:2])
    np.testing.assert_allclose(
        res["losses"], ref["losses"], rtol=0, atol=1e-5
    )
    assert res["final_step"] == steps
    # Reassembled params match the single-program fit too.
    np.testing.assert_allclose(
        np.asarray(res["params"]["wte"]),
        np.asarray(ref["state"].params["wte"]),
        atol=1e-5,
    )
    # Every stage produced steady-state stats.
    assert len(res["per_stage_stats"]) == 2
    assert all(
        0 <= s["bubble_fraction"] <= 1 for s in res["per_stage_stats"]
    )


@pytest.mark.slow
@pytest.mark.parametrize("schedule,interleave", [
    ("gpipe", 1), ("1f1b", 2),
])
def test_inproc_schedule_flavors_parity(cpu_mesh_devices, schedule,
                                        interleave):
    from ray_lightning_tpu.mpmd.inproc import run_inproc_pipeline_fit

    # interleave=2 over 2 workers needs >= 4 stacked layers.
    module, spec, full, data, steps = _parity_setup(n_layer=4)
    devices = cpu_mesh_devices
    res = run_inproc_pipeline_fit(
        spec, full, spec.tx_factory, lambda s: data[s], steps,
        n_workers=2, n_micro=4, schedule=schedule, interleave=interleave,
        device_groups=[devices[0:2], devices[2:4]],
    )
    ref = _reference_losses(spec, full, data, steps, 4, devices[:2])
    np.testing.assert_allclose(
        res["losses"], ref["losses"], rtol=0, atol=1e-5
    )


@pytest.mark.slow
def test_inproc_single_stage_degenerate_pipe(cpu_mesh_devices):
    """P=1: no transport at all, still the same math."""
    from ray_lightning_tpu.mpmd.inproc import run_inproc_pipeline_fit

    module, spec, full, data, steps = _parity_setup()
    res = run_inproc_pipeline_fit(
        spec, full, spec.tx_factory, lambda s: data[s], steps,
        n_workers=1, n_micro=4, schedule="gpipe",
        device_groups=[cpu_mesh_devices[0:2]],
    )
    ref = _reference_losses(
        spec, full, data, steps, 4, cpu_mesh_devices[:2]
    )
    np.testing.assert_allclose(
        res["losses"], ref["losses"], rtol=0, atol=1e-5
    )


@pytest.mark.slow
def test_micro_batches_fewer_than_stages(cpu_mesh_devices):
    """M < P: the pipeline degrades to mostly-bubble but stays correct
    (the MPMD analogue of the SPMD edge the parity tests lean on)."""
    from ray_lightning_tpu.mpmd.inproc import run_inproc_pipeline_fit

    module, spec, full, data, steps = _parity_setup()
    res = run_inproc_pipeline_fit(
        spec, full, spec.tx_factory, lambda s: data[s], steps,
        n_workers=2, n_micro=1, schedule="gpipe",
        device_groups=None,  # meshless: plain per-stage devices
    )
    ref = _reference_losses(
        spec, full, data, steps, 1, cpu_mesh_devices[:2]
    )
    np.testing.assert_allclose(
        res["losses"], ref["losses"], rtol=0, atol=1e-5
    )


def test_split_micro_batches_rejects_ragged():
    from ray_lightning_tpu.mpmd.inproc import split_micro_batches

    with pytest.raises(ValueError, match="not divisible"):
        split_micro_batches({"tokens": np.zeros((7, 4))}, 2)


# ---------------------------------------------------------------------------
# The real actor plane (slow: multi-process fits)
# ---------------------------------------------------------------------------

def _actor_fit_pieces(tmp_path, max_steps=3, **strategy_kwargs):
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.gpt import SyntheticLMDataModule
    from ray_lightning_tpu.parallel.strategies import MpmdStrategy

    module, cfg = _tiny_gpt()
    dm = SyntheticLMDataModule(cfg, batch_size=8, num_batches=4, seed=3)
    strategy = MpmdStrategy(
        num_stages=2, schedule="1f1b", num_microbatches=4,
        devices_per_stage=2, **strategy_kwargs,
    )
    trainer = Trainer(
        strategy=strategy, max_steps=max_steps, max_epochs=1,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
    )
    return module, cfg, dm, strategy, trainer


@pytest.mark.slow
@pytest.mark.remote
def test_mpmd_strategy_actor_fit_parity(tmp_path):
    import jax

    from ray_lightning_tpu.mpmd.plan import _gpt_untie, gpt_mpmd_spec

    module, cfg, dm, strategy, trainer = _actor_fit_pieces(tmp_path)
    trainer.fit(module, dm)
    assert trainer.global_step == 3

    spec = gpt_mpmd_spec(module)
    full = _gpt_untie(module.init_params(jax.random.PRNGKey(0)))
    dm2 = type(dm)(cfg, batch_size=8, num_batches=4, seed=3)
    dm2.setup("fit")
    batches = list(dm2.train_dataloader())
    ref = _reference_losses(
        spec, full, batches, 3, 4, jax.devices()[:2]
    )
    np.testing.assert_allclose(
        strategy.mpmd_report["losses"], ref["losses"], rtol=0, atol=1e-5
    )
    # The report carries the full pipeline story.
    report = strategy.mpmd_report
    assert report["schedule"] == "1f1b"
    assert 0 <= report["bubble_fraction"] <= 1
    assert "FWD" in report["op_costs_ms"]
    # Trainer adopted the reassembled params.
    np.testing.assert_allclose(
        np.asarray(trainer.params["wte"]),
        np.asarray(ref["state"].params["wte"]),
        atol=1e-5,
    )
    # Live snapshot landed for rlt_top.
    live = os.path.join(str(tmp_path), "telemetry", "mpmd-live.json")
    assert os.path.exists(live)
    import json

    from ray_lightning_tpu.telemetry.schema import validate_mpmd_snapshot

    with open(live) as f:
        doc = json.load(f)
    assert validate_mpmd_snapshot(doc["mpmd"]) == []


@pytest.mark.slow
@pytest.mark.remote
@pytest.mark.chaos
def test_mpmd_stage_kill_drives_restart_governor(tmp_path, monkeypatch):
    """The ISSUE-7 fault acceptance: kill one stage actor mid-fit; the
    restart governor must respawn the set and resume step-exactly."""
    state_dir = tmp_path / "fault-state"
    monkeypatch.setenv("RLT_FAULT", "crash@step:2,stage:1")
    monkeypatch.setenv("RLT_FAULT_STATE", str(state_dir))
    module, cfg, dm, strategy, trainer = _actor_fit_pieces(
        tmp_path / "chaos", max_steps=4, max_restarts=2,
        restart_backoff_s=0.1,
    )
    trainer.fit(module, dm)
    assert trainer.global_step == 4
    assert strategy.restarts_used == 1
    kinds = [e["kind"] for e in strategy.recovery_events]
    assert "elastic_restart" in kinds

    # Step-exact continuation: the post-resume losses equal an
    # uninterrupted fit's bitwise (same data, same seeds, same ckpt).
    monkeypatch.delenv("RLT_FAULT")
    module2, cfg2, dm2, strategy2, trainer2 = _actor_fit_pieces(
        tmp_path / "clean", max_steps=4,
    )
    trainer2.fit(module2, dm2)
    resumed = strategy.mpmd_report["losses"]
    clean = strategy2.mpmd_report["losses"]
    np.testing.assert_allclose(
        resumed, clean[-len(resumed):], rtol=0, atol=1e-6
    )
