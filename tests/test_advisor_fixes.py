"""Regression tests for the round-1/round-2 advisor findings (VERDICT r3
weak #4-5): agent-RPC retry, segment release, crc32c fallback, partial
accumulation-window flush, lr/optimizer-step conventions.
"""

import os

import jax
import numpy as np
import optax
import pytest

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.parallel.strategies import LocalStrategy

from test_trainer_features import FixedDataModule


# -- (r1-a) agent RPC retry before declaring death ---------------------------

class _FlakyClient:
    """AgentClient stand-in: fails transiently N times, then answers."""

    def __init__(self, failures, answer=None, exc=ConnectionError):
        self.failures = failures
        self.answer = answer
        self.exc = exc
        self.calls = 0

    def poll(self, pid):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.exc("transient")
        return self.answer


def _handle(client):
    from ray_lightning_tpu.cluster.agent import _RemoteProcHandle

    h = _RemoteProcHandle.__new__(_RemoteProcHandle)
    h._client = client
    h.pid = 123
    h.returncode = None
    return h


def test_poll_survives_transient_rpc_failure():
    """Two dropped RPCs then a healthy answer: the child must still read
    as ALIVE (None), not dead — a spurious -1 triggers a full elastic
    respawn upstream."""
    h = _handle(_FlakyClient(failures=2, answer=None))
    assert h.poll() is None
    assert h.returncode is None


def test_poll_declares_death_after_retry_budget():
    client = _FlakyClient(failures=99)
    h = _handle(client)
    assert h.poll() == -1
    assert client.calls == 3  # the full retry budget was spent


def test_poll_trusts_structured_agent_error():
    """A structured AgentError reply (unknown pid) is deterministic — no
    retries, immediate death verdict."""
    from ray_lightning_tpu.cluster.agent import AgentError

    client = _FlakyClient(failures=99, exc=AgentError)
    h = _handle(client)
    assert h.poll() == -1
    assert client.calls == 1


# -- (r1-b) segment release per fit ------------------------------------------

def test_objectref_release_reclaims_segment(tmp_path):
    from ray_lightning_tpu.cluster.backend import LocalBackend

    be = LocalBackend(min_segment_bytes=0)  # force segment spill
    try:
        ref = be.put({"blob": b"x" * 4096})
        path = ref._segment_path
        assert path is not None and os.path.exists(path)
        ref.release()
        assert not os.path.exists(path)
        ref.release()  # idempotent
    finally:
        be.shutdown()


def test_repeated_fits_do_not_accumulate_segments(tmp_path, monkeypatch):
    """The PBT pattern: many fits on one strategy/backend must not leak
    tmpfs segments (task payloads are released per fit)."""
    from ray_lightning_tpu.cluster.backend import LocalBackend
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    x = np.random.default_rng(0).standard_normal((16, 32)).astype(np.float32)
    # A caller-OWNED backend spans trainers (the PBT pattern): strategy
    # teardown must not shut it down, so leaked segments would pile up.
    be = LocalBackend(min_segment_bytes=0)
    try:
        live = []
        for _ in range(2):
            trainer = Trainer(
                strategy=RayStrategy(num_workers=1, backend=be),
                max_epochs=1, default_root_dir=str(tmp_path),
                enable_checkpointing=False,
            )
            trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
            live.append(
                sum(1 for p in be._store._paths if os.path.exists(p))
            )
        assert live[1] <= live[0]
        assert live[1] == 0  # every task payload was released
    finally:
        be.shutdown()


# -- (r1-c) crc32c software fallback -----------------------------------------

def test_crc32c_python_fallback_vector():
    from ray_lightning_tpu.native import _crc32c_py

    # RFC 3720 test vector for CRC32C (Castagnoli).
    assert _crc32c_py(b"123456789") == 0xE3069283
    # Seed chaining: crc(a+b) == crc(b, crc(a)).
    a, b = b"hello ", b"world"
    assert _crc32c_py(a + b) == _crc32c_py(b, _crc32c_py(a))


def test_crc32c_entrypoint_never_raises(monkeypatch):
    """crc32c() must work with the native library absent (pure-Python
    deployment), and agree with the native result when present."""
    import ray_lightning_tpu.native as native

    want = native._crc32c_py(b"123456789")
    if native.native_available():
        assert native.crc32c(b"123456789") == want
    monkeypatch.setattr(native, "_load", lambda: None)
    assert native.crc32c(b"123456789") == want


# -- (r2-a) partial accumulation window flushes at epoch end -----------------

def test_accum_flush_unit():
    """_build_accum_flush applies exactly one inner update from the mean
    of the accumulated micro-grads and resets the window."""
    from ray_lightning_tpu.core.loop import _build_accum_flush
    from ray_lightning_tpu.core.module import TrainState

    inner = optax.sgd(0.5)
    tx = optax.MultiSteps(inner, every_k_schedule=3)
    params = {"w": np.ones(4, np.float32)}
    state = TrainState.create(params, tx)
    g1 = {"w": np.full(4, 2.0, np.float32)}
    g2 = {"w": np.full(4, 4.0, np.float32)}
    for g in (g1, g2):  # two micro-grads of a 3-window
        updates, new_opt = tx.update(g, state.opt_state, state.params)
        state = TrainState(
            optax.apply_updates(state.params, updates), new_opt, state.step
        )
    assert int(state.opt_state.mini_step) == 2
    np.testing.assert_allclose(state.params["w"], 1.0)  # not applied yet

    flush = _build_accum_flush(inner, mesh=None, state_shardings=None)
    state = flush(state)
    # mean(2, 4) = 3; sgd(0.5) => 1 - 1.5
    np.testing.assert_allclose(np.asarray(state.params["w"]), -0.5,
                               rtol=1e-6)
    assert int(state.opt_state.mini_step) == 0
    assert int(state.opt_state.gradient_step) == 1


def test_accum_partial_window_flushes_in_fit(tmp_path):
    """3 micro-batches with accumulate=2: the trailing odd batch still
    reaches the params (global_step = 2 optimizer updates, not 1)."""
    x = np.random.default_rng(0).standard_normal((24, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=1, accumulate_grad_batches=2,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
    assert trainer.global_step == 2


def test_accum_flush_keeps_counter_synced_across_epochs(tmp_path):
    """After an epoch-end flush resets MultiSteps' window, the next
    epoch's optimizer-step counting must follow the window position, not
    micro_step % accum.  6 batches/epoch at accum=4, 2 epochs:
    epoch 0 -> update@4 + flush(2) = 2; epoch 1 -> update@(2+2... window
    of 4 spanning the boundary reset) = updates at micro 10 and flush(2)
    = 2 more; total 4."""
    x = np.random.default_rng(0).standard_normal((48, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=2, accumulate_grad_batches=4,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
    assert trainer.micro_step == 12
    assert trainer.global_step == 4


def test_max_steps_exact_after_flush(tmp_path):
    """max_steps counts REAL optimizer updates even when a flush happened
    in an earlier epoch (the desync would stop one update early)."""
    x = np.random.default_rng(0).standard_normal((48, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=10, accumulate_grad_batches=4,
        max_steps=3, default_root_dir=str(tmp_path),
        enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
    assert trainer.global_step == 3


def test_legacy_checkpoint_resume_micro_convention(tmp_path):
    """Pre-convention checkpoints stored the MICRO count in
    'global_step'; resume must map it to optimizer steps, not multiply
    it up."""
    from ray_lightning_tpu.core.loop import FitConfig, run_fit
    from ray_lightning_tpu.utils.state_stream import (
        state_stream_to_file, to_state_stream,
    )

    # Forge a legacy payload: fit once to get a real state, then strip
    # the micro_step key and store micro count under global_step.
    x = np.random.default_rng(0).standard_normal((32, 32)).astype(np.float32)
    cfg = FitConfig(max_epochs=1, accumulate_grad_batches=2, seed=0,
                    default_root_dir=str(tmp_path))
    module = BoringModel()
    run_fit(module, FixedDataModule(x, batch_size=8), cfg, callbacks=[])
    state = module.trainer.state
    legacy = {
        "state": jax.device_get(state),
        "epoch": 0,
        "global_step": 6,  # legacy = MICRO batches (3 optimizer steps)
        "callback_metrics": {},
    }
    path = str(tmp_path / "legacy.ckpt")
    state_stream_to_file(to_state_stream(legacy), path)

    cfg2 = FitConfig(max_epochs=2, accumulate_grad_batches=2, seed=0,
                     default_root_dir=str(tmp_path),
                     resume_from_checkpoint=path)
    module2 = BoringModel()
    res = run_fit(module2, FixedDataModule(x, batch_size=8), cfg2,
                  callbacks=[])
    # Resumed counters: global_step continued from 6//2=3, one more
    # epoch of 4 micro-batches = 2 more updates.
    assert res["global_step"] == 3 + 2
    assert res["micro_step"] == 6 + 4


# -- (r2-b) lr/global_step optimizer-step convention -------------------------

def test_global_step_counts_optimizer_steps(tmp_path):
    """4 micro-batches at accumulate=2 => global_step == 2 (Lightning's
    optimizer-step convention, not the micro-batch count)."""
    x = np.random.default_rng(0).standard_normal((32, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=1, accumulate_grad_batches=2,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
    assert trainer.global_step == 2


def test_logged_lr_is_last_applied(tmp_path):
    """The logged lr belongs to the optimizer step just TAKEN: after k
    updates the last one used schedule(k-1), not schedule(k)."""
    from test_trainer_features import ScheduledBoring

    x = np.random.default_rng(0).standard_normal((24, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=1,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        log_every_n_steps=1,
    )
    trainer.fit(ScheduledBoring(), FixedDataModule(x, batch_size=8))
    assert trainer.global_step == 3
    schedule = optax.linear_schedule(0.1, 0.0, 100)
    assert trainer.callback_metrics["lr"] == pytest.approx(
        float(schedule(2))
    )


# -- (r2-c) dual-convention MFU fields in bench ------------------------------

def test_bench_reports_both_mfu_conventions():
    import bench

    cfg_flops_full = bench.model_flops_per_token(
        bench.GPTConfig.tiny(), attn="full")
    cfg_flops_causal = bench.model_flops_per_token(
        bench.GPTConfig.tiny(), attn="causal")
    assert cfg_flops_causal < cfg_flops_full
    # Attention term is exactly halved; everything else is identical.
    cfg = bench.GPTConfig.tiny()
    attn_full = 3.0 * 4 * cfg.n_layer * cfg.seq_len * cfg.d_model
    assert cfg_flops_full - cfg_flops_causal == pytest.approx(attn_full / 2)


# -- (r4-a) kernel_probe: transient vs permanent classification --------------

def test_kernel_probe_bare_valueerror_is_retryable(monkeypatch):
    """A bare ValueError (e.g. dispatch-time failure under momentary
    device pressure) must NOT permanently disable the kernels: the next
    call re-probes and can succeed."""
    from ray_lightning_tpu.ops import kernel_probe

    monkeypatch.setattr(kernel_probe, "_interpret", lambda: False)
    monkeypatch.setattr(kernel_probe, "_CACHE", {})
    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        if calls["n"] == 1:
            raise ValueError("transient dispatch failure")

    with pytest.warns(UserWarning, match="for this call"):
        assert kernel_probe.kernel_available("k", probe) is False
    # Re-probed on the next call and recovered.
    assert kernel_probe.kernel_available("k", probe) is True
    assert calls["n"] == 2


@pytest.mark.parametrize("exc", [
    NotImplementedError("no lowering"),
    ValueError("Mosaic failed to compile"),
    RuntimeError("Ran out of VMEM"),
])
def test_kernel_probe_compiler_errors_are_permanent(monkeypatch, exc):
    from ray_lightning_tpu.ops import kernel_probe

    monkeypatch.setattr(kernel_probe, "_interpret", lambda: False)
    monkeypatch.setattr(kernel_probe, "_CACHE", {})
    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        raise exc

    with pytest.warns(UserWarning):
        assert kernel_probe.kernel_available("k", probe) is False
    assert kernel_probe.kernel_available("k", probe) is False
    assert calls["n"] == 1  # cached, never re-probed


# -- (r4-b) queue put() ack read cannot hang forever -------------------------

def test_queue_put_times_out_on_wedged_server(monkeypatch):
    """A server that accepts + reads but never acks must fail the put in
    bounded time (socket timeout -> close-and-raise), not hang while
    holding the handle lock."""
    import socket
    import threading

    from ray_lightning_tpu.cluster import queue as qmod

    monkeypatch.setattr(qmod, "_ACK_TIMEOUT_S", 0.2)
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)

    def wedged():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            # Read the frame but never send the ack byte.
            try:
                conn.recv(1 << 16)
            except OSError:
                pass

    t = threading.Thread(target=wedged, daemon=True)
    t.start()
    try:
        h = qmod.QueueHandle("127.0.0.1", srv.getsockname()[1])
        with pytest.raises(OSError):
            h.put({"metric": 1})
        h.close()
    finally:
        srv.close()


# -- (r4-c) precision='bf16-true' coerces loudly -----------------------------

def test_bf16_true_warns_and_coerces():
    from ray_lightning_tpu.core.loop import FitConfig

    with pytest.warns(UserWarning, match="bf16-true"):
        cfg = FitConfig(precision="bf16-true")
    assert cfg.precision == "bf16"


def test_bf16_mixed_silent():
    import warnings

    from ray_lightning_tpu.core.loop import FitConfig

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        cfg = FitConfig(precision="bf16-mixed")
    assert cfg.precision == "bf16"


# -- (r4-d) resume reconciles checkpoint dtypes with this run's policy -------

def test_resume_casts_stale_optimizer_dtype(tmp_path):
    """A checkpoint whose optimizer-state leaves carry a different dtype
    (e.g. written before a mu_dtype policy change) must restore onto the
    CURRENT run's template dtypes, not leak the old dtype into the new
    step function."""
    from ray_lightning_tpu.core.loop import FitConfig, run_fit
    from ray_lightning_tpu.utils.state_stream import (
        state_stream_to_file, to_state_stream,
    )

    x = np.random.default_rng(0).standard_normal((16, 32)).astype(np.float32)
    cfg = FitConfig(max_epochs=1, seed=0, default_root_dir=str(tmp_path))
    module = BoringModel()
    run_fit(module, FixedDataModule(x, batch_size=8), cfg, callbacks=[])
    state = jax.device_get(module.trainer.state)

    # Forge a stale-dtype checkpoint: every float leaf widened to f64
    # (stands in for any dtype-policy skew, incl. f32<->bf16 momentum).
    stale = jax.tree_util.tree_map(
        lambda a: a.astype(np.float64)
        if hasattr(a, "dtype") and a.dtype == np.float32 else a,
        state,
    )
    path = str(tmp_path / "stale.ckpt")
    state_stream_to_file(
        to_state_stream({"state": stale, "epoch": 0, "global_step": 2,
                         "micro_step": 2, "callback_metrics": {}}), path)

    cfg2 = FitConfig(max_epochs=2, seed=0, default_root_dir=str(tmp_path),
                     resume_from_checkpoint=path)
    module2 = BoringModel()
    run_fit(module2, FixedDataModule(x, batch_size=8), cfg2, callbacks=[])
    resumed = jax.device_get(module2.trainer.state)
    leaves_t = jax.tree_util.tree_leaves(state)
    leaves_r = jax.tree_util.tree_leaves(resumed)
    for a, b in zip(leaves_t, leaves_r):
        if hasattr(a, "dtype"):
            assert a.dtype == b.dtype, (a.dtype, b.dtype)


# -- (r5-a) EMA state_dict survives sharded (multi-host-style) shadows -------

def test_ema_state_dict_replicates_sharded_shadow(tmp_path):
    """swap_at_end=False must ship the shadow host-side even when it
    inherits a ZeRO-3 sharding: the gather goes through an identity jit
    with replicated out_shardings (the _gathered_state discipline), not
    a bare device_get that raises on non-addressable arrays."""
    from ray_lightning_tpu.core.callbacks import ExponentialMovingAverage
    from ray_lightning_tpu.parallel.strategies import LocalStrategy

    x = np.random.default_rng(0).standard_normal((32, 256)).astype(
        np.float32)
    ema = ExponentialMovingAverage(decay=0.5, swap_at_end=False)
    trainer = Trainer(
        strategy=LocalStrategy(mesh_axes={"data": 8}, zero_stage=3),
        max_epochs=2, default_root_dir=str(tmp_path),
        enable_checkpointing=False, callbacks=[ema],
    )
    module = BoringModel(in_dim=256, out_dim=128, lr=0.1)
    trainer.fit(module, FixedDataModule(x, batch_size=16))
    # Driver-side callback carries the host shadow after the round-trip.
    shadow = trainer.callbacks[-1].ema_params
    assert shadow is not None
    for leaf in jax.tree_util.tree_leaves(shadow):
        assert isinstance(leaf, np.ndarray)
        assert np.isfinite(leaf).all()
    # Trained params were NOT swapped (swap_at_end=False).
    assert trainer.state is not None


def test_host_copy_replicates_before_get():
    """The shared replicate-then-get helper must reassemble sharded
    trees exactly, and its jitted identity is cached per mesh (no
    re-trace per checkpoint)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_lightning_tpu.core.callbacks import _host_copy
    from ray_lightning_tpu.parallel import sharding as shardlib
    from ray_lightning_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec({"data": 8}))
    want = np.arange(64, dtype=np.float32).reshape(8, 8)
    sharded = jax.device_put(want, NamedSharding(mesh, P("data")))
    out = _host_copy({"w": sharded}, mesh)
    assert isinstance(out["w"], np.ndarray)
    np.testing.assert_array_equal(out["w"], want)
    # The replicate jit itself gathers a sharded tree to a replicated
    # one (the multi-host path), and is one cached object per mesh.
    repl = shardlib._replicate_fn(mesh)({"w": sharded})
    assert repl["w"].sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(repl["w"]), want)
    assert shardlib._replicate_fn(mesh) is shardlib._replicate_fn(mesh)


# -- (r5-b) the epoch-end accumulation flush enters the EMA shadow -----------

def test_epoch_end_flush_updates_ema(tmp_path):
    """5 batches at accumulate_grad_batches=2: the epoch ends on a
    partial window, the flush steps the optimizer — and the EMA shadow
    must observe that final step (global_step=3), not stop at 2."""
    from ray_lightning_tpu.core.callbacks import (
        Callback, ExponentialMovingAverage,
    )

    class StepSpy(Callback):
        def __init__(self):
            self.steps_seen = []
            self.flush_steps = []

        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            self.steps_seen.append(trainer.global_step)

        def on_accumulation_flush(self, trainer, module, logs, batch_idx):
            self.flush_steps.append(trainer.global_step)

    x = np.random.default_rng(1).standard_normal((40, 32)).astype(
        np.float32)
    ema = ExponentialMovingAverage(decay=0.5, swap_at_end=False)
    spy = StepSpy()
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=1,
        accumulate_grad_batches=2, default_root_dir=str(tmp_path),
        enable_checkpointing=False, callbacks=[ema, spy],
    )
    module = BoringModel(lr=0.1)
    trainer.fit(module, FixedDataModule(x, batch_size=8))
    assert trainer.global_step == 3  # 2 full windows + 1 flush
    # Batch-cadence hooks saw exactly the 5 micro-batches (no
    # double-fire), and the dedicated flush hook saw the final step...
    assert len(spy.steps_seen) == 5 and spy.steps_seen[-1] == 2
    assert spy.flush_steps == [3]
    # ...so the shadow's last update is the flushed optimizer step.
    assert trainer.callbacks[0]._last_step == 3
    # And the shadow really reflects post-flush params: it must differ
    # from the params (decay<1 lag) but be finite and close.
    shadow = trainer.callbacks[0].ema_params
    for s, p in zip(
        jax.tree_util.tree_leaves(jax.device_get(shadow)),
        jax.tree_util.tree_leaves(trainer.params),
    ):
        assert np.isfinite(s).all()


# -- (r5-c) steady-state async checkpointing stays async ---------------------

def test_prune_only_flushes_inflight_deletions(tmp_path):
    """save_top_k=1 steady state: the doomed (previous-epoch) file
    finished writing long ago, so _prune must NOT join the writer —
    joining every epoch made the async path synchronous again."""
    from ray_lightning_tpu.core.callbacks import ModelCheckpoint

    class FakeTrainer:
        current_epoch = 0
        global_step = 1
        is_global_zero = True
        callback_metrics = {}
        default_root_dir = "."

        def __init__(self):
            self.flushes = 0
            self.pending = set()
            self.saved = []

        def save_checkpoint(self, path, async_write=False):
            self.saved.append(path)
            open(path, "wb").close()

        def flush_checkpoints(self):
            self.flushes += 1
            self.pending.clear()

        def checkpoint_write_pending(self, path):
            return path in self.pending

    cb = ModelCheckpoint(
        dirpath=str(tmp_path), monitor=None, save_top_k=1,
        async_write=True, filename="e{epoch}",
    )
    t = FakeTrainer()
    # Epochs 0-3, writes complete instantly (pending always empty):
    for epoch in range(4):
        t.current_epoch = epoch
        t.global_step = epoch + 1
        cb.on_train_epoch_end(t, None)
    assert t.flushes == 0  # never joined — fully async steady state
    assert len(cb._saved) == 1

    # A doomed path still in flight DOES force the join.
    t.current_epoch, t.global_step = 4, 5
    t.pending = {cb._saved[0][1]}  # the file about to be pruned
    cb.on_train_epoch_end(t, None)
    assert t.flushes == 1


def test_loopcontext_tracks_pending_writes(tmp_path):
    """checkpoint_write_pending reflects the enqueued/finished state of
    each async write."""
    from ray_lightning_tpu.core.loop import FitConfig, LoopContext

    ctx = LoopContext(FitConfig(), 0, 1)
    ctx.state = {"w": np.zeros(2, np.float32)}
    path = str(tmp_path / "a.ckpt")
    assert ctx.checkpoint_write_pending(path) is False  # no writer yet
    ctx.save_checkpoint(path, async_write=True)
    ctx.flush_checkpoints()
    assert ctx.checkpoint_write_pending(path) is False  # write done
    assert os.path.exists(path)
    ctx.close_checkpoint_writer()


# -- (r5-d) kernel probe retries are bounded ---------------------------------

def test_kernel_probe_caches_false_after_repeated_identical_failures(
    monkeypatch,
):
    from ray_lightning_tpu.ops import kernel_probe

    monkeypatch.setattr(kernel_probe, "_interpret", lambda: False)
    monkeypatch.setattr(kernel_probe, "_CACHE", {})
    monkeypatch.setattr(kernel_probe, "_FAILURES", {})
    calls = []

    def probe():
        calls.append(1)
        raise ValueError("unlisted permanent breakage")

    key = ("test-family", 1)
    with pytest.warns(UserWarning):
        for _ in range(5):
            assert kernel_probe.kernel_available(key, probe) is False
    # Probe ran exactly the retry budget, then False was cached.
    assert len(calls) == kernel_probe._MAX_IDENTICAL_FAILURES
    assert kernel_probe._CACHE[key] is False


def test_kernel_probe_changing_errors_reset_the_retry_count(monkeypatch):
    from ray_lightning_tpu.ops import kernel_probe

    monkeypatch.setattr(kernel_probe, "_interpret", lambda: False)
    monkeypatch.setattr(kernel_probe, "_CACHE", {})
    monkeypatch.setattr(kernel_probe, "_FAILURES", {})
    msgs = iter(["a", "b", "a", "b", "a", "b"])
    calls = []

    def probe():
        calls.append(1)
        raise ValueError(next(msgs))

    key = ("test-family", 2)
    with pytest.warns(UserWarning):
        for _ in range(6):
            kernel_probe.kernel_available(key, probe)
    # Alternating messages never hit the identical-failure budget.
    assert len(calls) == 6
    assert key not in kernel_probe._CACHE


def test_kernel_probe_success_still_cached_once(monkeypatch):
    from ray_lightning_tpu.ops import kernel_probe

    monkeypatch.setattr(kernel_probe, "_interpret", lambda: False)
    monkeypatch.setattr(kernel_probe, "_CACHE", {})
    calls = []

    def probe():
        calls.append(1)

    key = ("test-family", 3)
    assert kernel_probe.kernel_available(key, probe) is True
    assert kernel_probe.kernel_available(key, probe) is True
    assert len(calls) == 1


# -- (r5-e) concurrent tuner fail-fast ---------------------------------------

def test_concurrent_tuner_fails_fast_and_cancels_unstarted():
    """raise_on_trial_error=True in concurrent mode: the first failure
    must cancel every not-yet-started trial instead of waiting for the
    whole sample budget."""
    import time as _time

    from ray_lightning_tpu.tuning import tune_run
    from ray_lightning_tpu.tuning.search import grid_search

    started = []

    def trainable(config):
        started.append(config["idx"])
        if config["idx"] == 0:
            raise RuntimeError("boom")
        _time.sleep(0.4)

    with pytest.raises(RuntimeError, match="boom"):
        tune_run(
            trainable,
            {"idx": grid_search([0, 1, 2, 3, 4, 5])},
            metric="loss",
            raise_on_trial_error=True,
            max_concurrent_trials=2,
            verbose=False,
        )
    # Only the two pool slots ever started; trials 2..5 were cancelled
    # before launch (the old path ran all six to completion).
    assert len(started) <= 3
