"""Regression tests for the round-1/round-2 advisor findings (VERDICT r3
weak #4-5): agent-RPC retry, segment release, crc32c fallback, partial
accumulation-window flush, lr/optimizer-step conventions.
"""

import os

import jax
import numpy as np
import optax
import pytest

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.parallel.strategies import LocalStrategy

from test_trainer_features import FixedDataModule


# -- (r1-a) agent RPC retry before declaring death ---------------------------

class _FlakyClient:
    """AgentClient stand-in: fails transiently N times, then answers."""

    def __init__(self, failures, answer=None, exc=ConnectionError):
        self.failures = failures
        self.answer = answer
        self.exc = exc
        self.calls = 0

    def poll(self, pid):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.exc("transient")
        return self.answer


def _handle(client):
    from ray_lightning_tpu.cluster.agent import _RemoteProcHandle

    h = _RemoteProcHandle.__new__(_RemoteProcHandle)
    h._client = client
    h.pid = 123
    h.returncode = None
    return h


def test_poll_survives_transient_rpc_failure():
    """Two dropped RPCs then a healthy answer: the child must still read
    as ALIVE (None), not dead — a spurious -1 triggers a full elastic
    respawn upstream."""
    h = _handle(_FlakyClient(failures=2, answer=None))
    assert h.poll() is None
    assert h.returncode is None


def test_poll_declares_death_after_retry_budget():
    client = _FlakyClient(failures=99)
    h = _handle(client)
    assert h.poll() == -1
    assert client.calls == 3  # the full retry budget was spent


def test_poll_trusts_structured_agent_error():
    """A structured AgentError reply (unknown pid) is deterministic — no
    retries, immediate death verdict."""
    from ray_lightning_tpu.cluster.agent import AgentError

    client = _FlakyClient(failures=99, exc=AgentError)
    h = _handle(client)
    assert h.poll() == -1
    assert client.calls == 1


# -- (r1-b) segment release per fit ------------------------------------------

def test_objectref_release_reclaims_segment(tmp_path):
    from ray_lightning_tpu.cluster.backend import LocalBackend

    be = LocalBackend(min_segment_bytes=0)  # force segment spill
    try:
        ref = be.put({"blob": b"x" * 4096})
        path = ref._segment_path
        assert path is not None and os.path.exists(path)
        ref.release()
        assert not os.path.exists(path)
        ref.release()  # idempotent
    finally:
        be.shutdown()


def test_repeated_fits_do_not_accumulate_segments(tmp_path, monkeypatch):
    """The PBT pattern: many fits on one strategy/backend must not leak
    tmpfs segments (task payloads are released per fit)."""
    from ray_lightning_tpu.cluster.backend import LocalBackend
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    x = np.random.default_rng(0).standard_normal((16, 32)).astype(np.float32)
    # A caller-OWNED backend spans trainers (the PBT pattern): strategy
    # teardown must not shut it down, so leaked segments would pile up.
    be = LocalBackend(min_segment_bytes=0)
    try:
        live = []
        for _ in range(2):
            trainer = Trainer(
                strategy=RayStrategy(num_workers=1, backend=be),
                max_epochs=1, default_root_dir=str(tmp_path),
                enable_checkpointing=False,
            )
            trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
            live.append(
                sum(1 for p in be._store._paths if os.path.exists(p))
            )
        assert live[1] <= live[0]
        assert live[1] == 0  # every task payload was released
    finally:
        be.shutdown()


# -- (r1-c) crc32c software fallback -----------------------------------------

def test_crc32c_python_fallback_vector():
    from ray_lightning_tpu.native import _crc32c_py

    # RFC 3720 test vector for CRC32C (Castagnoli).
    assert _crc32c_py(b"123456789") == 0xE3069283
    # Seed chaining: crc(a+b) == crc(b, crc(a)).
    a, b = b"hello ", b"world"
    assert _crc32c_py(a + b) == _crc32c_py(b, _crc32c_py(a))


def test_crc32c_entrypoint_never_raises(monkeypatch):
    """crc32c() must work with the native library absent (pure-Python
    deployment), and agree with the native result when present."""
    import ray_lightning_tpu.native as native

    want = native._crc32c_py(b"123456789")
    if native.native_available():
        assert native.crc32c(b"123456789") == want
    monkeypatch.setattr(native, "_load", lambda: None)
    assert native.crc32c(b"123456789") == want


# -- (r2-a) partial accumulation window flushes at epoch end -----------------

def test_accum_flush_unit():
    """_build_accum_flush applies exactly one inner update from the mean
    of the accumulated micro-grads and resets the window."""
    from ray_lightning_tpu.core.loop import _build_accum_flush
    from ray_lightning_tpu.core.module import TrainState

    inner = optax.sgd(0.5)
    tx = optax.MultiSteps(inner, every_k_schedule=3)
    params = {"w": np.ones(4, np.float32)}
    state = TrainState.create(params, tx)
    g1 = {"w": np.full(4, 2.0, np.float32)}
    g2 = {"w": np.full(4, 4.0, np.float32)}
    for g in (g1, g2):  # two micro-grads of a 3-window
        updates, new_opt = tx.update(g, state.opt_state, state.params)
        state = TrainState(
            optax.apply_updates(state.params, updates), new_opt, state.step
        )
    assert int(state.opt_state.mini_step) == 2
    np.testing.assert_allclose(state.params["w"], 1.0)  # not applied yet

    flush = _build_accum_flush(inner, mesh=None, state_shardings=None)
    state = flush(state)
    # mean(2, 4) = 3; sgd(0.5) => 1 - 1.5
    np.testing.assert_allclose(np.asarray(state.params["w"]), -0.5,
                               rtol=1e-6)
    assert int(state.opt_state.mini_step) == 0
    assert int(state.opt_state.gradient_step) == 1


def test_accum_partial_window_flushes_in_fit(tmp_path):
    """3 micro-batches with accumulate=2: the trailing odd batch still
    reaches the params (global_step = 2 optimizer updates, not 1)."""
    x = np.random.default_rng(0).standard_normal((24, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=1, accumulate_grad_batches=2,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
    assert trainer.global_step == 2


def test_accum_flush_keeps_counter_synced_across_epochs(tmp_path):
    """After an epoch-end flush resets MultiSteps' window, the next
    epoch's optimizer-step counting must follow the window position, not
    micro_step % accum.  6 batches/epoch at accum=4, 2 epochs:
    epoch 0 -> update@4 + flush(2) = 2; epoch 1 -> update@(2+2... window
    of 4 spanning the boundary reset) = updates at micro 10 and flush(2)
    = 2 more; total 4."""
    x = np.random.default_rng(0).standard_normal((48, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=2, accumulate_grad_batches=4,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
    assert trainer.micro_step == 12
    assert trainer.global_step == 4


def test_max_steps_exact_after_flush(tmp_path):
    """max_steps counts REAL optimizer updates even when a flush happened
    in an earlier epoch (the desync would stop one update early)."""
    x = np.random.default_rng(0).standard_normal((48, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=10, accumulate_grad_batches=4,
        max_steps=3, default_root_dir=str(tmp_path),
        enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
    assert trainer.global_step == 3


def test_legacy_checkpoint_resume_micro_convention(tmp_path):
    """Pre-convention checkpoints stored the MICRO count in
    'global_step'; resume must map it to optimizer steps, not multiply
    it up."""
    from ray_lightning_tpu.core.loop import FitConfig, run_fit
    from ray_lightning_tpu.utils.state_stream import (
        state_stream_to_file, to_state_stream,
    )

    # Forge a legacy payload: fit once to get a real state, then strip
    # the micro_step key and store micro count under global_step.
    x = np.random.default_rng(0).standard_normal((32, 32)).astype(np.float32)
    cfg = FitConfig(max_epochs=1, accumulate_grad_batches=2, seed=0,
                    default_root_dir=str(tmp_path))
    module = BoringModel()
    run_fit(module, FixedDataModule(x, batch_size=8), cfg, callbacks=[])
    state = module.trainer.state
    legacy = {
        "state": jax.device_get(state),
        "epoch": 0,
        "global_step": 6,  # legacy = MICRO batches (3 optimizer steps)
        "callback_metrics": {},
    }
    path = str(tmp_path / "legacy.ckpt")
    state_stream_to_file(to_state_stream(legacy), path)

    cfg2 = FitConfig(max_epochs=2, accumulate_grad_batches=2, seed=0,
                     default_root_dir=str(tmp_path),
                     resume_from_checkpoint=path)
    module2 = BoringModel()
    res = run_fit(module2, FixedDataModule(x, batch_size=8), cfg2,
                  callbacks=[])
    # Resumed counters: global_step continued from 6//2=3, one more
    # epoch of 4 micro-batches = 2 more updates.
    assert res["global_step"] == 3 + 2
    assert res["micro_step"] == 6 + 4


# -- (r2-b) lr/global_step optimizer-step convention -------------------------

def test_global_step_counts_optimizer_steps(tmp_path):
    """4 micro-batches at accumulate=2 => global_step == 2 (Lightning's
    optimizer-step convention, not the micro-batch count)."""
    x = np.random.default_rng(0).standard_normal((32, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=1, accumulate_grad_batches=2,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
    assert trainer.global_step == 2


def test_logged_lr_is_last_applied(tmp_path):
    """The logged lr belongs to the optimizer step just TAKEN: after k
    updates the last one used schedule(k-1), not schedule(k)."""
    from test_trainer_features import ScheduledBoring

    x = np.random.default_rng(0).standard_normal((24, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=1,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        log_every_n_steps=1,
    )
    trainer.fit(ScheduledBoring(), FixedDataModule(x, batch_size=8))
    assert trainer.global_step == 3
    schedule = optax.linear_schedule(0.1, 0.0, 100)
    assert trainer.callback_metrics["lr"] == pytest.approx(
        float(schedule(2))
    )


# -- (r2-c) dual-convention MFU fields in bench ------------------------------

def test_bench_reports_both_mfu_conventions():
    import bench

    cfg_flops_full = bench.model_flops_per_token(
        bench.GPTConfig.tiny(), attn="full")
    cfg_flops_causal = bench.model_flops_per_token(
        bench.GPTConfig.tiny(), attn="causal")
    assert cfg_flops_causal < cfg_flops_full
    # Attention term is exactly halved; everything else is identical.
    cfg = bench.GPTConfig.tiny()
    attn_full = 3.0 * 4 * cfg.n_layer * cfg.seq_len * cfg.d_model
    assert cfg_flops_full - cfg_flops_causal == pytest.approx(attn_full / 2)
