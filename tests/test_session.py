"""Session singleton tests (≙ reference session.py semantics)."""

import pytest

from ray_lightning_tpu import session as S


@pytest.fixture(autouse=True)
def _clean_session():
    S.shutdown_session()
    yield
    S.shutdown_session()


def test_init_get_shutdown():
    assert not S.is_session_enabled()
    sess = S.init_session(rank=3, queue=None, num_workers=4)
    assert S.is_session_enabled()
    assert S.get_session() is sess
    assert S.get_actor_rank() == 3
    S.shutdown_session()
    assert not S.is_session_enabled()


def test_double_init_raises():
    S.init_session(rank=0)
    with pytest.raises(ValueError, match="already active"):
        S.init_session(rank=1)


def test_get_without_init_raises():
    with pytest.raises(ValueError, match="No TpuTrainingSession"):
        S.get_session()


def test_put_queue_without_queue_raises():
    S.init_session(rank=0, queue=None)
    with pytest.raises(ValueError, match="No queue"):
        S.put_queue({"x": 1})


def test_put_queue_forwards():
    class FakeQueue:
        def __init__(self):
            self.items = []

        def put(self, item):
            self.items.append(item)

    q = FakeQueue()
    S.init_session(rank=0, queue=q)
    S.put_queue({"loss": 0.5})
    assert q.items == [{"loss": 0.5}]
