"""Weight-only int8 decode quantization (net-new): storage halves vs
bf16 (4x vs f32) on the bandwidth-bound decode path, logits stay close,
and the generation API consumes quantized trees transparently.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models import GPT, GPTConfig
from ray_lightning_tpu.models.generate import (
    generate,
    init_kv_cache,
    prefill,
)
from ray_lightning_tpu.models.quant import (
    is_quantized,
    quantize_decode_params,
    resolve_weight,
)


def tiny():
    return GPTConfig(vocab_size=512, n_layer=2, n_head=4, d_model=128,
                     seq_len=128, warmup_steps=2)


def test_per_channel_error_bound():
    """Symmetric int8 with per-output-channel scales: reconstruction
    error is bounded by scale/2 = amax/254 per channel."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 96)).astype(np.float32))
    params = {"blocks": {"qkv_w": w, "qkv_b": jnp.zeros(96)},
              "wte": jnp.asarray(
                  rng.standard_normal((32, 64)).astype(np.float32))}
    q = quantize_decode_params(params, tiny())
    deq = np.asarray(resolve_weight(q["blocks"], "qkv_w", jnp.float32))
    amax = np.abs(np.asarray(w)).max(axis=0)
    assert (np.abs(deq - np.asarray(w)) <= amax / 254 + 1e-7).all()
    # wte is row-quantized.
    deq_wte = np.asarray(q["wte_q8"]).astype(np.float32) * \
        np.asarray(q["wte_sc"])[:, None]
    amax_r = np.abs(np.asarray(params["wte"])).max(axis=1, keepdims=True)
    assert (np.abs(deq_wte - np.asarray(params["wte"]))
            <= amax_r / 254 + 1e-7).all()


def test_quantized_tree_is_4x_smaller():
    params = GPT(tiny()).init_params(jax.random.PRNGKey(0))
    q = quantize_decode_params(jax.device_get(params), tiny())

    def nbytes(tree, pred):
        return sum(
            np.asarray(x).nbytes
            for x in jax.tree_util.tree_leaves(tree) if pred(x)
        )

    big_f32 = nbytes(params, lambda x: np.asarray(x).ndim >= 2
                     and np.asarray(x).size > 10_000)
    big_q = nbytes(q, lambda x: np.asarray(x).dtype == np.int8)
    assert big_q * 3.9 < big_f32  # int8 + small scale arrays vs f32


def test_quantized_decode_logits_close():
    """Prefill logits from the int8 tree stay close to f32: small max
    error and near-total top-1 agreement on a random model."""
    cfg = tiny()
    params = jax.device_get(GPT(cfg).init_params(jax.random.PRNGKey(0)))
    q = quantize_decode_params(params, cfg)
    assert is_quantized(q) and not is_quantized(params)

    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (4, 32)),
        jnp.int32)
    cache = init_kv_cache(cfg, batch=4, total_len=48)
    logits_f, _ = jax.jit(lambda p, t: prefill(cfg, p, cache, t))(
        params, tokens)
    logits_q, _ = jax.jit(lambda p, t: prefill(cfg, p, cache, t))(q, tokens)
    lf, lq = np.asarray(logits_f), np.asarray(logits_q)
    assert np.abs(lf - lq).max() < 0.5 * np.abs(lf).max()
    agree = (lf.argmax(-1) == lq.argmax(-1)).mean()
    assert agree >= 0.75, agree


def test_generate_accepts_quantized_tree():
    cfg = tiny()
    params = jax.device_get(GPT(cfg).init_params(jax.random.PRNGKey(0)))
    q = quantize_decode_params(params, cfg)
    out = generate(GPT(cfg, attn_impl="xla"), q,
                   jnp.ones((2, 4), jnp.int32), max_new_tokens=6)
    out = np.asarray(out)
    assert out.shape == (2, 10)
    assert (out >= 0).all() and (out < cfg.vocab_size).all()
    # Greedy decode from the quantized tree matches the f32 tree on a
    # strong-signal model?  Not guaranteed at near-ties — instead check
    # both decode without error and stay in-vocab (above) and that the
    # quantized continuation equals ITSELF deterministically.
    out2 = np.asarray(generate(GPT(cfg, attn_impl="xla"), q,
                               jnp.ones((2, 4), jnp.int32),
                               max_new_tokens=6))
    np.testing.assert_array_equal(out, out2)


def test_quantized_moe_decode_runs():
    cfg = GPTConfig.tiny_moe(n_experts=4, moe_capacity_factor=4.0)
    params = jax.device_get(GPT(cfg).init_params(jax.random.PRNGKey(0)))
    q = quantize_decode_params(params, cfg)
    assert "moe_in_w_q8" in q["blocks"]
    out = generate(GPT(cfg, attn_impl="xla"), q,
                   jnp.ones((1, 4), jnp.int32), max_new_tokens=4)
    assert np.asarray(out).shape == (1, 8)


def test_quantize_guards():
    cfg = GPTConfig(vocab_size=128, n_layer=1, n_head=2, d_model=64,
                    seq_len=32, lora_rank=2)
    lora_params = jax.device_get(GPT(cfg).init_params(jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="merge_lora"):
        quantize_decode_params(lora_params, cfg)
    plain = jax.device_get(GPT(tiny()).init_params(jax.random.PRNGKey(0)))
    q = quantize_decode_params(plain, tiny())
    with pytest.raises(ValueError, match="already"):
        quantize_decode_params(q, tiny())


def test_fit_rejects_quantized_warm_start(tmp_path):
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.gpt import SyntheticLMDataModule
    from ray_lightning_tpu.parallel.strategies import LocalStrategy

    cfg = tiny()
    model = GPT(cfg)
    model.initial_params = quantize_decode_params(
        jax.device_get(model.init_params(jax.random.PRNGKey(0))), cfg)
    trainer = Trainer(strategy=LocalStrategy(), max_epochs=1,
                      limit_train_batches=1, limit_val_batches=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmp_path))
    with pytest.raises(Exception, match="int8-quantized"):
        trainer.fit(model, SyntheticLMDataModule(cfg, batch_size=8,
                                                 num_batches=1))
