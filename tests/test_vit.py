"""ViT model family: the second transformer workload for the
sharded/TP strategies (net-new; the reference's only large-model example
is pl_bolts ImageGPT, ``ray_ddp_sharded_example.py:62``).

≙ reference test taxonomy (SURVEY §4): weights move under training, the
sharded mesh is numerically a no-op, predictions beat chance on the
synthetic class-conditional data, and checkpoints roundtrip.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import ViT, ViTConfig
from ray_lightning_tpu.models.resnet import CIFARDataModule
from ray_lightning_tpu.parallel.strategies import LocalStrategy


def tiny_vit(**kw):
    cfg = ViTConfig(image_size=16, patch_size=4, n_layer=2, n_head=4,
                    d_model=128, lr=3e-3, warmup_steps=2, **kw)
    return ViT(cfg)


def make_data(**kw):
    kw.setdefault("batch_size", 32)
    kw.setdefault("num_samples", 512)
    kw.setdefault("image_size", 16)
    return CIFARDataModule(**kw)


def make_trainer(**kw):
    kw.setdefault("max_epochs", 1)
    kw.setdefault("enable_checkpointing", False)
    return Trainer(**kw)


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_vit_trains_and_converges():
    tr = make_trainer(max_epochs=3)
    tr.fit(tiny_vit(), make_data())
    assert np.isfinite(tr.callback_metrics["train_loss"])
    assert tr.callback_metrics["val_accuracy"] >= 0.5


def test_vit_sharded_mesh_parity():
    """DP×FSDP×TP mesh must match plain single-axis training numerically
    (the Megatron column/row TP layout is a numeric no-op)."""

    def run(strategy):
        tr = make_trainer(strategy=strategy, limit_train_batches=2,
                          limit_val_batches=1)
        tr.fit(tiny_vit(), make_data())
        return tr

    base = run(LocalStrategy())
    sharded = run(
        LocalStrategy(mesh_axes={"data": 2, "fsdp": 2, "tensor": 2},
                      zero_stage=3)
    )
    assert base.callback_metrics["train_loss"] == pytest.approx(
        sharded.callback_metrics["train_loss"], rel=1e-5
    )


def test_vit_partition_specs_cover_params():
    model = tiny_vit()
    params = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    specs = model.param_partition_specs()
    p_paths = {
        jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    s_paths = {
        jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))[0]
    }
    assert p_paths == s_paths


def test_vit_bf16_remat_forward_finite():
    model = ViT(ViTConfig(image_size=16, patch_size=4, n_layer=2,
                          n_head=4, d_model=128), remat=True)
    model.precision = "bf16"
    params = model.init_params(jax.random.PRNGKey(0))
    x = np.random.default_rng(0).standard_normal(
        (4, 16, 16, 3)).astype(np.float32)
    logits = jax.jit(model.forward)(params, x)
    assert logits.dtype == np.float32  # head output cast back
    assert logits.shape == (4, 10)
    assert np.all(np.isfinite(np.asarray(logits)))


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_vit_checkpoint_roundtrip(tmp_path):
    """Fit → checkpoint → resume on a fresh trainer: the resumed epoch
    continues from the saved weights (≙ reference load_test,
    tests/utils.py:248-253)."""
    dm = make_data()
    tr = make_trainer(max_epochs=1,
                      default_root_dir=str(tmp_path))
    tr.fit(tiny_vit(), dm)
    path = str(tmp_path / "vit.ckpt")
    tr.save_checkpoint(path)

    tr2 = make_trainer(max_epochs=2, default_root_dir=str(tmp_path),
                       resume_from_checkpoint=path)
    tr2.fit(tiny_vit(), dm)
    # Counters continued from the checkpoint: exactly ONE more epoch of
    # optimizer steps on top of the restored count.
    assert tr2.global_step == 2 * tr.global_step
    assert np.isfinite(tr2.callback_metrics["train_loss"])


def test_vit_rejects_bad_geometry():
    with pytest.raises(ValueError, match="patch_size"):
        ViT(ViTConfig(image_size=30, patch_size=4))
    with pytest.raises(ValueError, match="n_head"):
        ViT(ViTConfig(d_model=100, n_head=3))
