"""Tune integration tests (≙ reference ``tests/test_tune.py``).

Covers: trial-count/iteration invariants (≙ ``test_tune.py:42-51``),
checkpoint existence (≙ ``test_tune.py:66-78``), queue-thunk reporting from
remote workers, ASHA early stopping, PBT exploit/explore, search-space
generation.
"""

import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import (
    BoringDataModule,
    BoringModel,
    XORDataModule,
    XORModel,
)
from ray_lightning_tpu.parallel.strategies import LocalStrategy, RayStrategy
from ray_lightning_tpu.tune import (
    TuneReportCallback,
    TuneReportCheckpointCallback,
    get_tune_resources,
)
from ray_lightning_tpu.tuning import (
    ASHAScheduler,
    PopulationBasedTraining,
    choice,
    generate_trials,
    grid_search,
    loguniform,
    tune_run,
    uniform,
)
from ray_lightning_tpu.tuning.search import generate_trials  # noqa: F811


def _train_boring(config, tmp_path, strategy=None, max_epochs=2):
    trainer = Trainer(
        strategy=strategy or LocalStrategy(),
        max_epochs=max_epochs,
        callbacks=[TuneReportCallback(on="validation_end")],
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        log_every_n_steps=1,
    )
    trainer.fit(BoringModel(lr=config["lr"]), BoringDataModule())


class TestSearchSpace:
    def test_grid_cross_product_times_samples(self):
        space = {"a": grid_search([1, 2, 3]), "b": choice([10, 20]), "c": 5}
        trials = generate_trials(space, num_samples=2, seed=0)
        assert len(trials) == 6  # 3 grid × 2 samples
        assert all(t["c"] == 5 for t in trials)
        assert {t["a"] for t in trials} == {1, 2, 3}

    def test_loguniform_range(self):
        space = {"lr": loguniform(1e-5, 1e-1)}
        trials = generate_trials(space, num_samples=50, seed=1)
        vals = [t["lr"] for t in trials]
        assert all(1e-5 <= v <= 1e-1 for v in vals)
        assert min(vals) < 1e-3 < max(vals)  # spans decades

    def test_uniform(self):
        vals = [t["x"] for t in generate_trials({"x": uniform(0, 1)}, 20)]
        assert all(0 <= v <= 1 for v in vals)


class TestTuneRun:
    def test_iteration_invariant(self, tmp_path):
        # ≙ reference: training_iteration == max_epochs (test_tune.py:50-51)
        max_epochs = 3
        analysis = tune_run(
            lambda cfg: _train_boring(cfg, tmp_path, max_epochs=max_epochs),
            config={"lr": grid_search([0.05, 0.1])},
            metric="val_loss",
            mode="min",
            local_dir=str(tmp_path / "tune"),
            verbose=False,
        )
        assert len(analysis.trials) == 2
        for t in analysis.trials:
            assert t.status == "TERMINATED", t.error
            assert t.training_iteration == max_epochs
        assert analysis.best_config["lr"] in (0.05, 0.1)
        assert np.isfinite(analysis.best_result["val_loss"])

    def test_report_thunks_cross_queue_from_remote_worker(self, tmp_path):
        # The full nested-distribution path of SURVEY §3.3: trial driver →
        # worker actor → queue thunk → trial session.
        analysis = tune_run(
            lambda cfg: _train_boring(
                cfg, tmp_path, strategy=RayStrategy(num_workers=1)
            ),
            config={"lr": grid_search([0.1])},
            metric="val_loss",
            mode="min",
            local_dir=str(tmp_path / "tune"),
            verbose=False,
        )
        t = analysis.trials[0]
        assert t.status == "TERMINATED", t.error
        assert t.training_iteration == 2

    def test_checkpoint_callback_writes_trial_dir(self, tmp_path):
        def trainable(config):
            trainer = Trainer(
                strategy=LocalStrategy(),
                max_epochs=2,
                callbacks=[
                    TuneReportCheckpointCallback(
                        metrics={"loss": "val_loss"}, filename="ckpt"
                    )
                ],
                default_root_dir=str(tmp_path),
                enable_checkpointing=False,
            )
            trainer.fit(BoringModel(lr=config["lr"]), BoringDataModule())

        local_dir = str(tmp_path / "tune")
        analysis = tune_run(
            trainable,
            config={"lr": grid_search([0.1])},
            metric="loss",
            mode="min",
            local_dir=local_dir,
            verbose=False,
        )
        t = analysis.trials[0]
        assert t.status == "TERMINATED", t.error
        # ≙ reference checkpoint-existence assertion (test_tune.py:66-78)
        ckpts = []
        for root, _, files in os.walk(os.path.join(local_dir, t.trial_id)):
            ckpts += [os.path.join(root, f) for f in files if f == "ckpt"]
        assert ckpts, "no checkpoint written into the trial dir"
        # The checkpoint is a loadable state stream.
        from ray_lightning_tpu.utils.state_stream import load_state_stream

        payload = load_state_stream(open(ckpts[0], "rb").read())
        assert "state" in payload and payload["global_step"] > 0

    def test_asha_stops_bad_trials(self, tmp_path):
        # lr=0 never improves; ASHA must stop it before max_epochs while
        # a good lr runs to completion.
        analysis = tune_run(
            lambda cfg: _train_boring(cfg, tmp_path, max_epochs=9),
            config={"lr": grid_search([0.2, 0.0, 0.0, 0.0])},
            scheduler=ASHAScheduler(
                metric="val_loss", mode="min", max_t=9, grace_period=1,
                reduction_factor=3,
            ),
            metric="val_loss",
            mode="min",
            local_dir=str(tmp_path / "tune"),
            verbose=False,
        )
        statuses = {t.config["lr"]: t.status for t in analysis.trials}
        iters = [t.training_iteration for t in analysis.trials
                 if t.config["lr"] == 0.0]
        assert statuses[0.2] == "TERMINATED"
        assert any(i < 9 for i in iters), f"ASHA never stopped a trial: {iters}"
        assert analysis.best_config["lr"] == 0.2

    def test_trial_error_recorded(self, tmp_path):
        def bad(config):
            raise RuntimeError("trainable exploded")

        analysis = tune_run(
            bad, config={"lr": grid_search([0.1])}, verbose=False,
            local_dir=str(tmp_path / "tune"),
        )
        t = analysis.trials[0]
        assert t.status == "ERROR"
        assert "trainable exploded" in t.error

    def test_pbt_mutates_from_best(self, tmp_path):
        pbt = PopulationBasedTraining(
            metric="val_loss", mode="min", perturbation_interval=1,
            hyperparam_mutations={"lr": [0.05, 0.1, 0.2]},
        )
        analysis = tune_run(
            lambda cfg: _train_boring(cfg, tmp_path, max_epochs=2),
            config={"lr": uniform(0.05, 0.2)},
            num_samples=5,
            scheduler=pbt,
            metric="val_loss",
            mode="min",
            local_dir=str(tmp_path / "tune"),
            verbose=False,
        )
        assert len(analysis.trials) == 5
        assert all(t.status in ("TERMINATED", "STOPPED")
                   for t in analysis.trials)

    def test_pbt_exploited_trial_restores_donor_checkpoint(self, tmp_path):
        """The exploit half of PBT (VERDICT r4 missing #2): trial 1 must
        START from trial 0's checkpointed weights — its first report
        continues the donor's loss trajectory instead of from-scratch."""
        from ray_lightning_tpu.tuning import get_checkpoint

        seen_restores = []

        def trainable(config):
            restore = get_checkpoint()
            seen_restores.append(restore)
            trainer = Trainer(
                strategy=LocalStrategy(),
                # Donor trains epochs 0-1; the exploited trial resumes at
                # epoch 2 and trains two more.
                max_epochs=2 if restore is None else 4,
                callbacks=[TuneReportCheckpointCallback(on="validation_end")],
                default_root_dir=str(tmp_path),
                enable_checkpointing=False,
                log_every_n_steps=1,
                resume_from_checkpoint=restore,
            )
            trainer.fit(BoringModel(lr=config["lr"]), BoringDataModule())

        pbt = PopulationBasedTraining(
            metric="val_loss", mode="min", perturbation_interval=100,
            hyperparam_mutations={"lr": [0.1]},
        )
        analysis = tune_run(
            trainable,
            config={"lr": grid_search([0.1])},
            num_samples=2,
            scheduler=pbt,
            metric="val_loss",
            mode="min",
            local_dir=str(tmp_path / "tune"),
            verbose=False,
        )
        donor, exploited = analysis.trials
        assert donor.status == "TERMINATED", donor.error
        assert exploited.status == "TERMINATED", exploited.error
        # Trial 0 started fresh; trial 1 got the donor's checkpoint FILE.
        assert seen_restores[0] is None
        assert seen_restores[1] is not None
        assert os.path.exists(seen_restores[1])
        assert "trial_0000" in seen_restores[1]
        # The exploited trial's FIRST report continues the donor's
        # trajectory: better than the donor's own from-scratch first
        # epoch (deterministic data/seed; identical lr).
        first_exploited = exploited.reports[0]["val_loss"]
        first_fresh = donor.reports[0]["val_loss"]
        last_donor = donor.reports[-1]["val_loss"]
        assert first_exploited < first_fresh
        assert first_exploited <= last_donor * 1.05


def test_get_tune_resources_shape():
    # ≙ reference "+1 CPU head bundle" contract (tune.py:50-56, README:184)
    res = get_tune_resources(num_workers=2, num_cpus_per_worker=3,
                             use_tpu=True)
    assert res["strategy"] == "PACK"
    assert res["bundles"][0] == {"CPU": 1}
    assert res["bundles"][1] == {"CPU": 3, "TPU": 4}
    assert len(res["bundles"]) == 3


class TestSchedulerValidation:
    def test_asha_rejects_bad_params(self):
        with pytest.raises(ValueError):
            ASHAScheduler(grace_period=0)
        with pytest.raises(ValueError):
            ASHAScheduler(reduction_factor=1)

    def test_pbt_quantile_zero_never_stops(self):
        pbt = PopulationBasedTraining(metric="m", quantile_fraction=0.0,
                                      perturbation_interval=1)
        for i in range(10):
            assert pbt.on_result(f"t{i}", {"m": float(i),
                                           "training_iteration": 1}) == "CONTINUE"

    def test_report_callback_rejects_bad_hook(self):
        with pytest.raises(ValueError, match="not supported"):
            TuneReportCallback(on="validation_epoch_end")


def test_concurrent_trials_overlap_and_isolate(tmp_path):
    """max_concurrent_trials=N really overlaps trial drivers, and the
    thread-local trial session routes each report to ITS trial."""
    import threading
    import time as _time

    from ray_lightning_tpu.tuning import report

    lock = threading.Lock()
    active = []
    peak = [0]

    def trainable(cfg):
        with lock:
            active.append(1)
            peak[0] = max(peak[0], len(active))
        _time.sleep(0.3)
        report(marker=float(cfg["x"]))
        with lock:
            active.pop()

    analysis = tune_run(
        trainable,
        {"x": grid_search([1, 2, 3, 4])},
        metric="marker",
        mode="min",
        local_dir=str(tmp_path / "tune"),
        verbose=False,
        max_concurrent_trials=4,
    )
    assert peak[0] > 1, "trials never overlapped"
    assert len(analysis.trials) == 4
    for t in analysis.trials:
        assert t.status == "TERMINATED", t.error
        assert t.last_result["marker"] == float(t.config["x"])
    assert analysis.best_result["marker"] == 1.0


def _concurrent_real_fits_body(tmp_path: str) -> None:
    """The real-fit concurrency assertion — module-level so the
    subprocess harness below can import and run it in a FRESH
    interpreter."""
    analysis = tune_run(
        lambda cfg: _train_boring(cfg, pathlib.Path(tmp_path),
                                  max_epochs=2),
        config={"lr": grid_search([0.05, 0.1])},
        metric="val_loss",
        mode="min",
        local_dir=os.path.join(tmp_path, "tune"),
        verbose=False,
        max_concurrent_trials=2,
    )
    assert len(analysis.trials) == 2
    for t in analysis.trials:
        assert t.status == "TERMINATED", t.error
        assert t.training_iteration == 2


@pytest.mark.slow
def test_concurrent_trials_with_real_fits(tmp_path):
    """Two LocalStrategy fits in concurrent trial threads: jax dispatch,
    queue-less reporting, and per-thread sessions must not cross wires.

    QUARANTINE (round 11): run in a fresh subprocess with a hard
    timeout.  In-process this test wedged ONLY under whole-suite state
    (passes alone in 4s and in every subset bisected — suspicion is
    accumulated interpreter state after ~450 tests: compile-cache
    memory, leaked helper threads, monkeypatched globals).  A fresh
    interpreter is the isolation, the timeout turns any recurrence into
    a loud failure instead of a tier-1 hang.

    HARD QUARANTINE (round 16): the round-15 retry-once harness is
    retired.  The wedge reproduces ~2/3 of runs in a FRESH subprocess
    on this loaded 2-core container (scheduler starvation, not
    interpreter state), so a worst-case tier-1 run paid two 180s
    timeouts (~360s) out of the 870s budget for a flake that says
    nothing about the code under test.  The test is now ``slow``-marked
    (out of tier-1) and runs ONE attempt — on hardware sessions and
    explicit ``-m slow`` runs, where the box has the cores the test
    assumes.  ``tools/repro_tune_wedge.py`` pins the repro (N fresh
    subprocess attempts, wedge-frequency report) so the flake stays
    measurable without taxing every suite run."""
    script = (
        "import importlib.util, sys\n"
        "spec = importlib.util.spec_from_file_location('t', sys.argv[1])\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(mod)\n"
        "mod._concurrent_real_fits_body(sys.argv[2])\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script,
             os.path.abspath(__file__), str(tmp_path / "run")],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ),
        )
    except subprocess.TimeoutExpired as e:
        pytest.fail(
            "concurrent-trials subprocess TIMED OUT after "
            f"{e.timeout}s (the known concurrent-dispatch wedge — "
            "see tools/repro_tune_wedge.py)\nstdout:\n"
            f"{e.stdout}\nstderr:\n{e.stderr}"
        )
    assert proc.returncode == 0, (
        f"concurrent-trials subprocess failed (rc={proc.returncode})"
        f"\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )


def test_pbt_restore_path_resolves_directory_checkpoints(tmp_path):
    """A trainable that uses the bare checkpoint_dir() API (no callback)
    records a DIRECTORY as its last checkpoint; the exploited trial must
    receive a restorable FILE inside it, never the raw dir."""
    from ray_lightning_tpu.tuning import checkpoint_dir, get_checkpoint, report

    seen = []

    def trainable(cfg):
        seen.append(get_checkpoint())
        d = checkpoint_dir(step=1)
        with open(os.path.join(d, "weights.bin"), "wb") as f:
            f.write(b"donor-weights")
        report(loss=1.0)

    pbt = PopulationBasedTraining(metric="loss", mode="min",
                                  perturbation_interval=100)
    tune_run(
        trainable, config={"lr": grid_search([0.1])}, num_samples=2,
        scheduler=pbt, metric="loss", mode="min",
        local_dir=str(tmp_path / "tune"), verbose=False,
    )
    assert seen[0] is None
    assert seen[1] is not None and os.path.isfile(seen[1])
    assert open(seen[1], "rb").read() == b"donor-weights"


def test_report_from_helper_thread_single_trial(tmp_path):
    """Sequential mode keeps the old global-session affordance: a helper
    thread inside the trainable can still report into the sole active
    session (thread-locality only bites under real concurrency)."""
    import threading

    from ray_lightning_tpu.tuning import report

    def trainable(cfg):
        err = []

        def helper():
            try:
                report(side=123.0)
            except Exception as e:  # noqa: BLE001
                err.append(e)

        th = threading.Thread(target=helper)
        th.start()
        th.join()
        assert not err, err

    an = tune_run(
        trainable, config={"lr": grid_search([0.1])}, metric="side",
        mode="min", local_dir=str(tmp_path / "tune"), verbose=False,
    )
    assert an.trials[0].last_result["side"] == 123.0


def test_concurrent_experiment_rejects_foreign_thread_after_drain(tmp_path):
    """Under max_concurrent_trials>1, a foreign-thread report must raise
    even after the pool drains to a single surviving trial — silently
    attributing it to the survivor would corrupt the scheduler."""
    import threading
    import time as _time

    from ray_lightning_tpu.tuning import report

    outcome = {}
    release = threading.Event()

    def fast(cfg):
        report(m=1.0)

    def slow(cfg):
        release.wait(timeout=10)  # by now the fast trial has finished

        def foreign():
            try:
                report(m=2.0)
                outcome["raised"] = False
            except ValueError:
                outcome["raised"] = True

        th = threading.Thread(target=foreign)
        th.start()
        th.join()
        report(m=0.5)

    def trainable(cfg):
        if cfg["kind"] == "fast":
            fast(cfg)
            release.set()
        else:
            slow(cfg)

    an = tune_run(
        trainable, {"kind": grid_search(["fast", "slow"])}, metric="m",
        mode="min", local_dir=str(tmp_path / "tune"), verbose=False,
        max_concurrent_trials=2,
    )
    assert outcome.get("raised") is True
    # The slow trial's own-thread report still worked.
    slow_trial = next(t for t in an.trials if t.config["kind"] == "slow")
    assert slow_trial.last_result["m"] == 0.5


def test_resolve_ckpt_dir_tree_hands_over_directory(tmp_path):
    """A donor checkpoint that is a directory TREE (e.g. an Orbax save)
    resolves to the directory itself, not None."""
    from ray_lightning_tpu.tuning import checkpoint_dir, get_checkpoint, report

    seen = []

    def trainable(cfg):
        seen.append(get_checkpoint())
        d = checkpoint_dir(step=1)
        sub = os.path.join(d, "orbax_tree", "0")
        os.makedirs(sub, exist_ok=True)
        with open(os.path.join(sub, "arr.bin"), "wb") as f:
            f.write(b"x")
        report(loss=1.0)

    pbt = PopulationBasedTraining(metric="loss", mode="min",
                                  perturbation_interval=100)
    tune_run(trainable, {"lr": grid_search([0.1])}, num_samples=2,
             scheduler=pbt, metric="loss", mode="min",
             local_dir=str(tmp_path / "tune"), verbose=False)
    assert seen[0] is None
    assert seen[1] is not None and os.path.isdir(seen[1])
