"""Trainer-surface features: lr schedules, gradient accumulation, CSV
logging, predict/eval hardening, shard_map x ZeRO/TP refusal.

≙ the Lightning-inherited surface the reference gets for free
(``accumulate_grad_batches``, loggers, ``configure_optimizers`` returning
scheduler info) — here first-class framework features (VERDICT r1 items
7-10).
"""

import csv
import types

import jax
import numpy as np
import optax
import pytest

from ray_lightning_tpu.core.callbacks import CSVLogger
from ray_lightning_tpu.core.data import ArrayDataset, NumpyLoader, TpuDataModule
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import BoringDataModule, BoringModel
from ray_lightning_tpu.models.gpt import GPT, GPTConfig, SyntheticLMDataModule
from ray_lightning_tpu.parallel.strategies import LocalStrategy


class ScheduledBoring(BoringModel):
    """BoringModel whose configure_optimizers returns (tx, lr_schedule)."""

    def configure_optimizers(self):
        schedule = optax.linear_schedule(0.1, 0.0, 100)
        return optax.sgd(schedule), schedule


class FixedDataModule(TpuDataModule):
    """Deterministic rows so two runs see byte-identical data."""

    def __init__(self, x: np.ndarray, batch_size: int):
        super().__init__()
        self.x = x
        self.batch_size = batch_size

    def train_dataloader(self):
        return NumpyLoader(
            ArrayDataset(x=self.x), batch_size=self.batch_size,
            shard_index=self.shard_index, num_shards=self.num_shards,
        )


def test_lr_schedule_is_logged(tmp_path):
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=1, limit_train_batches=2,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
    )
    trainer.fit(ScheduledBoring(), BoringDataModule())
    assert "lr" in trainer.callback_metrics
    # The logged lr is the one the most recent optimizer step APPLIED:
    # update k uses schedule(k-1) (optax counts completed updates).
    expected = float(optax.linear_schedule(0.1, 0.0, 100)(
        trainer.global_step - 1))
    assert trainer.callback_metrics["lr"] == pytest.approx(expected)


def test_grad_accumulation_parity(tmp_path):
    """k micro-steps of batch B must train exactly like 1 step of batch
    k*B for SGD (the VERDICT-specified accumulation contract)."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 32)).astype(np.float32)

    def run(batch_size, accumulate):
        trainer = Trainer(
            strategy=LocalStrategy(), max_epochs=1,
            accumulate_grad_batches=accumulate,
            default_root_dir=str(tmp_path), enable_checkpointing=False,
        )
        trainer.fit(
            BoringModel(), FixedDataModule(x, batch_size=batch_size)
        )
        return trainer.params

    p_micro = run(batch_size=8, accumulate=2)    # 2 micro-steps of 8
    p_full = run(batch_size=16, accumulate=1)    # 1 step of 16
    for a, b in zip(
        jax.tree_util.tree_leaves(p_micro), jax.tree_util.tree_leaves(p_full)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_max_steps_counts_optimizer_steps(tmp_path):
    """max_steps means optimizer steps (Lightning semantics): with
    accumulate_grad_batches=2, max_steps=1 runs TWO micro-batches."""
    x = np.random.default_rng(0).standard_normal((32, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=10, max_steps=1,
        accumulate_grad_batches=2, default_root_dir=str(tmp_path),
        enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
    # Lightning convention: global_step counts OPTIMIZER steps.
    assert trainer.global_step == 1


def test_shard_map_eval_refuses_sharded_params(tmp_path):
    trainer = Trainer(
        strategy=LocalStrategy(
            mode="shard_map", mesh_axes={"data": 4, "tensor": 2}
        ),
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        limit_val_batches=1,
    )
    cfg = GPTConfig.tiny()
    with pytest.raises(ValueError, match="shard_map"):
        trainer.validate(
            GPT(cfg), SyntheticLMDataModule(cfg, batch_size=8, num_batches=1)
        )


def test_csv_logger_writes_curves(tmp_path):
    logger = CSVLogger(dirpath=str(tmp_path / "csv"))
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=2,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        callbacks=[logger],
    )
    trainer.fit(BoringModel(), BoringDataModule())
    assert logger.path is not None
    with open(logger.path, newline="") as f:
        rows = list(csv.DictReader(f))
    assert len(rows) >= 2
    assert "train_loss" in rows[0] and "epoch" in rows[0]
    # Val metrics appear in the header once validation has run.
    assert "val_loss" in rows[-1]
    assert float(rows[-1]["train_loss"]) == pytest.approx(
        trainer.callback_metrics["train_loss"], rel=1e-6
    )
    # Driver-side object holds the rows too (worker->driver round trip).
    assert len(logger.rows) == len(rows)


def test_csv_logger_per_step_rows(tmp_path):
    """log_every_n_steps metrics reach the CSV as per-STEP rows (VERDICT
    r3 weak #6): a 1-epoch run gets a training curve, not one row."""
    logger = CSVLogger(dirpath=str(tmp_path / "csv"))
    x = np.random.default_rng(0).standard_normal((48, 32)).astype(np.float32)
    trainer = Trainer(
        strategy=LocalStrategy(), max_epochs=1, log_every_n_steps=2,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        callbacks=[logger],
    )
    trainer.fit(BoringModel(), FixedDataModule(x, batch_size=8))
    with open(logger.path, newline="") as f:
        rows = list(csv.DictReader(f))
    # 6 batches at cadence 2 => 3 step rows, + 1 epoch-end row.
    assert len(rows) == 6 // 2 + 1
    steps = [int(r["step"]) for r in rows[:-1]]
    assert steps == sorted(steps)


def test_predict_raises_on_ragged_rank_batches(tmp_path):
    trainer = Trainer(
        strategy=LocalStrategy(), default_root_dir=str(tmp_path),
        enable_checkpointing=False,
    )
    ragged = [
        {"rank": 0, "prediction_batches": [np.zeros(4), np.zeros(4)]},
        {"rank": 1, "prediction_batches": [np.zeros(4)]},
    ]
    trainer.strategy = types.SimpleNamespace(
        setup=lambda t: None,
        run=lambda *a, **k: ragged,
        teardown=lambda: None,
    )
    with pytest.raises(ValueError, match="Ragged"):
        trainer.predict(BoringModel(), BoringDataModule())


def test_shard_map_refuses_zero_stage(tmp_path):
    trainer = Trainer(
        strategy=LocalStrategy(mode="shard_map", zero_stage=1),
        max_epochs=1, default_root_dir=str(tmp_path),
        enable_checkpointing=False,
    )
    with pytest.raises(ValueError, match="shard_map.*zero_stage"):
        trainer.fit(BoringModel(), BoringDataModule())


def test_shard_map_refuses_tensor_parallel_module(tmp_path):
    trainer = Trainer(
        strategy=LocalStrategy(
            mode="shard_map", mesh_axes={"data": 4, "tensor": 2}
        ),
        max_epochs=1, default_root_dir=str(tmp_path),
        enable_checkpointing=False,
    )
    cfg = GPTConfig.tiny()
    with pytest.raises(ValueError, match="shard_map"):
        trainer.fit(
            GPT(cfg), SyntheticLMDataModule(cfg, batch_size=8, num_batches=1)
        )


def test_fitless_eval_uses_zero3_shardings():
    """_resolve_params must place a ZeRO-3 model sharded, not replicated."""
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    from ray_lightning_tpu.core.loop import FitConfig, _resolve_params

    mesh = Mesh(mesh_utils.create_device_mesh((8,)), ("data",))
    module = GPT(GPTConfig.tiny())
    params, shardings = _resolve_params(
        module, FitConfig(), mesh, params_stream=None, ckpt_path=None,
        zero_stage=3,
    )
    specs = [
        leaf.sharding.spec
        for leaf in jax.tree_util.tree_leaves(params)
    ]
    assert any(
        any(e is not None for e in spec) for spec in specs
    ), "ZeRO-3 eval params ended up fully replicated"


def test_fitless_validate_runs_sharded(tmp_path):
    cfg = GPTConfig.tiny()
    trainer = Trainer(
        strategy=LocalStrategy(mesh_axes={"data": 8}, zero_stage=3),
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        limit_val_batches=1,
    )
    metrics = trainer.validate(
        GPT(cfg), SyntheticLMDataModule(cfg, batch_size=8, num_batches=1)
    )
    assert np.isfinite(metrics["val_loss"])
