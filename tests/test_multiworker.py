"""Multi-worker (multi-process mesh) integration tests.

The distributed heart: 2 worker actors each owning 8 virtual CPU devices
join ONE 16-device mesh via jax.distributed (Gloo collectives standing in
for ICI/DCN).  ≙ the reference's simulated-cluster tier
(``ray.cluster_utils.Cluster``, ``test_ddp.py:54-61``) — real multi-process
collectives without real hardware.
"""

import numpy as np
import pytest

import jax

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import (
    BoringDataModule,
    BoringModel,
    XORDataModule,
    XORModel,
)
from ray_lightning_tpu.parallel.strategies import (
    LocalStrategy,
    RayShardedStrategy,
    RayStrategy,
)

from utils import get_trainer

pytestmark = [pytest.mark.remote, pytest.mark.multiworker]


def test_two_worker_fit_matches_local(tmp_path):
    """2-process/16-device mesh reproduces the single-process trajectory."""
    dm = lambda: BoringDataModule(length=64, batch_size=32)  # noqa: E731
    local = get_trainer(LocalStrategy(), max_epochs=2, tmp_path=tmp_path / "a")
    local.fit(BoringModel(), dm())

    remote = get_trainer(
        RayStrategy(num_workers=2), max_epochs=2, tmp_path=tmp_path / "b"
    )
    remote.fit(BoringModel(), dm())
    assert remote.params is not None
    for x, y in zip(
        jax.tree_util.tree_leaves(local.params),
        jax.tree_util.tree_leaves(remote.params),
    ):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=5e-3, atol=1e-3
        )
    assert "val_loss" in remote.callback_metrics


def test_two_worker_zero3_sharded(tmp_path):
    """ZeRO-3 params sharded across a 2-process mesh; checkpoint is
    topology-independent (gathered), loadable on the driver."""
    trainer = get_trainer(
        RayShardedStrategy(num_workers=2, zero_stage=3),
        max_epochs=1,
        tmp_path=tmp_path,
    )
    trainer.fit(
        BoringModel(in_dim=256, out_dim=128),
        BoringDataModule(length=64, batch_size=32, in_dim=256),
    )
    assert trainer.params["w"].shape == (256, 128)  # gathered, full shape
    assert np.isfinite(trainer.callback_metrics["train_loss"])


def test_two_worker_predict_row_order(tmp_path):
    """Predictions must come back in dataset row order despite host-
    contiguous batch splitting (the interleave-reassembly contract)."""
    trainer = get_trainer(
        RayStrategy(num_workers=2), max_epochs=6, tmp_path=tmp_path
    )
    trainer.fit(XORModel(), XORDataModule(batch_size=16))
    preds = trainer.predict(XORModel(), XORDataModule(batch_size=16))
    # XOR table tiles [0,1,1,0]; a correctly ordered, converged model
    # reproduces the tiling exactly.
    expected = np.tile([0, 1, 1, 0], len(preds) // 4)
    assert (preds == expected).mean() > 0.9


def test_zero3_restart_checkpoint_sharded_per_host(tmp_path):
    """VERDICT r3 item #3 'Done' criterion: a ZeRO-3 multiworker restart
    checkpoint never materializes the full state on one host — each of
    the 2 processes writes only its addressable shards, and the set
    reassembles to the full shapes."""
    from ray_lightning_tpu.utils.sharded_ckpt import (
        is_sharded_ckpt, load_sharded,
    )

    rs = tmp_path / "restarts"
    trainer = get_trainer(
        RayShardedStrategy(num_workers=2, zero_stage=3),
        max_epochs=1, tmp_path=tmp_path, restart_dir=str(rs),
    )
    trainer.fit(
        BoringModel(in_dim=256, out_dim=128),
        BoringDataModule(length=64, batch_size=32, in_dim=256),
    )
    tags = [p for p in rs.iterdir() if p.name.endswith(".ckpt")]
    assert len(tags) == 1 and is_sharded_ckpt(str(tags[0]))
    shards = sorted(tags[0].glob("shard-*"))
    assert len(shards) == 2  # one file per process, not one gathered blob
    sizes = [s.stat().st_size for s in shards]
    # ZeRO-3: each host holds ~half the (w, m, v) state; neither file
    # may contain the whole thing.
    assert max(sizes) < 0.75 * sum(sizes), sizes
    payload = load_sharded(str(tags[0]))
    state = payload["state"]
    assert np.asarray(
        jax.tree_util.tree_leaves(state.params)[0]
    ).shape in ((256, 128), (128,))
