"""ResNet/CIFAR model family (BASELINE config #3 analogue).

≙ reference test taxonomy (SURVEY §4): weights move under training, the
sharded mesh is numerically a no-op, and predictions beat chance on the
synthetic class-conditional data (≙ ``predict_test`` accuracy ≥ 0.5,
reference ``tests/utils.py:256-272``).
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models.resnet import CIFARDataModule, ResNet
from ray_lightning_tpu.parallel.strategies import LocalStrategy


def tiny_resnet(**kw):
    # 1-block stages at small widths: fast on the CPU test mesh while
    # exercising every code path (downsample blocks, head, norm).
    kw.setdefault("depths", (1, 1))
    kw.setdefault("widths", (16, 32))
    kw.setdefault("lr", 3e-3)
    return ResNet(**kw)


def make_data(**kw):
    kw.setdefault("batch_size", 32)
    kw.setdefault("num_samples", 512)
    kw.setdefault("image_size", 16)
    return CIFARDataModule(**kw)


def make_trainer(**kw):
    kw.setdefault("max_epochs", 1)
    kw.setdefault("enable_checkpointing", False)
    return Trainer(**kw)


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_resnet_trains_and_converges():
    tr = make_trainer(max_epochs=3)
    tr.fit(tiny_resnet(), make_data())
    assert np.isfinite(tr.callback_metrics["train_loss"])
    # Class-conditional synthetic data is separable; beat chance solidly.
    assert tr.callback_metrics["val_accuracy"] >= 0.5


def test_resnet_sharded_mesh_parity():
    """DP×FSDP×TP mesh must match plain single-axis training numerically."""

    def run(strategy):
        tr = make_trainer(strategy=strategy, limit_train_batches=2,
                          limit_val_batches=1)
        tr.fit(tiny_resnet(), make_data())
        return tr

    base = run(LocalStrategy())
    sharded = run(
        LocalStrategy(mesh_axes={"data": 2, "fsdp": 2, "tensor": 2},
                      zero_stage=3)
    )
    assert base.callback_metrics["train_loss"] == pytest.approx(
        sharded.callback_metrics["train_loss"], rel=1e-5
    )


def test_resnet_partition_specs_cover_params():
    model = tiny_resnet()
    params = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    specs = model.param_partition_specs()
    p_paths = {
        jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    s_paths = {
        jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))[0]
    }
    assert p_paths == s_paths


def test_resnet_bf16_forward_finite():
    model = tiny_resnet()
    model.precision = "bf16"
    params = model.init_params(jax.random.PRNGKey(0))
    x = np.random.default_rng(0).standard_normal(
        (4, 16, 16, 3)).astype(np.float32)
    logits = jax.jit(model.forward)(params, x)
    assert logits.dtype == np.float32  # head output cast back
    assert np.all(np.isfinite(np.asarray(logits)))


def test_cifar_datamodule_nchw_npz_roundtrip(tmp_path):
    """data_path loading accepts NCHW uint8 npz and normalizes it."""
    path = str(tmp_path / "cifar.npz")
    rng = np.random.default_rng(0)
    np.savez(path,
             x=rng.integers(0, 255, (64, 3, 16, 16)).astype(np.uint8),
             y=rng.integers(0, 10, 64).astype(np.int64))
    dm = make_data(batch_size=8, data_path=path)
    dm.setup("fit")
    batch = next(iter(dm.train_dataloader()))
    assert batch["x"].shape == (8, 16, 16, 3)
    assert batch["x"].max() <= 1.0
