"""Request-scoped distributed tracing across the serving + MPMD planes.

Contract under test: a TraceContext born at the router (trace_id ==
rid) rides every wire frame, worker/replica spans parent to it across
processes (``SpanTracer.start_remote``), and the per-component JSONL
exports stitch into ONE timeline (``telemetry/trace_collect.py``) with
a complete ``queue_wait → … → first_token`` phase chain per completed
request; a failover hop shows as a span LINKED under the request root;
recompute-preemption re-emissions share the original trace_id; MPMD
step spans share one trace_id fleet-wide; and with tracing off nothing
is installed (byte-identical snapshots, no files).
"""

import json
import os
import queue as _pyqueue
import sys
import time

import numpy as np
import pytest

from ray_lightning_tpu.telemetry import propagate, trace_collect
from ray_lightning_tpu.telemetry.schema import (
    validate_bench_trace, validate_chrome_trace, validate_serve_request,
    validate_serve_snapshot, validate_span_jsonl, validate_trace_context,
)
from ray_lightning_tpu.telemetry.spans import SpanTracer

pytestmark = pytest.mark.trace


# ---------------------------------------------------------------------------
# jax-free units: propagation, start_remote, outbox, stitcher
# ---------------------------------------------------------------------------

class TestPropagate:
    def test_root_span_id_is_derived(self):
        ctx = propagate.root_context("abc")
        assert ctx.trace_id == "abc"
        assert ctx.span_id == "abc.root"
        assert ctx.parent_span_id is None
        # Any process that knows the trace id agrees on the root.
        assert propagate.root_context("abc").span_id == ctx.span_id

    def test_child_parents_to_caller(self):
        root = propagate.root_context("abc")
        child = propagate.child_context(root)
        assert child.trace_id == "abc"
        assert child.parent_span_id == root.span_id
        assert child.span_id != root.span_id

    def test_inject_extract_roundtrip(self):
        ctx = propagate.child_context(propagate.root_context("r1"))
        item = propagate.inject({"type": "x"}, ctx)
        assert validate_trace_context(item["trace"]) == []
        assert propagate.extract(item) == ctx
        assert propagate.sent_ts(item) == pytest.approx(
            time.time(), abs=5.0
        )

    def test_inject_none_is_noop_and_extract_tolerant(self):
        item = {"type": "x"}
        assert propagate.inject(item, None) is item
        assert "trace" not in item
        # Old/malformed producers must never fail the consumer.
        assert propagate.extract({"trace": "garbage"}) is None
        assert propagate.extract({"trace": {"span_id": "x"}}) is None
        assert propagate.extract(b"bytes") is None

    def test_request_fields_carry_trace(self):
        from ray_lightning_tpu.serve.dist.handoff import request_fields

        ctx = propagate.root_context("rid9")
        req = request_fields("rid9", [1, 2], 4, reply=("h", 1),
                             sample_seed=0, trace=ctx)
        assert validate_serve_request(req) == []
        assert propagate.extract(req) == ctx
        # Untraced producers emit the pre-tracing wire shape.
        bare = request_fields("rid9", [1, 2], 4, reply=("h", 1),
                              sample_seed=0)
        assert "trace" not in bare


class TestStartRemote:
    def test_remote_parent_nesting(self):
        tracer = SpanTracer(enabled=True, clock=time.time)
        root = propagate.root_context("t1")
        with tracer.start_remote(root, "prefill_compute",
                                 rid="t1") as outer:
            assert outer.ctx.parent_span_id == root.span_id
            with tracer.start_remote(outer.ctx, "handoff_send") as inner:
                assert inner.ctx.parent_span_id == outer.ctx.span_id
        spans = tracer.events()
        assert [s.name for s in spans] == ["handoff_send",
                                           "prefill_compute"]
        by_name = {s.name: s.args for s in spans}
        assert by_name["prefill_compute"]["trace_id"] == "t1"
        assert (by_name["handoff_send"]["parent_span_id"]
                == by_name["prefill_compute"]["span_id"])
        # Nesting depth tracked like plain spans.
        assert spans[0].depth == 1 and spans[1].depth == 0

    def test_disabled_or_contextless_is_noop(self):
        tracer = SpanTracer(enabled=False, clock=time.time)
        with tracer.start_remote(propagate.root_context("x"), "a") as sp:
            assert sp.ctx is None
        enabled = SpanTracer(enabled=True, clock=time.time)
        with enabled.start_remote(None, "a") as sp:
            assert sp.ctx is None
        assert tracer.events() == [] and enabled.events() == []

    def test_wall_clock_exports_validate(self, tmp_path):
        tracer = SpanTracer(enabled=True, clock=time.time)
        with tracer.span("queue_wait"):
            pass
        assert tracer.events()[0].ts == pytest.approx(time.time(),
                                                      abs=5.0)
        path = tmp_path / "trace-x.jsonl"
        tracer.export_jsonl(str(path))
        assert validate_span_jsonl(
            path.read_text().splitlines()) == []


class TestMemberOutbox:
    def test_sends_and_on_sent_fires(self):
        from ray_lightning_tpu.cluster.queue import DriverQueue
        from ray_lightning_tpu.serve.dist.handoff import MemberOutbox

        q = DriverQueue()
        sent = []
        box = MemberOutbox((q.handle.host, q.handle.port))
        try:
            box.put({"type": "x", "n": 1}, on_sent=sent.append)
            item = q.get(timeout=5)
            assert item["n"] == 1
            deadline = time.monotonic() + 2
            while not sent and time.monotonic() < deadline:
                time.sleep(0.01)
            assert len(sent) == 1  # fired after the wire write
        finally:
            box.close()
            q.shutdown()

    def test_dead_peer_reports_once_and_put_raises(self):
        from ray_lightning_tpu.cluster.queue import DriverQueue
        from ray_lightning_tpu.serve.dist.handoff import MemberOutbox

        q = DriverQueue()
        addr = (q.handle.host, q.handle.port)
        q.shutdown()  # nothing listens: the dead-member shape
        errors = []
        box = MemberOutbox(addr, on_error=errors.append)
        try:
            try:
                box.put({"type": "x"})
            except ConnectionError:
                pass  # racing the error report is fine
            deadline = time.monotonic() + 10
            while not errors and time.monotonic() < deadline:
                time.sleep(0.01)
            assert len(errors) == 1
            with pytest.raises(ConnectionError):
                box.put({"type": "x"})  # dead outbox refuses
        finally:
            box.close()

    def test_full_queue_is_backpressure(self):
        from ray_lightning_tpu.serve.dist.handoff import MemberOutbox

        box = MemberOutbox.__new__(MemberOutbox)
        box.addr = ("127.0.0.1", 1)
        box._on_error = None
        box._q = _pyqueue.Queue(maxsize=1)
        box._dead = False
        import threading

        box._closed = threading.Event()
        box._q.put_nowait(({"type": "x"}, None, 0.0))
        with pytest.raises(ConnectionError, match="full"):
            box.put({"type": "y"})


class TestTraceCollect:
    def _span(self, name, ts, dur, src, trace_id, span_id,
              parent=None, **extra):
        args = {"trace_id": trace_id, "span_id": span_id, **extra}
        if parent is not None:
            args["parent_span_id"] = parent
        return {"name": name, "ts": ts, "dur": dur, "rank": 0,
                "tid": 1, "depth": 0, "args": args, "_src": src}

    def _request_spans(self, rid, routed=True, handoff=True,
                       status="finished"):
        root = f"{rid}.root"
        spans = [
            self._span("request", 0.0, 1.0, "router", rid, root,
                       status=status),
            self._span("queue_wait", 0.1, 0.01, "serve-r0", rid, "q1",
                       parent=root),
            self._span("first_token", 0.5, 0.01, "serve-r0", rid, "f1",
                       parent=root),
        ]
        if routed:
            spans.append(self._span("placement", 0.05, 0.01, "router",
                                    rid, "p1", parent=root))
        if handoff:
            spans += [
                self._span("prefill_compute", 0.2, 0.1, "prefill-p0",
                           rid, "pf1", parent=root),
                self._span("handoff_transfer", 0.3, 0.05, "serve-r0",
                           rid, "h1", parent="pf1"),
                self._span("decode_admission", 0.35, 0.1, "serve-r0",
                           rid, "d1", parent=root),
            ]
        else:
            spans.append(self._span("prefill_compute", 0.2, 0.1,
                                    "serve-r0", rid, "pf1",
                                    parent=root))
        return spans

    def test_coverage_complete_and_incomplete(self):
        spans = self._request_spans("a") + self._request_spans("b")
        complete, total, frac = trace_collect.coverage(spans)
        assert (complete, total, frac) == (2, 2, 1.0)
        # Drop b's decode_admission while keeping its handoff leg: the
        # import never landed, so the chain is incomplete.
        broken = [s for s in spans
                  if not (s["args"]["trace_id"] == "b"
                          and s["name"] == "decode_admission")]
        complete, total, frac = trace_collect.coverage(broken)
        assert (complete, total) == (1, 2)

    def test_coverage_requires_placement_only_when_routed(self):
        solo = self._request_spans("a", routed=False, handoff=False)
        assert trace_collect.coverage(solo)[2] == 1.0
        # A routed corpus holds every trace to the placement leg.
        mixed = (self._request_spans("a", routed=False, handoff=False)
                 + self._request_spans("b"))
        complete, total, _ = trace_collect.coverage(mixed)
        assert (complete, total) == (1, 2)

    def test_expired_requests_not_counted(self):
        spans = self._request_spans("a") + [
            self._span("request", 0.0, 0.1, "router", "x", "x.root",
                       status="expired"),
        ]
        assert trace_collect.coverage(spans) == (1, 1, 1.0)

    def test_stitch_emits_cross_process_arrows(self):
        spans = self._request_spans("a")
        doc = trace_collect.stitch_chrome(spans)
        assert validate_chrome_trace(doc) == []
        flows = [e for e in doc["traceEvents"] if e.get("ph") == "s"]
        # handoff_transfer (serve-r0) parents to pf1 (prefill-p0), and
        # the replica/worker spans parent to the router root — every
        # cross-source link gets an arrow.
        assert len(flows) >= 4
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M"}
        assert {"router", "serve-r0", "prefill-p0"} <= names

    def test_phase_percentiles_and_report(self):
        spans = self._request_spans("a") + self._request_spans("b")
        pct = trace_collect.phase_percentiles(spans)
        assert pct["queue_wait"]["n"] == 2
        assert set(pct["queue_wait"]) == {"n", "p50_ms", "p95_ms"}
        block = {
            "coverage": trace_collect.coverage(spans)[2],
            "requests": 2, "overhead_pct": None, "phases": pct,
        }
        assert validate_bench_trace(block) == []
        report = trace_collect.format_report(spans)
        assert "chain coverage 2/2" in report
        assert "prefill_compute" in report

    def test_critical_path_reports_failover(self):
        spans = self._request_spans("a")
        spans.append(self._span("failover", 0.4, 0.0, "router", "a",
                                "fo1", parent="a.root",
                                from_replica="r0"))
        paths = trace_collect.slowest_requests(spans, 1)
        assert paths[0]["failovers"][0]["from_replica"] == "r0"

    def test_mpmd_step_report_groups_workers(self):
        tid = "mpmd-x-s0"
        spans = [
            self._span("mpmd_step", 0.0, 1.0, "mpmd-stage0", tid,
                       f"{tid}.root", step=0, worker=0),
            self._span("fwd", 0.1, 0.2, "mpmd-stage0", tid, "s1",
                       parent=f"{tid}.root", step=0, worker=0,
                       blocked_s=0.0),
            self._span("recv_act", 0.1, 0.3, "mpmd-stage1", tid, "s2",
                       parent=f"{tid}.w1", step=0, worker=1,
                       blocked_s=0.25),
        ]
        report = trace_collect.mpmd_step_report(spans)
        assert len(report) == 1
        workers = report[0]["workers"]
        assert workers["0"]["compute_s"] == pytest.approx(0.2)
        assert workers["1"]["blocked_s"] == pytest.approx(0.25)
        # MPMD traces never leak into the serve request grouping.
        assert trace_collect.request_traces(spans) == {}


# ---------------------------------------------------------------------------
# jax-backed: engine, fleet, MPMD end-to-end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    import jax

    from ray_lightning_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=128, n_layer=2, n_head=4, d_model=64,
                    seq_len=64, warmup_steps=1)
    m = GPT(cfg, attn_impl="xla")
    params = m.init_params(jax.random.PRNGKey(0))
    return m, params


def _serve_cfg(**kw):
    from ray_lightning_tpu.serve.engine import ServeConfig

    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 8)
    return ServeConfig(**kw)


def _prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 128,
                         size=(int(rng.integers(3, 14)),)).tolist()
            for _ in range(n)]


class TestEngineTracing:
    def test_off_by_default_installs_nothing(self, model, tmp_path):
        from ray_lightning_tpu.serve.engine import ServeEngine

        m, params = model
        eng = ServeEngine(m, params, _serve_cfg())
        try:
            assert not eng.tracer.enabled
            eng.generate([1, 2, 3], 4)
            snap = eng.snapshot()
            assert "phases" not in snap  # byte-identical to pre-trace
            assert eng.scheduler.queue == eng.scheduler.queue  # alive
        finally:
            eng.stop()
        assert list(tmp_path.iterdir()) == []

    def test_monolith_trace_chain_and_phase_stats(self, model,
                                                  tmp_path):
        from ray_lightning_tpu.serve.engine import ServeEngine
        from ray_lightning_tpu.telemetry.export_prom import (
            render_openmetrics,
        )

        m, params = model
        eng = ServeEngine(m, params, _serve_cfg(),
                          trace_dir=str(tmp_path), trace_name="mono")
        eng.generate([1, 2, 3, 4], 6)
        snap = eng.snapshot()
        assert validate_serve_snapshot(snap) == []
        assert {"queue_wait", "prefill_compute",
                "first_token"} <= set(snap["phases"])
        text = render_openmetrics({"serve": snap})
        assert 'rlt_serve_phase_latency_ms{phase="queue_wait"' in text
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "tools"))
        import rlt_top

        frame = rlt_top.render({"ts": snap["ts"], "serve": snap}, "x")
        assert "phases:" in frame and "queue_wait" in frame
        eng.stop()
        spans = trace_collect.load_trace_dir(str(tmp_path))
        assert trace_collect.coverage(spans) == (1, 1, 1.0)

    def test_preemption_reemission_shares_trace_id(self, model,
                                                   tmp_path):
        """Recompute preemption: the replayed admission's spans land in
        the ORIGINAL trace (queue_wait appears once per admission, same
        trace_id)."""
        from ray_lightning_tpu.serve.engine import ServeEngine

        m, params = model
        eng = ServeEngine(
            m, params,
            _serve_cfg(num_slots=2, block_size=4, num_blocks=8,
                       max_model_len=24),
            trace_dir=str(tmp_path), trace_name="preempt",
        )
        h1 = eng.submit([3, 1, 4, 1], 16)
        h2 = eng.submit([2, 7, 1], 16)
        eng.run_until_idle()
        assert h1.result(5) and h2.result(5)
        assert eng.snapshot()["counters"]["preempted"] >= 1
        eng.stop()
        spans = trace_collect.load_trace_dir(str(tmp_path))
        groups = trace_collect.request_traces(spans)
        assert len(groups) == 2  # re-emission created NO new trace
        preempted = [
            g for g in groups.values()
            if sum(1 for s in g if s["name"] == "queue_wait") >= 2
        ]
        assert preempted, "no trace carries the re-admission"
        assert trace_collect.coverage(spans)[2] == 1.0


class TestFleetTracing:
    def test_inproc_fleet_full_chain_stitch(self, model, tmp_path):
        """The acceptance shape: disaggregated fleet, every completed
        request stitches a complete queue_wait → placement →
        prefill_compute → handoff_transfer → decode_admission →
        first_token chain across router/worker/replica exports."""
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_inproc_fleet

        m, params = model
        trace_dir = str(tmp_path / "tel")
        # lost_after_s effectively OFF: under full-suite load on this
        # container the beat threads can starve past the 1s default,
        # and a spuriously "dead" prefill worker makes the router fall
        # back to direct submission — correct router behavior, but it
        # would turn this test's all-six-legs assertion flaky.  Death
        # detection has its own test below.
        fleet = launch_inproc_fleet(m, params, _serve_cfg(),
                                    n_replicas=2, n_prefill=1,
                                    lost_after_s=30.0,
                                    trace_dir=trace_dir)
        client = ServeClient(fleet.queue_handle())
        n = 6
        try:
            rids = [client.submit(p, 6) for p in _prompts(n)]
            for rid in rids:
                client.result(rid, timeout=120)
            deadline = time.monotonic() + 10
            while (fleet.router.snapshot()["counters"]["completed"] < n
                   and time.monotonic() < deadline):
                time.sleep(0.05)
        finally:
            client.close()
            fleet.close()
        spans = trace_collect.load_trace_dir(trace_dir)
        complete, total, frac = trace_collect.coverage(spans)
        assert total == n and frac == 1.0
        # Every chain carries every leg of the disagg topology.
        for rid, group in trace_collect.request_traces(spans).items():
            names = {p for p, _, _ in trace_collect.chain_for(group)}
            assert names == {"queue_wait", "placement",
                             "prefill_compute", "handoff_transfer",
                             "decode_admission", "first_token"}, (
                rid, names)
        # Stitch: one Perfetto doc, arrows crossing components.
        doc = trace_collect.stitch_chrome(spans)
        assert validate_chrome_trace(doc) == []
        assert any(e.get("ph") == "s" for e in doc["traceEvents"])

    def test_trace_stitch_cli_smoke(self, model, tmp_path):
        from ray_lightning_tpu.serve.engine import ServeEngine

        m, params = model
        trace_dir = str(tmp_path)
        eng = ServeEngine(m, params, _serve_cfg(),
                          trace_dir=trace_dir, trace_name="cli")
        eng.generate([5, 6, 7], 4)
        eng.stop()
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "tools"))
        import trace_stitch

        assert trace_stitch.main([trace_dir]) == 0
        merged = os.path.join(trace_dir, "trace-merged.json")
        with open(merged) as f:
            assert validate_chrome_trace(json.load(f)) == []
        # router-live.json discovery: any file inside the dir works.
        marker = os.path.join(trace_dir, "router-live.json")
        with open(marker, "w") as f:
            json.dump({"ts": 0}, f)
        assert trace_stitch.main([marker, "--no-report"]) == 0
        # An empty dir is a loud no-spans exit, not a crash.
        empty = tmp_path / "empty"
        empty.mkdir()
        assert trace_stitch.main([str(empty)]) == 1

    def test_failover_hop_is_linked_span(self, model, tmp_path):
        """A replica death mid-stream: the re-routed request's trace
        shows the failover hop as a span linked under the request root,
        and the survivor's spans land in the SAME trace."""
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_inproc_fleet

        m, params = model
        trace_dir = str(tmp_path / "tel")
        fleet = launch_inproc_fleet(m, params, _serve_cfg(),
                                    n_replicas=2, n_prefill=0,
                                    lost_after_s=0.5,
                                    trace_dir=trace_dir)
        client = ServeClient(fleet.queue_handle())
        try:
            r1 = client.submit(list(range(1, 9)), 30)
            r2 = client.submit(list(range(9, 17)), 30)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                track = fleet.router._inflight.get(r1)
                if (track is not None and track.replica is not None
                        and len(client._pending[r1].tokens) >= 3):
                    victim = track.replica
                    break
                time.sleep(0.01)
            else:
                pytest.fail("request never started streaming")
            next(r for r in fleet.replicas
                 if r.id == victim).kill(hard=True)
            out1 = client.result(r1, timeout=120)
            assert out1
            client.result(r2, timeout=120)
            assert fleet.router.counters["failovers"] >= 1
            deadline = time.monotonic() + 10
            while (r1 in fleet.router._inflight
                   and time.monotonic() < deadline):
                time.sleep(0.05)
        finally:
            client.close()
            fleet.close()
        spans = trace_collect.load_trace_dir(trace_dir)
        groups = trace_collect.request_traces(spans)
        failed_over = groups[r1]
        hops = [s for s in failed_over if s["name"] == "failover"]
        assert hops, "failover hop missing from the trace"
        assert hops[0]["args"]["parent_span_id"] == f"{r1}.root"
        assert hops[0]["args"]["from_replica"] == victim
        # The replay genuinely crossed replicas within ONE trace: the
        # request's engine-side spans come from two distinct exports.
        engine_srcs = {s["_src"] for s in failed_over
                       if s["name"] == "queue_wait"}
        assert len(engine_srcs) == 2
        # Both placements (original + failover re-route) recorded.
        placements = [s for s in failed_over
                      if s["name"] == "placement"]
        assert len(placements) >= 2

    def test_untraced_fleet_writes_nothing(self, model, tmp_path):
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_inproc_fleet

        m, params = model
        fleet = launch_inproc_fleet(m, params, _serve_cfg(),
                                    n_replicas=1, n_prefill=1)
        client = ServeClient(fleet.queue_handle())
        try:
            rid = client.submit([1, 2, 3], 4)
            client.result(rid, timeout=120)
            assert not fleet.router.tracer.enabled
        finally:
            client.close()
            fleet.close()
        assert trace_collect.load_trace_dir(str(tmp_path)) == []


class TestMpmdTracing:
    @pytest.mark.slow  # tier-1 diet (round 20): ~7s 2-worker pipeline
    # fit; the strategy trace-dir unit + untraced-runner pin stay in
    # tier-1, the stitched-timeline fit runs via -m slow
    def test_two_worker_stitched_step_timeline(self, tmp_path):
        """In-proc 2-worker pipeline: both workers' instruction spans
        share one step trace (minted on the embed worker, adopted from
        the wire downstream), and the report decomposes compute vs
        blocked-recv per worker per step."""
        import jax

        from ray_lightning_tpu.models.gpt import GPT, GPTConfig
        from ray_lightning_tpu.mpmd.inproc import run_inproc_pipeline_fit
        from ray_lightning_tpu.mpmd.plan import _gpt_untie, gpt_mpmd_spec

        cfg = GPTConfig(vocab_size=32, n_layer=2, n_head=2, d_model=16,
                        seq_len=8, warmup_steps=2)
        module = GPT(cfg, attn_impl="xla")
        module.precision = "f32"
        spec = gpt_mpmd_spec(module)
        full = _gpt_untie(module.init_params(jax.random.PRNGKey(0)))
        rng = np.random.default_rng(7)
        steps = 2
        data = [
            {"tokens": rng.integers(
                0, cfg.vocab_size,
                (8, cfg.seq_len + 1)).astype(np.int32)}
            for _ in range(steps)
        ]
        trace_dir = str(tmp_path)
        res = run_inproc_pipeline_fit(
            spec, full, spec.tx_factory, lambda s: data[s], steps,
            n_workers=2, n_micro=4, schedule="1f1b",
            trace_dir=trace_dir,
        )
        assert len(res["losses"]) == steps
        files = sorted(os.listdir(trace_dir))
        assert files == ["trace-mpmd-stage0.jsonl",
                         "trace-mpmd-stage1.jsonl"]
        spans = trace_collect.load_trace_dir(trace_dir)
        report = trace_collect.mpmd_step_report(spans)
        assert len(report) == steps
        for entry in report:
            assert set(entry["workers"]) == {"0", "1"}
            w1 = entry["workers"]["1"]
            # The downstream worker's warmup waits ARE its bubble.
            assert w1["blocked_s"] >= 0.0
        # Worker 1's step span links under worker 0's root.
        tid = report[0]["trace_id"]
        stage_steps = [s for s in spans
                       if s["name"] == "mpmd_stage_step"
                       and s["args"]["trace_id"] == tid]
        assert stage_steps
        assert (stage_steps[0]["args"]["parent_span_id"]
                == f"{tid}.root")
        # Stitches into one valid Perfetto doc with flow arrows.
        doc = trace_collect.stitch_chrome(spans)
        assert validate_chrome_trace(doc) == []
        assert any(e.get("ph") == "s" for e in doc["traceEvents"])
        assert "mpmd" in trace_collect.format_report(spans)

    def test_mpmd_strategy_ships_trace_dir(self):
        """The actor path: MpmdStrategy carries the knob its task dict
        ships to `_stage_execute_remote` (None = off)."""
        from ray_lightning_tpu.parallel.strategies import MpmdStrategy

        s = MpmdStrategy(num_stages=2, devices_per_stage=1,
                         trace_dir="/tmp/rlt-trace-x")
        assert s.trace_dir == "/tmp/rlt-trace-x"
        assert MpmdStrategy(num_stages=2,
                            devices_per_stage=1).trace_dir is None

    def test_untraced_runner_unchanged(self):
        """No trace_dir: LocalChannel frames carry no envelope and the
        runner records nothing (wire compat with old producers)."""
        from ray_lightning_tpu.mpmd.transfer import LocalChannel, Mailbox

        box = Mailbox()
        LocalChannel(box).send("act", 0, 0, {"x": np.zeros(2)})
        payload, blocked, trace = box.recv_traced(("act", 0, 0, 0),
                                                  timeout=5)
        assert trace is None and blocked >= 0.0
        np.testing.assert_array_equal(payload["x"], np.zeros(2))
