"""Disaggregated serving plane: prefill/decode split + router.

Correctness contract: the disaggregated fleet must be INVISIBLE in the
tokens — a request routed through prefill workers, KV handoffs, and
any number of replica deaths produces exactly the stream the
single-host engine produces (greedy and temperature>0; the router's
fleet-wide sample seeds + the position-keyed sampler make failover
re-emissions bitwise), with zero steady-state recompiles on decode
replicas after KV import.  On top: router placement/admission/failover
policy units (jax-free), the handoff wire schema, the kill -9 segment
sweep, and a 2-actor end-to-end smoke.
"""

import os
import queue as _pyqueue
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ray_lightning_tpu.cluster.queue import DriverQueue
from ray_lightning_tpu.serve.dist.handoff import (
    make_beat_item, make_dispatch_item, make_handoff_item,
    make_hello_item, request_fields,
)
from ray_lightning_tpu.serve.dist.router import RestartGovernor, Router
from ray_lightning_tpu.telemetry.schema import (
    validate_bench_serve_disagg, validate_router_snapshot,
    validate_serve_kv_handoff, validate_serve_request,
)

pytestmark = pytest.mark.serve


# ---------------------------------------------------------------------------
# jax-free units: governor, wire items, router policy
# ---------------------------------------------------------------------------

class TestRestartGovernor:
    def test_window_budget(self):
        g = RestartGovernor(max_restarts=2, window_s=10.0)
        assert g.permit(now=0.0)
        assert g.permit(now=1.0)
        assert not g.permit(now=2.0)          # window exhausted
        assert g.permit(now=11.5)             # early attempts aged out
        assert g.permit(now=12.0)             # window has room for two
        assert not g.permit(now=12.5)         # {11.5, 12.0} fill it

    def test_zero_budget_never_permits(self):
        g = RestartGovernor(max_restarts=0)
        assert not g.permit(now=0.0)


class TestWireItems:
    def _req(self, **kw):
        kw.setdefault("reply", ("127.0.0.1", 9))
        kw.setdefault("sample_seed", 3)
        return request_fields("rid1", [1, 2, 3], 8, **kw)

    def test_request_fields_validate_as_serve_request(self):
        assert validate_serve_request(self._req()) == []

    def test_handoff_item_one_of_payload(self):
        req = self._req()
        with pytest.raises(ValueError, match="exactly one"):
            make_handoff_item(req, 8)
        with pytest.raises(ValueError, match="exactly one"):
            make_handoff_item(req, 8, data=b"x", shm="/dev/shm/y")
        item = make_handoff_item(req, 8, data=b"x")
        assert validate_serve_kv_handoff(item) == []

    def test_handoff_schema_negatives(self):
        req = self._req()
        item = make_handoff_item(req, 8, data=b"x")
        assert validate_serve_kv_handoff({**item, "shm": "/x"})
        assert validate_serve_kv_handoff({**item, "bucket": 2})  # < plen
        seedless = dict(item)
        seedless["req"] = {k: v for k, v in req.items()
                           if k != "sample_seed"}
        assert validate_serve_kv_handoff(seedless)

    def test_dispatch_item_shape(self):
        item = make_dispatch_item(self._req(), ("127.0.0.1", 5))
        assert item["type"] == "serve_prefill_dispatch"
        assert item["kv_to"] == ["127.0.0.1", 5]

    def test_bench_disagg_block_schema(self):
        block = {"replicas": 2, "prefill_workers": 1,
                 "requests_per_sec": 1.5, "recompiles_steady_state": 0}
        assert validate_bench_serve_disagg(block) == []
        assert validate_bench_serve_disagg({**block, "replicas": 0})
        chaos = {"killed_replica": "r0", "submitted": 10,
                 "completed": 10, "lost_requests": 0,
                 "failed_over_requests": 2}
        assert validate_bench_serve_disagg(
            {**block, "chaos": chaos}) == []
        assert validate_bench_serve_disagg(
            {**block, "chaos": {**chaos, "completed": 11}})


class _StubHandle:
    def __init__(self, member_id, alive=True):
        self.id = member_id
        self._alive = alive
        self.killed = False

    def is_alive(self):
        return self._alive

    def kill(self):
        self.killed = True


def _drain(q, timeout=2.0):
    items = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            items.append(q.get_nowait())
        except _pyqueue.Empty:
            if items:
                return items
            time.sleep(0.01)
    return items


class _RouterRig:
    """Router + stub members with real DriverQueue inboxes."""

    def __init__(self, n_replicas=2, n_workers=0, caps=None, **router_kw):
        router_kw.setdefault("lost_after_s", 60.0)
        self.router = Router(**router_kw)
        self.caps = caps or {"num_slots": 2, "max_queue": 2,
                             "spec_k": 0, "max_prompt_len": 16,
                             "max_model_len": 64, "block_size": 8}
        self.replicas = {}
        self.workers = {}
        self.reply_q = DriverQueue()
        for i in range(n_replicas):
            self.add_replica(f"r{i}")
        for i in range(n_workers):
            self.add_worker(f"p{i}")
        self.router.poll()

    def add_replica(self, rid, **caps_over):
        q = DriverQueue()
        handle = _StubHandle(rid)
        self.router.add_replica(handle)
        caps = {**self.caps, **caps_over}
        self.router.beat_handle.put(make_hello_item(
            "decode", rid, (q.handle.host, q.handle.port), **caps))
        self.replicas[rid] = (handle, q)
        return handle, q

    def add_worker(self, wid):
        q = DriverQueue()
        handle = _StubHandle(wid)
        self.router.add_prefill(handle)
        self.router.beat_handle.put(make_hello_item(
            "prefill", wid, (q.handle.host, q.handle.port),
            max_prompt_len=16, max_model_len=64, block_size=8))
        self.workers[wid] = (handle, q)
        return handle, q

    def submit(self, rid, prompt_len=3, **kw):
        item = {
            "type": "serve_request", "rid": rid,
            "prompt": list(range(1, prompt_len + 1)),
            "max_new_tokens": kw.pop("max_new_tokens", 4),
            "reply": [self.reply_q.handle.host, self.reply_q.handle.port],
            **kw,
        }
        self.router.submit_request(item)
        # Dispatch sends are asynchronous (per-member outbox threads);
        # the rig's assertions want them LANDED.
        self.router.flush_outboxes()

    def beat_done(self, member_id, pairs, role="decode"):
        self.router.beat_handle.put(make_beat_item(
            role, member_id, done=pairs))
        self.router.poll()
        self.router.flush_outboxes()

    def close(self):
        self.router.stop()
        self.reply_q.shutdown()
        for _, q in list(self.replicas.values()) + list(
                self.workers.values()):
            q.shutdown()


class TestRouterPolicy:
    def test_hello_registers_and_wait_ready(self):
        rig = _RouterRig(n_replicas=1, n_workers=1)
        try:
            rig.router.wait_ready(timeout=5)
            snap = rig.router.snapshot()
            assert [r["id"] for r in snap["replicas"]] == ["r0"]
            assert [w["id"] for w in snap["workers"]] == ["p0"]
        finally:
            rig.close()

    def test_least_loaded_placement_direct(self):
        rig = _RouterRig(n_replicas=2)
        try:
            for i in range(4):
                rig.submit(f"q{i}")
            r0 = _drain(rig.replicas["r0"][1])
            r1 = _drain(rig.replicas["r1"][1])
            # Round-robin by in-flight count: 2 each, never 4/0.
            assert len(r0) == len(r1) == 2
            # Fleet-wide seeds: distinct, submission-ordered.
            seeds = sorted(item["sample_seed"] for item in r0 + r1)
            assert seeds == [0, 1, 2, 3]
            assert rig.router.counters["direct_submits"] == 4
        finally:
            rig.close()

    def test_prefix_affinity_prefers_warm_replica(self):
        rig = _RouterRig(n_replicas=2)
        try:
            rig.submit("a0", prompt_len=9)
            rig.submit("a1", prompt_len=9)  # same prompt family
            r0 = _drain(rig.replicas["r0"][1])
            r1 = _drain(rig.replicas["r1"][1])
            # The second request follows the chain to the replica that
            # served the first, even though the other replica is idle.
            assert {item["rid"] for item in r0} == {"a0", "a1"}
            assert r1 == []
            assert rig.router.counters["prefix_affinity_hits"] == 1
        finally:
            rig.close()

    def test_prefix_affinity_yields_when_warm_replica_full(self):
        rig = _RouterRig(n_replicas=2)  # num_slots=2 per replica
        try:
            for i in range(3):
                rig.submit(f"f{i}", prompt_len=9)
            r0 = _drain(rig.replicas["r0"][1])
            r1 = _drain(rig.replicas["r1"][1])
            # Affinity never queues: once the warm replica's slots are
            # full the third same-prefix request places by load.
            assert {item["rid"] for item in r0} == {"f0", "f1"}
            assert [item["rid"] for item in r1] == ["f2"]
        finally:
            rig.close()

    def test_capacity_rejection_typed(self):
        rig = _RouterRig(n_replicas=1,
                         caps={"num_slots": 1, "max_queue": 1,
                               "spec_k": 0, "max_prompt_len": 16,
                               "max_model_len": 64, "block_size": 8})
        try:
            rig.submit("a")
            rig.submit("b")
            rig.submit("c")  # over num_slots + max_queue = 2
            replies = _drain(rig.reply_q)
            assert len(replies) == 1
            assert replies[0]["rid"] == "c"
            assert replies[0]["status"] == "rejected"
            assert rig.router.counters["rejected"] == 1
            assert "c" not in rig.router._inflight
        finally:
            rig.close()

    def test_spec_requests_stick_to_draft_capable(self):
        rig = _RouterRig(n_replicas=1)
        try:
            rig.add_replica("rs", spec_k=4)
            rig.router.poll()
            for i in range(2):
                rig.submit(f"s{i}", spec=2)
            routed = _drain(rig.replicas["rs"][1])
            assert [item["rid"] for item in routed] == ["s0", "s1"]
        finally:
            rig.close()

    def test_spec_without_capable_replica_is_invalid(self):
        rig = _RouterRig(n_replicas=1)
        try:
            rig.submit("s0", spec=2)
            replies = _drain(rig.reply_q)
            assert replies[0]["status"] == "invalid"
            assert "draft-capable" in replies[0]["error"]
        finally:
            rig.close()

    def test_oversized_prompt_is_invalid(self):
        rig = _RouterRig(n_replicas=1)
        try:
            rig.submit("big", prompt_len=40)  # > max_prompt_len 16
            replies = _drain(rig.reply_q)
            assert replies[0]["status"] == "invalid"
            assert rig.router.counters["invalid"] == 1
        finally:
            rig.close()

    def test_malformed_wire_request_gets_invalid_reply(self):
        rig = _RouterRig(n_replicas=1)
        try:
            rig.router.queue_handle().put({
                "type": "serve_request", "rid": "m1",
                "prompt": [1, 2], "max_new_tokens": None,  # int(None)
                "reply": [rig.reply_q.handle.host,
                          rig.reply_q.handle.port],
            })
            rig.router.poll()
            replies = _drain(rig.reply_q)
            assert replies and replies[0]["status"] == "invalid"
            assert replies[0]["rid"] == "m1"
        finally:
            rig.close()

    def test_done_beat_prunes_inflight(self):
        rig = _RouterRig(n_replicas=1)
        try:
            rig.submit("a")
            assert rig.router._inflight
            rig.beat_done("r0", [("a", "finished")])
            assert not rig.router._inflight
            assert rig.router.counters["completed"] == 1
        finally:
            rig.close()

    def test_replica_death_fails_over_inflight(self):
        rig = _RouterRig(n_replicas=2)
        try:
            rig.submit("a")
            rig.submit("b")
            victim = next(
                t.replica for t in rig.router._inflight.values())
            survivor = "r1" if victim == "r0" else "r0"
            _drain(rig.replicas[victim][1])
            _drain(rig.replicas[survivor][1])
            moved = [r for r, t in rig.router._inflight.items()
                     if t.replica == victim]
            rig.replicas[victim][0]._alive = False
            rig.router.poll()
            re_routed = _drain(rig.replicas[survivor][1])
            assert sorted(i["rid"] for i in re_routed) == sorted(moved)
            # The re-submission carries the ORIGINAL fleet seed — the
            # bitwise-stream guarantee's transport half.
            for item in re_routed:
                assert item["sample_seed"] is not None
            c = rig.router.counters
            assert c["replica_deaths"] == 1 and c["failovers"] == 1
            assert c["failed_over_requests"] == len(moved)
            deadline = time.monotonic() + 2.0
            while (not rig.replicas[victim][0].killed
                   and time.monotonic() < deadline):
                time.sleep(0.01)  # reap runs off the control plane
            assert rig.replicas[victim][0].killed  # corpse reaped
        finally:
            rig.close()

    def test_failover_parks_when_survivor_saturated(self):
        rig = _RouterRig(n_replicas=2,
                         caps={"num_slots": 1, "max_queue": 0,
                               "spec_k": 0, "max_prompt_len": 16,
                               "max_model_len": 64, "block_size": 8})
        try:
            rig.submit("a")
            rig.submit("b")  # one per replica (capacity 1 each)
            victim = rig.router._inflight["a"].replica
            survivor = "r1" if victim == "r0" else "r0"
            rig.replicas[victim][0]._alive = False
            rig.router.poll()
            # Survivor full: "a" parked, NOT rejected/lost.
            assert "a" in rig.router._inflight
            assert not _drain(rig.reply_q, timeout=0.3)
            other = next(r for r in rig.router._inflight
                         if r != "a")
            rig.beat_done(survivor, [(other, "finished")])
            routed = _drain(rig.replicas[survivor][1])
            assert any(i["rid"] == "a" for i in routed)
        finally:
            rig.close()

    def test_closing_beat_is_planned_drain_not_failure(self):
        rig = _RouterRig(n_replicas=2)
        try:
            rig.submit("a")
            rig.submit("b")
            draining = next(
                t.replica for t in rig.router._inflight.values())
            survivor = "r1" if draining == "r0" else "r0"
            _drain(rig.replicas[draining][1])
            _drain(rig.replicas[survivor][1])
            moved = [r for r, t in rig.router._inflight.items()
                     if t.replica == draining]
            rig.router.beat_handle.put(make_beat_item(
                "decode", draining, closing=True))
            rig.router.poll()
            c = rig.router.counters
            assert c["replica_drains"] == 1
            assert c["replica_deaths"] == 0 and c["failovers"] == 0
            re_routed = _drain(rig.replicas[survivor][1])
            assert sorted(i["rid"] for i in re_routed) == sorted(moved)
            snap = rig.router.snapshot()
            entry = next(r for r in snap["replicas"]
                         if r["id"] == draining)
            assert entry["alive"] is False
        finally:
            rig.close()

    def test_spec_parks_when_capable_replica_excluded(self):
        rig = _RouterRig(n_replicas=1)  # r0 plain
        try:
            rig.add_replica("rs", spec_k=4)
            rig.router.poll()
            rig.submit("s0", spec=2)
            assert _drain(rig.replicas["rs"][1])  # placed on capable
            # Transient handoff-style failure excludes the ONLY capable
            # replica: the accepted request must PARK, never land on a
            # draft-less replica (instant "invalid") nor be dropped.
            rig.router._on_handoff_failure("s0", "ConnectionError()",
                                           now=0.0)
            assert "s0" in rig.router._inflight
            assert not _drain(rig.replicas["r0"][1], timeout=0.3)
            assert not _drain(rig.reply_q, timeout=0.2)
            rig.router.poll()  # retry queue: exclusion was one-shot
            routed = _drain(rig.replicas["rs"][1])
            assert [i["rid"] for i in routed] == ["s0"]
        finally:
            rig.close()

    def test_worker_death_respawns_under_governor(self):
        spawned = []

        def factory():
            handle = _StubHandle(f"px{len(spawned)}")
            spawned.append(handle)
            return handle

        rig = _RouterRig(n_replicas=1, n_workers=1,
                         governor=RestartGovernor(max_restarts=1),
                         prefill_factory=factory)
        try:
            rig.submit("a")
            assert _drain(rig.workers["p0"][1])  # dispatched to worker
            rig.workers["p0"][0]._alive = False
            rig.router.poll()
            c = rig.router.counters
            assert c["worker_deaths"] == 1
            assert c["prefill_respawns"] == 1 and len(spawned) == 1
            # The pending prompt re-dispatched: the respawned worker has
            # no inbox yet, so it falls back to direct submission.
            routed = _drain(rig.replicas["r0"][1])
            assert [i["rid"] for i in routed] == ["a"]
            # Second death exhausts the window: denied, no new spawn.
            spawned[0]._alive = False
            rig.router.poll()
            assert rig.router.counters["prefill_respawns_denied"] == 1
            assert len(spawned) == 1
        finally:
            rig.close()

    def test_worker_failed_handoff_reroutes_excluding_replica(self):
        rig = _RouterRig(n_replicas=2, n_workers=1)
        try:
            rig.submit("a")
            assert _drain(rig.workers["p0"][1])
            bound = rig.router._inflight["a"].replica
            other = "r1" if bound == "r0" else "r0"
            rig.router.beat_handle.put(make_beat_item(
                "prefill", "p0", failed=[("a", "ConnectionError()")]))
            rig.router.poll()
            assert rig.router._inflight["a"].replica == other
        finally:
            rig.close()

    def test_snapshot_schema_and_export(self, tmp_path):
        rig = _RouterRig(n_replicas=2, n_workers=1)
        try:
            rig.submit("a")
            rig.router.beat_handle.put(make_beat_item(
                "decode", "r0",
                snapshot={"ts": 0.0, "counters": {}, "latency": {},
                          "gauges": {"slots_active": 1, "num_slots": 2,
                                     "blocks_free": 5, "num_blocks": 9,
                                     "queue_depth": 0}},
                recompiles=4))
            rig.router.poll()
            snap = rig.router.snapshot()
            assert validate_router_snapshot(snap) == []
            import json

            from ray_lightning_tpu.telemetry.export_prom import (
                render_openmetrics,
            )
            text = render_openmetrics({"router": snap})
            assert 'rlt_serve_replica_inflight{replica=' in text
            assert 'rlt_serve_router_total{kind="routed"} 1' in text
            sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                            "..", "tools"))
            import rlt_top

            frame = rlt_top.render(
                {"ts": snap["ts"], "router": snap}, "x")
            assert "router:" in frame and "r0" in frame
            # Discovery: router-live.json in a telemetry dir.
            path = tmp_path / "router-live.json"
            path.write_text(json.dumps({"ts": snap["ts"],
                                        "router": snap}))
            loaded = rlt_top.load_snapshot(str(tmp_path))
            assert loaded and "router" in loaded
        finally:
            rig.close()


class TestServeResilience:
    """ISSUE 19 units (jax-free): parked-retry FIFO under sustained
    saturation, the migration claim vs beat-loss detection, and the
    brownout ladder's hysteresis.  The fleet-level chaos lives in the
    slow-marked TestInprocFleet drills + tools/chaos_serve_sweep.py."""

    def test_retry_queue_drains_in_submission_order(self):
        """A failover burst onto a saturated survivor parks every
        displaced request; as capacity frees one slot at a time they
        place in original submission order — sustained saturation must
        not reorder (starve) the oldest accepted work."""
        rig = _RouterRig(n_replicas=2,
                         caps={"num_slots": 2, "max_queue": 0,
                               "spec_k": 0, "max_prompt_len": 16,
                               "max_model_len": 64, "block_size": 8})
        try:
            for i in range(4):
                rig.submit(f"q{i}")   # 2 per replica, both full
            victim = rig.router._inflight["q0"].replica
            survivor = "r1" if victim == "r0" else "r0"
            displaced = [r for r, t in rig.router._inflight.items()
                         if t.replica == victim]
            resident = [r for r, t in rig.router._inflight.items()
                        if t.replica == survivor]
            _drain(rig.replicas[survivor][1])
            rig.replicas[victim][0]._alive = False
            rig.router.poll()
            # Survivor full: both displaced requests parked, in order.
            assert list(rig.router._retry) == displaced
            assert not _drain(rig.reply_q, timeout=0.2)  # none rejected
            placed = []
            for done_rid in resident:  # free ONE slot at a time
                rig.beat_done(survivor, [(done_rid, "finished")])
                placed += [i["rid"]
                           for i in _drain(rig.replicas[survivor][1])]
            assert placed == displaced  # FIFO, never newest-first
        finally:
            rig.close()

    def test_migration_claim_suppresses_beat_loss(self):
        """ISSUE 19 bugfix regression: a ``migrating`` beat claims the
        replica for ``migration_claim_s`` — the device->host KV gather
        can silence beats past ``lost_after_s``, and declaring the
        exporter dead mid-export would race recompute failover against
        migration frames already on the wire for the SAME rids.  The
        claim is bounded: once it expires a silent replica dies
        normally and nothing is lost."""
        rig = _RouterRig(n_replicas=2, lost_after_s=0.15,
                         migration_claim_s=0.6)
        try:
            rig.submit("x")
            victim = rig.router._inflight["x"].replica
            survivor = "r1" if victim == "r0" else "r0"
            rig.router.beat_handle.put(make_beat_item(
                "decode", victim, migrating=["x"]))
            rig.router.poll()
            time.sleep(0.25)  # beat-age > lost_after_s, claim active
            # The survivor beats on; ONLY the exporter goes silent.
            rig.router.beat_handle.put(make_beat_item(
                "decode", survivor))
            rig.router.poll()
            assert rig.router._replicas[victim].alive
            assert rig.router.counters["failovers"] == 0
            assert rig.router._inflight["x"].replica == victim
            time.sleep(0.5)   # claim expired, still no beat: dead now
            rig.router.beat_handle.put(make_beat_item(
                "decode", survivor))
            rig.router.poll()
            rig.router.flush_outboxes()
            assert not rig.router._replicas[victim].alive
            assert rig.router.counters["failovers"] == 1
            # The orphan finished the normal way: recompute failover.
            assert rig.router._inflight["x"].replica != victim
        finally:
            rig.close()

    def test_brownout_ladder_hysteresis_and_probe(self):
        """Thin unit beside tools/chaos_serve_sweep.py --selftest: one
        rung per observation, dwell between moves, descent needs the
        exit margin, one half-open probe per window."""
        from ray_lightning_tpu.serve.brownout import BrownoutLadder

        t = [0.0]
        b = BrownoutLadder(min_dwell_s=1.0, probe_every_s=5.0,
                           clock=lambda: t[0])
        assert b.observe(0.90) == 1   # first climb off 0 is immediate
        assert b.observe(0.99) == 1   # dwell holds the rung
        t[0] = 1.1
        assert b.observe(0.99) == 2
        t[0] = 2.2
        assert b.observe(1.00) == 3
        t[0] = 3.3
        assert b.observe(0.94) == 3   # within exit margin: no descent
        assert b.observe(0.10) == 2   # one rung down, never straight 0
        t[0] = 10.0
        assert b.allow_probe()        # opens the half-open window
        assert not b.allow_probe()    # window closed until it elapses
        t[0] = 15.1
        assert b.allow_probe()


# ---------------------------------------------------------------------------
# Segment lifetime: dead prefill handoffs must not leak tmpfs
# ---------------------------------------------------------------------------

class TestSegmentSweep:
    def _orphan_segment(self):
        """Write an rlt-kv segment from a subprocess and SIGKILL it —
        the dead-prefill-worker shape (owner pid gone, segment never
        consumed)."""
        code = (
            "import sys, time\n"
            "from ray_lightning_tpu.cluster.shm import SegmentStore\n"
            "import atexit\n"
            "store = SegmentStore(prefix='rlt-kv')\n"
            "atexit.unregister(store.unlink_all)\n"  # simulate -9: no cleanup
            "print(store.put(b'x' * 2048), flush=True)\n"
            "time.sleep(60)\n"
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", code], stdout=subprocess.PIPE,
            text=True,
        )
        path = proc.stdout.readline().strip()
        assert os.path.exists(path)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        return path

    def test_router_teardown_sweeps_killed_producer(self):
        path = self._orphan_segment()
        router = Router(lost_after_s=60.0)
        router.stop()  # teardown sweep (same path failover takes)
        assert not os.path.exists(path)

    def test_engine_close_sweeps_killed_producer(self, dist_model):
        from ray_lightning_tpu.serve.engine import (
            ServeConfig, ServeEngine,
        )

        m, params = dist_model
        eng = ServeEngine(m, params, ServeConfig(num_slots=1,
                                                 block_size=8))
        path = self._orphan_segment()
        eng.stop()
        assert not os.path.exists(path)


# ---------------------------------------------------------------------------
# jax-backed: KV export/import, handoff admission, fleets
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dist_model():
    import jax

    from ray_lightning_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=128, n_layer=2, n_head=4, d_model=64,
                    seq_len=64, warmup_steps=1)
    m = GPT(cfg, attn_impl="xla")
    params = m.init_params(jax.random.PRNGKey(0))
    return m, params


def _prompts(n, seed=0, vocab=128, lo=3, hi=14):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab,
                         size=(int(rng.integers(lo, hi)),)).tolist()
            for _ in range(n)]


def _serve_cfg(**kw):
    from ray_lightning_tpu.serve.engine import ServeConfig

    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 8)
    return ServeConfig(**kw)


def _reference_tokens(model, prompts, temps, max_new=8, **engine_kw):
    """Monolith engine run with the same submission order — the token
    stream the fleet must reproduce bitwise."""
    from ray_lightning_tpu.serve.engine import ServeEngine

    m, params = model
    eng = ServeEngine(m, params, _serve_cfg(**engine_kw.pop("cfg", {})),
                      **engine_kw)
    try:
        return [eng.generate(p, max_new, temperature=t)
                for p, t in zip(prompts, temps)]
    finally:
        eng.stop()


class TestKVExportImport:
    def test_roundtrip_distinct_block_ids(self, dist_model):
        import jax
        import jax.numpy as jnp

        from ray_lightning_tpu.serve.kv_cache import (
            PagedKVCache, import_blocks,
        )

        m, _ = dist_model
        cache = PagedKVCache(m.config, num_blocks=9, block_size=4)
        pool = cache.init_pool()
        rng = np.random.default_rng(0)
        content = {
            k: rng.normal(size=(m.config.n_layer, 2, 4, m.config.n_head,
                                m.config.head_dim)).astype(np.float32)
            for k in ("k", "v")
        }
        src_ids = [3, 5]
        pool = {k: pool[k].at[:, jnp.asarray(src_ids)].set(content[k])
                for k in pool}
        exported = cache.export_blocks(pool, src_ids)
        for k in ("k", "v"):
            assert isinstance(exported[k], np.ndarray)
            np.testing.assert_array_equal(exported[k], content[k])
        # Import into DIFFERENT physical ids of a fresh pool.
        dst = PagedKVCache(m.config, num_blocks=9, block_size=4)
        dst_pool = dst.init_pool()
        dst_ids = jnp.asarray([7, 1], jnp.int32)
        dst_pool = jax.jit(import_blocks)(
            dst_pool, {k: jnp.asarray(v) for k, v in exported.items()},
            dst_ids,
        )
        again = dst.export_blocks(dst_pool, [7, 1])
        for k in ("k", "v"):
            np.testing.assert_array_equal(again[k], content[k])
        # Untouched blocks (trash included) stayed zero.
        assert float(jnp.abs(dst_pool["k"][:, 0]).max()) == 0.0

    def test_export_rejects_trash_and_oob(self, dist_model):
        from ray_lightning_tpu.serve.kv_cache import PagedKVCache

        m, _ = dist_model
        cache = PagedKVCache(m.config, num_blocks=5, block_size=4)
        pool = cache.init_pool()
        with pytest.raises(ValueError, match="ids outside"):
            cache.export_blocks(pool, [0])
        with pytest.raises(ValueError, match="ids outside"):
            cache.export_blocks(pool, [5])


class TestHandoffAdmission:
    """One engine fed real serve_kv_handoff frames — the decode-replica
    half of the split, without the fleet around it."""

    def _handoff_via_worker(self, model, req, serve_cfg, kv_to,
                            same_host=True):
        from ray_lightning_tpu.serve.dist.prefill import PrefillRunner

        m, params = model
        beats = DriverQueue()
        worker = PrefillRunner("pw", m, params, serve_cfg,
                               beats.handle, beat_s=60.0)
        try:
            worker._inbox.handle.put(make_dispatch_item(
                req, kv_to, same_host=same_host))
            assert worker.step(timeout=5)
        finally:
            worker.close()
            beats.shutdown()

    @pytest.mark.parametrize("temperature", [0.0, 0.8])
    def test_import_admission_matches_local_prefill(self, dist_model,
                                                    temperature):
        from ray_lightning_tpu.serve.engine import ServeEngine

        m, params = dist_model
        cfg = _serve_cfg()
        prompt = list(range(1, 11))
        ref = _reference_tokens(dist_model, [prompt], [temperature])
        eng = ServeEngine(m, params, _serve_cfg())
        replies = DriverQueue()
        try:
            req = request_fields(
                "h1", prompt, 8,
                reply=(replies.handle.host, replies.handle.port),
                sample_seed=0, temperature=temperature,
            )
            self._handoff_via_worker(
                dist_model, req, cfg,
                (eng.queue_handle().host, eng.queue_handle().port),
            )
            eng.run_until_idle()
            done = [i for i in _drain(replies, timeout=5)
                    if i["type"] == "serve_done"]
            assert done and done[0]["status"] == "finished"
            assert done[0]["tokens"] == ref[0]
            assert eng.stats.counters["kv_imports"] == 1
            assert eng.stats.counters["prefills"] == 0
        finally:
            eng.stop()
            replies.shutdown()

    def test_import_steady_state_zero_recompiles(self, dist_model):
        """Steady state = long-lived worker + long-lived replica: once
        a bucket's prefill/import/first-token programs are warm, every
        further handoff of that bucket compiles NOTHING on either
        side."""
        from ray_lightning_tpu.serve.dist.prefill import PrefillRunner
        from ray_lightning_tpu.serve.engine import ServeEngine
        from ray_lightning_tpu.telemetry import compile_event_count

        m, params = dist_model
        eng = ServeEngine(m, params, _serve_cfg())
        replies = DriverQueue()
        beats = DriverQueue()
        worker = PrefillRunner("pw", m, params, _serve_cfg(),
                               beats.handle, beat_s=60.0)
        kv_to = (eng.queue_handle().host, eng.queue_handle().port)
        try:
            def one(rid, prompt, seed):
                req = request_fields(
                    rid, prompt, 4,
                    reply=(replies.handle.host, replies.handle.port),
                    sample_seed=seed,
                )
                worker._inbox.handle.put(make_dispatch_item(req, kv_to))
                assert worker.step(timeout=5)
                eng.run_until_idle()

            one("w1", list(range(1, 7)), 0)      # warms the import path
            before = compile_event_count()
            one("w2", list(range(2, 8)), 1)      # same bucket: steady
            assert compile_event_count() - before == 0
        finally:
            worker.close()
            beats.shutdown()
            eng.stop()
            replies.shutdown()

    def test_shm_handoff_consumed_and_unlinked(self, dist_model):
        """Same-host zero-copy: with the threshold forced to 0 the
        payload rides a tmpfs segment, the replica reads it once and
        unlinks it (consumer-owned lifetime) — and the tokens are the
        same as the inline path's."""
        import glob

        from ray_lightning_tpu.cluster.shm import segment_dir
        from ray_lightning_tpu.serve.dist.prefill import PrefillRunner
        from ray_lightning_tpu.serve.engine import ServeEngine

        m, params = dist_model
        prompt = list(range(1, 11))
        ref = _reference_tokens(dist_model, [prompt], [0.0])
        eng = ServeEngine(m, params, _serve_cfg())
        replies = DriverQueue()
        beats = DriverQueue()
        worker = PrefillRunner("pw", m, params, _serve_cfg(),
                               beats.handle, beat_s=60.0,
                               shm_threshold=0)
        try:
            req = request_fields(
                "shm1", prompt, 8,
                reply=(replies.handle.host, replies.handle.port),
                sample_seed=0,
            )
            worker._inbox.handle.put(make_dispatch_item(
                req, (eng.queue_handle().host,
                      eng.queue_handle().port), same_host=True))
            assert worker.step(timeout=5)
            assert len(worker._live_segments) == 1
            shm_path = worker._live_segments[0][0]
            assert os.path.exists(shm_path)
            eng.run_until_idle()
            done = [i for i in _drain(replies, timeout=5)
                    if i["type"] == "serve_done"]
            assert done and done[0]["tokens"] == ref[0]
            assert not os.path.exists(shm_path)  # consumer unlinked
        finally:
            worker.close()
            beats.shutdown()
            eng.stop()
            replies.shutdown()
            leftovers = glob.glob(os.path.join(segment_dir(),
                                               "rlt-kv-*"))
            assert not leftovers

    def test_prefill_graceful_drain_sends_closing_beat(self,
                                                       dist_model):
        """A planned worker stop must flag its final beat ``closing``
        (the router's drain-vs-death discriminator) — and a hard kill
        must NOT (a dead process sends nothing)."""
        import threading

        from ray_lightning_tpu.serve.dist.prefill import PrefillRunner

        m, params = dist_model
        beats = DriverQueue()
        worker = PrefillRunner("pw", m, params, _serve_cfg(),
                               beats.handle, beat_s=0.05)
        stop = threading.Event()
        thread = threading.Thread(target=worker.run,
                                  args=(stop.is_set,), daemon=True)
        thread.start()
        time.sleep(0.2)
        stop.set()
        thread.join(timeout=10)
        items = _drain(beats, timeout=2.0)
        beats.shutdown()
        assert items[0]["type"] == "serve_replica_hello"
        closing = [i for i in items
                   if i.get("type") == "serve_replica_beat"
                   and i.get("closing")]
        assert len(closing) == 1 and items[-1] is closing[0]

    def test_geometry_mismatch_is_typed_invalid(self, dist_model):
        from ray_lightning_tpu.mpmd.transfer import encode_tree
        from ray_lightning_tpu.serve.engine import ServeEngine

        m, params = dist_model
        eng = ServeEngine(m, params, _serve_cfg())
        replies = DriverQueue()
        try:
            req = request_fields(
                "bad", [1, 2, 3], 4,
                reply=(replies.handle.host, replies.handle.port),
                sample_seed=0,
            )
            payload = encode_tree({
                "kv": {k: np.zeros((m.config.n_layer, 3, 8,
                                    m.config.n_head,
                                    m.config.head_dim), np.float32)
                       for k in ("k", "v")},
                "logits": np.zeros((m.config.vocab_size,), np.float32),
            })
            # 3 blocks of 8 = 24 tokens, but a 3-token prompt buckets
            # at 8 — geometry drift must be loud, not a hang.
            eng.queue_handle().put(
                make_handoff_item(req, bucket=24, data=payload))
            eng.run_until_idle()
            eng.step()
            done = _drain(replies, timeout=5)
            assert done and done[0]["status"] == "invalid"
            assert "geometry" in done[0]["error"]
            assert ("bad", "invalid") in eng.drain_done()
        finally:
            eng.stop()
            replies.shutdown()


class TestInprocFleet:
    """Full dataflow on driver threads: client → router → prefill
    worker → KV handoff → decode replica → token stream."""

    def test_fleet_parity_and_zero_recompiles(self, dist_model):
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_inproc_fleet
        from ray_lightning_tpu.telemetry import compile_event_count

        m, params = dist_model
        prompts = _prompts(6)
        temps = [0.0, 0.8, 0.0, 0.8, 0.0, 0.8]
        ref = _reference_tokens(dist_model, prompts, temps)
        fleet = launch_inproc_fleet(m, params, _serve_cfg(),
                                    n_replicas=2, n_prefill=1)
        client = ServeClient(fleet.queue_handle())
        try:
            rids = [client.submit(p, 8, temperature=t)
                    for p, t in zip(prompts, temps)]
            out = [client.result(r, timeout=120) for r in rids]
            assert out == ref
            # Steady state (all programs warmed, every bucket seen):
            # a second wave triggers ZERO compiles anywhere in the
            # fleet — replicas, worker, router, client all share this
            # process, so the process counter bounds them all.
            before = compile_event_count()
            rids = [client.submit(p, 8, temperature=t)
                    for p, t in zip(_prompts(6, seed=5), temps)]
            out2 = [client.result(r, timeout=120) for r in rids]
            assert len(out2) == 6
            assert compile_event_count() - before == 0
            # The requests genuinely rode the handoff path.
            snap = fleet.router.snapshot()
            assert validate_router_snapshot(snap) == []
            assert snap["counters"]["prefill_dispatches"] == 12
            assert snap["counters"]["worker_deaths"] == 0
        finally:
            client.close()
            fleet.close()

    @pytest.mark.slow  # tier-1 diet (round 20): ~8s fleet fit; the
    # router-rig failover units + fleet_parity smoke stay in tier-1
    def test_client_failover_dedup_mid_stream(self, dist_model):
        """Satellite: engineered replica death mid-stream — the
        survivor's re-emission is deduped by token index and the final
        stream is bitwise the no-failure stream, greedy AND
        temperature>0 (the round-16 position-keyed sampler + the
        router's fleet seeds)."""
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_inproc_fleet

        m, params = dist_model
        p1, p2 = list(range(1, 9)), list(range(9, 17))
        ref = _reference_tokens(dist_model, [p1, p2], [0.7, 0.0],
                                max_new=30)
        fleet = launch_inproc_fleet(m, params, _serve_cfg(),
                                    n_replicas=2, n_prefill=0,
                                    lost_after_s=0.5)
        client = ServeClient(fleet.queue_handle())
        try:
            r1 = client.submit(p1, 30, temperature=0.7)
            r2 = client.submit(p2, 30)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                track = fleet.router._inflight.get(r1)
                if (track is not None and track.replica is not None
                        and len(client._pending[r1].tokens) >= 3):
                    victim = track.replica
                    break
                time.sleep(0.01)
            else:
                pytest.fail("request never started streaming")
            next(r for r in fleet.replicas
                 if r.id == victim).kill(hard=True)
            out1 = client.result(r1, timeout=120)
            out2 = client.result(r2, timeout=120)
            assert out1 == ref[0]          # bitwise across the failover
            assert out2 == ref[1]
            assert client.re_emitted_tokens > 0  # dedup genuinely hit
            c = fleet.router.counters
            assert c["failovers"] >= 1 and c["replica_deaths"] == 1
            assert c["failed_over_requests"] >= 1
        finally:
            client.close()
            fleet.close()

    @pytest.mark.slow  # tier-1 budget audit (round 19): ~10s fleet
    # fit; the migration-claim + closing-beat router units carry the
    # drain semantics in tier-1, tools/chaos_serve_sweep.py is the
    # full-matrix gate
    def test_drain_migration_parity_zero_reemit(self, dist_model):
        """Tentpole acceptance: planned drain live-migrates resident
        sequences — decode resumes mid-sequence on the survivor with
        ZERO recomputed prefill (re_emitted_tokens == 0, the failover
        path's signature) and bitwise token parity vs an uninterrupted
        engine, greedy AND temperature>0."""
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_inproc_fleet

        m, params = dist_model
        p1, p2 = list(range(1, 9)), list(range(9, 17))
        ref = _reference_tokens(dist_model, [p1, p2], [0.7, 0.0],
                                max_new=30)
        os.environ["RLT_MIGRATE_ON_DRAIN"] = "1"
        fleet = launch_inproc_fleet(m, params, _serve_cfg(),
                                    n_replicas=2, n_prefill=0,
                                    lost_after_s=0.5)
        client = ServeClient(fleet.queue_handle())
        try:
            r1 = client.submit(p1, 30, temperature=0.7)
            r2 = client.submit(p2, 30)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                track = fleet.router._inflight.get(r1)
                if (track is not None and track.replica is not None
                        and len(client._pending[r1].tokens) >= 3):
                    victim = track.replica
                    break
                time.sleep(0.01)
            else:
                pytest.fail("request never started streaming")
            next(r for r in fleet.replicas
                 if r.id == victim).kill(hard=False)
            out1 = client.result(r1, timeout=120)
            out2 = client.result(r2, timeout=120)
            assert out1 == ref[0]         # bitwise across the drain
            assert out2 == ref[1]
            assert client.re_emitted_tokens == 0  # nothing recomputed
            c = fleet.router.counters
            assert c["migrations"] >= 1
            assert c["failovers"] == 0 and c["replica_deaths"] == 0
        finally:
            os.environ.pop("RLT_MIGRATE_ON_DRAIN", None)
            client.close()
            fleet.close()

    @pytest.mark.slow  # tier-1 budget audit (round 19): ~10s fleet
    # fit; hedge admission/cancel policy units ride the router rig in
    # tier-1, this drill proves the wire + dedup end to end
    def test_hedge_first_winner_cancels_loser(self, dist_model):
        """A hedged duplicate races a fault-slowed replica: first
        finisher wins, the router cancels the loser's copy, and the
        duplicate stream merges bitwise through the token-index dedup
        (re_emitted_tokens counts the merged copies)."""
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_inproc_fleet

        m, params = dist_model
        p1 = list(range(1, 9))
        ref = _reference_tokens(dist_model, [p1], [0.7], max_new=30)
        fleet = launch_inproc_fleet(m, params, _serve_cfg(),
                                    n_replicas=2, n_prefill=0,
                                    lost_after_s=5.0)
        client = ServeClient(fleet.queue_handle())
        try:
            r1 = client.submit(p1, 30, temperature=0.7)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                track = fleet.router._inflight.get(r1)
                if (track is not None and track.replica is not None
                        and len(client._pending[r1].tokens) >= 3):
                    victim = track.replica
                    break
                time.sleep(0.01)
            else:
                pytest.fail("request never started streaming")
            # Stall the placed replica's decode ticks (the straggler
            # hedging exists for), then duplicate onto a survivor.
            os.environ["RLT_FAULT"] = (
                f"slow@point:replica_tick,replica:{victim},"
                f"secs:0.3,once:0")
            assert client.hedge(r1)
            out1 = client.result(r1, timeout=120)
            assert out1 == ref[0]          # merged stream is bitwise
            assert client.re_emitted_tokens > 0  # copies really merged
            c = fleet.router.counters
            assert c["hedges"] >= 1
            # The router learns the winner from the next done beat —
            # wait out the beat lag before asserting the cancel.
            deadline = time.monotonic() + 15
            while (c["hedge_cancels"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert c["hedge_cancels"] >= 1  # loser copy cancelled
        finally:
            os.environ.pop("RLT_FAULT", None)
            client.close()
            fleet.close()

    @pytest.mark.slow  # tier-1 diet (round 20): ~16s, the largest
    # serve_dist fit; spec x fleet composition is covered via -m slow,
    # fleet_parity_and_zero_recompiles is the tier-1 fleet smoke
    def test_spec_fleet_parity(self, dist_model):
        """Disagg x speculation: draft-capable replicas serve spec
        requests token-for-token like the monolith spec engine (KV
        import feeds the target pool; the draft prefills locally from
        the shipped prompt)."""
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_inproc_fleet
        from ray_lightning_tpu.serve.draft import early_exit_draft

        m, params = dist_model
        draft, draft_params = early_exit_draft(m, params, 1)
        prompts = _prompts(4, seed=3)
        temps = [0.0, 0.8, 0.0, 0.8]
        cfg = {"cfg": {"spec_k": 2}}
        ref = _reference_tokens(dist_model, prompts, temps,
                                draft_module=draft,
                                draft_params=draft_params, **cfg)
        fleet = launch_inproc_fleet(
            m, params, _serve_cfg(spec_k=2), n_replicas=2, n_prefill=1,
            draft_module=draft, draft_params=draft_params,
        )
        client = ServeClient(fleet.queue_handle())
        try:
            rids = [client.submit(p, 8, temperature=t, spec=2)
                    for p, t in zip(prompts, temps)]
            out = [client.result(r, timeout=120) for r in rids]
            assert out == ref
        finally:
            client.close()
            fleet.close()


# ---------------------------------------------------------------------------
# Actor fleet: the 2-actor smoke (tier-1) + chaos (slow)
# ---------------------------------------------------------------------------

@pytest.mark.remote
class TestActorFleet:
    @pytest.mark.slow  # tier-1 diet (round 20): ~15s actor spawn +
    # model build x2; the inproc fleet smoke covers the dataflow in
    # tier-1, the actor shapes run via -m slow with the chaos arm
    def test_two_actor_smoke(self, dist_model, tmp_path):
        """1 prefill actor + 1 decode actor — the full cross-process
        dataflow (dispatch → prefill → segment/queue handoff → import
        → stream) with token parity against the monolith."""
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_actor_fleet

        m, params = dist_model
        prompts = _prompts(3, seed=7)
        temps = [0.0, 0.7, 0.0]
        ref = _reference_tokens(dist_model, prompts, temps)
        fleet = launch_actor_fleet(
            m, params, _serve_cfg(), n_replicas=1, n_prefill=1,
            telemetry_dir=str(tmp_path),
        )
        client = ServeClient(fleet.queue_handle())
        try:
            rids = [client.submit(p, 8, temperature=t)
                    for p, t in zip(prompts, temps)]
            out = [client.result(r, timeout=300) for r in rids]
            assert out == ref
            snap = fleet.router.snapshot()
            assert validate_router_snapshot(snap) == []
            assert snap["counters"]["prefill_dispatches"] == 3
        finally:
            client.close()
            fleet.close()

    @pytest.mark.slow
    def test_actor_chaos_kill_replica_zero_lost(self, dist_model):
        """SIGKILL one of two decode actors under load: every request
        still completes (failover onto the survivor), bitwise-equal to
        the monolith run — the bench chaos arm's shape as a test."""
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_actor_fleet

        m, params = dist_model
        prompts = _prompts(8, seed=11)
        temps = [0.0, 0.6] * 4
        ref = _reference_tokens(dist_model, prompts, temps, max_new=16)
        fleet = launch_actor_fleet(
            m, params, _serve_cfg(), n_replicas=2, n_prefill=0,
            lost_after_s=1.5,
        )
        client = ServeClient(fleet.queue_handle())
        try:
            rids = [client.submit(p, 16, temperature=t)
                    for p, t in zip(prompts, temps)]
            deadline = time.monotonic() + 120
            victim = None
            while time.monotonic() < deadline and victim is None:
                with fleet.router._lock:
                    loads = {}
                    for t in fleet.router._inflight.values():
                        if t.replica:
                            loads[t.replica] = loads.get(t.replica,
                                                         0) + 1
                    started = sum(len(p.tokens) for p in
                                  client._pending.values())
                    if loads and started >= 4:
                        victim = max(loads, key=loads.get)
                time.sleep(0.05)
            assert victim is not None, "load never materialized"
            next(r for r in fleet.replicas
                 if r.id == victim).kill(hard=True)
            out = [client.result(r, timeout=300) for r in rids]
            assert out == ref
            c = fleet.router.counters
            assert c["replica_deaths"] == 1
            assert c["failed_over_requests"] >= 1
        finally:
            client.close()
            fleet.close()
