"""Native runtime layer: CRC32C, segment store, object-store spill.

≙ the role Ray core's C++ plasma store plays under the reference
(SURVEY §2.2): these tests cover the native/fallback format parity, the
corruption gate, and the LocalBackend large-payload spill path that ships
one segment instead of N socket copies.
"""

import os
import subprocess
import sys

import pytest

from ray_lightning_tpu import native
from ray_lightning_tpu.cluster.backend import LocalBackend, ObjectRef
from ray_lightning_tpu.cluster.shm import SegmentStore


def test_crc32c_known_answer():
    if not native.native_available():
        pytest.skip("native library unavailable")
    assert native.crc32c(b"123456789") == 0xE3069283
    # incremental == one-shot
    assert native.crc32c(b"6789", native.crc32c(b"12345")) == 0xE3069283


def test_segment_roundtrip(tmp_path):
    payload = os.urandom(300_000)
    path = str(tmp_path / "seg")
    native.write_segment(path, payload)
    assert native.segment_len(path) == len(payload)
    assert native.read_segment(path) == payload


def test_segment_write_once(tmp_path):
    path = str(tmp_path / "seg")
    native.write_segment(path, b"a")
    with pytest.raises((native.SegmentError, FileExistsError)):
        native.write_segment(path, b"b")


def test_segment_corruption_detected(tmp_path):
    payload = os.urandom(4096)
    path = str(tmp_path / "seg")
    native.write_segment(path, payload)
    with open(path, "r+b") as f:
        f.seek(native.SEGMENT_HEADER_SIZE + 100)
        f.write(b"\xff" * 4 if payload[100:104] != b"\xff" * 4 else b"\x00" * 4)
    with pytest.raises(native.SegmentError):
        native.read_segment(path)
    # unverified read still returns (corrupted) bytes — caller's choice
    assert len(native.read_segment(path, verify=False)) == len(payload)


def test_fallback_format_interop(tmp_path):
    """A segment written by the pure-Python fallback (zlib tag) must read
    back through the native path, and vice versa."""
    payload = os.urandom(65536)
    fb_path = str(tmp_path / "fallback-seg")
    code = (
        "import os; os.environ['RLT_DISABLE_NATIVE']='1';"
        "from ray_lightning_tpu import native;"
        f"native.write_segment({fb_path!r}, open({fb_path!r}+'.in','rb').read());"
        f"print(len(native.read_segment({fb_path!r})))"
    )
    with open(fb_path + ".in", "wb") as f:
        f.write(payload)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        check=True, cwd=repo_root,
    )
    assert out.stdout.strip() == str(len(payload))
    # native (or current-process) reader accepts the zlib-tagged file
    assert native.read_segment(fb_path) == payload


def test_header_length_corruption_rejected(tmp_path):
    """A bit-flipped length field must raise, not drive a huge alloc."""
    import struct

    path = str(tmp_path / "seg")
    native.write_segment(path, b"payload")
    with open(path, "r+b") as f:
        f.seek(8)
        f.write(struct.pack("<Q", 1 << 60))
    with pytest.raises(native.SegmentError, match="claims"):
        native.read_segment(path)


def test_stale_segment_sweep(tmp_path, monkeypatch):
    """Segments owned by a dead pid are reclaimed by the next store."""
    from ray_lightning_tpu.cluster import shm

    monkeypatch.setattr(shm, "segment_dir", lambda: str(tmp_path))
    dead_pid = 2 ** 22 + 11  # above default pid_max ⇒ never alive
    stale = tmp_path / f"rlt-seg-{dead_pid}-{'0' * 32}"
    stale.write_bytes(b"leak")
    live = tmp_path / f"rlt-seg-{os.getpid()}-{'1' * 32}"
    live.write_bytes(b"mine")
    assert shm.sweep_stale_segments() == 1
    assert not stale.exists() and live.exists()


def test_segment_store_lifecycle():
    store = SegmentStore()
    path = store.put(b"x" * 1000)
    assert os.path.exists(path)
    assert SegmentStore.get(path) == b"x" * 1000
    store.unlink_all()
    assert not os.path.exists(path)


def _identity(ref):
    return ref.get()


def test_local_backend_spills_large_payloads_to_segment():
    backend = LocalBackend(min_segment_bytes=1024)
    try:
        small = backend.put({"a": 1})
        big = backend.put({"blob": os.urandom(100_000)})
        assert small._segment_path is None
        assert big._segment_path is not None
        assert big.nbytes > 100_000
        # An actor on this host materializes the object from the segment.
        actor = backend.create_actor("seg-reader")
        out = actor.execute(_identity, big)
        assert out["blob"] == big.get()["blob"]
    finally:
        backend.shutdown()
    assert not os.path.exists(big._segment_path)
