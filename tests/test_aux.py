"""Auxiliary subsystems: profiler tracing, input prefetch, downsizing
resume, PBT over the flagship model.

Widens the test taxonomy toward the reference's full grid (SURVEY §4/§5):
profiling (net-new — reference has none), resume-with-fewer-workers
(≙ ``test_ddp_sharded.py:119-138``), and the BASELINE #5 config shape
(PBT sweep of GPT LR) at test scale.
"""

import os

import numpy as np
import pytest

from ray_lightning_tpu.core.callbacks import ProfilerCallback
from ray_lightning_tpu.core.loop import _prefetched
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models.boring import BoringDataModule, BoringModel
from ray_lightning_tpu.models.gpt import GPT, GPTConfig, SyntheticLMDataModule
from ray_lightning_tpu.parallel.strategies import LocalStrategy, RayStrategy


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_profiler_callback_writes_trace(tmp_path):
    cb = ProfilerCallback(start_step=1, num_steps=2)
    trainer = Trainer(
        strategy=LocalStrategy(),
        max_epochs=1,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=6,
        limit_val_batches=1,
        callbacks=[cb],
    )
    trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert cb.trace_dir is not None
    # jax.profiler writes plugins/profile/<ts>/*.pb under the trace dir.
    found = [
        os.path.join(r, f)
        for r, _, fs in os.walk(cb.trace_dir) for f in fs
    ]
    assert found, "profiler produced no trace files"


def test_profiler_callback_survives_short_run(tmp_path):
    """Window extends past the end of training: teardown closes the trace."""
    cb = ProfilerCallback(start_step=0, num_steps=100)
    trainer = Trainer(
        strategy=LocalStrategy(),
        max_epochs=1,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=2,
        limit_val_batches=0,
        callbacks=[cb],
    )
    trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert not cb._active
    assert cb.trace_dir is not None  # the window did open
    assert any(files for _, _, files in os.walk(cb.trace_dir))


def test_prefetched_preserves_order_and_errors():
    # Items arrive as (placed, n_inner) pairs since the megastep round
    # (n_inner == 1 when no stacking is configured).
    out = list(_prefetched(range(10), lambda x: x * 2))
    assert out == [(2 * i, 1) for i in range(10)]

    def boom():
        yield 1
        raise RuntimeError("loader died")

    it = _prefetched(boom(), lambda x: x)
    assert next(it) == (1, 1)
    with pytest.raises(RuntimeError, match="loader died"):
        list(it)


def test_prefetched_stacks_strides_within_budget():
    """stack=4 over 10 items with an 8-item stride budget: two full
    strides, then per-item singles (the megastep grouping contract)."""
    out = list(_prefetched(
        range(10), lambda x: x, stack=4, stack_limit=8,
        place_stride=lambda xs: tuple(xs),
    ))
    assert out == [
        ((0, 1, 2, 3), 4), ((4, 5, 6, 7), 4), (8, 1), (9, 1),
    ]


def test_prefetched_early_break_stops_cleanly():
    import threading

    before = threading.active_count()
    for item, _n in _prefetched(range(1000), lambda x: x):
        if item == 3:
            break
    import time

    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_resume_with_fewer_workers(tmp_path):
    """Checkpoints are topology-independent: fit on 2 workers, resume on 1
    (≙ reference downsizing test, test_ddp_sharded.py:119-138)."""
    first = Trainer(
        strategy=RayStrategy(num_workers=2),
        max_epochs=1,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=2,
        limit_val_batches=1,
    )
    first.fit(BoringModel(), BoringDataModule(batch_size=16))
    path = str(tmp_path / "downsize.ckpt")
    first.save_checkpoint(path)

    resumed = Trainer(
        strategy=RayStrategy(num_workers=1),
        max_epochs=3,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=2,
        limit_val_batches=1,
        resume_from_checkpoint=path,
    )
    resumed.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert resumed.epochs_run == 3
    assert resumed.global_step > first.global_step
    assert np.isfinite(resumed.callback_metrics["train_loss"])


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_pbt_sweep_of_gpt_lr(tmp_path):
    """BASELINE #5 shape at test scale: PBT explores GPT learning rates."""
    from ray_lightning_tpu.tune import TuneReportCallback
    from ray_lightning_tpu.tuning import (
        PopulationBasedTraining,
        loguniform,
        tune_run,
    )

    def train_gpt(config):
        cfg = GPTConfig(vocab_size=128, n_layer=1, n_head=2, d_model=32,
                        seq_len=32, lr=config["lr"], warmup_steps=1)
        trainer = Trainer(
            strategy=LocalStrategy(),
            max_epochs=2,
            default_root_dir=str(tmp_path),
            enable_checkpointing=False,
            limit_train_batches=2,
            limit_val_batches=1,
            callbacks=[TuneReportCallback({"loss": "val_loss"},
                                          on="validation_end")],
        )
        trainer.fit(GPT(cfg), SyntheticLMDataModule(cfg, batch_size=8,
                                                    num_batches=2))

    pbt = PopulationBasedTraining(
        metric="loss", mode="min", perturbation_interval=1,
        hyperparam_mutations={"lr": loguniform(1e-4, 1e-2)},
    )
    analysis = tune_run(
        train_gpt,
        config={"lr": loguniform(1e-4, 1e-2)},
        num_samples=3,
        scheduler=pbt,
        metric="loss",
        mode="min",
        local_dir=str(tmp_path / "pbt"),
        verbose=False,
    )
    assert analysis.best_config is not None
    assert np.isfinite(analysis.best_result["loss"])


def test_compile_cache_knob(tmp_path, monkeypatch):
    """RLT_COMPILE_CACHE: the fit enables jax's persistent compilation
    cache and populates the directory; workers additionally receive
    JAX_COMPILATION_CACHE_DIR through the strategy env bus."""
    import os

    import numpy as np

    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models import BoringDataModule, BoringModel
    from ray_lightning_tpu.parallel.strategies import LocalStrategy, RayStrategy

    cache = str(tmp_path / "xla_cache")
    monkeypatch.setenv("RLT_COMPILE_CACHE", cache)

    s = RayStrategy(num_workers=1)
    assert s.env_per_worker["JAX_COMPILATION_CACHE_DIR"] == cache
    # Threshold mirrored to workers (jax's ~1s default would skip fast
    # compiles nondeterministically).
    assert s.env_per_worker[
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] == "0"

    trainer = Trainer(strategy=LocalStrategy(), max_epochs=1,
                      default_root_dir=str(tmp_path),
                      enable_checkpointing=False)
    trainer.fit(BoringModel(), BoringDataModule())
    assert np.isfinite(trainer.callback_metrics["train_loss"])
    assert os.path.isdir(cache) and os.listdir(cache), (
        "compilation cache dir not populated"
    )
    # Eval/predict-only sessions enable the cache too.
    n_before = len(os.listdir(cache))
    preds = trainer.predict(BoringModel(), BoringDataModule())
    assert len(preds) > 0
    assert len(os.listdir(cache)) >= n_before


def test_compile_cache_knob_disables_on_unset(tmp_path, monkeypatch):
    """Unsetting RLT_COMPILE_CACHE before a later compile really stops
    cache writes (jax memoizes its cache decision — the disable path
    must reset it, not just flip the config)."""
    import jax as _jax
    import jax.numpy as _jnp

    from ray_lightning_tpu.core.loop import _enable_compile_cache

    cache = str(tmp_path / "xla_cache2")
    monkeypatch.setenv("RLT_COMPILE_CACHE", cache)
    _enable_compile_cache()
    assert _jax.config.jax_compilation_cache_dir == cache
    # Force a compile so jax initializes (and memoizes) the cache.
    _jax.jit(lambda x: x * 2 + 1)(_jnp.arange(7)).block_until_ready()
    n_on = len(os.listdir(cache))
    assert n_on > 0

    monkeypatch.delenv("RLT_COMPILE_CACHE")
    _enable_compile_cache()
    assert _jax.config.jax_compilation_cache_dir is None
    # A NEW compile in the "off" arm must not write the old directory.
    _jax.jit(lambda x: x * 3 - 4)(_jnp.arange(11)).block_until_ready()
    assert len(os.listdir(cache)) == n_on


class TestSWA:
    def test_swa_params_are_epoch_mean(self, tmp_path):
        """The final params equal the running mean of the end-of-epoch
        params from swa_start_epoch onward."""
        import jax

        from ray_lightning_tpu.core.callbacks import (
            Callback, StochasticWeightAveraging,
        )
        from ray_lightning_tpu.core.trainer import Trainer
        from ray_lightning_tpu.models import BoringDataModule, BoringModel
        from ray_lightning_tpu.parallel.strategies import LocalStrategy

        class Spy(Callback):
            def __init__(self):
                self.snaps = []

            def on_train_epoch_end(self, trainer, module):
                self.snaps.append(jax.device_get(trainer.state.params))

        spy, swa = Spy(), StochasticWeightAveraging(swa_start_epoch=1)
        trainer = Trainer(
            strategy=LocalStrategy(), max_epochs=4,
            # Spy FIRST so it snapshots the raw trained params before
            # SWA folds them into its mean.
            callbacks=[spy, swa],
            default_root_dir=str(tmp_path), enable_checkpointing=False,
        )
        trainer.fit(BoringModel(), BoringDataModule())
        tail = spy.snaps[1:]  # epochs 1..3
        expect = jax.tree_util.tree_map(
            lambda *xs: sum(np.asarray(x, np.float64) for x in xs)
            / len(xs), *tail)
        got = jax.device_get(trainer.state.params)
        for a, b in zip(jax.tree_util.tree_leaves(expect),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_allclose(np.asarray(b), a, rtol=1e-5,
                                       atol=1e-7)
        # And the SWA point differs from the last epoch's raw params.
        last = jax.tree_util.tree_leaves(spy.snaps[-1])
        assert any(
            np.abs(np.asarray(x) - np.asarray(y)).max() > 1e-8
            for x, y in zip(last, jax.tree_util.tree_leaves(got))
        )

    def test_swa_under_sharded_mesh(self, tmp_path):
        """SWA composes with GSPMD sharding (shard-local averaging)."""
        import jax

        from ray_lightning_tpu.core.callbacks import StochasticWeightAveraging
        from ray_lightning_tpu.core.trainer import Trainer
        from ray_lightning_tpu.models import BoringDataModule, BoringModel
        from ray_lightning_tpu.parallel.strategies import LocalStrategy

        trainer = Trainer(
            strategy=LocalStrategy(mesh_axes={"data": 4, "fsdp": 2},
                                   zero_stage=3),
            max_epochs=3,
            callbacks=[StochasticWeightAveraging(swa_start_epoch=1)],
            default_root_dir=str(tmp_path), enable_checkpointing=False,
        )
        trainer.fit(BoringModel(), BoringDataModule())
        assert np.isfinite(trainer.callback_metrics["train_loss"])
        leaves = jax.tree_util.tree_leaves(trainer.params)
        assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)


def test_swa_resets_between_fits(tmp_path):
    """One SWA instance across two fits must not fold the first model's
    weights into the second fit's average."""
    from ray_lightning_tpu.core.callbacks import StochasticWeightAveraging
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models import BoringDataModule, BoringModel
    from ray_lightning_tpu.parallel.strategies import LocalStrategy

    swa = StochasticWeightAveraging(swa_start_epoch=0)
    for _ in range(2):
        tr = Trainer(strategy=LocalStrategy(), max_epochs=2,
                     callbacks=[swa], default_root_dir=str(tmp_path),
                     enable_checkpointing=False)
        tr.fit(BoringModel(), BoringDataModule())
        assert swa._count == 2  # epochs of THIS fit only


def test_async_checkpoint_writes(tmp_path):
    """ModelCheckpoint(async_write=True): files are durable by fit end,
    top-k pruning holds, and the checkpoint resumes."""
    from ray_lightning_tpu.core.callbacks import ModelCheckpoint
    from ray_lightning_tpu.models import BoringDataModule, BoringModel
    from ray_lightning_tpu.parallel.strategies import LocalStrategy

    ckpt_dir = str(tmp_path / "ckpts")
    cb = ModelCheckpoint(dirpath=ckpt_dir, save_top_k=2,
                         async_write=True)
    trainer = Trainer(strategy=LocalStrategy(), max_epochs=4,
                      callbacks=[cb], default_root_dir=str(tmp_path),
                      enable_checkpointing=False)
    trainer.fit(BoringModel(), BoringDataModule())
    files = sorted(os.listdir(ckpt_dir))
    assert len(files) == 2, files  # top-k pruned, all writes durable
    assert cb.best_model_path and os.path.exists(cb.best_model_path)

    trainer2 = Trainer(strategy=LocalStrategy(), max_epochs=5,
                       default_root_dir=str(tmp_path),
                       enable_checkpointing=False,
                       resume_from_checkpoint=cb.best_model_path)
    trainer2.fit(BoringModel(), BoringDataModule())
    assert trainer2.global_step > trainer.global_step


def test_async_checkpoint_write_failure_raises(tmp_path, monkeypatch):
    """A failed BACKGROUND write (not the sync makedirs) must surface as
    a RuntimeError at flush — the deferred-error machinery itself."""
    import ray_lightning_tpu.core.loop as loop_mod
    from ray_lightning_tpu.core.loop import LoopContext, FitConfig

    def boom(stream, path):
        raise OSError("disk gone")

    monkeypatch.setattr(loop_mod, "state_stream_to_file", boom)
    ctx = LoopContext(FitConfig(max_epochs=1), 0, 1)
    ctx.state = None
    monkeypatch.setattr(ctx, "checkpoint_payload", lambda: {"state": {}})
    ctx.save_checkpoint(str(tmp_path / "x.ckpt"), async_write=True)
    with pytest.raises(RuntimeError, match="async checkpoint write"):
        ctx.flush_checkpoints()
    ctx.close_checkpoint_writer()


def test_async_checkpoint_writer_retires_per_fit(tmp_path):
    """The writer thread is per-fit, not per-process: after fit end no
    rlt-ckpt-writer thread survives (tuner sweeps run many fits)."""
    import threading as _threading

    from ray_lightning_tpu.core.callbacks import ModelCheckpoint
    from ray_lightning_tpu.models import BoringDataModule, BoringModel
    from ray_lightning_tpu.parallel.strategies import LocalStrategy

    for _ in range(2):
        cb = ModelCheckpoint(dirpath=str(tmp_path / "c"), async_write=True)
        tr = Trainer(strategy=LocalStrategy(), max_epochs=1,
                     callbacks=[cb], default_root_dir=str(tmp_path),
                     enable_checkpointing=False)
        tr.fit(BoringModel(), BoringDataModule())
    alive = [t.name for t in _threading.enumerate()
             if t.name == "rlt-ckpt-writer"]
    assert not alive, alive


class TestEMA:
    def test_ema_tracks_exponential_mean(self, tmp_path):
        """The shadow equals the analytically-compounded EMA of the
        per-step params (replayed on host from snapshots)."""
        import jax

        from ray_lightning_tpu.core.callbacks import (
            Callback, ExponentialMovingAverage,
        )
        from ray_lightning_tpu.core.trainer import Trainer
        from ray_lightning_tpu.models import BoringDataModule, BoringModel
        from ray_lightning_tpu.parallel.strategies import LocalStrategy

        class Spy(Callback):
            def __init__(self):
                self.snaps = []

            def on_train_batch_end(self, trainer, module, logs, i):
                self.snaps.append(jax.device_get(trainer.state.params))

        d = 0.9
        spy, ema = Spy(), ExponentialMovingAverage(decay=d)
        trainer = Trainer(strategy=LocalStrategy(), max_epochs=2,
                         callbacks=[spy, ema],  # spy first: raw params
                         default_root_dir=str(tmp_path),
                         enable_checkpointing=False)
        trainer.fit(BoringModel(), BoringDataModule())
        expect = None
        for p in spy.snaps:
            if expect is None:
                expect = jax.tree_util.tree_map(
                    lambda a: np.asarray(a, np.float64), p)
            else:
                expect = jax.tree_util.tree_map(
                    lambda e, a: e * d + np.asarray(a, np.float64) * (1 - d),
                    expect, p)
        got = jax.device_get(trainer.params)  # swap_at_end=True
        for a, b in zip(jax.tree_util.tree_leaves(expect),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_allclose(np.asarray(b), a, rtol=1e-5,
                                       atol=1e-7)

    def test_ema_cadence_compounds_decay(self, tmp_path):
        """update_every_n_steps=k: updates fire every k OPTIMIZER steps
        with decay compounded as decay**advanced — verified against an
        analytic host replay of exactly that rule."""
        import jax

        from ray_lightning_tpu.core.callbacks import (
            Callback, ExponentialMovingAverage,
        )
        from ray_lightning_tpu.core.trainer import Trainer
        from ray_lightning_tpu.models import BoringDataModule, BoringModel
        from ray_lightning_tpu.parallel.strategies import LocalStrategy

        class Spy(Callback):
            def __init__(self):
                self.snaps = []  # (global_step, params)

            def on_train_batch_end(self, trainer, module, logs, i):
                self.snaps.append(
                    (trainer.global_step,
                     jax.device_get(trainer.state.params)))

        d, k = 0.9, 2
        spy = Spy()
        ema = ExponentialMovingAverage(decay=d, update_every_n_steps=k,
                                       swap_at_end=False)
        trainer = Trainer(strategy=LocalStrategy(), max_epochs=2,
                         callbacks=[spy, ema],
                         default_root_dir=str(tmp_path),
                         enable_checkpointing=False)
        trainer.fit(BoringModel(), BoringDataModule())

        expect, last = None, None
        for gs, p in spy.snaps:
            if gs == 0 or gs == last:
                continue
            if expect is None:
                expect = jax.tree_util.tree_map(
                    lambda a: np.asarray(a, np.float64), p)
                last = gs
                continue
            if gs - last < k:
                continue
            dd = d ** (gs - last)
            expect = jax.tree_util.tree_map(
                lambda e, a: e * dd + np.asarray(a, np.float64) * (1 - dd),
                expect, p)
            last = gs
        shadow = jax.device_get(ema.ema_params)
        for a, b in zip(jax.tree_util.tree_leaves(expect),
                        jax.tree_util.tree_leaves(shadow)):
            np.testing.assert_allclose(np.asarray(b), a, rtol=1e-5,
                                       atol=1e-7)
        # swap_at_end=False: returned params are the RAW trained ones.
        raw = jax.tree_util.tree_leaves(jax.device_get(trainer.params))
        assert any(
            np.abs(np.asarray(a) - np.asarray(b)).max() > 1e-9
            for a, b in zip(raw, jax.tree_util.tree_leaves(shadow)))

    def test_ema_respects_grad_accumulation(self, tmp_path):
        """Under accumulate_grad_batches the EMA advances per OPTIMIZER
        step, not per micro-batch: the horizon is what the user set."""
        from ray_lightning_tpu.core.callbacks import ExponentialMovingAverage
        from ray_lightning_tpu.core.trainer import Trainer
        from ray_lightning_tpu.models import BoringDataModule, BoringModel
        from ray_lightning_tpu.parallel.strategies import LocalStrategy

        ema = ExponentialMovingAverage(decay=0.5, swap_at_end=False)
        trainer = Trainer(strategy=LocalStrategy(), max_epochs=1,
                         accumulate_grad_batches=2, callbacks=[ema],
                         default_root_dir=str(tmp_path),
                         enable_checkpointing=False)
        trainer.fit(BoringModel(), BoringDataModule())
        # 4 micro-batches -> 2 optimizer steps: seed at gs=1 plus ONE
        # decay update at gs=2.
        assert trainer.global_step == 2
        assert ema._last_step == 2

    def test_ema_shadow_survives_remote_roundtrip(self, tmp_path):
        """swap_at_end=False on a REMOTE strategy: the shadow ships in
        the callback state, so the driver-side callback has it."""
        import jax

        from ray_lightning_tpu.core.callbacks import ExponentialMovingAverage
        from ray_lightning_tpu.core.trainer import Trainer
        from ray_lightning_tpu.models import BoringDataModule, BoringModel
        from ray_lightning_tpu.parallel.strategies import RayStrategy

        ema = ExponentialMovingAverage(decay=0.9, swap_at_end=False)
        trainer = Trainer(strategy=RayStrategy(num_workers=1), max_epochs=2,
                         callbacks=[ema], default_root_dir=str(tmp_path),
                         enable_checkpointing=False)
        trainer.fit(BoringModel(), BoringDataModule())
        assert ema.ema_params is not None  # restored driver-side
        leaves = jax.tree_util.tree_leaves(ema.ema_params)
        assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)

    def test_ema_rejects_bad_args(self):
        from ray_lightning_tpu.core.callbacks import ExponentialMovingAverage

        with pytest.raises(ValueError):
            ExponentialMovingAverage(decay=1.0)
        with pytest.raises(ValueError):
            ExponentialMovingAverage(update_every_n_steps=0)
