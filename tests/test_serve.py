"""Serving plane: paged KV cache vs the static path, continuous batching.

The correctness contract mirrors the repo's grad-parity discipline:
``models/generate.py`` (the static one-cache-per-batch path) is the
reference — a request served through the paged cache must produce
exactly the tokens ``generate()`` would, regardless of what else is in
flight, which blocks it landed on, or how many times it was preempted.
On top: block free/reuse correctness, the zero-recompile steady-state
guarantee (via the telemetry recompile counter), scheduler policy
units, SLO stats schema, and the DriverQueue client plane.
"""

import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.generate import generate
from ray_lightning_tpu.models.gpt import GPT, GPTConfig
from ray_lightning_tpu.serve.engine import (
    ServeConfig, ServeEngine, ServeRejected,
)
from ray_lightning_tpu.serve.kv_cache import (
    TRASH_BLOCK, BlockAllocator, PagedKVCache, paged_decode_step,
    paged_prefill,
)
from ray_lightning_tpu.serve.metrics import ServeStats, percentile
from ray_lightning_tpu.serve.scheduler import (
    Request, Scheduler, default_buckets,
)
from ray_lightning_tpu.telemetry import compile_event_count

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def model():
    cfg = GPTConfig(vocab_size=128, n_layer=2, n_head=4, d_model=64,
                    seq_len=64, warmup_steps=1)
    m = GPT(cfg, attn_impl="xla")
    params = m.init_params(jax.random.PRNGKey(0))
    return m, params


def _ref_tokens(m, params, prompt, n):
    """Static-path greedy reference continuation."""
    out = generate(m, params, jnp.asarray([prompt], jnp.int32), n)
    return np.asarray(out)[0, len(prompt):].tolist()


def _rand_prompt(seed, length, vocab):
    rng = np.random.default_rng(seed)
    return rng.integers(1, vocab, size=(length,)).tolist()


# ---------------------------------------------------------------------------
# Block allocator + scheduler policy (jax-free units)
# ---------------------------------------------------------------------------

class TestAllocator:
    def test_alloc_free_reuse(self):
        a = BlockAllocator(6)
        assert a.free_blocks == 5  # block 0 reserved
        ids = a.alloc(3)
        assert len(ids) == 3 and TRASH_BLOCK not in ids
        assert a.alloc(3) is None          # all-or-nothing
        assert a.free_blocks == 2
        a.free(ids)
        assert a.free_blocks == 5
        again = a.alloc(5)
        assert sorted(again) == [1, 2, 3, 4, 5]

    def test_double_free_raises(self):
        a = BlockAllocator(4)
        ids = a.alloc(1)
        a.free(ids)
        with pytest.raises(RuntimeError, match="double-free"):
            a.free(ids)
        with pytest.raises(RuntimeError, match="not live"):
            a.free([2])

    def test_too_small_pool_rejected(self):
        with pytest.raises(ValueError, match="reserved"):
            BlockAllocator(1)


class TestSchedulerPolicy:
    def _sched(self, num_slots=2, num_blocks=9, max_queue=4):
        alloc = BlockAllocator(num_blocks)
        return Scheduler(num_slots, alloc, block_size=4,
                         max_blocks_per_seq=4, buckets=[4, 8, 16],
                         max_queue=max_queue)

    def _req(self, rid, prompt_len=3, max_new=4, **kw):
        return Request(rid=rid, prompt=list(range(1, prompt_len + 1)),
                       max_new_tokens=max_new, **kw)

    def test_default_buckets_cover_max_prompt(self):
        assert default_buckets(16, 100) == [16, 32, 64, 128]
        assert default_buckets(8, 8) == [8]

    def test_bucket_for_picks_smallest_cover(self):
        s = self._sched()
        assert s.bucket_for(3) == 4
        assert s.bucket_for(4) == 4
        assert s.bucket_for(5) == 8
        with pytest.raises(ValueError, match="exceeds"):
            s.bucket_for(17)

    def test_admission_fifo_and_slot_fill(self):
        s = self._sched()
        for i in range(3):
            assert s.submit(self._req(f"r{i}"))
        admissions, expired = s.poll(now=0.0)
        assert not expired
        assert [r.rid for _, r, _ in admissions] == ["r0", "r1"]
        assert s.queue_depth == 1 and s.active_slots == 2
        # Slot rows populated for the compiled step.
        for slot, req, bucket in admissions:
            assert bucket == 4
            assert s.seq_lens[slot] == req.prompt_len
            assert s.block_tables[slot, 0] != TRASH_BLOCK

    def test_backpressure_rejects_beyond_max_queue(self):
        s = self._sched(max_queue=2)
        assert s.submit(self._req("a")) and s.submit(self._req("b"))
        rej = self._req("c")
        assert not s.submit(rej)
        assert rej.done_reason == "rejected"

    def test_deadline_expires_queued_requests(self):
        s = self._sched()
        req = self._req("late", deadline_s=0.5)
        req.arrival_t = 100.0
        s.submit(req)
        admissions, expired = s.poll(now=101.0)
        assert not admissions and [r.rid for r in expired] == ["late"]
        assert req.done_reason == "expired"

    def test_growth_and_preemption_frees_youngest(self):
        # Pool of 8 usable blocks, two admitted sequences (1 block
        # each); exhaust the rest, then growth must preempt the
        # YOUNGER request and requeue it at the front.
        s = self._sched(num_blocks=9)
        s.submit(self._req("old", prompt_len=4))
        s.submit(self._req("young", prompt_len=4))
        (s0, old, _), (s1, young, _) = s.poll(now=0.0)[0]
        hog = s.allocator.alloc(6)
        s.seq_lens[s0] += 4  # next write crosses into block 2
        assert s.needs_block(s0) and not s.grow(s0)
        victim = s.preempt_youngest(protect=s0)
        assert victim is young and victim.preemptions == 1
        assert s.queue[0].rid == "young"
        s.allocator.free(hog)
        assert s.grow(s0)
        # The freed slot is admissible again.
        admissions, _ = s.poll(now=1.0)
        assert [r.rid for _, r, _ in admissions] == ["young"]

    def test_finish_releases_everything(self):
        s = self._sched()
        s.submit(self._req("a"))
        (slot, req, _), = s.poll(now=0.0)[0]
        free_before = s.allocator.free_blocks
        s.append_token(slot, 7, now=0.1)
        done = s.finish(slot, now=0.2)
        assert done.state.value == "finished"
        assert s.slots[slot] is None
        assert (s.block_tables[slot] == TRASH_BLOCK).all()
        assert s.allocator.free_blocks == free_before + 1

    def test_preempted_request_survives_deadline_on_requeue(self):
        """deadline_s is a TTFT-at-admission SLO: a request that already
        streamed tokens and was preempted back into the queue must NOT
        be expired on re-admission, however late it is."""
        s = self._sched()
        req = self._req("a", deadline_s=0.5)
        req.arrival_t = 100.0
        s.submit(req)
        (slot, r, _), = s.poll(now=100.1)[0]
        s.append_token(slot, 7, now=100.2)  # first token delivered
        assert s.preempt_youngest() is req
        admissions, expired = s.poll(now=200.0)  # way past the deadline
        assert not expired
        assert [x.rid for _, x, _ in admissions] == ["a"]

    def test_raising_on_token_does_not_break_append(self):
        s = self._sched()

        def bad(i, t):
            raise RuntimeError("consumer bug")

        s.submit(self._req("a", on_token=bad, max_new=1))
        (slot, req, _), = s.poll(now=0.0)[0]
        assert s.append_token(slot, 5) is True
        assert req.generated == [5]


# ---------------------------------------------------------------------------
# Paged cache vs the static path (device programs)
# ---------------------------------------------------------------------------

class TestPagedParity:
    def test_prefill_logits_match_full_forward(self, model):
        """A padded-bucket prefill == the full forward's logits at the
        last VALID prompt position, and the written blocks hold exactly
        the contiguous cache's k/v."""
        m, params = model
        cfg = m.config
        toks = _rand_prompt(1, 5, cfg.vocab_size)
        full = np.asarray(m.forward(params, jnp.asarray([toks])))
        cache = PagedKVCache(cfg, num_blocks=8, block_size=8)
        pool = cache.init_pool()
        ids = cache.allocator.alloc(1)
        padded = np.zeros((8,), np.int32)
        padded[:5] = toks
        logits, pool = paged_prefill(
            cfg, params, pool, jnp.asarray(padded), jnp.int32(5),
            jnp.asarray(np.asarray(ids, np.int32)),
        )
        np.testing.assert_allclose(
            np.asarray(logits), full[0, 4], rtol=1e-4, atol=1e-4
        )
        # Cache content parity against the static path.
        from ray_lightning_tpu.models.generate import init_kv_cache, prefill
        ref_cache = init_kv_cache(cfg, 1, 8)
        _, ref_cache = prefill(cfg, params, ref_cache,
                               jnp.asarray(padded[None, :5]))
        got_k = np.asarray(pool["k"][:, ids[0], :5])
        np.testing.assert_allclose(
            got_k, np.asarray(ref_cache["k"][:, 0, :5]),
            rtol=1e-5, atol=1e-5,
        )

    @pytest.mark.slow  # tier-1 diet (round 20): ~11s token-by-token
    # sweep; prefill_logits parity is the tier-1 paged-parity smoke
    def test_teacher_forced_decode_matches_full_forward(self, model):
        """Feeding tokens one-by-one through the PAGED cache reproduces
        the full forward's logits at every position — across block
        boundaries and with the sequence's blocks deliberately
        scattered through the pool."""
        m, params = model
        cfg = m.config
        toks = np.asarray(_rand_prompt(2, 15, cfg.vocab_size))
        full = np.asarray(m.forward(params, jnp.asarray([toks])))
        cache = PagedKVCache(cfg, num_blocks=16, block_size=4)
        pool = cache.init_pool()
        # Non-contiguous physical placement: logical block i lands on
        # physical block 2i+1.
        phys = [1, 3, 5, 7]
        bt = np.full((2, 4), TRASH_BLOCK, np.int32)
        seq_lens = np.zeros((2,), np.int32)
        for t in range(15):
            if t % 4 == 0:
                bt[0, t // 4] = phys[t // 4]
            logits, pool = paged_decode_step(
                cfg, params, pool, jnp.asarray(bt),
                jnp.asarray(seq_lens),
                jnp.asarray(np.array([toks[t], 0], np.int32)),
            )
            np.testing.assert_allclose(
                np.asarray(logits)[0], full[0, t], rtol=1e-4, atol=1e-4
            )
            seq_lens[0] += 1


# ---------------------------------------------------------------------------
# Engine acceptance: continuous batching == isolated static decoding
# ---------------------------------------------------------------------------

class TestEngine:
    def test_single_request_matches_generate(self, model):
        m, params = model
        eng = ServeEngine(m, params, ServeConfig(num_slots=2,
                                                 block_size=8))
        prompt = _rand_prompt(3, 7, m.config.vocab_size)
        assert eng.generate(prompt, 9) == _ref_tokens(m, params, prompt, 9)

    def test_join_on_arrival_matches_isolated(self, model):
        """A request admitted MID-decode of another must not disturb
        either: both match their isolated static-path rollouts."""
        m, params = model
        eng = ServeEngine(m, params, ServeConfig(num_slots=4,
                                                 block_size=8))
        p1 = _rand_prompt(4, 6, m.config.vocab_size)
        p2 = _rand_prompt(5, 11, m.config.vocab_size)
        h1 = eng.submit(p1, 12)
        for _ in range(4):  # p1 alone for a few decode steps
            eng.step()
        h2 = eng.submit(p2, 8)  # joins the running batch
        eng.run_until_idle()
        assert h1.result(5) == _ref_tokens(m, params, p1, 12)
        assert h2.result(5) == _ref_tokens(m, params, p2, 8)
        assert eng.snapshot()["counters"]["completed"] == 2

    @pytest.mark.slow  # tier-1 diet (round 20): ~8s multi-wave fit;
    # join_on_arrival + preemption keep block reuse covered in tier-1
    def test_block_free_and_reuse_is_clean(self, model):
        """After a request finishes its blocks are reused by the next
        admission — stale cache content leaking through would corrupt
        the successor's tokens."""
        m, params = model
        # 5 usable blocks: a full max_model_len sequence needs 4, so
        # consecutive requests MUST reuse each other's blocks.
        eng = ServeEngine(m, params, ServeConfig(
            num_slots=1, block_size=8, num_blocks=6, max_model_len=32,
        ))
        for seed in (6, 7, 8):
            prompt = _rand_prompt(seed, 9, m.config.vocab_size)
            assert eng.generate(prompt, 12) == _ref_tokens(
                m, params, prompt, 12
            )
        snap = eng.snapshot()
        assert snap["gauges"]["blocks_free"] == 5.0
        assert snap["counters"]["completed"] == 3

    def test_steady_state_triggers_zero_recompiles(self, model):
        """The acceptance bar: after warmup, join-on-arrival traffic of
        mixed prompt lengths (same buckets) and evict-on-finish churn
        must not trigger a single XLA compile (telemetry counter)."""
        m, params = model
        eng = ServeEngine(m, params, ServeConfig(num_slots=3,
                                                 block_size=8))
        # Warmup: one request per bucket the traffic will use.
        eng.generate(_rand_prompt(9, 5, m.config.vocab_size), 4)   # b=8
        eng.generate(_rand_prompt(10, 12, m.config.vocab_size), 4)  # b=16
        eng.stats = ServeStats()  # count steady-state traffic only
        before = compile_event_count()
        for seed in range(8):
            eng.submit(
                _rand_prompt(20 + seed, 3 + (seed % 12), 128),
                3 + seed % 5,
            )
        eng.run_until_idle()
        assert eng.snapshot()["counters"]["completed"] == 8
        assert compile_event_count() - before == 0

    def test_preemption_under_block_exhaustion(self, model):
        """Pool too small for two full sequences: the younger request
        is preempted (recompute) and BOTH still match the static path
        bitwise."""
        m, params = model
        eng = ServeEngine(m, params, ServeConfig(
            num_slots=2, block_size=4, num_blocks=8, max_model_len=24,
        ))
        p1, p2 = [3, 1, 4, 1], [2, 7, 1]
        h1 = eng.submit(p1, 16)
        h2 = eng.submit(p2, 16)
        eng.run_until_idle()
        assert h1.result(5) == _ref_tokens(m, params, p1, 16)
        assert h2.result(5) == _ref_tokens(m, params, p2, 16)
        snap = eng.snapshot()
        assert snap["counters"]["preempted"] >= 1
        assert snap["gauges"]["blocks_free"] == 7.0  # all returned

    def test_backpressure_and_deadline(self, model):
        m, params = model
        eng = ServeEngine(m, params, ServeConfig(
            num_slots=1, block_size=8, max_queue=2,
        ))
        a = eng.submit([1, 2, 3], 4)
        b = eng.submit([4, 5], 4)
        c = eng.submit([6], 4)  # queue full → rejected synchronously
        assert c.status == "rejected"
        with pytest.raises(ServeRejected, match="rejected"):
            c.result(1)
        # Deadline: admit a first (freeing a queue seat), then a
        # zero-deadline request expires while queued behind b.
        eng.step()
        d = eng.submit([7, 8], 4, deadline_s=0.0)
        time.sleep(0.01)
        eng.run_until_idle()
        assert a.result(5) and b.result(5)
        with pytest.raises(ServeRejected, match="expired"):
            d.result(1)
        counters = eng.snapshot()["counters"]
        assert counters["rejected"] == 1 and counters["expired"] == 1

    def test_submit_validates(self, model):
        m, params = model
        eng = ServeEngine(m, params, ServeConfig(num_slots=1,
                                                 block_size=8))
        with pytest.raises(ValueError, match="at least one"):
            eng.submit([], 4)
        with pytest.raises(ValueError, match=">= 1"):
            eng.submit([1], 0)
        with pytest.raises(ValueError, match="max_model_len"):
            eng.submit([1] * 60, 10)
        with pytest.raises(ValueError, match="vocab"):
            eng.submit([m.config.vocab_size], 2)

    def test_prompt_beyond_largest_bucket_is_typed_rejection(self, model):
        """A non-bucket-aligned max_model_len drops the covering
        bucket; prompts past the largest RETAINED bucket must be a
        typed submit() rejection, never a serve-loop crash."""
        m, params = model
        eng = ServeEngine(m, params, ServeConfig(
            num_slots=2, block_size=8, max_model_len=24,
        ))
        assert eng.max_prompt_len == 16  # buckets [8, 16]; 32 dropped
        with pytest.raises(ValueError, match="largest prefill bucket"):
            eng.submit(list(range(1, 18)), 1)  # 17+1 <= 24 alone passes
        assert len(eng.generate([1, 2, 3], 2)) == 2  # loop healthy

    def test_unbucketable_block_size_raises_at_build(self, model):
        m, params = model
        with pytest.raises(ValueError, match="no prefill bucket"):
            ServeEngine(m, params, ServeConfig(
                num_slots=1, block_size=32, max_model_len=16,
            ))

    def test_serve_loop_death_fails_pending_loudly(self, model):
        """An exception escaping step() on the background thread must
        fail every pending handle with the chained error and turn the
        engine dead for new submits — never strand clients at their
        timeouts."""
        m, params = model
        eng = ServeEngine(m, params, ServeConfig(num_slots=2,
                                                 block_size=8))

        def boom(*a, **k):
            raise RuntimeError("injected device fault")

        eng._decode_fn = boom
        eng.start()
        try:
            h = eng.submit([1, 2, 3], 4)
            with pytest.raises(RuntimeError, match="engine died"):
                h.result(timeout=30)
            with pytest.raises(RuntimeError, match="dead"):
                eng.submit([1, 2, 3], 4)
        finally:
            eng.stop()

    def test_eos_and_streaming_callback(self, model):
        """eos stops the request early; on_token saw every token in
        order."""
        m, params = model
        prompt = _rand_prompt(11, 5, m.config.vocab_size)
        ref = _ref_tokens(m, params, prompt, 8)
        eos = ref[3]
        seen = []
        eng = ServeEngine(m, params, ServeConfig(num_slots=2,
                                                 block_size=8))
        h = eng.submit(prompt, 8, eos_token_id=eos,
                       on_token=lambda i, t: seen.append((i, t)))
        eng.run_until_idle()
        got = h.result(5)
        # Stopped at the FIRST occurrence of eos in the reference
        # rollout (greedy regenerates the same prefix).
        assert got == ref[: ref.index(eos) + 1]
        assert seen == list(enumerate(got))
        assert h.request.done_reason == "eos"

    def test_temperature_sampling_reproducible(self, model):
        m, params = model
        prompt = _rand_prompt(12, 6, m.config.vocab_size)
        outs = []
        for _ in range(2):
            eng = ServeEngine(m, params, ServeConfig(
                num_slots=2, block_size=8, seed=7,
            ))
            outs.append(eng.generate(prompt, 8, temperature=1.0))
        assert outs[0] == outs[1]

    def test_int8_engine_matches_int8_generate(self, model):
        """The int8-storage tree through the paged path == the static
        path fed the SAME tree (both dequant-hoisted off-TPU)."""
        from ray_lightning_tpu.models.quant import quantize_decode_params

        m, params = model
        q8 = quantize_decode_params(params, m.config)
        prompt = _rand_prompt(13, 6, m.config.vocab_size)
        eng = ServeEngine(m, q8, ServeConfig(num_slots=2, block_size=8))
        ref = generate(m, q8, jnp.asarray([prompt], jnp.int32), 7)
        assert eng.generate(prompt, 7) == np.asarray(ref)[0, 6:].tolist()


# ---------------------------------------------------------------------------
# SLO stats + schema + exporters
# ---------------------------------------------------------------------------

class TestServeStats:
    def test_percentile_nearest_rank(self):
        assert percentile([], 50) is None
        assert percentile([3.0], 99) == 3.0
        vals = [float(i) for i in range(1, 101)]
        assert percentile(vals, 50) == 50.0
        assert percentile(vals, 99) == 99.0
        assert percentile(vals, 0) == 1.0

    def test_snapshot_is_schema_valid(self):
        from ray_lightning_tpu.telemetry.schema import (
            validate_serve_snapshot,
        )

        s = ServeStats()
        s.bump("submitted", 3)
        s.note_admitted(0.01)
        s.note_first_token(0.02)
        s.note_token_latency(0.004, n_tokens=2)
        s.note_completed(0.5)
        s.set_gauges(queue_depth=1, slots_active=1, num_slots=4,
                     blocks_free=3, blocks_live=2, num_blocks=6)
        snap = s.snapshot()
        assert validate_serve_snapshot(snap) == []
        assert snap["counters"]["tokens_out"] == 2
        assert snap["latency"]["token"]["n"] == 2

    def test_engine_snapshot_schema_and_prom_render(self, model):
        from ray_lightning_tpu.telemetry.export_prom import (
            render_openmetrics,
        )
        from ray_lightning_tpu.telemetry.schema import (
            validate_serve_snapshot,
        )

        m, params = model
        eng = ServeEngine(m, params, ServeConfig(num_slots=2,
                                                 block_size=8))
        eng.generate([1, 2, 3], 4)
        snap = eng.snapshot()
        assert validate_serve_snapshot(snap) == []
        text = render_openmetrics({"serve": snap})
        assert "rlt_serve_slots_active" in text
        assert 'rlt_serve_requests_total{kind="completed"} 1' in text
        assert 'rlt_serve_token_latency_ms{quantile="p50"}' in text

    def test_rlt_top_renders_serve_live(self, model, tmp_path):
        m, params = model
        eng = ServeEngine(
            m, params,
            ServeConfig(num_slots=2, block_size=8, export_every_s=0.0),
            telemetry_dir=str(tmp_path),
        )
        eng.generate([5, 6], 3)
        assert (tmp_path / "serve-live.json").exists()
        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "tools",
                          "rlt_top.py"),
             "--once", str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "serve:" in out.stdout and "slots" in out.stdout

    def test_bench_serve_block_schema(self):
        from ray_lightning_tpu.telemetry.schema import validate_bench_serve

        good = {
            "requests_per_sec": 10.0, "p50_token_latency_ms": 5.0,
            "p99_token_latency_ms": 9.0, "recompiles_steady_state": 0,
            "continuous_vs_sequential": 2.0,
            "rate_sweep": [{"offered_rps": 1.0, "requests_per_sec": 1.0,
                            "p50_token_latency_ms": None,
                            "p99_token_latency_ms": None}],
        }
        assert validate_bench_serve(good) == []
        assert validate_bench_serve({"requests_per_sec": 1.0})
        assert validate_bench_serve({**good, "surprise": 1})


# ---------------------------------------------------------------------------
# DriverQueue client plane
# ---------------------------------------------------------------------------

class TestClientPlane:
    def test_generate_stream_and_backpressure_over_queue(self, model):
        from ray_lightning_tpu.serve.client import ServeClient

        m, params = model
        eng = ServeEngine(m, params, ServeConfig(
            num_slots=1, block_size=8, max_queue=2,
        ))
        client = ServeClient(eng.queue_handle())
        try:
            p1 = _rand_prompt(14, 5, m.config.vocab_size)
            p2 = _rand_prompt(15, 4, m.config.vocab_size)
            r1 = client.submit(p1, 6)
            r2 = client.submit(p2, 5)
            r3 = client.submit([1], 2)   # queue full once drained
            # Engine not started: drain deterministically.
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and eng.step():
                pass
            eng.run_until_idle()
            assert client.result(r1, 10) == _ref_tokens(m, params, p1, 6)
            assert client.result(r2, 10) == _ref_tokens(m, params, p2, 5)
            with pytest.raises(ServeRejected):
                client.result(r3, 10)
            # Streaming (engine thread drives) + invalid submission.
            eng.start()
            toks = list(client.stream(p1, 6, timeout=30))
            assert toks == _ref_tokens(m, params, p1, 6)
            with pytest.raises(ValueError, match="max_model_len"):
                client.generate([1] * 60, 10, timeout=30)
        finally:
            eng.stop()
            client.close()

    def test_malformed_queue_request_gets_invalid_reply(self, model):
        """Bad field TYPES (int(None), ...) after the reply address is
        known must come back as serve_done(status="invalid"), not a
        silent drop that strands the client at its timeout."""
        from ray_lightning_tpu.cluster.queue import DriverQueue

        m, params = model
        eng = ServeEngine(m, params, ServeConfig(num_slots=1,
                                                 block_size=8))
        replies = DriverQueue()
        try:
            eng.queue_handle().put({
                "type": "serve_request", "rid": "bad", "prompt": [1, 2],
                "max_new_tokens": None,
                "reply": [replies.handle.host, replies.handle.port],
            })
            deadline = time.monotonic() + 10
            item = None
            while item is None and time.monotonic() < deadline:
                eng.step()
                try:
                    item = replies.get(timeout=0.2)
                except Exception:
                    item = None
            assert item is not None, "no reply for the malformed request"
            assert item["type"] == "serve_done"
            assert item["status"] == "invalid"
        finally:
            replies.shutdown()
            eng.stop()

    def test_wire_items_are_schema_valid(self, model):
        """Capture real wire traffic and pin it to the schema."""
        from ray_lightning_tpu.telemetry.schema import (
            validate_serve_reply, validate_serve_request,
        )

        m, params = model
        eng = ServeEngine(m, params, ServeConfig(num_slots=1,
                                                 block_size=8))
        sent = []
        orig = eng._reply

        def spy(addr, item):
            sent.append(item)
            orig(addr, item)

        eng._reply = spy
        from ray_lightning_tpu.serve.client import ServeClient

        client = ServeClient(eng.queue_handle())
        try:
            rid = client.submit([1, 2, 3], 3)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and eng.step():
                pass
            eng.run_until_idle()
            client.result(rid, 10)
            # The request as the engine saw it (re-build from client
            # fields) + every reply it actually sent.
            req_item = {
                "type": "serve_request", "rid": rid, "prompt": [1, 2, 3],
                "max_new_tokens": 3, "temperature": 0.0,
                "eos_token_id": None, "deadline_s": None,
                "reply": list(client._reply_addr),
            }
            assert validate_serve_request(req_item) == []
            assert sent, "engine sent no replies"
            for item in sent:
                assert validate_serve_reply(item) == [], item
        finally:
            eng.stop()
            client.close()


def test_bench_serve_block_in_artifacts_gated():
    """A drifted serve block in a committed BENCH artifact fails the
    format.sh layer-4 gate (scan wired into check_telemetry_schema)."""
    root = os.path.join(os.path.dirname(__file__), "..")
    sys.path.insert(0, os.path.join(root, "tools"))
    try:
        import importlib

        mod = importlib.import_module("check_telemetry_schema")
        block = {"requests_per_sec": 1.0, "p50_token_latency_ms": 1.0,
                 "p99_token_latency_ms": 2.0, "recompiles_steady_state": 0}
        from ray_lightning_tpu.telemetry.schema import validate_bench_serve
        assert validate_bench_serve(block) == []
        assert mod.self_test() == []
    finally:
        sys.path.pop(0)
