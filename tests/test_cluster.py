"""Control-plane tests: process actors, distributed queue, result pump.

Parity targets: reference RayExecutor behavior (ray_ddp.py:38-63), queue
streaming (ray_ddp.py:344-347 + util.py:47-68), fail-fast worker-death
semantics (SURVEY §5).
"""

import os
import time

import pytest

from ray_lightning_tpu.cluster import (
    ActorDiedError,
    DriverQueue,
    LocalBackend,
    ObjectRef,
    ProcessActor,
    RemoteError,
    find_free_port,
)
from ray_lightning_tpu.util import process_results


# -- top-level fns shipped to actors ----------------------------------------

def _add(a, b):
    return a + b


def _read_env(name):
    return os.environ.get(name)


def _boom():
    raise ValueError("intentional failure inside actor")


def _put_through_queue(handle, n):
    for i in range(n):
        handle.put({"step": i})
    return "done"


def _put_thunk(handle, value):
    # A cloudpickled closure crossing the process boundary — the Tune-report
    # trick (reference tune.py:130-134).
    handle.put(lambda: value * 2)
    return "sent"


def _exit_hard():
    os._exit(17)


@pytest.fixture
def actor():
    a = ProcessActor(name="test-actor")
    yield a
    a.kill()


class TestProcessActor:
    def test_execute_roundtrip(self, actor):
        assert actor.execute(_add, 2, 3) == 5

    def test_execute_lambda(self, actor):
        # cloudpickle lets arbitrary closures cross, like Ray tasks.
        captured = 10
        assert actor.execute(lambda x: x + captured, 5) == 15

    def test_submit_is_async(self, actor):
        futs = [actor.submit(_add, i, i) for i in range(5)]
        assert [f.result() for f in futs] == [0, 2, 4, 6, 8]

    def test_env_vars(self):
        a = ProcessActor(name="env-actor", env={"RLT_TEST_SPAWN": "at-start"})
        try:
            assert a.execute(_read_env, "RLT_TEST_SPAWN") == "at-start"
            a.set_env_vars({"RLT_TEST_LATER": "later"})
            assert a.execute(_read_env, "RLT_TEST_LATER") == "later"
        finally:
            a.kill()

    def test_remote_error_propagates(self, actor):
        with pytest.raises(RemoteError, match="intentional failure"):
            actor.execute(_boom)
        # Actor survives an exception (like a Ray actor does).
        assert actor.execute(_add, 1, 1) == 2

    def test_actor_death_fails_pending_futures(self):
        a = ProcessActor(name="dying-actor")
        fut = a.submit(_exit_hard)
        with pytest.raises(ActorDiedError):
            fut.result(timeout=30)
        with pytest.raises(ActorDiedError):
            a.submit(_add, 1, 2)
        a.kill()

    def test_get_node_ip(self, actor):
        ip = actor.get_node_ip()
        assert isinstance(ip, str) and ip.count(".") == 3

    def test_kill_idempotent(self):
        a = ProcessActor(name="kill-actor")
        a.kill()
        a.kill()
        assert not a.is_alive()


class TestDriverQueue:
    def test_local_put_get(self):
        q = DriverQueue()
        q.handle.put({"a": 1})
        assert q.get(timeout=10) == {"a": 1}
        q.shutdown()

    def test_cross_process_streaming(self):
        q = DriverQueue()
        a = ProcessActor(name="queue-actor")
        try:
            result = a.execute(_put_through_queue, q.handle, 5)
            assert result == "done"
            got = [q.get(timeout=10) for _ in range(5)]
            assert got == [{"step": i} for i in range(5)]
        finally:
            a.kill()
            q.shutdown()

    def test_handle_repickles(self):
        import cloudpickle

        q = DriverQueue()
        h2 = cloudpickle.loads(cloudpickle.dumps(q.handle))
        h2.put("x")
        assert q.get(timeout=10) == "x"
        q.shutdown()

    def test_put_is_synchronous(self):
        """Once put() returns the item must be visible to a drain — no
        in-flight window (the process_results final-drain race)."""
        q = DriverQueue()
        h = q.handle
        for i in range(50):
            h.put(i)
            assert not q.empty(), f"put({i}) returned before item landed"
            assert q.get_nowait() == i
        q.shutdown()

    def test_replayed_frames_dedup(self):
        """A retry that resends an already-enqueued seq (lost ack) must
        not produce a duplicate item."""
        from ray_lightning_tpu.cluster import rpc as _rpc

        q = DriverQueue()
        h = q.handle
        h.put("first")
        assert q.get(timeout=10) == "first"
        # Forge the retry: resend seq=1 on a fresh connection, as the
        # reconnect path does when the ack (not the item) was lost.
        import socket as _s

        with _s.create_connection((h.host, h.port), timeout=10) as sock:
            replay = _rpc.dumps((h._client_id, 1, "first"))
            _rpc.send_frame(sock, replay)
            assert sock.recv(1) == b"\x01"  # replay is acked...
            fresh = _rpc.dumps((h._client_id, 2, "second"))
            _rpc.send_frame(sock, fresh)
            assert sock.recv(1) == b"\x01"
        assert q.get(timeout=10) == "second"  # ...but never re-enqueued
        assert q.empty()
        q.shutdown()

    def test_concurrent_producers_exactly_once(self):
        """8 threads × 50 acked puts: every item arrives exactly once
        (per-producer seq spaces + the server's seen-dict under lock)."""
        import threading

        q = DriverQueue()
        n_threads, n_items = 8, 50
        errors = []

        def producer(tid):
            h = q.handle  # fresh handle -> own client_id/seq space
            try:
                for i in range(n_items):
                    h.put((tid, i))
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=producer, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), "producer hung"
        assert not errors, errors
        got = []
        while not q.empty():
            got.append(q.get_nowait())
        assert sorted(got) == [
            (t, i) for t in range(n_threads) for i in range(n_items)
        ]
        q.shutdown()

    def test_put_after_shutdown_fails_fast(self):
        """shutdown() must wake reader threads and refuse late puts —
        not ack items into a queue nobody will drain."""
        q = DriverQueue()
        h = q.handle
        h.put("warm")  # opens the persistent connection
        q.shutdown()
        time.sleep(0.1)
        with pytest.raises((ConnectionError, OSError)):
            h.put("late")


class TestProcessResults:
    def test_pump_callback_raising_keeps_fit_result(self):
        """A raising on_item observer must neither deadlock the pump
        nor drop the futures' results (satellite: driver resilience)."""
        q = DriverQueue()
        a = ProcessActor(name="raising-pump-actor")
        seen = []

        def bad_observer(item):
            seen.append(item)
            raise RuntimeError("observer blew up")

        try:
            fut = a.submit(_put_through_queue, q.handle, 3)
            with pytest.warns(UserWarning, match="stream-item callback"):
                out = process_results([fut], q, on_item=bad_observer)
            assert out == ["done"]
            assert seen == [{"step": i} for i in range(3)]
        finally:
            a.kill()
            q.shutdown()

    def test_pump_tick_callback_raising_is_survived(self):
        q = DriverQueue()
        a = ProcessActor(name="tick-actor")

        def bad_tick():
            raise ValueError("tick broke")

        try:
            fut = a.submit(_add, 2, 2)
            with pytest.warns(UserWarning, match="tick callback"):
                assert process_results([fut], q, on_tick=bad_tick) == [4]
        finally:
            a.kill()
            q.shutdown()

    def test_multi_rank_producers_exactly_once_under_pump(self):
        """3 worker processes streaming concurrently while the driver
        pumps: every item arrives exactly once, in per-rank order, even
        with an observer that raises on some items."""
        q = DriverQueue()
        actors = [
            ProcessActor(name=f"mp-producer-{i}") for i in range(3)
        ]
        got = []

        def observer(item):
            got.append(item)
            if item["step"] % 5 == 0:
                raise RuntimeError("selective observer failure")

        try:
            futures = [
                a.submit(_put_through_queue, q.handle, 20) for a in actors
            ]
            out = process_results(futures, q, on_item=observer)
            assert out == ["done"] * 3
            assert len(got) == 60
            # per-producer FIFO survives the concurrency
            assert sorted(i["step"] for i in got) == sorted(
                list(range(20)) * 3
            )
        finally:
            for a in actors:
                a.kill()
            q.shutdown()

    def test_pump_drains_queue_and_returns_results(self):
        q = DriverQueue()
        a = ProcessActor(name="pump-actor")
        try:
            fut = a.submit(_put_through_queue, q.handle, 3)
            seen = []
            out = process_results([fut], q, on_item=seen.append)
            assert out == ["done"]
            assert seen == [{"step": i} for i in range(3)]
        finally:
            a.kill()
            q.shutdown()

    def test_thunks_execute_in_driver(self):
        q = DriverQueue()
        a = ProcessActor(name="thunk-actor")
        try:
            fut = a.submit(_put_thunk, q.handle, 21)
            process_results([fut], q)
            # The thunk ran driver-side during the pump; verify by running
            # another and checking handle_queue_item directly.
            a.execute(_put_thunk, q.handle, 5)
            item = q.get(timeout=10)
            assert callable(item) and item() == 10
        finally:
            a.kill()
            q.shutdown()

    def test_worker_failure_raises(self):
        a = ProcessActor(name="fail-actor")
        try:
            fut = a.submit(_boom)
            with pytest.raises(RemoteError):
                process_results([fut], None)
        finally:
            a.kill()


class TestBackend:
    def test_object_ref_copies(self):
        ref = ObjectRef.from_object({"w": [1, 2, 3]})
        a, b = ref.get(), ref.get()
        assert a == b
        a["w"].append(4)
        assert ref.get() == {"w": [1, 2, 3]}  # no aliasing

    def test_local_backend_lifecycle(self):
        be = LocalBackend()
        a = be.create_actor("be-actor")
        assert a.execute(_add, 4, 4) == 8
        q = be.create_queue()
        q.handle.put(1)
        assert q.get(timeout=10) == 1
        q.shutdown()
        be.shutdown()
        assert not a.is_alive()


def test_find_free_port():
    p = find_free_port()
    assert 1024 <= p <= 65535
