"""KV-cache decode: teacher-forcing parity with the full forward.

Strategy ≙ the repo's grad-parity discipline applied to inference: the
training-path full forward (``GPT.forward``) is the reference; greedy
decoding through the cache must pick exactly the tokens the full forward
would, step by step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.generate import (
    decode_step, generate, init_kv_cache,
)
from ray_lightning_tpu.models.gpt import GPT, GPTConfig


@pytest.fixture(scope="module")
def model():
    cfg = GPTConfig(vocab_size=128, n_layer=2, n_head=4, d_model=64,
                    seq_len=32, warmup_steps=1)
    m = GPT(cfg, attn_impl="xla")
    params = m.init_params(jax.random.PRNGKey(0))
    return m, params


def test_decode_logits_match_full_forward(model):
    """Feeding tokens one-by-one through the cache reproduces the full
    forward's next-token logits at every position."""
    m, params = model
    cfg = m.config
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)
    full = m.forward(params, tokens)  # (B, 8, V)

    cache = init_kv_cache(cfg, 2, 8)
    for t in range(8):
        step_logits, cache = decode_step(
            cfg, params, cache, tokens[:, t], jnp.int32(t)
        )
        np.testing.assert_allclose(
            np.asarray(step_logits), np.asarray(full[:, t]),
            rtol=1e-4, atol=1e-4,
        )


def test_greedy_generation_matches_argmax_rollout(model):
    """jit-compiled greedy generate == python loop of full forwards."""
    m, params = model
    cfg = m.config
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0,
                                cfg.vocab_size)
    out = jax.jit(
        lambda p, pr: generate(m, p, pr, max_new_tokens=6)
    )(params, prompt)
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out[:, :4]),
                                  np.asarray(prompt))

    # Reference rollout: repeatedly run the FULL forward and take argmax.
    cur = np.asarray(prompt)
    for _ in range(6):
        logits = m.forward(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), cur)


def test_sampled_generation_reproducible(model):
    m, params = model
    prompt = jnp.zeros((1, 2), jnp.int32)
    a = generate(m, params, prompt, 5, temperature=0.8,
                 rng=jax.random.PRNGKey(7))
    b = generate(m, params, prompt, 5, temperature=0.8,
                 rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (1, 7)


def test_generate_accepts_host_param_pytree(model):
    """``trainer.params`` is a numpy pytree — generate() must accept it
    (numpy leaves cannot be gather-indexed by traced tokens)."""
    m, params = model
    host_params = jax.tree.map(np.asarray, params)
    prompt = np.zeros((1, 2), np.int32)
    out = generate(m, host_params, prompt, 3)
    ref = generate(m, params, jnp.asarray(prompt), 3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_generate_refuses_overlong_and_moe(model):
    m, params = model
    prompt = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError, match="exceeds"):
        generate(m, params, prompt, 10)
    with pytest.raises(ValueError, match=">= 0"):
        generate(m, params, prompt, -1)
    moe = GPT(GPTConfig.tiny_moe())
    with pytest.raises(NotImplementedError, match="MoE"):
        generate(moe, moe.init_params(jax.random.PRNGKey(0)),
                 jnp.zeros((1, 2), jnp.int32), 2)
