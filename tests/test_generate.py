"""KV-cache decode: teacher-forcing parity with the full forward.

Strategy ≙ the repo's grad-parity discipline applied to inference: the
training-path full forward (``GPT.forward``) is the reference; greedy
decoding through the cache must pick exactly the tokens the full forward
would, step by step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.generate import (
    _sample, decode_step, generate, init_kv_cache, prefill,
)
from ray_lightning_tpu.models.gpt import GPT, GPTConfig


@pytest.fixture(scope="module")
def model():
    cfg = GPTConfig(vocab_size=128, n_layer=2, n_head=4, d_model=64,
                    seq_len=32, warmup_steps=1)
    m = GPT(cfg, attn_impl="xla")
    params = m.init_params(jax.random.PRNGKey(0))
    return m, params


def test_decode_logits_match_full_forward(model):
    """Feeding tokens one-by-one through the cache reproduces the full
    forward's next-token logits at every position."""
    m, params = model
    cfg = m.config
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)
    full = m.forward(params, tokens)  # (B, 8, V)

    cache = init_kv_cache(cfg, 2, 8)
    for t in range(8):
        step_logits, cache = decode_step(
            cfg, params, cache, tokens[:, t], jnp.int32(t)
        )
        np.testing.assert_allclose(
            np.asarray(step_logits), np.asarray(full[:, t]),
            rtol=1e-4, atol=1e-4,
        )


def test_prefill_matches_sequential_decode(model):
    """One fused prefill pass == feeding the prompt token-by-token:
    identical last-position logits AND identical cache contents."""
    m, params = model
    cfg = m.config
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0,
                                cfg.vocab_size)
    fused_logits, fused_cache = prefill(
        cfg, params, init_kv_cache(cfg, 2, 10), tokens
    )
    seq_cache = init_kv_cache(cfg, 2, 10)
    for t in range(6):
        seq_logits, seq_cache = decode_step(
            cfg, params, seq_cache, tokens[:, t], jnp.int32(t)
        )
    np.testing.assert_allclose(np.asarray(fused_logits),
                               np.asarray(seq_logits), rtol=1e-4, atol=1e-4)
    for k in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(fused_cache[k]), np.asarray(seq_cache[k]),
            rtol=1e-5, atol=1e-5,
        )


def test_topk_one_equals_greedy(model):
    """top_k=1 sampling at any temperature is exactly greedy decoding."""
    m, params = model
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 3), 0,
                                m.config.vocab_size)
    greedy = generate(m, params, prompt, 5)
    topk1 = generate(m, params, prompt, 5, temperature=1.3, top_k=1,
                     rng=jax.random.PRNGKey(11))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))


def test_top_p_nucleus_masks_tail():
    """top-p keeps the smallest prefix of sorted probs reaching the mass
    and never samples outside it; always keeps the argmax token."""
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    # nucleus at 0.6: exclusive-cumsum {0, .5, .8, .95} < 0.6 keeps the
    # top two tokens.
    draws = [
        int(_sample(logits, jax.random.PRNGKey(i), 1.0, None, 0.6)[0])
        for i in range(50)
    ]
    assert set(draws) <= {0, 1} and 0 in draws
    # tiny top_p still keeps exactly the argmax
    draws = [
        int(_sample(logits, jax.random.PRNGKey(i), 1.0, None, 1e-6)[0])
        for i in range(10)
    ]
    assert set(draws) == {0}


def test_greedy_generation_matches_argmax_rollout(model):
    """jit-compiled greedy generate == python loop of full forwards."""
    m, params = model
    cfg = m.config
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0,
                                cfg.vocab_size)
    out = jax.jit(
        lambda p, pr: generate(m, p, pr, max_new_tokens=6)
    )(params, prompt)
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out[:, :4]),
                                  np.asarray(prompt))

    # Reference rollout: repeatedly run the FULL forward and take argmax.
    cur = np.asarray(prompt)
    for _ in range(6):
        logits = m.forward(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), cur)


def test_eos_freezes_finished_sequences(model):
    """Once a row samples eos, every later position repeats eos; rows
    that never sample it are unaffected (match the no-eos output)."""
    m, params = model
    cfg = m.config
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 3), 0,
                                cfg.vocab_size)
    base = generate(m, params, prompt, 8)
    # Pick the token row 0 greedily emits first as the "eos" id: row 0
    # must freeze right there; use an id row 1 never emits to leave it
    # untouched.
    eos = int(base[0, 3])
    out = generate(m, params, prompt, 8, eos_token_id=eos)
    got = np.asarray(out)
    ref = np.asarray(base)
    assert (got[0, 3:] == eos).all(), "finished row did not freeze"
    # Unconditional per-row property: identical to the no-eos rollout up
    # to and including each row's first eos, frozen at eos after it.
    for r in range(got.shape[0]):
        hits = np.where(ref[r, 3:] == eos)[0]
        cut = 3 + (hits[0] + 1 if hits.size else ref.shape[1])
        np.testing.assert_array_equal(got[r, :cut], ref[r, :cut])
        assert (got[r, cut:] == eos).all()
    # jit parity (the scan carry gained a done mask).
    jout = jax.jit(
        lambda p, pr: generate(m, p, pr, 8, eos_token_id=eos)
    )(params, prompt)
    np.testing.assert_array_equal(np.asarray(jout), got)


def test_sampled_generation_reproducible(model):
    m, params = model
    prompt = jnp.zeros((1, 2), jnp.int32)
    a = generate(m, params, prompt, 5, temperature=0.8,
                 rng=jax.random.PRNGKey(7))
    b = generate(m, params, prompt, 5, temperature=0.8,
                 rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (1, 7)


def test_generate_accepts_host_param_pytree(model):
    """``trainer.params`` is a numpy pytree — generate() must accept it
    (numpy leaves cannot be gather-indexed by traced tokens)."""
    m, params = model
    host_params = jax.tree.map(np.asarray, params)
    prompt = np.zeros((1, 2), np.int32)
    out = generate(m, host_params, prompt, 3)
    ref = generate(m, params, jnp.asarray(prompt), 3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_generate_under_tp_mesh(model):
    """The decode loop is GSPMD-cleanly shardable: jitted over a
    (data, tensor) mesh with the module's Megatron param specs and a
    batch-sharded prompt, generation runs and matches the unsharded
    tokens (serving story for TP-sharded models)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_lightning_tpu.parallel.sharding import (
        params_shardings_for_module,
    )

    m, params = model
    prompt = jax.random.randint(jax.random.PRNGKey(5), (4, 5), 0,
                                m.config.vocab_size)
    ref = generate(m, params, prompt, 6)

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                ("data", "tensor"))
    sharded_params = jax.device_put(
        params, params_shardings_for_module(m, params, mesh)
    )
    sharded_prompt = jax.device_put(
        prompt, NamedSharding(mesh, P("data", None))
    )
    with mesh:
        out = jax.jit(
            lambda p, pr: generate(m, p, pr, max_new_tokens=6)
        )(sharded_params, sharded_prompt)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_generate_validates_args(model):
    m, params = model
    prompt = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError, match="exceeds"):
        generate(m, params, prompt, 10)
    with pytest.raises(ValueError, match=">= 0"):
        generate(m, params, prompt, -1)
    small = jnp.zeros((1, 2), jnp.int32)
    with pytest.raises(ValueError, match="top_k"):
        generate(m, params, small, 2, temperature=1.0, top_k=0)
    with pytest.raises(ValueError, match="top_p"):
        generate(m, params, small, 2, temperature=1.0, top_p=1.5)
    with pytest.raises(ValueError, match="temperature > 0"):
        generate(m, params, small, 2, top_k=5)
    with pytest.raises(ValueError, match="at least one token"):
        generate(m, params, jnp.zeros((1, 0), jnp.int32), 2)
    # Oversized top_k clamps to the vocab (HF behavior) instead of
    # erroring from inside lax.top_k.
    out = generate(m, params, small, 2, temperature=1.0,
                   top_k=m.config.vocab_size + 7)
    assert out.shape == (1, 4)


def test_moe_greedy_generation_matches_argmax_rollout():
    """MoE decode == python loop of full MoE forwards.

    capacity_factor = n_experts guarantees zero capacity drops, which
    makes per-step routing identical to whole-batch routing (the caveat
    documented on generate())."""
    from dataclasses import replace

    cfg = GPTConfig.tiny_moe()
    cfg = replace(cfg, moe_capacity_factor=float(cfg.n_experts))
    m = GPT(cfg)
    params = m.init_params(jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 4), 0,
                                cfg.vocab_size)
    out = jax.jit(
        lambda p, pr: generate(m, p, pr, max_new_tokens=5)
    )(params, prompt)
    assert out.shape == (2, 9)

    cur = np.asarray(prompt)
    for _ in range(5):
        logits = m.forward(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), cur)
