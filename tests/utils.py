"""Assertion helpers (≙ reference ``tests/utils.py:213-272``)."""

from __future__ import annotations

import numpy as np

import jax

from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import (
    BoringDataModule,
    BoringModel,
    XORDataModule,
    XORModel,
)


def get_trainer(strategy=None, max_epochs: int = 1, tmp_path=".", **kwargs):
    """≙ reference ``get_trainer`` (``tests/utils.py:213-233``)."""
    return Trainer(
        strategy=strategy,
        max_epochs=max_epochs,
        default_root_dir=str(tmp_path),
        log_every_n_steps=1,
        **kwargs,
    )


def _flat_norm_delta(a, b) -> float:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return float(
        sum(
            np.linalg.norm(np.asarray(x) - np.asarray(y))
            for x, y in zip(la, lb)
        )
    )


def train_test(trainer: Trainer, module, datamodule) -> None:
    """Weights must move under training (≙ ``tests/utils.py:236-245``)."""
    initial = jax.device_get(
        jax.jit(module.init_params)(jax.random.PRNGKey(trainer.config.seed))
    )
    trainer.fit(module, datamodule)
    assert trainer.params is not None
    delta = _flat_norm_delta(initial, trainer.params)
    assert delta > 0.1, f"params barely moved: ‖Δ‖={delta}"


def load_test(trainer: Trainer, module, datamodule, tmp_path) -> None:
    """Checkpoint roundtrip (≙ ``tests/utils.py:248-253``)."""
    trainer.fit(module, datamodule)
    path = str(tmp_path / "model.ckpt")
    trainer.save_checkpoint(path)
    from ray_lightning_tpu.utils.state_stream import load_state_stream

    payload = load_state_stream(open(path, "rb").read())
    restored = payload["state"].params
    assert _flat_norm_delta(restored, trainer.params) == 0.0


def predict_test(trainer: Trainer, module, datamodule) -> None:
    """Post-train accuracy ≥ 0.5 (≙ ``tests/utils.py:256-272``)."""
    trainer.fit(module, datamodule)
    metrics = trainer.validate(module, datamodule)
    acc = metrics.get("val_acc")
    assert acc is not None and acc >= 0.5, f"val_acc={acc}"
