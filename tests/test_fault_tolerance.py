"""Failure detection + elastic restart + the recovery-plane acceptance
matrix.

The reference only fails fast (worker death raises out of ``ray.get``,
SURVEY §5 "failure detection: ABSENT"); this framework adds opt-in
elastic recovery: ``max_restarts=N`` respawns the worker set and resumes
from the newest VERIFIED restart checkpoint.  The ``chaos``-marked tests
drive every recovery path end-to-end with deterministically injected
faults (``RLT_FAULT``, fault/inject.py): crash, hang→monitor-abort,
SIGTERM preemption drain, and torn/bit-flipped checkpoints falling back
to the previous good one.
"""

import os

import numpy as np
import pytest

from ray_lightning_tpu.cluster.actor import ActorDiedError
from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.fault.drain import PreemptedError
from ray_lightning_tpu.models.boring import BoringDataModule, BoringModel
from ray_lightning_tpu.parallel.strategies import RayStrategy


@pytest.fixture
def chaos_env(tmp_path, monkeypatch):
    """Inject one RLT_FAULT plan with a shared fired-marker dir (so the
    respawned worker set trains through instead of re-dying)."""

    def _arm(fault: str) -> None:
        monkeypatch.setenv("RLT_FAULT", fault)
        monkeypatch.setenv("RLT_FAULT_STATE", str(tmp_path / "chaos"))

    return _arm


class CrashOnce(Callback):
    """Hard-kill one rank at a given epoch, only on the first attempt.

    A marker file on the (shared) filesystem records that the crash
    already happened, so the respawned worker set trains through.
    """

    def __init__(self, marker: str, crash_rank: int = 1, crash_epoch: int = 1):
        self.marker = marker
        self.crash_rank = crash_rank
        self.crash_epoch = crash_epoch

    def on_train_epoch_start(self, trainer, module) -> None:
        if (
            trainer.global_rank == self.crash_rank
            and trainer.current_epoch == self.crash_epoch
            and not os.path.exists(self.marker)
        ):
            with open(self.marker, "w") as f:
                f.write("crashed")
            os._exit(1)  # simulate hard worker death (OOM/preemption)


class EpochRecorder(Callback):
    def __init__(self):
        self.epochs = []

    def on_train_epoch_end(self, trainer, module) -> None:
        self.epochs.append(trainer.current_epoch)

    def state_dict(self):
        return {"epochs": list(self.epochs)}

    def load_state_dict(self, state):
        self.epochs = list(state["epochs"])


def _fit(tmp_path, max_restarts, crash=True, max_epochs=4, crash_epoch=1):
    callbacks = []
    if crash:
        callbacks.append(CrashOnce(str(tmp_path / "crash-marker"),
                                   crash_epoch=crash_epoch))
    recorder = EpochRecorder()
    callbacks.append(recorder)
    strategy = RayStrategy(num_workers=2, max_restarts=max_restarts)
    trainer = Trainer(
        strategy=strategy,
        max_epochs=max_epochs,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=2,
        limit_val_batches=1,
        callbacks=callbacks,
    )
    trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    return trainer, strategy, recorder


def test_worker_death_fails_fast_without_elastic(tmp_path):
    """max_restarts=0 keeps reference semantics: crash propagates."""
    with pytest.raises(ActorDiedError):
        _fit(tmp_path, max_restarts=0)


def test_elastic_restart_completes_fit(tmp_path):
    trainer, strategy, recorder = _fit(tmp_path, max_restarts=1)
    assert strategy.restarts_used == 1
    assert np.isfinite(trainer.callback_metrics["train_loss"])
    # Completed all epochs: epoch 0 ran pre-crash, checkpointed, then the
    # respawned set resumed at epoch 1 (<= restart_every_n_epochs lost).
    assert trainer.epochs_run == 4
    # Callback state rode the restart checkpoint: epoch 0 (pre-crash)
    # survives, epochs 1-3 ran on the respawned set — no resets, no gaps.
    assert recorder.epochs == [0, 1, 2, 3]
    # Restart scratch dir is cleaned up after success.
    leftovers = [d for d in os.listdir(tmp_path)
                 if d.startswith(".rlt-restart-")]
    assert not leftovers


def test_elastic_budget_exhaustion_raises(tmp_path):
    """Crashing more times than max_restarts still fails."""
    marker = str(tmp_path / "never-written-marker")

    class AlwaysCrash(CrashOnce):
        def on_train_epoch_start(self, trainer, module) -> None:
            if (trainer.global_rank == self.crash_rank
                    and trainer.current_epoch == self.crash_epoch):
                os._exit(1)

    strategy = RayStrategy(num_workers=2, max_restarts=1)
    trainer = Trainer(
        strategy=strategy,
        max_epochs=3,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=2,
        limit_val_batches=1,
        callbacks=[AlwaysCrash(marker)],
    )
    with pytest.raises(ActorDiedError):
        trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert strategy.restarts_used == 1
    # Scratch dir is reclaimed on failure too.
    assert not [d for d in os.listdir(tmp_path)
                if d.startswith(".rlt-restart-")]


def test_user_exception_is_not_retried(tmp_path):
    """Deterministic exceptions in user code must fail fast, not burn the
    restart budget re-raising the same error."""
    from ray_lightning_tpu.cluster.actor import RemoteError

    class BadHook(Callback):
        def on_train_epoch_start(self, trainer, module) -> None:
            raise ValueError("deterministic user bug")

    strategy = RayStrategy(num_workers=1, max_restarts=3)
    trainer = Trainer(
        strategy=strategy,
        max_epochs=1,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=1,
        callbacks=[BadHook()],
    )
    with pytest.raises(RemoteError, match="deterministic user bug"):
        trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert strategy.restarts_used == 0


def test_elastic_restart_without_checkpoint_restarts_from_scratch(tmp_path):
    """Crash at epoch 0 (before any restart checkpoint exists): the
    respawned set simply begins again."""
    trainer, strategy, _ = _fit(tmp_path, max_restarts=1, max_epochs=2,
                                crash_epoch=0)
    assert strategy.restarts_used == 1
    assert trainer.epochs_run == 2


# ---------------------------------------------------------------------------
# Chaos acceptance matrix (deterministic fault injection, fault/inject.py)
# ---------------------------------------------------------------------------

def _chaos_fit(tmp_path, max_epochs=3, max_restarts=1, **strategy_kw):
    """One worker actor, 2 batches/epoch: every scenario below must end
    with exactly ``max_epochs * 2`` optimizer steps after recovering."""
    strategy = RayStrategy(
        num_workers=1, max_restarts=max_restarts,
        restart_backoff_s=0.05, **strategy_kw,
    )
    trainer = Trainer(
        strategy=strategy,
        max_epochs=max_epochs,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=2,
        limit_val_batches=1,
    )
    trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    return trainer, strategy


def _event_kinds(trainer):
    return [e["kind"] for e in trainer.monitor_report.get("events", [])]


@pytest.mark.remote
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_crash_recovers_with_backoff(tmp_path, chaos_env):
    """Injected hard crash: the fit completes with the exact step
    count, and the governor's backoff delay is observable in
    monitor_report (the acceptance criterion)."""
    chaos_env("crash@step:3,rank:0")
    trainer, strategy = _chaos_fit(tmp_path)
    assert trainer.global_step == 6
    assert strategy.restarts_used == 1
    kinds = _event_kinds(trainer)
    assert "backoff" in kinds and "elastic_restart" in kinds
    backoff = next(
        e for e in trainer.monitor_report["events"]
        if e["kind"] == "backoff"
    )
    assert backoff["delay_s"] > 0


@pytest.mark.remote
@pytest.mark.chaos
@pytest.mark.slow
def test_monitor_abort_feeds_elastic_restart(tmp_path, chaos_env):
    """A hang injected via the chaos plane: the watchdog stalls→aborts,
    the abort becomes an elastic restart (not a dead fit), the fit
    completes, and monitor_report records the whole story."""
    chaos_env("hang@step:3,rank:0,secs:300")
    trainer, strategy = _chaos_fit(
        tmp_path,
        telemetry={"tier": "cheap", "heartbeat_s": 0.3},
        monitor={"hang_intervals": 2, "abort_after_s": 1.0},
    )
    assert trainer.global_step == 6
    assert strategy.restarts_used == 1
    kinds = _event_kinds(trainer)
    # The failed attempt's watchdog records survive the respawn — the
    # final report narrates the fit, not just the last attempt.  Under
    # CPU contention the wedged rank may read as heartbeat_lost rather
    # than stall (late beats); either way the abort must have fired and
    # fed the elastic path.
    assert "stall" in kinds or "heartbeat_lost" in kinds
    assert "abort" in kinds
    assert "elastic_restart" in kinds


@pytest.mark.remote
@pytest.mark.chaos
@pytest.mark.slow
def test_sigterm_preemption_drains_without_consuming_budget(
    tmp_path, chaos_env
):
    """SIGTERM → graceful drain → step-granular checkpoint → budget-free
    respawn.  The resumed fit replays NOTHING (exact final step count)
    and ``restarts_used`` stays 0."""
    chaos_env("sigterm@step:3,rank:0")
    trainer, strategy = _chaos_fit(tmp_path, max_epochs=2)
    assert trainer.global_step == 4
    assert strategy.restarts_used == 0
    assert strategy.preempt_restarts_used == 1
    kinds = _event_kinds(trainer)
    assert "drain" in kinds and "preempt_restart" in kinds
    drain_ev = next(
        e for e in trainer.monitor_report["events"]
        if e["kind"] == "drain"
    )
    assert "drain-step-" in drain_ev["ckpt"]


@pytest.mark.remote
@pytest.mark.chaos
@pytest.mark.slow
def test_sigterm_without_elastic_raises_resumable(tmp_path, chaos_env):
    """No elastic recovery: the drain surfaces as a TYPED
    PreemptedError (across the actor RPC boundary) naming a drain
    checkpoint that a follow-up fit resumes from with no lost steps."""
    chaos_env("sigterm@step:3,rank:0")
    strategy = RayStrategy(num_workers=1, max_restarts=0)
    trainer = Trainer(
        strategy=strategy, max_epochs=2,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        limit_train_batches=2, limit_val_batches=1,
    )
    with pytest.raises(PreemptedError) as err:
        trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    ckpt = err.value.checkpoint
    assert ckpt and os.path.exists(ckpt)
    assert "drain checkpoint" in str(err.value)

    resumed = Trainer(
        strategy=RayStrategy(num_workers=1), max_epochs=2,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        limit_train_batches=2, limit_val_batches=1,
        resume_from_checkpoint=ckpt,
    )
    resumed.fit(BoringModel(), BoringDataModule(batch_size=16))
    # 3 micro-steps trained pre-drain + 1 after resume = the full 4.
    assert resumed.global_step == 4
    assert resumed.micro_step == 4


@pytest.mark.remote
@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("corruption", ["bitflip", "torn"])
def test_corrupt_newest_checkpoint_falls_back(
    tmp_path, chaos_env, corruption
):
    """The newest restart checkpoint is corrupted (bit flip / torn
    write), then the worker crashes: restart discovery walks back to
    the previous VERIFIED checkpoint — never from scratch — and the
    fallback is loud (``ckpt_corrupt`` event)."""
    chaos_env(
        f"{corruption}@point:ckpt_write,nth:2,rank:0;crash@step:5,rank:0"
    )
    trainer, strategy = _chaos_fit(tmp_path, max_epochs=4)
    assert trainer.global_step == 8
    assert strategy.restarts_used == 1
    kinds = _event_kinds(trainer)
    assert "ckpt_corrupt" in kinds
    restart = next(
        e for e in trainer.monitor_report["events"]
        if e["kind"] == "elastic_restart"
    )
    # Fell back to the epoch-0 checkpoint, not scratch.
    assert "restart-epoch-000000" in restart["ckpt"]
