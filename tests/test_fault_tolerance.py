"""Failure detection + elastic restart.

The reference only fails fast (worker death raises out of ``ray.get``,
SURVEY §5 "failure detection: ABSENT"); this framework adds opt-in
elastic recovery: ``max_restarts=N`` respawns the worker set and resumes
from the newest restart checkpoint.
"""

import os

import numpy as np
import pytest

from ray_lightning_tpu.cluster.actor import ActorDiedError
from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models.boring import BoringDataModule, BoringModel
from ray_lightning_tpu.parallel.strategies import RayStrategy


class CrashOnce(Callback):
    """Hard-kill one rank at a given epoch, only on the first attempt.

    A marker file on the (shared) filesystem records that the crash
    already happened, so the respawned worker set trains through.
    """

    def __init__(self, marker: str, crash_rank: int = 1, crash_epoch: int = 1):
        self.marker = marker
        self.crash_rank = crash_rank
        self.crash_epoch = crash_epoch

    def on_train_epoch_start(self, trainer, module) -> None:
        if (
            trainer.global_rank == self.crash_rank
            and trainer.current_epoch == self.crash_epoch
            and not os.path.exists(self.marker)
        ):
            with open(self.marker, "w") as f:
                f.write("crashed")
            os._exit(1)  # simulate hard worker death (OOM/preemption)


class EpochRecorder(Callback):
    def __init__(self):
        self.epochs = []

    def on_train_epoch_end(self, trainer, module) -> None:
        self.epochs.append(trainer.current_epoch)

    def state_dict(self):
        return {"epochs": list(self.epochs)}

    def load_state_dict(self, state):
        self.epochs = list(state["epochs"])


def _fit(tmp_path, max_restarts, crash=True, max_epochs=4, crash_epoch=1):
    callbacks = []
    if crash:
        callbacks.append(CrashOnce(str(tmp_path / "crash-marker"),
                                   crash_epoch=crash_epoch))
    recorder = EpochRecorder()
    callbacks.append(recorder)
    strategy = RayStrategy(num_workers=2, max_restarts=max_restarts)
    trainer = Trainer(
        strategy=strategy,
        max_epochs=max_epochs,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=2,
        limit_val_batches=1,
        callbacks=callbacks,
    )
    trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    return trainer, strategy, recorder


def test_worker_death_fails_fast_without_elastic(tmp_path):
    """max_restarts=0 keeps reference semantics: crash propagates."""
    with pytest.raises(ActorDiedError):
        _fit(tmp_path, max_restarts=0)


def test_elastic_restart_completes_fit(tmp_path):
    trainer, strategy, recorder = _fit(tmp_path, max_restarts=1)
    assert strategy.restarts_used == 1
    assert np.isfinite(trainer.callback_metrics["train_loss"])
    # Completed all epochs: epoch 0 ran pre-crash, checkpointed, then the
    # respawned set resumed at epoch 1 (<= restart_every_n_epochs lost).
    assert trainer.epochs_run == 4
    # Callback state rode the restart checkpoint: epoch 0 (pre-crash)
    # survives, epochs 1-3 ran on the respawned set — no resets, no gaps.
    assert recorder.epochs == [0, 1, 2, 3]
    # Restart scratch dir is cleaned up after success.
    leftovers = [d for d in os.listdir(tmp_path)
                 if d.startswith(".rlt-restart-")]
    assert not leftovers


def test_elastic_budget_exhaustion_raises(tmp_path):
    """Crashing more times than max_restarts still fails."""
    marker = str(tmp_path / "never-written-marker")

    class AlwaysCrash(CrashOnce):
        def on_train_epoch_start(self, trainer, module) -> None:
            if (trainer.global_rank == self.crash_rank
                    and trainer.current_epoch == self.crash_epoch):
                os._exit(1)

    strategy = RayStrategy(num_workers=2, max_restarts=1)
    trainer = Trainer(
        strategy=strategy,
        max_epochs=3,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=2,
        limit_val_batches=1,
        callbacks=[AlwaysCrash(marker)],
    )
    with pytest.raises(ActorDiedError):
        trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert strategy.restarts_used == 1
    # Scratch dir is reclaimed on failure too.
    assert not [d for d in os.listdir(tmp_path)
                if d.startswith(".rlt-restart-")]


def test_user_exception_is_not_retried(tmp_path):
    """Deterministic exceptions in user code must fail fast, not burn the
    restart budget re-raising the same error."""
    from ray_lightning_tpu.cluster.actor import RemoteError

    class BadHook(Callback):
        def on_train_epoch_start(self, trainer, module) -> None:
            raise ValueError("deterministic user bug")

    strategy = RayStrategy(num_workers=1, max_restarts=3)
    trainer = Trainer(
        strategy=strategy,
        max_epochs=1,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        limit_train_batches=1,
        callbacks=[BadHook()],
    )
    with pytest.raises(RemoteError, match="deterministic user bug"):
        trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert strategy.restarts_used == 0


def test_elastic_restart_without_checkpoint_restarts_from_scratch(tmp_path):
    """Crash at epoch 0 (before any restart checkpoint exists): the
    respawned set simply begins again."""
    trainer, strategy, _ = _fit(tmp_path, max_restarts=1, max_epochs=2,
                                crash_epoch=0)
    assert strategy.restarts_used == 1
    assert trainer.epochs_run == 2
