"""rlt-lint: rule matrix, suppression policy, baseline semantics,
scoping, and the tree-wide acceptance gate (ISSUE 14).

The fixture corpus under ``tools/rlt_lint/fixtures/`` is the per-rule
positive/negative matrix (each rule ships flagged AND clean snippets,
asserted line-exactly by the selftest).  These tests drive that corpus
plus the pieces fixtures cannot cover: the committed baseline, git
scoping, the repo-config registries, and the two ISSUE-pinned negative
self-tests — deleting a distributed tracer's ``clock=`` or moving a
``jax.jit`` construction into ``ServeEngine.step`` must fail
``./format.sh``.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time

import pytest

from tools.rlt_lint.cli import (
    _FIXTURE_DIR, apply_baseline, in_scope, load_baseline, run_fixture,
    run_lint, selftest, _git_files,
)
from tools.rlt_lint.core import (
    Config, check_source, load_env_registry, load_schema_keys,
    repo_config,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(rel):
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# Fixture matrix
# ---------------------------------------------------------------------------

def test_fixture_matrix_selftest_passes():
    assert selftest() == 0


def test_every_rule_has_flagged_and_clean_fixtures():
    """Each rule's fixture file must carry >= 2 expected findings AND
    >= 2 'clean' markers (negative snippets the rule must NOT flag)."""
    import re

    by_rule = {}
    for name in sorted(os.listdir(_FIXTURE_DIR)):
        if not name.endswith(".py"):
            continue
        src = open(os.path.join(_FIXTURE_DIR, name)).read()
        m = re.match(r"(rlt\d{3})", name)
        assert m, f"fixture {name} must be named rltNNN_*.py"
        rule = m.group(1).upper()
        rec = by_rule.setdefault(rule, {"expect": 0, "clean": 0})
        rec["expect"] += len(re.findall(r"#\s*expect\[", src))
        rec["clean"] += len(re.findall(r"#\s*clean", src, re.I))
    for rule in [f"RLT{i:03d}" for i in range(8)]:
        assert rule in by_rule, f"no fixture file for {rule}"
        assert by_rule[rule]["expect"] >= 2, f"{rule}: <2 flagged snippets"
        assert by_rule[rule]["clean"] >= 2, f"{rule}: <2 clean snippets"


def test_fixture_runner_catches_a_broken_rule(tmp_path):
    """The selftest fails BOTH ways: a finding that stops firing and a
    finding that fires unexpectedly."""
    p = tmp_path / "rlt007_broken.py"
    p.write_text(
        "import threading\n"
        "t = threading.Thread(target=print, daemon=True)  # expect[RLT007]\n"
    )
    problems, n = run_fixture(str(p))
    assert n == 1
    assert any("did not fire" in x for x in problems)
    p.write_text(
        "import threading\n"
        "t = threading.Thread(target=print)\n"
    )
    problems, _ = run_fixture(str(p))
    assert any("unexpected RLT007" in x for x in problems)


# ---------------------------------------------------------------------------
# Suppression policy
# ---------------------------------------------------------------------------

def test_noqa_with_reason_suppresses():
    src = (
        "import threading\n"
        "t = threading.Thread(target=print)"
        "  # rlt: noqa[RLT007] joined in caller\n"
    )
    assert check_source("x.py", src, Config()) == []


def test_noqa_without_reason_is_a_finding_and_does_not_suppress():
    src = (
        "import threading\n"
        "t = threading.Thread(target=print)  # rlt: noqa[RLT007]\n"
    )
    findings = check_source("x.py", src, Config())
    assert _rules_of(findings) == {"RLT000", "RLT007"}


def test_noqa_unknown_rule_is_a_finding():
    src = "x = 1  # rlt: noqa[RLT999] not a rule\n"
    findings = check_source("x.py", src, Config())
    assert [f.rule for f in findings] == ["RLT000"]


def test_noqa_only_suppresses_the_named_rule():
    src = (
        "import threading\n"
        "t = threading.Thread(target=print)  # rlt: noqa[RLT001] wrong\n"
    )
    findings = check_source("x.py", src, Config())
    assert _rules_of(findings) == {"RLT007"}


# ---------------------------------------------------------------------------
# Baseline semantics
# ---------------------------------------------------------------------------

def _finding(path="a.py", rule="RLT007", text="t = Thread()"):
    from tools.rlt_lint.core import Finding

    return Finding(path, 10, rule, "msg", text)


def test_baseline_suppresses_matching_findings_up_to_count():
    entries = [{"path": "a.py", "rule": "RLT007",
                "text": "t = Thread()", "count": 2}]
    findings = [_finding(), _finding(), _finding()]
    kept, stale = apply_baseline(findings, entries, ["a.py"])
    assert len(kept) == 1 and not stale


def test_baseline_matches_on_text_not_line():
    """Line drift must not churn the baseline: the same source text at
    a different line still matches its entry."""
    entries = [{"path": "a.py", "rule": "RLT007",
                "text": "t = Thread()", "count": 1}]
    moved = _finding()._replace(line=999)
    kept, stale = apply_baseline([moved], entries, ["a.py"])
    assert kept == [] and stale == []


def test_stale_baseline_entry_is_reported_for_scanned_files():
    entries = [{"path": "a.py", "rule": "RLT007",
                "text": "gone = Thread()", "count": 1}]
    kept, stale = apply_baseline([], entries, ["a.py"])
    assert stale and "stale baseline entry" in stale[0]
    # ...but NOT when the file was out of scope this run (--changed).
    kept, stale = apply_baseline([], entries, ["b.py"])
    assert stale == []


def test_partially_consumed_baseline_count_is_stale():
    """Fixing SOME of an entry's sites must flag the leftover count:
    otherwise the unused budget silently suppresses a future same-text
    finding without noqa or review (the baseline must only shrink)."""
    entries = [{"path": "a.py", "rule": "RLT007",
                "text": "t = Thread()", "count": 3}]
    kept, stale = apply_baseline([_finding()], entries, ["a.py"])
    assert kept == []
    assert stale and "only 1 matched" in stale[0]
    # An exactly-consumed count is NOT stale.
    kept, stale = apply_baseline(
        [_finding(), _finding(), _finding()], entries, ["a.py"]
    )
    assert kept == [] and stale == []


def test_committed_baseline_is_well_formed_and_documented():
    entries = load_baseline(
        os.path.join(REPO, "tools", "rlt_lint", "baseline.json")
    )
    assert entries, "committed baseline unexpectedly empty"
    # Only the grandfathered MPMD instruction-loop syncs are allowed in
    # the shipped baseline; anything else must be fixed or noqa'd.
    assert {e["path"] for e in entries} == {
        "ray_lightning_tpu/mpmd/stage.py"
    }
    assert {e["rule"] for e in entries} == {"RLT002"}
    docs = _read("docs/STATIC_ANALYSIS.md")
    assert "mpmd/stage.py" in docs and "baseline" in docs.lower()


# ---------------------------------------------------------------------------
# Scoping
# ---------------------------------------------------------------------------

def test_in_scope_covers_package_tools_bench_not_tests():
    assert in_scope("ray_lightning_tpu/serve/engine.py")
    assert in_scope("tools/rlt_top.py")
    assert in_scope("bench_serve.py")
    assert in_scope("__graft_entry__.py")
    assert in_scope("examples/tpu_serve_example.py")
    assert not in_scope("tests/test_lint.py")
    assert not in_scope("tools/rlt_lint/fixtures/rlt007_threads.py")
    assert not in_scope("README.md")


def test_changed_scoping_against_synthetic_git_diff(tmp_path):
    """--changed lints exactly the files git reports as changed."""
    repo = tmp_path / "r"
    os.makedirs(repo / "ray_lightning_tpu")
    env = dict(os.environ,
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, env=env,
                       check=True, capture_output=True)

    git("init", "-q")
    (repo / "ray_lightning_tpu" / "old.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "base")
    # one modified, one added, one untouched
    (repo / "ray_lightning_tpu" / "old.py").write_text("x = 2\n")
    (repo / "ray_lightning_tpu" / "new.py").write_text("y = 1\n")
    git("add", "ray_lightning_tpu/new.py")
    changed = sorted(p for p in _git_files(False, cwd=str(repo))
                     if in_scope(p))
    assert changed == [
        "ray_lightning_tpu/new.py", "ray_lightning_tpu/old.py",
    ]


def test_changed_scope_includes_renames_and_untracked(tmp_path):
    """A renamed-and-edited file (git status R — dropped by plain
    --diff-filter=ACM) and a brand-new untracked file (invisible to
    both ls-files and diff) must both land in the lint scope; either
    slipping through ships an unlinted hot-path edit."""
    repo = tmp_path / "r"
    os.makedirs(repo / "ray_lightning_tpu")
    env = dict(os.environ,
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, env=env,
                       check=True, capture_output=True)

    git("init", "-q")
    body = "".join(f"x{i} = {i}\n" for i in range(40))
    (repo / "ray_lightning_tpu" / "engine_old.py").write_text(body)
    git("add", "-A")
    git("commit", "-qm", "base")
    # rename + a small edit: similar enough for rename detection.
    git("mv", "ray_lightning_tpu/engine_old.py",
        "ray_lightning_tpu/engine_new.py")
    (repo / "ray_lightning_tpu" / "engine_new.py").write_text(
        body + "y = 1\n"
    )
    # brand-new file, never git-added.
    (repo / "ray_lightning_tpu" / "untracked.py").write_text("z = 1\n")
    changed = sorted(p for p in _git_files(False, cwd=str(repo))
                     if in_scope(p))
    assert "ray_lightning_tpu/engine_new.py" in changed
    assert "ray_lightning_tpu/untracked.py" in changed
    # --all picks up the untracked file too.
    everything = sorted(p for p in _git_files(True, cwd=str(repo))
                        if in_scope(p))
    assert "ray_lightning_tpu/untracked.py" in everything


# ---------------------------------------------------------------------------
# Repo config registries
# ---------------------------------------------------------------------------

def test_repo_config_loads_env_registry_and_schema_keys():
    cfg = repo_config(REPO)
    assert "RLT_GRAD_COMM" in cfg.env_registry
    assert "RLT_FAULT" in cfg.env_registry
    req, opt = cfg.schema_keys["HEARTBEAT"]
    assert "global_step" in req and "open_span" in opt


def test_env_bus_registry_matches_runtime_module():
    """The linter's AST parse of env_bus.py and the runtime module
    agree — strategies forward exactly the forward-marked subset."""
    from ray_lightning_tpu.parallel import env_bus

    parsed = load_env_registry(
        _read("ray_lightning_tpu/parallel/env_bus.py")
    )
    assert parsed == frozenset(env_bus.registered_names())
    assert set(env_bus.forwarded_vars()) <= parsed
    # the forwarding bridge the strategies actually use
    assert "RLT_GRAD_COMM" in env_bus.forwarded_vars()
    assert "RLT_AGENT_TOKEN" not in env_bus.forwarded_vars()


def test_registry_drift_is_a_finding():
    """A registered hot-path qualname that no longer exists fails the
    lint, so the protection moves with refactors instead of silently
    evaporating."""
    cfg = Config(hot_sync={"m.py": frozenset({"Engine.gone"})})
    findings = check_source("m.py", "class Engine:\n    pass\n", cfg)
    assert [f.rule for f in findings] == ["RLT000"]
    assert "Engine.gone" in findings[0].message


def test_schema_key_loader_reads_required_and_optional():
    keys = load_schema_keys(
        "_BEAT_REQUIRED = {'a': int}\n_BEAT_OPTIONAL = {'b': str}\n"
    )
    assert keys == {"BEAT": (frozenset({"a"}), frozenset({"b"}))}


# ---------------------------------------------------------------------------
# ISSUE-pinned negative self-tests (format.sh must fail on these edits)
# ---------------------------------------------------------------------------

def test_deleting_spantracer_clock_fails_lint():
    """Removing ``clock=time.time`` from a distributed tracer's
    SpanTracer construction is the PR-13 stitching bug — RLT004 pins
    it in every registered wall-clock-tracer module."""
    cfg = repo_config(REPO)
    for rel in sorted(cfg.wall_clock_tracer_files):
        src = _read(rel)
        clean = check_source(rel, src, cfg)
        assert "RLT004" not in _rules_of(clean), rel
        # stage.py aliases `import time as _time`
        mutated = src.replace("clock=time.time,", "") \
                     .replace("clock=_time.time,", "")
        assert mutated != src, rel
        findings = check_source(rel, mutated, cfg)
        assert "RLT004" in _rules_of(findings), rel


def test_moving_jit_into_engine_step_fails_lint():
    """A fresh ``jax.jit`` per serve iteration is the PR-12 recompile
    footgun ('zero steady-state recompiles' dies under cache pressure)
    — RLT001 pins ServeEngine.step."""
    rel = "ray_lightning_tpu/serve/engine.py"
    cfg = repo_config(REPO)
    src = _read(rel)
    anchor = "    def step(self) -> bool:\n"
    assert anchor in src
    mutated = src.replace(
        anchor,
        anchor + "        _oops = jax.jit(lambda z: z)\n",
    )
    assert "RLT001" not in _rules_of(check_source(rel, src, cfg))
    findings = check_source(rel, mutated, cfg)
    assert "RLT001" in _rules_of(findings)


def test_partial_jit_nested_def_in_hot_path_fails_lint():
    """Review fix: ``@partial(jax.jit, ...)`` — the required form for
    static/donated args — constructs a fresh jit object per enclosing
    call exactly like ``@jax.jit``; the nested-def check must unwrap
    partial or the most common decorator idiom evades RLT001."""
    rel = "ray_lightning_tpu/serve/engine.py"
    cfg = repo_config(REPO)
    anchor = "    def step(self) -> bool:\n"
    injected = anchor + (
        "        @functools.partial(jax.jit, donate_argnums=0)\n"
        "        def _oops(z):\n"
        "            return z\n"
    )
    mutated = _read(rel).replace(anchor, injected)
    findings = check_source(rel, mutated, cfg)
    assert "RLT001" in _rules_of(findings)


def test_unregistered_env_knob_fails_lint():
    """A new RLT_* knob read anywhere without an env_bus entry fails —
    the class of bug where a knob silently never reaches workers."""
    rel = "ray_lightning_tpu/core/loop.py"
    cfg = repo_config(REPO)
    src = _read(rel) + (
        "\n\ndef _sneaky():\n"
        "    import os\n"
        "    return os.environ.get('RLT_BRAND_NEW_KNOB')\n"
    )
    findings = check_source(rel, src, cfg)
    assert any(f.rule == "RLT005"
               and "RLT_BRAND_NEW_KNOB" in f.message for f in findings)


def test_schema_producer_key_drift_fails_lint():
    """A key added to make_beat without a schema entry fails RLT006
    (the static complement to tools/check_telemetry_schema.py)."""
    rel = "ray_lightning_tpu/telemetry/heartbeat.py"
    cfg = repo_config(REPO)
    src = _read(rel)
    mutated = src.replace(
        '"phase": str(getattr(ctx, "phase", "init")),',
        '"phase": str(getattr(ctx, "phase", "init")),\n'
        '        "phse_typo": 0,',
    )
    assert mutated != src
    assert "RLT006" not in _rules_of(check_source(rel, src, cfg))
    assert "RLT006" in _rules_of(check_source(rel, mutated, cfg))


# ---------------------------------------------------------------------------
# Acceptance: the shipped tree is clean
# ---------------------------------------------------------------------------

def test_shipped_tree_is_lint_clean_modulo_baseline(capsys):
    paths = [p for p in _git_files(True) if in_scope(p)]
    assert len(paths) > 80, "scan scope suspiciously small"
    rc = run_lint(paths, os.path.join("tools", "rlt_lint",
                                      "baseline.json"))
    out = capsys.readouterr().out
    assert rc == 0, f"tree has unsuppressed findings:\n{out}"


def test_guard_comment_on_use_site_is_not_a_suppression():
    """Review fix: only the annotated DECLARATION assignment is exempt
    from RLT003 — pasting '# guarded by ...' on a use site must not
    bypass the lock check without a reasoned noqa."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._state = []  # guarded by self._lock\n"
        "    def bad(self):\n"
        "        return len(self._state)  # guarded by self._lock\n"
    )
    findings = check_source("c.py", src, Config())
    assert [f.rule for f in findings] == ["RLT003"]
    assert findings[0].line == 7


def test_explicit_absolute_path_is_normalized(tmp_path, capsys):
    """Review fix: an absolute path to a registered file must hit the
    same path-keyed rules as the repo-relative form (no false clean)."""
    rel = "ray_lightning_tpu/serve/engine.py"
    src = _read(rel)
    anchor = "    def step(self) -> bool:\n"
    mutated = src.replace(
        anchor, anchor + "        _oops = jax.jit(lambda z: z)\n"
    )
    scratch = os.path.join(REPO, rel + ".lintbak")
    os.rename(os.path.join(REPO, rel), scratch)
    try:
        with open(os.path.join(REPO, rel), "w") as f:
            f.write(mutated)
        rc = run_lint([os.path.join(REPO, rel)],
                      os.path.join("tools", "rlt_lint", "baseline.json"))
    finally:
        os.replace(scratch, os.path.join(REPO, rel))
    out = capsys.readouterr().out
    assert rc == 1 and "RLT001" in out, out


def test_heartbeat_stop_does_not_hang_on_never_released_sink():
    """Review fix: with the publisher wedged inside a sink put holding
    the publish lock, stop() must return within its timeout budget
    (skipping the final beat) instead of blocking unboundedly."""
    from ray_lightning_tpu.telemetry.heartbeat import HeartbeatPublisher

    class Ctx:
        global_step = micro_step = current_epoch = progress = 0
        phase = "train"

    class WedgedSink:
        def __init__(self):
            self.first = threading.Event()

        def put(self, beat):
            self.first.set()
            time.sleep(3600)  # never returns within the test

    sink = WedgedSink()
    pub = HeartbeatPublisher(0, Ctx(), sink, interval_s=0.01)
    pub.start()
    assert sink.first.wait(5.0)
    t0 = time.monotonic()
    pub.stop(final=True, timeout_s=0.2)
    assert time.monotonic() - t0 < 5.0, "stop() hung on a wedged sink"


def test_guarded_by_annotations_are_live():
    """The lock discipline the sweep added is actually enforced: strip
    one 'with self._feed_lock' from PrefillRunner and RLT003 fires."""
    rel = "ray_lightning_tpu/serve/dist/prefill.py"
    cfg = repo_config(REPO)
    src = _read(rel)
    mutated = src.replace(
        "        with self._feed_lock:\n"
        "            done, self._done = self._done, []\n",
        "        if True:\n"
        "            done, self._done = self._done, []\n",
    )
    assert mutated != src
    assert "RLT003" not in _rules_of(check_source(rel, src, cfg))
    assert "RLT003" in _rules_of(check_source(rel, mutated, cfg))


# ---------------------------------------------------------------------------
# Sweep regressions (the genuine fixes the tree-wide run surfaced)
# ---------------------------------------------------------------------------

class _BlockingSink:
    """Sink whose put() can be held open — and which records overlap."""

    def __init__(self):
        self.release = threading.Event()
        self.release.set()
        self.beats = []
        self._inside = 0
        self.max_inside = 0
        self._mu = threading.Lock()

    def put(self, beat):
        with self._mu:
            self._inside += 1
            self.max_inside = max(self.max_inside, self._inside)
        try:
            self.release.wait(5.0)
            self.beats.append(beat)
        finally:
            with self._mu:
                self._inside -= 1


def test_heartbeat_stop_final_beat_serializes_with_wedged_publisher():
    """Sweep fix: stop() joins the publisher with a timeout; a wedged
    sink used to leave BOTH threads inside _publish (duplicate seq,
    interleaved file writes).  The publish lock serializes them: with
    the publisher wedged mid-put, stop() either lands the final beat
    AFTER the put completes or (lock unavailable within budget) skips
    it — never overlaps.  Either way stop() stays bounded."""
    from ray_lightning_tpu.telemetry.heartbeat import HeartbeatPublisher

    class Ctx:
        global_step = micro_step = current_epoch = progress = 0
        phase = "train"

    sink = _BlockingSink()
    pub = HeartbeatPublisher(0, Ctx(), sink, interval_s=0.01)
    pub.start()
    deadline = time.monotonic() + 5
    while not sink.beats and time.monotonic() < deadline:
        time.sleep(0.005)
    sink.release.clear()          # wedge the NEXT publish mid-put
    time.sleep(0.05)              # let the publisher enter the wedge

    done = threading.Event()

    def stopper():
        pub.stop(final=True, timeout_s=0.05)  # join times out
        done.set()

    t = threading.Thread(target=stopper, daemon=True)
    t.start()
    # Pre-fix, the stopper thread would now be INSIDE _publish
    # concurrently with the wedged publisher (max_inside == 2).
    time.sleep(0.1)
    assert done.wait(5.0), "stop() not bounded while sink wedged"
    sink.release.set()
    t.join(5.0)
    deadline = time.monotonic() + 5
    while sink._inside and time.monotonic() < deadline:
        time.sleep(0.005)
    assert sink.max_inside == 1, "concurrent _publish detected"
    seqs = [b["seq"] for b in sink.beats]
    assert len(seqs) == len(set(seqs)), f"duplicate seq: {seqs}"


def test_engine_reply_handle_cache_is_lock_guarded():
    """Sweep fix: ServeEngine._reply_handles is mutated by the serve
    thread and cleared by stop() after a join that can time out — the
    annotation (and RLT003) now pin it under self._lock."""
    rel = "ray_lightning_tpu/serve/engine.py"
    src = _read(rel)
    assert "# guarded by self._lock\n" \
           "        self._reply_handles" in src
    cfg = repo_config(REPO)
    assert "RLT003" not in _rules_of(check_source(rel, src, cfg))


def test_inproc_pipeline_threads_are_daemonized():
    src = _read("ray_lightning_tpu/mpmd/inproc.py")
    assert 'name=f"rlt-mpmd-w{r.worker}",\n            daemon=True' in src, \
        "inproc drive threads must pass explicit daemon="


@pytest.mark.parametrize("rule", [f"RLT{i:03d}" for i in range(8)])
def test_rule_catalog_documented(rule):
    docs = _read("docs/STATIC_ANALYSIS.md")
    assert rule in docs, f"{rule} missing from docs/STATIC_ANALYSIS.md"
