"""Prefix-aware KV reuse + chunked prefill.

The correctness bar mirrors the serving plane's: a request whose
prompt shares a resident prefix chain must produce EXACTLY the tokens
the static ``generate()`` reference produces — greedy AND sampled —
because claiming is refcount bookkeeping, never recompute.  On top:
the allocator's refcount/COW discipline (sharing never enables a
double-free; eviction never takes a block a live chain holds), the
radix index units (match / insert / mid-edge split / LRU eviction),
the scheduler's claim + reclaim hooks (evict-before-preempt), COW
bookkeeping, chunked prefill's no-stall bound (a long admission never
starves resident decode slots for more than one chunk tick — pinned
via per-tick token emission), and adapter-drop invalidation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_lightning_tpu.models.generate import generate
from ray_lightning_tpu.models.gpt import GPT, GPTConfig
from ray_lightning_tpu.serve.engine import ServeConfig, ServeEngine
from ray_lightning_tpu.serve.kv_cache import (
    TRASH_BLOCK, BlockAllocator, PrefixIndex,
)
from ray_lightning_tpu.serve.scheduler import Request, Scheduler
from ray_lightning_tpu.telemetry import compile_event_count

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def model():
    cfg = GPTConfig(vocab_size=128, n_layer=2, n_head=4, d_model=64,
                    seq_len=64, warmup_steps=1)
    m = GPT(cfg, attn_impl="xla")
    params = m.init_params(jax.random.PRNGKey(0))
    return m, params


def _ref_tokens(m, params, prompt, n):
    out = generate(m, params, jnp.asarray([prompt], jnp.int32), n)
    return np.asarray(out)[0, len(prompt):].tolist()


def _rand_prompt(seed, length, vocab=128):
    rng = np.random.default_rng(seed)
    return rng.integers(1, vocab, size=(length,)).tolist()


# ---------------------------------------------------------------------------
# BlockAllocator: refcount discipline (jax-free)
# ---------------------------------------------------------------------------

class TestAllocatorRefcounts:
    def test_retain_free_lifecycle(self):
        a = BlockAllocator(6)
        ids = a.alloc(2)
        b = ids[0]
        assert a.refcount(b) == 1 and not a.is_shared(b)
        a.retain([b])
        assert a.refcount(b) == 2 and a.is_shared(b)
        free_before = a.free_blocks
        a.free([b])                        # drops to 1: still live
        assert a.refcount(b) == 1
        assert a.free_blocks == free_before
        a.free([b])                        # drops to 0: returns to pool
        assert a.refcount(b) == 0
        assert a.free_blocks == free_before + 1
        a.free([ids[1]])

    def test_shared_block_double_free_still_raises(self):
        """Sharing widens the legal free count to the refcount — one
        PAST it is still the hard error."""
        a = BlockAllocator(4)
        (b,) = a.alloc(1)
        a.retain([b])
        a.free([b])
        a.free([b])
        with pytest.raises(RuntimeError, match="double-free"):
            a.free([b])

    def test_retain_dead_block_raises(self):
        a = BlockAllocator(4)
        (b,) = a.alloc(1)
        a.free([b])
        with pytest.raises(RuntimeError, match="not live"):
            a.retain([b])

    def test_shared_block_survives_one_owner(self):
        """The chain-resident case: request frees its blocks, the
        index's reference keeps them out of the free list — a fresh
        alloc never hands out a block a chain still holds."""
        a = BlockAllocator(4)                 # 3 usable
        ids = a.alloc(3)
        a.retain(ids)                         # the "index" reference
        a.free(ids)                           # the "request" reference
        assert all(a.refcount(b) == 1 for b in ids)
        assert a.alloc(1) is None             # nothing actually freed
        a.free(ids)
        assert a.free_blocks == 3


# ---------------------------------------------------------------------------
# PrefixIndex: radix units (jax-free)
# ---------------------------------------------------------------------------

def _tok(n, base=0):
    return [base + i + 1 for i in range(n)]


class TestPrefixIndex:
    def _index(self, num_blocks=16, block_size=4):
        a = BlockAllocator(num_blocks)
        return a, PrefixIndex(a, block_size)

    def _insert(self, a, idx, key, tokens):
        """Prefill-sim: alloc the full blocks, insert, free the
        request's own references (the index keeps its retains)."""
        ids = a.alloc(len(tokens) // idx.block_size)
        idx.insert(key, tokens, ids)
        a.free(ids)
        return ids

    def test_insert_claim_roundtrip(self):
        a, idx = self._index()
        toks = _tok(12)                       # 3 full blocks
        ids = self._insert(a, idx, None, toks)
        got = idx.claim(None, toks, max_blocks=3)
        assert got == ids
        assert all(a.refcount(b) == 2 for b in got)  # claim retained
        a.free(got)
        assert idx.stats()["hits"] == 1
        assert idx.stats()["blocks_claimed"] == 3

    def test_claim_cap_and_partial_edge(self):
        a, idx = self._index()
        toks = _tok(16)                       # one 4-block edge
        ids = self._insert(a, idx, None, toks)
        # Cap below the edge length: partial-edge match, 2 blocks.
        got = idx.claim(None, toks, max_blocks=2)
        assert got == ids[:2]
        a.free(got)
        # Diverging tokens mid-edge: only the shared blocks match.
        fork = toks[:8] + _tok(8, base=100)
        got = idx.claim(None, fork, max_blocks=4)
        assert got == ids[:2]
        a.free(got)

    def test_claim_miss_and_zero_cap(self):
        a, idx = self._index()
        assert idx.claim(None, _tok(8), max_blocks=2) == []
        self._insert(a, idx, None, _tok(8))
        assert idx.claim(None, _tok(8), max_blocks=0) == []
        st = idx.stats()
        assert st["lookups"] == 2 and st["hits"] == 0

    def test_mid_edge_split(self):
        a, idx = self._index()
        long = _tok(16)
        ids = self._insert(a, idx, None, long)
        # Shares 2 of the 4 blocks, then diverges: splits the edge.
        fork = long[:8] + _tok(8, base=50)
        fork_ids = a.alloc(4)
        added = idx.insert(None, fork, fork_ids)
        assert added == 2                     # only the new suffix
        a.free(fork_ids)
        # Both chains stay fully claimable after the split.
        got = idx.claim(None, long, max_blocks=4)
        assert got == ids
        a.free(got)
        got = idx.claim(None, fork, max_blocks=4)
        assert got == ids[:2] + fork_ids[2:]
        a.free(got)

    def test_insert_covered_is_free(self):
        a, idx = self._index()
        toks = _tok(12)
        self._insert(a, idx, None, toks)
        cached = idx.stats()["cached_blocks"]
        ids = a.alloc(3)
        assert idx.insert(None, toks, ids) == 0   # walk matches, no-op
        a.free(ids)
        assert idx.stats()["cached_blocks"] == cached

    def test_insert_short_ids_raises(self):
        a, idx = self._index()
        with pytest.raises(ValueError, match="full blocks"):
            idx.insert(None, _tok(12), a.alloc(2))

    def test_keys_are_isolated(self):
        """One tenant's chain never satisfies another's lookup."""
        a, idx = self._index()
        toks = _tok(8)
        self._insert(a, idx, "tenant-a", toks)
        assert idx.claim("tenant-b", toks, max_blocks=2) == []
        assert idx.claim(None, toks, max_blocks=2) == []

    def test_evict_lru_and_refcount_pin(self):
        a, idx = self._index(num_blocks=16)
        cold = self._insert(a, idx, None, _tok(8))           # older
        hot = self._insert(a, idx, None, _tok(8, base=40))   # newer
        held = idx.claim(None, _tok(8, base=40), max_blocks=2)
        assert held == hot
        # Ask for everything: the LRU chain goes, the claimed (shared,
        # refcount 2) chain is pinned — NEVER evicted under a live
        # claim.
        freed = idx.evict(4)
        assert freed == 2
        assert idx.stats()["blocks_evicted"] == 2
        assert all(a.refcount(b) == 0 for b in cold)
        assert all(a.refcount(b) == 2 for b in hot)
        a.free(held)
        assert idx.evict(4) == 2              # now droppable
        assert idx.stats()["cached_blocks"] == 0

    def test_evict_tail_first_preserves_prefix(self):
        """Partial eviction trims chains from the tail: the surviving
        prefix must still match (chain integrity)."""
        a, idx = self._index()
        toks = _tok(16)
        ids = self._insert(a, idx, None, toks)
        assert idx.evict(1) == 1              # drops ids[-1] only
        got = idx.claim(None, toks, max_blocks=4)
        assert got == ids[:3]
        a.free(got)

    def test_drop_key_and_drop_all(self):
        a, idx = self._index()
        self._insert(a, idx, "t0", _tok(8))
        self._insert(a, idx, None, _tok(8, base=30))
        assert idx.drop("t0") == 2
        assert idx.claim("t0", _tok(8), max_blocks=2) == []
        assert idx.drop_all() == 2
        assert a.free_blocks == a.num_blocks - 1
        assert idx.drop("t0") == 0            # idempotent


# ---------------------------------------------------------------------------
# Scheduler: claim admission, evict-before-preempt, COW (jax-free)
# ---------------------------------------------------------------------------

def _sched(num_blocks=16, **kw):
    alloc = BlockAllocator(num_blocks)
    args = dict(num_slots=2, block_size=4, max_blocks_per_seq=4,
                buckets=[4, 8, 16], max_queue=4)
    args.update(kw)
    return Scheduler(args.pop("num_slots"), alloc, **args)


def _req(rid, prompt_len, **kw):
    return Request(rid=rid, prompt=_tok(prompt_len),
                   max_new_tokens=kw.pop("max_new_tokens", 4), **kw)


class TestSchedulerClaim:
    def test_claimed_admission_exact_coverage(self):
        s = _sched()
        claimed = s.allocator.alloc(2)        # pretend-resident chain
        s.allocator.retain(claimed)           # the claim's reference
        s.claim_fn = lambda req: list(claimed)
        s.submit(_req("r1", 11))              # ceil(11/4) = 3 blocks
        (adm,), _ = s.poll()
        slot, req, bucket = adm
        assert bucket == 0                    # exact-coverage sentinel
        assert req.claimed_tokens == 8
        assert s._blocks[slot][:2] == claimed
        assert len(s._blocks[slot]) == 3      # claimed + 1 fresh
        row = s.block_tables[slot]
        assert row[3] == TRASH_BLOCK
        s.finish(slot)                        # frees the claim refs too
        assert [s.allocator.refcount(b) for b in claimed] == [1, 1]

    def test_reclaim_runs_before_admission_fails(self):
        """Pool dry at admission: the reclaim hook (cache eviction) is
        consulted before the grant stalls — a resident chain is always
        cheaper than a waiting request."""
        s = _sched(num_blocks=5)              # 4 usable
        resident = s.allocator.alloc(3)       # cache-held blocks
        calls = []

        def reclaim(n):
            calls.append(n)
            s.allocator.free(resident[:n])
            return n

        s.reclaim = reclaim
        s.submit(_req("r1", 16))              # needs all 4 blocks
        (adm,), _ = s.poll()
        assert adm[2] == 16
        assert calls == [3]

    def test_claim_refs_dropped_when_pool_dry(self):
        """An admission that claims but cannot cover its suffix must
        drop the claim references (no leak, no double-retain when the
        request is re-granted later)."""
        s = _sched(num_blocks=4)              # 3 usable
        chain = s.allocator.alloc(2)
        s.allocator.retain(chain)
        s.claim_fn = lambda req: (s.allocator.retain(chain),
                                  list(chain))[1]
        s.allocator.alloc(1)                  # drain the pool
        s.submit(_req("r1", 16))              # needs 2 fresh: dry
        adms, _ = s.poll()
        assert adms == []
        assert [s.allocator.refcount(b) for b in chain] == [2, 2]

    def test_cow_slot(self):
        s = _sched()
        s.submit(_req("r1", 16))
        ((slot, _, _),), _ = s.poll()
        assert s.cow_slot(slot, 4) == ([], [])      # nothing shared
        shared = s._blocks[slot][:2]
        s.allocator.retain(shared)                  # now refcount 2
        src, dst = s.cow_slot(slot, 2)
        assert src == shared and len(dst) == 2
        assert s._blocks[slot][:2] == dst
        assert list(s.block_tables[slot][:2]) == dst
        assert [s.allocator.refcount(b) for b in shared] == [1, 1]
        s.allocator.free(shared)

    def test_cow_slot_pool_dry_mutates_nothing(self):
        s = _sched(num_blocks=5)              # 4 usable
        s.submit(_req("r1", 16))              # takes all 4
        ((slot, _, _),), _ = s.poll()
        shared = s._blocks[slot][:1]
        s.allocator.retain(shared)
        before = list(s._blocks[slot])
        assert s.cow_slot(slot, 4) is None
        assert s._blocks[slot] == before
        assert s.allocator.refcount(shared[0]) == 2
        s.allocator.free(shared)


# ---------------------------------------------------------------------------
# Engine: shared-prefix parity, chunked no-stall, invalidation
# ---------------------------------------------------------------------------

class TestPrefixEngine:
    def test_shared_prefix_parity_greedy_and_sampled(self, model):
        """The tentpole contract: a claim-served request is bitwise the
        static reference, greedy and at temperature>0 — and the second
        request actually HITS the cache."""
        m, params = model
        shared = _rand_prompt(5, 18)          # 2 full blocks @ Bs=8
        p1 = shared + _rand_prompt(6, 4)
        p2 = shared + _rand_prompt(7, 6)
        eng = ServeEngine(m, params,
                          ServeConfig(num_slots=2, block_size=8,
                                      prefix_cache=True))
        try:
            t1 = eng.generate(p1, 8)
            assert eng.prefix_cache.stats()["cached_blocks"] >= 2
            t2 = eng.generate(p2, 8)
            t2s = eng.generate(p2, 8, temperature=0.8, sample_seed=11)
            st = eng.prefix_cache.stats()
            assert st["hits"] >= 2 and st["blocks_claimed"] >= 4
            assert t1 == _ref_tokens(m, params, p1, 8)
            assert t2 == _ref_tokens(m, params, p2, 8)
            # Sampled arm: reference is the SAME seed served by a
            # cache-less engine (the static path doesn't sample).
            ref = ServeEngine(m, params,
                              ServeConfig(num_slots=2, block_size=8))
            try:
                t2s_ref = ref.generate(p2, 8, temperature=0.8,
                                       sample_seed=11)
            finally:
                ref.stop()
            assert t2s == t2s_ref
        finally:
            eng.stop()

    def test_steady_state_hit_zero_recompiles(self, model):
        m, params = model
        prompt = _rand_prompt(8, 24)
        eng = ServeEngine(m, params,
                          ServeConfig(num_slots=2, block_size=8,
                                      prefix_cache=True))
        try:
            ref = eng.generate(prompt, 6)
            # First claimed replay warms the suffix program (the
            # chunk executable at the smallest bucket covering the
            # uncovered tail — compiled once, like any bucket).
            warm = eng.generate(prompt, 6)
            assert warm == ref
            before = compile_event_count()
            again = eng.generate(prompt, 6)
            assert again == ref
            assert compile_event_count() - before == 0
            assert eng.prefix_cache.stats()["hits"] >= 2
        finally:
            eng.stop()

    def test_chunked_prefill_never_stalls_residents(self, model):
        """The no-stall pin, per-tick token emission: while a long
        prompt chunks in, every resident decode slot emits on every
        step except at most ONE chunk tick in a row."""
        m, params = model
        eng = ServeEngine(m, params,
                          ServeConfig(num_slots=3, block_size=8,
                                      prefill_chunk=16))
        long_prompt = _rand_prompt(9, 48)
        try:
            eng.generate(_rand_prompt(10, 12), 2)     # warm short path
            eng.generate(_rand_prompt(11, 48), 2)     # warm chunk path
            emitted = {0: 0, 1: 0}
            residents = [
                eng.submit(_rand_prompt(12 + i, 12), 48,
                           on_token=lambda idx, tok, i=i:
                           emitted.__setitem__(i, emitted[i] + 1))
                for i in (0, 1)
            ]
            while not all(emitted.values()):
                eng.step()
            first_long = []
            h = eng.submit(long_prompt, 4,
                           on_token=lambda idx, tok:
                           first_long.append(tok))
            stall, max_stall = {0: 0, 1: 0}, 0
            while not first_long:
                seen = dict(emitted)
                assert eng.step()
                for i in (0, 1):
                    stall[i] = 0 if emitted[i] > seen[i] else stall[i] + 1
                    max_stall = max(max_stall, stall[i])
            assert max_stall <= 1, f"resident stalled {max_stall} ticks"
            eng.run_until_idle()
            assert h.result(0) == _ref_tokens(m, params, long_prompt, 4)
            assert all(r.done() for r in residents)
            assert eng.stats.counters.get("prefill_chunks", 0) >= 2
        finally:
            eng.stop()

    def test_cache_pressure_evicts_not_preempts(self, model):
        """A full pool of resident chains yields to admissions via the
        reclaim hook — running requests are never preempted to make
        room while evictable cache blocks exist."""
        m, params = model
        eng = ServeEngine(m, params,
                          ServeConfig(num_slots=2, block_size=8,
                                      # 10 usable: 4 chains (8 resident
                                      # blocks) leave 2 free, the next
                                      # bucket-32 admission needs 4 —
                                      # MUST reclaim, never preempt.
                                      num_blocks=11,
                                      prefix_cache=True))
        try:
            for s in range(4):                # fill the pool with chains
                eng.generate(_rand_prompt(20 + s, 17), 2)
            assert eng.prefix_cache.stats()["cached_blocks"] >= 4
            prompt = _rand_prompt(30, 17)
            toks = eng.generate(prompt, 4)
            assert toks == _ref_tokens(m, params, prompt, 4)
            assert eng.prefix_cache.stats()["blocks_evicted"] > 0
            assert eng.stats.counters.get("preemptions", 0) == 0
        finally:
            eng.stop()

    def test_adapter_drop_invalidates_chains(self, model):
        """Replacing an adapter drops its chains (stale KV) without
        touching the base key's."""
        import dataclasses

        from ray_lightning_tpu.models.gpt import synthetic_lora_adapter

        m, params = model
        lora_cfg = dataclasses.replace(m.config, lora_rank=4)
        ad_a, merged_a = synthetic_lora_adapter(
            params, lora_cfg, jax.random.PRNGKey(31))
        ad_b, _ = synthetic_lora_adapter(
            params, lora_cfg, jax.random.PRNGKey(32))
        eng = ServeEngine(m, params,
                          ServeConfig(num_slots=2, block_size=8,
                                      max_adapters=2, adapter_rank=4,
                                      prefix_cache=True),
                          adapters={"t": ad_a})
        prompt = _rand_prompt(40, 18)
        try:
            ref = eng.generate(prompt, 6, adapter="t")
            assert ref == _ref_tokens(m, merged_a, prompt, 6)
            eng.generate(prompt, 6)           # base chain, same tokens
            assert "t" in eng.prefix_cache._roots
            eng.add_adapter("t", ad_b)        # hot-replace: stale KV
            eng.generate(prompt, 2)           # a step processes drops
            assert "t" not in eng.prefix_cache._roots
            assert None in eng.prefix_cache._roots  # base chain kept
            hits_before = eng.prefix_cache.stats()["hits"]
            # The t-keyed lookup after the drop must MISS (the stale
            # chain is gone) and the fresh chain re-registers.
            eng.generate(prompt, 6, adapter="t")
            assert eng.prefix_cache.stats()["hits"] == hits_before
            assert "t" in eng.prefix_cache._roots
        finally:
            eng.stop()
