"""Optimizer-state precision + sharded weight update (the HBM diet).

Covers ISSUE 10's acceptance surface:

* block-scaled int8 AdamW state (``ops/optim_quant.py`` +
  ``models/optim.py``): codec error bounds, transform structure, the
  >= 3.5x analytic byte cut, and fit-level loss parity vs the f32 arm
  at the ``int8_ef`` grad-comm tolerance;
* state round-trips: gathered (single-file) checkpoints, drain → resume
  bitwise, N→M elastic reshard through the ``RLTSHRD2`` selective
  reader (scales ride along), cross-``opt_state_dtype`` resume
  conversion, and the EF-residual interaction warning path;
* the checkpoint codec registry (``UnsupportedLeafDtypeError`` at the
  boundary, ``verify_sharded`` flagging);
* the cross-replica sharded weight update (``update_sharding``):
  resolution rules, loud downgrade, sharding layout, and fit parity
  against the replicated-update formulation on the CPU mesh.
"""

import os
import warnings
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.core.loop import (
    FitConfig,
    _normalize_update_sharding,
    _reconcile_opt_state_format,
    _resolve_update_sharding,
    init_train_state,
    run_fit,
)
from ray_lightning_tpu.core.module import TrainState
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models.gpt import (
    GPT,
    GPTConfig,
    SyntheticLMDataModule,
)
from ray_lightning_tpu.models.optim import (
    opt_state_bytes,
    quantize_opt_state,
    resolve_opt_state_dtype,
)
from ray_lightning_tpu.ops.optim_quant import (
    BlockQuantized,
    dequantize_moment,
    is_block_quantized,
    quantize_moment,
)
from ray_lightning_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_lightning_tpu.parallel.strategies import LocalStrategy
from ray_lightning_tpu.utils import sharded_ckpt as sc
from ray_lightning_tpu.utils.state_stream import (
    tree_from_bytes,
    tree_to_bytes,
)


def mesh_of(n):
    return build_mesh(MeshSpec({"data": n}), devices=jax.devices()[:n])


def tiny(**kw):
    return replace(GPTConfig.tiny(), **kw)


def _dm(cfg, num_batches=6):
    return SyntheticLMDataModule(cfg, batch_size=8, num_batches=num_batches)


# ---------------------------------------------------------------------------
# Codec units
# ---------------------------------------------------------------------------

def test_quantize_moment_roundtrip_error_bound():
    """Linear codec: per-element error bounded by the block's
    absmax/254 (half a quantization step), exactly like the gradient
    wire's bound."""
    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.normal(size=(300, 17)).astype(np.float32))
    bq = quantize_moment(v, block_size=128)
    assert bq.q.dtype == jnp.int8 and bq.scale.dtype == jnp.float32
    assert bq.q.size % 128 == 0 and bq.scale.size == bq.q.size // 128
    back = dequantize_moment(bq)
    assert back.shape == v.shape and back.dtype == jnp.float32
    flat = np.asarray(v).reshape(-1)
    pad = (-flat.size) % 128
    blocks = np.pad(flat, (0, pad)).reshape(-1, 128)
    bound = np.abs(blocks).max(axis=1) / 254.0 + 1e-7
    err = np.abs(np.pad(np.asarray(back - v).reshape(-1), (0, pad))
                 ).reshape(-1, 128)
    assert (err.max(axis=1) <= bound).all()


def test_quantize_moment_sqrt_domain():
    """The second-moment codec stores sqrt(nu): nonnegative round-trip
    with small relative error at the block scale, and tiny elements do
    NOT collapse to zero until ~8 orders below the block max (the
    failure mode a linear nu codec hits at ~4)."""
    rng = np.random.default_rng(1)
    nu = jnp.asarray((rng.normal(size=(4096,)) ** 2).astype(np.float32))
    bq = quantize_moment(nu, block_size=128, sqrt_domain=True)
    assert bq.sqrt_domain
    back = np.asarray(dequantize_moment(bq))
    assert (back >= 0).all()
    rel = np.abs(back - np.asarray(nu)).max() / np.asarray(nu).max()
    assert rel < 0.02
    # 4 orders below block max survives the sqrt codec.
    mixed = jnp.asarray(
        np.array([1.0] * 127 + [1e-4], np.float32))
    small = np.asarray(dequantize_moment(
        quantize_moment(mixed, 128, sqrt_domain=True)))[-1]
    assert small > 0


def test_zero_block_is_exact():
    z = jnp.zeros((256,), jnp.float32)
    assert np.asarray(
        dequantize_moment(quantize_moment(z, 128))
    ).sum() == 0.0


# ---------------------------------------------------------------------------
# Transform structure + accounting
# ---------------------------------------------------------------------------

def test_int8_transform_state_structure():
    """Big moment leaves quantize (both moments, nu in sqrt domain);
    small leaves (LN gains, biases) stay f32; counts/schedule state
    untouched."""
    m = GPT(tiny(opt_state_dtype="int8"))
    p = m.init_params(jax.random.PRNGKey(0))
    s = m.configure_optimizers().init(p)

    adam = [n for n in jax.tree_util.tree_leaves(
        s, is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState))
        if isinstance(x := n, optax.ScaleByAdamState)]
    assert len(adam) == 1
    mu_nodes = jax.tree_util.tree_leaves(
        adam[0].mu, is_leaf=is_block_quantized)
    q = [n for n in mu_nodes if is_block_quantized(n)]
    raw = [n for n in mu_nodes if not is_block_quantized(n)]
    assert q and raw
    assert all(not n.sqrt_domain for n in q)
    assert all(int(np.prod(n.shape)) >= 4096 for n in q)
    assert all(n.size < 4096 for n in raw)
    nu_q = [n for n in jax.tree_util.tree_leaves(
        adam[0].nu, is_leaf=is_block_quantized) if is_block_quantized(n)]
    assert nu_q and all(n.sqrt_domain for n in nu_q)


def test_bf16_transform_casts_both_moments():
    m = GPT(tiny(opt_state_dtype="bfloat16"))
    s = m.configure_optimizers().init(
        m.init_params(jax.random.PRNGKey(0)))
    adam = next(
        n for n in jax.tree_util.tree_leaves(
            s, is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState))
        if isinstance(n, optax.ScaleByAdamState))
    for tree in (adam.mu, adam.nu):
        assert all(
            leaf.dtype == jnp.bfloat16
            for leaf in jax.tree_util.tree_leaves(tree))


def test_resolve_and_eager_validation():
    assert resolve_opt_state_dtype(None) is None
    assert resolve_opt_state_dtype("f32") == "float32"
    assert resolve_opt_state_dtype("bf16") == "bfloat16"
    assert resolve_opt_state_dtype("int8") == "int8"
    with pytest.raises(ValueError, match="opt_state_dtype"):
        resolve_opt_state_dtype("fp8")
    with pytest.raises(ValueError, match="opt_state_dtype"):
        GPT(tiny(opt_state_dtype="int4"))
    from ray_lightning_tpu.models.vit import ViT, ViTConfig

    with pytest.raises(ValueError, match="opt_state_dtype"):
        ViT(replace(ViTConfig.tiny(), opt_state_dtype="nope"))


def test_float32_policy_is_passthrough():
    inner = optax.adam(1e-3)
    assert quantize_opt_state(inner, "float32") is inner


def test_opt_state_bytes_ratio_bar():
    """The analytic HBM accounting must clear the >= 3.5x acceptance
    bar on both the test config and the GPT-2-small headline shape."""
    for cfg in (GPTConfig.tiny(), GPTConfig.gpt2_small()):
        params = jax.eval_shape(
            GPT(cfg).init_params, jax.random.PRNGKey(0))
        f32 = opt_state_bytes(params, "float32")
        i8 = opt_state_bytes(params, "int8")
        assert f32 / i8 >= 3.5, (cfg, f32 / i8)
        assert opt_state_bytes(params, "bfloat16") * 2 == f32
        # Legacy default (bf16 mu, f32 nu) sits between.
        assert i8 < opt_state_bytes(params, None) < f32


# ---------------------------------------------------------------------------
# Fit-level parity (the acceptance gate — int8_ef tolerance: 1% rel)
# ---------------------------------------------------------------------------

def _fit_loss(cfg, **trainer_kw):
    t = Trainer(strategy=LocalStrategy(), max_epochs=2,
                enable_checkpointing=False, log_every_n_steps=1,
                **trainer_kw)
    t.fit(GPT(cfg), _dm(cfg, num_batches=6))
    return float(t.callback_metrics["train_loss"])


@pytest.mark.slow  # tier-1 diet (round 20): two full fits, ~20s on a
# loaded container; the quantize units + bytes-ratio bar are the
# tier-1 smoke, the fit-parity arms run via -m slow
def test_int8_fit_loss_parity_vs_f32():
    """The tentpole gate: the int8 opt-state fit matches the f32 arm's
    loss curve within the tolerance the int8_ef grad-comm gate uses
    (1% relative on the final train loss)."""
    ref = _fit_loss(tiny(opt_state_dtype="float32"))
    got = _fit_loss(tiny(opt_state_dtype="int8"))
    assert abs(got - ref) <= 0.01 * abs(ref)


@pytest.mark.slow  # tier-1 budget: fit-parity arms are slow-tier
def test_bf16_fit_loss_parity_vs_f32():
    ref = _fit_loss(tiny(opt_state_dtype="float32"))
    got = _fit_loss(tiny(opt_state_dtype="bfloat16"))
    assert abs(got - ref) <= 0.01 * abs(ref)


# ---------------------------------------------------------------------------
# Round-trips: gathered stream, drain/resume, N→M selective reshard
# ---------------------------------------------------------------------------

def test_quantized_state_stream_roundtrip_bitwise():
    """The gathered single-file format must carry BlockQuantized nodes
    bit-exactly: int8 payloads, f32 scales, aux (shape/block/sqrt) all
    preserved through tree_to_bytes/tree_from_bytes."""
    m = GPT(tiny(opt_state_dtype="int8"))
    p = m.init_params(jax.random.PRNGKey(0))
    s = TrainState.create(p, m.configure_optimizers())
    back = tree_from_bytes(tree_to_bytes(s))
    assert (jax.tree_util.tree_structure(back)
            == jax.tree_util.tree_structure(s))
    for a, b in zip(jax.tree_util.tree_leaves(s),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # tier-1 budget (round-16 audit: >10s): the stream
# round-trip above pins int8 byte-exactness and the N→M reshard fits
# pin resume; this full restart fit runs outside the sweep
def test_int8_restart_resume_bitwise(tmp_path):
    """Same-policy resume through a restart checkpoint is bit-exact:
    the int8 payload round-trips as raw bytes, so the resumed fit's
    losses equal the uninterrupted fit's."""
    cfg = tiny(opt_state_dtype="int8")
    # The 2-epoch reference fit IS the checkpoint writer: resume from
    # its epoch-0 restart checkpoint and the losses must re-converge
    # bitwise.
    base = run_fit(GPT(cfg), _dm(cfg),
                   FitConfig(max_epochs=2, seed=0,
                             default_root_dir=str(tmp_path),
                             restart_dir=str(tmp_path / "rs")),
                   callbacks=[])
    cands = [n for n in os.listdir(tmp_path / "rs")
             if n.startswith("restart-epoch-")]
    assert cands
    res = run_fit(GPT(cfg), _dm(cfg),
                  FitConfig(max_epochs=2, seed=0,
                            default_root_dir=str(tmp_path),
                            resume_from_checkpoint=str(
                                tmp_path / "rs" / sorted(cands)[0])),
                  callbacks=[])
    assert (res["callback_metrics"]["train_loss"]
            == base["callback_metrics"]["train_loss"])


@pytest.mark.slow  # mesh fits; the single-device bitwise pin runs fast
def test_int8_drain_resume_n_to_m_parity(tmp_path):
    """Drain a 4-way ZeRO-1 fit with int8 moments, resume on 2 devices:
    the RLTSHRD2 selective reader reshards the int8 payload AND scale
    leaves onto the new mesh, and losses stay bitwise-equal to an
    uninterrupted fit."""
    from ray_lightning_tpu.fault import drain as drain_mod
    from ray_lightning_tpu.fault.drain import PreemptedError

    cfg = tiny(opt_state_dtype="int8")
    base = run_fit(GPT(cfg), _dm(cfg),
                   FitConfig(max_epochs=2, seed=0,
                             default_root_dir=str(tmp_path)),
                   callbacks=[], mesh=mesh_of(4), zero_stage=1)

    class DrainAt(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            if trainer.micro_step >= 4:
                drain_mod.request_drain("test")

    with pytest.raises(PreemptedError) as err:
        run_fit(GPT(cfg), _dm(cfg),
                FitConfig(max_epochs=2, seed=0,
                          default_root_dir=str(tmp_path),
                          restart_dir=str(tmp_path / "rs")),
                callbacks=[DrainAt()], mesh=mesh_of(4), zero_stage=1)
    ckpt = err.value.checkpoint
    res = run_fit(GPT(cfg), _dm(cfg),
                  FitConfig(max_epochs=2, seed=0,
                            default_root_dir=str(tmp_path),
                            resume_from_checkpoint=ckpt),
                  callbacks=[], mesh=mesh_of(2), zero_stage=1)
    assert sc.LOAD_STATS["selective"], (
        "the index-selective reshard reader must handle int8+scale "
        "leaves, not fall back to the full host read")
    assert (res["callback_metrics"]["train_loss"]
            == base["callback_metrics"]["train_loss"])


@pytest.mark.slow  # tier-1 budget: the reconcile UNIT test runs fast
def test_cross_policy_resume_converts_with_warning(tmp_path):
    """f32-era checkpoint into an int8 run (and back): the storage-
    format reconcile converts the moments with a loud warning instead
    of crashing on the treedef mismatch."""
    cfg_f32 = tiny(opt_state_dtype="float32")
    run_fit(GPT(cfg_f32), _dm(cfg_f32),
            FitConfig(max_epochs=1, seed=0,
                      default_root_dir=str(tmp_path),
                      restart_dir=str(tmp_path / "rs")),
            callbacks=[])
    ckpt = str(tmp_path / "rs" / sorted(
        n for n in os.listdir(tmp_path / "rs")
        if n.startswith("restart-epoch-"))[-1])
    cfg_i8 = tiny(opt_state_dtype="int8")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res = run_fit(GPT(cfg_i8), _dm(cfg_i8),
                      FitConfig(max_epochs=2, seed=0,
                                default_root_dir=str(tmp_path),
                                resume_from_checkpoint=ckpt),
                      callbacks=[])
    assert any("opt_state_dtype change" in str(x.message) for x in w)
    assert np.isfinite(res["callback_metrics"]["train_loss"])


def test_reconcile_opt_state_format_units():
    """Direct units over the converter: quantized→float dequantizes,
    float→quantized requantizes, same-format passes through untouched
    (object identity for the int8 payload — bit-exact resumes)."""
    m8 = GPT(tiny(opt_state_dtype="int8"))
    mf = GPT(tiny(opt_state_dtype="float32"))
    p = m8.init_params(jax.random.PRNGKey(0))
    s8 = TrainState.create(p, m8.configure_optimizers())
    sf = TrainState.create(p, mf.configure_optimizers())

    same = _reconcile_opt_state_format(s8, s8)
    assert (jax.tree_util.tree_structure(same.opt_state)
            == jax.tree_util.tree_structure(s8.opt_state))
    to_f = _reconcile_opt_state_format(s8, sf)
    assert (jax.tree_util.tree_structure(to_f.opt_state)
            == jax.tree_util.tree_structure(sf.opt_state))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        to_q = _reconcile_opt_state_format(sf, s8)
    assert any("opt_state_dtype change" in str(x.message) for x in w)
    assert (jax.tree_util.tree_structure(to_q.opt_state)
            == jax.tree_util.tree_structure(s8.opt_state))


def test_ef_residual_interaction_warning_path():
    """int8 opt state + int8_ef error feedback: the per-device residual
    reconcile still fires its world-change warning and leaves the
    quantized opt state untouched."""
    from ray_lightning_tpu.models.boring import BoringModel
    from ray_lightning_tpu.parallel import grad_sync as gsync

    mesh = mesh_of(8)
    module = BoringModel(in_dim=64, out_dim=32)
    gs = gsync.maybe_build_grad_sync(
        module, mesh, {"mode": "int8_ef", "dcn_only": False},
        mode="gspmd", zero_stage=0)
    assert gs is not None and gs.use_ef
    m8 = GPT(tiny(opt_state_dtype="int8"))
    p8 = m8.init_params(jax.random.PRNGKey(0))
    s8 = TrainState.create(p8, m8.configure_optimizers())
    stale = TrainState(
        s8.params, s8.opt_state, s8.step,
        grad_residual=np.zeros((3, 128), np.float32),  # wrong world
    )
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = gs.reconcile_resumed_state(stale)
    assert any("residual" in str(x.message) for x in w)
    assert (jax.tree_util.tree_structure(out.opt_state)
            == jax.tree_util.tree_structure(s8.opt_state))


# ---------------------------------------------------------------------------
# Checkpoint codec registry
# ---------------------------------------------------------------------------

def test_unregistered_dtype_rejected_typed(tmp_path):
    """A leaf dtype with no registered codec fails TYPED at the
    checkpoint boundary — on write, on load, and in verify_sharded's
    pre-flight (so restart discovery walks back instead of crashing)."""
    with pytest.raises(sc.UnsupportedLeafDtypeError, match="registered"):
        sc.save_shard({"x": np.zeros((4,), np.complex64)},
                      str(tmp_path / "c.ckpt"), 0, 1)

    # Hand-build a valid checkpoint, then rewrite its header to claim a
    # future dtype: load must raise the typed error, verify must FLAG.
    d = str(tmp_path / "v.ckpt")
    sc.save_shard({"x": np.arange(8, dtype=np.float32)}, d, 0, 1)
    sc.save_meta({"x": np.arange(8, dtype=np.float32)}, d, 1)
    assert sc.verify_sharded(d) == []
    shard = os.path.join(d, "shard-00000-of-00001.ckpt")
    with open(shard, "rb") as f:
        blob = f.read()
    blob = blob.replace(b"float32", b"float8e", 1)
    with open(shard, "wb") as f:
        f.write(blob)
    # Refresh META so the whole-file checksum matches the edited bytes
    # (we are testing the codec gate, not the crc gate).
    with open(shard + ".crc32", "w") as f:
        import zlib

        f.write(str(zlib.crc32(blob)))
    sc.save_meta({"x": np.arange(8, dtype=np.float32)}, d, 1)
    problems = sc.verify_sharded(d)
    assert problems and "no registered codec" in problems[0]
    with pytest.raises(sc.UnsupportedLeafDtypeError, match="float8e"):
        sc.load_sharded(d)


def test_registered_codecs_cover_state_dtypes():
    for name in ("float32", "bfloat16", "int8", "int32", "bool"):
        assert name in sc.LEAF_DTYPE_CODECS
        sc.LEAF_DTYPE_CODECS[name]()  # constructible


# ---------------------------------------------------------------------------
# Cross-replica sharded weight update
# ---------------------------------------------------------------------------

def test_normalize_update_sharding():
    assert _normalize_update_sharding(None) is None
    assert _normalize_update_sharding("auto") == "auto"
    assert _normalize_update_sharding(True) == "on"
    assert _normalize_update_sharding(False) == "off"
    assert _normalize_update_sharding("") == "off"
    with pytest.raises(ValueError, match="update_sharding"):
        _normalize_update_sharding("maybe")
    with pytest.raises(ValueError, match="update_sharding"):
        LocalStrategy(update_sharding="maybe")
    with pytest.raises(ValueError, match="update_sharding"):
        FitConfig(update_sharding=3)


def test_resolve_update_sharding_rules(monkeypatch):
    mesh = mesh_of(4)
    cfg_on = FitConfig(update_sharding="on")
    cfg_auto = FitConfig(update_sharding="auto")
    cfg_none = FitConfig()
    # Explicit on, eligible mesh.
    assert _resolve_update_sharding(cfg_on, mesh, "gspmd", 0) is True
    # auto stays off on the CPU backend (megastep precedent).
    assert _resolve_update_sharding(cfg_auto, mesh, "gspmd", 0) is False
    # Env bus fills an unset knob.
    monkeypatch.setenv("RLT_UPDATE_SHARDING", "on")
    assert _resolve_update_sharding(cfg_none, mesh, "gspmd", 0) is True
    monkeypatch.setenv("RLT_UPDATE_SHARDING", "off")
    assert _resolve_update_sharding(cfg_none, mesh, "gspmd", 0) is False
    monkeypatch.delenv("RLT_UPDATE_SHARDING")
    # Loud downgrade wherever the technique doesn't apply.
    for mesh_, mode_, zs in (
        (mesh, "gspmd", 1),      # ZeRO already shards
        (mesh, "shard_map", 0),  # replicated-state contract
        (None, "gspmd", 0),      # no mesh
    ):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert _resolve_update_sharding(
                cfg_on, mesh_, mode_, zs) is False
        assert any("update_sharding" in str(x.message) for x in w)


def test_update_sharding_env_forwarded(monkeypatch):
    monkeypatch.setenv("RLT_UPDATE_SHARDING", "on")
    s = LocalStrategy()
    assert s.env_per_worker.get("RLT_UPDATE_SHARDING") == "on"


def test_shard_update_layout():
    """shard_update=True shards the big optimizer moments over the data
    axis while params stay replicated — the ZeRO-1-shaped layout the
    paper's update sharding reduces to, without changing the run's
    semantic zero_stage."""
    mesh = mesh_of(8)
    m = GPT(tiny())
    tx = m.configure_optimizers()
    _, sh = init_train_state(m, tx, mesh, 0, seed=0, shard_update=True)
    def replicated(spec):
        return all(e is None for e in tuple(spec))

    assert all(
        replicated(s.spec) for s in jax.tree_util.tree_leaves(sh.params)
    ), "params must stay replicated"
    opt_specs = [tuple(s.spec) for s in
                 jax.tree_util.tree_leaves(sh.opt_state)]
    assert any(
        any(e is not None for e in spec) for spec in opt_specs
    ), "big moments must shard over the data axis"
    # Control: without shard_update everything is replicated.
    _, sh0 = init_train_state(m, tx, mesh, 0, seed=0, shard_update=False)
    assert all(
        replicated(s.spec)
        for s in jax.tree_util.tree_leaves(sh0.opt_state))


@pytest.mark.slow  # tier-1 budget (round-16 audit: >10s):
# test_shard_update_layout pins the sharding layout fast; the full
# 8-device bitwise fit parity runs outside the sweep
def test_update_sharding_fit_parity_cpu_mesh(tmp_path):
    """The arm's acceptance pin: a fit with the sharded update matches
    the replicated-update formulation bitwise on the 8-device CPU mesh
    (GSPMD resharding moves bytes, not math), and the dispatch count
    per optimizer step is unchanged."""
    def fit(us):
        t = Trainer(
            strategy=LocalStrategy(mesh_axes={"data": 8},
                                   update_sharding=us),
            max_epochs=1, enable_checkpointing=False,
            log_every_n_steps=1, default_root_dir=str(tmp_path),
        )
        t.fit(GPT(tiny()), _dm(tiny()))
        counters = t.telemetry_report.get("counters", {})
        dispatches = (counters.get("train_dispatches") or {}).get("mean")
        return float(t.callback_metrics["train_loss"]), dispatches

    loss_off, disp_off = fit("off")
    loss_on, disp_on = fit("on")
    assert loss_on == loss_off
    assert disp_on == disp_off


@pytest.mark.slow  # second mesh-fit matrix; the bitwise pin above runs fast
def test_update_sharding_composes_with_int8_state_and_ef(tmp_path):
    """The full diet stack — int8 moments + sharded update + int8_ef
    grad compression — trains at parity with its own replicated-update
    arm."""
    cfg = tiny(opt_state_dtype="int8")

    def fit(us):
        t = Trainer(
            strategy=LocalStrategy(
                mesh_axes={"data": 8}, update_sharding=us,
                grad_comm={"mode": "int8_ef", "dcn_only": False},
            ),
            max_epochs=1, enable_checkpointing=False,
            log_every_n_steps=1, default_root_dir=str(tmp_path),
        )
        t.fit(GPT(cfg), _dm(cfg))
        assert t.comm_stats["grad_sync_mode"] == "int8_ef"
        return float(t.callback_metrics["train_loss"])

    assert fit("on") == fit("off")
