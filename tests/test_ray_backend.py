"""Real-Ray control-plane tests — run only where Ray is installed.

≙ the reference's Ray-version CI axis and Ray Client suites
(``/root/reference/.github/workflows/test.yaml:24-160``,
``tests/test_client.py:10-31``, ``tests/test_tune.py:42-78``).  The dev
image for this repo has no Ray (and no installs), so these tests are
``importorskip``-gated; the ``ray-backend`` CI job installs ``ray[tune]``
and runs exactly this file, giving the ``RayBackend`` /
``RAY_TUNE_INSTALLED`` branches their coverage.
"""

import os

import numpy as np
import pytest

ray = pytest.importorskip("ray")


@pytest.fixture(scope="module", autouse=True)
def _ray_cluster():
    ray.init(num_cpus=4, include_dashboard=False, ignore_reinit_error=True)
    yield
    ray.shutdown()


def _mark(x):
    return x * 2


def test_ray_backend_actor_lifecycle():
    """create_actor → env plumbing → execute/submit → kill (≙ RayExecutor
    lifecycle, reference ray_ddp.py:183-189,339-353)."""
    from ray_lightning_tpu.cluster.backend import RayBackend, get_backend

    os.environ["RLT_BACKEND"] = "ray"
    try:
        be = get_backend()
    finally:
        del os.environ["RLT_BACKEND"]
    assert isinstance(be, RayBackend)

    actor = be.create_actor("w0", env={"RLT_TEST_MARKER": "42"})
    assert actor.execute(_mark, 21) == 42
    # runtime_env must land BEFORE worker start (import-time semantics).
    assert actor.execute(os.environ.get, "RLT_TEST_MARKER") == "42"
    fut = actor.submit(_mark, 5)
    assert fut.result(timeout=30) == 10
    assert fut.exception() is None
    ip = actor.get_node_ip()
    assert isinstance(ip, str) and ip
    ref = be.put({"a": np.arange(3)})
    np.testing.assert_array_equal(ref.get()["a"], np.arange(3))
    be.shutdown()


def test_ray_backend_two_worker_fit():
    """End-to-end 2-worker DDP fit with Ray as the control plane
    (RLT_BACKEND=ray) — the data plane stays jax.distributed + XLA."""
    from ray_lightning_tpu import Trainer, RayStrategy
    from ray_lightning_tpu.models.boring import BoringModel, BoringDataModule

    os.environ["RLT_BACKEND"] = "ray"
    try:
        trainer = Trainer(
            strategy=RayStrategy(num_workers=2),
            max_epochs=1,
            enable_checkpointing=False,
        )
        trainer.fit(BoringModel(), BoringDataModule())
        assert np.isfinite(trainer.callback_metrics["train_loss"])
    finally:
        del os.environ["RLT_BACKEND"]


def test_tune_resources_placement_group():
    """RAY_TUNE_INSTALLED branch: get_tune_resources returns a real
    PlacementGroupFactory (≙ reference tune.py:102-128)."""
    from ray.tune import PlacementGroupFactory

    from ray_lightning_tpu.tune import get_tune_resources

    pgf = get_tune_resources(num_workers=2, num_cpus_per_worker=1, use_tpu=False)
    assert isinstance(pgf, PlacementGroupFactory)
    bundles = pgf.bundles
    assert len(bundles) >= 2


def test_tune_report_callback_under_ray_tune():
    """TuneReportCallback streams per-epoch metrics into a real ray.tune
    session (≙ reference tests/test_tune.py:42-60)."""
    from ray import tune as ray_tune

    from ray_lightning_tpu import Trainer, LocalStrategy
    from ray_lightning_tpu.models.boring import BoringModel, BoringDataModule
    from ray_lightning_tpu.tune import TuneReportCallback

    def train_fn(config):
        trainer = Trainer(
            strategy=LocalStrategy(),
            max_epochs=2,
            enable_checkpointing=False,
            callbacks=[TuneReportCallback(["train_loss"], on="train_epoch_end")],
        )
        trainer.fit(BoringModel(), BoringDataModule())

    tuner = ray_tune.Tuner(
        train_fn,
        tune_config=ray_tune.TuneConfig(num_samples=1),
    )
    results = tuner.fit()
    assert not results.errors
    df = results.get_dataframe()
    assert "train_loss" in df.columns
    assert np.isfinite(df["train_loss"].iloc[0])
