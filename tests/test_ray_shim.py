"""Structural coverage for the real-Ray branches WITHOUT Ray installed.

This environment cannot install ``ray[tune]`` (VERDICT r4 next-round #3
asks for a green real-Ray run; the dev image forbids installs), so the
next-best evidence is executing the adapter code paths against a
structural fake of the Ray API surface the code actually touches:
``ray.remote``/``.options().remote()``, ``ray.get``/``wait``/``kill``,
and ``ray.tune.report(metrics, checkpoint=...)``.  These tests catch
wiring regressions (wrong kwarg names, broken adapter plumbing, dead
``RAY_TUNE_INSTALLED`` branches); true Ray-version compatibility still needs
the ``ray-backend`` CI job (``tests/test_ray_backend.py``) on an image
with Ray.
"""

import sys
import types

import pytest


# ---------------------------------------------------------------------------
# The fake: just enough of Ray's surface, executing synchronously in-process.
# ---------------------------------------------------------------------------

class _FakeObjectRef:
    def __init__(self, value=None, exc=None):
        self.value = value
        self.exc = exc


class _FakeMethod:
    def __init__(self, bound):
        self._bound = bound

    def remote(self, *args, **kwargs):
        try:
            return _FakeObjectRef(value=self._bound(*args, **kwargs))
        except Exception as e:  # noqa: BLE001 - delivered via ray.get
            return _FakeObjectRef(exc=e)


class _FakeHandle:
    def __init__(self, instance, opts):
        self._instance = instance
        self._opts = opts
        self.killed = False

    def __getattr__(self, name):
        return _FakeMethod(getattr(self._instance, name))


class _FakeActorFactory:
    def __init__(self, cls, opts):
        self._cls = cls
        self._opts = opts

    def remote(self, *args, **kwargs):
        return _FakeHandle(self._cls(*args, **kwargs), self._opts)


def make_fake_ray(created):
    ray = types.ModuleType("ray")
    ray.__spec__ = types.SimpleNamespace(name="ray")

    def remote(cls):
        class _Remote:
            @staticmethod
            def options(**opts):
                created.append(opts)
                return _FakeActorFactory(cls, opts)

            @staticmethod
            def remote(*args, **kwargs):
                return _FakeActorFactory(cls, {}).remote(*args, **kwargs)

        return _Remote

    def get(ref, timeout=None):
        if isinstance(ref, list):
            return [get(r) for r in ref]
        if ref.exc is not None:
            raise ref.exc
        return ref.value

    ray.remote = remote
    ray.get = get
    ray.wait = lambda refs, timeout=0: (refs, [])
    ray.kill = lambda handle, no_restart=True: setattr(
        handle, "killed", True
    )
    ray.is_initialized = lambda: True
    ray.init = lambda *a, **k: None
    return ray


@pytest.fixture
def fake_ray(monkeypatch):
    created = []
    ray = make_fake_ray(created)
    monkeypatch.setitem(sys.modules, "ray", ray)
    return ray, created


# ---------------------------------------------------------------------------
# RayBackend adapter plumbing
# ---------------------------------------------------------------------------

def test_ray_backend_adapter_lifecycle(fake_ray, monkeypatch):
    """get_backend('ray') → create_actor(options plumbed) → execute /
    submit / future protocol → kill/shutdown (≙ tests/test_ray_backend.py
    lifecycle, runnable without Ray)."""
    _, created = fake_ray
    from ray_lightning_tpu.cluster.backend import RayBackend, get_backend

    monkeypatch.setenv("RLT_BACKEND", "ray")
    be = get_backend()
    assert isinstance(be, RayBackend)

    actor = be.create_actor(
        "w0", env={"RLT_TEST_MARKER": "42"}, num_cpus=2,
        resources={"TPU": 4},
    )
    # The options the scheduler would see: resource reservation + the
    # import-time env contract via runtime_env (reference
    # ray_ddp.py:183-189 analogue).
    opts = created[-1]
    assert opts["num_cpus"] == 2
    assert opts["resources"] == {"TPU": 4}
    assert opts["name"] == "w0"
    assert opts["runtime_env"] == {"env_vars": {"RLT_TEST_MARKER": "42"}}

    assert actor.execute(lambda x: x * 2, 21) == 42
    fut = actor.submit(lambda x: x + 1, 5)
    assert fut.result(timeout=1) == 6
    assert fut.done()
    assert fut.exception() is None

    boom = actor.submit(_raise_marker)
    assert isinstance(fut.exception(), type(None))
    assert "marker-boom" in str(boom.exception())
    with pytest.raises(RuntimeError, match="marker-boom"):
        boom.result()

    handle = actor._handle
    be.shutdown()
    assert handle.killed
    assert be._actors == []


def _raise_marker():
    raise RuntimeError("marker-boom")


def test_get_backend_ray_requires_ray():
    """Without Ray (and without the shim), RLT_BACKEND=ray must fail loud,
    never fall back silently."""
    from ray_lightning_tpu.cluster.backend import get_backend

    assert "ray" not in sys.modules or not hasattr(
        sys.modules.get("ray"), "remote"
    )
    with pytest.raises(ImportError, match="falling back is disabled"):
        get_backend("ray")


# ---------------------------------------------------------------------------
# RAY_TUNE_INSTALLED branches in tune.py
# ---------------------------------------------------------------------------

def test_driver_report_uses_ray_tune_when_installed(monkeypatch):
    import ray_lightning_tpu.tune as rlt_tune

    calls = []
    fake_tune = types.SimpleNamespace(
        report=lambda metrics, checkpoint=None: calls.append(
            (metrics, checkpoint)
        )
    )
    monkeypatch.setattr(rlt_tune, "RAY_TUNE_INSTALLED", True)
    monkeypatch.setattr(rlt_tune, "_ray_tune", fake_tune)
    rlt_tune._driver_report({"loss": 0.5})
    assert calls == [({"loss": 0.5}, None)]


def test_driver_write_checkpoint_ray_tune_single_transaction(monkeypatch,
                                                            tmp_path):
    """Under real Ray Tune, metrics + checkpoint MUST travel in ONE
    report call (Ray Tune 2.x semantics documented at tune.py:55-65)."""
    import ray_lightning_tpu.tune as rlt_tune

    calls = []

    class _FakeCheckpoint:
        def __init__(self, dirpath):
            self.dir = dirpath

        @classmethod
        def from_directory(cls, dirpath):
            import os

            # Capture the payload NOW: the tempdir dies after report.
            ckpt = cls(dirpath)
            ckpt.files = {
                f: open(os.path.join(dirpath, f), "rb").read()
                for f in os.listdir(dirpath)
            }
            return ckpt

    fake_tune = types.SimpleNamespace(
        report=lambda metrics, checkpoint=None: calls.append(
            (metrics, checkpoint)
        ),
        Checkpoint=_FakeCheckpoint,
    )
    monkeypatch.setattr(rlt_tune, "RAY_TUNE_INSTALLED", True)
    monkeypatch.setattr(rlt_tune, "_ray_tune", fake_tune)

    rlt_tune._driver_write_checkpoint(
        b"\x00payload", step=3, filename="ckpt", metrics={"loss": 1.0}
    )
    assert len(calls) == 1  # ONE transaction, not separate report+ckpt
    metrics, ckpt = calls[0]
    assert metrics == {"loss": 1.0}
    assert ckpt.files == {"ckpt": b"\x00payload"}


def test_fit_through_fake_ray_backend(fake_ray, tmp_path):
    """A full RayStrategy fit with the fake-Ray control plane: exercises
    RayBackend.create_actor/put/create_queue/shutdown wired through the
    real strategy, with worker tasks executing synchronously in-process."""
    import os

    import numpy as np

    from ray_lightning_tpu.cluster.backend import RayBackend
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models import BoringDataModule, BoringModel
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    env_before = dict(os.environ)
    try:
        trainer = Trainer(
            strategy=RayStrategy(num_workers=1, backend=RayBackend()),
            max_epochs=1,
            default_root_dir=str(tmp_path),
            enable_checkpointing=False,
        )
        trainer.fit(BoringModel(), BoringDataModule())
        assert trainer.state is not None
        leaves = [np.asarray(x) for x in
                  __import__("jax").tree_util.tree_leaves(trainer.params)]
        assert all(np.all(np.isfinite(l)) for l in leaves)
    finally:
        os.environ.clear()
        os.environ.update(env_before)
