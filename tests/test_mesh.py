"""Mesh/rank-mapping unit tests (no cluster — ≙ reference fake-IP actor
trick, ``test_ddp.py:80-114``)."""

import pytest

from ray_lightning_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    compute_host_ranks,
)


class TestComputeHostRanks:
    def test_two_nodes_two_workers_each(self):
        # ≙ reference Node1Actor/Node2Actor hardcoded-IP scenario.
        ips = ["10.0.0.1", "10.0.0.1", "10.0.0.2", "10.0.0.2"]
        ranks = compute_host_ranks(ips)
        assert ranks == {0: (0, 0), 1: (0, 1), 2: (1, 0), 3: (1, 1)}

    def test_interleaved_nodes(self):
        ips = ["a", "b", "a", "b"]
        ranks = compute_host_ranks(ips)
        assert ranks == {0: (0, 0), 1: (1, 0), 2: (0, 1), 3: (1, 1)}

    def test_single_node(self):
        assert compute_host_ranks(["x"]) == {0: (0, 0)}

    def test_empty(self):
        assert compute_host_ranks([]) == {}


class TestMeshSpec:
    def test_default_is_1d_data(self):
        spec = MeshSpec()
        assert spec.axis_names == ("data",)
        assert spec.resolve(8) == {"data": 8}

    def test_infer_axis(self):
        spec = MeshSpec({"data": -1, "model": 2})
        assert spec.resolve(8) == {"data": 4, "model": 2}

    def test_exact_match_required(self):
        with pytest.raises(ValueError, match="wants"):
            MeshSpec({"data": 3}).resolve(8)

    def test_indivisible(self):
        with pytest.raises(ValueError, match="not divisible"):
            MeshSpec({"data": -1, "model": 3}).resolve(8)

    def test_two_inferred_axes_rejected(self):
        with pytest.raises(ValueError, match="Only one"):
            MeshSpec({"a": -1, "b": -1})


def test_build_mesh_cpu(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec({"data": 2, "model": 4}))
    assert mesh.shape == {"data": 2, "model": 4}
    assert mesh.axis_names == ("data", "model")


# -- per-host chip partitioning (VERDICT r3 item #6) -------------------------

def test_partition_host_chips_colocated():
    """2 workers sharing each of 2 hosts: disjoint half-splits by local
    rank; submission order decides who gets the low chips."""
    from ray_lightning_tpu.parallel.mesh import partition_host_chips

    ips = ["10.0.0.1", "10.0.0.2", "10.0.0.1", "10.0.0.2"]
    got = partition_host_chips(ips, chips_per_host=4)
    assert got == {0: "0,1", 1: "0,1", 2: "2,3", 3: "2,3"}


def test_partition_host_chips_sole_owner_unconstrained():
    from ray_lightning_tpu.parallel.mesh import partition_host_chips

    got = partition_host_chips(["a", "b", "c"], chips_per_host=4)
    assert got == {0: None, 1: None, 2: None}


def test_partition_host_chips_refuses_uneven_split():
    import pytest

    from ray_lightning_tpu.parallel.mesh import partition_host_chips

    with pytest.raises(ValueError, match="do not divide"):
        partition_host_chips(["a", "a", "a"], chips_per_host=4)


def test_strategy_pushes_chip_partition(monkeypatch):
    """The strategy consumes the chip map: co-located stub workers receive
    disjoint TPU_VISIBLE_CHIPS, sole owners receive nothing."""
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    class StubWorker:
        def __init__(self, ip):
            self.ip = ip
            self.env = {}

        def get_node_ip(self):
            return self.ip

        def set_env_vars(self, env):
            self.env.update(env)

    s = RayStrategy(num_workers=3, use_tpu=True)
    s._workers = [StubWorker("h1"), StubWorker("h1"), StubWorker("h2")]
    s._partition_host_chips()
    assert s._workers[0].env["TPU_VISIBLE_CHIPS"] == "0,1"
    assert s._workers[1].env["TPU_VISIBLE_CHIPS"] == "2,3"
    assert "TPU_VISIBLE_CHIPS" not in s._workers[2].env
