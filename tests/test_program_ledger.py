"""Program ledger tests (ISSUE 17): executable registration with cost
and memory accounting, recompile forensics with argument-level
attribution, dispatch behavior (MRU fast path, variant reuse, tracer
fallback), the MFU drift guard, the kill switch, and the derived
HBM/roofline reports.

All tests use a private :class:`ProgramLedger` registry so they neither
see nor pollute the process-global ledger other suites dispatch into.
"""

import logging

import pytest

import jax
import jax.numpy as jnp

from ray_lightning_tpu.telemetry.program_ledger import (
    LedgeredFunction,
    ProgramLedger,
    hbm_report,
    ledger,
    ledgered_jit,
    roofline,
)
from ray_lightning_tpu.telemetry.schema import (
    validate_program_snapshot,
    validate_recompile_record,
)
from ray_lightning_tpu.telemetry.step_stats import (
    StepStats,
    compile_event_count,
)


def _double(x):
    return x * 2.0 + 1.0


def _tree_sum(state):
    return sum(jnp.sum(v) for v in state.values())


# ---------------------------------------------------------------------------
# Registration: identity, cost, memory
# ---------------------------------------------------------------------------

class TestRegistration:
    def test_first_dispatch_registers_program(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/double", registry=reg)
        out = fn(jnp.ones((8,), jnp.float32))
        assert float(out[0]) == 3.0
        snap = reg.snapshot()
        assert len(snap["programs"]) == 1
        row = snap["programs"][0]
        assert row["site"] == "test/double"
        assert row["variant"] == 0
        assert row["ncalls"] == 1
        assert row["compile_s"] > 0.0
        assert "f32[8]" in row["signature"]
        assert snap["recompiles"] == []

    def test_cost_and_memory_rows(self):
        # The acceptance bar: every registered program carries
        # cost_analysis FLOPs and memory_analysis byte accounting
        # (present on the CPU backend this suite runs on).
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/cost", registry=reg)
        fn(jnp.ones((16, 4), jnp.float32))
        row = reg.snapshot()["programs"][0]
        assert row["flops"] > 0
        assert row["argument_bytes"] > 0
        assert row["output_bytes"] > 0
        assert "temp_bytes" in row

    def test_snapshot_is_schema_valid(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/schema", registry=reg)
        fn(jnp.ones((4,), jnp.float32))
        fn(jnp.ones((8,), jnp.float32))  # one recompile on the ring
        assert validate_program_snapshot(reg.snapshot()) == []

    def test_compile_time_total_and_event_count(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/compile", registry=reg)
        before = compile_event_count()
        fn(jnp.ones((32,), jnp.float32))
        assert compile_event_count() >= before + 1
        assert reg.compile_time_total_s() > 0.0
        assert reg.snapshot()["compile_time_total_s"] > 0.0

    def test_donation_recorded(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/donate", registry=reg,
                              donate_argnums=0)
        fn(jnp.ones((8,), jnp.float32))
        row = reg.snapshot()["programs"][0]
        assert row["donated"] == "(0,)"


# ---------------------------------------------------------------------------
# Dispatch: MRU fast path, variant reuse, tracer fallback
# ---------------------------------------------------------------------------

class TestDispatch:
    def test_repeat_calls_one_variant(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/mru", registry=reg)
        x = jnp.ones((8,), jnp.float32)
        for _ in range(5):
            fn(x)
        assert fn.variants == 1
        snap = reg.snapshot()
        assert len(snap["programs"]) == 1
        assert snap["programs"][0]["ncalls"] == 5
        assert snap["recompiles"] == []

    def test_alternating_shapes_compile_once_each(self):
        # Bucketed dispatch: two shapes alternate.  Each compiles once;
        # flipping between existing variants is a cache hit, not a
        # recompile — exactly one forensics event total.
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/buckets", registry=reg)
        a = jnp.ones((8,), jnp.float32)
        b = jnp.ones((16,), jnp.float32)
        for _ in range(3):
            fn(a)
            fn(b)
        assert fn.variants == 2
        assert len(reg.snapshot()["recompiles"]) == 1

    def test_tracer_fallback_inlines(self):
        # Invoked under an enclosing trace, the wrapper must fall back
        # to the plain jit (a Compiled cannot take tracers) and must
        # NOT mint a ledger entry for the inlined call.
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/traced", registry=reg)

        @jax.jit
        def outer(x):
            return fn(x) + 1.0

        out = outer(jnp.ones((4,), jnp.float32))
        assert float(out[0]) == 4.0
        assert fn.variants == 0
        assert reg.snapshot()["programs"] == []

    def test_static_argnums_variant_per_value(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(lambda n, x: x * n, "test/static",
                              registry=reg, static_argnums=0,
                              arg_names=("n", "x"))
        x = jnp.ones((4,), jnp.float32)
        assert float(fn(2, x)[0]) == 2.0
        assert float(fn(3, x)[0]) == 3.0
        assert float(fn(2, x)[0]) == 2.0   # reuses the first variant
        assert fn.variants == 2
        recs = reg.snapshot()["recompiles"]
        assert len(recs) == 1
        assert recs[0]["kind"] == "static"


# ---------------------------------------------------------------------------
# Recompile forensics: attribution names the offending argument
# ---------------------------------------------------------------------------

class TestRecompileForensics:
    def _events(self, reg):
        recs = reg.snapshot()["recompiles"]
        for rec in recs:
            assert validate_recompile_record(rec) == []
        return recs

    def test_shape_change_attribution(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/shape", registry=reg)
        fn(jnp.ones((8,), jnp.float32))
        fn(jnp.ones((16,), jnp.float32))
        (rec,) = self._events(reg)
        assert rec["kind"] == "shape"
        assert rec["argument"] == "x"
        assert "f32[8]" in rec["old"]
        assert "f32[16]" in rec["new"]
        assert rec["variant"] == 1

    def test_dtype_change_attribution(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/dtype", registry=reg)
        fn(jnp.ones((8,), jnp.float32))
        fn(jnp.ones((8,), jnp.int32))
        (rec,) = self._events(reg)
        assert rec["kind"] == "dtype"
        assert rec["argument"] == "x"

    def test_structure_change_attribution(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_tree_sum, "test/tree", registry=reg)
        fn({"a": jnp.ones((4,), jnp.float32)})
        fn({"a": jnp.ones((4,), jnp.float32),
            "b": jnp.ones((4,), jnp.float32)})
        (rec,) = self._events(reg)
        assert rec["kind"] == "structure"
        assert rec["argument"] == "state"

    def test_leaf_level_attribution_in_pytree(self):
        # A shape change on ONE leaf of a pytree names that leaf, not
        # just the whole argument — the forensics must say which param.
        reg = ProgramLedger()
        fn = LedgeredFunction(_tree_sum, "test/leaf", registry=reg)
        fn({"w": jnp.ones((4, 4), jnp.float32),
            "b": jnp.ones((4,), jnp.float32)})
        fn({"w": jnp.ones((8, 4), jnp.float32),
            "b": jnp.ones((4,), jnp.float32)})
        (rec,) = self._events(reg)
        assert rec["kind"] == "shape"
        assert "w" in rec["argument"]
        assert "b" not in rec["argument"]

    def test_recompile_warns_and_fans_out(self, caplog):
        reg = ProgramLedger()
        captured = []
        reg.add_emitter(captured.append)
        try:
            fn = LedgeredFunction(_double, "test/emit", registry=reg)
            fn(jnp.ones((8,), jnp.float32))
            with caplog.at_level(
                logging.WARNING,
                logger="ray_lightning_tpu.program_ledger",
            ):
                fn(jnp.ones((16,), jnp.float32))
        finally:
            reg.remove_emitter(captured.append)
        assert any("recompile at test/emit" in r.getMessage()
                   for r in caplog.records)
        assert len(captured) == 1
        assert captured[0]["type"] == "recompile"
        assert captured[0]["site"] == "test/emit"


# ---------------------------------------------------------------------------
# Kill switch + global registration path
# ---------------------------------------------------------------------------

class TestWiring:
    def test_kill_switch_returns_bare_jit(self, monkeypatch):
        monkeypatch.setenv("RLT_PROGRAM_LEDGER", "0")
        fn = ledgered_jit(_double, site="test/killed")
        assert not isinstance(fn, LedgeredFunction)
        assert float(fn(jnp.ones((4,), jnp.float32))[0]) == 3.0

    def test_ledgered_jit_registers_globally(self):
        fn = ledgered_jit(_double, site="test/global_site")
        assert isinstance(fn, LedgeredFunction)
        fn(jnp.ones((8,), jnp.float32))
        sites = {r["site"] for r in ledger().snapshot()["programs"]}
        assert "test/global_site" in sites

    def test_site_flops_prefers_most_called(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/flops", registry=reg)
        a = jnp.ones((8,), jnp.float32)
        b = jnp.ones((64,), jnp.float32)
        fn(a)
        for _ in range(3):
            fn(b)
        flops = reg.site_flops("test/flops")
        rows = {r["variant"]: r for r in reg.snapshot()["programs"]}
        assert flops == rows[1]["flops"]  # the (64,) variant dominates

    def test_reset_clears_observatory_not_variants(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/reset", registry=reg)
        x = jnp.ones((8,), jnp.float32)
        fn(x)
        reg.reset()
        assert reg.snapshot()["programs"] == []
        fn(x)  # live variant survives: no recompile, no new record
        assert fn.variants == 1
        assert reg.snapshot()["programs"] == []


# ---------------------------------------------------------------------------
# Derived reports: HBM budget + roofline
# ---------------------------------------------------------------------------

class TestReports:
    def test_hbm_report_peaks(self):
        reg = ProgramLedger()
        small = LedgeredFunction(_double, "test/small", registry=reg)
        big = LedgeredFunction(_double, "test/big", registry=reg)
        small(jnp.ones((8,), jnp.float32))
        big(jnp.ones((4096,), jnp.float32))
        report = hbm_report(reg.snapshot())
        assert set(report["sites"]) == {"test/small", "test/big"}
        assert (report["peak_argument_bytes"]
                == report["sites"]["test/big"]["argument_bytes"])

    def test_roofline_placement(self):
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/roof", registry=reg)
        fn(jnp.ones((128,), jnp.float32))
        roof = roofline("test/roof", peak_flops=1e12,
                        peak_bytes_per_s=1e11, snap=reg.snapshot())
        assert roof is not None
        assert roof["flops"] > 0
        assert roof["arithmetic_intensity"] == pytest.approx(
            roof["flops"] / roof["bytes_accessed"]
        )
        assert roof["ridge_intensity"] == pytest.approx(10.0)
        assert roof["bound"] in ("compute", "memory")

    def test_roofline_unknown_site_is_none(self):
        assert roofline("test/nope", snap={"programs": []}) is None

    def test_site_flops_latest_tracks_most_recent_compile(self):
        # The loop's measured-MFU basis must read the program that just
        # compiled; most-called would leak an earlier fit's variant in
        # a long-lived process.
        reg = ProgramLedger()
        fn = LedgeredFunction(_double, "test/latest", registry=reg)
        for _ in range(5):
            fn(jnp.ones((8,), jnp.float32))    # most-called variant
        fn(jnp.ones((1024,), jnp.float32))     # most recent compile
        most_called = reg.site_flops("test/latest")
        latest = reg.site_flops_latest("test/latest")
        assert most_called is not None and latest is not None
        assert latest > most_called
        assert reg.site_flops_latest("test/nope") is None


# ---------------------------------------------------------------------------
# MFU drift guard (ledger-measured vs analytic FLOPs)
# ---------------------------------------------------------------------------

class TestMfuDriftGuard:
    def test_drift_beyond_10pct_warns(self, caplog):
        stats = StepStats(flops_per_example=100.0, peak_flops=1e12)
        with caplog.at_level(
            logging.WARNING,
            logger="ray_lightning_tpu.telemetry",
        ):
            stats.configure_measured_flops(150.0)
        assert stats.mfu_basis == "measured"
        assert any("MFU drift" in r.getMessage() for r in caplog.records)

    def test_small_drift_is_silent(self, caplog):
        stats = StepStats(flops_per_example=100.0, peak_flops=1e12)
        with caplog.at_level(
            logging.WARNING,
            logger="ray_lightning_tpu.telemetry",
        ):
            stats.configure_measured_flops(105.0)
        assert stats.mfu_basis == "measured"
        assert not any("MFU drift" in r.getMessage()
                       for r in caplog.records)

    def test_summary_carries_basis(self):
        stats = StepStats(flops_per_example=100.0, peak_flops=1e12)
        # step 0 is booked as compile; steady-state steps feed the
        # throughput the MFU (and with it, mfu_basis) hangs off.
        for _ in range(4):
            stats.record_step(0.01, 0.0, 0.001, examples=8)
        assert stats.summary().get("mfu_basis") == "analytic"
        stats.configure_measured_flops(101.0)
        assert stats.summary().get("mfu_basis") == "measured"
