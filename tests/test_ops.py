"""Numerical parity tests for the attention ops.

Strategy ≙ SURVEY §6 "grad-parity verification" (hard-part #5): the XLA
einsum attention is the reference; the Pallas flash kernel (interpreter on
CPU) and the ring sequence-parallel implementation must match it forward
and backward to float32 tolerance on a fixed seed.
"""

import jax
import jax.numpy as jnp
import pytest
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from ray_lightning_tpu.ops.attention import xla_causal_attention
from ray_lightning_tpu.ops.flash_attention import flash_attention
from ray_lightning_tpu.ops.ring_attention import ring_attention_sharded

B, S, H, D = 2, 256, 4, 64


@pytest.fixture(scope="module")
def qkv():
    rng = jax.random.PRNGKey(0)
    return tuple(
        jax.random.normal(r, (B, S, H, D)) for r in jax.random.split(rng, 3)
    )


def test_flash_forward_matches_xla(qkv):
    q, k, v = qkv
    ref = xla_causal_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=128, block_k=128)
    assert float(jnp.abs(out - ref).max()) < 1e-5


def test_flash_grad_matches_xla(qkv):
    q, k, v = qkv

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=128, block_k=128) ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_causal_attention(q, k, v) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 1e-4


@pytest.mark.parametrize("block_q,block_k", [(128, 256), (256, 128)])
def test_flash_grad_uneven_blocks(qkv, block_q, block_k):
    """The dq/dkv kernels walk each other's axis in the *other* block
    size — both divisibility directions must stay correct."""
    q, k, v = qkv

    def loss_flash(q, k, v):
        return (flash_attention(
            q, k, v, block_q=block_q, block_k=block_k) ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_causal_attention(q, k, v) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 1e-4


def test_flash_grad_matches_xla_bf16(qkv):
    """bf16 inputs: f32 accumulators inside the kernels keep the error at
    bf16-rounding scale (the VERDICT-specified 1e-2 budget)."""
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv)

    def loss_flash(q, k, v):
        return (flash_attention(
            q, k, v, block_q=128, block_k=128).astype(jnp.float32) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (xla_causal_attention(q, k, v).astype(jnp.float32) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        denom = max(float(jnp.abs(b.astype(jnp.float32)).max()), 1.0)
        rel = float(
            jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)).max()
        ) / denom
        assert rel < 1e-2


def test_flash_rejects_lane_misaligned_block_k(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="multiple of 128"):
        flash_attention(q, k, v, block_q=128, block_k=64)


def test_flash_rejects_ragged_seq(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=100)


@pytest.mark.parametrize("mesh_shape,axes", [
    ((8,), ("sp",)),
    ((2, 4), ("data", "sp")),
    ((1, 8), ("data", "sp")),
])
def test_ring_forward_matches_xla(qkv, mesh_shape, axes):
    q, k, v = qkv
    mesh = Mesh(mesh_utils.create_device_mesh(mesh_shape), axes)
    data_axis = "data" if "data" in axes else None
    ref = xla_causal_attention(q, k, v)
    out = ring_attention_sharded(q, k, v, mesh, data_axis=data_axis)
    assert float(jnp.abs(out - ref).max()) < 1e-5


def test_ring_grad_matches_xla(qkv):
    """Full grad parity: dq AND dk/dv through the ppermute re-scan."""
    q, k, v = qkv
    mesh = Mesh(mesh_utils.create_device_mesh((2, 4)), ("data", "sp"))

    def loss_ring(q, k, v):
        return (ring_attention_sharded(q, k, v, mesh) ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_causal_attention(q, k, v) ** 2).sum()

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, ("dq", "dk", "dv")):
        err = float(jnp.abs(a - b).max())
        assert err < 1e-4, f"{name} max err {err}"


def test_ring_under_jit(qkv):
    """Ring attention composes with jit (the training-step context)."""
    q, k, v = qkv
    mesh = Mesh(mesh_utils.create_device_mesh((8,)), ("sp",))
    fn = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, mesh, data_axis=None
        )
    )
    ref = xla_causal_attention(q, k, v)
    assert float(jnp.abs(fn(q, k, v) - ref).max()) < 1e-5


# -- fused LM-head cross-entropy (ops/cross_entropy.py) ----------------------

class TestFusedCrossEntropy:
    """Chunked-vs-naive parity (VERDICT r3 item #1: f32, 1e-5)."""

    def _inputs(self, V=515, B=2, T=32, d=64):
        rng = jax.random.PRNGKey(42)
        kx, kw, kt = jax.random.split(rng, 3)
        x = jax.random.normal(kx, (B, T, d), jnp.float32)
        wte = jax.random.normal(kw, (V, d), jnp.float32) * 0.1
        targets = jax.random.randint(kt, (B, T), 0, V)
        return x, wte, targets

    @pytest.mark.parametrize("num_chunks", [1, 3, 4])
    def test_loss_parity_f32(self, num_chunks):
        from ray_lightning_tpu.ops.cross_entropy import (
            fused_lm_head_cross_entropy, naive_lm_head_cross_entropy)
        x, wte, t = self._inputs()  # V=515: exercises padded last chunk
        fused = fused_lm_head_cross_entropy(
            x, wte, t, num_chunks=num_chunks, compute_dtype=jnp.float32)
        naive = naive_lm_head_cross_entropy(
            x, wte, t, compute_dtype=jnp.float32)
        assert fused.shape == t.shape
        assert float(jnp.abs(fused - naive).max()) < 1e-5

    def test_grad_parity_f32(self):
        from ray_lightning_tpu.ops.cross_entropy import (
            fused_lm_head_cross_entropy, naive_lm_head_cross_entropy)
        x, wte, t = self._inputs()

        def loss_f(x, w):
            return fused_lm_head_cross_entropy(
                x, w, t, num_chunks=4, compute_dtype=jnp.float32).mean()

        def loss_n(x, w):
            return naive_lm_head_cross_entropy(
                x, w, t, compute_dtype=jnp.float32).mean()

        gf = jax.grad(loss_f, argnums=(0, 1))(x, wte)
        gn = jax.grad(loss_n, argnums=(0, 1))(x, wte)
        for a, b, name in zip(gf, gn, ("dx", "dwte")):
            err = float(jnp.abs(a - b).max())
            assert err < 1e-5, f"{name} max err {err}"

    def test_bf16_close_to_f32(self):
        from ray_lightning_tpu.ops.cross_entropy import (
            fused_lm_head_cross_entropy, naive_lm_head_cross_entropy)
        x, wte, t = self._inputs()
        fused = jax.jit(
            lambda x, w: fused_lm_head_cross_entropy(x, w, t, num_chunks=4)
        )(x, wte).mean()
        naive = naive_lm_head_cross_entropy(
            x, wte, t, compute_dtype=jnp.float32).mean()
        assert abs(float(fused) - float(naive)) < 5e-2

    def test_sharded_under_mesh(self):
        """Fused CE under a dp×tp GSPMD mesh: batch sharded over data,
        wte feature-sharded over tensor — matches the replicated result."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_lightning_tpu.ops.cross_entropy import (
            fused_lm_head_cross_entropy, naive_lm_head_cross_entropy)
        x, wte, t = self._inputs(V=512, B=4, T=32, d=64)
        mesh = Mesh(
            mesh_utils.create_device_mesh((2, 4)), ("data", "tensor"))
        xs = jax.device_put(x, NamedSharding(mesh, P("data", None, None)))
        ws = jax.device_put(wte, NamedSharding(mesh, P(None, "tensor")))
        ts = jax.device_put(t, NamedSharding(mesh, P("data", None)))

        fused = jax.jit(
            lambda x, w, t: fused_lm_head_cross_entropy(
                x, w, t, num_chunks=4, compute_dtype=jnp.float32)
        )(xs, ws, ts)
        naive = naive_lm_head_cross_entropy(
            x, wte, t, compute_dtype=jnp.float32)
        assert float(jnp.abs(fused - naive).max()) < 1e-5


class TestFusedCEPallas:
    """Kernel-path (use_pallas=True) parity vs the naive head, run under
    the Pallas interpreter on the CPU mesh (same program as TPU)."""

    def _inputs(self, V=515, B=4, T=128, d=128):
        rng = jax.random.PRNGKey(7)
        kx, kw, kt = jax.random.split(rng, 3)
        x = jax.random.normal(kx, (B, T, d), jnp.float32)
        wte = jax.random.normal(kw, (V, d), jnp.float32) * 0.1
        targets = jax.random.randint(kt, (B, T), 0, V)
        return x, wte, targets

    # (4,128): token count divides _CE_BLOCK_T; (2,33): ragged -> padded.
    @pytest.mark.parametrize("B,T", [(4, 128), (2, 33)])
    def test_loss_and_grad_parity_f32(self, B, T):
        from ray_lightning_tpu.ops.cross_entropy import (
            fused_lm_head_cross_entropy, naive_lm_head_cross_entropy)
        x, wte, t = self._inputs(B=B, T=T)

        def loss_p(x, w):
            return fused_lm_head_cross_entropy(
                x, w, t, compute_dtype=jnp.float32, use_pallas=True).mean()

        def loss_n(x, w):
            return naive_lm_head_cross_entropy(
                x, w, t, compute_dtype=jnp.float32).mean()

        lp = loss_p(x, wte)
        ln = loss_n(x, wte)
        assert abs(float(lp) - float(ln)) < 1e-5
        gp = jax.grad(loss_p, argnums=(0, 1))(x, wte)
        gn = jax.grad(loss_n, argnums=(0, 1))(x, wte)
        for a, b, name in zip(gp, gn, ("dx", "dwte")):
            err = float(jnp.abs(a - b).max())
            assert err < 1e-5, f"{name} max err {err}"

    def test_kernel_probe_failure_falls_back(self, monkeypatch):
        """If the one-time Mosaic probe marked the kernels unavailable,
        use_pallas=True must silently take the scan path."""
        import ray_lightning_tpu.ops.cross_entropy as ce

        monkeypatch.setattr(ce, "_kernel_path_available",
                            lambda d, dt: False)
        x, wte, t = self._inputs()
        fused = ce.fused_lm_head_cross_entropy(
            x, wte, t, compute_dtype=jnp.float32, use_pallas=True)
        naive = ce.naive_lm_head_cross_entropy(
            x, wte, t, compute_dtype=jnp.float32)
        assert float(jnp.abs(fused - naive).max()) < 1e-5

    def test_misaligned_d_falls_back_to_scan(self):
        """d=64 is not lane-aligned: use_pallas must silently take the
        scan path and still match."""
        from ray_lightning_tpu.ops.cross_entropy import (
            fused_lm_head_cross_entropy, naive_lm_head_cross_entropy)
        x, wte, t = self._inputs(d=64)
        fused = fused_lm_head_cross_entropy(
            x, wte, t, compute_dtype=jnp.float32, use_pallas=True)
        naive = naive_lm_head_cross_entropy(
            x, wte, t, compute_dtype=jnp.float32)
        assert float(jnp.abs(fused - naive).max()) < 1e-5

    # jit > shard_map island > pallas: the multi-chip replicated-head
    # path (one dwte psum is the only collective).
    @pytest.mark.parametrize("pallas", [True, False])
    def test_sharded_island_parity(self, pallas):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_lightning_tpu.ops.cross_entropy import (
            fused_lm_head_cross_entropy_sharded,
            naive_lm_head_cross_entropy)

        x, wte, t = self._inputs(B=8, T=64)
        mesh = Mesh(
            mesh_utils.create_device_mesh((2, 2, 2)),
            ("data", "fsdp", "tensor"),
        )
        xs = jax.device_put(x, NamedSharding(mesh, P(("data", "fsdp"))))
        ts = jax.device_put(t, NamedSharding(mesh, P(("data", "fsdp"))))
        ws = jax.device_put(wte, NamedSharding(mesh, P()))

        def loss_s(x, w):
            return fused_lm_head_cross_entropy_sharded(
                x, w, ts, mesh, compute_dtype=jnp.float32,
                use_pallas=pallas).mean()

        def loss_n(x, w):
            return naive_lm_head_cross_entropy(
                x, w, t, compute_dtype=jnp.float32).mean()

        lv, gv = jax.jit(jax.value_and_grad(loss_s, argnums=(0, 1)))(
            xs, ws)
        ln, gn = jax.value_and_grad(loss_n, argnums=(0, 1))(x, wte)
        assert abs(float(lv) - float(ln)) < 1e-5
        for a, b, name in zip(gv, gn, ("dx", "dwte")):
            err = float(jnp.abs(a - b).max())
            assert err < 1e-5, f"{name} max err {err}"

    def test_sharded_rejects_indivisible_batch(self):
        from ray_lightning_tpu.ops.cross_entropy import (
            fused_lm_head_cross_entropy_sharded)
        import numpy as np

        x, wte, t = self._inputs(B=3, T=64)
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        with pytest.raises(ValueError, match="not divisible"):
            fused_lm_head_cross_entropy_sharded(
                x, wte, t, mesh, compute_dtype=jnp.float32)

    def test_batch_only_mesh_gate(self):
        """GPT engages the shard_map island only for batch-only GSPMD
        meshes with unsharded params."""
        import numpy as np

        from ray_lightning_tpu.models.gpt import GPT

        class Ctx:
            mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
            step_mode = "gspmd"
            zero_stage = 1

        assert GPT._batch_only_mesh(Ctx, batch_dim=8)
        # Indivisible batch: the island can't pad uneven shards -> veto.
        assert not GPT._batch_only_mesh(Ctx, batch_dim=6)
        for attr, bad in (("step_mode", "shard_map"), ("zero_stage", 3)):
            ctx = type("C", (Ctx,), {attr: bad})
            assert not GPT._batch_only_mesh(ctx, batch_dim=8)
        tp = type("C", (Ctx,), {"mesh": Mesh(
            np.array(jax.devices()[:4]).reshape(2, 2),
            ("data", "tensor"))})
        assert not GPT._batch_only_mesh(tp, batch_dim=8)
        assert not GPT._batch_only_mesh(
            type("C", (), {"mesh": None}), batch_dim=8)


class TestFusedLayerNorm:
    """Pallas LN kernels (interpret mode) vs the XLA reference math."""

    def _inputs(self, n=700, d=256):  # n=700: exercises token padding
        rng = jax.random.PRNGKey(11)
        kx, kg, kb = jax.random.split(rng, 3)
        x = jax.random.normal(kx, (4, n // 4, d), jnp.float32) * 3 + 1
        g = jax.random.normal(kg, (d,), jnp.float32) * 0.5 + 1
        b = jax.random.normal(kb, (d,), jnp.float32)
        return x, g, b

    def test_forward_and_grad_parity(self):
        from ray_lightning_tpu.ops.layer_norm import layer_norm

        x, g, b = self._inputs()

        def lp(x, g, b):
            return (layer_norm(x, g, b, use_pallas=True) ** 2).mean()

        def ln(x, g, b):
            return (layer_norm(x, g, b, use_pallas=False) ** 2).mean()

        yp = layer_norm(x, g, b, use_pallas=True)
        yn = layer_norm(x, g, b, use_pallas=False)
        assert float(jnp.abs(yp - yn).max()) < 1e-5
        gp = jax.grad(lp, argnums=(0, 1, 2))(x, g, b)
        gn = jax.grad(ln, argnums=(0, 1, 2))(x, g, b)
        for a, c, name in zip(gp, gn, ("dx", "dg", "db")):
            err = float(jnp.abs(a - c).max())
            assert err < 1e-5, f"{name} max err {err}"

    def test_bf16_input(self):
        from ray_lightning_tpu.ops.layer_norm import layer_norm

        x, g, b = self._inputs(n=512, d=128)
        xb = x.astype(jnp.bfloat16)
        yp = layer_norm(xb, g, b, use_pallas=True)
        yn = layer_norm(xb, g, b, use_pallas=False)
        assert yp.dtype == jnp.bfloat16
        assert float(jnp.abs(
            yp.astype(jnp.float32) - yn.astype(jnp.float32)
        ).max()) < 2e-2

    def test_misaligned_d_falls_back(self):
        from ray_lightning_tpu.ops.layer_norm import layer_norm

        x, g, b = self._inputs(n=64, d=96)  # 96 % 128 != 0
        yp = layer_norm(x, g, b, use_pallas=True)  # silently XLA
        yn = layer_norm(x, g, b, use_pallas=False)
        assert float(jnp.abs(yp - yn).max()) == 0.0


@pytest.mark.parametrize("mesh_shape,axes", [
    ((8,), ("sp",)),
    ((2, 4), ("data", "sp")),
])
def test_zigzag_ring_forward_matches_xla(qkv, mesh_shape, axes):
    """Zig-zag (causally balanced) layout: same math, permuted shards."""
    q, k, v = qkv
    mesh = Mesh(mesh_utils.create_device_mesh(mesh_shape), axes)
    data_axis = "data" if "data" in axes else None
    ref = xla_causal_attention(q, k, v)
    out = ring_attention_sharded(
        q, k, v, mesh, data_axis=data_axis, layout="zigzag")
    assert float(jnp.abs(out - ref).max()) < 1e-5


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_zigzag_ring_grad_matches_xla(qkv):
    q, k, v = qkv
    mesh = Mesh(mesh_utils.create_device_mesh((2, 4)), ("data", "sp"))

    def loss_ring(q, k, v):
        return (ring_attention_sharded(
            q, k, v, mesh, layout="zigzag") ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_causal_attention(q, k, v) ** 2).sum()

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, ("dq", "dk", "dv")):
        err = float(jnp.abs(a - b).max())
        assert err < 1e-4, f"{name} max err {err}"


def test_zigzag_indices_partition():
    from ray_lightning_tpu.ops.ring_attention import zigzag_indices

    idx = zigzag_indices(16, 4)
    # Shard j holds chunks j and 2n-1-j of 8 chunks (chunk = 2 rows).
    assert list(idx[:4]) == [0, 1, 14, 15]      # shard 0: chunks 0, 7
    assert list(idx[4:8]) == [2, 3, 12, 13]     # shard 1: chunks 1, 6
    assert sorted(idx) == list(range(16))       # a true permutation
    with pytest.raises(ValueError, match="divisible"):
        zigzag_indices(20, 8)


class TestKernelDisableSwitch:
    """RLT_DISABLE_KERNELS: the on-hardware A/B switch must force the
    fallback per family and be reflected by the probes (bench.py records
    kernel_path from exactly these)."""

    def test_family_disable_forces_fallback(self, monkeypatch):
        from ray_lightning_tpu.ops import kernel_probe

        monkeypatch.setenv("RLT_DISABLE_KERNELS", "ce, ln")
        assert kernel_probe.kernel_family_disabled("ce")
        assert kernel_probe.kernel_family_disabled("ln")
        assert not kernel_probe.kernel_family_disabled("flash")
        # Even under the interpreter (CPU), a disabled family reports
        # unavailable — no probe runs.
        assert kernel_probe.kernel_available(
            ("ce", 128, "float32"), lambda: None) is False
        assert kernel_probe.kernel_available(
            ("flash", 128), lambda: None) is True  # interpret: no probe

    def test_flash_disable_switch(self, monkeypatch):
        import jax.numpy as jnp

        from ray_lightning_tpu.ops.attention import _flash_supported

        q = jnp.zeros((1, 256, 4, 64), jnp.float32)
        monkeypatch.setenv("RLT_DISABLE_KERNELS", "flash")
        assert _flash_supported(q) is False

    def test_disabled_ce_still_correct(self, monkeypatch):
        """Numerics with the family disabled: the scan fallback answers."""
        import jax
        import jax.numpy as jnp

        from ray_lightning_tpu.ops.cross_entropy import (
            fused_lm_head_cross_entropy, naive_lm_head_cross_entropy)

        k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        x = jax.random.normal(k1, (2, 16, 128), jnp.float32)
        w = jax.random.normal(k2, (256, 128), jnp.float32) * 0.1
        t = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 256)
        monkeypatch.setenv("RLT_DISABLE_KERNELS", "ce")
        fused = fused_lm_head_cross_entropy(
            x, w, t, compute_dtype=jnp.float32, use_pallas=True)
        naive = naive_lm_head_cross_entropy(x, w, t,
                                            compute_dtype=jnp.float32)
        assert float(jnp.abs(fused - naive).max()) < 1e-5
