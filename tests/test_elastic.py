"""Elastic world-size recovery: reshard-on-load, shrink/grow restart
governance, gang-packed trials (docs/FAULT_TOLERANCE.md "Elastic
resume").

Fast tier-1 units cover the index-selective shard reader (hand-built
shard files), accum re-derivation, the governor's resize decisions (no
processes), the FleetPacker, and the resize event schema.  Every real
fit — the N→M drain/resume parity matrix and the ``lose_worker`` chaos
acceptance — is ``slow``-marked per the tier-1 budget.
"""

import os
import threading
import time
import warnings

import jax
import numpy as np
import pytest
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_lightning_tpu.core.loop import (
    FitConfig,
    _elastic_resume_info,
    _rederive_accum,
    run_fit,
)
from ray_lightning_tpu.fault import inject
from ray_lightning_tpu.models.boring import BoringDataModule, BoringModel
from ray_lightning_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_lightning_tpu.utils import sharded_ckpt as sc


def mesh_of(n):
    return build_mesh(MeshSpec({"data": n}), devices=jax.devices()[:n])


# ---------------------------------------------------------------------------
# Index-selective reader (fast units against hand-built shard files)
# ---------------------------------------------------------------------------

def _write_fake_world(dirpath, world=2):
    """Hand-build a ``world``-host checkpoint of one (16, 8) leaf: host
    r writes rows [r*8, (r+1)*8) — the multi-host layout a single test
    process cannot produce through save_shard."""
    import zlib

    full = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    os.makedirs(dirpath, exist_ok=True)
    rows_per = 16 // world
    shard_crcs = {}
    for r in range(world):
        lo, hi = r * rows_per, (r + 1) * rows_per
        records = [{
            "s": [16, 8], "d": "float32",
            "e": [{"i": [[lo, hi], [0, 8]], "b": full[lo:hi].tobytes()}],
        }]
        blob = sc._encode_shard_v2(r, world, records)
        path = os.path.join(dirpath, f"shard-{r:05d}-of-{world:05d}.ckpt")
        with open(path, "wb") as f:
            f.write(blob)
        with open(path + ".crc32", "w") as f:
            f.write(str(zlib.crc32(blob)))
        shard_crcs[str(r)] = zlib.crc32(blob)
    import msgpack
    import pickle

    treedef = jax.tree_util.tree_structure({"w": 0})
    body = msgpack.packb(
        {"world": world, "treedef": pickle.dumps(treedef),
         "extra": pickle.dumps({"epoch": 0}),
         "shard_crcs": shard_crcs},
        use_bin_type=True,
    )
    blob = msgpack.packb(
        {"v": 2, "crc": zlib.crc32(body), "body": body}, use_bin_type=True
    )
    with open(os.path.join(dirpath, "META.ckpt"), "wb") as f:
        f.write(blob)
    return full


def test_selective_reader_reads_only_overlapping_bytes(tmp_path):
    """A 1-device target whose sharding needs only the first half of
    the leaf must NOT read the second shard file's data bytes."""
    tag = str(tmp_path / "ck.ckpt")
    full = _write_fake_world(tag, world=2)
    full_size = sum(
        os.path.getsize(os.path.join(tag, n))
        for n in os.listdir(tag) if n.endswith(".ckpt") and "shard" in n
    )
    # Target: rows sharded over 2 devices — each device holds 8 rows,
    # both addressable in one process, so the WHOLE leaf is needed.
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("data",))
    sh = {"w": NamedSharding(mesh2, P("data", None))}
    payload = sc.load_sharded(tag, shardings=sh)
    assert sc.LOAD_STATS["selective"]
    np.testing.assert_array_equal(np.asarray(payload["state"]["w"]), full)

    # Now a target sharding placing rows 0-7 on THIS process only:
    # simulate via a sharding whose addressable map covers half.  A
    # 1-device mesh over device 0 with rows replicated would need all
    # rows; instead restrict with a custom object exposing the index
    # map protocol.
    class HalfSharding:
        def addressable_devices_indices_map(self, shape):
            return {jax.devices()[0]: (slice(0, 8), slice(0, 8))}

        # make_array_from_callback needs a real Sharding — assemble via
        # the internal reader instead and check its I/O accounting.

    needs = sc._needed_regions(HalfSharding(), (16, 8))
    assert needs == [((0, 8), (0, 8))]
    header0, off0 = sc._read_shard_header(
        os.path.join(tag, "shard-00000-of-00002.ckpt")
    )
    sc.LOAD_STATS.update(bytes_read=0, entries_read=0)
    entry = header0["leaves"][0]["e"][0]
    assert sc._regions_overlap(
        tuple((a, b) for a, b in entry["i"]), needs[0]
    )
    header1, off1 = sc._read_shard_header(
        os.path.join(tag, "shard-00001-of-00002.ckpt")
    )
    entry1 = header1["leaves"][0]["e"][0]
    # The second shard's rows [8, 16) do not overlap the needed half.
    assert not sc._regions_overlap(
        tuple((a, b) for a, b in entry1["i"]), needs[0]
    )
    # Reading just the overlapping entry costs half the data bytes.
    b = sc._entry_bytes(
        os.path.join(tag, "shard-00000-of-00002.ckpt"), entry, off0
    )
    assert len(b) == 8 * 8 * 4
    # The non-overlapping shard's data section (8×8 f32) stayed unread.
    assert sc.LOAD_STATS["entries_read"] == 1
    assert sc.LOAD_STATS["bytes_read"] <= full_size - 8 * 8 * 4


def test_selective_reader_places_resharded_leaves(tmp_path):
    """2-host checkpoint → 4-device mesh placement: values identical,
    leaves arrive as jax.Arrays with the requested shardings."""
    tag = str(tmp_path / "ck.ckpt")
    full = _write_fake_world(tag, world=2)
    mesh4 = Mesh(np.array(jax.devices()[:4]), ("data",))
    sh = {"w": NamedSharding(mesh4, P("data", None))}
    payload = sc.load_sharded(tag, shardings=sh)
    got = payload["state"]["w"]
    assert isinstance(got, jax.Array) and got.sharding == sh["w"]
    np.testing.assert_array_equal(np.asarray(got), full)
    # Structure mismatch falls back to the full host read.
    bad = {"w": NamedSharding(mesh4, P()), "extra_leaf": None}
    payload = sc.load_sharded(tag, shardings=bad)
    assert not sc.LOAD_STATS["selective"]
    np.testing.assert_array_equal(payload["state"]["w"], full)


def test_selective_entry_crc_catches_corruption(tmp_path):
    tag = str(tmp_path / "ck.ckpt")
    _write_fake_world(tag, world=2)
    path = os.path.join(tag, "shard-00000-of-00002.ckpt")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:  # flip a byte in the DATA section
        f.seek(size - 4)
        byte = f.read(1)
        f.seek(size - 4)
        f.write(bytes([byte[0] ^ 0xFF]))
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("data",))
    sh = {"w": NamedSharding(mesh2, P("data", None))}
    with pytest.raises(sc.CorruptCheckpointError, match="crc32"):
        sc.load_sharded(tag, shardings=sh)


def _rewrite_meta_crcs(dirpath, world):
    """Refresh META's recorded shard checksums from the sidecars (what
    a real rank-0 save_meta does) after a test rewrote a shard file."""
    import msgpack
    import pickle
    import zlib

    crcs = {}
    for r in range(world):
        with open(os.path.join(
            dirpath, f"shard-{r:05d}-of-{world:05d}.ckpt.crc32"
        )) as f:
            crcs[str(r)] = int(f.read().strip())
    treedef = jax.tree_util.tree_structure({"w": 0})
    body = msgpack.packb(
        {"world": world, "treedef": pickle.dumps(treedef),
         "extra": pickle.dumps({"epoch": 0}), "shard_crcs": crcs},
        use_bin_type=True,
    )
    blob = msgpack.packb(
        {"v": 2, "crc": zlib.crc32(body), "body": body}, use_bin_type=True
    )
    with open(os.path.join(dirpath, "META.ckpt"), "wb") as f:
        f.write(blob)


def _rewrite_shard1_as_v1(tag, full):
    """Replace shard 1 with a pre-elastic (bare msgpack) file."""
    import msgpack
    import zlib

    path = os.path.join(tag, "shard-00001-of-00002.ckpt")
    blob = msgpack.packb(
        {"rank": 1, "world": 2, "leaves": [{
            "s": [16, 8], "d": "float32",
            "e": [{"i": [[8, 16], [0, 8]], "b": full[8:].tobytes()}],
        }]},
        use_bin_type=True,
    )
    with open(path, "wb") as f:
        f.write(blob)
    with open(path + ".crc32", "w") as f:
        f.write(str(zlib.crc32(blob)))
    _rewrite_meta_crcs(tag, 2)
    return path


def test_v1_shard_files_still_load(tmp_path):
    """Pre-elastic shard files (bare msgpack, entry bytes inline) load
    through both the full and the selective path."""
    tag = str(tmp_path / "ck.ckpt")
    full = _write_fake_world(tag, world=2)
    _rewrite_shard1_as_v1(tag, full)
    payload = sc.load_sharded(tag)
    np.testing.assert_array_equal(payload["state"]["w"], full)
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("data",))
    payload = sc.load_sharded(
        tag, shardings={"w": NamedSharding(mesh2, P("data", None))}
    )
    np.testing.assert_array_equal(np.asarray(payload["state"]["w"]), full)


def test_v1_selective_load_still_verifies_checksums(tmp_path):
    """Review regression: the selective path must NOT bypass integrity
    for v1 shards (no per-entry crcs there) — the META whole-file
    checksum is checked at header-read time instead."""
    tag = str(tmp_path / "ck.ckpt")
    full = _write_fake_world(tag, world=2)
    path = _rewrite_shard1_as_v1(tag, full)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:  # flip a byte mid-file
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("data",))
    sh = {"w": NamedSharding(mesh2, P("data", None))}
    with pytest.raises(sc.CorruptCheckpointError, match="checksum"):
        sc.load_sharded(tag, shardings=sh)
    with pytest.raises(sc.CorruptCheckpointError, match="checksum"):
        sc.load_sharded(tag)


def test_verify_flags_world_mismatch_and_discovery_walks_back(tmp_path):
    """Satellite: a candidate dir whose shard files disagree with
    META's world size is skipped with a ckpt_corrupt-style record, and
    discovery walks back to the previous verified checkpoint."""
    from ray_lightning_tpu.parallel.strategies import (
        _remote_latest_restart_checkpoint,
    )

    mesh = Mesh(mesh_utils.create_device_mesh((8,)), ("data",))
    tree = {"w": jax.device_put(
        np.arange(64, dtype=np.float32).reshape(8, 8),
        NamedSharding(mesh, P("data", None)),
    )}
    rdir = tmp_path / "restarts"
    good = str(rdir / "restart-epoch-000000.ckpt")
    sc.save_shard(tree, good, rank=0, world=1)
    sc.save_meta(tree, good, world=1)
    time.sleep(0.05)
    stale = str(rdir / "restart-epoch-000001.ckpt")
    sc.save_shard(tree, stale, rank=0, world=1)
    sc.save_meta(tree, stale, world=1)
    # A leftover shard from an older, larger world in the newest dir.
    with open(os.path.join(stale, "shard-00000-of-00004.ckpt"), "wb") as f:
        f.write(b"leftover")
    problems = sc.verify_sharded(stale)
    assert any("world size 4" in p for p in problems)
    info = _remote_latest_restart_checkpoint(str(rdir))
    assert info["path"] == good
    assert [c["path"] for c in info["corrupt"]] == [stale]


# ---------------------------------------------------------------------------
# Accum re-derivation (global-batch invariance)
# ---------------------------------------------------------------------------

def test_rederive_accum():
    assert _rederive_accum(4, 2, 2) == 4      # shrink 4→2 doubles accum
    assert _rederive_accum(2, 2, 4) == 1      # grow 2→4 halves it
    assert _rederive_accum(2, 3, 2) == 3      # same world: unchanged
    assert _rederive_accum(2, 1, 4) is None   # 2 rows !% 4 → not exact
    assert _rederive_accum(3, 2, 2) == 3      # 6 / 2
    assert _rederive_accum(1, 1, 0) is None


def test_elastic_resume_info_reads_meta(tmp_path):
    mesh = Mesh(mesh_utils.create_device_mesh((8,)), ("data",))
    tree = {"w": jax.device_put(
        np.arange(8, dtype=np.float32), NamedSharding(mesh, P())
    )}
    tag = str(tmp_path / "drain-step-00000006.ckpt")
    sc.save_shard(tree, tag, rank=0, world=1)
    sc.save_meta(tree, tag, world=1,
                 extra={"world_size": 2, "accum": 2, "epoch": 0})
    info = _elastic_resume_info(tag, world_size=1, cfg_accum=2)
    assert info is not None and info["accum"] == 4 and info["exact"]
    assert (info["old_world"], info["new_world"]) == (2, 1)
    # Same world + same accum: no resize.
    assert _elastic_resume_info(tag, world_size=2, cfg_accum=2) is None
    # Same world but the checkpoint's recorded accum differs (a
    # previous elastic resize re-derived it): the recorded value wins
    # — reverting to the config's would change the global batch
    # mid-trajectory and hand the resume a mismatched opt_state.
    cont = _elastic_resume_info(tag, world_size=2, cfg_accum=1)
    assert cont is not None and cont["accum"] == 2
    assert cont["old_world"] == cont["new_world"] == 2
    # Pre-elastic checkpoint (no recorded world): no resize.
    tag2 = str(tmp_path / "drain-step-00000007.ckpt")
    sc.save_shard(tree, tag2, rank=0, world=1)
    sc.save_meta(tree, tag2, world=1, extra={"epoch": 0})
    assert _elastic_resume_info(tag2, world_size=1, cfg_accum=2) is None


@pytest.mark.slow
def test_accum_rederived_in_fit(tmp_path):
    """A checkpoint claiming world_size=2, accum=2 resumed at world 1
    must train with accum 4: 8 micro-batches advance exactly 2
    optimizer steps."""
    dm = BoringDataModule(length=128, batch_size=16)
    cfg = FitConfig(max_epochs=1, seed=0, default_root_dir=str(tmp_path),
                    restart_dir=str(tmp_path / "rs"))
    res = run_fit(BoringModel(), dm, cfg, callbacks=[])
    tag = str(tmp_path / "rs" / "restart-epoch-000000.ckpt")
    assert sc.is_sharded_ckpt(tag)
    # Rewrite META claiming the state came from a 2-host, accum-2 run.
    payload = sc.load_meta(tag)
    extra = dict(payload["extra"])
    extra.update(world_size=2, accum=2)
    state = sc.load_sharded(tag)["state"]
    sc.save_meta(state, tag, world=1, extra=extra)
    cfg2 = FitConfig(max_epochs=2, seed=0, accumulate_grad_batches=2,
                     default_root_dir=str(tmp_path),
                     resume_from_checkpoint=tag)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res2 = run_fit(BoringModel(), BoringDataModule(
            length=128, batch_size=16), cfg2, callbacks=[])
    assert any("elastic resume" in str(x.message) for x in w)
    # Epoch 2: 8 micro-batches at accum 4 → 2 optimizer steps on top of
    # the resumed counter.
    assert res2["micro_step"] - res["micro_step"] == 8
    assert res2["global_step"] - res["global_step"] == 2


@pytest.mark.slow
def test_same_world_resume_honors_recorded_accum(tmp_path):
    """Review regression (shrink-then-crash): a checkpoint whose META
    records an elastically re-derived accum must keep that accum on a
    SAME-world resume, even when the config says otherwise — reverting
    would change the global batch mid-trajectory and crash on the
    mismatched opt_state structure."""
    dm = BoringDataModule(length=128, batch_size=16)
    cfg = FitConfig(max_epochs=1, seed=0, default_root_dir=str(tmp_path),
                    restart_dir=str(tmp_path / "rs"))
    res = run_fit(BoringModel(), dm, cfg, callbacks=[])
    tag = str(tmp_path / "rs" / "restart-epoch-000000.ckpt")
    # Simulate the post-shrink record: world 1, accum 2 (the first fit
    # ran accum 1, so the opt_state is BARE — the resume must wrap it).
    payload = sc.load_meta(tag)
    extra = dict(payload["extra"])
    extra.update(world_size=1, accum=2)
    state = sc.load_sharded(tag)["state"]
    sc.save_meta(state, tag, world=1, extra=extra)
    cfg2 = FitConfig(max_epochs=2, seed=0, accumulate_grad_batches=1,
                     default_root_dir=str(tmp_path),
                     resume_from_checkpoint=tag)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res2 = run_fit(BoringModel(), BoringDataModule(
            length=128, batch_size=16), cfg2, callbacks=[])
    assert any("recorded accum" in str(x.message) for x in w)
    # Epoch 2: 8 micro-batches at the RECORDED accum 2 → 4 optimizer
    # steps (the config's accum 1 would have made 8).
    assert res2["micro_step"] - res["micro_step"] == 8
    assert res2["global_step"] - res["global_step"] == 4


# ---------------------------------------------------------------------------
# N→M drain/resume parity (slow fits; the tentpole acceptance)
# ---------------------------------------------------------------------------

def _drain_ckpt(tmp_path, accum, megastep, drain_at=4):
    from ray_lightning_tpu.core.callbacks import Callback
    from ray_lightning_tpu.fault import drain as drain_mod
    from ray_lightning_tpu.fault.drain import PreemptedError

    class DrainAt(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            # >= not ==: under megastep, hooks fire once per stride
            # with micro_step advancing K at a time.
            if trainer.micro_step >= drain_at:
                drain_mod.request_drain("test")

    cfg = FitConfig(
        max_epochs=2, seed=0, default_root_dir=str(tmp_path),
        restart_dir=str(tmp_path / "rs"),
        accumulate_grad_batches=accum, megastep=megastep,
    )
    with pytest.raises(PreemptedError) as err:
        run_fit(BoringModel(), BoringDataModule(length=96, batch_size=16),
                cfg, callbacks=[DrainAt()], mesh=mesh_of(4))
    assert err.value.checkpoint
    return err.value.checkpoint


@pytest.mark.slow
@pytest.mark.parametrize("accum", [1, 4])
@pytest.mark.parametrize("megastep", ["off", 2])
def test_n_to_m_resume_parity(tmp_path, accum, megastep):
    """Drain on a 4-way mesh, resume on 2 and on 1: losses and step
    counters match an uninterrupted fit — across accum and megastep."""
    base_cfg = FitConfig(
        max_epochs=2, seed=0, default_root_dir=str(tmp_path),
        accumulate_grad_batches=accum, megastep=megastep,
    )
    base = run_fit(
        BoringModel(), BoringDataModule(length=96, batch_size=16),
        base_cfg, callbacks=[], mesh=mesh_of(4),
    )
    ckpt = _drain_ckpt(tmp_path, accum, megastep)
    for m in (2, 1):
        cfg = FitConfig(
            max_epochs=2, seed=0, default_root_dir=str(tmp_path),
            resume_from_checkpoint=ckpt,
            accumulate_grad_batches=accum, megastep=megastep,
        )
        res = run_fit(
            BoringModel(), BoringDataModule(length=96, batch_size=16),
            cfg, callbacks=[], mesh=mesh_of(m),
        )
        assert res["global_step"] == base["global_step"]
        assert res["micro_step"] == base["micro_step"]
        assert res["callback_metrics"]["train_loss"] == pytest.approx(
            base["callback_metrics"]["train_loss"], abs=1e-5
        )


# ---------------------------------------------------------------------------
# Capacity oracle + governor decisions (fast, no processes)
# ---------------------------------------------------------------------------

def test_lost_worker_count_expiry(tmp_path):
    d = str(tmp_path / "chaos")
    inject.record_worker_loss(1, regain_s=None, state_dir=d)
    inject.record_worker_loss(2, regain_s=30.0, state_dir=d)
    assert inject.lost_worker_count(state_dir=d) == 2
    assert inject.lost_worker_count(
        now=time.time() + 60, state_dir=d) == 1
    assert inject.lost_worker_count(state_dir=str(tmp_path / "nope")) == 0


def test_lose_worker_grammar():
    spec = inject.parse_faults("lose_worker@point:spawn,rank:1,secs:5")[0]
    assert spec.kind == "lose_worker" and spec.rank == 1
    assert spec.secs == 5.0 and spec.point == "spawn"


def test_governor_resize_decisions():
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    cap = [4]
    s = RayStrategy(num_workers=4, max_restarts=1,
                    elastic_min_workers=2,
                    elastic_capacity_fn=lambda: cap[0])
    assert s.world_size == 4
    assert s._elastic_resize_decision() == (4, False)
    cap[0] = 3
    assert s._elastic_resize_decision() == (3, False)
    cap[0] = 9  # capacity above the request never grows past it
    assert s._elastic_resize_decision() == (4, False)
    cap[0] = 1
    assert s._elastic_resize_decision() == (1, True)
    # Fixed-size strategy: never resizes regardless of markers.
    fixed = RayStrategy(num_workers=4, max_restarts=1)
    assert fixed._elastic_resize_decision() == (None, False)


def test_governor_knob_validation():
    from ray_lightning_tpu.parallel.strategies import (
        MpmdStrategy,
        RayStrategy,
    )

    with pytest.raises(ValueError, match="elastic_min_workers"):
        RayStrategy(num_workers=2, elastic_min_workers=3)
    with pytest.raises(ValueError, match="elastic_min_workers"):
        RayStrategy(num_workers=2, elastic_min_workers=0)
    with pytest.raises(ValueError, match="elastic_grow_after_s"):
        RayStrategy(num_workers=2, elastic_grow_after_s=-1.0)
    with pytest.raises(ValueError, match="cannot resize"):
        MpmdStrategy(num_stages=2, elastic_min_workers=1)


def test_governor_env_bus(monkeypatch):
    from ray_lightning_tpu.parallel.strategies import (
        MpmdStrategy,
        RayStrategy,
    )

    monkeypatch.setenv("RLT_ELASTIC_MIN_WORKERS", "1")
    monkeypatch.setenv("RLT_ELASTIC_GROW_AFTER_S", "2.5")
    s = RayStrategy(num_workers=2, max_restarts=1)
    assert s.elastic_min_workers == 1
    assert s.elastic_grow_after_s == 2.5
    # A fleet-wide floor larger than this strategy clamps, not crashes.
    monkeypatch.setenv("RLT_ELASTIC_MIN_WORKERS", "8")
    s2 = RayStrategy(num_workers=2, max_restarts=1)
    assert s2.elastic_min_workers == 2
    # MpmdStrategy ignores the env bus entirely: stages are structural.
    m = MpmdStrategy(num_stages=2)
    assert m.elastic_min_workers is None
    assert m.elastic_grow_after_s is None


def test_governor_shrink_grow_simulation(tmp_path):
    """The whole shrink→grow trace without processes: attempt 1 dies
    with capacity 1 → shrink to 1 (budget-free); attempt 2 drains on
    the grow request → respawn at 2; attempt 3 completes."""
    from ray_lightning_tpu.cluster.actor import ActorDiedError
    from ray_lightning_tpu.fault.drain import PreemptedError
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    cap = [1]
    s = RayStrategy(
        num_workers=2, max_restarts=1, restart_backoff_s=0.0,
        elastic_min_workers=1, elastic_grow_after_s=0.0,
        elastic_capacity_fn=lambda: cap[0],
    )
    s._backend = object()
    s._respawn_workers = lambda: None
    s._kill_workers = lambda *a, **k: None
    s._latest_restart_checkpoint = (
        lambda rd: {"path": None, "corrupt": []}
    )
    worlds, attempt = [], [0]

    def fake_run_once(*a, **k):
        attempt[0] += 1
        worlds.append(s.active_workers)
        if attempt[0] == 1:
            raise ActorDiedError("worker 1 preempted")
        if attempt[0] == 2:
            cap[0] = 2
            s._grow_pending = True
            raise PreemptedError("grow drain", step=5, reason="grow")
        return [{"rank": 0}]

    s._run_once = fake_run_once
    s.run("fit", None, None,
          FitConfig(max_epochs=1, default_root_dir=str(tmp_path)), [])
    assert worlds == [2, 1, 2]
    assert s.restarts_used == 0
    assert s.preempt_restarts_used == 1
    assert s.resizes_used == 2
    kinds = [e["kind"] for e in s.recovery_events]
    assert kinds.count("resize") == 2
    resizes = [e for e in s.recovery_events if e["kind"] == "resize"]
    assert (resizes[0]["old_world"], resizes[0]["new_world"]) == (2, 1)
    assert (resizes[1]["old_world"], resizes[1]["new_world"]) == (1, 2)


def test_governor_resize_flap_guard(tmp_path):
    """Consecutive shrinks resuming from the same point must raise (a
    flapping fleet cannot loop budget-free forever)."""
    from ray_lightning_tpu.cluster.actor import ActorDiedError
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    cap = [3]
    s = RayStrategy(
        num_workers=4, max_restarts=1, elastic_min_workers=1,
        elastic_capacity_fn=lambda: cap[0],
    )
    s._backend = object()
    s._respawn_workers = lambda: None
    s._kill_workers = lambda *a, **k: None
    s._latest_restart_checkpoint = (
        lambda rd: {"path": "/same/ckpt", "corrupt": []}
    )
    attempt = [0]

    def fake_run_once(*a, **k):
        attempt[0] += 1
        cap[0] = max(cap[0] - (attempt[0] > 1), 1)
        raise ActorDiedError(f"death {attempt[0]}")

    s._run_once = fake_run_once
    with pytest.raises(ActorDiedError, match="flap guard"):
        s.run("fit", None, None,
              FitConfig(max_epochs=1, default_root_dir=str(tmp_path)), [])
    assert attempt[0] == 3  # shrink, shrink-same-ckpt, shrink-flagged


def test_governor_flap_guard_not_preseeded_by_scratch(tmp_path):
    """Review regression: a fit with NO checkpoint yet (resume None)
    must get the same two-strike allowance as one with checkpoints —
    the initial sentinel must not make the first scratch shrink count
    as a repeat."""
    from ray_lightning_tpu.cluster.actor import ActorDiedError
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    cap = [3]
    s = RayStrategy(
        num_workers=4, max_restarts=1, elastic_min_workers=1,
        elastic_capacity_fn=lambda: cap[0],
    )
    s._backend = object()
    s._respawn_workers = lambda: None
    s._kill_workers = lambda *a, **k: None
    s._latest_restart_checkpoint = (
        lambda rd: {"path": None, "corrupt": []}  # always scratch
    )
    attempt = [0]

    def fake_run_once(*a, **k):
        attempt[0] += 1
        if attempt[0] == 2:
            return [{"rank": 0}]  # second attempt (first shrink) runs
        cap[0] -= 1
        raise ActorDiedError(f"death {attempt[0]}")

    s._run_once = fake_run_once
    s.run("fit", None, None,
          FitConfig(max_epochs=1, default_root_dir=str(tmp_path)), [])
    assert attempt[0] == 2  # the single scratch shrink was allowed
    assert s.resizes_used == 1


def test_resize_events_validate():
    from ray_lightning_tpu.telemetry.monitor import make_event
    from ray_lightning_tpu.telemetry.schema import (
        validate_bench_fault,
        validate_event,
    )

    ev = make_event("resize", -1, old_world=4, new_world=2,
                    recover_s=1.5, ckpt="/tmp/x.ckpt", message="m")
    assert validate_event(ev) == []
    rej = make_event("resize_rejected", -1, old_world=4, new_world=0,
                     message="below min")
    assert validate_event(rej) == []
    assert validate_bench_fault(
        {"resize_time_to_recover_s": 2.0, "resize_old_world": 2,
         "resize_new_world": 1}
    ) == []
    assert validate_bench_fault({"resize_old_world": -1})


# ---------------------------------------------------------------------------
# EF residual under a changed device count (satellite regression)
# ---------------------------------------------------------------------------

def test_grad_residual_dropped_loudly_on_world_change():
    from ray_lightning_tpu.core.module import TrainState
    from ray_lightning_tpu.parallel import grad_sync as gsync
    from ray_lightning_tpu.telemetry import Telemetry

    mesh = Mesh(mesh_utils.create_device_mesh((8,)), ("data",))
    module = BoringModel(in_dim=64, out_dim=8)
    gs = gsync.maybe_build_grad_sync(
        module, mesh, {"mode": "int8_ef", "dcn_only": False}
    )
    assert gs is not None
    tel = Telemetry.build({"tier": "cheap"}, 0, 1, n_chips=8)
    gs.register_telemetry(tel)
    params = module.init_params(jax.random.PRNGKey(0))
    # A residual from a 4-device world: wrong leading dim here (8).
    wrong = np.zeros((4, gs.plan.total_padded), np.float32)
    state = TrainState(params, None, 0, wrong)
    with pytest.warns(UserWarning, match="elastic world-size change"):
        out = gs.reconcile_resumed_state(state)
    assert out.grad_residual.shape == (8, gs.plan.total_padded)
    assert not out.grad_residual.any()
    assert tel.snapshot()["counters"]["grad_residual_dropped"] == 1
    # A matching residual passes through untouched, silently.
    good = np.ones((8, gs.plan.total_padded), np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        kept = gs.reconcile_resumed_state(
            TrainState(params, None, 0, good)
        )
    assert kept.grad_residual is good


# ---------------------------------------------------------------------------
# Gang-packing (FleetPacker + session wiring; fast)
# ---------------------------------------------------------------------------

def test_fleet_packer_disjoint_and_blocking():
    from ray_lightning_tpu.tuning.pack import FleetPacker

    p = FleetPacker(8)
    a = p.acquire(4)
    b = p.acquire(4)
    assert set(a.devices).isdisjoint(b.devices)
    assert len(a.devices) == len(b.devices) == 4
    with pytest.raises(TimeoutError):
        p.acquire(1, timeout=0.05)
    got = []
    t = threading.Thread(target=lambda: got.append(p.acquire(2)))
    t.start()
    time.sleep(0.05)
    assert not got  # still blocked
    p.release(a)
    t.join(timeout=2)
    assert got and len(got[0].devices) == 2
    # min_n: a busy fleet hands out what it has.
    c = p.acquire(4, min_n=2)
    assert len(c.devices) == 2
    snap = p.snapshot()
    assert snap["total"] == 8 and snap["free"] == []


def test_fleet_packer_resize_repacks():
    from ray_lightning_tpu.tuning.pack import FleetPacker

    p = FleetPacker(8)
    a = p.acquire(6)
    assert p.resize(a, 3) == 3
    assert len(p.snapshot()["free"]) == 5
    b = p.acquire(4)
    assert set(a.devices).isdisjoint(b.devices)
    # Growing takes only what is free (never steals from b).
    assert p.resize(a, 8) == 4
    p.release(b)
    assert p.resize(a, 8) == 8
    p.release(a)
    assert len(p.snapshot()["free"]) == 8


def test_session_resize_notifies_packer(tmp_path):
    from ray_lightning_tpu.tuning.pack import FleetPacker
    from ray_lightning_tpu.tuning.session import (
        current_trial_devices,
        init_trial_session,
        notify_world_resize,
        shutdown_trial_session,
    )

    p = FleetPacker(8)
    alloc = p.acquire(4)
    sess = init_trial_session(
        "t0", str(tmp_path), devices=alloc.devices
    )
    try:
        assert current_trial_devices() == alloc.devices

        def on_resize(old, new, _a=alloc, _s=sess):
            p.resize(_a, max((_a.n * new) // old, 1))
            _s.devices = _a.devices

        sess.on_resize = on_resize
        notify_world_resize(2, 1)  # the governor's shrink hook
        assert len(current_trial_devices()) == 2
        assert len(p.snapshot()["free"]) == 6
        notify_world_resize(1, 2)  # grow back
        assert len(current_trial_devices()) == 4
    finally:
        shutdown_trial_session()


@pytest.mark.slow
def test_gang_packed_trials_get_disjoint_meshes(tmp_path):
    """Two concurrent LocalStrategy trials on one 8-device fleet train
    on DISJOINT 4-device sub-meshes."""
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.parallel.strategies import LocalStrategy
    from ray_lightning_tpu.tuning import tune_run
    from ray_lightning_tpu.tuning.session import (
        current_trial_devices,
        get_trial_session,
        report,
    )

    seen = {}
    lock = threading.Lock()

    def trainable(cfg):
        devs = current_trial_devices()
        tr = Trainer(
            strategy=LocalStrategy(), max_epochs=1,
            limit_train_batches=2, limit_val_batches=0,
            enable_checkpointing=False,
            default_root_dir=str(tmp_path),
        )
        tr.fit(BoringModel(), BoringDataModule(batch_size=16))
        with lock:
            seen[get_trial_session().trial_id] = tuple(devs)
        report(loss=float(tr.callback_metrics["train_loss"]))

    ana = tune_run(
        trainable, {"lr": 0.1}, num_samples=2,
        max_concurrent_trials=2, fleet_devices=8,
        local_dir=str(tmp_path / "tune"), raise_on_trial_error=True,
    )
    assert [t.status for t in ana.trials] == ["TERMINATED"] * 2
    a, b = seen.values()
    assert len(a) == len(b) == 4 and set(a).isdisjoint(b)


# ---------------------------------------------------------------------------
# Chaos acceptance: lose_worker → shrink (slow; real worker actors)
# ---------------------------------------------------------------------------

@pytest.mark.remote
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_lose_worker_shrinks_and_completes(tmp_path, monkeypatch):
    """The acceptance pin: a fit killed by a ``lose_worker`` fault
    resumes at the smaller world size with step-exact counters, the
    shrink is budget-free, and the resize event records
    old/new world + recover_s (the scorecard's
    ``resize_time_to_recover_s``)."""
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    monkeypatch.setenv("RLT_FAULT", "lose_worker@point:spawn,rank:1")
    monkeypatch.setenv("RLT_FAULT_STATE", str(tmp_path / "chaos"))
    strategy = RayStrategy(
        num_workers=2, max_restarts=1, restart_backoff_s=0.05,
        elastic_min_workers=1,
    )
    trainer = Trainer(
        strategy=strategy, max_epochs=3, default_root_dir=str(tmp_path),
        limit_train_batches=2, limit_val_batches=1,
        enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert trainer.global_step == 6
    assert strategy.active_workers == 1
    assert strategy.resizes_used == 1
    assert strategy.restarts_used == 0  # budget-free shrink
    kinds = [e["kind"] for e in trainer.monitor_report["events"]]
    assert "resize" in kinds
    resize = next(
        e for e in trainer.monitor_report["events"]
        if e["kind"] == "resize"
    )
    assert (resize["old_world"], resize["new_world"]) == (2, 1)
    assert resize["recover_s"] > 0
    assert strategy.last_resize_recover_s == resize["recover_s"]


@pytest.mark.remote
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_shrink_below_min_rejects(tmp_path, monkeypatch):
    from ray_lightning_tpu.cluster.actor import ActorDiedError
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    monkeypatch.setenv("RLT_FAULT", "lose_worker@point:spawn,rank:1")
    monkeypatch.setenv("RLT_FAULT_STATE", str(tmp_path / "chaos"))
    strategy = RayStrategy(
        num_workers=2, max_restarts=1, restart_backoff_s=0.05,
        elastic_min_workers=2,
    )
    trainer = Trainer(
        strategy=strategy, max_epochs=3, default_root_dir=str(tmp_path),
        limit_train_batches=2, limit_val_batches=1,
        enable_checkpointing=False,
    )
    with pytest.raises(ActorDiedError, match="shrink rejected"):
        trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert strategy.active_workers == 2  # never resized
    kinds = [e["kind"] for e in strategy.recovery_events]
    assert "resize_rejected" in kinds
