"""HF GPT-2 import: logits parity between a randomly-initialized
``transformers`` GPT-2 and the imported in-framework GPT — the
migration-path guarantee for users arriving from the torch ecosystem.

No downloads (zero-egress environment): a tiny random-init HF model is
the oracle.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ray_lightning_tpu.models.gpt import GPT  # noqa: E402
from ray_lightning_tpu.utils.hf_import import (  # noqa: E402
    gpt_config_from_hf,
    import_gpt2,
)


def _tiny_hf(vocab=97, n_layer=2, n_head=4, d=64, seq=32):
    config = transformers.GPT2Config(
        vocab_size=vocab, n_positions=seq, n_embd=d,
        n_layer=n_layer, n_head=n_head,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(config)
    model.eval()
    return model


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_logits_parity_with_transformers():
    hf = _tiny_hf()
    cfg, params = import_gpt2(hf)
    model = GPT(cfg, attn_impl="xla")
    model.precision = "f32"

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int64)

    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.numpy()

    ours = np.asarray(
        jax.jit(model.forward)(params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_imported_params_train_under_strategy(tmp_path):
    """Imported weights drop into the normal fit path (sharded mesh):
    the loss moves and stays finite."""
    from ray_lightning_tpu.core.trainer import Trainer
    from ray_lightning_tpu.models.gpt import SyntheticLMDataModule
    from ray_lightning_tpu.parallel.strategies import LocalStrategy

    hf = _tiny_hf()
    cfg, params = import_gpt2(hf)
    model = GPT(cfg, attn_impl="xla")
    model.initial_params = params  # seed the fit from imported weights

    trainer = Trainer(
        strategy=LocalStrategy(mesh_axes={"data": 2, "fsdp": 2, "tensor": 2},
                               zero_stage=3),
        max_epochs=1, limit_train_batches=2, limit_val_batches=1,
        enable_checkpointing=False, default_root_dir=str(tmp_path),
    )
    trainer.fit(model, SyntheticLMDataModule(cfg, batch_size=8,
                                             num_batches=2))
    assert np.isfinite(trainer.callback_metrics["train_loss"])


def test_generation_parity_greedy():
    """Greedy decode agrees with HF's greedy generate on the same
    imported weights — the end-to-end inference parity check."""
    from ray_lightning_tpu.models.generate import generate

    hf = _tiny_hf()
    cfg, params = import_gpt2(hf)
    model = GPT(cfg, attn_impl="xla")
    model.precision = "f32"

    prompt = np.asarray([[5, 17, 3, 42]], dtype=np.int64)
    new = 8
    with torch.no_grad():
        ref = hf.generate(
            torch.from_numpy(prompt), max_new_tokens=new, do_sample=False,
            pad_token_id=0,
        ).numpy()[:, prompt.shape[1]:]

    ours = np.asarray(generate(
        model, params, jnp.asarray(prompt, jnp.int32), max_new_tokens=new,
    ))[:, prompt.shape[1]:]
    np.testing.assert_array_equal(ours, ref)


def test_import_rejects_incompatible_activation():
    config = transformers.GPT2Config(
        vocab_size=64, n_positions=16, n_embd=32, n_layer=1, n_head=2,
        activation_function="relu",
    )
    with pytest.raises(ValueError, match="activation"):
        gpt_config_from_hf(config)


def test_import_rejects_attention_variants():
    base = dict(vocab_size=64, n_positions=16, n_embd=32, n_layer=1,
                n_head=2)
    with pytest.raises(ValueError, match="inverse_layer_idx"):
        gpt_config_from_hf(transformers.GPT2Config(
            **base, scale_attn_by_inverse_layer_idx=True))
    with pytest.raises(ValueError, match="reorder_and_upcast"):
        gpt_config_from_hf(transformers.GPT2Config(
            **base, reorder_and_upcast_attn=True))
    with pytest.raises(ValueError, match="n_inner"):
        gpt_config_from_hf(transformers.GPT2Config(**base, n_inner=100))
    with pytest.raises(ValueError, match="scale_attn_weights"):
        gpt_config_from_hf(transformers.GPT2Config(
            **base, scale_attn_weights=False))


def test_resume_skips_preset_transfer(tmp_path):
    """With resume_from_checkpoint set, initial_params must not be
    shipped to the device at all (it would be immediately overwritten)."""
    from ray_lightning_tpu.core.loop import FitConfig, run_fit
    from ray_lightning_tpu.models import BoringModel, BoringDataModule

    x_dm = BoringDataModule()
    cfg = FitConfig(max_epochs=1, seed=0, default_root_dir=str(tmp_path))
    m = BoringModel()
    run_fit(m, x_dm, cfg, callbacks=[])
    p = str(tmp_path / "b.ckpt")
    m.trainer.save_checkpoint(p)

    class Exploding(dict):
        """initial_params stand-in that detonates on any tree access."""

        def __iter__(self):
            raise AssertionError("preset consumed despite resume")

    m2 = BoringModel()
    m2.initial_params = Exploding()
    cfg2 = FitConfig(max_epochs=2, seed=0, default_root_dir=str(tmp_path),
                     resume_from_checkpoint=p)
    run_fit(m2, x_dm, cfg2, callbacks=[])  # must not touch the preset


def test_export_roundtrip_logits_parity():
    """Train here, serve with HF: export reproduces the in-framework
    logits, and import(export(x)) is the identity on weights."""
    from ray_lightning_tpu.utils import export_gpt2

    hf = _tiny_hf()
    cfg, params = import_gpt2(hf)
    # Perturb so we are not merely exporting what we imported.
    params["blocks"]["mlp_in_w"] = params["blocks"]["mlp_in_w"] + 0.01

    model = GPT(cfg, attn_impl="xla")
    model.precision = "f32"
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 16)).astype(np.int64)
    ours = np.asarray(jax.jit(model.forward)(
        params, jnp.asarray(tokens, jnp.int32)))

    exported = export_gpt2(params, cfg)
    with torch.no_grad():
        ref = exported(torch.from_numpy(tokens)).logits.numpy()
    np.testing.assert_allclose(ref, ours, rtol=2e-4, atol=2e-4)

    cfg2, params2 = import_gpt2(exported)
    assert cfg2 == cfg
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(params2)):
        np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6, atol=1e-6)


def test_export_rejects_unmerged_lora():
    from ray_lightning_tpu.models import GPT as _GPT
    from ray_lightning_tpu.models.gpt import GPTConfig as _Cfg
    from ray_lightning_tpu.utils import export_gpt2

    cfg = _Cfg(vocab_size=97, n_layer=1, n_head=2, d_model=32,
               seq_len=16, lora_rank=2)
    params = _GPT(cfg).init_params(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="merge_lora"):
        export_gpt2(jax.device_get(params), cfg)


def test_export_rejects_moe_and_wide_mlp():
    from ray_lightning_tpu.models.gpt import GPTConfig as _Cfg
    from ray_lightning_tpu.utils import export_gpt2

    with pytest.raises(ValueError, match="MoE"):
        export_gpt2({"blocks": {}}, _Cfg.tiny_moe(n_experts=2))
    with pytest.raises(ValueError, match="mlp_ratio"):
        export_gpt2({"blocks": {}}, _Cfg(vocab_size=64, n_layer=1,
                                         n_head=2, d_model=32, seq_len=16,
                                         mlp_ratio=2))
