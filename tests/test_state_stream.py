"""State-stream roundtrip tests (≙ reference weight-transfer at util.py:71-90)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.utils.state_stream import (
    load_state_stream,
    to_state_stream,
    tree_from_bytes,
    tree_to_bytes,
)


def _assert_trees_equal(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        if isinstance(x, (int, float, bool, str)) or x is None:
            assert x == y
        else:
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip_nested_pytree():
    tree = {
        "params": {
            "dense": {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.zeros(4)},
            "ln": {"scale": np.ones(7, dtype=np.float32)},
        },
        "step": 3,
        "lr": 1e-3,
        "note": "hello",
        "none_leaf": None,
    }
    out = tree_from_bytes(tree_to_bytes(tree))
    _assert_trees_equal(tree, out)


def test_roundtrip_bfloat16_and_int_dtypes():
    tree = {
        "bf16": jnp.ones((4, 4), dtype=jnp.bfloat16) * 1.5,
        "i32": jnp.arange(5, dtype=jnp.int32),
        "u8": np.array([1, 2, 255], dtype=np.uint8),
        "bool": np.array([True, False]),
    }
    out = tree_from_bytes(tree_to_bytes(tree))
    assert str(np.asarray(out["bf16"]).dtype) == "bfloat16"
    _assert_trees_equal(tree, out)


def test_load_with_device_put():
    tree = {"w": np.ones((2, 2), dtype=np.float32)}
    stream = to_state_stream(tree)
    loaded = load_state_stream(stream, device=jax.devices()[0])
    leaf = loaded["w"]
    assert isinstance(leaf, jax.Array)
    assert leaf.devices() == {jax.devices()[0]}


def test_stream_is_topology_independent():
    # Save from a sharded array (8-device mesh), restore on a single device.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    x = jax.device_put(
        jnp.arange(16.0).reshape(8, 2), NamedSharding(mesh, P("data", None))
    )
    stream = to_state_stream({"x": x})
    restored = load_state_stream(stream, device=jax.devices()[0])
    np.testing.assert_array_equal(
        np.asarray(restored["x"]), np.arange(16.0).reshape(8, 2)
    )


def test_empty_tree():
    assert tree_from_bytes(tree_to_bytes({})) == {}
