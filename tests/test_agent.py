"""Node agent + RemoteBackend: the laptop-driver / multi-host launch path.

≙ the reference's Ray Client tests (``tests/test_client*.py``,
``README.md:82-95``): drive the full stack through a network hop — here
agents on localhost stand in for remote TPU hosts, exactly how
``ray_start_client_server`` emulates a remote cluster in-process.
"""

import numpy as np
import pytest

from ray_lightning_tpu.cluster.agent import AgentClient, AgentError, NodeAgent
from ray_lightning_tpu.cluster.backend import RemoteBackend, get_backend
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models.boring import BoringDataModule, BoringModel
from ray_lightning_tpu.parallel.strategies import RayStrategy


@pytest.fixture
def agent():
    a = NodeAgent(host="127.0.0.1", port=0, token="secret")
    a.start()
    yield a
    a.shutdown()


def _add(a, b):
    return a + b


def test_agent_spawns_working_actor(agent):
    backend = RemoteBackend([f"127.0.0.1:{agent.port}"], token="secret")
    try:
        actor = backend.create_actor("remote-0")
        assert actor.execute(_add, 2, 40) == 42
        assert actor.is_alive()
    finally:
        backend.shutdown()


def test_agent_rejects_bad_token(agent):
    with pytest.raises(AgentError, match="bad token"):
        AgentClient(f"127.0.0.1:{agent.port}", token="wrong")


def test_agent_kill_reaps_child(agent):
    client = AgentClient(f"127.0.0.1:{agent.port}", token="secret")
    backend = RemoteBackend([f"127.0.0.1:{agent.port}"], token="secret")
    try:
        actor = backend.create_actor("remote-kill")
        pid = actor._proc.pid
        assert client.poll(pid) is None  # running
        actor.kill()
        assert client.poll(pid) is not None
    finally:
        backend.shutdown()
        client.close()


def test_get_backend_passes_instances_through(agent):
    backend = RemoteBackend([f"127.0.0.1:{agent.port}"], token="secret")
    try:
        assert get_backend(backend) is backend
    finally:
        backend.shutdown()


@pytest.mark.slow  # tier-1 diet (round 11): see pytest.ini 'slow'
def test_user_owned_backend_survives_fit_teardown(agent):
    """A caller-provided backend instance must remain usable after fit
    (the strategy only owns backends it constructed itself)."""
    backend = RemoteBackend([f"127.0.0.1:{agent.port}"], token="secret")
    try:
        for _ in range(2):
            trainer = Trainer(
                strategy=RayStrategy(num_workers=1, backend=backend),
                max_epochs=1,
                enable_checkpointing=False,
                limit_train_batches=1,
                limit_val_batches=1,
            )
            trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
            assert np.isfinite(trainer.callback_metrics["train_loss"])
    finally:
        backend.shutdown()


def test_remote_backend_fit_end_to_end(agent):
    """Full trainer.fit through the agent hop, 2 workers forming one mesh
    (≙ reference test_client.py running the examples through Ray Client)."""
    backend = RemoteBackend([f"127.0.0.1:{agent.port}"], token="secret")
    trainer = Trainer(
        strategy=RayStrategy(num_workers=2, backend=backend),
        max_epochs=1,
        enable_checkpointing=False,
        limit_train_batches=2,
        limit_val_batches=1,
    )
    trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
    assert np.isfinite(trainer.callback_metrics["train_loss"])
    assert trainer.params is not None


def test_agent_on_real_interface_fit(tmp_path):
    """Agent + queue + actor dial-back over the node's REAL (non-loopback)
    interface: the exact TCP paths a multi-host deployment uses (VERDICT
    r3 weak #7 — everything else binds loopback).  Skipped when the
    sandbox has no routable non-loopback address."""
    import socket

    from ray_lightning_tpu.cluster import rpc as rpc_mod

    ip = rpc_mod.get_node_ip()
    if ip.startswith("127."):
        pytest.skip("no non-loopback interface available")
    # Confirm the address is actually bindable+connectable in this netns.
    try:
        probe = socket.socket()
        probe.bind((ip, 0))
        port = probe.getsockname()[1]
        probe.listen(1)
        c = socket.create_connection((ip, port), timeout=2)
        c.close()
        probe.close()
    except OSError:
        pytest.skip(f"interface {ip} not connectable in this sandbox")

    agent = NodeAgent(host=ip, port=0, token="secret")
    agent.start()
    try:
        backend = RemoteBackend([f"{ip}:{agent.port}"], token="secret")
        trainer = Trainer(
            strategy=RayStrategy(num_workers=2, backend=backend),
            max_epochs=1, default_root_dir=str(tmp_path),
            enable_checkpointing=False,
        )
        trainer.fit(BoringModel(), BoringDataModule(length=32,
                                                    batch_size=16))
        assert np.isfinite(trainer.callback_metrics["train_loss"])
    finally:
        agent.shutdown()
