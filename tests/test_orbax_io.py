"""Orbax interop bridges: ecosystem-format export/import round trips."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_lightning_tpu.utils.orbax_io import (
    ORBAX_INSTALLED, load_orbax, save_orbax,
)

pytestmark = pytest.mark.skipif(
    not ORBAX_INSTALLED, reason="orbax-checkpoint not installed"
)


def _tree():
    return {
        "params": {
            "w": jnp.arange(12.0, dtype=jnp.float32).reshape(3, 4),
            "b": jnp.ones((4,), jnp.bfloat16),
        },
        "step": jnp.int32(7),
    }


def test_round_trip(tmp_path):
    tree = _tree()
    p = save_orbax(str(tmp_path / "ckpt"), tree)
    back = load_orbax(p)
    flat_a = jax.tree_util.tree_leaves_with_path(tree)
    flat_b = jax.tree_util.tree_leaves_with_path(back)
    assert [k for k, _ in flat_a] == [k for k, _ in flat_b]
    for (_, a), (_, b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.asarray(a).dtype == np.asarray(b).dtype


def test_restore_onto_mesh_shardings(tmp_path):
    """A checkpoint written unsharded restores directly onto a 2x4 mesh
    with NamedShardings — the cross-topology property."""
    tree = {"w": jnp.arange(64.0, dtype=jnp.float32).reshape(8, 8)}
    p = save_orbax(str(tmp_path / "ckpt"), tree)

    mesh = Mesh(mesh_utils.create_device_mesh((2, 4)), ("data", "tensor"))
    sh = NamedSharding(mesh, P("data", "tensor"))
    target = {
        "w": jax.ShapeDtypeStruct((8, 8), jnp.float32, sharding=sh)
    }
    back = load_orbax(p, target=target)
    assert back["w"].sharding == sh
    np.testing.assert_array_equal(
        np.asarray(back["w"]), np.asarray(tree["w"])
    )


def test_trained_state_round_trips(tmp_path):
    """Export a real trained TrainState's pytree and re-import it."""
    from ray_lightning_tpu.core.module import TrainState
    from ray_lightning_tpu.models.boring import BoringModel

    m = BoringModel()
    params = m.init_params(jax.random.PRNGKey(0))
    state = TrainState.create(params, m.configure_optimizers())
    tree = {"params": state.params, "opt_state": state.opt_state,
            "step": state.step}
    p = save_orbax(str(tmp_path / "state"), tree)
    back = load_orbax(p)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(tree),
        jax.tree_util.tree_leaves_with_path(back),
    ):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_overwrite_guard(tmp_path):
    tree = _tree()
    p = save_orbax(str(tmp_path / "c"), tree)
    with pytest.raises(Exception):
        save_orbax(p, tree)  # no overwrite without force
    save_orbax(p, tree, overwrite=True)
