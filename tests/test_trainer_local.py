"""Local (in-process) Trainer integration tests on the 8-device CPU mesh.

≙ the reference's CPU integration tier (``test_ddp.py`` run with
``ray.init(num_cpus=N)``): weights-change, ckpt roundtrip, accuracy,
early stopping, metrics fidelity — all against LocalStrategy first, which
exercises the full loop/step/sharding machinery without actors.
"""

import numpy as np
import pytest

import jax

from ray_lightning_tpu.core.callbacks import (
    Callback,
    EarlyStopping,
    ModelCheckpoint,
)
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models import (
    BoringDataModule,
    BoringModel,
    XORDataModule,
    XORModel,
)
from ray_lightning_tpu.parallel.strategies import LocalStrategy

from utils import get_trainer, load_test, predict_test, train_test


def test_train_weights_change(tmp_path):
    trainer = get_trainer(LocalStrategy(), max_epochs=2, tmp_path=tmp_path)
    train_test(trainer, BoringModel(), BoringDataModule())


def test_checkpoint_roundtrip(tmp_path):
    trainer = get_trainer(LocalStrategy(), max_epochs=1, tmp_path=tmp_path)
    load_test(trainer, BoringModel(), BoringDataModule(), tmp_path)


def test_xor_learns(tmp_path):
    trainer = get_trainer(LocalStrategy(), max_epochs=12, tmp_path=tmp_path)
    predict_test(trainer, XORModel(), XORDataModule())


def test_predict_returns_rows(tmp_path):
    trainer = get_trainer(LocalStrategy(), max_epochs=4, tmp_path=tmp_path)
    trainer.fit(XORModel(), XORDataModule())
    preds = trainer.predict(XORModel(), XORDataModule())
    assert preds.ndim == 1 and len(preds) > 0
    assert set(np.unique(preds)).issubset({0, 1})


def test_metrics_populated(tmp_path):
    trainer = get_trainer(LocalStrategy(), max_epochs=1, tmp_path=tmp_path)
    trainer.fit(BoringModel(), BoringDataModule())
    # ≙ reference metrics-fidelity test (test_ddp.py:326-350)
    assert "train_loss" in trainer.callback_metrics
    assert "val_loss" in trainer.callback_metrics
    assert np.isfinite(trainer.callback_metrics["train_loss"])


def test_early_stopping(tmp_path):
    # ≙ reference test_ddp.py:289-308 — must stop before max_epochs.
    es = EarlyStopping(monitor="val_loss", patience=1, min_delta=10.0)
    trainer = get_trainer(
        LocalStrategy(), max_epochs=50, tmp_path=tmp_path, callbacks=[es]
    )
    trainer.fit(BoringModel(), BoringDataModule())
    assert trainer.epochs_run < 50


def test_model_checkpoint_best_path(tmp_path):
    ckpt = ModelCheckpoint(
        dirpath=str(tmp_path / "ckpts"), monitor="val_loss", mode="min"
    )
    trainer = get_trainer(
        LocalStrategy(),
        max_epochs=3,
        tmp_path=tmp_path,
        callbacks=[ckpt],
        enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), BoringDataModule())
    assert ckpt.best_model_path
    assert trainer.best_model_path == ckpt.best_model_path
    import os

    assert os.path.exists(ckpt.best_model_path)


def test_resume_from_checkpoint(tmp_path):
    # ≙ reference resume test (test_ddp_sharded.py:84-105)
    ckpt_dir = str(tmp_path / "ckpts")
    trainer = get_trainer(
        LocalStrategy(),
        max_epochs=2,
        tmp_path=tmp_path,
        callbacks=[ModelCheckpoint(dirpath=ckpt_dir)],
        enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), BoringDataModule())
    first_steps = trainer.global_step
    path = trainer.best_model_path

    resumed = get_trainer(
        LocalStrategy(),
        max_epochs=4,
        tmp_path=tmp_path,
        resume_from_checkpoint=path,
    )
    resumed.fit(BoringModel(), BoringDataModule())
    assert resumed.global_step > first_steps
    assert resumed.epochs_run == 4


def test_validate_without_fit(tmp_path):
    # ≙ reference test-without-fit (test_ddp_sharded.py:108-116)
    trainer = get_trainer(LocalStrategy(), tmp_path=tmp_path)
    metrics = trainer.validate(BoringModel(), BoringDataModule())
    assert "val_loss" in metrics


def test_fast_dev_run(tmp_path):
    trainer = get_trainer(
        LocalStrategy(), tmp_path=tmp_path, fast_dev_run=True, max_epochs=10
    )
    trainer.fit(BoringModel(), BoringDataModule())
    assert trainer.global_step == 1


def test_max_steps(tmp_path):
    trainer = get_trainer(
        LocalStrategy(), max_epochs=10, tmp_path=tmp_path, max_steps=3
    )
    trainer.fit(BoringModel(), BoringDataModule())
    assert trainer.global_step == 3


def test_callback_hook_order(tmp_path):
    calls = []

    class Recorder(Callback):
        def setup(self, trainer, module, stage):
            calls.append("setup")

        def on_fit_start(self, trainer, module):
            calls.append("fit_start")

        def on_train_epoch_start(self, trainer, module):
            calls.append("epoch_start")

        def on_train_epoch_end(self, trainer, module):
            calls.append("epoch_end")

        def on_fit_end(self, trainer, module):
            calls.append("fit_end")

    trainer = get_trainer(
        LocalStrategy(),
        max_epochs=2,
        tmp_path=tmp_path,
        callbacks=[Recorder()],
        enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), BoringDataModule())
    assert calls == [
        "setup",
        "fit_start",
        "epoch_start",
        "epoch_end",
        "epoch_start",
        "epoch_end",
        "fit_end",
    ]


def test_module_dataloaders_without_datamodule(tmp_path):
    class SelfFeeding(BoringModel):
        def train_dataloader(self):
            return BoringDataModule().train_dataloader()

        def val_dataloader(self):
            return None

    trainer = get_trainer(LocalStrategy(), tmp_path=tmp_path)
    trainer.fit(SelfFeeding())
    assert trainer.params is not None


def test_max_steps_zero_trains_nothing(tmp_path):
    trainer = get_trainer(
        LocalStrategy(), max_epochs=2, tmp_path=tmp_path, max_steps=0,
        enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), BoringDataModule())
    assert trainer.global_step == 0


def test_checkpoint_monitor_none_keeps_latest(tmp_path):
    import os

    ckpt = ModelCheckpoint(dirpath=str(tmp_path / "c"), monitor=None,
                           save_top_k=1)
    trainer = get_trainer(
        LocalStrategy(), max_epochs=3, tmp_path=tmp_path, callbacks=[ckpt],
        enable_checkpointing=False,
    )
    trainer.fit(BoringModel(), BoringDataModule())
    files = os.listdir(tmp_path / "c")
    assert len(files) == 1
    assert "epoch=2" in files[0]  # the LATEST, not epoch 0
    assert ckpt.best_model_path.endswith(files[0])
