"""Multi-tenant LoRA multiplexing on the serving plane.

The correctness bar: a request decoded through adapter k must produce
EXACTLY the tokens ``generate()`` produces on the merged model — for
every tenant in a ≥4-adapter pool, in mixed-tenant batches, composing
with speculative decoding and the disaggregated prefill→handoff path —
while the compiled program set never grows with the tenant count
(zero steady-state recompiles across joins and hot-adds).  On top:
the pool's slot registry discipline (free-list reuse, typed misuse
errors, in-use removal refused), the adapter wire codec, and the
scheduler's per-tenant admission caps + deficit-round-robin fairness.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_lightning_tpu.models.generate import generate
from ray_lightning_tpu.models.gpt import (
    GPT, GPTConfig, extract_lora, synthetic_lora_adapter,
)
from ray_lightning_tpu.serve.engine import ServeConfig, ServeEngine
from ray_lightning_tpu.serve.lora import (
    AdapterPool, decode_adapter, encode_adapter, validate_adapter,
)
from ray_lightning_tpu.telemetry import compile_event_count

pytestmark = pytest.mark.serve

RANK = 4


def _rand_prompt(seed, length, vocab=128):
    rng = np.random.default_rng(seed)
    return rng.integers(1, vocab, size=(length,)).tolist()


def _ref_tokens(m, params, prompt, n):
    out = generate(m, params, jnp.asarray([prompt], jnp.int32), n)
    return np.asarray(out)[0, len(prompt):].tolist()


def _make_tenant(params, lora_cfg, seed):
    """One synthetic tenant via the shared builder (random non-zero
    factors → distinct greedy stream): ``(adapter, merged_params)``."""
    return synthetic_lora_adapter(params, lora_cfg,
                                  jax.random.PRNGKey(seed))


@pytest.fixture(scope="module")
def model():
    """Base model + 5 tenants (4 preloaded in tests, 1 for hot-add)."""
    import dataclasses

    cfg = GPTConfig(vocab_size=128, n_layer=2, n_head=4, d_model=64,
                    seq_len=64, warmup_steps=1)
    m = GPT(cfg, attn_impl="xla")
    params = m.init_params(jax.random.PRNGKey(0))
    lora_cfg = dataclasses.replace(cfg, lora_rank=RANK)
    tenants = {f"t{i}": _make_tenant(params, lora_cfg, seed=10 + i)
               for i in range(5)}
    adapters = {k: v[0] for k, v in tenants.items()}
    merged = {k: v[1] for k, v in tenants.items()}
    return m, params, adapters, merged


def _pool_engine(m, params, adapters, max_adapters=6, **cfg_kw):
    kw = dict(num_slots=6, block_size=8)
    kw.update(cfg_kw)
    return ServeEngine(
        m, params,
        ServeConfig(max_adapters=max_adapters, adapter_rank=RANK, **kw),
        adapters=adapters,
    )


# ---------------------------------------------------------------------------
# AdapterPool: slot registry discipline (host-side, one tiny pool)
# ---------------------------------------------------------------------------

class TestAdapterPool:
    @pytest.fixture()
    def pool(self, model):
        m, _, _, _ = model
        return AdapterPool(m.config, max_adapters=2, rank=RANK)

    def test_capacity_and_lifo_reuse(self, pool, model):
        _, _, adapters, _ = model
        s0 = pool.add("a", adapters["t0"])
        s1 = pool.add("b", adapters["t1"])
        assert 0 not in (s0, s1)  # slot 0 = the NULL/base adapter
        with pytest.raises(RuntimeError, match="pool full"):
            pool.add("c", adapters["t2"])
        pool.remove("b")
        assert pool.add("c", adapters["t2"]) == s1  # LIFO reuse
        assert pool.names() == ["a", "c"]
        assert pool.loaded == 2 and pool.slots_free == 0
        assert pool.loads == 3 and pool.unloads == 1

    def test_replace_reuses_slot(self, pool, model):
        _, _, adapters, _ = model
        slot = pool.add("a", adapters["t0"])
        assert pool.add("a", adapters["t1"]) == slot
        assert pool.loaded == 1

    def test_typed_misuse(self, pool, model):
        m, _, adapters, _ = model
        with pytest.raises(KeyError):
            pool.remove("ghost")
        with pytest.raises(KeyError):
            pool.slot_of("ghost")
        with pytest.raises(ValueError, match="missing factor"):
            pool.add("a", {"qkv_a": np.zeros((1,))})
        bad = dict(adapters["t0"])
        bad["qkv_b"] = np.zeros((m.config.n_layer, RANK + 1,
                                 3 * m.config.d_model), np.float32)
        with pytest.raises(ValueError, match="rank"):
            pool.add("a", bad)
        with pytest.raises(ValueError, match="dict"):
            validate_adapter([1, 2], m.config, RANK)

    def test_snapshot_shape(self, pool, model):
        _, _, adapters, _ = model
        pool.add("a", adapters["t0"])
        snap = pool.snapshot()
        assert snap["loaded"] == 1 and snap["slots_free"] == 1
        assert snap["max_adapters"] == 2 and snap["rank"] == RANK
        assert snap["impl"] in ("xla", "pallas")


class TestAdapterCodec:
    def test_encode_decode_roundtrip(self, model):
        _, _, adapters, _ = model
        adapter = dict(adapters["t0"])
        blob = encode_adapter(adapter)
        back = decode_adapter({"type": "serve_adapter_load",
                               "name": "t0", "rank": RANK,
                               "data": blob})
        assert back["scale"] == pytest.approx(float(adapter["scale"]))
        for key in ("qkv_a", "qkv_b", "proj_a", "proj_b"):
            np.testing.assert_array_equal(
                np.asarray(back[key]), np.asarray(adapter[key])
            )

    def test_extract_requires_adapters(self, model):
        import dataclasses

        m, params, _, _ = model
        lora_cfg = dataclasses.replace(m.config, lora_rank=RANK)
        with pytest.raises(ValueError, match="no LoRA adapters"):
            extract_lora(params, lora_cfg)
        with pytest.raises(ValueError, match="lora_rank"):
            extract_lora(params, m.config)


# ---------------------------------------------------------------------------
# BGMV: both arms against a dense per-row reference
# ---------------------------------------------------------------------------

class TestBgmv:
    """``ops/lora.py``: the gathered-einsum arm everywhere, and the
    Pallas kernel under the interpreter off-TPU (same machinery every
    optional kernel uses), both against a dense per-row reference."""

    def _case(self, seed=0, W=5, d=16, r=4, k=12, N=3):
        rng = np.random.default_rng(seed)
        h = rng.standard_normal((W, d)).astype(np.float32)
        a = rng.standard_normal((N, d, r)).astype(np.float32)
        b = rng.standard_normal((N, r, k)).astype(np.float32)
        a[0] = 0.0
        b[0] = 0.0  # slot 0 = the NULL adapter
        ids = rng.integers(0, N, size=(W,)).astype(np.int32)
        ref = np.stack([h[w] @ a[ids[w]] @ b[ids[w]]
                        for w in range(W)])
        return h, a, b, ids, ref

    def test_xla_and_pallas_match_dense_reference(self):
        from ray_lightning_tpu.ops.lora import bgmv_pallas, bgmv_xla

        h, a, b, ids, ref = self._case()
        got_xla = np.asarray(bgmv_xla(*map(jnp.asarray, (h, a, b, ids))))
        np.testing.assert_allclose(got_xla, ref, rtol=1e-5, atol=1e-5)
        got_pl = np.asarray(
            bgmv_pallas(*map(jnp.asarray, (h, a, b, ids)))
        )
        np.testing.assert_allclose(got_pl, ref, rtol=1e-5, atol=1e-5)

    def test_null_slot_delta_is_exactly_zero(self):
        from ray_lightning_tpu.ops.lora import lora_delta

        h, a, b, _, _ = self._case()
        zero_ids = jnp.zeros((h.shape[0],), jnp.int32)
        for impl in ("xla", "pallas"):
            got = np.asarray(lora_delta(
                jnp.asarray(h), jnp.asarray(a), jnp.asarray(b),
                zero_ids, impl=impl,
            ))
            assert (got == 0.0).all(), impl

    def test_three_dim_form_repeats_ids_per_position(self):
        from ray_lightning_tpu.ops.lora import lora_delta

        h, a, b, ids, ref = self._case(W=6)
        B, T = 2, 3
        got = np.asarray(lora_delta(
            jnp.asarray(h.reshape(B, T, -1)), jnp.asarray(a),
            jnp.asarray(b), jnp.asarray(ids.reshape(B, T)[:, 0]),
        ))
        # Per-SEQUENCE ids: rows of one sequence share its adapter.
        seq_ids = np.repeat(ids.reshape(B, T)[:, 0], T)
        ref_seq = np.stack([h[w] @ a[seq_ids[w]] @ b[seq_ids[w]]
                            for w in range(B * T)])
        np.testing.assert_allclose(
            got.reshape(B * T, -1), ref_seq, rtol=1e-5, atol=1e-5
        )

    def test_resolve_respects_forced_arm(self, monkeypatch):
        from ray_lightning_tpu.ops import lora as ops_lora

        monkeypatch.setenv("RLT_LORA_BGMV", "pallas")
        assert ops_lora.resolve_bgmv_impl(16, 4, 48, jnp.float32) \
            == "pallas"
        monkeypatch.setenv("RLT_LORA_BGMV", "xla")
        assert ops_lora.resolve_bgmv_impl(16, 4, 48, jnp.float32) \
            == "xla"
        monkeypatch.delenv("RLT_LORA_BGMV")
        # Off-TPU the gather is the selected path.
        assert ops_lora.resolve_bgmv_impl(16, 4, 48, jnp.float32) \
            == "xla"


# ---------------------------------------------------------------------------
# Engine: per-tenant greedy parity + the zero-recompile contract
# ---------------------------------------------------------------------------

class TestEnginePool:
    def test_four_tenant_mixed_batch_parity(self, model):
        """Acceptance bar: adapter k's engine output is token-for-token
        generate() on the merged model, for every tenant of a 4-adapter
        pool — submitted as ONE mixed batch alongside a base request."""
        m, params, adapters, merged = model
        pre = {k: adapters[k] for k in ("t0", "t1", "t2", "t3")}
        eng = _pool_engine(m, params, pre)
        prompt = _rand_prompt(1, 8)
        try:
            handles = {k: eng.submit(prompt, 8, adapter=k) for k in pre}
            handles["base"] = eng.submit(prompt, 8)
            eng.run_until_idle()
            outs = {k: h.result(0) for k, h in handles.items()}
        finally:
            eng.stop()
        assert outs["base"] == _ref_tokens(m, params, prompt, 8)
        streams = set()
        for k in pre:
            ref = _ref_tokens(m, merged[k], prompt, 8)
            assert outs[k] == ref, k
            streams.add(tuple(ref))
        # The tenants must actually be distinct models, or the parity
        # above proves nothing about per-slot application.
        assert len(streams) > 1

    def test_zero_recompiles_across_joins_and_hot_add(self, model):
        m, params, adapters, merged = model
        pre = {k: adapters[k] for k in ("t0", "t1", "t2", "t3")}
        eng = _pool_engine(m, params, pre)
        prompt = _rand_prompt(2, 8)
        try:
            # Warm every program (submit + drive, not generate(): its
            # wall-clock result timeout can expire under whole-suite
            # load while XLA compiles the program set).
            eng.submit(prompt, 4)
            eng.run_until_idle()
            before = compile_event_count()
            handles = [eng.submit(_rand_prompt(3 + i, 8), 6, adapter=k)
                       for i, k in enumerate(pre)]
            eng.add_adapter("t4", adapters["t4"])   # hot join
            handles.append(eng.submit(prompt, 6, adapter="t4"))
            eng.run_until_idle()
            assert all(h.done() for h in handles)
            assert compile_event_count() - before == 0
            assert handles[-1].result(0) == _ref_tokens(
                m, merged["t4"], prompt, 6
            )
        finally:
            eng.stop()

    def test_unknown_and_pool_less_rejections(self, model):
        m, params, adapters, _ = model
        eng = _pool_engine(m, params, {"t0": adapters["t0"]})
        try:
            with pytest.raises(ValueError, match="unknown adapter"):
                eng.submit([1, 2, 3], 4, adapter="ghost")
        finally:
            eng.stop()
        plain = ServeEngine(m, params,
                            ServeConfig(num_slots=2, block_size=8))
        try:
            with pytest.raises(ValueError, match="no adapter pool"):
                plain.submit([1, 2, 3], 4, adapter="t0")
        finally:
            plain.stop()

    def test_config_misuse_is_typed(self, model):
        m, params, adapters, _ = model
        with pytest.raises(ValueError, match="max_adapters"):
            ServeEngine(m, params,
                        ServeConfig(num_slots=2, block_size=8),
                        adapters={"t0": adapters["t0"]})
        with pytest.raises(ValueError, match="adapter_rank"):
            ServeEngine(m, params,
                        ServeConfig(num_slots=2, block_size=8,
                                    max_adapters=2))

    def test_in_use_removal_refused_then_slot_reuse_serves_clean(
            self, model):
        """Removing (or replacing) an adapter a live request decodes
        through is refused; after completion the freed slot re-issued
        to a NEW tenant serves the new tenant's delta, not the old."""
        m, params, adapters, merged = model
        eng = _pool_engine(m, params, {"t0": adapters["t0"]},
                           max_adapters=1)
        prompt = _rand_prompt(4, 8)
        try:
            h = eng.submit(prompt, 8, adapter="t0")
            with pytest.raises(RuntimeError, match="drain"):
                eng.remove_adapter("t0")
            with pytest.raises(RuntimeError, match="mid-stream"):
                eng.add_adapter("t0", adapters["t1"])
            eng.run_until_idle()
            assert h.result(0) == _ref_tokens(m, merged["t0"], prompt, 8)
            eng.remove_adapter("t0")
            eng.add_adapter("t1", adapters["t1"])   # reuses the slot
            h2 = eng.submit(prompt, 8, adapter="t1")
            eng.run_until_idle()
            assert h2.result(0) == _ref_tokens(
                m, merged["t1"], prompt, 8
            )
        finally:
            eng.stop()

    def test_per_tenant_accounting_in_snapshot(self, model):
        m, params, adapters, _ = model
        from ray_lightning_tpu.telemetry.schema import (
            validate_serve_snapshot,
        )

        eng = _pool_engine(m, params, {"t0": adapters["t0"],
                                       "t1": adapters["t1"]})
        try:
            for k in ("t0", "t1"):
                eng.submit(_rand_prompt(5, 8), 4, adapter=k)
            eng.run_until_idle()
            snap = eng.snapshot()
        finally:
            eng.stop()
        assert validate_serve_snapshot(snap) == []
        assert snap["adapters"]["t0"]["tokens_out"] == 4
        assert snap["adapters"]["t1"]["completed"] == 1
        assert snap["gauges"]["lora_fairness_spread"] == 1.0
        assert snap["gauges"]["lora_adapters_loaded"] == 2


# ---------------------------------------------------------------------------
# Scheduler: per-tenant caps + deficit-round-robin grants (jax-free)
# ---------------------------------------------------------------------------

def _sched(num_slots=1, max_queue=16, per_adapter=None):
    from ray_lightning_tpu.serve.kv_cache import BlockAllocator
    from ray_lightning_tpu.serve.scheduler import Scheduler

    return Scheduler(num_slots, BlockAllocator(64), block_size=4,
                     max_blocks_per_seq=8, buckets=[4, 8],
                     max_queue=max_queue,
                     max_queue_per_adapter=per_adapter)


def _req(rid, adapter=None, preemptions=0):
    from ray_lightning_tpu.serve.scheduler import Request

    r = Request(rid=rid, prompt=[1, 2, 3], max_new_tokens=1,
                adapter=adapter)
    r.preemptions = preemptions
    return r


class TestSchedulerFairness:
    def _drain_one(self, s):
        """Admit one request on the 1-slot scheduler, complete it, and
        return its rid."""
        admissions, _ = s.poll(now=0.0)
        assert len(admissions) == 1
        slot, req, _ = admissions[0]
        assert s.append_token(slot, 7)  # max_new_tokens=1 -> done
        s.finish(slot)
        return req.rid

    def test_drr_rotates_across_tenants(self, s=None):
        """One tenant's burst cannot monopolize slot turnover: grants
        cycle a -> b -> c -> a... while FIFO holds within a tenant."""
        s = _sched()
        for rid, tenant in (("a1", "a"), ("a2", "a"), ("a3", "a"),
                            ("b1", "b"), ("c1", "c")):
            assert s.submit(_req(rid, adapter=tenant))
        order = [self._drain_one(s) for _ in range(5)]
        assert order == ["a1", "b1", "c1", "a2", "a3"]

    def test_base_traffic_is_a_tenant_key_too(self):
        """None (the base model) cycles like any other key — pre-LoRA
        single-key traffic reduces exactly to FIFO."""
        s = _sched()
        for rid, tenant in (("n1", None), ("n2", None), ("a1", "a")):
            assert s.submit(_req(rid, adapter=tenant))
        assert [self._drain_one(s) for _ in range(3)] \
            == ["n1", "a1", "n2"]
        s2 = _sched()
        for rid in ("x1", "x2", "x3"):
            assert s2.submit(_req(rid))
        assert [self._drain_one(s2) for _ in range(3)] \
            == ["x1", "x2", "x3"]

    def test_preempted_outranks_fairness(self):
        s = _sched()
        assert s.submit(_req("a1", adapter="a"))
        assert s.submit(_req("b1", adapter="b", preemptions=1))
        # DRR alone would grant "a1" first (canonical order); the
        # preempted request's front-requeue contract wins.
        assert self._drain_one(s) == "b1"

    def test_per_adapter_cap_is_per_tenant(self):
        from ray_lightning_tpu.serve.scheduler import RequestState

        s = _sched(max_queue=16, per_adapter=2)
        assert s.submit(_req("a1", adapter="a"))
        assert s.submit(_req("a2", adapter="a"))
        burst = _req("a3", adapter="a")
        assert not s.submit(burst)          # tenant a saturated its cap
        assert burst.state is RequestState.REJECTED
        assert s.submit(_req("b1", adapter="b"))   # b keeps its seats
        assert s.submit(_req("n1"))                # and so does base

    def test_engine_surfaces_per_adapter_rejection(self, model):
        m, params, adapters, _ = model
        eng = _pool_engine(m, params, {"t0": adapters["t0"]},
                           num_slots=1, max_queue_per_adapter=1)
        try:
            # Slot busy + one queued for t0: the next t0 submission
            # must bounce while the pool-wide queue still has room.
            eng.submit(_rand_prompt(6, 8), 8, adapter="t0")
            eng.submit(_rand_prompt(7, 8), 8, adapter="t0")
            h = eng.submit(_rand_prompt(8, 8), 8, adapter="t0")
            assert h.status == "rejected"
            h2 = eng.submit(_rand_prompt(9, 8), 8)   # base unaffected
            eng.run_until_idle()
            assert h2.done()
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# Composition: speculative decoding + the disaggregated handoff path
# ---------------------------------------------------------------------------

class TestSpecCompose:
    def test_spec_engine_matches_merged_generate(self, model):
        """The TARGET carries the tenant's adapter; a base-model draft
        merely proposes, and greedy verification corrects every
        disagreement — so spec output through adapter k is still
        token-for-token the merged model's, at zero steady-state
        recompiles."""
        from ray_lightning_tpu.serve.draft import early_exit_draft

        m, params, adapters, merged = model
        draft, draft_params = early_exit_draft(m, params, 1)
        pre = {k: adapters[k] for k in ("t0", "t1")}
        eng = ServeEngine(
            m, params,
            ServeConfig(num_slots=4, block_size=8, spec_k=2,
                        max_adapters=4, adapter_rank=RANK),
            draft_module=draft, draft_params=draft_params,
            adapters=pre,
        )
        prompt = _rand_prompt(11, 8)
        try:
            # Warm EVERY spec-engine program deterministically: the
            # default-spec request compiles prefill/draft/verify, and
            # the spec=0 request forces the plain-decode FALLBACK tick
            # (+ its draft-cache mirror ops) — whether a spec request
            # alone ever hits the fallback depends on its acceptance
            # pattern, which must not decide what the recompile pin
            # below sees.
            eng.submit(prompt, 4)
            eng.submit(prompt, 4, spec=0)
            eng.run_until_idle()
            before = compile_event_count()
            handles = {k: eng.submit(prompt, 8, adapter=k) for k in pre}
            handles["base"] = eng.submit(prompt, 8)
            eng.run_until_idle()
            assert compile_event_count() - before == 0
            for k in pre:
                assert handles[k].result(0) == _ref_tokens(
                    m, merged[k], prompt, 8
                ), k
            assert handles["base"].result(0) == _ref_tokens(
                m, params, prompt, 8
            )
        finally:
            eng.stop()


class TestHandoffLoadRace:
    def test_handoff_outrunning_adapter_load_defers_not_fails(
            self, model):
        """The prefill worker's handoff rides its OWN connection and
        can reach the replica before the router's serve_adapter_load
        frame: the engine must DEFER the admission (bounded) until the
        load lands — never fail a valid request 'unknown adapter' —
        and the deferred import must still match the merged model."""
        import time as _time

        from ray_lightning_tpu.cluster.queue import DriverQueue
        from ray_lightning_tpu.serve.dist.handoff import (
            make_adapter_load_item, make_dispatch_item, request_fields,
        )
        from ray_lightning_tpu.serve.dist.prefill import PrefillRunner
        from ray_lightning_tpu.serve.lora import encode_adapter

        m, params, adapters, merged = model
        scfg = ServeConfig(num_slots=2, block_size=8, max_adapters=2,
                           adapter_rank=RANK)
        eng = ServeEngine(m, params, scfg)
        replies = DriverQueue()
        beats = DriverQueue()
        worker = PrefillRunner("pw", m, params, scfg, beats.handle,
                               beat_s=60.0)
        worker.adapters.add("t0", adapters["t0"])
        handle = eng.queue_handle()
        prompt = _rand_prompt(13, 8)
        try:
            req = request_fields(
                "r1", prompt, 8,
                reply=(replies.handle.host, replies.handle.port),
                sample_seed=0, adapter="t0",
            )
            worker._inbox.handle.put(
                make_dispatch_item(req, (handle.host, handle.port))
            )
            assert worker.step(timeout=10)
            # The handoff is in flight to the engine; its tenant is NOT
            # loaded.  Drive until the engine has seen (and deferred)
            # it — not replied invalid.
            deadline = _time.monotonic() + 10
            while not eng._deferred_inbox \
                    and _time.monotonic() < deadline:
                eng.step()
                _time.sleep(0.01)
            assert eng._deferred_inbox, "handoff was not deferred"
            assert eng.stats.counters.get("completed", 0) == 0
            # The (late) load frame lands; the next drains admit it.
            handle.put(make_adapter_load_item(
                "t0", RANK, data=encode_adapter(adapters["t0"]),
            ))
            done = None
            deadline = _time.monotonic() + 30
            while done is None and _time.monotonic() < deadline:
                eng.step()
                try:
                    item = replies.get_nowait()
                except Exception:  # noqa: BLE001 - empty queue
                    _time.sleep(0.01)
                    continue
                if item.get("type") == "serve_done":
                    done = item
            assert done is not None and done["status"] == "finished"
            assert done["tokens"] == _ref_tokens(
                m, merged["t0"], prompt, 8
            )
            assert eng.stats.counters["kv_imports"] == 1
        finally:
            worker.close()
            beats.shutdown()
            eng.stop()
            replies.shutdown()


class TestDisaggCompose:
    def test_fleet_routes_hot_loads_and_matches_merged(self, model):
        """Through the full prefill → KV-handoff → decode path: the
        router hot-loads the tenant onto BOTH the prefill worker and
        the decode replica (lazy serve_adapter_load frames), placement
        prefers holders, and the streamed tokens are the merged
        model's."""
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.serve.dist import launch_inproc_fleet

        m, params, adapters, merged = model
        pre = {k: adapters[k] for k in ("t0", "t1")}
        fleet = launch_inproc_fleet(
            m, params,
            ServeConfig(num_slots=4, block_size=8, max_adapters=4,
                        adapter_rank=RANK),
            n_replicas=1, n_prefill=1, lost_after_s=30.0,
            adapters=pre,
        )
        client = ServeClient(fleet.queue_handle())
        prompt = _rand_prompt(12, 8)
        try:
            rids = {k: client.submit(prompt, 8, adapter=k) for k in pre}
            rids["base"] = client.submit(prompt, 8)
            outs = {k: client.result(rid, timeout=240)
                    for k, rid in rids.items()}
            for k in pre:
                assert outs[k] == _ref_tokens(m, merged[k], prompt, 8), k
            assert outs["base"] == _ref_tokens(m, params, prompt, 8)
            # Unknown tenant: the router's typed invalid, never a
            # silent base-model stream.
            with pytest.raises(ValueError, match="unknown adapter"):
                client.result(client.submit(prompt, 4, adapter="ghost"),
                              timeout=60)
            snap = fleet.router.snapshot()
            # One load per member per tenant, at most (lazy + cached).
            assert 2 <= snap["counters"]["adapter_loads_sent"] <= 4
            from ray_lightning_tpu.telemetry.schema import (
                validate_router_snapshot,
            )

            assert validate_router_snapshot(snap) == []
            assert snap["replicas"][0].get("adapters", 0) >= 2
            assert snap["workers"][0].get("adapters", 0) >= 2
        finally:
            client.close()
            fleet.close()
