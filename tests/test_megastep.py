"""Megastep execution (ISSUE 5): K micro-steps fused into one compiled
scan must train the SAME fit as the per-step loop.

Parity is pinned on the 8-device CPU mesh (conftest) across every
semantic surface the stride touches: loss/metric/params trajectories,
``global_step``/``micro_step`` accounting, gradient accumulation,
partial final strides, checkpoint cadence, EMA shadows, mid-stride
preemption drains, and pinned chaos injections (which lower K to 1
around the fault).  Plus the prefetch-lifecycle regression: a fit that
raises mid-epoch must never leak its ``rlt-prefetch`` producer thread
into the next attempt.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax

from ray_lightning_tpu.core.callbacks import (
    Callback,
    CSVLogger,
    ExponentialMovingAverage,
    ModelCheckpoint,
)
from ray_lightning_tpu.core.loop import (
    FitConfig,
    _resolve_megastep,
    init_train_state,
)
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.fault import drain as drain_mod
from ray_lightning_tpu.fault.drain import PreemptedError, sync_point_crossed
from ray_lightning_tpu.fault.inject import FaultInjected, step_fault_in_range
from ray_lightning_tpu.models.boring import BoringDataModule, BoringModel
from ray_lightning_tpu.parallel import step_fns
from ray_lightning_tpu.parallel import sharding as shardlib
from ray_lightning_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_lightning_tpu.parallel.strategies import LocalStrategy

pytestmark = pytest.mark.megastep

K = 4
BATCHES = 16  # micro-batches per epoch (length/batch_size below)


def _fit(tmp_path, megastep, *, lr=0.05, callbacks=None, **kw):
    kw.setdefault("max_epochs", 1)
    trainer = Trainer(
        strategy=LocalStrategy(megastep=megastep),
        enable_checkpointing=False,
        default_root_dir=str(tmp_path),
        callbacks=list(callbacks or []),
        **kw,
    )
    trainer.fit(
        BoringModel(lr=lr), BoringDataModule(length=BATCHES * 16,
                                             batch_size=16)
    )
    return trainer


def _assert_params_close(a, b, tol=1e-5):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(x, y, rtol=tol, atol=tol)


# -- make_multi_step vs K single steps ---------------------------------------

def _run_multi_vs_single(mesh):
    module = BoringModel(in_dim=16, out_dim=4, lr=0.1)
    tx = module.configure_optimizers()
    rng = jax.random.PRNGKey(7)
    raw = {"x": np.random.default_rng(0).standard_normal(
        (16, 16), dtype=np.float32)}

    state_s, sh = init_train_state(module, tx, mesh, 0, seed=0)
    state_m = init_train_state(module, tx, mesh, 0, seed=0)[0]
    single = step_fns.build_train_step(module, tx, mesh, state_shardings=sh)
    multi = step_fns.make_multi_step(
        module, tx, mesh, K, state_shardings=sh
    )
    if mesh is None:
        batch = raw
        kbatch = jax.tree_util.tree_map(lambda x: np.stack([x] * K), raw)
    else:
        batch = shardlib.make_global_batch(raw, mesh)
        kbatch = shardlib.make_global_stacked_batch([raw] * K, mesh)

    logs_seq = []
    for i in range(K):
        state_s, logs = single(state_s, batch, jax.random.fold_in(rng, i))
        logs_seq.append(float(logs["train_loss"]))
    state_m, aux = multi(state_m, kbatch, rng, np.int32(0))

    _assert_params_close(
        jax.device_get(state_s.params), jax.device_get(state_m.params)
    )
    # Stride-final logs == the last single step's logs.
    np.testing.assert_allclose(
        float(aux["last"]["train_loss"]), logs_seq[-1], rtol=1e-5
    )
    # On-device sum == sum of the per-step losses; all K finite.
    np.testing.assert_allclose(
        float(aux["sum"]["train_loss"]), sum(logs_seq), rtol=1e-5
    )
    assert float(aux["cnt"]["train_loss"]) == K


def test_multi_step_matches_singles_no_mesh():
    _run_multi_vs_single(None)


def test_multi_step_matches_singles_on_mesh():
    _run_multi_vs_single(build_mesh(MeshSpec()))


def test_multi_step_counts_nonfinite_like_host_accumulator():
    """A NaN loss inside the stride must land in the finite-count, not
    poison the on-device sum (the _RunningMeanLogs contract)."""
    class NaNAtStep(BoringModel):
        def training_step(self, params, batch, rng):
            import jax.numpy as jnp

            loss, logs = super().training_step(params, batch, rng)
            # Poison exactly one inner step: fold_in(rng, step) differs
            # per step, so key on the data instead — first batch row
            # sentinel set by the test below.
            poison = batch["x"][0, 0] > 1e5
            bad = jnp.where(poison, jnp.nan, logs["train_loss"])
            return loss, {"train_loss": bad}

    module = NaNAtStep(in_dim=8, out_dim=2, lr=0.0)
    tx = module.configure_optimizers()
    multi = step_fns.make_multi_step(module, tx, None, K)
    state = init_train_state(module, tx, None, 0, seed=0)[0]
    base = np.random.default_rng(0).standard_normal(
        (K, 4, 8)).astype(np.float32)
    base[2, 0, 0] = 1e6  # poison inner step 2
    _, aux = multi(state, {"x": base}, jax.random.PRNGKey(0), np.int32(0))
    assert float(aux["cnt"]["train_loss"]) == K - 1
    assert np.isfinite(float(aux["sum"]["train_loss"]))


# -- fit-level parity --------------------------------------------------------

def test_fit_parity_bundle(tmp_path):
    """One 2-epoch off/on fit pair carries the aligned-parity surface:
    step counters, epoch-mean metrics, final params, EMA compounding,
    checkpoint cadence, CSV cadence rows and dispatch counters — one
    compile per arm instead of one per concern (tier-1 wall budget)."""
    decay = 0.9
    snapshots = {}

    class SnapParams(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            if trainer.global_step % K == 0:
                snapshots[trainer.global_step] = jax.device_get(
                    trainer.state.params
                )

    arms = {}
    for name, mode, extra in (
        ("off", "off", [SnapParams()]),
        ("on", K, [ExponentialMovingAverage(decay=decay,
                                            swap_at_end=False)]),
    ):
        cbs = extra + [
            ModelCheckpoint(dirpath=str(tmp_path / f"{name}_ck")),
            CSVLogger(dirpath=str(tmp_path / f"csv_{name}")),
        ]
        arms[name] = (_fit(tmp_path / name, mode, max_epochs=2,
                           log_every_n_steps=4, callbacks=cbs), cbs)
    t_off, t_on = arms["off"][0], arms["on"][0]

    # Step accounting + metrics + trained params.
    assert t_on.global_step == t_off.global_step == 2 * BATCHES
    assert t_on.micro_step == t_off.micro_step == 2 * BATCHES
    assert t_on.callback_metrics["train_loss"] == pytest.approx(
        t_off.callback_metrics["train_loss"], rel=1e-5
    )
    _assert_params_close(t_off.state.params, t_on.state.params)

    # EMA follows the documented cadence contract EXACTLY: decay**K
    # compounded against stride-boundary params (== the per-step arm's
    # boundary snapshots, since the trains are param-parity).
    ema = arms["on"][1][0]
    steps = sorted(snapshots)
    expected = snapshots[steps[0]]
    d = decay ** K
    for gs in steps[1:]:
        expected = jax.tree_util.tree_map(
            lambda e, p: e * d + p * (1.0 - d), expected, snapshots[gs]
        )
    for x, y in zip(
        jax.device_get(jax.tree_util.tree_leaves(expected)),
        jax.device_get(jax.tree_util.tree_leaves(ema.ema_params)),
    ):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-6)

    # Checkpoint cadence: identical (epoch, global_step) filenames.
    assert (
        sorted(os.listdir(tmp_path / "off_ck"))
        == sorted(os.listdir(tmp_path / "on_ck"))
    )
    # CSV cadence (4 divides K): identical row counts.
    assert len(arms["on"][1][2].rows) == len(arms["off"][1][2].rows)

    # Dispatch counters: 2*16 micro-steps in 2*16/K stride dispatches.
    c_on = t_on.telemetry_report["counters"]
    assert c_on["megastep_dispatches"]["mean"] == 2 * BATCHES / K
    assert c_on["train_dispatches"]["mean"] == 2 * BATCHES / K
    c_off = t_off.telemetry_report["counters"]
    assert c_off["train_dispatches"]["mean"] == 2 * BATCHES
    assert "megastep_dispatches" not in c_off


def test_fit_parity_partial_final_stride(tmp_path):
    """limit=7 with K=4: one fused stride + 3 per-step fallbacks."""
    t_off = _fit(tmp_path / "off", "off", limit_train_batches=7)
    t_on = _fit(tmp_path / "on", K, limit_train_batches=7)
    assert t_on.global_step == t_off.global_step == 7
    assert t_on.micro_step == 7
    _assert_params_close(t_off.state.params, t_on.state.params)


def test_fit_parity_with_accumulation(tmp_path):
    """accum=2 runs INSIDE the scan (MultiSteps state is carry);
    global_step advances K/accum per stride."""
    t_off = _fit(tmp_path / "off", "off", accumulate_grad_batches=2)
    t_on = _fit(tmp_path / "on", K, accumulate_grad_batches=2)
    assert t_on.global_step == t_off.global_step == BATCHES // 2
    assert t_on.micro_step == BATCHES
    _assert_params_close(t_off.state.params, t_on.state.params)


def test_max_steps_means_max_steps(tmp_path):
    """max_steps=5 with K=4: one stride (4) + one single (1), exactly
    5 optimizer updates — parity with the per-step loop."""
    t_on = _fit(tmp_path / "on", K, max_epochs=5, max_steps=5)
    t_off = _fit(tmp_path / "off", "off", max_epochs=5, max_steps=5)
    assert t_on.global_step == t_off.global_step == 5
    _assert_params_close(t_off.state.params, t_on.state.params)


def test_epoch_mean_metrics_parity(tmp_path):
    """The epoch train_loss is the mean over ALL micro-steps — the
    on-device stride sums must agree with the host accumulator."""
    t_off = _fit(tmp_path / "off", "off", max_epochs=2)
    t_on = _fit(tmp_path / "on", K, max_epochs=2)
    for key in ("train_loss",):
        assert t_on.callback_metrics[key] == pytest.approx(
            t_off.callback_metrics[key], rel=1e-5
        )


def test_ema_parity(tmp_path):
    """EMA under megastep follows the documented cadence contract
    EXACTLY: the shadow compounds ``decay**K`` against stride-boundary
    params — the same trajectory as ``update_every_n_steps=K`` over the
    per-step fit's params (horizon-preserving; both trains are
    param-parity anyway, pinned above)."""
    decay = 0.9
    snapshots = {}

    class SnapParams(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            if trainer.global_step % K == 0:
                snapshots[trainer.global_step] = jax.device_get(
                    trainer.state.params
                )

    ema_on = ExponentialMovingAverage(decay=decay, swap_at_end=False)
    _fit(tmp_path / "off", "off", callbacks=[SnapParams()])
    _fit(tmp_path / "on", K, callbacks=[ema_on])

    # Expected: init at the first stride boundary, then decay**K blends
    # against each later boundary's params.
    steps = sorted(snapshots)
    expected = snapshots[steps[0]]
    d = decay ** K
    for gs in steps[1:]:
        expected = jax.tree_util.tree_map(
            lambda e, p: e * d + p * (1.0 - d), expected, snapshots[gs]
        )
    la = jax.device_get(jax.tree_util.tree_leaves(expected))
    lb = jax.device_get(jax.tree_util.tree_leaves(ema_on.ema_params))
    for x, y in zip(la, lb):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-6)


def test_checkpoint_cadence_parity(tmp_path):
    """ModelCheckpoint epochs see identical (epoch, global_step) under
    megastep — same filenames, same best path."""
    cb_off = ModelCheckpoint(dirpath=str(tmp_path / "off_ck"))
    cb_on = ModelCheckpoint(dirpath=str(tmp_path / "on_ck"))
    _fit(tmp_path / "off", "off", max_epochs=2, callbacks=[cb_off])
    _fit(tmp_path / "on", K, max_epochs=2, callbacks=[cb_on])
    assert (
        sorted(os.listdir(tmp_path / "off_ck"))
        == sorted(os.listdir(tmp_path / "on_ck"))
    )
    assert (
        os.path.basename(cb_off.best_model_path)
        == os.path.basename(cb_on.best_model_path)
    )


def test_csv_rows_on_cadence_crossings(tmp_path):
    """The logger fires on cadence CROSSINGS, not `% == 0` (megastep
    strides jump over exact multiples).  With the cadence dividing K
    the two modes produce identical rows; a non-dividing cadence
    rounds to stride boundaries — one row per crossed stride."""
    rows = {}
    for name, mode, cadence in (
        ("off4", "off", 4), ("on4", K, 4), ("on3", K, 3),
    ):
        logger = CSVLogger(dirpath=str(tmp_path / f"csv_{name}"))
        _fit(tmp_path / name, mode, log_every_n_steps=cadence,
             callbacks=[logger])
        rows[name] = len(logger.rows)
    # 16 batches, cadence 4: rows at 4/8/12/16 + epoch row + val row.
    assert rows["on4"] == rows["off4"] == 4 + 2
    # Cadence 3: per-stride rounding — strides end at 4/8/12/16, the
    # 12-boundary covers two cadence points (9 and 12) in one row.
    assert rows["on3"] == 4 + 2


def test_csv_cadence_stays_aligned_across_resume(tmp_path):
    """A resumed fit keeps CSV rows on the log_every_n_steps grid: the
    cadence anchor is the restore point, not zero — no spurious row on
    the first post-resume hook."""
    class DrainMid(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            if (trainer.micro_step >= 6
                    and not drain_mod.drain_requested()):
                drain_mod.request_drain("csv-cadence")

    with pytest.raises(PreemptedError) as err:
        _fit(tmp_path / "a", K, log_every_n_steps=8,
             callbacks=[DrainMid()])
    drain_mod.reset_drain()
    logger = CSVLogger(dirpath=str(tmp_path / "csv"))
    resumed = Trainer(
        strategy=LocalStrategy(megastep=K),
        enable_checkpointing=False,
        default_root_dir=str(tmp_path / "resume"),
        resume_from_checkpoint=err.value.checkpoint,
        log_every_n_steps=8,
        callbacks=[logger],
    )
    resumed.fit(
        BoringModel(lr=0.05),
        BoringDataModule(length=BATCHES * 16, batch_size=16),
    )
    # Drain landed at the stride-2 boundary (micro 8); the remaining
    # strides end at 12 and 16, and the only cadence-8 crossing left is
    # 16 — one step row, plus the epoch and val rows.  An anchor of 0
    # instead of the restore point would fire a spurious extra row on
    # the first post-resume stride (crossing(0, 12, 8) is true).
    assert len(logger.rows) == 1 + 2, [r.get("step") for r in logger.rows]


def test_dispatch_counters(tmp_path):
    """16 micro-steps in 4 stride dispatches — the counter behind the
    bench's dispatches_per_opt_step acceptance number."""
    t = _fit(tmp_path, K)
    counters = t.telemetry_report["counters"]
    assert counters["megastep_dispatches"]["mean"] == K
    assert counters["train_dispatches"]["mean"] == K  # all fused
    t2 = _fit(tmp_path / "off", "off")
    assert (
        t2.telemetry_report["counters"]["train_dispatches"]["mean"]
        == BATCHES
    )


# -- drain / chaos -----------------------------------------------------------

def test_mid_stride_drain_and_exact_resume(tmp_path):
    """A drain request landing mid-stride is honored at the next stride
    boundary; the resumed fit replays exactly the remaining batches
    (zero lost steps) and matches the uninterrupted trajectory."""
    class DrainLate(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            if trainer.micro_step >= 6:  # lands inside stride 2
                drain_mod.request_drain("test-preempt")

    with pytest.raises(PreemptedError) as err_info:
        _fit(tmp_path, K, callbacks=[DrainLate()])
    err = err_info.value
    assert err.step == 8, "drain must land at the stride boundary"
    assert err.checkpoint and os.path.exists(err.checkpoint)

    resumed = Trainer(
        strategy=LocalStrategy(megastep=K),
        enable_checkpointing=False,
        default_root_dir=str(tmp_path / "resume"),
        resume_from_checkpoint=err.checkpoint,
    )
    resumed.fit(
        BoringModel(lr=0.05),
        BoringDataModule(length=BATCHES * 16, batch_size=16),
    )
    assert resumed.micro_step == BATCHES
    assert resumed.global_step == BATCHES
    clean = _fit(tmp_path / "clean", K)
    _assert_params_close(clean.state.params, resumed.state.params)


def test_chaos_step_injection_fires_at_exact_inner_step(tmp_path):
    """A pinned exc@step:5 inside stride 2 lowers K to 1 around the
    injection and fires exactly at micro-step 5."""
    seen = []

    class Track(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            seen.append(trainer.micro_step)

    os.environ["RLT_FAULT"] = "exc@step:5,rank:0"
    try:
        with pytest.raises(FaultInjected):
            _fit(tmp_path, K, callbacks=[Track()])
    finally:
        os.environ.pop("RLT_FAULT", None)
    # Stride 1 fused (boundary hook at 4), stride 2 degraded to singles:
    # step 4 trains (hook at 5), then the fault fires BEFORE step 5.
    assert seen == [4, 5]


def test_strides_resume_after_once_fault_fired(tmp_path):
    """An exactly-once fault stops degrading strides after its marker
    lands — chaos runs keep megastep performance post-injection."""
    os.environ["RLT_FAULT"] = "exc@step:2,rank:0"
    os.environ["RLT_FAULT_STATE"] = str(tmp_path / "chaos")
    try:
        with pytest.raises(FaultInjected):
            _fit(tmp_path / "a", K)
        assert not step_fault_in_range(0, 100, epoch=0, rank=0)
        t = _fit(tmp_path / "b", K)  # trains through, fused again
        assert t.telemetry_report["counters"][
            "megastep_dispatches"]["mean"] == K
    finally:
        os.environ.pop("RLT_FAULT", None)
        os.environ.pop("RLT_FAULT_STATE", None)


def test_step_fault_in_range_matching():
    os.environ["RLT_FAULT"] = "crash@step:7,rank:1;hang@point:spawn"
    try:
        assert step_fault_in_range(0, 8, epoch=0, rank=1)
        # Rank pins do NOT narrow the degrade decision: strides shape
        # the compiled program's collective sequence, so every rank must
        # lower K around the injection or the mesh would run divergent
        # programs and hang.  fire() still honors the pin.
        assert step_fault_in_range(0, 8, epoch=0, rank=0)
        assert not step_fault_in_range(8, 16, epoch=0, rank=1)
        assert not step_fault_in_range(8, 16, epoch=0, rank=0)
    finally:
        os.environ.pop("RLT_FAULT", None)


def test_sync_point_crossed():
    # Per-step shape: crossing iff step % every == 0.
    assert [sync_point_crossed(s, s + 1, 8) for s in range(7, 9)] == [
        True, False,
    ]
    # Stride shape: one crossing per covered multiple.
    assert sync_point_crossed(4, 8, 8)
    assert not sync_point_crossed(8, 12, 8)
    assert sync_point_crossed(0, 16, 8)
    assert sync_point_crossed(5, 6, 1)  # every<=1: always


# -- prefetch lifecycle ------------------------------------------------------

def _prefetch_threads():
    return [t for t in threading.enumerate() if t.name == "rlt-prefetch"]


def _await_no_prefetch_threads(timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _prefetch_threads():
            return True
        time.sleep(0.05)
    return False


def test_prefetch_thread_joined_after_midfit_raise(tmp_path):
    """Drain raises and user exceptions mid-epoch must signal AND join
    the rlt-prefetch producer — the respawn/tuner-sweep leak
    regression: repeated raising fits in one process accumulate zero
    threads."""
    class Boom(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            raise RuntimeError("boom")

    class DrainNow(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            drain_mod.request_drain("leak-test")

    for i in range(3):
        with pytest.raises(RuntimeError):
            _fit(tmp_path / f"boom{i}", K, callbacks=[Boom()])
        assert _await_no_prefetch_threads(), "leaked rlt-prefetch thread"
    err = None
    with pytest.raises(PreemptedError) as err:
        _fit(tmp_path / "drain", K, callbacks=[DrainNow()])
    assert _await_no_prefetch_threads(), "leaked rlt-prefetch thread"
    # The elastic-respawn shape: resume from the drain ckpt in the SAME
    # process — the fresh fit must start with a clean producer slate.
    resumed = Trainer(
        strategy=LocalStrategy(megastep=K),
        enable_checkpointing=False,
        default_root_dir=str(tmp_path / "resume"),
        resume_from_checkpoint=err.value.checkpoint,
    )
    resumed.fit(
        BoringModel(lr=0.05),
        BoringDataModule(length=BATCHES * 16, batch_size=16),
    )
    assert resumed.micro_step == BATCHES
    assert _await_no_prefetch_threads()


# -- crash forensics vs the async log fetch ----------------------------------

def test_crash_bundle_carries_latest_log_boundary(tmp_path):
    """The async log fetch must not cost crash forensics their
    freshness: a fit that dies right after a log boundary was SCHEDULED
    (but not yet landed) must flush it before the flight bundle
    snapshots callback_metrics — the bundle's ``train_loss`` equals the
    loss a clean fit reports when truncated at the crash step, not the
    previous boundary's value."""
    import json

    crash_at = 5

    class Boom(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            if trainer.micro_step >= crash_at:
                raise RuntimeError("boom-forensics")

    with pytest.raises(RuntimeError, match="boom-forensics"):
        _fit(tmp_path / "crash", "off", callbacks=[Boom()],
             log_every_n_steps=1)
    bundle = (tmp_path / "crash" / "telemetry" / "flight"
              / "bundle-rank0.json")
    assert bundle.exists()
    doc = json.loads(bundle.read_text())
    assert doc["micro_step"] == crash_at
    # A clean fit's per-step log trajectory pins the expected value:
    # the bundle must carry the CRASH step's loss (same seed/data ->
    # bitwise equal), not the previous boundary's.
    per_step = []

    class Rec(Callback):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            per_step.append(
                {k: float(v) for k, v in jax.device_get(logs).items()}
            )

    _fit(tmp_path / "clean", "off", callbacks=[Rec()],
         log_every_n_steps=1)
    assert doc["callback_metrics"]["train_loss"] == pytest.approx(
        per_step[crash_at - 1]["train_loss"], abs=0.0
    )
    # The guarded regression: before the crash-path flush, the bundle
    # froze one boundary behind (the step-4 value here).
    assert (doc["callback_metrics"]["train_loss"]
            != per_step[crash_at - 2]["train_loss"])


# -- knob resolution ---------------------------------------------------------

def test_resolve_megastep_env_and_values(monkeypatch):
    monkeypatch.delenv("RLT_MEGASTEP", raising=False)
    assert _resolve_megastep(FitConfig(megastep="off")) == 1
    assert _resolve_megastep(FitConfig(megastep=6)) == 6
    assert _resolve_megastep(FitConfig(megastep="4")) == 4
    # auto on the CPU test backend = off (docs/PERFORMANCE.md).
    assert _resolve_megastep(FitConfig(megastep="auto")) == 1
    assert _resolve_megastep(FitConfig()) == 1
    monkeypatch.setenv("RLT_MEGASTEP", "5")
    assert _resolve_megastep(FitConfig()) == 5
    assert _resolve_megastep(FitConfig(megastep=2)) == 2  # explicit wins
    # An operator CLEARING the knob (RLT_MEGASTEP=) means off, not auto.
    monkeypatch.setenv("RLT_MEGASTEP", "")
    assert _resolve_megastep(FitConfig()) == 1


def test_midfit_first_use_compile_excluded_from_step_aggregates():
    from ray_lightning_tpu.telemetry.step_stats import StepStats

    ss = StepStats(sample_every=1000)
    ss.record_stride(5.0, 0.0, 4.9, examples=32, k=8)     # compile stride
    for _ in range(4):
        ss.record_stride(0.08, 0.001, 0.002, examples=32, k=8)
    # The lazy per-step program compiles at the partial tail: booked as
    # compile, NOT a steady-state outlier in step_time_ms/dispatch_ms.
    ss.record_step(3.0, 0.0, 2.9, examples=4, compiled=True)
    ss.record_step(0.01, 0.001, 0.002, examples=4)
    snap = ss.summary()
    assert snap["compile_ms"] == pytest.approx(5000.0 + 3000.0)
    assert snap["step_max_ms"] < 100.0       # no 3s outlier
    assert snap["dispatch_max_ms"] < 100.0


def test_megastep_validation_is_eager():
    with pytest.raises(ValueError):
        FitConfig(megastep="bogus")
    with pytest.raises(ValueError):
        FitConfig(megastep=0)
    with pytest.raises(ValueError):
        LocalStrategy(megastep=-3)
    with pytest.raises(ValueError):
        Trainer(megastep="nope")


def test_strategy_knob_fills_unset_trainer_default(tmp_path):
    t = _fit(tmp_path, 2)  # via LocalStrategy(megastep=2)
    assert t.telemetry_report["counters"]["megastep_dispatches"][
        "mean"] == BATCHES / 2


# -- schema ------------------------------------------------------------------

def test_host_overhead_schema():
    from ray_lightning_tpu.telemetry.schema import (
        validate_bench_host_overhead,
    )

    good = {
        "fit_vs_raw": 0.95, "dispatches_per_opt_step": 1.0,
        "megastep_k": 8, "megastep_dispatches_per_opt_step": 0.125,
        "megastep_tokens_per_sec": None, "megastep_speedup": 1.1,
    }
    assert validate_bench_host_overhead(good) == []
    assert validate_bench_host_overhead({}) == []  # all-optional block
    assert validate_bench_host_overhead({"surprise": 1})
    assert validate_bench_host_overhead({"megastep_k": 0})
    assert validate_bench_host_overhead({"megastep_k": "8"})
