"""Speculative decoding on the serving plane: draft-propose /
target-verify over the paged KV cache.

The correctness bar extends round 11's contract: a request served
SPECULATIVELY must produce exactly the tokens the non-speculative
engine (and the static ``generate()`` path) would — the lossless-
speculation guarantee, pinned bitwise for greedy.  On top: the verify
program's logits parity against the full forward, shape-static top-k
sampling vs a host reference, multi-token append / rollback block
arithmetic, the zero-recompile steady state with the draft+verify
program set, temperature>0 reproducibility across recompute preemption
(the rollback path's load-bearing contract), and client-side index
dedup under variable-width emission.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_lightning_tpu.models.generate import generate
from ray_lightning_tpu.models.gpt import GPT, GPTConfig
from ray_lightning_tpu.serve.draft import (
    early_exit_draft, pad_identity_layers,
)
from ray_lightning_tpu.serve.engine import ServeConfig, ServeEngine
from ray_lightning_tpu.serve.kv_cache import (
    TRASH_BLOCK, BlockAllocator, PagedKVCache, extend_block_coverage,
    make_slot_keys, paged_verify_step, sample_tokens, truncate_to,
)
from ray_lightning_tpu.telemetry import compile_event_count

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def model():
    """4-layer target whose 2-layer early-exit is the draft."""
    cfg = GPTConfig(vocab_size=128, n_layer=4, n_head=4, d_model=64,
                    seq_len=64, warmup_steps=1)
    m = GPT(cfg, attn_impl="xla")
    params = m.init_params(jax.random.PRNGKey(0))
    draft, draft_params = early_exit_draft(m, params, 2)
    return m, params, draft, draft_params


def _ref_tokens(m, params, prompt, n, **kw):
    out = generate(m, params, jnp.asarray([prompt], jnp.int32), n, **kw)
    return np.asarray(out)[0, len(prompt):].tolist()


def _rand_prompt(seed, length, vocab=128):
    rng = np.random.default_rng(seed)
    return rng.integers(1, vocab, size=(length,)).tolist()


def _spec_engine(m, params, draft, draft_params, spec_k=3, **cfg_kw):
    kw = dict(num_slots=3, block_size=8)
    kw.update(cfg_kw)
    return ServeEngine(
        m, params, ServeConfig(spec_k=spec_k, **kw),
        draft_module=draft, draft_params=draft_params,
    )


# ---------------------------------------------------------------------------
# Block arithmetic: multi-token coverage + rollback (jax-free units)
# ---------------------------------------------------------------------------

class TestBlockArithmetic:
    def test_extend_coverage_all_or_nothing(self):
        alloc = BlockAllocator(6)  # 5 usable
        blocks, row = [], np.full((8,), TRASH_BLOCK, np.int32)
        assert extend_block_coverage(alloc, blocks, row, 7, 4)  # 2 blocks
        assert len(blocks) == 2 and alloc.free_blocks == 3
        assert list(row[:2]) == blocks
        # Already covered: no-op.
        assert extend_block_coverage(alloc, blocks, row, 5, 4)
        assert len(blocks) == 2
        # 4 more blocks needed, only 3 free: nothing is taken.
        assert not extend_block_coverage(alloc, blocks, row, 23, 4)
        assert len(blocks) == 2 and alloc.free_blocks == 3

    def test_truncate_frees_tail_and_restores_trash(self):
        alloc = BlockAllocator(8)
        blocks, row = [], np.full((8,), TRASH_BLOCK, np.int32)
        assert extend_block_coverage(alloc, blocks, row, 15, 4)  # 4 blocks
        kept = list(blocks)
        freed = truncate_to(alloc, blocks, row, 6, 4)  # covers 2 blocks
        assert freed == 2 and blocks == kept[:2]
        assert (row[2:] == TRASH_BLOCK).all()
        assert alloc.free_blocks == 7 - 2
        # Freed blocks are immediately reusable.
        assert alloc.alloc(5) is not None

    def test_truncate_to_zero(self):
        alloc = BlockAllocator(4)
        blocks, row = [], np.full((4,), TRASH_BLOCK, np.int32)
        extend_block_coverage(alloc, blocks, row, 3, 4)
        assert truncate_to(alloc, blocks, row, 0, 4) == 1
        assert blocks == [] and alloc.free_blocks == 3

    def test_scheduler_truncate_slot(self):
        from ray_lightning_tpu.serve.scheduler import Request, Scheduler

        alloc = BlockAllocator(10)
        s = Scheduler(1, alloc, block_size=4, max_blocks_per_seq=6,
                      buckets=[4, 8])
        s.submit(Request(rid="a", prompt=[1, 2, 3], max_new_tokens=8))
        (slot, req, _), = s.poll(now=0.0)[0]
        assert s.cover(slot, 14)  # 4 blocks total
        assert len(s._blocks[slot]) == 4
        s.seq_lens[slot] = 15
        s.truncate_slot_to(slot, 5)
        assert int(s.seq_lens[slot]) == 5
        assert len(s._blocks[slot]) == 2
        assert (s.block_tables[slot, 2:] == TRASH_BLOCK).all()


# ---------------------------------------------------------------------------
# Verify program vs the full forward (device parity)
# ---------------------------------------------------------------------------

class TestVerifyParity:
    def test_verify_window_logits_match_full_forward(self, model):
        """Teacher-forcing a (K+1)-token window through
        paged_verify_step reproduces the full forward's logits at every
        window position — across block boundaries, on scattered
        physical blocks, mid-sequence."""
        m, params, _, _ = model
        cfg = m.config
        toks = np.asarray(_rand_prompt(2, 15, cfg.vocab_size))
        full = np.asarray(m.forward(params, jnp.asarray([toks])))
        cache = PagedKVCache(cfg, num_blocks=16, block_size=4)
        pool = cache.init_pool()
        phys = [5, 1, 7, 3]
        bt = np.full((2, 4), TRASH_BLOCK, np.int32)
        bt[0, :4] = phys
        seq_lens = np.zeros((2,), np.int32)
        T = 5  # window width: tokens [0, 5), then [5, 10), then [10, 15)
        for start in range(0, 15, T):
            window = np.zeros((2, T), np.int32)
            window[0] = toks[start: start + T]
            limits = np.asarray([start + T, 0], np.int32)
            logits, pool = paged_verify_step(
                cfg, params, pool, jnp.asarray(bt),
                jnp.asarray(seq_lens), jnp.asarray(window),
                jnp.asarray(limits),
            )
            np.testing.assert_allclose(
                np.asarray(logits)[0], full[0, start: start + T],
                rtol=1e-4, atol=1e-4,
            )
            seq_lens[0] += T

    def test_write_limit_trashes_pad_positions(self, model):
        """Window positions at/past the limit must land in the trash
        block, never in the slot's own blocks."""
        m, params, _, _ = model
        cfg = m.config
        cache = PagedKVCache(cfg, num_blocks=8, block_size=4)
        pool = cache.init_pool()
        bt = np.full((1, 2), TRASH_BLOCK, np.int32)
        bt[0, 0] = 2
        before = np.asarray(pool["k"][:, 2])
        window = np.asarray([[5, 6, 7]], np.int32)
        _, pool = paged_verify_step(
            cfg, params, pool, jnp.asarray(bt),
            jnp.asarray([1], np.int32), jnp.asarray(window),
            jnp.asarray([2], np.int32),  # only position 1 writable
        )
        after = np.asarray(pool["k"][:, 2])
        assert not np.allclose(after[:, 1], before[:, 1])  # pos 1 written
        np.testing.assert_array_equal(after[:, 2:], before[:, 2:])


# ---------------------------------------------------------------------------
# Shape-static top-k sampling (satellite) vs a host reference
# ---------------------------------------------------------------------------

class TestTopK:
    def _host_topk_mask(self, logits, k):
        if k <= 0:
            return logits
        kth = np.sort(logits)[::-1][k - 1]
        return np.where(logits < kth, -1e30, logits)

    def test_topk_masks_match_host_reference(self):
        rng = np.random.default_rng(0)
        logits = rng.normal(size=(4, 32)).astype(np.float32)
        top_ks = np.asarray([0, 1, 5, 32], np.int32)
        temps = np.full((4,), 1.0, np.float32)
        keys = make_slot_keys(
            jax.random.PRNGKey(0), jnp.arange(4), jnp.zeros(4, jnp.int32)
        )
        # Same keys, hand-masked host logits → identical draws.
        want = sample_tokens(
            jnp.asarray(np.stack([
                self._host_topk_mask(row, int(k))
                for row, k in zip(logits, top_ks)
            ])), keys, jnp.asarray(temps),
        )
        got = sample_tokens(
            jnp.asarray(logits), keys, jnp.asarray(temps),
            jnp.asarray(top_ks),
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_topk_one_is_greedy(self):
        rng = np.random.default_rng(1)
        logits = rng.normal(size=(3, 16)).astype(np.float32)
        keys = make_slot_keys(
            jax.random.PRNGKey(7), jnp.arange(3), jnp.arange(3)
        )
        got = sample_tokens(
            jnp.asarray(logits), keys,
            jnp.full((3,), 2.0, jnp.float32),
            jnp.ones((3,), jnp.int32),
        )
        np.testing.assert_array_equal(
            np.asarray(got), logits.argmax(-1)
        )

    def test_greedy_rows_ignore_topk_and_keys(self):
        rng = np.random.default_rng(2)
        logits = rng.normal(size=(2, 16)).astype(np.float32)
        for seed in (0, 1):
            keys = make_slot_keys(
                jax.random.PRNGKey(seed), jnp.arange(2), jnp.arange(2)
            )
            got = sample_tokens(
                jnp.asarray(logits), keys,
                jnp.zeros((2,), jnp.float32),
                jnp.asarray([3, 0], jnp.int32),
            )
            np.testing.assert_array_equal(
                np.asarray(got), logits.argmax(-1)
            )

    def test_engine_accepts_topk_requests(self, model):
        m, params, draft, dparams = model
        prompt = _rand_prompt(3, 6)
        # The sampling stream is (engine seed, submit ordinal,
        # position)-keyed: fresh engines replay the same request
        # sequence identically.
        outs = [
            _spec_engine(m, params, draft, dparams, seed=3).generate(
                prompt, 8, temperature=1.0, top_k=4
            )
            for _ in range(2)
        ]
        assert outs[0] == outs[1]
        eng = _spec_engine(m, params, draft, dparams, seed=3)
        with pytest.raises(ValueError, match="top_k"):
            eng.submit(prompt, 4, top_k=0)
        with pytest.raises(ValueError, match="temperature"):
            eng.submit(prompt, 4, top_k=4)


# ---------------------------------------------------------------------------
# Engine acceptance: lossless speculation
# ---------------------------------------------------------------------------

class TestSpecEngine:
    def test_greedy_spec_matches_generate_and_plain_engine(self, model):
        """The lossless-speculation guarantee: spec greedy == non-spec
        greedy == static generate(), token for token."""
        m, params, draft, dparams = model
        spec = _spec_engine(m, params, draft, dparams)
        plain = ServeEngine(m, params,
                            ServeConfig(num_slots=3, block_size=8))
        for seed, n in ((4, 12), (6, 16)):
            prompt = _rand_prompt(seed, 3 + seed)
            want = _ref_tokens(m, params, prompt, n)
            assert spec.generate(prompt, n) == want
            assert plain.generate(prompt, n) == want
        counters = spec.snapshot()["counters"]
        assert counters["spec_ticks"] > 0
        assert counters["spec_drafted"] > 0
        assert counters["spec_accepted"] <= counters["spec_drafted"]

    @pytest.mark.slow  # one verify/chain compile per K (~13s total);
    # the K=3 parity pin above runs in tier-1
    def test_spec_k_sweep_all_lossless(self, model):
        m, params, draft, dparams = model
        prompt = _rand_prompt(7, 5)
        want = _ref_tokens(m, params, prompt, 14)
        for k in (1, 2, 4, 8):
            eng = _spec_engine(m, params, draft, dparams, spec_k=k)
            assert eng.generate(prompt, 14) == want, f"spec_k={k}"

    def test_per_request_spec_zero_rides_along(self, model):
        """spec=0 requests batched WITH speculating requests take the
        verify program's width-1 lane and still match the reference."""
        m, params, draft, dparams = model
        eng = _spec_engine(m, params, draft, dparams)
        p1, p2 = _rand_prompt(8, 6), _rand_prompt(9, 9)
        h1 = eng.submit(p1, 12, spec=0)
        h2 = eng.submit(p2, 12)
        eng.run_until_idle()
        assert h1.result(5) == _ref_tokens(m, params, p1, 12)
        assert h2.result(5) == _ref_tokens(m, params, p2, 12)

    def test_spec_zero_only_traffic_uses_decode_fallback(self, model):
        """An all-spec=0 tick must dispatch the plain decode program
        (decode_steps advances, verify does not)."""
        m, params, draft, dparams = model
        eng = _spec_engine(m, params, draft, dparams)
        prompt = _rand_prompt(10, 4)
        assert eng.generate(prompt, 6, spec=0) == _ref_tokens(
            m, params, prompt, 6
        )
        counters = eng.snapshot()["counters"]
        assert counters["decode_steps"] > 0
        assert counters.get("verify_steps", 0) == 0

    def test_identity_tail_pair_accepts_everything(self, model):
        """Draft + identity-tail target: target logits == draft logits,
        so every draft is accepted and ticks emit K+1 tokens."""
        m, params, draft, dparams = model
        del m, params
        target, tparams = pad_identity_layers(draft, dparams, 3)
        eng = ServeEngine(
            target, tparams, ServeConfig(num_slots=2, block_size=8,
                                         spec_k=3),
            draft_module=draft, draft_params=dparams,
        )
        prompt = _rand_prompt(11, 5)
        got = eng.generate(prompt, 13)
        assert got == _ref_tokens(target, tparams, prompt, 13)
        snap = eng.snapshot()
        assert snap["gauges"]["spec_acceptance_rate"] == 1.0

    def test_eos_inside_accepted_window_stops_exactly(self, model):
        """An eos token landing mid-window truncates the emission at
        eos (inclusive) — no token after it leaks out, and the caches
        roll back to the real frontier."""
        m, params, draft, dparams = model
        prompt = _rand_prompt(12, 5)
        ref = _ref_tokens(m, params, prompt, 10)
        eos = ref[4]
        eng = _spec_engine(m, params, draft, dparams)
        h = eng.submit(prompt, 10, eos_token_id=eos)
        eng.run_until_idle()
        assert h.result(5) == ref[: ref.index(eos) + 1]
        assert h.request.done_reason == "eos"
        assert eng.snapshot()["gauges"]["blocks_free"] == float(
            eng.cache.num_blocks - 1
        )

    def test_join_on_arrival_under_spec(self, model):
        m, params, draft, dparams = model
        eng = _spec_engine(m, params, draft, dparams, num_slots=4)
        p1, p2 = _rand_prompt(13, 6), _rand_prompt(14, 11)
        h1 = eng.submit(p1, 12)
        for _ in range(2):
            eng.step()
        h2 = eng.submit(p2, 8)
        eng.run_until_idle()
        assert h1.result(5) == _ref_tokens(m, params, p1, 12)
        assert h2.result(5) == _ref_tokens(m, params, p2, 8)

    def test_preemption_under_block_exhaustion_with_spec(self, model):
        """Speculative coverage claims more blocks per tick; preemption
        under exhaustion must still produce reference tokens for both
        requests and return every block."""
        m, params, draft, dparams = model
        # 7 usable blocks vs two sequences needing 5 each: baseline
        # growth must preempt (the spec windows only shrink).
        eng = _spec_engine(
            m, params, draft, dparams,
            num_slots=2, block_size=4, num_blocks=8, max_model_len=24,
        )
        p1, p2 = [3, 1, 4, 1], [2, 7, 1]
        h1, h2 = eng.submit(p1, 16), eng.submit(p2, 16)
        eng.run_until_idle()
        assert h1.result(5) == _ref_tokens(m, params, p1, 16)
        assert h2.result(5) == _ref_tokens(m, params, p2, 16)
        snap = eng.snapshot()
        assert snap["counters"]["preempted"] >= 1
        assert snap["gauges"]["blocks_free"] == 7.0

    def test_spec_coverage_never_preempts_and_terminates(self, model):
        """Regression (round-16 verify): speculative window coverage is
        OPPORTUNISTIC.  Two temperature>0 requests on a pool that can't
        fund both verify windows used to preempt each other's windows
        in a ping-pong that never made forward progress; now a dry pool
        shrinks the tick's draft width instead, preemption stays
        baseline-only, and both requests finish."""
        m, params, draft, dparams = model
        eng = _spec_engine(
            m, params, draft, dparams,
            num_slots=2, block_size=4, num_blocks=8, max_model_len=24,
            seed=11,
        )
        h1 = eng.submit([3, 1, 4, 1], 16, temperature=1.0)
        h2 = eng.submit([2, 7, 1], 16, temperature=0.8, top_k=8)
        eng.run_until_idle(max_steps=4000)  # livelock = loud failure
        assert len(h1.result(5)) == 16 and len(h2.result(5)) == 16
        assert eng.snapshot()["gauges"]["blocks_free"] == 7.0

    def test_fallback_ticks_keep_draft_cache_synced(self, model):
        """Regression (round-16 review): a decode-fallback tick on a
        speculative engine (pool pressure shrank every window to zero)
        must mirror its write into the DRAFT cache — with the
        identity-tail pair any stale draft position shows up as
        acceptance < 1.0 on later ticks."""
        m, params, draft, dparams = model
        del m, params
        target, tparams = pad_identity_layers(draft, dparams, 3)
        eng = ServeEngine(
            target, tparams,
            ServeConfig(num_slots=1, block_size=4, spec_k=3),
            draft_module=draft, draft_params=dparams,
        )
        p = [3, 1, 4]  # seq 3 → first spec tick lands on 7 (mid-block)
        h = eng.submit(p, 12)
        eng.step()  # prefill + full-width spec tick: seq_len 3 → 7
        assert int(eng.scheduler.seq_lens[0]) == 7
        # Dry pool at a frontier whose NEXT position is still covered:
        # every window width fails cover, baseline doesn't need a
        # block — the tick must fall back to plain decode.
        alloc = eng.cache.allocator
        hog = alloc.alloc(alloc.free_blocks)
        before = eng.snapshot()["counters"].get("decode_steps", 0)
        eng.step()
        assert eng.snapshot()["counters"]["decode_steps"] == before + 1
        assert int(eng.scheduler.seq_lens[0]) == 8
        # The frontier claim must be BACKED by a real write: position 7
        # (block 1, offset 3) of the DRAFT pool carries the fallback
        # token's k/v, not the pool's zero-fill (the discriminating
        # probe — a zero/stale row only degrades acceptance softly).
        assert int(eng.scheduler.draft_lens[0]) == 8
        blk = eng.scheduler._blocks[0][1]
        assert np.any(np.asarray(eng._draft_pool["k"][:, blk, 3]) != 0.0)
        # Pool returns; speculation resumes conditioned on the
        # fallback-written position.
        alloc.free(hog)
        eng.run_until_idle(max_steps=4000)
        assert h.result(5) == _ref_tokens(target, tparams, p, 12)
        snap = eng.snapshot()
        assert snap["counters"]["spec_drafted"] > 0
        # The draft never proposed from a stale cache.
        assert snap["gauges"]["spec_acceptance_rate"] == 1.0

    def test_steady_state_zero_recompiles_with_spec(self, model):
        """The program-set contract: draft prefill/step, verify, decode
        fallback and the bucketed target prefills compile during
        warmup; steady-state speculative traffic compiles NOTHING."""
        m, params, draft, dparams = model
        eng = _spec_engine(m, params, draft, dparams)
        eng.generate(_rand_prompt(15, 5), 4)            # bucket 8
        eng.generate(_rand_prompt(16, 12), 4)           # bucket 16
        eng.generate(_rand_prompt(17, 4), 3, spec=0)    # decode fallback
        from ray_lightning_tpu.serve.metrics import ServeStats

        eng.stats = ServeStats()  # count steady-state traffic only
        before = compile_event_count()
        for seed in range(8):
            eng.submit(
                _rand_prompt(20 + seed, 3 + (seed % 12)),
                3 + seed % 6, spec=0 if seed % 4 == 0 else None,
            )
        eng.run_until_idle()
        assert eng.snapshot()["counters"]["completed"] == 8
        assert compile_event_count() - before == 0

    def test_draftless_engine_rejects_spec_and_spec_knob_validates(
            self, model):
        m, params, draft, dparams = model
        plain = ServeEngine(m, params,
                            ServeConfig(num_slots=1, block_size=8))
        with pytest.raises(ValueError, match="draft"):
            plain.submit([1, 2], 4, spec=2)
        with pytest.raises(ValueError, match="spec_k"):
            ServeEngine(m, params,
                        ServeConfig(num_slots=1, block_size=8, spec_k=2))
        with pytest.raises(ValueError, match="spec_k"):
            ServeEngine(m, params,
                        ServeConfig(num_slots=1, block_size=8),
                        draft_module=draft, draft_params=dparams)
        with pytest.raises(ValueError, match="vocab"):
            other = GPT(GPTConfig(vocab_size=64, n_layer=2, n_head=4,
                                  d_model=64, seq_len=64,
                                  warmup_steps=1), attn_impl="xla")
            ServeEngine(
                m, params,
                ServeConfig(num_slots=1, block_size=8, spec_k=2),
                draft_module=other,
                draft_params=other.init_params(jax.random.PRNGKey(1)),
            )


# ---------------------------------------------------------------------------
# Temperature reproducibility across recompute preemption (satellite):
# the rollback path's load-bearing contract beyond greedy.
# ---------------------------------------------------------------------------

class TestSamplingReproducibility:
    def _run_with_preemption(self, m, params, draft, dparams, spec_k):
        emissions = {}

        def on_token(rid):
            def cb(i, t):
                emissions.setdefault(rid, []).append((i, t))
            return cb

        kw = dict(num_slots=2, block_size=4, num_blocks=10,
                  max_model_len=24, seed=7)
        if spec_k:
            eng = _spec_engine(m, params, draft, dparams,
                               spec_k=spec_k, **kw)
        else:
            eng = ServeEngine(m, params, ServeConfig(**kw))
        h1 = eng.submit([3, 1, 4, 1], 16, temperature=1.0,
                        on_token=on_token("a"))
        h2 = eng.submit([2, 7, 1], 16, temperature=0.8,
                        on_token=on_token("b"))
        eng.run_until_idle()
        assert eng.snapshot()["counters"]["preempted"] >= 1
        return emissions, h1.result(5), h2.result(5)

    @pytest.mark.parametrize("spec_k", [0, 3])
    def test_reemitted_tokens_bitwise_equal(self, model, spec_k):
        """After a recompute preemption the re-decode replays the SAME
        per-position sampling keys: every re-emitted index carries the
        token of the first emission, at temperature > 0."""
        m, params, draft, dparams = model
        emissions, r1, r2 = self._run_with_preemption(
            m, params, draft, dparams, spec_k
        )
        reemitted = 0
        for rid, ems in emissions.items():
            seen = {}
            for i, t in ems:
                if i in seen:
                    reemitted += 1
                    assert seen[i] == t, (
                        f"request {rid} re-emitted index {i} as {t}, "
                        f"first emission was {seen[i]}"
                    )
                seen[i] = t
            # The final result is exactly the deduped stream.
            assert [seen[i] for i in range(len(seen))] in (r1, r2)
        assert reemitted > 0, "no preemption re-emission exercised"

    def test_fresh_engine_reproduces_preempted_run(self, model):
        """Same seed, no preemption pressure → identical outputs: the
        preempted run lost nothing to the rollback."""
        m, params, draft, dparams = model
        _, r1, r2 = self._run_with_preemption(
            m, params, draft, dparams, spec_k=3
        )
        calm = _spec_engine(m, params, draft, dparams, spec_k=3,
                            num_slots=2, block_size=4, seed=7)
        g1 = calm.submit([3, 1, 4, 1], 16, temperature=1.0)
        g2 = calm.submit([2, 7, 1], 16, temperature=0.8)
        calm.run_until_idle()
        assert calm.snapshot()["counters"]["preempted"] == 0
        assert g1.result(5) == r1
        assert g2.result(5) == r2

    def test_temperature_stream_slot_independent(self, model):
        """A request's sampled tokens must not depend on which slot it
        lands in or who shares the batch (the property that makes
        preemption rollback safe)."""
        m, params, draft, dparams = model
        prompt = _rand_prompt(18, 5)
        alone = _spec_engine(m, params, draft, dparams, seed=5)
        want = alone.generate(prompt, 8, temperature=0.9)
        # Same submit ordinal (first), but now two neighbours share the
        # batch: the probe's tokens must not move.
        crowded = _spec_engine(m, params, draft, dparams, seed=5,
                               num_slots=3)
        h = crowded.submit(prompt, 8, temperature=0.9)
        others = [crowded.submit(_rand_prompt(19 + i, 4 + i), 8,
                                 temperature=1.3) for i in range(2)]
        crowded.run_until_idle()
        for o in others:
            o.result(5)
        assert h.result(5) == want


# ---------------------------------------------------------------------------
# Client plane under variable-width emission (satellite)
# ---------------------------------------------------------------------------

class TestClientVariableWidth:
    def test_stream_dedup_under_spec_and_preemption(self, model):
        """Index-based dedup holds when tokens arrive in multi-token
        bursts and re-emissions cross burst boundaries."""
        from ray_lightning_tpu.serve.client import ServeClient

        m, params, draft, dparams = model
        # 7 usable blocks, two 20-token sequences needing 5 each plus
        # speculative coverage: exhaustion (hence preemption and
        # re-emission) is guaranteed while both are in flight.
        eng = _spec_engine(
            m, params, draft, dparams,
            num_slots=2, block_size=4, num_blocks=8, max_model_len=24,
        )
        client = ServeClient(eng.queue_handle())
        try:
            p1, p2 = [3, 1, 4, 1], [2, 7, 1]
            r2 = client.submit(p2, 16)
            stream = client.stream(p1, 16, timeout=60)
            eng.start()  # engine thread drives while the stream consumes
            toks = list(stream)
            assert toks == _ref_tokens(m, params, p1, 16)
            assert client.result(r2, 30) == _ref_tokens(m, params, p2, 16)
            assert eng.snapshot()["counters"]["preempted"] >= 1
        finally:
            eng.stop()
            client.close()

    def test_client_spec_and_topk_fields_roundtrip(self, model):
        from ray_lightning_tpu.serve.client import ServeClient
        from ray_lightning_tpu.telemetry.schema import (
            validate_serve_request,
        )

        m, params, draft, dparams = model
        eng = _spec_engine(m, params, draft, dparams)
        seen = []
        orig = eng._handle_queue_request

        def spy(item):
            seen.append(item)
            orig(item)

        eng._handle_queue_request = spy
        client = ServeClient(eng.queue_handle())
        try:
            eng.start()
            prompt = _rand_prompt(20, 5)
            got = client.generate(prompt, 6, temperature=1.0, top_k=5,
                                  spec=2, timeout=60)
            assert len(got) == 6
            assert seen and seen[0]["top_k"] == 5 and seen[0]["spec"] == 2
            assert validate_serve_request(seen[0]) == []
            # spec=0 over the wire → plain decode, reference tokens.
            want = _ref_tokens(m, params, prompt, 6)
            assert client.generate(prompt, 6, spec=0, timeout=60) == want
        finally:
            eng.stop()
            client.close()


# ---------------------------------------------------------------------------
# Telemetry: snapshot schema, prom family, bench block
# ---------------------------------------------------------------------------

class TestSpecTelemetry:
    def test_snapshot_schema_and_prom_family(self, model):
        from ray_lightning_tpu.telemetry.export_prom import (
            render_openmetrics,
        )
        from ray_lightning_tpu.telemetry.schema import (
            validate_serve_snapshot,
        )

        m, params, draft, dparams = model
        eng = _spec_engine(m, params, draft, dparams)
        eng.generate(_rand_prompt(21, 5), 8)
        snap = eng.snapshot()
        assert validate_serve_snapshot(snap) == []
        assert 0.0 <= snap["gauges"]["spec_acceptance_rate"] <= 1.0
        # 8 new tokens = 1 from prefill + 7 speculative.
        assert snap["counters"]["spec_emitted"] == 7
        text = render_openmetrics({"serve": snap})
        assert 'rlt_serve_spec_tokens_total{kind="drafted"}' in text
        assert 'rlt_serve_spec_tokens_total{kind="accepted"}' in text
        assert "rlt_serve_spec_acceptance_rate" in text
        assert "rlt_serve_spec_goodput_tokens_per_sec" in text
        # Spec token counters stay OUT of the generic request family.
        assert 'rlt_serve_requests_total{kind="spec_drafted"}' not in text

    def test_rlt_top_shows_acceptance(self, model, tmp_path):
        import os
        import subprocess
        import sys

        m, params, draft, dparams = model
        eng = ServeEngine(
            m, params,
            ServeConfig(num_slots=2, block_size=8, spec_k=3,
                        export_every_s=0.0),
            telemetry_dir=str(tmp_path),
            draft_module=draft, draft_params=dparams,
        )
        eng.generate(_rand_prompt(22, 5), 6)
        assert (tmp_path / "serve-live.json").exists()
        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "tools",
                          "rlt_top.py"),
             "--once", str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "spec acc" in out.stdout

    def test_bench_spec_block_schema(self):
        from ray_lightning_tpu.telemetry.schema import (
            validate_bench_spec_decode,
        )

        good = {
            "spec_k": 4, "tokens_per_sec": 100.0,
            "baseline_tokens_per_sec": 50.0, "vs_baseline": 2.0,
            "acceptance_rate": 0.9, "recompiles_steady_state": 0,
            "baseline_recompiles_steady_state": 0,
            "acceptance_sweep": [{"noise": 0.01, "acceptance_rate": 0.7,
                                  "tokens_per_sec": 80.0,
                                  "vs_baseline": 1.6}],
        }
        assert validate_bench_spec_decode(good) == []
        assert validate_bench_spec_decode({"spec_k": 4})
        assert validate_bench_spec_decode({**good, "acceptance_rate": 2})
        assert validate_bench_spec_decode({**good, "surprise": 1})
