"""Sharded large-model example (≙ reference
``examples/ray_ddp_sharded_example.py``).

The reference trains pl_bolts ImageGPT (embed_dim 2048) under
``RayShardedPlugin`` (FairScale ZeRO) and measures epoch time + peak GPU
memory with a ``CUDACallback`` (``ray_ddp_sharded_example.py:16-45``).
The TPU-native equivalent: the in-framework GPT under
:class:`RayShardedStrategy` — ZeRO expressed as NamedSharding annotations
over the fsdp axis, optionally combined with tensor parallelism — with
:class:`DeviceStatsCallback` reporting mesh epoch time and peak HBM.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/tpu_sharded_example.py --smoke-test
"""

from __future__ import annotations

import argparse

from ray_lightning_tpu import RayShardedStrategy, Trainer
from ray_lightning_tpu.core.callbacks import DeviceStatsCallback
from ray_lightning_tpu.models.gpt import GPT, GPTConfig, SyntheticLMDataModule


def train(
    num_workers: int = 1,
    num_epochs: int = 2,
    batch_size: int = 8,
    embed_dim: int = 512,
    n_layer: int = 8,
    seq_len: int = 256,
    zero_stage: int = 3,
    smoke_test: bool = False,
):
    """≙ reference ``train`` (``ray_ddp_sharded_example.py:48-71``)."""
    if smoke_test:
        cfg = GPTConfig.tiny()
        num_epochs, batch_size = 1, 8
    else:
        cfg = GPTConfig(
            vocab_size=50304, n_layer=n_layer,
            n_head=max(4, embed_dim // 64), d_model=embed_dim,
            seq_len=seq_len,
        )
    model = GPT(cfg)
    model.precision = "bf16"

    stats = DeviceStatsCallback()
    trainer = Trainer(
        strategy=RayShardedStrategy(
            num_workers=num_workers, zero_stage=zero_stage,
        ),
        max_epochs=num_epochs,
        callbacks=[stats],
        default_root_dir="rlt_logs/gpt_sharded",
        enable_checkpointing=False,
        limit_train_batches=4 if smoke_test else None,
        limit_val_batches=1 if smoke_test else None,
    )
    trainer.fit(model, SyntheticLMDataModule(
        cfg, batch_size=batch_size,
        num_batches=4 if smoke_test else 64,
    ))

    # ≙ the reference's end-of-run prints (ray_ddp_sharded_example.py:40-45)
    summary = stats.summary()
    if "avg_epoch_time_s" in summary:
        print(f"Average Epoch time: {summary['avg_epoch_time_s']:.2f} s")
    if "avg_peak_memory_bytes" in summary:
        print("Average Peak memory "
              f"{summary['avg_peak_memory_bytes'] / 2**20:.2f} MiB")
    return trainer


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--embed-dim", type=int, default=512)
    parser.add_argument("--n-layer", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=256)
    parser.add_argument("--zero-stage", type=int, default=3)
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    train(
        num_workers=args.num_workers,
        num_epochs=args.num_epochs,
        batch_size=args.batch_size,
        embed_dim=args.embed_dim,
        n_layer=args.n_layer,
        seq_len=args.seq_len,
        zero_stage=args.zero_stage,
        smoke_test=args.smoke_test,
    )
