"""MNIST data-parallel example (≙ reference ``examples/ray_ddp_example.py``).

Train the MNIST classifier under :class:`RayStrategy` (data-parallel over a
TPU host's devices, or the CPU-simulated mesh), optionally as a Tune sweep
(``--tune``), with the same CLI contract as the reference
(``ray_ddp_example.py:119-150``): ``--num-workers``, ``--smoke-test``,
``--tune``, ``--num-samples``.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/tpu_ddp_example.py --smoke-test
"""

from __future__ import annotations

import argparse

from ray_lightning_tpu import RayStrategy, Trainer
from ray_lightning_tpu.models.mnist import MNISTClassifier, MNISTDataModule
from ray_lightning_tpu.tune import TuneReportCallback, get_tune_resources
from ray_lightning_tpu.tuning import grid_search, loguniform, tune_run


def train_mnist(
    config: dict,
    num_workers: int = 1,
    num_epochs: int = 4,
    batch_size: int = 32,
    use_tune: bool = False,
    grad_comm: str = "full",
    telemetry: str = "cheap",
    heartbeat_s: float = 5.0,
    megastep: str = "auto",
):
    """≙ reference ``train_mnist`` (``ray_ddp_example.py:18-52``)."""
    callbacks = (
        [TuneReportCallback(
            {"loss": "ptl/val_loss", "mean_accuracy": "ptl/val_accuracy"},
            on="validation_end",
        )]
        if use_tune
        else []
    )
    trainer = Trainer(
        # grad_comm="int8_ef" compresses the cross-host gradient wire
        # ~4x (parallel/grad_sync.py); "full" is the exact default.
        # telemetry="cheap" (the default) records the step-time split +
        # throughput into callback_metrics for free; "full" additionally
        # exports span traces (Perfetto-loadable) under
        # rlt_logs/mnist_ddp/telemetry — see docs/OBSERVABILITY.md.
        # heartbeat_s sets the live-monitor cadence (--heartbeat; watch
        # the run with `python tools/rlt_top.py rlt_logs/mnist_ddp/
        # telemetry`); 0 disables the publisher.
        # megastep fuses K micro-steps into one compiled scan dispatch
        # (--megastep; "auto" = K=8 on TPU, off on CPU — see
        # docs/PERFORMANCE.md "Host dispatch & megastep").
        strategy=RayStrategy(num_workers=num_workers, grad_comm=grad_comm,
                             megastep=megastep,
                             telemetry={"tier": telemetry,
                                        "heartbeat_s": heartbeat_s}
                             if telemetry != "off" else "off"),
        max_epochs=num_epochs,
        callbacks=callbacks,
        log_every_n_steps=10,
        default_root_dir="rlt_logs/mnist_ddp",
    )
    trainer.fit(
        MNISTClassifier(lr=config.get("lr", 1e-3),
                        hidden_1=config.get("layer_1", 128),
                        hidden_2=config.get("layer_2", 256)),
        MNISTDataModule(batch_size=batch_size),
    )
    return trainer


def tune_mnist(
    num_workers: int = 1,
    num_samples: int = 2,
    num_epochs: int = 4,
    batch_size: int = 32,
):
    """≙ reference ``tune_mnist`` (``ray_ddp_example.py:105-117``)."""
    config = {
        "layer_1": grid_search([64, 128]),
        "layer_2": 256,
        "lr": loguniform(1e-4, 1e-2),
    }
    analysis = tune_run(
        lambda cfg: train_mnist(
            cfg, num_workers=num_workers, num_epochs=num_epochs,
            batch_size=batch_size, use_tune=True,
        ),
        config=config,
        num_samples=num_samples,
        metric="loss",
        mode="min",
        local_dir="rlt_logs/mnist_tune",
    )
    print("Best hyperparameters:", analysis.best_config)
    print("Resource request per trial:",
          get_tune_resources(num_workers=num_workers))
    return analysis


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--num-epochs", type=int, default=4)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--tune", action="store_true")
    parser.add_argument("--num-samples", type=int, default=2)
    parser.add_argument("--smoke-test", action="store_true")
    parser.add_argument("--grad-comm", default="full",
                        choices=["full", "int8", "int8_ef"])
    parser.add_argument("--telemetry", default="cheap",
                        choices=["off", "cheap", "full"])
    parser.add_argument("--heartbeat", type=float, default=5.0,
                        help="live-monitor heartbeat cadence in seconds "
                        "(0 disables; see docs/OBSERVABILITY.md)")
    parser.add_argument("--megastep", default="auto",
                        help="micro-steps fused per compiled dispatch: "
                        "'auto' (K=8 on TPU, off on CPU), 'off', or an "
                        "integer K (docs/PERFORMANCE.md)")
    args = parser.parse_args()

    epochs = 1 if args.smoke_test else args.num_epochs
    samples = 1 if args.smoke_test else args.num_samples
    if args.tune:
        tune_mnist(args.num_workers, samples, epochs, args.batch_size)
    else:
        trainer = train_mnist(
            {}, num_workers=args.num_workers, num_epochs=epochs,
            batch_size=args.batch_size, grad_comm=args.grad_comm,
            telemetry=args.telemetry, heartbeat_s=args.heartbeat,
            megastep=args.megastep,
        )
        print("final metrics:", {
            k: round(v, 4) for k, v in trainer.callback_metrics.items()
        })
        if trainer.telemetry_report:
            from ray_lightning_tpu.telemetry import format_report

            print(format_report(trainer.telemetry_report))
