"""Pipeline-parallel GPT trunk: GPipe over a ``pipe`` mesh axis.

Net-new capability (no reference analogue — the reference has no pipeline
concept): the GPT block stack's stacked ``(L, ...)`` parameter layout
doubles as the stage assignment — sharding that axis over ``pipe`` gives
each device a contiguous run of layers, and
:func:`ray_lightning_tpu.parallel.pipeline_apply` streams microbatches
through the stages with ``lax.ppermute`` handoffs.

The example builds a tiny GPT, runs its trunk both plain (one scan over
all layers) and pipelined (4 stages × 8 microbatches), checks they agree,
and takes one gradient step through the pipeline — demonstrating that the
reversed pipeline schedule falls out of ``jax.grad`` with no extra code.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/tpu_pipeline_example.py --smoke-test
"""

from __future__ import annotations

import argparse

import numpy as np


def main(smoke_test: bool = False, n_stages: int = 4,
         num_microbatches: int = 8):
    # Self-provision a virtual device mesh when the host has too few
    # devices (CI runs with no XLA_FLAGS) — must happen before the first
    # jax import, which is why jax is imported inside main.
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_stages}"
        ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ray_lightning_tpu.models.gpt import (
        GPT, GPTConfig, make_block_stage,
    )
    from ray_lightning_tpu.parallel import pipeline_apply

    cfg = GPTConfig(vocab_size=256, n_layer=n_stages * 2, n_head=4,
                    d_model=64, seq_len=64, warmup_steps=1)
    model = GPT(cfg, attn_impl="xla")
    params = model.init_params(jax.random.PRNGKey(0))
    batch = num_microbatches * (1 if smoke_test else 2)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, cfg.seq_len), 0, cfg.vocab_size
    )
    x0 = (params["wte"][tokens] + params["wpe"][: cfg.seq_len]).astype(
        jnp.float32
    )

    block_stage = make_block_stage(cfg)

    devices = jax.devices()
    if len(devices) < n_stages:
        raise SystemExit(
            f"need {n_stages} devices for {n_stages} stages; run with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    mesh = Mesh(np.asarray(devices[:n_stages]), ("pipe",))

    plain = block_stage(params["blocks"], x0)
    piped = pipeline_apply(
        block_stage, params["blocks"], x0, mesh,
        num_microbatches=num_microbatches,
    )
    err = float(jnp.abs(piped - plain).max())
    assert err < 1e-4, f"pipeline/plain mismatch: {err}"

    def loss(blocks):
        out = pipeline_apply(
            block_stage, blocks, x0, mesh,
            num_microbatches=num_microbatches,
        )
        return (out.astype(jnp.float32) ** 2).mean()

    grads = jax.jit(jax.grad(loss))(params["blocks"])
    gnorm = float(
        jnp.sqrt(sum(
            (g.astype(jnp.float32) ** 2).sum()
            for g in jax.tree_util.tree_leaves(grads)
        ))
    )
    assert np.isfinite(gnorm)
    print(
        f"pipeline({n_stages} stages x {num_microbatches} microbatches): "
        f"fwd matches plain scan (max err {err:.2e}), grad norm {gnorm:.4f}"
    )


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--smoke-test", action="store_true")
    p.add_argument("--num-stages", type=int, default=4)
    p.add_argument("--num-microbatches", type=int, default=8)
    a = p.parse_args()
    main(a.smoke_test, a.num_stages, a.num_microbatches)
