"""ViT image-classification example: the vision-transformer workload
under the sharded strategy (net-new model family; the reference's only
vision-transformer-adjacent example is pl_bolts ImageGPT under
``RayShardedPlugin``, ``examples/ray_ddp_sharded_example.py``).

The Megatron TP layout is shared with the GPT family
(``models/vit.py param_partition_specs``), so the same
data × fsdp × tensor mesh that trains GPT trains ViT.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/tpu_vit_example.py --smoke-test
"""

from __future__ import annotations

import argparse

from ray_lightning_tpu import RayShardedStrategy, Trainer
from ray_lightning_tpu.core.callbacks import DeviceStatsCallback
from ray_lightning_tpu.models import ViT, ViTConfig
from ray_lightning_tpu.models.resnet import CIFARDataModule


def train(
    num_workers: int = 1,
    num_epochs: int = 3,
    batch_size: int = 128,
    d_model: int = 384,
    n_layer: int = 6,
    zero_stage: int = 3,
    data_path: str | None = None,
    smoke_test: bool = False,
):
    if smoke_test:
        cfg = ViTConfig.tiny()
        num_epochs, batch_size = 1, 32
    else:
        cfg = ViTConfig(
            d_model=d_model, n_layer=n_layer,
            n_head=max(4, d_model // 64),
        )
    model = ViT(cfg)
    model.precision = "bf16"

    stats = DeviceStatsCallback()
    trainer = Trainer(
        strategy=RayShardedStrategy(
            num_workers=num_workers, zero_stage=zero_stage,
        ),
        max_epochs=num_epochs,
        callbacks=[stats],
        default_root_dir="rlt_logs/vit",
        enable_checkpointing=False,
        limit_train_batches=4 if smoke_test else None,
        limit_val_batches=1 if smoke_test else None,
    )
    trainer.fit(model, CIFARDataModule(
        batch_size=batch_size,
        num_samples=256 if smoke_test else 4096,
        image_size=cfg.image_size,
        data_path=data_path,
    ))

    print(f"val_accuracy: {trainer.callback_metrics.get('val_accuracy')}")
    summary = stats.summary()
    if "avg_epoch_time_s" in summary:
        print(f"Average Epoch time: {summary['avg_epoch_time_s']:.2f} s")
    return trainer


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--d-model", type=int, default=384)
    parser.add_argument("--n-layer", type=int, default=6)
    parser.add_argument("--zero-stage", type=int, default=3)
    parser.add_argument("--data-path", type=str, default=None)
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    train(
        num_workers=args.num_workers,
        num_epochs=args.num_epochs,
        batch_size=args.batch_size,
        d_model=args.d_model,
        n_layer=args.n_layer,
        zero_stage=args.zero_stage,
        data_path=args.data_path,
        smoke_test=args.smoke_test,
    )
