"""Minimal serving example: train a tiny GPT, then serve it.

The serving half of the lifecycle (docs/SERVING.md): stand up the
continuous-batching :class:`ServeEngine` on the trained weights, hit it
from a :class:`ServeClient` over the DriverQueue request plane
(submission + per-token streaming, exactly how a remote client would),
and print the SLO snapshot the telemetry plane exports.

Run (CPU):
    JAX_PLATFORMS=cpu python examples/tpu_serve_example.py --smoke-test
    # speculative decoding through an early-exit draft:
    JAX_PLATFORMS=cpu python examples/tpu_serve_example.py \
        --smoke-test --spec 4
    # disaggregated fleet: 2 decode replicas fed by 1 prefill worker
    # behind the load-aware router (docs/SERVING.md "Disaggregated
    # serving"):
    JAX_PLATFORMS=cpu python examples/tpu_serve_example.py \
        --smoke-test --replicas 2 --prefill-workers 1
    # multi-tenant LoRA: N adapters multiplexed over ONE resident base
    # (docs/SERVING.md "Multi-tenant LoRA"; composes with --replicas /
    # --prefill-workers — the router hot-loads members on demand):
    JAX_PLATFORMS=cpu python examples/tpu_serve_example.py \
        --smoke-test --adapters 3
    # SLO & capacity plane: burn-rate SLOs evaluated while serving,
    # plus the headroom oracle's capacity / predicted-knee view
    # (docs/OBSERVABILITY.md "SLO, burn rate & capacity"):
    JAX_PLATFORMS=cpu python examples/tpu_serve_example.py \
        --smoke-test --slo
"""

from __future__ import annotations

import argparse

import numpy as np

from ray_lightning_tpu import LocalStrategy, Trainer
from ray_lightning_tpu.models import GPT, GPTConfig, SyntheticLMDataModule
from ray_lightning_tpu.serve import ServeClient, ServeConfig, ServeEngine


def _fmt(v, digits=1):
    """None-tolerant number formatting — a short demo run may not
    feed the oracle enough bins for every derived metric."""
    return "n/a" if v is None else f"{v:.{digits}f}"


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--max-epochs", type=int, default=2)
    parser.add_argument("--requests", type=int, default=12)
    parser.add_argument("--max-new-tokens", type=int, default=16)
    parser.add_argument("--num-slots", type=int, default=4)
    parser.add_argument("--spec", type=int, default=0, metavar="K",
                        help="speculative decoding: draft K tokens per "
                        "tick through a 1-layer early-exit draft of the "
                        "trained model (0 = off)")
    parser.add_argument("--replicas", type=int, default=1, metavar="N",
                        help="decode replicas; N > 1 (or any prefill "
                        "workers) serves through the disaggregated "
                        "router fleet instead of one engine")
    parser.add_argument("--prefill-workers", type=int, default=0,
                        metavar="M",
                        help="dedicated prefill workers shipping KV "
                        "handoffs to the decode replicas (0 = replicas "
                        "prefill locally)")
    parser.add_argument("--adapters", type=int, default=0, metavar="N",
                        help="multi-tenant LoRA: serve N synthetic "
                        "tenants' adapters over ONE resident base "
                        "model (per-slot gathered application; any "
                        "tenant mix shares the compiled programs)")
    parser.add_argument("--prefix-cache", action="store_true",
                        help="prefix-aware KV reuse: serve a "
                        "shared-prefix request mix through resident "
                        "prompt chains — later requests claim the "
                        "shared blocks by refcount bumps and prefill "
                        "only their suffix (docs/SERVING.md § Prefix "
                        "caching)")
    parser.add_argument("--slo", action="store_true",
                        help="SLO & capacity plane: evaluate burn-rate "
                        "SLOs while serving and print the headroom "
                        "oracle's view — capacity, utilization and the "
                        "predicted saturation knee "
                        "(docs/OBSERVABILITY.md § SLO, burn rate & "
                        "capacity)")
    parser.add_argument("--trace", action="store_true",
                        help="request-scoped distributed tracing: "
                        "every component exports span JSONL into the "
                        "telemetry dir; the example stitches them and "
                        "prints each request's critical path "
                        "(docs/OBSERVABILITY.md § Distributed tracing)")
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    if args.smoke_test:
        args.max_epochs = 1
        args.requests = 6
        args.max_new_tokens = 8

    cfg = GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                    seq_len=64, warmup_steps=4)
    module = GPT(cfg, attn_impl="xla")
    trainer = Trainer(
        strategy=LocalStrategy(),
        max_epochs=args.max_epochs,
        default_root_dir="rlt_logs/serve_example",
    )
    trainer.fit(module, SyntheticLMDataModule(cfg, batch_size=16,
                                              num_batches=4))
    print(f"train_loss = {trainer.callback_metrics['train_loss']:.4f}")

    # One engine, compiled static-shape programs, requests of DIFFERENT
    # lengths continuously batched over the paged KV cache.  With
    # --spec K, a 1-layer early-exit draft of the trained model
    # proposes K tokens per tick and the full model verifies them in
    # one dispatch — same tokens, fewer target dispatches.
    draft_kw = {}
    if args.spec > 0:
        from ray_lightning_tpu.serve import early_exit_draft

        draft, draft_params = early_exit_draft(module, trainer.params, 1)
        draft_kw = dict(draft_module=draft, draft_params=draft_params)
    # Multi-tenant LoRA: N synthetic tenants of the trained base —
    # random non-zero factors so each tenant visibly generates its own
    # stream.  Real tenants come out of a lora_rank > 0 fine-tune via
    # models.extract_lora (docs/SERVING.md "Multi-tenant LoRA").
    adapters = {}
    if args.adapters > 0:
        import dataclasses

        import jax

        from ray_lightning_tpu.models.gpt import synthetic_lora_adapter

        lora_cfg = dataclasses.replace(cfg, lora_rank=4)
        rng = jax.random.PRNGKey(7)
        for i in range(args.adapters):
            rng, ki = jax.random.split(rng)
            adapters[f"tenant{i}"], _ = synthetic_lora_adapter(
                trainer.params, lora_cfg, ki
            )
    slo_kw = {}
    if args.slo:
        # Fine-grained bins + a fast export tick so even a short demo
        # run gives the oracle enough data to call the knee.
        slo_kw = dict(slo=True, capacity=True,
                      ts_interval_s=0.25, export_every_s=0.25)
    serve_cfg = ServeConfig(num_slots=args.num_slots, block_size=16,
                            spec_k=args.spec,
                            max_adapters=args.adapters,
                            adapter_rank=4 if args.adapters else 0,
                            prefix_cache=args.prefix_cache,
                            **slo_kw)
    telemetry_dir = "rlt_logs/serve_example/telemetry"
    trace_dir = telemetry_dir if args.trace else None
    if trace_dir:
        # Fresh traces per run: stale exports from a previous run would
        # merge into this run's stitched report (trace_stitch reads the
        # whole dir by design).
        import glob as _glob
        import os as _os

        for stale in _glob.glob(f"{trace_dir}/trace-*.json*"):
            _os.unlink(stale)
    engine = fleet = None
    if args.replicas > 1 or args.prefill_workers > 0:
        # Disaggregated: N engines (+ M prefill workers) behind the
        # load-aware router — the client code below is UNCHANGED, the
        # router speaks the engine's wire dialect.
        from ray_lightning_tpu.serve.dist import launch_inproc_fleet

        fleet = launch_inproc_fleet(
            module, trainer.params, serve_cfg,
            n_replicas=args.replicas, n_prefill=args.prefill_workers,
            telemetry_dir=telemetry_dir, trace_dir=trace_dir,
            adapters=adapters or None,
            **draft_kw,
        )
        handle = fleet.queue_handle()
    else:
        engine = ServeEngine(
            module, trainer.params, serve_cfg,
            telemetry_dir=telemetry_dir, trace_dir=trace_dir,
            adapters=adapters or None,
            **draft_kw,
        ).start()
        handle = engine.queue_handle()
    client = ServeClient(handle)
    try:
        rng = np.random.default_rng(0)
        tenant_names = sorted(adapters) if adapters else [None]
        # With the prefix cache on, make the mix prefix-heavy (the
        # production shape: one shared system prompt, per-request
        # tails) so claims actually happen; otherwise fully random.
        shared_head = (rng.integers(1, cfg.vocab_size,
                                    size=(32,)).tolist()
                       if args.prefix_cache else [])
        rids = [
            client.submit(
                shared_head + rng.integers(
                    1, cfg.vocab_size,
                    size=(int(rng.integers(4, 17)),)).tolist(),
                args.max_new_tokens,
                # Round-robin the tenants (None = the shared base
                # model): any mix rides the same decode dispatches.
                adapter=tenant_names[i % len(tenant_names)],
            )
            for i in range(args.requests - 1)
        ]
        # Streaming: tokens arrive as the decode loop emits them.
        stream = client.stream([1, 2, 3, 4], args.max_new_tokens)
        print("streamed:", [tok for tok in stream])
        for rid in rids:
            client.result(rid, timeout=120)

        if fleet is not None:
            # Completions reach the router on the next beat; give the
            # feed a moment so the printed count matches.
            import time as _time

            deadline = _time.monotonic() + 5
            while (fleet.router.snapshot()["counters"]["completed"]
                   < args.requests and _time.monotonic() < deadline):
                _time.sleep(0.05)
            rsnap = fleet.router.snapshot()
            done = rsnap["counters"]["completed"]
            print(f"router: completed={done} over "
                  f"{len(rsnap['replicas'])} replica(s), "
                  f"prefill_dispatches="
                  f"{rsnap['counters']['prefill_dispatches']}")
            per = {e["id"]: e.get("slots_active") for e
                   in rsnap["replicas"]}
            print(f"per-replica slots: {per}")
            if args.adapters > 0:
                loaded = {e["id"]: e.get("adapters", 0)
                          for e in rsnap["replicas"]}
                print(f"lora: loads sent="
                      f"{rsnap['counters']['adapter_loads_sent']}, "
                      f"adapters/replica={loaded}")
            if args.slo:
                # Per-replica capacity blocks ride the beats; the
                # router folds them into the fleet view.
                fc = rsnap.get("capacity") or {}
                print(f"fleet capacity: "
                      f"{fc.get('replicas_reporting', 0)} replica(s) "
                      f"reporting, capacity="
                      f"{_fmt(fc.get('capacity_tokens_per_s'))} tok/s, "
                      f"headroom="
                      f"{_fmt(fc.get('headroom_tokens_per_s'))} tok/s")
        else:
            snap = engine.snapshot()
            lat = snap["latency"]
            print(f"completed={snap['counters']['completed']} "
                  f"ttft_p50={lat['ttft']['p50_ms']:.1f}ms "
                  f"token_p50={lat['token']['p50_ms']:.1f}ms")
            if args.spec > 0:
                print(f"spec: acceptance="
                      f"{snap['gauges']['spec_acceptance_rate']:.2f} "
                      f"drafted={snap['counters']['spec_drafted']} "
                      f"emitted={snap['counters']['spec_emitted']}")
            if args.prefix_cache:
                pb = snap.get("prefix", {})
                print(f"prefix cache: hit_rate="
                      f"{snap['gauges']['prefix_cache_hit_rate']:.2f} "
                      f"claimed={pb.get('blocks_claimed', 0)} "
                      f"resident={pb.get('cached_blocks', 0)} blocks")
            if args.adapters > 0:
                # .get: the per-tenant block is lazily created on the
                # first adapter-bearing emission (--requests 1 serves
                # only the base stream).
                per = {name: entry["tokens_out"]
                       for name, entry in snap.get("adapters",
                                                   {}).items()}
                print(f"lora: {int(snap['gauges']['lora_adapters_loaded'])}"
                      f" tenant(s) over one resident base, fairness="
                      f"{snap['gauges']['lora_fairness_spread']:.2f}, "
                      f"tokens/tenant={per}")
            if args.slo:
                # The oracle watched the whole serve above through the
                # export tick; ask it for the derived view — and for
                # the knee it would predict at this request shape.
                cap = engine.capacity_oracle.snapshot(window_s=60.0)
                knee = engine.capacity_oracle.predict_saturation_rps(
                    args.max_new_tokens, window_s=60.0)
                burn = {name: round(s["burn_rate"], 2) for name, s in
                        engine.slo_evaluator.snapshot().items()}
                print(f"slo/capacity: capacity="
                      f"{_fmt(cap['capacity_tokens_per_s'])} tok/s, "
                      f"utilization={_fmt(cap['utilization'], 2)}, "
                      f"predicted_knee={_fmt(knee)} req/s, "
                      f"burn_rates={burn}, "
                      f"alerts={len(engine.slo_alerts)}")
            assert snap["counters"]["completed"] == args.requests
        print("OK — watch live with: "
              "python tools/rlt_top.py rlt_logs/serve_example/telemetry")
    finally:
        client.close()
        if fleet is not None:
            fleet.close()
        else:
            engine.stop()

    if args.trace:
        # Components exported their span JSONL at teardown; stitch and
        # show where each request's TTFT went (same path as
        # `python tools/trace_stitch.py <telemetry-dir>`).
        from ray_lightning_tpu.telemetry import trace_collect

        spans = trace_collect.load_trace_dir(trace_dir)
        print("distributed trace "
              f"({len(spans)} spans — merge with tools/trace_stitch.py):")
        print(trace_collect.format_report(spans, slowest_k=3))


main()
