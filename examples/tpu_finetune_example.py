"""LoRA fine-tuning example: the torch-ecosystem migration recipe.

Import a Hugging Face GPT-2 checkpoint (``utils/hf_import.py``), attach
LoRA adapters, fine-tune with the base frozen under a sharded strategy,
merge, and generate — the end-to-end path a reference
(``ray_lightning``) user follows to bring an existing torch LM onto
TPU.

Without ``--model-name`` (or offline), a randomly-initialized tiny HF
GPT-2 stands in for the checkpoint so the flow runs in zero-egress
environments; pass ``--model-name gpt2`` where the HF cache is
available to fine-tune the real 124M model.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/tpu_finetune_example.py --smoke-test
"""

from __future__ import annotations

import argparse
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu import RayShardedStrategy, Trainer
from ray_lightning_tpu.models import GPT, add_lora_adapters, merge_lora
from ray_lightning_tpu.models.gpt import SyntheticLMDataModule
from ray_lightning_tpu.models.generate import generate
from ray_lightning_tpu.utils import import_gpt2


def _load_hf(model_name: str | None):
    import torch
    import transformers

    if model_name:
        return transformers.GPT2LMHeadModel.from_pretrained(model_name)
    config = transformers.GPT2Config(
        vocab_size=97, n_positions=128, n_embd=64, n_layer=2, n_head=4,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    torch.manual_seed(0)
    return transformers.GPT2LMHeadModel(config)


def train(
    model_name: str | None = None,
    num_workers: int = 1,
    num_epochs: int = 1,
    batch_size: int = 8,
    lora_rank: int = 8,
    smoke_test: bool = False,
):
    hf = _load_hf(model_name)
    cfg, params = import_gpt2(hf)
    cfg = dataclasses.replace(
        cfg, lora_rank=lora_rank, lr=1e-3, warmup_steps=0,
    )
    params = add_lora_adapters(params, cfg, jax.random.PRNGKey(0))

    model = GPT(cfg, attn_impl="auto")
    model.initial_params = params

    trainer = Trainer(
        strategy=RayShardedStrategy(num_workers=num_workers, zero_stage=1),
        max_epochs=num_epochs,
        default_root_dir="rlt_logs/finetune",
        enable_checkpointing=False,
        limit_train_batches=2 if smoke_test else None,
        limit_val_batches=0,
    )
    trainer.fit(model, SyntheticLMDataModule(
        cfg, batch_size=batch_size, num_batches=2 if smoke_test else 64,
    ))

    tuned = jax.device_get(trainer.params)
    # The base is untouched; only adapters learned.
    assert (tuned["blocks"]["qkv_w"] == params["blocks"]["qkv_w"]).all()
    merged = merge_lora(tuned, cfg)
    base = GPT(dataclasses.replace(cfg, lora_rank=0), attn_impl="auto")
    out = generate(base, merged, jnp.ones((1, 8), jnp.int32),
                   max_new_tokens=8)
    print("generated continuation:", np.asarray(out)[0, 8:].tolist())
    return trainer


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--model-name", type=str, default=None,
                        help="HF checkpoint (e.g. gpt2); default: tiny "
                             "random-init stand-in (offline-safe)")
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--lora-rank", type=int, default=8)
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    train(
        model_name=args.model_name,
        num_workers=args.num_workers,
        num_epochs=args.num_epochs,
        batch_size=args.batch_size,
        lora_rank=args.lora_rank,
        smoke_test=args.smoke_test,
    )
