"""MNIST under the explicit-SPMD flavor (≙ reference
``examples/ray_horovod_example.py``).

The reference offers Horovod's ring all-reduce as a second communication
protocol; on TPU that duality maps to the execution-strategy choice:
:class:`HorovodRayStrategy` compiles the step with ``jax.shard_map`` —
per-device programs with explicit ``lax.pmean`` collectives (the ring
all-reduce analogue) — instead of GSPMD's global-view partitioning.
Numerically identical to :class:`RayStrategy`; kept as the
explicitly-scheduled escape hatch.  Same CLI contract as the reference
example (``--num-workers``, ``--smoke-test``, ``--tune``).

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/tpu_shard_map_example.py --smoke-test
"""

from __future__ import annotations

import argparse

from ray_lightning_tpu import HorovodRayStrategy, Trainer
from ray_lightning_tpu.models.mnist import MNISTClassifier, MNISTDataModule
from ray_lightning_tpu.tune import TuneReportCallback
from ray_lightning_tpu.tuning import loguniform, tune_run


def train_mnist(
    config: dict,
    num_workers: int = 1,
    num_epochs: int = 4,
    batch_size: int = 32,
    use_tune: bool = False,
):
    """≙ reference ``train_mnist`` (``ray_horovod_example.py:18-52``)."""
    callbacks = (
        [TuneReportCallback(
            {"loss": "ptl/val_loss", "mean_accuracy": "ptl/val_accuracy"},
            on="validation_end",
        )]
        if use_tune
        else []
    )
    trainer = Trainer(
        strategy=HorovodRayStrategy(num_workers=num_workers),
        max_epochs=num_epochs,
        callbacks=callbacks,
        default_root_dir="rlt_logs/mnist_shard_map",
    )
    trainer.fit(
        MNISTClassifier(lr=config.get("lr", 1e-3)),
        MNISTDataModule(batch_size=batch_size),
    )
    return trainer


def tune_mnist(num_workers=1, num_samples=2, num_epochs=4, batch_size=32):
    """≙ reference ``tune_mnist`` (``ray_horovod_example.py:105-117``)."""
    analysis = tune_run(
        lambda cfg: train_mnist(
            cfg, num_workers=num_workers, num_epochs=num_epochs,
            batch_size=batch_size, use_tune=True,
        ),
        config={"lr": loguniform(1e-4, 1e-2)},
        num_samples=num_samples,
        metric="loss",
        mode="min",
        local_dir="rlt_logs/mnist_shard_map_tune",
    )
    print("Best hyperparameters:", analysis.best_config)
    return analysis


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--num-epochs", type=int, default=4)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-samples", type=int, default=2)
    parser.add_argument("--tune", action="store_true")
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    if args.smoke_test:
        args.num_epochs, args.num_samples = 1, 1
    if args.tune:
        tune_mnist(args.num_workers, args.num_samples, args.num_epochs,
                   args.batch_size)
    else:
        trainer = train_mnist(
            {}, num_workers=args.num_workers, num_epochs=args.num_epochs,
            batch_size=args.batch_size,
        )
        print("val_accuracy:",
              trainer.callback_metrics.get("ptl/val_accuracy"))
