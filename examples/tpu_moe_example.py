"""Mixture-of-Experts training + generation over an expert-parallel mesh.

Net-new capability over the reference (SURVEY §2.3: "EP (expert
parallel / MoE): absent"): every block's MLP is replaced by top-k
capacity-routed experts (``ops/moe.py``); with an ``expert`` mesh axis,
GSPMD turns the dispatch einsum into the all-to-all that ships token
slots to their expert's device.  After training, the same routed math
decodes through the KV-cache generation path.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/tpu_moe_example.py --smoke-test
"""

from __future__ import annotations

import argparse


def train(
    num_epochs: int = 2,
    batch_size: int = 16,
    n_experts: int = 4,
    expert_shards: int = 2,
    smoke_test: bool = False,
):
    if expert_shards < 1:
        raise ValueError(f"expert_shards must be >= 1, got {expert_shards}")
    # Self-provision a virtual device mesh when the host has too few
    # devices (CI runs with no XLA_FLAGS) — must happen before the first
    # jax import (≙ tpu_pipeline_example.py).
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{2 * expert_shards}"
        ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import (
        GPT, GPTConfig, SyntheticLMDataModule,
    )
    from ray_lightning_tpu.parallel.strategies import LocalStrategy

    if smoke_test:
        cfg = GPTConfig.tiny_moe(n_experts=n_experts)
        num_epochs = 1
    else:
        cfg = GPTConfig(
            vocab_size=50304, n_layer=8, n_head=8, d_model=512,
            seq_len=512, n_experts=n_experts,
        )
    model = GPT(cfg)

    n_dev = jax.local_device_count()
    # The expert axis must divide BOTH the device count (mesh factoring)
    # and the expert count (expert-stacked weights shard along it).
    expert_shards = min(expert_shards, n_experts, n_dev)
    while n_dev % expert_shards or n_experts % expert_shards:
        expert_shards -= 1
    mesh_axes = {"data": n_dev // expert_shards, "expert": expert_shards}
    trainer = Trainer(
        strategy=LocalStrategy(mesh_axes=mesh_axes),
        max_epochs=num_epochs,
        precision="bf16",
        default_root_dir="rlt_logs/gpt_moe",
        enable_checkpointing=False,
        limit_train_batches=4 if smoke_test else -1,
        limit_val_batches=1 if smoke_test else -1,
    )
    trainer.fit(model, SyntheticLMDataModule(
        cfg, batch_size=batch_size, num_batches=4 if smoke_test else 64,
    ))
    print(f"mesh={mesh_axes}  train_loss="
          f"{trainer.callback_metrics['train_loss']:.4f}  moe_aux="
          f"{trainer.callback_metrics.get('moe_aux_loss', float('nan')):.4f}")

    # Decode from the trained weights: MoE routes per generated token
    # through the same expert MLPs (models/generate.py).
    from ray_lightning_tpu.models.generate import generate

    prompt = jax.numpy.ones((2, 4), jax.numpy.int32)
    out = generate(model, trainer.params, prompt,
                   max_new_tokens=8, temperature=0.7,
                   rng=jax.random.PRNGKey(0))
    print(f"generated continuations: {out[:, 4:].tolist()}")
    return trainer


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--num-experts", type=int, default=4)
    parser.add_argument("--expert-shards", type=int, default=2)
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    train(
        num_epochs=args.num_epochs,
        batch_size=args.batch_size,
        n_experts=args.num_experts,
        expert_shards=args.expert_shards,
        smoke_test=args.smoke_test,
    )
