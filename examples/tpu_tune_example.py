"""MNIST + ASHA hyperparameter sweep (≙ reference ``examples/ray_ddp_tune.py``).

Demonstrates the init_hook pattern for per-host dataset preparation
(≙ the FileLock download hook, reference ``ray_ddp_tune.py:22-25,39``) and
an ASHA-scheduled sweep over lr/hidden sizes.
"""

from __future__ import annotations

import argparse


def prepare_data_hook():
    """Runs once on every worker before training (≙ ``download_data``
    with FileLock, reference ``ray_ddp_tune.py:22-25``)."""
    from ray_lightning_tpu.models.mnist import _digits_as_mnist

    _digits_as_mnist()  # warms any on-disk cache; idempotent


def tune_mnist_asha(num_workers=1, num_samples=4, num_epochs=6,
                    batch_size=32):
    from ray_lightning_tpu import RayStrategy, Trainer
    from ray_lightning_tpu.models.mnist import (
        MNISTClassifier,
        MNISTDataModule,
    )
    from ray_lightning_tpu.tune import TuneReportCallback
    from ray_lightning_tpu.tuning import ASHAScheduler, choice, loguniform, tune_run

    def trainable(config):
        trainer = Trainer(
            strategy=RayStrategy(
                num_workers=num_workers, init_hook=prepare_data_hook
            ),
            max_epochs=num_epochs,
            callbacks=[TuneReportCallback(
                {"loss": "ptl/val_loss",
                 "mean_accuracy": "ptl/val_accuracy"},
                on="validation_end",
            )],
            default_root_dir="rlt_logs/mnist_asha",
        )
        trainer.fit(
            MNISTClassifier(lr=config["lr"], hidden_1=config["layer_1"]),
            MNISTDataModule(batch_size=batch_size),
        )

    analysis = tune_run(
        trainable,
        config={
            "layer_1": choice([64, 128]),
            "lr": loguniform(1e-4, 1e-2),
        },
        num_samples=num_samples,
        scheduler=ASHAScheduler(
            metric="loss", mode="min", max_t=num_epochs, grace_period=1,
        ),
        metric="loss",
        mode="min",
        local_dir="rlt_logs/mnist_asha_tune",
    )
    print("Best hyperparameters:", analysis.best_config)
    return analysis


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--num-samples", type=int, default=4)
    parser.add_argument("--num-epochs", type=int, default=6)
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    tune_mnist_asha(
        args.num_workers,
        1 if args.smoke_test else args.num_samples,
        2 if args.smoke_test else args.num_epochs,
    )
