"""Train-then-generate example: the full LM lifecycle in one script.

The reference's inference story ends at ``predict_step``; this example
shows the net-new TPU-native decode path — train a tiny GPT with
:class:`RayStrategy`, pull the weights back to the driver, and run
KV-cache autoregressive generation (greedy and nucleus sampling) from
the trained parameters.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/tpu_generate_example.py --smoke-test
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from ray_lightning_tpu import RayStrategy, Trainer
from ray_lightning_tpu.models import (
    GPT, GPTConfig, SyntheticLMDataModule, generate,
)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--max-epochs", type=int, default=2)
    parser.add_argument("--max-new-tokens", type=int, default=16)
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()

    if args.smoke_test:
        args.max_epochs = 1
        args.max_new_tokens = 8

    cfg = GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                    seq_len=64, warmup_steps=4)
    module = GPT(cfg, attn_impl="xla")
    world = args.num_workers * len(jax.devices())
    batch = max(16, world)
    dm = SyntheticLMDataModule(cfg, batch_size=batch,
                               num_batches=2 if args.smoke_test else 8)

    trainer = Trainer(
        strategy=RayStrategy(num_workers=args.num_workers),
        max_epochs=args.max_epochs,
        default_root_dir="rlt_logs/generate_example",
    )
    trainer.fit(module, dm)
    print(f"train_loss = {trainer.callback_metrics['train_loss']:.4f}")

    # trainer.params is a host pytree — generate() accepts it directly.
    prompt = np.asarray([[1, 2, 3, 4], [9, 8, 7, 6]], np.int32)
    greedy = generate(module, trainer.params, prompt,
                      max_new_tokens=args.max_new_tokens)
    sampled = generate(module, trainer.params, prompt,
                       max_new_tokens=args.max_new_tokens,
                       temperature=0.8, top_p=0.95,
                       rng=jax.random.PRNGKey(0))
    print("greedy :", np.asarray(greedy)[0].tolist())
    print("sampled:", np.asarray(sampled)[0].tolist())
    assert greedy.shape == (2, 4 + args.max_new_tokens)
    print("OK")


main()
