"""Headline benchmark: flagship GPT training throughput on one TPU chip.

Prints ONE JSON line ``{"metric", "value", "unit", "vs_baseline"}``.

The reference (`sxjscience/ray_lightning`) publishes no performance
numbers (BASELINE.md: ``"published": {}``), so ``vs_baseline`` is
reported as the ratio against the framework's own recorded target of
parity (1.0 ≡ established baseline; >1 is headroom over it).

Config: GPT-2-small-shaped model (124M params), bf16 activations, seq
1024, per-chip batch 8, full optimizer step (adamw + global-norm clip,
donated buffers) through the same ``build_train_step`` path the
strategies compile.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.core.module import TrainState
from ray_lightning_tpu.models.gpt import GPT, GPTConfig
from ray_lightning_tpu.parallel.step_fns import build_train_step

WARMUP_STEPS = 3
TIMED_STEPS = 10


def main() -> None:
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = GPTConfig(
            vocab_size=50304, n_layer=12, n_head=12, d_model=768,
            seq_len=1024, warmup_steps=10,
        )
        batch_size = 8
    else:
        # CPU fallback so the harness always produces a line.
        cfg = GPTConfig.tiny()
        batch_size = 4

    module = GPT(cfg)
    module.precision = "bf16"

    params = module.init_params(jax.random.PRNGKey(0))
    tx = module.configure_optimizers()
    state = TrainState.create(params, tx)
    step = build_train_step(module, tx, mesh=None)

    rng = jax.random.PRNGKey(0)
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(batch_size, cfg.seq_len + 1)
    ).astype(np.int32)
    batch = {"tokens": jnp.asarray(tokens)}

    for _ in range(WARMUP_STEPS):
        state, logs = step(state, batch, rng)
    # Synchronize via host transfer: on the experimental remote-TPU
    # platform block_until_ready can return before execution finishes,
    # but a device->host copy of the result cannot.
    float(logs["loss"])

    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        state, logs = step(state, batch, rng)
    loss = float(logs["loss"])
    dt = time.perf_counter() - t0
    assert np.isfinite(loss), f"non-finite loss {loss}"

    steps_per_sec = TIMED_STEPS / dt
    tokens_per_sec = steps_per_sec * batch_size * cfg.seq_len

    print(json.dumps({
        "metric": "gpt2_small_train_tokens_per_sec_per_chip"
        if on_tpu else "gpt_tiny_train_tokens_per_sec_cpu",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
