"""Headline benchmark: flagship GPT training throughput through Trainer.fit().

Prints ONE JSON line ``{"metric", "value", "unit", "vs_baseline", ...}``.

The north-star metric (BASELINE.md) is **``Trainer.fit()`` steps/sec/chip**
— so the timed path is the real user path: ``Trainer`` + strategy + loop +
prefetch + callbacks, NOT a raw ``build_train_step`` call.  The raw-step
path is measured alongside it and reported as ``fit_vs_raw`` (the loop
overhead budget: ≥ 0.95 means the Trainer path gives away <5%).

Noise discipline (VERDICT r3 weak #8): every number is the MEDIAN of
``WINDOWS`` independent steady-state timing windows, and the JSON carries
``spread_pct`` (full min→max range of the windows, % of the median) so a
±2% run-to-run wobble can't be misread as a regression.

The reference (`sxjscience/ray_lightning`) publishes no performance
numbers (BASELINE.md: ``"published": {}``), so ``vs_baseline`` is the
ratio against this framework's own first recorded number for the same
config family (BENCH_r01: 66,010 tokens/s/chip), making round-over-round
progress visible.

Config: GPT-2-small (124M params), bf16 activations, seq 1024, per-chip
batch 16, Pallas flash attention (fwd + fused bwd kernel), rematerialized
blocks, fused vocab-chunked cross-entropy (no (B,S,V) logits tensor),
full optimizer step (adamw + global-norm clip, donated buffers).

MFU is reported in BOTH conventions (VERDICT r3 weak #5c):
* ``mfu`` — standard 6N+full-attention accounting (the industry-default
  convention; comparable with published numbers and with rounds 1-3);
* ``mfu_executed`` — same accounting but the attention term halved, since
  the causal kernels never compute the masked upper triangle (FLOPs the
  hardware actually ran).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.core.module import TrainState
from ray_lightning_tpu.core.trainer import Trainer
from ray_lightning_tpu.models.gpt import GPT, GPTConfig, SyntheticLMDataModule
from ray_lightning_tpu.parallel.step_fns import build_train_step
from ray_lightning_tpu.parallel.strategies import LocalStrategy
# The analytic-FLOPs/peak accounting lives in the telemetry subsystem
# now (telemetry/step_stats.py) — bench and the live fit loop must agree
# on the MFU arithmetic by construction, not by copy.
from ray_lightning_tpu.telemetry import (
    model_flops_per_token,
    peak_flops_per_chip,
)

WARMUP_STEPS = 3
WINDOW_STEPS = 8          # steps per timing window
WINDOWS = 3               # median-of-k windows (k >= 3)
MEGASTEP_K = 8            # the host_overhead block's megastep A/B arm
# First recorded number for this config family (BENCH_r01.json, round 1:
# raw-step path, B=8, XLA-recompute attention backward).
R1_TOKENS_PER_SEC = 66010.1


def _median_spread(vals):
    vals = sorted(vals)
    med = vals[len(vals) // 2]
    spread_pct = 100.0 * (vals[-1] - vals[0]) / med if med else 0.0
    return med, spread_pct


class _StepTimer(Callback):
    """Times WINDOWS consecutive steady-state windows inside the fit loop.

    Sync discipline: device->host transfer of the loss (on the
    experimental remote-TPU platform ``block_until_ready`` can return
    before execution finishes, but a host copy cannot).

    Megastep-aware: the hook fires once per stride there, so marks are
    taken at threshold CROSSINGS (step may jump past the exact multiple)
    and each mark records the step count — window throughput divides by
    the steps a window actually covered, not a nominal constant.
    """

    def __init__(self):
        self.marks = []  # [(perf_counter, micro_step)]

    def on_train_batch_end(self, trainer, module, logs, batch_idx):
        step = trainer.micro_step if hasattr(trainer, "micro_step") else (
            trainer.global_step)
        threshold = WARMUP_STEPS + len(self.marks) * WINDOW_STEPS
        if step >= threshold and len(self.marks) <= WINDOWS:
            float(jax.device_get(logs["train_loss"]))
            self.marks.append((time.perf_counter(), step))

    def window_times(self):
        """Per-window (seconds, steps) pairs."""
        return [
            (b[0] - a[0], b[1] - a[1])
            for a, b in zip(self.marks, self.marks[1:])
        ]


def _bench_raw_step(module: GPT, cfg: GPTConfig, batch_size: int):
    """Median tokens/s through a bare build_train_step call (no Trainer)."""
    params = module.init_params(jax.random.PRNGKey(0))
    tx = module.configure_optimizers()
    state = TrainState.create(params, tx)
    step = build_train_step(module, tx, mesh=None)
    rng = jax.random.PRNGKey(0)
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(batch_size, cfg.seq_len + 1)
    ).astype(np.int32)
    batch = {"tokens": jnp.asarray(tokens)}
    for _ in range(WARMUP_STEPS):
        state, logs = step(state, batch, rng)
    float(jax.device_get(logs["loss"]))
    windows = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(WINDOW_STEPS):
            state, logs = step(state, batch, rng)
        loss = float(jax.device_get(logs["loss"]))
        windows.append(
            WINDOW_STEPS * batch_size * cfg.seq_len
            / (time.perf_counter() - t0)
        )
    assert np.isfinite(loss), f"non-finite loss {loss}"
    return _median_spread(windows)


def _bench_fit(module: GPT, cfg: GPTConfig, batch_size: int,
               megastep=None):
    """Median tokens/s through the real Trainer.fit() path.  Also
    returns the run's fleet telemetry report (the BENCH_* telemetry
    block, making the perf trajectory machine-comparable).
    ``megastep`` drives the A/B arm of the ``host_overhead`` block
    (None = the default auto resolution)."""
    timer = _StepTimer()
    total = WARMUP_STEPS + WINDOWS * WINDOW_STEPS + 1
    if isinstance(megastep, int) and megastep > 1:
        # Whole strides only: a ragged tail would fall back to the
        # per-step path and pay ITS first-use jit compile inside a
        # timed window — the A/B must measure steady-state strides.
        total = ((total + megastep - 1) // megastep) * megastep
    trainer = Trainer(
        strategy=LocalStrategy(megastep=megastep),
        max_epochs=1,
        limit_train_batches=total,
        limit_val_batches=0,
        enable_checkpointing=False,
        precision="bf16",
        log_every_n_steps=10_000,  # keep host syncs out of the hot loop
        callbacks=[timer],
    )
    dm = SyntheticLMDataModule(
        cfg, batch_size=batch_size, num_batches=total + 1,
    )
    trainer.fit(module, dm)
    times = timer.window_times()
    assert len(times) >= WINDOWS, (
        f"fit ended with {len(times)} timed windows (< {WINDOWS})"
    )
    assert np.isfinite(trainer.callback_metrics["train_loss"])
    # LocalStrategy data-parallels over every local device; the metric is
    # per-chip, so divide whole-host throughput by the device count (the
    # raw-step path is genuinely single-device, mesh=None).
    n_chips = jax.local_device_count()
    tps = [
        steps * batch_size * cfg.seq_len / dt / n_chips
        for dt, steps in times[:WINDOWS]
        if steps > 0
    ]
    med, spread = _median_spread(tps)
    monitor_events = len(trainer.monitor_report.get("events", []))
    return med, spread, trainer.telemetry_report, monitor_events, trainer


def _dispatches_per_opt_step(trainer) -> float:
    """Jit dispatches per optimizer update, from the fit's telemetry
    counters (the host-dispatch acceptance number: ~1.0 per-step,
    ~1/K under megastep)."""
    counters = trainer.telemetry_report.get("counters", {})
    dispatches = (counters.get("train_dispatches") or {}).get("mean")
    if not dispatches or not trainer.global_step:
        return None
    return round(float(dispatches) / trainer.global_step, 4)


def _bench_host_overhead(make_module, cfg, batch_size, fit_tps,
                         raw_tps, headline_trainer) -> dict:
    """The schema-gated ``host_overhead`` block: Trainer-path overhead
    (``fit_vs_raw``), dispatch accounting for the headline fit, and a
    megastep=MEGASTEP_K on/off A/B.  Best-effort per probe — a failed
    arm nulls its fields, never the headline line."""
    block = {
        "fit_vs_raw": round(fit_tps / raw_tps, 3) if raw_tps else None,
        "dispatches_per_opt_step": _dispatches_per_opt_step(
            headline_trainer
        ),
        "megastep_k": MEGASTEP_K,
        "megastep_dispatches_per_opt_step": None,
        "megastep_tokens_per_sec": None,
        "megastep_speedup": None,
    }
    try:
        mega_tps, _, _, _, mega_trainer = _bench_fit(
            make_module(), cfg, batch_size, megastep=MEGASTEP_K
        )
        block["megastep_tokens_per_sec"] = round(mega_tps, 1)
        block["megastep_speedup"] = (
            round(mega_tps / fit_tps, 3) if fit_tps else None
        )
        block["megastep_dispatches_per_opt_step"] = (
            _dispatches_per_opt_step(mega_trainer)
        )
    except Exception as e:  # noqa: BLE001 - probe must not cost the line
        sys.stderr.write(f"megastep A/B skipped: {e}\n")
    return block


def _bench_opt_state_block(cfg: GPTConfig, batch_size: int,
                           fit_tps) -> dict:
    """The schema-gated ``opt_state`` block: analytic persistent AdamW
    moment bytes under f32 vs block-scaled int8 (the >= 3.5x HBM-diet
    acceptance bar), the ACTIVE policy's bytes, a measured tiny-fit
    loss-parity probe (int8 vs f32 arm, the int8_ef grad-comm
    tolerance), and — when an explicit policy is active — the headline
    fit's tokens/s re-recorded under the arm's name (the headline
    already ran WITH the policy).  Best-effort per probe."""
    from dataclasses import replace as _replace

    from ray_lightning_tpu.models.optim import (
        opt_state_bytes,
        resolve_opt_state_dtype,
    )
    from ray_lightning_tpu.ops.optim_quant import DEFAULT_BLOCK_SIZE

    params = jax.eval_shape(
        GPT(cfg).init_params, jax.random.PRNGKey(0)
    )
    osd = resolve_opt_state_dtype(cfg.opt_state_dtype)
    block = {
        "dtype": osd or f"default(mu={cfg.mu_dtype})",
        "block_size": DEFAULT_BLOCK_SIZE,
        "bytes_f32": opt_state_bytes(params, "float32"),
        "bytes_int8": opt_state_bytes(params, "int8"),
        "bytes_active": opt_state_bytes(params, osd),
        "hbm_ratio": None,  # filled below
        "loss_rel_diff_vs_f32": None,
        "tokens_per_sec": None,
        "vs_baseline": None,
        # The sharded-update arm as configured for this invocation
        # (worker-side resolution happens against the real mesh).
        "update_sharding": os.environ.get("RLT_UPDATE_SHARDING", "auto"),
    }
    block["hbm_ratio"] = round(
        block["bytes_f32"] / max(block["bytes_int8"], 1), 3
    )
    try:
        # Parity is a numerics property, not a perf one — probe it on
        # the tiny config regardless of backend so every artifact
        # carries the number.
        def parity_fit(dtype):
            pcfg = _replace(GPTConfig.tiny(), opt_state_dtype=dtype)
            t = Trainer(
                strategy=LocalStrategy(), max_epochs=2,
                enable_checkpointing=False, log_every_n_steps=1,
            )
            t.fit(GPT(pcfg), SyntheticLMDataModule(
                pcfg, batch_size=8, num_batches=8))
            return float(t.callback_metrics["train_loss"])

        ref = parity_fit("float32")
        got = parity_fit("int8")
        block["loss_rel_diff_vs_f32"] = round(
            abs(got - ref) / max(abs(ref), 1e-12), 9
        )
    except Exception as e:  # noqa: BLE001 - probe must not cost the line
        sys.stderr.write(f"opt_state parity probe skipped: {e}\n")
    if osd is not None and fit_tps:
        # The headline fit already ran WITH the active policy (main()
        # bakes RLT_OPT_STATE_DTYPE into cfg before measuring), so it
        # IS this arm's measurement — re-fitting here would compare
        # the arm against itself.  Cross-arm speedups come from one
        # bench.py invocation per RLT_OPT_STATE_DTYPE value
        # (tools/hw_session.sh), read side by side.
        block["tokens_per_sec"] = round(fit_tps, 1)
    return block


def _bench_residual_policy_block(cfg: GPTConfig, batch_size: int,
                                 remat_policy: str, fit_tps,
                                 on_tpu: bool) -> dict:
    """The schema-gated ``residual_policy`` block: analytic remat-saved
    residual bytes of the active arm vs the ``dots+flash`` baseline
    (models/gpt.py:residual_save_bytes — the profiler's dynamic-
    update-slice lines are the chip truth), plus the measured headline
    tokens/s when the headline actually ran rematerialized (TPU; the
    CPU fallback fits remat=False, so its tokens carry no residual
    signal).  Cross-arm speedups come from running bench.py once per
    RLT_REMAT_POLICY value — tools/hw_session.sh does exactly that."""
    from ray_lightning_tpu.models.gpt import residual_save_bytes

    baseline = "dots+flash"
    arm = residual_save_bytes(cfg, batch_size, remat_policy, "bf16")
    base = residual_save_bytes(cfg, batch_size, baseline, "bf16")
    return {
        "policy": remat_policy,
        "baseline_policy": baseline,
        "residual_bytes_per_step": arm,
        "baseline_residual_bytes_per_step": base,
        "bytes_saved_pct": round(100.0 * (1 - arm / base), 2),
        "tokens_per_sec": round(fit_tps, 1) if on_tpu else None,
        "vs_baseline": None,
        # Numerics deltas are tolerance-pinned by tests/test_gpt.py;
        # the artifact records the accounting, not a re-measurement.
        "loss_rel_diff_vs_baseline": None,
    }


def _bench_boring_fit(tier, steps: int = 80) -> float:
    """Steady-state seconds/step of a boring-model fit at one telemetry
    config — tier string or full dict (the overhead probes' arm)."""
    from ray_lightning_tpu.models.boring import (
        BoringDataModule,
        BoringModel,
    )

    timer = _StepTimer()
    trainer = Trainer(
        strategy=LocalStrategy(telemetry=tier),
        max_epochs=1,
        limit_train_batches=steps,
        limit_val_batches=0,
        enable_checkpointing=False,
        log_every_n_steps=10_000,
        callbacks=[timer],
    )
    trainer.fit(
        BoringModel(),
        BoringDataModule(length=steps * 16 + 16, batch_size=16),
    )
    times = timer.window_times()
    assert len(times) >= WINDOWS
    return _median_spread(
        [dt / steps for dt, steps in times[:WINDOWS] if steps > 0]
    )[0]


def _telemetry_overhead_pct() -> float:
    """Measured per-step cost of the default cheap telemetry tier vs a
    telemetry-off run on the boring model — the precise record of the
    <1% acceptance budget (the smoke test asserts it loosely)."""
    off = _bench_boring_fit("off")
    cheap = _bench_boring_fit("cheap")
    return 100.0 * (cheap - off) / off if off else 0.0


def _heartbeat_overhead_pct(repeats: int = 3) -> float:
    """Measured per-step cost of the live heartbeat publisher
    (telemetry/heartbeat.py) vs the same cheap-tier fit with the
    publisher disabled.  Probed at 10x the default cadence (0.5s vs
    5s) so short bench fits see many beats — an upper bound on the
    production cost, recorded so BENCH_r06+ tracks it.

    Best-of-N per arm: single boring-model fits jitter far more than
    the publisher costs (observed ±40% run-to-run on the CPU mesh),
    and min-of-runs is the standard noise-robust floor estimator.
    """
    silent = min(
        _bench_boring_fit({"tier": "cheap", "heartbeat_s": 0})
        for _ in range(repeats)
    )
    beating = min(
        _bench_boring_fit({"tier": "cheap", "heartbeat_s": 0.5})
        for _ in range(repeats)
    )
    return 100.0 * (beating - silent) / silent if silent else 0.0


def _ledger_overhead_pct(repeats: int = 3) -> float:
    """Measured per-step cost of the program-ledger dispatch wrapper
    (telemetry/program_ledger.py) vs the same cheap-tier fit with the
    ledger killed (``RLT_PROGRAM_LEDGER=0`` builds bare ``jax.jit``).
    The steady-state path is one MRU try/except per dispatch (~0.2us
    micro-benchmarked), so this records a noise-floor bound, not a
    measurable cost.  Best-of-N per arm, like the heartbeat probe."""
    def _arm(value: str) -> float:
        prev = os.environ.get("RLT_PROGRAM_LEDGER")
        os.environ["RLT_PROGRAM_LEDGER"] = value
        try:
            return min(
                _bench_boring_fit("cheap") for _ in range(repeats)
            )
        finally:
            if prev is None:
                os.environ.pop("RLT_PROGRAM_LEDGER", None)
            else:
                os.environ["RLT_PROGRAM_LEDGER"] = prev

    bare = _arm("0")
    ledgered = _arm("1")
    return 100.0 * (ledgered - bare) / bare if bare else 0.0


def _bench_programs_block(snap: dict, tel_report: dict,
                          ledger_overhead_pct) -> dict:
    """The schema-gated ``programs`` block (telemetry/schema.py::
    validate_bench_programs): the headline fit's compiled-executable
    inventory — taken right after the fit, before the probe fits
    pollute the process-global ledger — plus the measured wrapper
    overhead and the HBM/roofline accounting for the train step."""
    from ray_lightning_tpu.telemetry import program_ledger

    rows = [
        {k: v for k, v in row.items()}
        for row in snap.get("programs", [])
        if row["site"].startswith(("train/", "eval/"))
    ]
    block: dict = {
        "n_programs": len(rows),
        "compile_time_total_s": round(
            float(snap.get("compile_time_total_s", 0.0)), 3
        ),
        "recompile_events": len(snap.get("recompiles", [])),
        "ledger_overhead_pct": ledger_overhead_pct,
        "rows": rows,
        "hbm": program_ledger.hbm_report(snap),
    }
    roof = program_ledger.roofline("train/step", snap=snap)
    if roof is not None:
        block["roofline"] = roof
    basis = (tel_report.get("meta") or {}).get("mfu_basis")
    if basis:
        block["mfu_basis"] = basis
    if snap.get("dropped"):
        block["dropped"] = snap["dropped"]
    return block


def _bench_fault_block() -> dict:
    """Recovery-cost probes for the schema-gated ``fault`` block
    (docs/FAULT_TOLERANCE.md): ``drain_checkpoint_s`` (step-granular
    drain write on an inline fit), ``time_to_recover_s`` (deterministic
    injected crash → training resumed, measured end-to-end as the wall
    delta against the same fit without the crash — respawn, backoff,
    checkpoint discovery and recompile all included), and ``backoff_s``
    (the jittered delay the governor actually slept).  Every probe is
    best-effort: a None field means the probe failed, never that the
    bench lied."""
    import tempfile

    from ray_lightning_tpu.core.callbacks import Callback as _CB
    from ray_lightning_tpu.fault import drain as drain_mod
    from ray_lightning_tpu.fault.drain import PreemptedError
    from ray_lightning_tpu.models.boring import (
        BoringDataModule,
        BoringModel,
    )
    from ray_lightning_tpu.parallel.strategies import RayStrategy

    block: dict = {"drain_checkpoint_s": None, "time_to_recover_s": None,
                   "backoff_s": None, "resize_time_to_recover_s": None,
                   "resize_old_world": None, "resize_new_world": None}

    class _DrainAt(_CB):
        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            if trainer.micro_step == 5:
                drain_mod.request_drain("bench")

    try:
        with tempfile.TemporaryDirectory(prefix="rlt_bench_drain_") as d:
            trainer = Trainer(
                strategy=LocalStrategy(), max_epochs=2,
                default_root_dir=d, limit_train_batches=8,
                limit_val_batches=0, enable_checkpointing=False,
                callbacks=[_DrainAt()],
            )
            try:
                trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
            except PreemptedError as err:
                if err.drain_s is not None:
                    block["drain_checkpoint_s"] = round(err.drain_s, 4)
    except Exception as e:  # noqa: BLE001 - probe must not cost the line
        sys.stderr.write(f"drain probe skipped: {e}\n")

    def _crash_fit(inject: bool) -> tuple:
        with tempfile.TemporaryDirectory(prefix="rlt_bench_crash_") as d:
            if inject:
                os.environ["RLT_FAULT"] = "crash@step:3,rank:0"
                os.environ["RLT_FAULT_STATE"] = os.path.join(d, "chaos")
            try:
                strategy = RayStrategy(
                    num_workers=1, max_restarts=1, restart_backoff_s=0.1,
                )
                trainer = Trainer(
                    strategy=strategy, max_epochs=3, default_root_dir=d,
                    limit_train_batches=2, limit_val_batches=0,
                    enable_checkpointing=False,
                )
                t0 = time.perf_counter()
                trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
                wall = time.perf_counter() - t0
                assert trainer.global_step == 6, trainer.global_step
                return wall, strategy.recovery_events
            finally:
                os.environ.pop("RLT_FAULT", None)
                os.environ.pop("RLT_FAULT_STATE", None)

    try:
        clean_wall, _ = _crash_fit(inject=False)
        crash_wall, events = _crash_fit(inject=True)
        block["time_to_recover_s"] = round(
            max(crash_wall - clean_wall, 0.0), 3
        )
        backoff = next(
            (e for e in events if e.get("kind") == "backoff"), None
        )
        if backoff is not None:
            block["backoff_s"] = backoff.get("delay_s")
    except Exception as e:  # noqa: BLE001
        sys.stderr.write(f"recovery probe skipped: {e}\n")

    # Elastic shrink probe (docs/FAULT_TOLERANCE.md "Elastic resume"):
    # a 2-worker fit loses worker 1 at spawn (lose_worker fault), the
    # governor respawns with the survivor, and the cost of the whole
    # detour — doomed attempt, kill, resize, re-discovery, recompile —
    # is the wall delta against the same fit run at 1 worker cleanly.
    def _shrink_fit() -> tuple:
        with tempfile.TemporaryDirectory(prefix="rlt_bench_resize_") as d:
            os.environ["RLT_FAULT"] = "lose_worker@point:spawn,rank:1"
            os.environ["RLT_FAULT_STATE"] = os.path.join(d, "chaos")
            try:
                strategy = RayStrategy(
                    num_workers=2, max_restarts=1,
                    restart_backoff_s=0.05, elastic_min_workers=1,
                )
                trainer = Trainer(
                    strategy=strategy, max_epochs=3, default_root_dir=d,
                    limit_train_batches=2, limit_val_batches=0,
                    enable_checkpointing=False,
                )
                t0 = time.perf_counter()
                trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
                wall = time.perf_counter() - t0
                assert trainer.global_step == 6, trainer.global_step
                assert strategy.active_workers == 1
                return wall, strategy.recovery_events
            finally:
                os.environ.pop("RLT_FAULT", None)
                os.environ.pop("RLT_FAULT_STATE", None)

    def _clean_one_worker_fit() -> float:
        with tempfile.TemporaryDirectory(prefix="rlt_bench_resize_") as d:
            strategy = RayStrategy(num_workers=1)
            trainer = Trainer(
                strategy=strategy, max_epochs=3, default_root_dir=d,
                limit_train_batches=2, limit_val_batches=0,
                enable_checkpointing=False,
            )
            t0 = time.perf_counter()
            trainer.fit(BoringModel(), BoringDataModule(batch_size=16))
            return time.perf_counter() - t0

    try:
        clean_wall = _clean_one_worker_fit()
        shrink_wall, events = _shrink_fit()
        block["resize_time_to_recover_s"] = round(
            max(shrink_wall - clean_wall, 0.0), 3
        )
        resize = next(
            (e for e in events if e.get("kind") == "resize"), None
        )
        if resize is not None:
            block["resize_old_world"] = resize.get("old_world")
            block["resize_new_world"] = resize.get("new_world")
    except Exception as e:  # noqa: BLE001
        sys.stderr.write(f"resize probe skipped: {e}\n")
    return block


def _bench_generate(module: GPT, cfg: GPTConfig, on_tpu: bool):
    """Greedy decode throughput (new tokens/s, whole batch) through the
    KV-cache generation path — f32/bf16 weights AND the int8-storage
    tree (models/quant.py), so the weight-traffic win is recorded.
    Strictly best-effort: any failure returns None rather than costing
    the headline training line."""
    try:
        from ray_lightning_tpu.models.generate import generate
        from ray_lightning_tpu.models.quant import quantize_decode_params

        B = 8 if on_tpu else 2
        new = 128 if on_tpu else 8
        t0_len = min(32, cfg.seq_len - new - 1)
        params = module.init_params(jax.random.PRNGKey(0))
        prompt = jnp.ones((B, t0_len), jnp.int32)
        fn = jax.jit(
            lambda p, pr: generate(module, p, pr, max_new_tokens=new)
        )

        def measure(tree):
            jax.block_until_ready(fn(tree, prompt))  # compile
            tps = []
            for _ in range(WINDOWS):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(tree, prompt))
                tps.append(B * new / (time.perf_counter() - t0))
            return round(_median_spread(tps)[0], 1)

        full = measure(params)
        try:
            q8 = measure(quantize_decode_params(params, cfg))
        except Exception as e:  # noqa: BLE001 - int8 arm is optional
            sys.stderr.write(f"int8 decode bench skipped: {e}\n")
            q8 = None
        return full, q8
    except Exception as e:  # pragma: no cover - defensive
        sys.stderr.write(f"generate bench skipped: {e}\n")
        return None, None


def _kernel_paths(cfg: GPTConfig, on_tpu: bool) -> dict:
    """Which compute path each optional Pallas kernel will take for THIS
    bench config — the Mosaic probe results (VERDICT r4 next #2: the
    bench artifact must say what it actually measured).  On CPU the
    kernels run under the Pallas interpreter, so probes are moot."""
    if not on_tpu:
        return {"mode": "cpu-interpret"}
    out: dict = {"mode": "tpu-mosaic"}
    try:
        from ray_lightning_tpu.ops.cross_entropy import (
            _kernel_path_available as ce_ok,
        )

        out["ce_pallas"] = bool(ce_ok(cfg.d_model, jnp.bfloat16))
    except Exception as e:  # noqa: BLE001 - report, don't fail the bench
        out["ce_pallas"] = f"probe error: {e}"
    try:
        from ray_lightning_tpu.ops.layer_norm import (
            _kernels_available as ln_ok,
        )

        out["ln_pallas"] = bool(ln_ok(cfg.d_model, jnp.bfloat16))
    except Exception as e:  # noqa: BLE001
        out["ln_pallas"] = f"probe error: {e}"
    try:
        # The REAL dispatch predicate (honors RLT_DISABLE_KERNELS), fed
        # the bench's q shape; ShapeDtypeStruct because only .shape is
        # consulted.
        from ray_lightning_tpu.ops.attention import _flash_supported

        out["flash_attention"] = bool(_flash_supported(
            jax.ShapeDtypeStruct(
                (1, cfg.seq_len, cfg.n_head, cfg.head_dim), jnp.bfloat16
            )
        ))
    except Exception as e:  # noqa: BLE001
        out["flash_attention"] = f"probe error: {e}"
    disabled = os.environ.get("RLT_DISABLE_KERNELS", "")
    if disabled:
        out["disabled_families"] = disabled
    return out


def _bench_mpmd(on_tpu: bool) -> dict:
    """The ``--mpmd`` A/B arm (schema: ``validate_bench_mpmd``): a
    2-stage mesh-of-meshes fit (in-process harness — same StageRunner
    code path the actor plane drives, minus spawn cost) vs the
    single-mesh SPMD GPipe formulation of the SAME model, plus the
    GPipe-vs-interleaved-1F1B bubble decomposition at measured per-op
    costs (docs/PERFORMANCE.md "Pipeline bubbles")."""
    from ray_lightning_tpu.models.gpt import GPTConfig as _Cfg
    from ray_lightning_tpu.mpmd.inproc import run_inproc_pipeline_fit
    from ray_lightning_tpu.mpmd.plan import _gpt_untie, gpt_mpmd_spec
    from ray_lightning_tpu.mpmd.reference import gpipe_reference_fit
    from ray_lightning_tpu.mpmd.schedule import (
        fleet_pipeline_stats,
        measured_schedule_bubble,
        pool_op_costs,
    )

    cfg = _Cfg(vocab_size=256, n_layer=4, n_head=4, d_model=64,
               seq_len=64, warmup_steps=2)
    module = GPT(cfg, attn_impl="xla")
    module.precision = "f32"
    spec = gpt_mpmd_spec(module)
    full = _gpt_untie(module.init_params(jax.random.PRNGKey(0)))
    steps, bsz, n_micro, interleave = 5, 16, 8, 2
    rng = np.random.default_rng(11)
    data = [
        {"tokens": rng.integers(
            0, cfg.vocab_size, (bsz, cfg.seq_len + 1)).astype(np.int32)}
        for _ in range(steps)
    ]
    devices = jax.devices()
    groups = [devices[0:2], devices[2:4]] if len(devices) >= 4 else None
    tokens_per_step = bsz * cfg.seq_len

    arms = {}
    for name, v in (("gpipe", 1), ("1f1b", interleave)):
        res = run_inproc_pipeline_fit(
            spec, full, spec.tx_factory, lambda s: data[s], steps,
            n_workers=2, n_micro=n_micro, schedule=name, interleave=v,
            device_groups=groups,
        )
        costs = pool_op_costs(res["op_costs"])
        loss_stats = res["step_summaries"][-1][1:]  # loss worker, warm
        wall = sum(s["wall_s"] for s in loss_stats)
        arms[name] = {
            "res": res,
            "costs": costs,
            "bubble": measured_schedule_bubble(name, 2, n_micro, v, costs),
            "tps": tokens_per_step * len(loss_stats) / max(wall, 1e-9),
        }

    # Single-mesh SPMD GPipe reference: warm the compile, then time.
    ref_devices = devices[:2]
    gpipe_reference_fit(spec, full, spec.tx_factory(),
                        lambda s: data[s], 1, 2, n_micro,
                        devices=ref_devices)
    t0 = time.perf_counter()
    ref = gpipe_reference_fit(spec, full, spec.tx_factory(),
                              lambda s: data[s], steps, 2, n_micro,
                              devices=ref_devices)
    ref_wall = time.perf_counter() - t0
    ref_tps = tokens_per_step * steps / max(ref_wall, 1e-9)

    head = arms["1f1b"]
    parity = float(np.max(np.abs(
        np.asarray(head["res"]["losses"]) - np.asarray(ref["losses"])
    )))
    fleet = fleet_pipeline_stats(head["res"]["per_stage_stats"])
    return {
        "schedule": "1f1b",
        "interleave": interleave,
        "n_stages": 2,
        "n_micro": n_micro,
        "bubble_fraction": round(head["bubble"], 4),
        "gpipe_bubble_fraction": round(arms["gpipe"]["bubble"], 4),
        "stage_occupancy": round(fleet["stage_occupancy"], 4),
        "stage_skew_ms": round(fleet["stage_skew_ms"], 3),
        "tokens_per_sec": round(head["tps"], 1),
        "single_mesh_tokens_per_sec": round(ref_tps, 1),
        "vs_single_mesh": round(head["tps"] / max(ref_tps, 1e-9), 3),
        "loss_parity_max_diff": parity,
        "op_costs_ms": {
            k: round(v * 1e3, 3) for k, v in head["costs"].items()
        },
    }


def _collectives_before_last_dot(hlo) -> "int | None":
    """HLO-structural overlap proof: count collective ops scheduled
    BEFORE the program's last matmul.  A step-end sync is data-
    dependence-ordered after every backward dot (count 0); the tapped
    backward interleaves its bucket collectives into the dot stream
    (count > 0).  On the CPU backend sharded collectives lower to
    all-to-all/all-gather; data dependence, not the scheduler, fixes
    their position, so the text order is trustworthy."""
    if not hlo:
        return None
    lines = hlo.splitlines()
    last_dot = max(
        (i for i, line in enumerate(lines) if " dot(" in line),
        default=None,
    )
    if last_dot is None:
        return None
    return sum(
        1 for line in lines[:last_dot]
        if "=" in line and ("all-to-all" in line or "all-gather" in line)
    )


def _bench_comm_overlap(on_tpu: bool) -> dict:
    """The schema-gated ``comm_overlap`` block (round 25): step-end vs
    backward-overlapped grad sync, both arms at grad_comm=int8_ef on a
    mesh over every local device.  Acceptance surface: loss parity at
    the EF tolerance, identical wire volume (bucket re-planning only
    pads), unchanged dispatches/opt-step, zero steady-state recompiles
    in both arms, and the HLO gate proving the overlapped arm's
    collectives are interleaved into the backward."""
    from ray_lightning_tpu.telemetry import program_ledger as _ledger

    cfg = GPTConfig.tiny()
    n_dev = jax.local_device_count()
    segments = 2
    steps = 6
    batch_size = max(8, n_dev)

    class _HloProbe(Callback):
        """Grab the step program's HLO MID-fit: the ledger's site
        registry holds the LedgeredFunction by weak reference, so the
        text is only reachable while the loop's step fn is alive."""

        def __init__(self):
            self.collectives = None

        def on_train_batch_end(self, trainer, module, logs, batch_idx):
            if self.collectives is None:
                self.collectives = _collectives_before_last_dot(
                    _ledger.hlo_text("train/step")
                )

    def run(seg):
        pre = len(_ledger.snapshot().get("recompiles", []))
        probe = _HloProbe()
        module = GPT(cfg, attn_impl="auto" if on_tpu else "xla")
        module.precision = "f32"
        trainer = Trainer(
            strategy=LocalStrategy(
                mesh_axes={"data": n_dev},
                grad_comm={"mode": "int8_ef", "dcn_only": False},
                grad_overlap_segments=seg,
            ),
            max_steps=steps,
            enable_checkpointing=False,
            limit_val_batches=0,
            log_every_n_steps=10_000,
            callbacks=[probe],
        )
        dm = SyntheticLMDataModule(
            cfg, batch_size=batch_size, num_batches=steps + 1,
        )
        trainer.fit(module, dm)
        events = _ledger.snapshot().get("recompiles", [])[pre:]
        return {
            "loss": float(trainer.callback_metrics["train_loss"]),
            "bytes": float(trainer.comm_stats["grad_sync_bytes"]),
            "dispatches": _dispatches_per_opt_step(trainer),
            # variant 0 events are cross-arm first compiles of a fresh
            # LedgeredFunction; steady-state recompiles re-lower an
            # EXISTING function (variant >= 1).
            "recompiles": sum(
                1 for e in events
                if e.get("site") == "train/step"
                and e.get("variant", 0) >= 1
            ),
            "collectives": probe.collectives,
        }

    a = run(0)          # step-end sync (the zero-risk default)
    b = run(segments)   # tapped backward
    rel = abs(b["loss"] - a["loss"]) / max(abs(a["loss"]), 1e-9)
    block = {
        "segments": segments,
        "mode": "int8_ef",
        "devices": n_dev,
        "loss_rel_diff": round(rel, 6),
        "loss_step_end": round(a["loss"], 6),
        "loss_overlap": round(b["loss"], 6),
        "grad_sync_bytes_step_end": a["bytes"],
        "grad_sync_bytes_overlap": b["bytes"],
        "bytes_ratio": round(b["bytes"] / max(a["bytes"], 1e-9), 4),
        "dispatches_per_opt_step_step_end": a["dispatches"],
        "dispatches_per_opt_step_overlap": b["dispatches"],
        "recompiles_step_end": a["recompiles"],
        "recompiles_overlap": b["recompiles"],
        "collectives_before_last_dot_step_end": a["collectives"],
        "collectives_before_last_dot_overlap": b["collectives"],
        "hlo_gate": (
            None if a["collectives"] is None or b["collectives"] is None
            else a["collectives"] == 0 and b["collectives"] > 0
        ),
    }

    # Quantized-DCN-wire probe: the in-proc 2-worker pipeline (the same
    # StageRunner code path the actor plane drives) at f32 vs the
    # bf16-act/int8-grad codec — loss parity + measured byte ratio.
    try:
        from ray_lightning_tpu.mpmd.inproc import run_inproc_pipeline_fit
        from ray_lightning_tpu.mpmd.plan import _gpt_untie, gpt_mpmd_spec

        mcfg = GPTConfig(vocab_size=256, n_layer=4, n_head=4, d_model=64,
                         seq_len=64, warmup_steps=2)
        mmod = GPT(mcfg, attn_impl="xla")
        mmod.precision = "f32"
        spec = gpt_mpmd_spec(mmod)
        full = _gpt_untie(mmod.init_params(jax.random.PRNGKey(0)))
        rng = np.random.default_rng(17)
        data = [
            {"tokens": rng.integers(
                0, mcfg.vocab_size, (8, mcfg.seq_len + 1)
            ).astype(np.int32)}
            for _ in range(3)
        ]
        arms = {
            enc: run_inproc_pipeline_fit(
                spec, full, spec.tx_factory, lambda s: data[s], 3,
                n_workers=2, n_micro=4, wire_dtype=enc,
            )
            for enc in ("f32", "act:bf16,grad:int8")
        }
        ref, q = arms["f32"], arms["act:bf16,grad:int8"]
        sent = sum(x["bytes_sent"] for x in q["xfer"])
        fullw = sum(x["bytes_full_width"] for x in q["xfer"])
        block["mpmd_wire_enc"] = "act:bf16,grad:int8"
        block["mpmd_wire_ratio"] = round(fullw / max(sent, 1), 4)
        block["mpmd_loss_rel_diff"] = round(
            max(
                abs(x - y) / max(abs(x), 1e-9)
                for x, y in zip(ref["losses"], q["losses"])
            ), 6,
        )
    except Exception as e:  # noqa: BLE001 - probe must not cost the block
        sys.stderr.write(f"comm_overlap mpmd wire probe skipped: {e}\n")
        block["mpmd_wire_enc"] = None
        block["mpmd_wire_ratio"] = None
        block["mpmd_loss_rel_diff"] = None
    return block


def _detect_backend() -> str:
    """Resolve the backend, degrading to CPU if the TPU runtime is
    unreachable (tunnel/service outage) — the harness must always get a
    JSON line; a missing-bench round is indistinguishable from a broken
    build."""
    try:
        return jax.default_backend()
    except RuntimeError as e:
        sys.stderr.write(f"TPU backend unavailable ({e}); CPU fallback\n")
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend()


def main() -> None:
    on_tpu = _detect_backend() == "tpu"
    if on_tpu:
        cfg = GPTConfig(
            vocab_size=50304, n_layer=12, n_head=12, d_model=768,
            seq_len=1024, warmup_steps=10,
        )
        batch_size = 16
    else:
        # CPU fallback so the harness always produces a line (batch must
        # split over however many virtual devices the host exposes).
        cfg = GPTConfig.tiny()
        batch_size = max(4, 2 * jax.local_device_count())

    # On-hardware A/B surface (PERFORMANCE.md prepared experiments):
    # RLT_REMAT_POLICY picks what the remat backward keeps;
    # RLT_OPT_STATE_DTYPE the optimizer-state storage precision
    # (float32 | bfloat16 | int8 — models/optim.py).
    remat_policy = os.environ.get("RLT_REMAT_POLICY", "dots+flash")
    opt_state_dtype = os.environ.get("RLT_OPT_STATE_DTYPE") or None
    if opt_state_dtype is not None:
        import dataclasses as _dc

        cfg = _dc.replace(cfg, opt_state_dtype=opt_state_dtype)

    def make_module():
        m = GPT(cfg, attn_impl="auto", remat=on_tpu,
                remat_policy=remat_policy)
        m.precision = "bf16"
        return m

    kernel_path = _kernel_paths(cfg, on_tpu)
    raw_tps, raw_spread = _bench_raw_step(make_module(), cfg, batch_size)
    # Headline fit pins megastep OFF so the metric stays comparable with
    # every prior round; the host_overhead block carries the fused arm.
    fit_tps, fit_spread, tel_report, monitor_events, fit_trainer = (
        _bench_fit(make_module(), cfg, batch_size, megastep="off")
    )
    # Ledger snapshot NOW: the probe fits below add their own programs
    # (and shape-change recompile events) to the process-global ledger;
    # the artifact's programs block must describe the headline fit.
    from ray_lightning_tpu.telemetry import program_ledger as _ledger

    headline_programs = _ledger.snapshot()
    try:
        host_overhead = _bench_host_overhead(
            make_module, cfg, batch_size, fit_tps, raw_tps, fit_trainer
        )
    except Exception as e:  # noqa: BLE001 - probe must not cost the line
        sys.stderr.write(f"host_overhead probes skipped: {e}\n")
        host_overhead = None
    gen_tps, gen_tps_int8 = _bench_generate(make_module(), cfg, on_tpu)
    try:
        overhead_pct = round(_telemetry_overhead_pct(), 3)
    except Exception as e:  # noqa: BLE001 - probe must not cost the line
        sys.stderr.write(f"telemetry overhead probe skipped: {e}\n")
        overhead_pct = None
    try:
        hb_overhead_pct = round(_heartbeat_overhead_pct(), 3)
    except Exception as e:  # noqa: BLE001 - same discipline
        sys.stderr.write(f"heartbeat overhead probe skipped: {e}\n")
        hb_overhead_pct = None
    try:
        ledger_overhead_pct = round(_ledger_overhead_pct(), 3)
    except Exception as e:  # noqa: BLE001 - same discipline
        sys.stderr.write(f"ledger overhead probe skipped: {e}\n")
        ledger_overhead_pct = None
    try:
        programs_block = _bench_programs_block(
            headline_programs, tel_report, ledger_overhead_pct
        )
    except Exception as e:  # noqa: BLE001 - same discipline
        sys.stderr.write(f"programs block skipped: {e}\n")
        programs_block = None
    try:
        fault_block = _bench_fault_block()
    except Exception as e:  # noqa: BLE001 - same discipline
        sys.stderr.write(f"fault probes skipped: {e}\n")
        fault_block = None
    mpmd_block = None
    if "--mpmd" in sys.argv[1:]:
        try:
            mpmd_block = _bench_mpmd(on_tpu)
        except Exception as e:  # noqa: BLE001 - same discipline
            sys.stderr.write(f"mpmd probes skipped: {e}\n")
    try:
        comm_overlap_block = _bench_comm_overlap(on_tpu)
    except Exception as e:  # noqa: BLE001 - same discipline
        sys.stderr.write(f"comm_overlap probes skipped: {e}\n")
        comm_overlap_block = None
    try:
        opt_state_block = _bench_opt_state_block(cfg, batch_size, fit_tps)
    except Exception as e:  # noqa: BLE001 - same discipline
        sys.stderr.write(f"opt_state probes skipped: {e}\n")
        opt_state_block = None
    try:
        residual_block = _bench_residual_policy_block(
            cfg, batch_size, remat_policy, fit_tps, on_tpu
        )
    except Exception as e:  # noqa: BLE001 - same discipline
        sys.stderr.write(f"residual_policy probes skipped: {e}\n")
        residual_block = None

    peak = peak_flops_per_chip() if on_tpu else None

    def mfu(attn):
        if peak is None:
            return None
        return round(fit_tps * model_flops_per_token(cfg, attn) / peak, 3)

    print(json.dumps({
        "metric": "gpt2_small_trainer_fit_tokens_per_sec_per_chip"
        if on_tpu else "gpt_tiny_trainer_fit_tokens_per_sec_cpu",
        "value": round(fit_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(fit_tps / R1_TOKENS_PER_SEC, 3)
        if on_tpu else 1.0,
        "steps_per_sec": round(fit_tps / (batch_size * cfg.seq_len), 3),
        "raw_step_tokens_per_sec": round(raw_tps, 1),
        "fit_vs_raw": round(fit_tps / raw_tps, 3),
        "mfu": mfu("full"),
        "mfu_executed": mfu("causal"),
        "spread_pct": round(fit_spread, 2),
        "raw_spread_pct": round(raw_spread, 2),
        "generate_tokens_per_sec": gen_tps,
        "generate_tokens_per_sec_int8": gen_tps_int8,
        "kernel_path": {
            **kernel_path,
            # The active state-precision and remat arms ride the
            # kernel-path record: an artifact must say which program it
            # measured or round comparisons silently mix arms.
            "opt_state_dtype": opt_state_dtype or "default",
            "remat_policy": remat_policy,
        },
        "remat_policy": remat_policy,
        # Machine-comparable telemetry block (schema:
        # telemetry/schema.py, gated by tools/check_telemetry_schema.py):
        # the fit run's step-time breakdown + the measured cost of the
        # always-on cheap tier.
        "telemetry": {
            # The tier the fit ACTUALLY ran at (RLT_TELEMETRY may have
            # overridden the cheap default; an artifact claiming a tier
            # that never ran would poison round comparisons).
            "tier": tel_report.get("tier") or "off",
            "overhead_pct": overhead_pct,
            # Live-plane cost + activity (docs/OBSERVABILITY.md "Live
            # monitoring"): publisher overhead measured at 10x the
            # default cadence, and the headline fit's monitor event
            # count.  NOTE: the headline fit runs LocalStrategy, whose
            # inline path has no RunMonitor — this stays 0 until the
            # bench fit moves to a remote strategy; it is recorded so
            # the schema (and any future remote bench) carries it.
            "heartbeat_overhead_pct": hb_overhead_pct,
            "monitor_events": monitor_events,
            "report": {
                "step_stats": tel_report.get("step_stats", {}),
                "counters": tel_report.get("counters", {}),
            },
        },
        # Compiled-executable observatory (schema-gated): the headline
        # fit's program inventory with compile/cost/memory accounting,
        # recompile-forensics count, and the measured dispatch-wrapper
        # overhead (docs/OBSERVABILITY.md "Program ledger").
        "programs": programs_block,
        # Recovery cost in the perf trajectory (schema-gated like the
        # telemetry block): injected-crash recovery wall time, drain-
        # checkpoint write time, observed backoff delay.
        "fault": fault_block,
        # Host-dispatch accounting (schema-gated): the Trainer-path
        # overhead budget, jit dispatches per optimizer step, and the
        # megastep on/off A/B (docs/PERFORMANCE.md "Host dispatch &
        # megastep").
        "host_overhead": host_overhead,
        # MPMD pipeline A/B (--mpmd; schema-gated): mesh-of-meshes
        # tokens/sec vs the single-mesh GPipe formulation + the
        # GPipe-vs-interleaved-1F1B bubble decomposition.
        **({"mpmd": mpmd_block} if mpmd_block is not None else {}),
        # Backward-overlapped grad sync A/B (schema-gated): loss parity,
        # wire-volume invariance, zero-recompile pins, the HLO
        # interleaving proof, and the quantized MPMD wire probe
        # (docs/PERFORMANCE.md "Comm/compute overlap").
        "comm_overlap": comm_overlap_block,
        # HBM-traffic diet (schema-gated): optimizer-state precision
        # accounting + parity, and the scan-residual-compression arm
        # (docs/PERFORMANCE.md "Optimizer-state precision & update
        # sharding").
        "opt_state": opt_state_block,
        "residual_policy": residual_block,
        "windows": WINDOWS,
        "window_steps": WINDOW_STEPS,
        "bottleneck": "attention bwd kernel + scan residual-save HBM "
        "traffic; LM-head matmul (skinny 50304x768 @ ~55% MXU)"
        if on_tpu else "cpu fallback",
    }))


if __name__ == "__main__":
    main()
