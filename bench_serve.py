"""Serving SLO bench: continuous batching under a Poisson load generator.

Prints ONE JSON line with a schema-gated ``serve`` block
(``telemetry/schema.py::validate_bench_serve``, wired into
``tools/check_telemetry_schema.py``) — the serving half of the perf
trajectory alongside ``bench.py``'s training line.

Three phases, all through the REAL :class:`ServeEngine` path:

1. **warmup** — compile every program the steady state needs (one
   prefill per bucket the traffic uses + the one decode program), then
   pin the telemetry recompile counter;
2. **headline (closed loop)** — saturating load: every request
   submitted at once, uniform shape, engine driven to idle.  Reports
   ``requests_per_sec`` / ``tokens_per_sec`` / token-latency
   percentiles, asserts ZERO steady-state recompiles, and runs the A/B:
   the SAME request set through sequential one-at-a-time
   ``generate()`` calls (compiled once, warmed) →
   ``continuous_vs_sequential`` — the acceptance bar is ≥ 1.5x at
   batch-capable load;
3. **rate sweep (open loop)** — Poisson arrivals at fractions of the
   measured capacity; each arm reports offered vs achieved rate, TTFT
   and token-latency percentiles — the latency-vs-load curve an SLO is
   set against.

Methodology notes (docs/SERVING.md): the load generator is
deterministic (seeded exponential inter-arrivals); latency families
are nearest-rank percentiles over the phase's full token stream; the
sequential baseline uses the same prompt shapes so neither arm pays a
compile or padding tax the other doesn't.

A fourth phase benches **speculative decoding** (the ``spec_decode``
block, ``validate_bench_spec_decode``): a shallow draft proposes
``RLT_SPEC_K`` (default 4) tokens per tick and the deeper target
verifies them in one fixed-width dispatch, A/B'd against the same
target on a plain (non-spec) engine.  The draft/target pair is
CONSTRUCTED, not trained: the target is the draft plus identity tail
blocks (``serve/draft.py::pad_identity_layers``) — full-depth compute,
draft-equal logits — so the headline arm measures the program
machinery at a known ~1.0 acceptance rate, and the acceptance sweep
perturbs the tail to scan realistic acceptance regimes without
training anything.

A sixth phase benches **distributed tracing** (the ``trace`` block,
``validate_bench_trace``): an inproc disaggregated fleet (replicas +
prefill worker behind the router) runs with request tracing ON, its
per-component span exports are stitched
(``telemetry/trace_collect.py``), and the block reports stitch
coverage (fraction of completed requests with a complete
``queue_wait → … → first_token`` phase chain — the ≥0.95 bar),
per-phase p50/p95, and the measured closed-loop headline overhead of
cheap-tier tracing (ONE monolith engine toggling its tracer flag,
median of adjacent alternating on/off pairs — the <2% bar).

A seventh phase benches **multi-tenant LoRA multiplexing** (the
``multi_lora`` block, ``validate_bench_multi_lora``):
``RLT_MAX_ADAPTERS`` (default 8) tenants' adapters stacked in ONE
resident engine's pool (``serve/lora.py``) and served as mixed batches
— per-slot ``adapter_ids`` operands, so any tenant mix shares the
compiled-once program set — A/B'd against the **merge-and-swap**
baseline (fold tenant k's factors into the weights, upload, serve its
requests alone, swap for the next tenant: the pre-pool shape where
every tenant needs its own resident merged copy).  Two of the tenants
hot-join THROUGH the pool mid-load; both arms pin their steady-state
recompile counters at ZERO, every tenant's multiplexed stream is
token-for-token its merged baseline's (``greedy_parity``), and
``fairness_spread`` reports min/max lifetime tokens across tenants
under the uniform offered load.

An eighth phase benches **prefix-aware KV reuse** (the
``prefix_cache`` block, ``validate_bench_prefix_cache``): a
shared-system-prompt mix (every request is the same 6-block prefix
plus a unique one-block tail, ``prefix_share`` ≈ 0.86) through a
cache-on engine — resident prefix blocks claimed by refcount, only
the unique tail prefilled through the suffix chunk program — A/B'd
against the same mix on a cache-off engine.  Sequential closed loop
(one request in flight), so the TTFT percentiles are the prefill path
itself; acceptance is ``ttft_speedup`` ≥ 1.5x with bitwise token
parity, a live hit-rate, and steady-state recompiles pinned at ZERO
in both arms.  ``RLT_PREFIX_CACHE=0`` skips the phase.

A ninth phase calibrates the **SLO & capacity plane** (the ``slo``
block, ``validate_bench_slo``): a fresh plane-on engine serves a cold
(0.5x capacity) Poisson arm from which the headroom oracle
(``serve/capacity.py``) must PREDICT the saturation knee — per-slot
service rate is load-invariant, so half load calibrates the ceiling —
then a hot (1.5x) arm measures the real knee (±20% bar) and must trip
the multi-window burn-rate alert (``telemetry/slo.py``) that the cold
arm kept silent.  Steady-state recompiles stay pinned at ZERO with
the plane on, and the plane's closed-loop overhead (ONE engine
toggling the plane, median of adjacent alternating-order pairs) must
sit under the 2% bar.  ``RLT_SLO=0`` skips the phase.

A fifth phase benches **disaggregated serving** (the ``serve_disagg``
block, ``validate_bench_serve_disagg``): a real actor fleet —
``RLT_DISAGG_REPLICAS`` (default 2) decode replicas +
``RLT_DISAGG_PREFILL`` (default 1) prefill workers, each its own
process — behind the load-aware router, driven open-loop at a
fraction of measured monolith capacity, reporting throughput vs the
monolith (process contention makes this an honest <1x on the 2-core
CPU container; the TPU arm in tools/hw_session.sh is where
disaggregation pays) and pinning per-replica steady-state recompiles
at ZERO from the replicas' beat counters.  The **chaos arm** then
SIGKILLs the busiest decode replica mid-sweep under Poisson load:
zero lost requests (failover re-submission onto survivors), with
failover detection latency and client-deduped re-emission counts in
the block.  ``RLT_DISAGG_REPLICAS=0`` skips the phase.

The final phase is the **serving-chaos A/B** (the ``serve_chaos``
block, ``validate_bench_serve_chaos``): a planned drain with
``RLT_MIGRATE_ON_DRAIN=1`` live-migrates resident KV blocks +
scheduler position to a survivor (decode resumes mid-sequence, zero
recomputed prefill) while an abrupt kill takes the recompute-failover
path; both arms report time-to-recovery (the migration must beat the
failover), bitwise parity vs an uninterrupted monolith — sampled AND
greedy — zero lost requests, and steady-state recompiles pinned at
ZERO.  The full fault x recovery matrix (beat blackhole, torn
handoff, shm vanish, hedging, brownout) lives in
``tools/chaos_serve_sweep.py``.  ``RLT_SERVE_CHAOS=0`` skips the
phase.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.models.generate import generate
from ray_lightning_tpu.models.gpt import GPT, GPTConfig
from ray_lightning_tpu.serve.draft import pad_identity_layers
from ray_lightning_tpu.serve.engine import ServeConfig, ServeEngine
from ray_lightning_tpu.serve.metrics import ServeStats
from ray_lightning_tpu.telemetry import compile_event_count
from ray_lightning_tpu.telemetry.schema import (
    validate_bench_multi_lora, validate_bench_prefix_cache,
    validate_bench_serve, validate_bench_serve_chaos,
    validate_bench_serve_disagg, validate_bench_slo,
    validate_bench_spec_decode, validate_bench_trace,
)

PROMPT_LEN = 16
MAX_NEW = 16
HEADLINE_REQUESTS = 48
SWEEP_REQUESTS = 24
SWEEP_FRACTIONS = (0.5, 0.9, 1.5)   # of measured closed-loop capacity
SPEC_REQUESTS = 16
# Longer generations than the headline arm: speculation pays per decode
# tick, so the arm amortizes its (two-model) prefill cost the way a
# real serving mix does.
SPEC_MAX_NEW = 32
SPEC_NOISE_SWEEP = (0.002, 0.01)    # identity-tail perturbation scales


def _detect_backend() -> str:
    try:
        return jax.default_backend()
    except RuntimeError as e:
        sys.stderr.write(f"TPU backend unavailable ({e}); CPU fallback\n")
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend()


def _prompts(n: int, vocab: int, length: int = PROMPT_LEN,
             seed: int = 0) -> list:
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, size=(length,)).tolist()
            for _ in range(n)]


def _lat(snapshot: dict, family: str, q: str):
    return (snapshot["latency"].get(family) or {}).get(q)


def _closed_loop(engine: ServeEngine, prompts: list) -> dict:
    """Saturating load: submit everything, drive to idle."""
    engine.stats = ServeStats()
    handles = [engine.submit(p, MAX_NEW) for p in prompts]
    t0 = time.perf_counter()
    engine.run_until_idle()
    wall = time.perf_counter() - t0
    assert all(h.done() for h in handles)
    snap = engine.snapshot()
    return {
        "wall_s": wall,
        "completed": snap["counters"]["completed"],
        "tokens_out": snap["counters"]["tokens_out"],
        "snapshot": snap,
    }


def _sequential(module: GPT, params, prompts: list) -> dict:
    """The A/B baseline: one-at-a-time static-path generate() —
    compiled once for the shared shape, warmed before timing."""
    fn = jax.jit(
        lambda p, pr: generate(module, p, pr, max_new_tokens=MAX_NEW)
    )
    prompt0 = jnp.asarray([prompts[0]], jnp.int32)
    jax.block_until_ready(fn(params, prompt0))  # compile
    t0 = time.perf_counter()
    for p in prompts:
        jax.block_until_ready(fn(params, jnp.asarray([p], jnp.int32)))
    wall = time.perf_counter() - t0
    return {"wall_s": wall,
            "requests_per_sec": len(prompts) / wall,
            "tokens_per_sec": len(prompts) * MAX_NEW / wall}


def _poisson_arm(engine: ServeEngine, prompts: list, rate_rps: float,
                 seed: int) -> dict:
    """Open loop: submit on a seeded exponential arrival schedule while
    the engine thread serves, then wait for the tail."""
    import random

    engine.stats = ServeStats()
    rng = random.Random(seed)
    handles = []
    t0 = time.perf_counter()
    next_t = 0.0
    for p in prompts:
        next_t += rng.expovariate(rate_rps)
        lag = t0 + next_t - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        handles.append(engine.submit(p, MAX_NEW))
    deadline = time.perf_counter() + 120
    for h in handles:
        h._done.wait(max(0.0, deadline - time.perf_counter()))
    # Drain stragglers of an overloaded arm INTO THIS ARM's stats —
    # the caller swaps engine.stats next, and a request finishing after
    # the swap would corrupt the next arm's completed/latency numbers.
    while engine.scheduler.has_work():
        if time.perf_counter() > deadline + 60:
            sys.stderr.write(
                "bench_serve: rate arm failed to drain within its "
                "deadline — sweep numbers for later arms are suspect\n"
            )
            break
        time.sleep(0.01)
    wall = time.perf_counter() - t0
    snap = engine.snapshot()
    return {
        "offered_rps": round(rate_rps, 3),
        "requests_per_sec": round(snap["counters"]["completed"] / wall, 3),
        "p50_token_latency_ms": _lat(snap, "token", "p50_ms"),
        "p99_token_latency_ms": _lat(snap, "token", "p99_ms"),
        "p50_ttft_ms": _lat(snap, "ttft", "p50_ms"),
        "p99_ttft_ms": _lat(snap, "ttft", "p99_ms"),
        "completed": snap["counters"]["completed"],
        "expired": snap["counters"]["expired"],
        "rejected": snap["counters"]["rejected"],
    }


def _spec_arm(target, target_params, serve_cfg: ServeConfig,
              prompts: list, draft=None, draft_params=None) -> dict:
    """One closed-loop pass on a fresh engine: warmup (compiles), then
    the timed saturating load with the recompile counter pinned."""
    eng = ServeEngine(
        target, target_params, serve_cfg,
        draft_module=draft, draft_params=draft_params,
    )
    for p in prompts[:2]:
        eng.generate(p, SPEC_MAX_NEW)
    eng.stats = ServeStats()
    before = compile_event_count()
    handles = [eng.submit(p, SPEC_MAX_NEW) for p in prompts]
    t0 = time.perf_counter()
    eng.run_until_idle()
    wall = time.perf_counter() - t0
    assert all(h.done() for h in handles)
    snap = eng.snapshot()
    counters = snap["counters"]
    drafted = counters.get("spec_drafted", 0)
    return {
        "tokens": [h.result(0) for h in handles],
        "tokens_per_sec": counters["tokens_out"] / wall,
        "recompiles": int(compile_event_count() - before),
        "acceptance_rate": (
            counters.get("spec_accepted", 0) / drafted if drafted else None
        ),
        "drafted": drafted,
        "accepted": counters.get("spec_accepted", 0),
        "emitted": counters.get("spec_emitted", 0),
    }


def _spec_block(on_tpu: bool) -> dict:
    """The speculative-decoding A/B: draft + identity-tail target pair,
    spec vs non-spec closed loop, then the acceptance-rate sweep."""
    spec_k = int(os.environ.get("RLT_SPEC_K", "4") or 4)
    if on_tpu:
        draft_cfg = GPTConfig(vocab_size=50304, n_layer=2, n_head=12,
                              d_model=768, seq_len=1024, warmup_steps=10)
        n_extra, serve_cfg = 10, ServeConfig(num_slots=16, block_size=32,
                                             spec_k=spec_k)
    else:
        # Same weight-streaming-regime sizing rationale as the headline
        # arm: the 2-layer draft is ~1/6 the per-token weight traffic
        # of the 12-layer target, which is where drafting pays — a
        # tiny-draft/large-target pair, not two near-equals.
        draft_cfg = GPTConfig(vocab_size=512, n_layer=2, n_head=8,
                              d_model=512, seq_len=128, warmup_steps=2)
        n_extra, serve_cfg = 10, ServeConfig(num_slots=8, block_size=16,
                                             spec_k=spec_k)
    draft = GPT(draft_cfg, attn_impl="auto")
    if on_tpu:
        draft.precision = "bf16"
    draft_params = draft.init_params(jax.random.PRNGKey(0))
    target, target_params = pad_identity_layers(
        draft, draft_params, n_extra
    )
    prompts = _prompts(SPEC_REQUESTS, draft_cfg.vocab_size, seed=42)
    base_cfg = ServeConfig(num_slots=serve_cfg.num_slots,
                           block_size=serve_cfg.block_size)
    baseline = _spec_arm(target, target_params, base_cfg, prompts)
    spec = _spec_arm(target, target_params, serve_cfg, prompts,
                     draft=draft, draft_params=draft_params)
    sweep = []
    for noise in SPEC_NOISE_SWEEP:
        noisy, noisy_params = pad_identity_layers(
            draft, draft_params, n_extra, noise=noise
        )
        arm = _spec_arm(noisy, noisy_params, serve_cfg, prompts,
                        draft=draft, draft_params=draft_params)
        # The perturbed target costs exactly the clean target's compute
        # (same shapes, different values), so the clean baseline arm is
        # the denominator for every sweep point.
        sweep.append({
            "noise": noise,
            "acceptance_rate": round(arm["acceptance_rate"], 4),
            "tokens_per_sec": round(arm["tokens_per_sec"], 1),
            "vs_baseline": round(
                arm["tokens_per_sec"] / baseline["tokens_per_sec"], 3
            ),
        })
    return {
        "spec_k": spec_k,
        "draft_layers": draft_cfg.n_layer,
        "target_layers": draft_cfg.n_layer + n_extra,
        "tokens_per_sec": round(spec["tokens_per_sec"], 1),
        "baseline_tokens_per_sec": round(baseline["tokens_per_sec"], 1),
        "vs_baseline": round(
            spec["tokens_per_sec"] / baseline["tokens_per_sec"], 3
        ),
        "acceptance_rate": round(spec["acceptance_rate"], 4),
        "recompiles_steady_state": spec["recompiles"],
        "baseline_recompiles_steady_state": baseline["recompiles"],
        "drafted": spec["drafted"],
        "accepted": spec["accepted"],
        "emitted": spec["emitted"],
        "greedy_parity": spec["tokens"] == baseline["tokens"],
        "requests": SPEC_REQUESTS,
        "max_new_tokens": SPEC_MAX_NEW,
        "acceptance_sweep": sweep,
    }


DISAGG_REQUESTS = 24
DISAGG_CHAOS_REQUESTS = 24


def _fleet_recompiles(router, ids, timeout=15.0) -> dict:
    """Per-replica compile-event counters from FRESH beats: wait out at
    least one beat interval so the reading postdates the work being
    measured, then require a recent beat from every queried replica."""
    time.sleep(0.6)  # > 2 beat intervals at the fleet default 0.25s
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        snap = router.snapshot()
        entries = {r["id"]: r for r in snap["replicas"]
                   if r["id"] in ids and r.get("alive")}
        if len(entries) == len(ids) and all(
            "recompiles" in e
            and e.get("last_beat_age_s") is not None
            and e["last_beat_age_s"] < 1.0
            for e in entries.values()
        ):
            return {rid: e["recompiles"] for rid, e in entries.items()}
        time.sleep(0.1)
    snap = router.snapshot()
    return {r["id"]: r.get("recompiles", 0) for r in snap["replicas"]
            if r["id"] in ids}


def _disagg_poisson(client, prompts, rate_rps, seed,
                    kill_at=None, kill_fn=None):
    """Open-loop Poisson submission through the router; returns
    (rids, killed_at_index).  ``kill_fn`` fires once after the
    ``kill_at``-th submission — the mid-sweep chaos trigger."""
    import random

    rng = random.Random(seed)
    rids = []
    t0 = time.perf_counter()
    next_t = 0.0
    killed = None
    for i, p in enumerate(prompts):
        next_t += rng.expovariate(rate_rps)
        lag = t0 + next_t - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        rids.append(client.submit(p, MAX_NEW))
        if kill_fn is not None and killed is None and i + 1 >= kill_at:
            kill_fn()
            killed = i
    return rids, killed


def _disagg_block(module, params, serve_cfg, monolith_rps,
                  cfg) -> dict:
    """Phase 5: the disaggregated fleet A/B + kill-a-replica chaos."""
    from ray_lightning_tpu.serve.client import ServeClient
    from ray_lightning_tpu.serve.dist import launch_actor_fleet

    n_replicas = int(os.environ.get("RLT_DISAGG_REPLICAS", "2") or 2)
    n_prefill = int(os.environ.get("RLT_DISAGG_PREFILL", "1") or 1)
    fleet = launch_actor_fleet(
        module, params, serve_cfg, n_replicas=n_replicas,
        n_prefill=n_prefill, lost_after_s=2.0,
    )
    client = ServeClient(fleet.queue_handle())
    replica_ids = [r.id for r in fleet.replicas]
    try:
        # Warmup: every replica compiles its bucket prefill/import +
        # decode programs (uniform prompt length = one bucket; spread
        # enough requests that least-loaded placement hits them all).
        warm = [client.submit(p, MAX_NEW)
                for p in _prompts(4 * n_replicas, cfg.vocab_size,
                                  seed=100)]
        for rid in warm:
            client.result(rid, timeout=600)
        base_rec = _fleet_recompiles(fleet.router, replica_ids)

        # Headline: open loop at ~0.9x monolith capacity.
        rate = max(0.9 * monolith_rps, 0.5)
        t0 = time.perf_counter()
        rids, _ = _disagg_poisson(
            client, _prompts(DISAGG_REQUESTS, cfg.vocab_size, seed=201),
            rate, seed=21,
        )
        completed = 0
        for rid in rids:
            try:
                client.result(rid, timeout=600)
                completed += 1
            except Exception:  # noqa: BLE001 - counted below
                pass
        wall = time.perf_counter() - t0
        after_rec = _fleet_recompiles(fleet.router, replica_ids)
        recompiles = sum(after_rec.get(r, 0) - base_rec.get(r, 0)
                         for r in replica_ids)
        rps = completed / wall

        # Chaos arm: SIGKILL the busiest replica mid-sweep.
        client.re_emitted_tokens = 0
        survivor_base = dict(after_rec)

        def kill_busiest():
            with fleet.router._lock:
                loads = {r: 0 for r in replica_ids}
                for t in fleet.router._inflight.values():
                    if t.replica in loads:
                        loads[t.replica] += 1
            victim_id = max(loads, key=lambda r: loads[r])
            next(r for r in fleet.replicas
                 if r.id == victim_id).kill(hard=True)
            kill_busiest.victim = victim_id

        t0 = time.perf_counter()
        rids, _ = _disagg_poisson(
            client, _prompts(DISAGG_CHAOS_REQUESTS, cfg.vocab_size,
                             seed=202),
            rate, seed=22,
            kill_at=DISAGG_CHAOS_REQUESTS // 3, kill_fn=kill_busiest,
        )
        chaos_completed, lost = 0, 0
        for rid in rids:
            try:
                client.result(rid, timeout=600)
                chaos_completed += 1
            except Exception:  # noqa: BLE001 - every non-completion is
                # a LOST request; the acceptance bar is zero
                lost += 1
        victim = getattr(kill_busiest, "victim", replica_ids[0])
        survivors = [r for r in replica_ids if r != victim]
        surv_rec = _fleet_recompiles(fleet.router, survivors)
        survivor_recompiles = sum(
            surv_rec.get(r, 0) - survivor_base.get(r, 0)
            for r in survivors
        )
        counters = fleet.router.counters
        detect = fleet.router.last_failover_detect_s
        with fleet.router._lock:
            kv_imports = sum(
                (m.snapshot or {}).get("counters", {}).get(
                    "kv_imports", 0)
                for m in fleet.router._replicas.values()
            )
        return {
            "replicas": n_replicas,
            "prefill_workers": n_prefill,
            "requests": DISAGG_REQUESTS,
            "requests_per_sec": round(rps, 3),
            "monolith_requests_per_sec": round(monolith_rps, 3),
            "vs_monolith": round(rps / monolith_rps, 3),
            "kv_imports": int(kv_imports),
            "prefill_dispatches": counters["prefill_dispatches"],
            "recompiles_steady_state": int(recompiles),
            "chaos": {
                "killed_replica": victim,
                "submitted": DISAGG_CHAOS_REQUESTS,
                "completed": chaos_completed,
                "lost_requests": lost,
                "failed_over_requests":
                    counters["failed_over_requests"],
                "failover_detect_s": (
                    None if detect is None else round(detect, 3)
                ),
                "re_emitted_tokens": client.re_emitted_tokens,
                "survivor_recompiles_steady_state":
                    int(survivor_recompiles),
                "offered_rps": round(rate, 3),
            },
        }
    finally:
        client.close()
        fleet.close()


# Longer generations than the headline arm: the drain has to land
# while the disturbed stream still has decode left to migrate, and the
# failover arm's recompute cost (what migration avoids) scales with
# the tokens already generated at kill time.  The kill lands at 3/4 of
# the stream — the rolling-restart shape, where long-running sequences
# are resident at drain time and recompute-from-zero is at its most
# expensive (inproc members are detected dead instantly via their
# thread handle, so the unplanned arm pays no detection window here;
# recomputed work is the whole difference being measured).
CHAOS_MAX_NEW = 96
CHAOS_KILL_AT = 3 * CHAOS_MAX_NEW // 4


def _serve_chaos_disturb(module, params, serve_cfg, ref, *, hard):
    """One disturbance arm of the migration-vs-failover A/B: launch a
    two-replica inproc fleet, start a sampled stream + a greedy
    companion, take the placed replica down (``hard=False`` = planned
    drain, ``hard=True`` = abrupt death) and measure time-to-recovery as
    kill -> first FRESH token AFTER the router booked the recovery (the
    counter anchor keeps a straggler token already in flight from
    under-measuring TTR).  Returns the arm's booking dict."""
    from ray_lightning_tpu.serve.client import ServeClient
    from ray_lightning_tpu.serve.dist import launch_inproc_fleet

    counter = "failovers" if hard else "migrations"
    fleet = launch_inproc_fleet(
        module, params, serve_cfg, n_replicas=2, n_prefill=0,
        lost_after_s=0.5,
    )
    client = ServeClient(fleet.queue_handle())
    try:
        prompts = _prompts(2, module.config.vocab_size, seed=303)
        r1 = client.submit(prompts[0], CHAOS_MAX_NEW, temperature=0.7)
        r2 = client.submit(prompts[1], CHAOS_MAX_NEW)

        def streaming():
            track = fleet.router._inflight.get(r1)
            return (track is not None and track.replica is not None
                    and len(client._pending[r1].tokens) >= CHAOS_KILL_AT)

        deadline = time.perf_counter() + 60
        while not streaming():
            if time.perf_counter() > deadline:
                raise RuntimeError("disturbed stream never started")
            time.sleep(0.01)
        victim = fleet.router._inflight[r1].replica
        t_kill = time.perf_counter()
        next(r for r in fleet.replicas if r.id == victim).kill(hard=hard)
        deadline = time.perf_counter() + 60
        while fleet.router.counters[counter] < 1:
            if time.perf_counter() > deadline:
                raise RuntimeError(f"router never booked a {counter!r}")
            time.sleep(0.01)
        n_base = len(client._pending[r1].tokens)
        while len(client._pending[r1].tokens) <= n_base:
            if time.perf_counter() > deadline:
                raise RuntimeError("stream never resumed post-recovery")
            time.sleep(0.005)
        ttr = time.perf_counter() - t_kill

        lost = 0
        outs = []
        for rid in (r1, r2):
            try:
                outs.append(client.result(rid, timeout=600))
            except Exception:  # noqa: BLE001 - booked as a lost request
                lost += 1
                outs.append(None)
        parity = all(o is not None and o == r
                     for o, r in zip(outs, ref))
        re_emitted = client.re_emitted_tokens

        # Steady-state pin AFTER recovery: a second wave must replay
        # every compiled program (the one cold kv_import executable is
        # allowed to compile DURING recovery, never after it).
        before = compile_event_count()
        w1 = client.submit(prompts[0], CHAOS_MAX_NEW, temperature=0.7)
        w2 = client.submit(prompts[1], CHAOS_MAX_NEW)
        client.result(w1, timeout=600)
        client.result(w2, timeout=600)
        steady = compile_event_count() - before
        counters = fleet.router.counters
        return {
            "ttr_s": round(ttr, 3),
            "lost": lost,
            "parity": parity,
            "re_emitted": re_emitted,
            "steady": int(steady),
            "migrations": counters["migrations"],
            "failovers": counters["failovers"],
        }
    finally:
        client.close()
        fleet.close()


def _serve_chaos_block(module, params, serve_cfg) -> dict:
    """Phase 10: planned-drain live KV migration vs recompute failover.

    The A/B behind the rolling-restart story: arm A drains the placed
    replica with ``RLT_MIGRATE_ON_DRAIN=1`` (resident KV blocks +
    scheduler position move to the survivor, decode resumes
    mid-sequence, zero recomputed prefill); arm B SIGKILL-style kills
    it (recompute failover, the client dedups re-emitted tokens).  Both
    arms must stream bitwise-identical tokens vs an uninterrupted
    monolith engine — sampled AND greedy — lose nothing, and leave no
    cold executables behind.  The full fault matrix lives in
    ``tools/chaos_serve_sweep.py``; this block pins the headline
    numbers per bench round."""
    ref_eng = ServeEngine(module, params, serve_cfg)
    prompts = _prompts(2, module.config.vocab_size, seed=303)
    ref = (ref_eng.generate(prompts[0], CHAOS_MAX_NEW, temperature=0.7),
           ref_eng.generate(prompts[1], CHAOS_MAX_NEW))
    ref_eng.stop()

    os.environ["RLT_MIGRATE_ON_DRAIN"] = "1"
    try:
        # Unmeasured warmup drain: the survivor's kv_import program
        # compiles on the first migration this process ever runs; pay
        # that once here so the measured arm reports steady-state TTR
        # (compile time is the ledger's to book, not a latency number
        # to smuggle into the A/B).
        _serve_chaos_disturb(module, params, serve_cfg, ref,
                             hard=False)
        mig = _serve_chaos_disturb(module, params, serve_cfg, ref,
                                   hard=False)
    finally:
        os.environ.pop("RLT_MIGRATE_ON_DRAIN", None)
    failover = _serve_chaos_disturb(module, params, serve_cfg, ref,
                                    hard=True)
    return {
        "requests": 4,
        "migrations": mig["migrations"],
        "migration_ttr_s": mig["ttr_s"],
        "failover_ttr_s": failover["ttr_s"],
        # Speedup of the planned path over the unplanned one: drain
        # skips the lost_after_s detection window AND the recomputed
        # prefill, so this must land >= 1.
        "migration_vs_failover": round(
            failover["ttr_s"] / max(mig["ttr_s"], 1e-9), 3
        ),
        "lost_requests": mig["lost"] + failover["lost"],
        "parity": mig["parity"] and failover["parity"],
        "migration_re_emitted_tokens": mig["re_emitted"],
        "failover_re_emitted_tokens": failover["re_emitted"],
        "recompiles_steady_state": mig["steady"] + failover["steady"],
    }


LORA_REQUESTS_PER_TENANT = 2
LORA_MAX_NEW = 16
LORA_RANK = 8


def _lora_tenants(cfg, params, n: int, seed: int = 7):
    """``(adapters, merged)`` for ``n`` synthetic tenants of one base
    (``models/gpt.py::synthetic_lora_adapter``), each tenant's merged
    tree kept as the baseline arm's resident copy — computed OUTSIDE
    any timed section (merging is offline prep in the swap workflow;
    the swap itself — the weight upload — is what the timed arm
    pays)."""
    import dataclasses

    from ray_lightning_tpu.models.gpt import synthetic_lora_adapter

    lora_cfg = dataclasses.replace(cfg, lora_rank=LORA_RANK)
    rng = jax.random.PRNGKey(seed)
    adapters, merged = {}, {}
    for i in range(n):
        rng, ki = jax.random.split(rng)
        adapter, merged_tree = synthetic_lora_adapter(
            params, lora_cfg, ki, scale=0.05
        )
        adapters[f"tenant{i}"] = adapter
        merged[f"tenant{i}"] = jax.tree.map(np.asarray, merged_tree)
    return adapters, merged


def _multi_lora_block(module, params, serve_cfg: ServeConfig) -> dict:
    """Phase 7: N-tenant multiplexed pool vs merge-and-swap baseline."""
    n = int(os.environ.get("RLT_MAX_ADAPTERS", "8") or 8)
    cfg = module.config
    prompts = _prompts(n * LORA_REQUESTS_PER_TENANT, cfg.vocab_size,
                       seed=55)
    adapters, merged = _lora_tenants(cfg, params, n)
    names = sorted(adapters)
    hot = names[-2:] if n > 2 else []       # join through the pool
    preloaded = {k: adapters[k] for k in names if k not in hot}

    # -- multiplexed arm: ONE resident base, mixed-tenant batches -------
    mux_cfg = ServeConfig(
        num_slots=serve_cfg.num_slots, block_size=serve_cfg.block_size,
        max_adapters=n, adapter_rank=LORA_RANK,
        # The closed loop submits every request before the first drain:
        # the admission queue must hold the whole wave or the default
        # bound (64) rejects the tail at the hw sweep's 64 tenants.
        max_queue=max(64, n * LORA_REQUESTS_PER_TENANT),
    )
    eng = ServeEngine(module, params, mux_cfg, adapters=preloaded)
    for p in prompts[:2]:
        eng.generate(p, LORA_MAX_NEW)       # warm every program
    eng.stats = ServeStats()
    before = compile_event_count()
    t0 = time.perf_counter()
    handles: dict = {k: [] for k in names}
    for r in range(LORA_REQUESTS_PER_TENANT):
        for i, name in enumerate(names):
            if name in hot and not eng.adapters.has(name):
                eng.add_adapter(name, adapters[name])   # hot join
            handles[name].append(eng.submit(
                prompts[r * n + i], LORA_MAX_NEW, adapter=name,
            ))
    eng.run_until_idle()
    mux_wall = time.perf_counter() - t0
    mux_recompiles = int(compile_event_count() - before)
    snap = eng.snapshot()
    mux_tokens = snap["counters"]["tokens_out"]
    spread = snap["gauges"]["lora_fairness_spread"]
    impl = eng.adapters.impl
    pool_loads = eng.adapters.loads
    mux_streams = {k: [h.result(0) for h in hs]
                   for k, hs in handles.items()}
    eng.stop()

    # -- merge-and-swap baseline: one tenant resident at a time --------
    base_cfg = ServeConfig(num_slots=serve_cfg.num_slots,
                           block_size=serve_cfg.block_size)
    beng = ServeEngine(module, params, base_cfg)
    for p in prompts[:2]:
        beng.generate(p, LORA_MAX_NEW)      # warm the shared programs
    beng.stats = ServeStats()
    before = compile_event_count()
    t0 = time.perf_counter()
    base_streams: dict = {}
    for i, name in enumerate(names):
        # The swap: tenant k's merged copy becomes the resident model
        # (same shapes/dtypes — weights are operands, so no recompile;
        # the cost is the upload plus losing cross-tenant batching).
        beng.params = jax.device_put(merged[name])
        hs = [beng.submit(prompts[r * n + i], LORA_MAX_NEW)
              for r in range(LORA_REQUESTS_PER_TENANT)]
        beng.run_until_idle()
        base_streams[name] = [h.result(0) for h in hs]
    base_wall = time.perf_counter() - t0
    base_recompiles = int(compile_event_count() - before)
    base_tokens = beng.stats.counters["tokens_out"]
    beng.stop()

    parity = all(mux_streams[k] == base_streams[k] for k in names)
    return {
        "adapters": n,
        "rank": LORA_RANK,
        "requests": n * LORA_REQUESTS_PER_TENANT,
        "max_new_tokens": LORA_MAX_NEW,
        "tokens_per_sec": round(mux_tokens / mux_wall, 1),
        "baseline_tokens_per_sec": round(base_tokens / base_wall, 1),
        "vs_baseline": round(
            (mux_tokens / mux_wall) / (base_tokens / base_wall), 3
        ),
        "fairness_spread": round(float(spread), 4),
        "recompiles_steady_state": mux_recompiles,
        "baseline_recompiles_steady_state": base_recompiles,
        "greedy_parity": parity,
        "hot_adds": len(hot),
        "pool_loads": int(pool_loads),
        "bgmv_impl": impl,
        "completed": n * LORA_REQUESTS_PER_TENANT,
    }


PREFIX_REQUESTS = 16
PREFIX_MAX_NEW = 8
PREFIX_SHARED_BLOCKS = 6    # shared system-prompt prefix, whole blocks
PREFIX_UNIQUE_BLOCKS = 1    # per-request unique tail


def _prefix_prompts(cfg, block_size: int, seed: int = 91,
                    share_pct: int = 100) -> tuple:
    """A shared-prefix request mix: ``share_pct``% of the prompts are
    the SAME ``PREFIX_SHARED_BLOCKS``-block system prefix followed by
    a unique one-block tail — the many-users-one-system-prompt shape
    prefix caching exists for — and the rest are fully unique
    same-length prompts (cache misses by construction).
    ``RLT_PREFIX_SHARE`` sweeps this axis on hardware sessions.
    Returns ``(prompts, prefix_share)`` with the share measured in
    TOKENS across the whole mix."""
    rng = np.random.default_rng(seed)
    shared = rng.integers(
        1, cfg.vocab_size, size=(PREFIX_SHARED_BLOCKS * block_size,)
    ).tolist()
    total = (PREFIX_SHARED_BLOCKS + PREFIX_UNIQUE_BLOCKS) * block_size
    carriers = max(1, round(PREFIX_REQUESTS * share_pct / 100))
    prompts = [
        shared + rng.integers(
            1, cfg.vocab_size,
            size=(PREFIX_UNIQUE_BLOCKS * block_size,),
        ).tolist()
        if i < carriers else
        rng.integers(1, cfg.vocab_size, size=(total,)).tolist()
        for i in range(PREFIX_REQUESTS)
    ]
    share = carriers * len(shared) / (PREFIX_REQUESTS * total)
    return prompts, share


def _prefix_arm(module, params, serve_cfg: ServeConfig, prompts: list,
                prefix_on: bool) -> dict:
    """One sequential closed loop on a fresh engine (one request in
    flight at a time, so TTFT is the prefill path and nothing else).
    Warmup covers every program the arm uses — the full-bucket prefill
    AND (cache arm) the suffix chunk program plus a resident chain —
    then the recompile counter is pinned across the timed pass."""
    eng = ServeEngine(module, params, ServeConfig(
        num_slots=serve_cfg.num_slots, block_size=serve_cfg.block_size,
        prefix_cache=prefix_on,
    ))
    try:
        # Two warm requests sharing the mix's prefix: the first
        # compiles the cold full-bucket prefill (and seeds the chain),
        # the second compiles the claimed-suffix program on the cache
        # arm.  Distinct tails keep them out of the measured set.
        rng = np.random.default_rng(977)
        tail = len(prompts[0]) - PREFIX_SHARED_BLOCKS * serve_cfg.block_size
        for _ in range(2):
            warm = prompts[0][: PREFIX_SHARED_BLOCKS
                              * serve_cfg.block_size]
            warm += rng.integers(1, module.config.vocab_size,
                                 size=(tail,)).tolist()
            eng.generate(warm, PREFIX_MAX_NEW)
        eng.stats = ServeStats()
        before = compile_event_count()
        tokens = []
        t0 = time.perf_counter()
        for p in prompts:
            h = eng.submit(p, PREFIX_MAX_NEW)
            eng.run_until_idle()
            tokens.append(h.result(0))
        wall = time.perf_counter() - t0
        recompiles = int(compile_event_count() - before)
        snap = eng.snapshot()
        return {
            "tokens": tokens,
            "wall_s": wall,
            "tokens_per_sec": snap["counters"]["tokens_out"] / wall,
            "ttft_p50_ms": _lat(snap, "ttft", "p50_ms"),
            "recompiles": recompiles,
            "prefix": snap.get("prefix"),
            "prefill_chunks": snap["counters"].get("prefill_chunks", 0),
        }
    finally:
        eng.stop()


def _prefix_cache_block(module, params, serve_cfg: ServeConfig,
                        cfg) -> dict:
    """Phase 8: prefix-aware KV reuse A/B — the same shared-prefix mix
    through a cache-on and a cache-off engine.  The cache arm claims
    the resident prefix by refcount and prefills only the unique tail;
    the headline is the TTFT win, with both arms' steady-state
    recompile counters pinned and bitwise token parity required."""
    share_pct = int(os.environ.get("RLT_PREFIX_SHARE", "100") or 100)
    prompts, share = _prefix_prompts(cfg, serve_cfg.block_size,
                                     share_pct=share_pct)
    cached = _prefix_arm(module, params, serve_cfg, prompts, True)
    baseline = _prefix_arm(module, params, serve_cfg, prompts, False)
    pstats = cached["prefix"] or {}
    return {
        "prefix_share": round(share, 4),
        "requests": PREFIX_REQUESTS,
        "max_new_tokens": PREFIX_MAX_NEW,
        "hit_rate": pstats.get("hit_rate", 0.0),
        "blocks_claimed": int(pstats.get("blocks_claimed", 0)),
        "blocks_inserted": int(pstats.get("blocks_inserted", 0)),
        "cached_blocks": int(pstats.get("cached_blocks", 0)),
        "prefill_chunks": int(cached["prefill_chunks"]),
        "ttft_p50_ms": cached["ttft_p50_ms"],
        "baseline_ttft_p50_ms": baseline["ttft_p50_ms"],
        "ttft_speedup": round(
            baseline["ttft_p50_ms"] / cached["ttft_p50_ms"], 3
        ),
        "tokens_per_sec": round(cached["tokens_per_sec"], 1),
        "baseline_tokens_per_sec": round(
            baseline["tokens_per_sec"], 1
        ),
        "recompiles_steady_state": cached["recompiles"],
        "baseline_recompiles_steady_state": baseline["recompiles"],
        "token_parity": cached["tokens"] == baseline["tokens"],
    }


TRACE_REQUESTS = 24
TRACE_AB_REQUESTS = 24


def _trace_block(module, params, serve_cfg, cfg) -> dict:
    """Phase 6: stitch coverage on an inproc disagg fleet + the
    tracing-overhead A/B on a monolith engine."""
    import shutil
    import tempfile

    from ray_lightning_tpu.serve.client import ServeClient
    from ray_lightning_tpu.serve.dist import launch_inproc_fleet
    from ray_lightning_tpu.telemetry import trace_collect

    # -- overhead A/B: traced vs untraced closed loop ---------------------
    # ONE engine, toggling its tracer flag between passes: identical
    # programs, pool, and allocation history, so the delta is EXACTLY
    # the instrumentation cost.  (Two separate engines measure their
    # own construction-order memory-placement skew — observed ~10% on
    # this container, an order of magnitude above the tracing signal.)
    # The headline is the MEDIAN of adjacent alternating-pair deltas
    # (see the comment at the pair loop); min-wall per arm feeds only
    # the informational rps fields.
    prompts = _prompts(TRACE_AB_REQUESTS, cfg.vocab_size, seed=77)

    def closed_wall(eng):
        eng.stats = ServeStats()
        handles = [eng.submit(p, MAX_NEW) for p in prompts]
        t0 = time.perf_counter()
        eng.run_until_idle()
        wall = time.perf_counter() - t0
        assert all(h.done() for h in handles)
        return wall

    trace_tmp = tempfile.mkdtemp(prefix="rlt_trace_ab_")
    eng = ServeEngine(module, params, serve_cfg, trace_dir=trace_tmp)
    try:
        for p in prompts[:2]:
            eng.generate(p, MAX_NEW)      # warm every program
        closed_wall(eng)                  # one untimed shakeout pass
        # Adjacent pairs with alternating order, MEDIAN of per-pair
        # deltas: the container's throughput drifts for tens of
        # seconds after phase 5's actor teardown, and a min-per-arm
        # over interleaved rounds reads that monotone drift as a
        # multi-percent phantom speedup; per-pair deltas see only the
        # drift ACROSS one adjacent pair, and alternating the order
        # flips its sign pair to pair.
        deltas = []
        base_wall = traced_wall = None
        for pair in range(6):
            order = (False, True) if pair % 2 == 0 else (True, False)
            walls = {}
            for traced in order:
                eng.tracer.enabled = traced
                walls[traced] = closed_wall(eng)
            deltas.append(
                100.0 * (walls[True] - walls[False]) / walls[False]
            )
            base_wall = (walls[False] if base_wall is None
                         else min(base_wall, walls[False]))
            traced_wall = (walls[True] if traced_wall is None
                           else min(traced_wall, walls[True]))
        deltas.sort()
        overhead_pct = deltas[len(deltas) // 2]
        eng.tracer.enabled = True  # export a real trace at stop
    finally:
        eng.stop()
        shutil.rmtree(trace_tmp, ignore_errors=True)

    # -- stitch coverage: traced inproc disagg fleet ----------------------
    stitch_tmp = tempfile.mkdtemp(prefix="rlt_trace_stitch_")
    try:
        # lost_after_s effectively OFF: this phase runs right after the
        # actor-fleet teardown, and an inproc member's beat thread
        # starving past the 1s default would read as a death — the
        # router's (correct) direct-submission fallback would then
        # drop handoff legs from the committed phase chains.
        fleet = launch_inproc_fleet(
            module, params, serve_cfg, n_replicas=2, n_prefill=1,
            lost_after_s=30.0, trace_dir=stitch_tmp,
        )
        client = ServeClient(fleet.queue_handle())
        try:
            rids = [client.submit(p, MAX_NEW)
                    for p in _prompts(TRACE_REQUESTS, cfg.vocab_size,
                                      seed=78)]
            for rid in rids:
                client.result(rid, timeout=600)
            # Completions land router-side on the next beat; the root
            # "request" spans the coverage check counts are recorded
            # there.
            deadline = time.perf_counter() + 10
            while (fleet.router.snapshot()["counters"]["completed"]
                   < TRACE_REQUESTS
                   and time.perf_counter() < deadline):
                time.sleep(0.05)
        finally:
            client.close()
            fleet.close()  # members export their span JSONL here
        spans = trace_collect.load_trace_dir(stitch_tmp)
        complete, total, frac = trace_collect.coverage(spans)
        phases = trace_collect.phase_percentiles(spans)
        sys.stderr.write(
            trace_collect.format_report(spans, slowest_k=3) + "\n"
        )
    finally:
        shutil.rmtree(stitch_tmp, ignore_errors=True)

    return {
        "coverage": round(frac, 4),
        "requests": TRACE_REQUESTS,
        "complete_chains": complete,
        "spans": len(spans),
        "overhead_pct": round(overhead_pct, 3),
        "traced_requests_per_sec": round(
            len(prompts) / traced_wall, 3
        ),
        "baseline_requests_per_sec": round(
            len(prompts) / base_wall, 3
        ),
        "replicas": 2,
        "prefill_workers": 1,
        "phases": phases,
    }


SLO_ARM_S = 10.0            # wall-clock per Poisson alert arm
# Longer passes + more pairs than the tracing A/B: the plane's true
# cost is a few per-export-tick dict folds, so per-pass wall noise —
# not the effect — is what the median has to beat.
SLO_AB_REQUESTS = 48
SLO_AB_PAIRS = 8
# Serving-horizon window pairs for the bench arms: the stock
# minutes-scale defaults would dilute a 10 s overload arm into noise.
SLO_BENCH_WINDOWS = ((1.0, 4.0, 6.0), (2.0, 8.0, 3.0))


def _slo_block(module, params, serve_cfg: ServeConfig, cfg,
               cont_rps: float) -> dict:
    """Phase 9: SLO & capacity-oracle calibration (the ``slo`` block,
    ``validate_bench_slo``).  A fresh plane-on engine serves a cold
    (0.5x capacity) Poisson arm — from which the headroom oracle must
    PREDICT the saturation knee before ever seeing overload — then a
    hot (1.5x) arm measures the real knee and must trip the burn-rate
    alert the cold arm kept silent.  The overhead A/B rides a second
    engine toggling the plane between closed-loop passes (median of
    adjacent alternating-order pairs — the tracing round's
    methodology)."""
    ts_interval = float(
        os.environ.get("RLT_TS_INTERVAL_S", "0.25") or 0.25
    )
    slo_cfg = ServeConfig(
        num_slots=serve_cfg.num_slots, block_size=serve_cfg.block_size,
        capacity=True, slo=True, ts_interval_s=ts_interval,
        export_every_s=ts_interval, slo_windows=SLO_BENCH_WINDOWS,
        # The hot arm holds a standing backlog by design; the queue
        # must absorb it rather than reject (rejections would shed the
        # very overload the alert exists to see).
        max_queue=4096,
    )
    eng = ServeEngine(module, params, slo_cfg)
    oracle = eng.capacity_oracle
    evaluator = eng.slo_evaluator
    # Duration-sized arms: request counts scale with measured capacity
    # so every machine sees ~SLO_ARM_S of sustained load — queue-wait
    # growth under overload is a time-scale effect (backlog grows at
    # 0.5x the service rate, so waits ramp ~0.5 s/s regardless of how
    # fast the chip is), which is what keeps the stock 500 ms bound
    # meaningful across hosts.
    n_cold = max(16, int(0.5 * cont_rps * SLO_ARM_S))
    n_hot = max(24, int(1.5 * cont_rps * SLO_ARM_S))
    cold_prompts = _prompts(n_cold, cfg.vocab_size, seed=311)
    hot_prompts = _prompts(n_hot, cfg.vocab_size, seed=312)
    try:
        for p in cold_prompts[:2]:
            eng.generate(p, MAX_NEW)        # warm every program
        before = compile_event_count()
        eng.start()
        try:
            cold = _poisson_arm(eng, cold_prompts,
                                rate_rps=max(0.5 * cont_rps, 0.5),
                                seed=91)
            alerts_cold = evaluator.alerts_total
            # The oracle calls the knee from cold-arm data alone: the
            # per-slot service rate is load-invariant (each decode tick
            # costs the full width whether 2 or 8 slots are live), so
            # half-load suffices to calibrate the ceiling.
            predicted = oracle.predict_saturation_rps(
                MAX_NEW, window_s=SLO_ARM_S
            )
            hot = _poisson_arm(eng, hot_prompts,
                               rate_rps=max(1.5 * cont_rps, 0.75),
                               seed=92)
            alerts_hot = evaluator.alerts_total - alerts_cold
            hot_cap = oracle.snapshot(window_s=SLO_ARM_S / 2)
        finally:
            eng.stop()
        recompiles = int(compile_event_count() - before)
        ts_points = len(oracle.store.points())
    finally:
        if eng._thread is not None:  # belt: stop() already joined
            eng.stop()
    measured = hot["requests_per_sec"]
    err_pct = None
    if predicted and measured:
        err_pct = 100.0 * abs(predicted - measured) / measured

    # -- overhead A/B: plane on vs off, ONE engine ------------------------
    ab = ServeEngine(module, params, slo_cfg)
    ab_prompts = _prompts(SLO_AB_REQUESTS, cfg.vocab_size, seed=313)
    plane = (ab._capacity, ab._slo)

    def set_plane(on: bool) -> None:
        ab._capacity, ab._slo = plane if on else (None, None)

    def closed_wall() -> float:
        ab.stats = ServeStats()
        handles = [ab.submit(p, MAX_NEW) for p in ab_prompts]
        t0 = time.perf_counter()
        ab.run_until_idle()
        wall = time.perf_counter() - t0
        assert all(h.done() for h in handles)
        return wall

    try:
        for p in ab_prompts[:2]:
            ab.generate(p, MAX_NEW)
        closed_wall()                       # untimed shakeout
        deltas = []
        for pair in range(SLO_AB_PAIRS):
            order = (False, True) if pair % 2 == 0 else (True, False)
            walls = {}
            for on in order:
                set_plane(on)
                walls[on] = closed_wall()
            deltas.append(
                100.0 * (walls[True] - walls[False]) / walls[False]
            )
        deltas.sort()
        overhead_pct = deltas[len(deltas) // 2]
    finally:
        set_plane(True)
        ab.stop()

    return {
        "predicted_saturation_rps": (
            None if predicted is None else round(predicted, 3)
        ),
        "measured_saturation_rps": round(measured, 3),
        "prediction_error_pct": (
            None if err_pct is None else round(err_pct, 2)
        ),
        "alerts_hot": int(alerts_hot),
        "alerts_cold": int(alerts_cold),
        "recompiles_steady_state": recompiles,
        "overhead_pct": round(overhead_pct, 3),
        "capacity_tokens_per_s": hot_cap.get("capacity_tokens_per_s"),
        "service_rate_per_slot": hot_cap.get("service_rate_per_slot"),
        "hot_rps": hot["requests_per_sec"],
        "cold_rps": cold["requests_per_sec"],
        "hot_utilization": hot_cap.get("utilization"),
        "ts_points": ts_points,
    }


def main() -> None:
    on_tpu = _detect_backend() == "tpu"
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, n_layer=12, n_head=12,
                        d_model=768, seq_len=1024, warmup_steps=10)
        serve_cfg = ServeConfig(num_slots=16, block_size=32)
    else:
        # NOT GPTConfig.tiny(): a 1.6 MB-weight model fits in L2, so
        # CPU decode is dispatch-bound and an A/B there measures python
        # overhead, not batching.  ~13M params (~50 MB f32) puts
        # single-token decode in the weight-streaming regime serving
        # actually lives in — each decode step reads every weight once
        # whether it serves 1 token or num_slots of them.
        cfg = GPTConfig(vocab_size=512, n_layer=4, n_head=8,
                        d_model=512, seq_len=128, warmup_steps=2)
        serve_cfg = ServeConfig(num_slots=8, block_size=16)
    module = GPT(cfg, attn_impl="auto")
    if on_tpu:
        module.precision = "bf16"
    params = module.init_params(jax.random.PRNGKey(0))
    engine = ServeEngine(module, params, serve_cfg)
    prompts = _prompts(HEADLINE_REQUESTS, cfg.vocab_size)

    # Phase 1: warmup — compile the bucket + decode programs.
    for p in prompts[:2]:
        engine.generate(p, MAX_NEW)
    compiles_before = compile_event_count()

    # Phase 2: closed-loop headline + sequential A/B.
    closed = _closed_loop(engine, prompts)
    recompiles = compile_event_count() - compiles_before
    seq = _sequential(module, params, prompts)
    cont_rps = closed["completed"] / closed["wall_s"]

    # Phase 3: Poisson rate sweep on the engine thread.
    sweep = []
    engine.start()
    try:
        for i, frac in enumerate(SWEEP_FRACTIONS):
            sweep.append(_poisson_arm(
                engine, _prompts(SWEEP_REQUESTS, cfg.vocab_size,
                                 seed=i + 1),
                rate_rps=max(frac * cont_rps, 0.5), seed=i,
            ))
    finally:
        engine.stop()

    snap = closed["snapshot"]
    serve_block = {
        "requests_per_sec": round(cont_rps, 3),
        "tokens_per_sec": round(
            closed["tokens_out"] / closed["wall_s"], 1
        ),
        "p50_token_latency_ms": _lat(snap, "token", "p50_ms"),
        "p99_token_latency_ms": _lat(snap, "token", "p99_ms"),
        "p50_ttft_ms": _lat(snap, "ttft", "p50_ms"),
        "p99_ttft_ms": _lat(snap, "ttft", "p99_ms"),
        "recompiles_steady_state": int(recompiles),
        "continuous_vs_sequential": round(
            cont_rps / seq["requests_per_sec"], 3
        ),
        "sequential_requests_per_sec": round(
            seq["requests_per_sec"], 3
        ),
        "sequential_tokens_per_sec": round(seq["tokens_per_sec"], 1),
        "num_slots": engine.config.num_slots,
        "block_size": engine.config.block_size,
        "num_blocks": engine.cache.num_blocks,
        "completed": closed["completed"],
        "preempted": snap["counters"]["preempted"],
        "rejected": snap["counters"]["rejected"],
        "expired": snap["counters"]["expired"],
        "rate_sweep": sweep,
    }
    # Phase 4: speculative-decoding A/B + acceptance sweep.
    spec_block = _spec_block(on_tpu)

    # Phase 5: disaggregated fleet A/B + kill-a-replica chaos.
    disagg_block = None
    if int(os.environ.get("RLT_DISAGG_REPLICAS", "2") or 0) > 0:
        disagg_block = _disagg_block(module, params, serve_cfg,
                                     cont_rps, cfg)

    # Phase 6: distributed-tracing stitch coverage + overhead A/B.
    trace_block = _trace_block(module, params, serve_cfg, cfg)

    # Phase 7: multi-tenant LoRA multiplexed vs merge-and-swap A/B.
    multi_lora_block = _multi_lora_block(module, params, serve_cfg)

    # Phase 8: prefix-aware KV reuse A/B (cache on vs off).
    prefix_block = None
    if os.environ.get("RLT_PREFIX_CACHE", "1") != "0":
        prefix_block = _prefix_cache_block(module, params, serve_cfg,
                                           cfg)

    # Phase 9: SLO & capacity-oracle calibration (predict the knee
    # cold, measure it hot, alert only under overload).
    slo_block = None
    if os.environ.get("RLT_SLO", "1") != "0":
        slo_block = _slo_block(module, params, serve_cfg, cfg,
                               cont_rps)

    # Phase 10: planned-drain live migration vs recompute failover A/B.
    chaos_block = None
    if os.environ.get("RLT_SERVE_CHAOS", "1") != "0":
        chaos_block = _serve_chaos_block(module, params, serve_cfg)

    # Compiled-program observatory: by this point every serve plane ran
    # (bucketed prefills, decode, chunked prefill, draft + K+1 verify,
    # LoRA scatter), so the process ledger must hold each steady-state
    # program WITH its cost/memory accounting — the coverage gate below
    # turns a silently-unregistered site into a bench failure.
    from ray_lightning_tpu.telemetry import program_ledger
    from ray_lightning_tpu.telemetry.schema import validate_bench_programs

    ledger_snap = program_ledger.snapshot()
    serve_rows = [r for r in ledger_snap["programs"]
                  if r["site"].startswith("serve/")]
    programs_block = {
        "n_programs": len(serve_rows),
        "compile_time_total_s": round(
            float(ledger_snap["compile_time_total_s"]), 3
        ),
        "recompile_events": len(ledger_snap["recompiles"]),
        # The dispatch-overhead A/B rides bench.py's boring-fit arms;
        # this producer records coverage, not the micro-cost.
        "ledger_overhead_pct": None,
        "rows": serve_rows,
        "hbm": program_ledger.hbm_report(ledger_snap),
    }

    problems = validate_bench_serve(serve_block)
    problems += validate_bench_programs(programs_block)
    for site in ("serve/prefill", "serve/decode", "serve/verify",
                 "serve/lora_scatter"):
        rows = [r for r in serve_rows if r["site"] == site]
        if not rows:
            problems.append(
                f"programs: steady-state serve program {site} missing "
                "from the ledger"
            )
        elif not any("flops" in r and "argument_bytes" in r
                     for r in rows):
            problems.append(
                f"programs: {site} registered without cost+memory rows"
            )
    problems += validate_bench_spec_decode(spec_block)
    problems += validate_bench_trace(trace_block)
    problems += validate_bench_multi_lora(multi_lora_block)
    for arm in ("recompiles_steady_state",
                "baseline_recompiles_steady_state"):
        if multi_lora_block[arm] != 0:
            problems.append(
                f"multi_lora: {arm} = {multi_lora_block[arm]} — the "
                "zero-recompile contract covers adapter joins and "
                "hot-adds in BOTH arms"
            )
    if not multi_lora_block["greedy_parity"]:
        problems.append(
            "multi_lora: multiplexed tenant streams diverged from "
            "their merged-model baselines"
        )
    if trace_block["coverage"] < 0.95:
        problems.append(
            f"trace: stitch coverage {trace_block['coverage']} below "
            "the 0.95 bar"
        )
    if (trace_block["overhead_pct"] is not None
            and trace_block["overhead_pct"] >= 2.0):
        problems.append(
            f"trace: cheap-tier overhead {trace_block['overhead_pct']}% "
            "at or above the 2% bar"
        )
    if prefix_block is not None:
        problems += validate_bench_prefix_cache(prefix_block)
        for arm in ("recompiles_steady_state",
                    "baseline_recompiles_steady_state"):
            if prefix_block[arm] != 0:
                problems.append(
                    f"prefix_cache: {arm} = {prefix_block[arm]} — "
                    "claimed-prefix admissions must replay warmed "
                    "programs in BOTH arms"
                )
        if not prefix_block["token_parity"]:
            problems.append(
                "prefix_cache: cached streams diverged from the "
                "cache-off baseline — shared blocks are not "
                "transparent"
            )
        if prefix_block["hit_rate"] <= 0.0:
            problems.append(
                "prefix_cache: hit_rate 0 under a shared-prefix mix — "
                "the cache never matched"
            )
        # The TTFT bar holds for prefix-heavy mixes (the acceptance
        # shape: >= 50% shared tokens); an RLT_PREFIX_SHARE sweep arm
        # below that measures the hit-rate curve, not the headline.
        if (prefix_block["prefix_share"] >= 0.5
                and prefix_block["ttft_speedup"] < 1.5):
            problems.append(
                f"prefix_cache: ttft_speedup "
                f"{prefix_block['ttft_speedup']} below the 1.5x bar "
                f"at prefix_share {prefix_block['prefix_share']}"
            )
    if disagg_block is not None:
        problems += validate_bench_serve_disagg(disagg_block)
        if disagg_block["chaos"]["lost_requests"]:
            problems.append(
                "serve_disagg.chaos: "
                f"{disagg_block['chaos']['lost_requests']} request(s) "
                "LOST across the replica kill — failover bar is zero"
            )
    if chaos_block is not None:
        problems += validate_bench_serve_chaos(chaos_block)
        if chaos_block["migrations"] < 1:
            problems.append(
                "serve_chaos: planned drain landed no migration frame "
                "— the drain fell back to recompute failover"
            )
        if chaos_block["lost_requests"]:
            problems.append(
                f"serve_chaos: {chaos_block['lost_requests']} "
                "request(s) LOST across the drain/kill arms — the "
                "resilience bar is zero"
            )
        if not chaos_block["parity"]:
            problems.append(
                "serve_chaos: recovered streams diverged from the "
                "uninterrupted monolith reference"
            )
        if chaos_block["migration_re_emitted_tokens"]:
            problems.append(
                "serve_chaos: migration_re_emitted_tokens = "
                f"{chaos_block['migration_re_emitted_tokens']} — a "
                "live migration recomputed prefill"
            )
        if chaos_block["recompiles_steady_state"]:
            problems.append(
                "serve_chaos: recompiles_steady_state = "
                f"{chaos_block['recompiles_steady_state']} — recovery "
                "left cold executables behind in one of the arms"
            )
        if chaos_block["migration_vs_failover"] < 1.0:
            problems.append(
                "serve_chaos: migration TTR "
                f"{chaos_block['migration_ttr_s']}s did not beat "
                f"failover TTR {chaos_block['failover_ttr_s']}s — the "
                "planned path must win"
            )
    if slo_block is not None:
        problems += validate_bench_slo(slo_block)
        if (slo_block["prediction_error_pct"] is None
                or slo_block["prediction_error_pct"] > 20.0):
            problems.append(
                "slo: oracle predicted "
                f"{slo_block['predicted_saturation_rps']} req/s vs "
                f"measured knee {slo_block['measured_saturation_rps']} "
                f"({slo_block['prediction_error_pct']}% error) — "
                "outside the ±20% calibration bar"
            )
        if slo_block["alerts_hot"] < 1:
            problems.append(
                "slo: the 1.5x overload arm fired no burn-rate alert"
            )
        if slo_block["alerts_cold"] != 0:
            problems.append(
                f"slo: {slo_block['alerts_cold']} alert(s) fired in "
                "the 0.5x arm — the burn-rate pager is noisy at "
                "half load"
            )
        if slo_block["recompiles_steady_state"] != 0:
            problems.append(
                "slo: recompiles_steady_state = "
                f"{slo_block['recompiles_steady_state']} with the "
                "plane on — the oracle must be host-side only"
            )
        if (slo_block["overhead_pct"] is not None
                and slo_block["overhead_pct"] >= 2.0):
            problems.append(
                f"slo: plane overhead {slo_block['overhead_pct']}% at "
                "or above the 2% bar"
            )
    if problems:  # the gate that keeps this producer honest
        for p in problems:
            sys.stderr.write(f"bench_serve schema: {p}\n")
        raise SystemExit(1)

    out = {
        "metric": "serve_requests_per_sec"
        if on_tpu else "serve_requests_per_sec_cpu",
        "value": serve_block["requests_per_sec"],
        "unit": "req/s",
        "prompt_len": PROMPT_LEN,
        "max_new_tokens": MAX_NEW,
        "requests": HEADLINE_REQUESTS,
        "serve": serve_block,
        "spec_decode": spec_block,
        "trace": trace_block,
        "multi_lora": multi_lora_block,
        "programs": programs_block,
    }
    if disagg_block is not None:
        out["serve_disagg"] = disagg_block
    if prefix_block is not None:
        out["prefix_cache"] = prefix_block
    if slo_block is not None:
        out["slo"] = slo_block
    if chaos_block is not None:
        out["serve_chaos"] = chaos_block
    print(json.dumps(out))


if __name__ == "__main__":
    main()
