"""Serving SLO bench: continuous batching under a Poisson load generator.

Prints ONE JSON line with a schema-gated ``serve`` block
(``telemetry/schema.py::validate_bench_serve``, wired into
``tools/check_telemetry_schema.py``) — the serving half of the perf
trajectory alongside ``bench.py``'s training line.

Three phases, all through the REAL :class:`ServeEngine` path:

1. **warmup** — compile every program the steady state needs (one
   prefill per bucket the traffic uses + the one decode program), then
   pin the telemetry recompile counter;
2. **headline (closed loop)** — saturating load: every request
   submitted at once, uniform shape, engine driven to idle.  Reports
   ``requests_per_sec`` / ``tokens_per_sec`` / token-latency
   percentiles, asserts ZERO steady-state recompiles, and runs the A/B:
   the SAME request set through sequential one-at-a-time
   ``generate()`` calls (compiled once, warmed) →
   ``continuous_vs_sequential`` — the acceptance bar is ≥ 1.5x at
   batch-capable load;
3. **rate sweep (open loop)** — Poisson arrivals at fractions of the
   measured capacity; each arm reports offered vs achieved rate, TTFT
   and token-latency percentiles — the latency-vs-load curve an SLO is
   set against.

Methodology notes (docs/SERVING.md): the load generator is
deterministic (seeded exponential inter-arrivals); latency families
are nearest-rank percentiles over the phase's full token stream; the
sequential baseline uses the same prompt shapes so neither arm pays a
compile or padding tax the other doesn't.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.models.generate import generate
from ray_lightning_tpu.models.gpt import GPT, GPTConfig
from ray_lightning_tpu.serve.engine import ServeConfig, ServeEngine
from ray_lightning_tpu.serve.metrics import ServeStats
from ray_lightning_tpu.telemetry import compile_event_count
from ray_lightning_tpu.telemetry.schema import validate_bench_serve

PROMPT_LEN = 16
MAX_NEW = 16
HEADLINE_REQUESTS = 48
SWEEP_REQUESTS = 24
SWEEP_FRACTIONS = (0.5, 0.9, 1.5)   # of measured closed-loop capacity


def _detect_backend() -> str:
    try:
        return jax.default_backend()
    except RuntimeError as e:
        sys.stderr.write(f"TPU backend unavailable ({e}); CPU fallback\n")
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend()


def _prompts(n: int, vocab: int, length: int = PROMPT_LEN,
             seed: int = 0) -> list:
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, size=(length,)).tolist()
            for _ in range(n)]


def _lat(snapshot: dict, family: str, q: str):
    return (snapshot["latency"].get(family) or {}).get(q)


def _closed_loop(engine: ServeEngine, prompts: list) -> dict:
    """Saturating load: submit everything, drive to idle."""
    engine.stats = ServeStats()
    handles = [engine.submit(p, MAX_NEW) for p in prompts]
    t0 = time.perf_counter()
    engine.run_until_idle()
    wall = time.perf_counter() - t0
    assert all(h.done() for h in handles)
    snap = engine.snapshot()
    return {
        "wall_s": wall,
        "completed": snap["counters"]["completed"],
        "tokens_out": snap["counters"]["tokens_out"],
        "snapshot": snap,
    }


def _sequential(module: GPT, params, prompts: list) -> dict:
    """The A/B baseline: one-at-a-time static-path generate() —
    compiled once for the shared shape, warmed before timing."""
    fn = jax.jit(
        lambda p, pr: generate(module, p, pr, max_new_tokens=MAX_NEW)
    )
    prompt0 = jnp.asarray([prompts[0]], jnp.int32)
    jax.block_until_ready(fn(params, prompt0))  # compile
    t0 = time.perf_counter()
    for p in prompts:
        jax.block_until_ready(fn(params, jnp.asarray([p], jnp.int32)))
    wall = time.perf_counter() - t0
    return {"wall_s": wall,
            "requests_per_sec": len(prompts) / wall,
            "tokens_per_sec": len(prompts) * MAX_NEW / wall}


def _poisson_arm(engine: ServeEngine, prompts: list, rate_rps: float,
                 seed: int) -> dict:
    """Open loop: submit on a seeded exponential arrival schedule while
    the engine thread serves, then wait for the tail."""
    import random

    engine.stats = ServeStats()
    rng = random.Random(seed)
    handles = []
    t0 = time.perf_counter()
    next_t = 0.0
    for p in prompts:
        next_t += rng.expovariate(rate_rps)
        lag = t0 + next_t - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        handles.append(engine.submit(p, MAX_NEW))
    deadline = time.perf_counter() + 120
    for h in handles:
        h._done.wait(max(0.0, deadline - time.perf_counter()))
    # Drain stragglers of an overloaded arm INTO THIS ARM's stats —
    # the caller swaps engine.stats next, and a request finishing after
    # the swap would corrupt the next arm's completed/latency numbers.
    while engine.scheduler.has_work():
        if time.perf_counter() > deadline + 60:
            sys.stderr.write(
                "bench_serve: rate arm failed to drain within its "
                "deadline — sweep numbers for later arms are suspect\n"
            )
            break
        time.sleep(0.01)
    wall = time.perf_counter() - t0
    snap = engine.snapshot()
    return {
        "offered_rps": round(rate_rps, 3),
        "requests_per_sec": round(snap["counters"]["completed"] / wall, 3),
        "p50_token_latency_ms": _lat(snap, "token", "p50_ms"),
        "p99_token_latency_ms": _lat(snap, "token", "p99_ms"),
        "p50_ttft_ms": _lat(snap, "ttft", "p50_ms"),
        "p99_ttft_ms": _lat(snap, "ttft", "p99_ms"),
        "completed": snap["counters"]["completed"],
        "expired": snap["counters"]["expired"],
        "rejected": snap["counters"]["rejected"],
    }


def main() -> None:
    on_tpu = _detect_backend() == "tpu"
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, n_layer=12, n_head=12,
                        d_model=768, seq_len=1024, warmup_steps=10)
        serve_cfg = ServeConfig(num_slots=16, block_size=32)
    else:
        # NOT GPTConfig.tiny(): a 1.6 MB-weight model fits in L2, so
        # CPU decode is dispatch-bound and an A/B there measures python
        # overhead, not batching.  ~13M params (~50 MB f32) puts
        # single-token decode in the weight-streaming regime serving
        # actually lives in — each decode step reads every weight once
        # whether it serves 1 token or num_slots of them.
        cfg = GPTConfig(vocab_size=512, n_layer=4, n_head=8,
                        d_model=512, seq_len=128, warmup_steps=2)
        serve_cfg = ServeConfig(num_slots=8, block_size=16)
    module = GPT(cfg, attn_impl="auto")
    if on_tpu:
        module.precision = "bf16"
    params = module.init_params(jax.random.PRNGKey(0))
    engine = ServeEngine(module, params, serve_cfg)
    prompts = _prompts(HEADLINE_REQUESTS, cfg.vocab_size)

    # Phase 1: warmup — compile the bucket + decode programs.
    for p in prompts[:2]:
        engine.generate(p, MAX_NEW)
    compiles_before = compile_event_count()

    # Phase 2: closed-loop headline + sequential A/B.
    closed = _closed_loop(engine, prompts)
    recompiles = compile_event_count() - compiles_before
    seq = _sequential(module, params, prompts)
    cont_rps = closed["completed"] / closed["wall_s"]

    # Phase 3: Poisson rate sweep on the engine thread.
    sweep = []
    engine.start()
    try:
        for i, frac in enumerate(SWEEP_FRACTIONS):
            sweep.append(_poisson_arm(
                engine, _prompts(SWEEP_REQUESTS, cfg.vocab_size,
                                 seed=i + 1),
                rate_rps=max(frac * cont_rps, 0.5), seed=i,
            ))
    finally:
        engine.stop()

    snap = closed["snapshot"]
    serve_block = {
        "requests_per_sec": round(cont_rps, 3),
        "tokens_per_sec": round(
            closed["tokens_out"] / closed["wall_s"], 1
        ),
        "p50_token_latency_ms": _lat(snap, "token", "p50_ms"),
        "p99_token_latency_ms": _lat(snap, "token", "p99_ms"),
        "p50_ttft_ms": _lat(snap, "ttft", "p50_ms"),
        "p99_ttft_ms": _lat(snap, "ttft", "p99_ms"),
        "recompiles_steady_state": int(recompiles),
        "continuous_vs_sequential": round(
            cont_rps / seq["requests_per_sec"], 3
        ),
        "sequential_requests_per_sec": round(
            seq["requests_per_sec"], 3
        ),
        "sequential_tokens_per_sec": round(seq["tokens_per_sec"], 1),
        "num_slots": engine.config.num_slots,
        "block_size": engine.config.block_size,
        "num_blocks": engine.cache.num_blocks,
        "completed": closed["completed"],
        "preempted": snap["counters"]["preempted"],
        "rejected": snap["counters"]["rejected"],
        "expired": snap["counters"]["expired"],
        "rate_sweep": sweep,
    }
    problems = validate_bench_serve(serve_block)
    if problems:  # the gate that keeps this producer honest
        for p in problems:
            sys.stderr.write(f"bench_serve schema: {p}\n")
        raise SystemExit(1)

    print(json.dumps({
        "metric": "serve_requests_per_sec"
        if on_tpu else "serve_requests_per_sec_cpu",
        "value": serve_block["requests_per_sec"],
        "unit": "req/s",
        "prompt_len": PROMPT_LEN,
        "max_new_tokens": MAX_NEW,
        "requests": HEADLINE_REQUESTS,
        "serve": serve_block,
    }))


if __name__ == "__main__":
    main()
