"""Shared-memory segment store: same-host write-once/read-many payloads.

The plasma-object-store analogue of the control plane (reference rides
Ray's plasma for ``ray.put(model)``, ``ray_ddp.py:339-342``): instead of
pushing a multi-hundred-MB pickled task through N actor sockets, the
driver writes it ONCE to a checksummed segment on tmpfs
(:mod:`ray_lightning_tpu.native` format) and ships only the path; each
same-host actor reads the payload at page-cache speed, verified against
corruption.  Lifetime is owner-managed: the creating store unlinks its
segments on shutdown (≙ driver-scoped ``ObjectRef`` lifetime in Ray).
"""

from __future__ import annotations

import atexit
import os
import re
import tempfile
import threading
import uuid
from typing import List

from ray_lightning_tpu import native

__all__ = ["SegmentStore", "segment_dir", "sweep_stale_segments",
           "ALL_PREFIXES"]

_NAME_RE = re.compile(r"^(?P<prefix>.+)-(?P<pid>\d+)-[0-9a-f]{32}$")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    return True


# Every segment family the queue plane creates: MPMD activation
# transfers ("rlt-seg") and serve-plane KV handoffs ("rlt-kv").  Teardown
# sweeps that don't know which producer died pass the tuple.
ALL_PREFIXES = ("rlt-seg", "rlt-kv")


def sweep_stale_segments(prefix="rlt-seg") -> int:
    """Unlink segments whose owner pid is gone (tmpfs is RAM: a SIGKILL'd
    driver must not leak its spilled payloads until reboot).  Runs
    opportunistically at store creation — the plasma-janitor analogue.
    ``prefix`` is one family name or a tuple of them
    (:data:`ALL_PREFIXES` for a whole-plane sweep)."""
    prefixes = (prefix,) if isinstance(prefix, str) else tuple(prefix)
    removed = 0
    try:
        entries = os.listdir(segment_dir())
    except OSError:
        return 0
    for entry in entries:
        m = _NAME_RE.match(entry)
        if not m or m.group("prefix") not in prefixes:
            continue
        if _pid_alive(int(m.group("pid"))):
            continue
        try:
            os.unlink(os.path.join(segment_dir(), entry))
            removed += 1
        except OSError:
            pass
    return removed


def segment_dir() -> str:
    """tmpfs when available (Linux /dev/shm), else the tempdir."""
    base = "/dev/shm"
    if not os.path.isdir(base) or not os.access(base, os.W_OK):
        base = tempfile.gettempdir()
    return base


class SegmentStore:
    """Driver-owned collection of payload segments."""

    def __init__(self, prefix: str = "rlt-seg"):
        self._prefix = prefix
        self._dir = segment_dir()
        self._paths: List[str] = []
        self._lock = threading.Lock()
        sweep_stale_segments(prefix)
        # Interpreter exit without a clean backend.shutdown() still
        # reclaims tmpfs (SIGKILL leaks are caught by the next sweep).
        atexit.register(self.unlink_all)

    def put(self, payload: bytes) -> str:
        path = os.path.join(
            self._dir, f"{self._prefix}-{os.getpid()}-{uuid.uuid4().hex}"
        )
        native.write_segment(path, payload)
        with self._lock:
            self._paths.append(path)
        return path

    @staticmethod
    def get(path: str, verify: bool = True) -> bytes:
        return native.read_segment(path, verify=verify)

    def unlink_all(self) -> None:
        with self._lock:
            paths, self._paths = self._paths, []
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass
