"""Process actors: the worker-launch layer of the built-in control plane.

TPU-native analogue of the reference's ``RayExecutor`` actor
(``/root/reference/ray_lightning/ray_ddp.py:38-63``): a generic remote
process shell with ``set_env_var(s)``, ``get_node_ip``, device introspection
and an arbitrary-function runner (``execute``).  The reference creates one
Ray actor per GPU; here one actor ≙ one **TPU host** (a v4 host owns 4
chips; JAX is multi-controller SPMD).

Launch mechanics — deliberately Ray-like, NOT ``multiprocessing``-like:
the child is a fresh ``subprocess`` running a dedicated module entry
(``python -m ray_lightning_tpu.cluster.actor``), so the user's ``__main__``
is **never re-imported** (no ``if __name__ == "__main__"`` guard required
in user scripts, matching Ray-actor ergonomics) and the child does not
inherit the driver's libtpu/XLA runtime (TPU chips are single-owner per
process).  Code travels exclusively via cloudpickle, which serializes
``__main__``-defined functions by value.

RPC protocol: length-prefixed cloudpickle frames over a loopback TCP
socket; a random authkey passed through the child's stdin authenticates the
connection.  A dedicated receiver thread resolves
``concurrent.futures.Future`` objects, so the driver can poll futures while
pumping the distributed queue (reference ``util.py:55-68``).

Env-var plumbing matters: JAX reads ``XLA_FLAGS`` / ``JAX_PLATFORMS`` /
``TPU_VISIBLE_CHIPS`` / ``LIBTPU_INIT_ARGS`` at import time, so the actor's
env dict is applied in the child *before* any user function (and hence any
jax import) runs — the analogue of the reference broadcasting
``MASTER_ADDR``/seed env vars to actors before training
(``ray_ddp.py:215-228``).
"""

from __future__ import annotations

import itertools
import os
import socket
import subprocess
import sys
import threading
import time
import traceback
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional

from . import rpc

__all__ = ["ProcessActor", "RemoteError", "ActorDiedError"]


class RemoteError(RuntimeError):
    """An exception raised inside an actor, re-raised on the driver."""

    def __init__(self, actor_name: str, formatted_traceback: str):
        super().__init__(
            f"Remote call on actor {actor_name!r} failed:\n{formatted_traceback}"
        )
        self.actor_name = actor_name
        self.remote_traceback = formatted_traceback


class ActorDiedError(RuntimeError):
    """The actor process exited before answering (≙ Ray's RayActorError).

    The reference surfaces worker death as a raised Ray error from
    ``ray.get`` inside ``process_results`` (``util.py:55-68``); we do the
    same — failures propagate fast and crash the fit.

    Structured context rides as attributes so death reports can say
    *when* and *how* the rank died, not just that it did: ``exit_code``
    (agent/subprocess ``poll()``), ``rank``, ``last_heartbeat_age_s``
    (from the RunMonitor), ``actor_name``.  Raise sites fill what they
    know; the strategy layer adds the rest via :meth:`enrich`.
    """

    def __init__(self, message: str, *, actor_name=None, exit_code=None,
                 rank=None, last_heartbeat_age_s=None):
        super().__init__(message)
        self.actor_name = actor_name
        self.exit_code = exit_code
        self.rank = rank
        self.last_heartbeat_age_s = last_heartbeat_age_s

    def enrich(self, **fields) -> "ActorDiedError":
        """Fill unset context fields and fold them into the message
        (in place — the exception identity/traceback is preserved)."""
        notes = []
        for key in ("actor_name", "exit_code", "rank",
                    "last_heartbeat_age_s"):
            if key in fields and getattr(self, key) is None:
                setattr(self, key, fields[key])
        if self.rank is not None:
            notes.append(f"rank={self.rank}")
        if self.exit_code is not None:
            notes.append(f"exit_code={self.exit_code}")
        if self.last_heartbeat_age_s is not None:
            notes.append(
                f"last_heartbeat={self.last_heartbeat_age_s}s ago"
            )
        extra = fields.get("note")
        if notes or extra:
            detail = "; ".join(notes + ([extra] if extra else []))
            self.args = (f"{self.args[0]} [{detail}]",) + self.args[1:]
        return self


def _apply_env(env: Dict[str, str]) -> None:
    for k, v in env.items():
        os.environ[k] = str(v)


# ---------------------------------------------------------------------------
# Functions commonly shipped to actors (top-level so plain pickle also works)
# ---------------------------------------------------------------------------

def _remote_set_env_vars(env: Dict[str, str]) -> None:
    """≙ RayExecutor.set_env_vars (reference ``ray_ddp.py:44-49``)."""
    _apply_env(env)


def _remote_get_node_ip() -> str:
    """≙ RayExecutor.get_node_ip (reference ``ray_ddp.py:51-53``)."""
    return rpc.get_node_ip()


def _remote_get_host_stats() -> Dict[str, Any]:
    """Host load/memory of the actor's node (straggler context for the
    fleet telemetry report; jax-free — safe before/without PJRT init)."""
    from ray_lightning_tpu.telemetry.aggregate import host_stats

    return {"ip": rpc.get_node_ip(), **host_stats()}


def _remote_get_device_info() -> Dict[str, Any]:
    """TPU analogue of get_node_and_gpu_ids (reference ``ray_ddp.py:55-58``).

    Imports jax *inside the actor* (first touch of the accelerator) and
    reports the local device topology for the driver's rank/mesh mapping.
    """
    import jax

    devices = jax.local_devices()
    return {
        "ip": rpc.get_node_ip(),
        "process_index": jax.process_index(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
        "platform": devices[0].platform if devices else "none",
        "device_kinds": [d.device_kind for d in devices],
    }


# ---------------------------------------------------------------------------
# Child-side main loop
# ---------------------------------------------------------------------------

def _remote_dump_stacks() -> Dict[str, Any]:
    """Out-of-band forensics: py-stacks of every live thread
    (``sys._current_frames``) + best-effort device memory.

    Served on the child's **control lane**, so it answers even while a
    ``call`` (the fit) is wedged inside a collective — the whole point:
    the RunMonitor asks a *hung* worker what it is stuck on.
    """
    from ray_lightning_tpu.telemetry.flight_recorder import (
        format_all_stacks,
    )
    from ray_lightning_tpu.telemetry.heartbeat import device_memory_stats

    out: Dict[str, Any] = {
        "pid": os.getpid(),
        "ts": time.time(),
        "stacks": format_all_stacks(),
    }
    mem = device_memory_stats()
    if mem:
        out["device_memory"] = mem
    return out


def _remote_request_drain() -> Dict[str, Any]:
    """Control-lane drain request: the driver received the preemption
    notice (or the user asked for a graceful stop) and tells this
    worker to finish its in-flight step, checkpoint, and exit with
    ``PreemptedError``.  Served on the control lane so it lands even
    while the fit call is busy — that is the whole point."""
    from ray_lightning_tpu.fault import drain

    drain.request_drain("driver-request")
    return {"pid": os.getpid(), "draining": True}


_CONTROL_HANDLERS: Dict[str, Callable[..., Any]] = {
    "dump_stacks": _remote_dump_stacks,
    "ping": lambda: {"pid": os.getpid(), "ts": time.time()},
    "drain": _remote_request_drain,
}


def _encode_call_error(exc: BaseException) -> Any:
    """Error payload for the call lane: the formatted traceback, plus —
    for the fault-plane's typed exceptions — the exception BY VALUE, so
    the driver can catch ``PreemptedError`` as a type instead of
    grepping a RemoteError string.  Arbitrary user exceptions stay
    string-only (their classes may not exist driver-side)."""
    tb = traceback.format_exc()
    from ray_lightning_tpu.fault.drain import PreemptedError

    if isinstance(exc, PreemptedError):
        try:
            return {"tb": tb, "exc": rpc.dumps(exc)}
        except Exception:  # noqa: BLE001 - fall back to the string form
            pass
    return tb


def _decode_call_error(actor_name: str, payload: Any) -> BaseException:
    """Driver-side inverse of :func:`_encode_call_error`."""
    if isinstance(payload, dict):
        blob = payload.get("exc")
        if blob is not None:
            try:
                exc = rpc.loads(blob)
                exc.remote_traceback = payload.get("tb", "")
                return exc
            except Exception:  # noqa: BLE001 - unpicklable: degrade
                pass
        payload = payload.get("tb", "")
    return RemoteError(actor_name, payload)


def _child_main() -> None:
    """Entry point of the actor subprocess (``python -m ...cluster.actor``).

    Two lanes over one connection:

    * ``call`` — user functions, executed **sequentially** on a single
      worker thread (the pre-control-lane ordering contract: a queued
      call never overlaps the one before it);
    * ``ctl`` — small, jax-light control requests (stack dumps, pings)
      handled inline on the receive thread, so they answer even while
      a call is stuck in a collective.  This is what makes driver-side
      hang diagnosis possible at all.
    """
    host = sys.argv[1]
    port = int(sys.argv[2])
    # Preemption-safe drain: SIGTERM/SIGINT during a fit become a drain
    # request the loop honors at the next step boundary (fault/drain.py)
    # instead of killing the process mid-collective.  Must happen here —
    # signal handlers are only installable from the MAIN thread, and the
    # fit runs on the call-worker thread.
    from ray_lightning_tpu.fault import drain as _drain

    _drain.install_signal_handlers()
    authkey = bytes.fromhex(sys.stdin.readline().strip())
    sock = socket.create_connection((host, port), timeout=60)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    rpc.send_frame(sock, authkey)

    send_lock = threading.Lock()

    def reply(obj: Any) -> None:
        with send_lock:
            rpc.send_frame(sock, rpc.dumps(obj))

    import queue as _pyqueue

    calls: "_pyqueue.Queue" = _pyqueue.Queue()

    def call_worker() -> None:
        while True:
            msg = calls.get()
            if msg is None:
                return
            _, call_id, payload = msg
            try:
                fn, args, kwargs = payload
                result = fn(*args, **kwargs)
                out = ("ok", call_id, result)
            except BaseException as e:  # noqa: BLE001 - ship it all back
                out = ("err", call_id, _encode_call_error(e))
            try:
                reply(out)
            except (ConnectionError, OSError):
                return
            except BaseException:
                # Result not serializable — report that instead of dying.
                reply(
                    ("err", call_id,
                     "actor result failed to serialize:\n"
                     + traceback.format_exc())
                )

    worker = threading.Thread(
        target=call_worker, name="rlt-actor-calls", daemon=True
    )
    worker.start()

    while True:
        try:
            msg = rpc.loads(rpc.recv_frame(sock))
        except (ConnectionError, OSError):
            break
        kind = msg[0]
        if kind == "exit":
            reply(("bye", None, None))
            break
        if kind == "call":
            calls.put(msg)
        elif kind == "ctl":
            _, call_id, (op, kw) = msg
            handler = _CONTROL_HANDLERS.get(op)
            try:
                if handler is None:
                    raise ValueError(f"unknown control op {op!r}")
                out = ("ok", call_id, handler(**kw))
            except BaseException:  # noqa: BLE001
                out = ("err", call_id, traceback.format_exc())
            try:
                reply(out)
            except (ConnectionError, OSError):
                break
    sock.close()
    # The call worker is a daemon: a kill()-initiated exit must not wait
    # for a wedged fit call (≙ ray.kill's no-grace semantics).
    sys.exit(0)


def build_child_env(env: Dict[str, str]) -> Dict[str, str]:
    """Child environment = this process's env + overrides + import paths.

    Mirror the spawning process's import environment: cloudpickle
    serializes functions from importable modules *by reference*, so
    anything the driver can import (the user's project, this package from a
    source checkout, pytest-rootdir test modules) must be importable in the
    child too.  '' means cwd on sys.path; make that explicit.  Called on
    the host that actually spawns — the driver for local actors, the node
    agent for remote ones (whose sys.path, not the driver's, is what
    exists on that host).
    """
    child_env = dict(os.environ)
    child_env.update({k: str(v) for k, v in env.items()})
    spawner_path = [p if p else os.getcwd() for p in sys.path]
    pp = child_env.get("PYTHONPATH", "")
    extra = [p for p in pp.split(os.pathsep) if p and p not in spawner_path]
    child_env["PYTHONPATH"] = os.pathsep.join(spawner_path + extra)
    return child_env


def spawn_child(
    connect_host: str, port: int, authkey_hex: str, env: Dict[str, str]
) -> subprocess.Popen:
    """Start one actor child that dials ``connect_host:port`` and
    authenticates with ``authkey_hex`` (fed via stdin, never argv)."""
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "from ray_lightning_tpu.cluster.actor import _child_main; "
         "_child_main()",
         connect_host, str(port)],
        stdin=subprocess.PIPE,
        env=build_child_env(env),
    )
    assert proc.stdin is not None
    proc.stdin.write(authkey_hex.encode() + b"\n")
    proc.stdin.flush()
    return proc


def _local_launcher(
    connect_host: str, port: int, authkey_hex: str,
    env: Dict[str, str], name: str,
):
    return spawn_child(connect_host, port, authkey_hex, env)


class ProcessActor:
    """A worker subprocess with a generic ``execute`` RPC (≙ ``RayExecutor``).

    ``launcher`` abstracts *where* the child process starts: the default
    spawns it on this host; :func:`..agent.agent_launcher` asks a remote
    node agent to spawn it on another host, with the child dialing back to
    this driver over TCP.  ``bind_host``/``advertise_host`` follow the
    queue's pattern: bind loopback for local children, ``0.0.0.0`` + the
    routable NIC address for remote ones.
    """

    _ids = itertools.count()

    def __init__(
        self,
        name: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        startup_timeout_s: float = 120.0,
        launcher: Optional[Callable[..., Any]] = None,
        bind_host: str = "127.0.0.1",
        advertise_host: Optional[str] = None,
    ):
        self.name = name or f"rlt-actor-{next(self._ids)}"
        self._env = dict(env or {})
        authkey = os.urandom(16)

        server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind((bind_host, 0))
        server.listen(1)
        host, port = server.getsockname()
        connect_host = advertise_host or host

        try:
            self._proc = (launcher or _local_launcher)(
                connect_host, port, authkey.hex(), self._env, self.name
            )
        except BaseException:
            server.close()
            raise

        # Accept with timeout + child liveness polling — a child that dies
        # during startup must surface as ActorDiedError, never a hang.
        server.settimeout(1.0)
        conn: Optional[socket.socket] = None
        deadline = time.monotonic() + startup_timeout_s
        while conn is None:
            if self._proc.poll() is not None:
                server.close()
                raise ActorDiedError(
                    f"Actor {self.name!r} exited during startup "
                    f"(exit code {self._proc.returncode}).",
                    actor_name=self.name,
                    exit_code=self._proc.returncode,
                )
            if time.monotonic() > deadline:
                server.close()
                self._proc.terminate()
                raise ActorDiedError(
                    f"Actor {self.name!r} did not connect within "
                    f"{startup_timeout_s}s.",
                    actor_name=self.name,
                )
            try:
                conn, _ = server.accept()
            except socket.timeout:
                continue
        server.close()
        conn.settimeout(None)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if rpc.recv_frame(conn) != authkey:
            conn.close()
            self._proc.terminate()
            raise ActorDiedError(
                f"Actor {self.name!r} failed authentication.",
                actor_name=self.name,
            )
        self._conn = conn

        self._send_lock = threading.Lock()
        self._call_ids = itertools.count()
        self._pending: Dict[int, Future] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._conn_dead = False
        self._recv_thread = threading.Thread(
            target=self._receive_loop, name=f"{self.name}-recv", daemon=True
        )
        self._recv_thread.start()

    # -- receive path -------------------------------------------------------
    def _receive_loop(self) -> None:
        while True:
            try:
                msg = rpc.loads(rpc.recv_frame(self._conn))
            except (ConnectionError, OSError):
                self._fail_all_pending()
                return
            status, call_id, payload = msg
            if status == "bye":
                self._fail_all_pending()
                return
            with self._lock:
                fut = self._pending.pop(call_id, None)
            if fut is None:
                continue
            if status == "ok":
                fut.set_result(payload)
            else:
                fut.set_exception(_decode_call_error(self.name, payload))

    def _fail_all_pending(self) -> None:
        with self._lock:
            self._conn_dead = True
            pending, self._pending = self._pending, {}
        exit_code = self._proc.poll()
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(
                    ActorDiedError(
                        f"Actor {self.name!r} died before answering "
                        f"(exit code {exit_code}).",
                        actor_name=self.name, exit_code=exit_code,
                    )
                )

    # -- submit path --------------------------------------------------------
    def _submit_msg(self, lane: str, payload: Any, what: str) -> Future:
        """Ship one (call_id-tagged) frame; return its pending Future.
        Shared by the call lane and the control lane."""
        if self._closed or self._conn_dead or self._proc.poll() is not None:
            raise ActorDiedError(
                f"Actor {self.name!r} is not alive.",
                actor_name=self.name, exit_code=self._proc.poll(),
            )
        fut: Future = Future()
        call_id = next(self._call_ids)
        with self._lock:
            self._pending[call_id] = fut
        try:
            with self._send_lock:
                rpc.send_frame(
                    self._conn, rpc.dumps((lane, call_id, payload))
                )
        except (OSError, ValueError) as e:
            with self._lock:
                self._pending.pop(call_id, None)
            raise ActorDiedError(
                f"Failed to submit {what} to actor {self.name!r}: {e}",
                actor_name=self.name, exit_code=self._proc.poll(),
            )
        # Close the race with _fail_all_pending(): if the connection died
        # between our aliveness check and the insert above, the swap may
        # have missed this future — TCP happily buffers bytes into a dying
        # socket, so the send alone proves nothing.
        with self._lock:
            if self._conn_dead and not fut.done():
                self._pending.pop(call_id, None)
                fut.set_exception(
                    ActorDiedError(
                        f"Actor {self.name!r} died during submit.",
                        actor_name=self.name, exit_code=self._proc.poll(),
                    )
                )
        return fut

    def submit(self, fn: Callable, *args: Any, **kwargs: Any) -> Future:
        """Asynchronously run ``fn(*args, **kwargs)`` in the actor.

        ≙ ``RayExecutor.execute.remote`` (reference ``ray_ddp.py:60-62``,
        submission at ``ray_ddp.py:349-353``).  Returns a standard
        ``concurrent.futures.Future``.
        """
        return self._submit_msg("call", (fn, args, kwargs), "call")

    def execute(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        """Synchronous convenience wrapper around :meth:`submit`."""
        return self.submit(fn, *args, **kwargs).result()

    # -- control lane -------------------------------------------------------
    def control(self, op: str, timeout: Optional[float] = 10.0,
                **kwargs: Any) -> Any:
        """Out-of-band control request (``dump_stacks``, ``ping``).

        Served by the child's receive thread, NOT the call worker — so
        it answers even while a submitted call is hung.  That is the
        mechanism behind the RunMonitor's stack dumps of stuck ranks.
        """
        return self._submit_msg("ctl", (op, kwargs), f"ctl:{op}").result(
            timeout
        )

    def dump_stacks(self, timeout: Optional[float] = 10.0) -> Dict[str, Any]:
        """Py-stacks of every thread in the actor + device memory
        (``_remote_dump_stacks``) — works mid-call by design."""
        return self.control("dump_stacks", timeout=timeout)

    def request_drain(self, wait: bool = False,
                      timeout: Optional[float] = 10.0) -> Any:
        """Ask the worker to gracefully drain its in-flight fit
        (control lane — lands even mid-call).  ``wait=False`` returns
        the pending Future so a driver-side preemption handler can fan
        the request out to every worker without serializing on acks."""
        fut = self._submit_msg("ctl", ("drain", {}), "ctl:drain")
        return fut.result(timeout) if wait else fut

    # -- RayExecutor-parity conveniences ------------------------------------
    def set_env_vars(self, env: Dict[str, str]) -> None:
        self._env.update(env)
        self.execute(_remote_set_env_vars, env)

    def get_node_ip(self) -> str:
        return self.execute(_remote_get_node_ip)

    def get_device_info(self) -> Dict[str, Any]:
        return self.execute(_remote_get_device_info)

    def get_host_stats(self) -> Dict[str, Any]:
        """Load/memory of the actor's host (straggler context)."""
        return self.execute(_remote_get_host_stats)

    # -- lifecycle ----------------------------------------------------------
    def is_alive(self) -> bool:
        return (
            not self._closed
            and not self._conn_dead
            and self._proc.poll() is None
        )

    def kill(self, timeout: float = 5.0) -> None:
        """Tear down the actor (≙ ``ray.kill(w, no_restart=True)``,
        reference ``ray_ddp.py:398-400``)."""
        if self._closed:
            return
        self._closed = True
        try:
            with self._send_lock:
                rpc.send_frame(self._conn, rpc.dumps(("exit",)))
        except (OSError, ValueError):
            pass
        deadline = time.monotonic() + timeout
        while self._proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        try:
            self._conn.close()
        except OSError:
            pass
        # Reclaim tmpfs the dead child may have leaked: a stage worker
        # killed mid-transfer leaves rlt-seg segments (and a serve
        # prefill worker killed mid-handoff leaves rlt-kv ones) whose
        # owner pid is gone — sweeping every family at every kill keeps
        # /dev/shm bounded even for crash-looping fleets (the next
        # SegmentStore() would sweep too, but only its own prefix, and
        # only if one is ever created again).
        try:
            from ray_lightning_tpu.cluster.shm import (
                ALL_PREFIXES, sweep_stale_segments,
            )

            sweep_stale_segments(ALL_PREFIXES)
        except Exception:  # noqa: BLE001 - janitorial, never raises out
            pass


if __name__ == "__main__":
    _child_main()
