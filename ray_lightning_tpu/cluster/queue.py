"""Distributed queue: worker → driver streaming channel.

TPU-native analogue of ``ray.util.queue.Queue`` as used by the reference
(``/root/reference/ray_lightning/ray_ddp.py:344-347`` creates it driver-side
and ships the handle to every worker; workers ``put`` thunks/metrics from
inside the fit loop, the driver drains them while polling futures,
``util.py:47-68``).

Implementation: the *server* lives in the driver process — an accept loop on
a TCP socket feeding a thread-safe in-memory queue.  The *handle*
(:class:`QueueHandle`) is a picklable ``(host, port)`` pair; any worker on
any host can connect and push cloudpickled items.  TCP (not a pipe) so the
same mechanism works across hosts of a TPU pod, exactly like Ray's
actor-backed queue works across a cluster.
"""

from __future__ import annotations

import queue as _pyqueue
import socket
import threading
from typing import Any, Optional

from . import rpc

__all__ = ["DriverQueue", "QueueHandle"]


class QueueHandle:
    """Picklable client handle to a :class:`DriverQueue`.

    One persistent connection per process, lazily opened on first ``put``.
    """

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    # -- pickling: drop the live socket -------------------------------------
    def __getstate__(self):
        return {"host": self.host, "port": self.port}

    def __setstate__(self, state):
        self.host = state["host"]
        self.port = state["port"]
        self._sock = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port), timeout=60)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def put(self, item: Any) -> None:
        """Ship ``item`` to the driver (reference ``session.py:61-63``)."""
        payload = rpc.dumps(item)
        with self._lock:
            try:
                rpc.send_frame(self._connect(), payload)
            except (OSError, ConnectionError):
                # One reconnect attempt — the driver may have restarted the
                # accept loop between epochs.
                self.close()
                rpc.send_frame(self._connect(), payload)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class DriverQueue:
    """Driver-side queue server (≙ ``ray.util.queue.Queue`` actor)."""

    def __init__(self, host: str = "127.0.0.1", advertise_host: Optional[str] = None):
        self._items: _pyqueue.Queue = _pyqueue.Queue()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, 0))
        self._server.listen(128)
        self._port = self._server.getsockname()[1]
        self._advertise_host = advertise_host or host
        self._closed = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rlt-queue-accept", daemon=True
        )
        self._accept_thread.start()

    @property
    def handle(self) -> QueueHandle:
        return QueueHandle(self._advertise_host, self._port)

    # -- server side --------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return  # listener closed
            t = threading.Thread(
                target=self._reader_loop, args=(conn,), daemon=True
            )
            t.start()

    def _reader_loop(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                frame = rpc.recv_frame(conn)
                self._items.put(rpc.loads(frame))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    # -- driver consumption (reference util.py:47-52) -----------------------
    def empty(self) -> bool:
        return self._items.empty()

    def get_nowait(self) -> Any:
        return self._items.get_nowait()

    def get(self, timeout: Optional[float] = None) -> Any:
        return self._items.get(timeout=timeout)

    def shutdown(self) -> None:
        self._closed.set()
        try:
            self._server.close()
        except OSError:
            pass
