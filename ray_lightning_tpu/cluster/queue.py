"""Distributed queue: worker → driver streaming channel.

TPU-native analogue of ``ray.util.queue.Queue`` as used by the reference
(``/root/reference/ray_lightning/ray_ddp.py:344-347`` creates it driver-side
and ships the handle to every worker; workers ``put`` thunks/metrics from
inside the fit loop, the driver drains them while polling futures,
``util.py:47-68``).

Implementation: the *server* lives in the driver process — an accept loop on
a TCP socket feeding a thread-safe in-memory queue.  The *handle*
(:class:`QueueHandle`) is a picklable ``(host, port)`` pair; any worker on
any host can connect and push cloudpickled items.  TCP (not a pipe) so the
same mechanism works across hosts of a TPU pod, exactly like Ray's
actor-backed queue works across a cluster.
"""

from __future__ import annotations

import queue as _pyqueue
import socket
import threading
import uuid
from typing import Any, Optional

from . import rpc

__all__ = ["DriverQueue", "QueueHandle"]

# Hard ceiling on the 1-byte ack read.  The ack read must never block
# forever while holding the handle lock: if the driver process is alive
# but its reader thread is wedged, a bare ``recv`` would hang every
# worker ``put`` with no failover.  A timeout surfaces as
# ``socket.timeout`` (an ``OSError``) and flows into the close-and-raise
# path, which the caller's reconnect retry handles.
_ACK_TIMEOUT_S = 60.0
# The frame send gets a size-scaled budget instead: checkpoint thunks
# and MPMD activations can be GBs/multi-MB, and a Python socket timeout
# caps sendall's TOTAL duration — a fixed 60s would hard-fail any
# payload needing longer on a slow inter-host link.  Budget assumes
# worst-case ~1 MiB/s sustained.
_MIN_SEND_THROUGHPUT = 1 << 20  # bytes/s
# Frames above this are sent in chunks with a PER-CHUNK timeout: one
# slow multi-MB activation then can't trip a whole-frame budget — as
# long as each ~8MB chunk makes progress inside its own budget, the
# send succeeds no matter how long the total takes (the MPMD transfer
# lane's DCN contract).
_SEND_CHUNK_BYTES = 8 << 20


def _send_timeout_s(payload_bytes: int) -> float:
    """Size-scaled socket budget.  Applied to every slow half of a
    ``put``: connect (SYN retry storms on a congested DCN hop scale
    with load too), each send chunk, and the post-send ack drain (the
    server acks only after the full frame is read AND enqueued — for a
    multi-MB payload that read itself takes payload/throughput)."""
    return max(_ACK_TIMEOUT_S, payload_bytes / _MIN_SEND_THROUGHPUT)


def _sendall_chunked(sock: socket.socket, payload: bytes,
                     chunk_bytes: int = _SEND_CHUNK_BYTES) -> None:
    """``sendall`` in ``chunk_bytes`` slices, re-arming the size-scaled
    timeout per slice — total duration is unbounded, per-slice progress
    is not."""
    view = memoryview(payload)
    for off in range(0, len(view), chunk_bytes):
        chunk = view[off:off + chunk_bytes]
        sock.settimeout(_send_timeout_s(len(chunk)))
        sock.sendall(chunk)


class QueueHandle:
    """Picklable client handle to a :class:`DriverQueue`.

    One persistent connection per process, lazily opened on first ``put``.
    """

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._client_id = uuid.uuid4().hex
        self._seq = 0

    # -- pickling: drop the live socket; each unpickled copy is a fresh
    # producer with its own dedup identity --------------------------------
    def __getstate__(self):
        return {"host": self.host, "port": self.port}

    def __setstate__(self, state):
        self.host = state["host"]
        self.port = state["port"]
        self._sock = None
        self._lock = threading.Lock()
        self._client_id = uuid.uuid4().hex
        self._seq = 0

    def _connect(self, timeout: float = 60.0) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(
                (self.host, self.port), timeout=timeout
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def put(self, item: Any) -> None:
        """Ship ``item`` to the driver (reference ``session.py:61-63``).

        Synchronous like ``ray.util.queue.Queue.put`` (an actor call): the
        server acks only after the item is in the driver's queue, so once
        ``put`` returns the item is visible to any subsequent drain.
        Fire-and-forget would race :func:`util.process_results`'s final
        drain — a worker future can resolve before its last in-flight
        frame lands, silently dropping late metrics/thunks.

        Exactly-once enqueue: every frame carries ``(client_id, seq)``;
        the reconnect retry resends the *same* seq, and the server drops
        replays it has already enqueued.  Without this, an ack lost after
        the server committed the item would make the retry a duplicate —
        fatal for thunk items (a ``tune.report``/checkpoint lambda would
        execute twice driver-side).
        """
        # Chaos injection point: a crash/hang on the queue send path
        # exercises what a wedged control plane does to the fit (beats
        # and metrics ride this same lane).
        from ray_lightning_tpu.fault import inject as _chaos

        _chaos.fire("queue_put")
        with self._lock:
            # Burn the seq up front: if both attempts fail after the server
            # already committed this frame (ack lost, then reconnect
            # refused), the number must never be reused for a different
            # item — the server would dedup-drop it while acking success.
            self._seq += 1
            payload = rpc.dumps((self._client_id, self._seq, item))
            try:
                self._put_once(payload)
            except (OSError, ConnectionError):
                # One reconnect attempt — the driver may have restarted the
                # accept loop between epochs.
                self.close()
                self._put_once(payload)

    def _put_once(self, payload: bytes) -> None:
        budget = _send_timeout_s(len(payload))
        # Connect under the size-scaled budget too: a congested DCN hop
        # that throttles the payload also drops SYNs, and a 60s cap
        # would give up on exactly the links the scaling exists for.
        sock = self._connect(timeout=budget)
        try:
            sock.settimeout(budget)
            # Length prefix, then the payload in per-timeout chunks.
            sock.sendall(rpc.FRAME_HEADER.pack(len(payload)))
            _sendall_chunked(sock, payload)
            # The ack drains only after the server has READ the whole
            # frame off its socket — scale the wait with the payload.
            sock.settimeout(budget)
            ack = sock.recv(1)
        except Exception:
            # The frame may be half-sent or its ack still in flight; the
            # connection's ack stream can no longer be trusted (a later
            # put would read THIS frame's late ack as its own).  Drop it.
            self.close()
            raise
        if ack != b"\x01":
            self.close()
            raise ConnectionError("queue server closed before ack")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class DriverQueue:
    """Driver-side queue server (≙ ``ray.util.queue.Queue`` actor)."""

    def __init__(self, host: str = "127.0.0.1", advertise_host: Optional[str] = None):
        self._items: _pyqueue.Queue = _pyqueue.Queue()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, 0))
        self._server.listen(128)
        self._port = self._server.getsockname()[1]
        self._advertise_host = advertise_host or host
        self._closed = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        # Per-producer high-water marks for replay dedup (one entry per
        # worker process — bounded by world size).
        self._seen: dict = {}
        self._seen_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rlt-queue-accept", daemon=True
        )
        self._accept_thread.start()

    @property
    def handle(self) -> QueueHandle:
        return QueueHandle(self._advertise_host, self._port)

    # -- server side --------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return  # listener closed
            with self._conns_lock:
                self._conns.add(conn)
            t = threading.Thread(
                target=self._reader_loop, args=(conn,), daemon=True
            )
            t.start()

    def _reader_loop(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                frame = rpc.recv_frame(conn)
                if self._closed.is_set():
                    # Shutdown raced the recv: drop the frame unacked so
                    # the producer's put raises instead of getting a
                    # false-success ack into a queue nobody will drain.
                    break
                try:
                    cid, seq, item = rpc.loads(frame)
                except Exception:
                    # Garbage / old-protocol frame (the queue binds
                    # non-loopback in multi-host backends): drop the
                    # connection, never the reader thread.
                    break
                with self._seen_lock:
                    fresh = seq > self._seen.get(cid, 0)
                    if fresh:
                        self._seen[cid] = seq
                if fresh:
                    self._items.put(item)
                # Ack whether fresh or a replay (a replay means the ack —
                # not the item — was lost on the previous attempt).
                conn.sendall(b"\x01")
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    # -- driver consumption (reference util.py:47-52) -----------------------
    def empty(self) -> bool:
        return self._items.empty()

    def get_nowait(self) -> Any:
        return self._items.get_nowait()

    def get(self, timeout: Optional[float] = None) -> Any:
        return self._items.get(timeout=timeout)

    def shutdown(self) -> None:
        self._closed.set()
        try:
            self._server.close()
        except OSError:
            pass
        # Close live reader connections too: a worker's next (acked) put
        # must fail fast instead of feeding a queue nobody will drain.
        # shutdown(SHUT_RDWR) first — close() alone does not wake a reader
        # thread blocked in recv on the same file description, which could
        # otherwise ack an item into the dead queue.
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
