from .actor import ProcessActor, RemoteError, ActorDiedError
from .agent import NodeAgent, AgentClient, AgentError
from .queue import DriverQueue, QueueHandle
from .backend import (
    ObjectRef,
    ClusterBackend,
    LocalBackend,
    RemoteBackend,
    RayBackend,
    get_backend,
    ray_is_available,
)
from .rpc import find_free_port, get_node_ip

__all__ = [
    "ProcessActor",
    "RemoteError",
    "ActorDiedError",
    "NodeAgent",
    "AgentClient",
    "AgentError",
    "DriverQueue",
    "QueueHandle",
    "ObjectRef",
    "ClusterBackend",
    "LocalBackend",
    "RemoteBackend",
    "RayBackend",
    "get_backend",
    "ray_is_available",
    "find_free_port",
    "get_node_ip",
]
