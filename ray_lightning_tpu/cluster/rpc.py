"""Wire protocol helpers for the built-in control plane.

The reference outsources its control plane to Ray core (C++ raylet/GCS,
``/root/reference/ray_lightning/ray_ddp.py:38-63`` uses ``@ray.remote``
actors).  This package ships its own minimal, dependency-free control plane;
this module is the shared serialization/framing layer:

* **cloudpickle payloads** — like Ray, arbitrary callables (including
  lambdas with captured metrics, the Tune-report trick at reference
  ``tune.py:130-134``) must cross process boundaries;
* **length-prefixed frames** over sockets for the distributed queue.

The data plane (gradients, activations) NEVER touches this layer — that is
XLA collectives over ICI/DCN.  Only control messages and (relatively small)
state streams flow here.
"""

from __future__ import annotations

import socket
import struct
from typing import Any

import cloudpickle

_LEN = struct.Struct("!Q")
# Public alias: callers that stream a frame in pieces (the queue's
# chunked sender) must emit the exact same header this module parses.
FRAME_HEADER = _LEN


def dumps(obj: Any) -> bytes:
    return cloudpickle.dumps(obj)


def loads(data: bytes) -> Any:
    return cloudpickle.loads(data)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    # Two sendalls, not header+payload concatenation: payloads carry full
    # model state streams, and the concat would transiently double memory.
    sock.sendall(_LEN.pack(len(payload)))
    sock.sendall(payload)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket, max_len: int = 0) -> bytes:
    """Receive one frame; ``max_len`` (if nonzero) rejects oversized
    claims before any allocation — used on pre-authentication reads."""
    (length,) = _LEN.unpack(recv_exact(sock, _LEN.size))
    if max_len and length > max_len:
        raise ConnectionError(
            f"frame of {length} bytes exceeds limit {max_len}"
        )
    return recv_exact(sock, length)


def send_obj(sock: socket.socket, obj: Any) -> None:
    send_frame(sock, dumps(obj))


def recv_obj(sock: socket.socket) -> Any:
    return loads(recv_frame(sock))


def find_free_port(host: str = "") -> int:
    """OS-assigned free port (reference ``ray_ddp.py:31-35``).

    Used by the driver to broker rendezvous addresses: the distributed
    queue server, and the ``jax.distributed.initialize`` coordinator
    (the analogue of MASTER_ADDR/MASTER_PORT at reference
    ``ray_ddp.py:215-228``).
    """
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        return s.getsockname()[1]


def get_node_ip() -> str:
    """Best-effort routable IP of this node (≙ ``ray.util.get_node_ip_address``)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            # No packets are sent; this just selects the egress interface.
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
